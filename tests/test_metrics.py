"""Telemetry plane tests (ISSUE 8): registry semantics, exposition
format, the JobServer scrape surface (framed + plain HTTP), and
cross-rank aggregation over TAG_METRICS.

The flight-recorder incident path's end-to-end cases live in
test_fault.py (they ride the chaos/kill machinery)."""

import re
import socket
import time

from parsec_tpu.prof.metrics import (BUCKET_BOUNDS, Counter, Family, Gauge,
                                     Histogram, bucket_index,
                                     counter_sample, gauge_sample,
                                     histogram_sample, merge_samples,
                                     render_text)
from parsec_tpu.utils.mca import params


# ---------------------------------------------------------------------------
# registry semantics
# ---------------------------------------------------------------------------

def test_bucket_index_le_invariant():
    """Every observation lands in the smallest bucket whose bound is
    >= the value (Prometheus ``le`` semantics) — including exact powers
    of two, the frexp edge case."""
    vals = [1e-9, 1e-6, 2.0 ** -20, 2.0 ** -10, 3e-4, 0.25, 0.5,
            0.500001, 1.0, 2.0, 63.9, 64.0, 100.0, 1e6]
    for v in vals:
        i = bucket_index(v)
        if i < len(BUCKET_BOUNDS):
            assert v <= BUCKET_BOUNDS[i], (v, i)
        if 0 < i <= len(BUCKET_BOUNDS):
            assert v > BUCKET_BOUNDS[i - 1], (v, i)


def test_histogram_counts_sum_quantile():
    h = Histogram(ring=64)
    vals = [1e-5, 1e-5, 2e-3, 0.1, 0.1, 0.1, 5.0]
    for v in vals:
        h.observe(v)
    buckets, s, c = h.snapshot()
    assert c == len(vals)
    assert abs(s - sum(vals)) < 1e-12
    assert sum(buckets) == len(vals)
    # exact per-bucket placement
    for v in set(vals):
        assert buckets[bucket_index(v)] >= 1
    # the recent-window quantile brackets the data
    assert 1e-5 <= h.quantile(0.0) <= 5.0
    assert h.quantile(0.99) == 5.0


def test_counter_gauge_and_family_bounding():
    c = Counter()
    c.inc()
    c.inc(2.5)
    assert c.value == 3.5
    g = Gauge()
    g.set(7)
    g.add(-2)
    assert g.value == 5.0
    fam = Family(Counter, ("peer",), max_series=3)
    for r in range(5):
        fam.labels(peer=r).inc(r)
    items = fam.items()
    assert len(items) == 3          # oldest two evicted
    peers = {lab["peer"] for lab, _m in items}
    assert peers == {"2", "3", "4"}


def test_render_text_exposition_format():
    h = Histogram()
    for v in (1e-4, 1e-4, 2.0):
        h.observe(v)
    text = render_text([
        counter_sample("parsec_demo_total", 3),
        gauge_sample("parsec_demo_depth", 2, {"peer": "1"}),
        histogram_sample("parsec_demo_seconds", h),
    ])
    assert "# TYPE parsec_demo_total counter" in text
    assert "parsec_demo_total 3" in text
    assert 'parsec_demo_depth{peer="1"} 2' in text
    # cumulative bucket counts, monotonic, +Inf == count
    counts = [int(m.group(1)) for m in re.finditer(
        r'parsec_demo_seconds_bucket\{le="[^"]+"\} (\d+)', text)]
    assert counts == sorted(counts)
    assert counts[-1] == 3
    assert 'le="+Inf"' in text
    assert "parsec_demo_seconds_count 3" in text
    m = re.search(r"parsec_demo_seconds_sum (\S+)", text)
    assert abs(float(m.group(1)) - 2.0002) < 1e-9


def test_merge_samples_sums_counters_and_labels_gauges():
    h0, h1 = Histogram(), Histogram()
    h0.observe(1e-4)
    h1.observe(1e-4)
    h1.observe(2.0)
    merged = merge_samples({
        0: [counter_sample("parsec_x_total", 5),
            gauge_sample("parsec_x_depth", 2),
            histogram_sample("parsec_x_seconds", h0)],
        1: [counter_sample("parsec_x_total", 7),
            gauge_sample("parsec_x_depth", 9),
            histogram_sample("parsec_x_seconds", h1)],
    })
    by = {(s["n"], tuple(sorted(s["l"].items()))): s for s in merged}
    assert by[("parsec_x_total", ())]["v"] == 12
    assert by[("parsec_x_depth", (("rank", "0"),))]["v"] == 2
    assert by[("parsec_x_depth", (("rank", "1"),))]["v"] == 9
    hs = by[("parsec_x_seconds", ())]
    assert hs["cnt"] == 3 and sum(hs["b"]) == 3


# ---------------------------------------------------------------------------
# the always-on registry on a Context
# ---------------------------------------------------------------------------

def _n_pool(n, name="m"):
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG(name, N=n)
    p.task("E", i=Range(0, n - 1)).flow("x", "CTL").body(lambda: None)
    return p.build()


def test_runtime_metrics_counts_every_task():
    from parsec_tpu.core.context import Context
    params.set("metrics_sample", 1)
    try:
        with Context(nb_cores=2) as ctx:
            assert ctx.metrics is not None
            assert ctx._ready_stamp     # schedule() stamps ready_at
            ctx.add_taskpool(_n_pool(40))
            ctx.wait(timeout=60)
            text = render_text(ctx.metrics.samples())
    finally:
        params.unset("metrics_sample")
    assert re.search(r"parsec_tasks_retired_total 40\b", text)
    assert re.search(r"parsec_pending_tasks 0\b", text)
    # with stride 1 every task contributes a sojourn-latency sample
    assert re.search(r"parsec_task_latency_seconds_count 40\b", text)


def test_queue_wait_split_is_opt_in():
    """metrics_queue_wait=1 hooks select too, separating queue-wait
    (ready->select) from execution latency (select->complete); the
    default single-hook path keeps the telemetry budget."""
    from parsec_tpu.core.context import Context
    params.set("metrics_sample", 1)
    params.set("metrics_queue_wait", 1)
    try:
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(_n_pool(30))
            ctx.wait(timeout=60)
            text = render_text(ctx.metrics.samples())
    finally:
        params.unset("metrics_sample")
        params.unset("metrics_queue_wait")
    assert re.search(r"parsec_task_queue_wait_seconds_count 30\b", text)
    assert re.search(r"parsec_task_latency_seconds_count 30\b", text)


def test_metrics_disabled_removes_every_hook():
    from parsec_tpu.core.context import Context
    params.set("metrics_enabled", 0)
    try:
        with Context(nb_cores=1) as ctx:
            assert ctx.metrics is None
            assert not ctx._ready_stamp
            ctx.add_taskpool(_n_pool(5))
            ctx.wait(timeout=60)
    finally:
        params.unset("metrics_enabled")


def test_causal_tracer_keeps_ready_stamp_without_metrics():
    """The queue-wait stamp survives metrics-off when a causal tracer
    is installed (the pre-existing contract)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.prof.causal import install_causal_tracer
    from parsec_tpu.prof.profiling import Profile
    params.set("metrics_enabled", 0)
    try:
        with Context(nb_cores=1) as ctx:
            assert not ctx._ready_stamp
            tr = install_causal_tracer(ctx, Profile())
            assert ctx._ready_stamp
            tr.uninstall(ctx)
            assert not ctx._ready_stamp
    finally:
        params.unset("metrics_enabled")


# ---------------------------------------------------------------------------
# scrape surface: framed op, HTTP GET, CLI client
# ---------------------------------------------------------------------------

def _tiny_job_factory():
    def factory():
        return _n_pool(8, name="job-pool")
    return factory


def test_scrape_over_job_server_framed():
    from parsec_tpu.service.server import request, serve
    params.set("metrics_sample", 1)
    service, server = serve(port=0, nb_cores=2)
    try:
        job = service.submit(_tiny_job_factory(), name="scrapee")
        assert job.wait(timeout=30)
        reply = request(server.host, server.port, {"op": "metrics"})
        assert reply["ok"] and reply["ranks"] == [0]
        text = reply["text"]
        # task + job families are present, and the job SLO histogram
        # has exactly the one completed job with cumulative buckets
        assert "parsec_tasks_retired_total" in text
        assert 'parsec_jobs_done_total{status="done"} 1' in text
        assert re.search(r"parsec_job_duration_seconds_count 1\b", text)
        counts = [int(m.group(1)) for m in re.finditer(
            r'parsec_job_duration_seconds_bucket\{le="[^"]+"\} (\d+)',
            text)]
        assert counts == sorted(counts) and counts[-1] == 1
        # per-job task counters ride the JobGauges window, one series
        # per counter column
        assert re.search(
            r'parsec_job_tasks_total\{job="%d",kind="retired"\} 8\b'
            % job.job_id, text)
    finally:
        params.unset("metrics_sample")
        server.close()
        service.shutdown(timeout=10.0)


def test_scrape_over_http_get():
    """A stock HTTP client (curl, Prometheus) scrapes the SAME port:
    the server sniffs the first four bytes to pick the protocol."""
    from parsec_tpu.service.server import serve
    service, server = serve(port=0, nb_cores=2)
    try:
        with socket.create_connection((server.host, server.port),
                                      timeout=10.0) as s:
            s.sendall(b"GET /metrics HTTP/1.0\r\nHost: x\r\n\r\n")
            data = b""
            while True:
                chunk = s.recv(4096)
                if not chunk:
                    break
                data += chunk
        head, body = data.split(b"\r\n\r\n", 1)
        assert head.startswith(b"HTTP/1.0 200 OK")
        assert b"text/plain" in head
        assert b"parsec_tasks_retired_total" in body
        # and a wrong path 404s instead of hanging the connection
        with socket.create_connection((server.host, server.port),
                                      timeout=10.0) as s:
            s.sendall(b"GET /nope HTTP/1.0\r\n\r\n")
            assert s.recv(4096).startswith(b"HTTP/1.0 404")
    finally:
        server.close()
        service.shutdown(timeout=10.0)


def test_metrics_client_one_shot():
    from tools.metrics_client import scrape
    from parsec_tpu.service.server import serve
    service, server = serve(port=0, nb_cores=2)
    try:
        text = scrape(server.host, server.port)
        assert "parsec_pending_tasks" in text
    finally:
        server.close()
        service.shutdown(timeout=10.0)


# ---------------------------------------------------------------------------
# heartbeat-detector observability (satellite: per-peer rebase)
# ---------------------------------------------------------------------------

def test_starved_checker_rebase_is_per_peer():
    """A starved checker rebases ONLY peers whose silence it cannot
    judge (last heard before the stall); a peer heard DURING the stall
    keeps its real silence clock — and the rebases are counted for the
    metrics plane."""
    from parsec_tpu.comm.engine import CommEngine

    params.set("comm_peer_timeout_s", 0.5)
    try:
        ce = CommEngine(0, 3)
        now = time.monotonic()
        ce._hb_check_at = now - 10.0          # WE were frozen for 10s
        ce._last_heard[1] = now - 10.0        # silent since before stall
        ce._last_heard[2] = now - 0.6         # heard DURING the stall,
        ce.check_peer_timeouts()              # age already past timeout
        # a starved round NEVER declares (unread frames may be parked
        # in the kernel) — but only the stale peer was rebased
        assert not ce.dead_peers
        assert ce.hb_rebase_total == 1
        assert ce.hb_rebases() == {1: 1}
        # peer 2's clock was NOT rebased: the next HEALTHY check
        # declares on its true silence age immediately
        ce.check_peer_timeouts()
        assert 2 in ce.dead_peers
        assert 1 not in ce.dead_peers         # rebased peer got fresh time
        assert ce.peer_debug()[1].get("hb_rebases") == 1
    finally:
        params.unset("comm_peer_timeout_s")


# ---------------------------------------------------------------------------
# cross-rank aggregation over TAG_METRICS (the 2-rank acceptance)
# ---------------------------------------------------------------------------

def _chain_pool(V, nranks, name="chain"):
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    NT = 6
    p = PTG(name, NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=NT: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    return p.build()


def _scrape_worker(ctx, rank, nranks):
    """Rank 0 runs a JobService + JobServer over the SHARED 2-rank
    context and scrapes /metrics; the reply must cover the mesh."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    V = VectorTwoDimCyclic(mb=4, lm=24, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    ctx.add_taskpool(_chain_pool(V, nranks))
    ctx.wait(timeout=60)
    local_frames = ctx.comm.stats()["frames_sent"]
    if rank != 0:
        return {"frames": local_frames}
    from parsec_tpu.service.server import JobServer, request
    from parsec_tpu.service.service import JobService
    svc = JobService(context=ctx)
    server = JobServer(svc, port=0)
    try:
        job = svc.submit(_tiny_job_factory(), name="agg")
        assert job.wait(timeout=30)
        reply = request(server.host, server.port,
                        {"op": "metrics", "timeout": 5.0})
    finally:
        server.close()
        svc.shutdown(timeout=10.0)
    return {"frames": local_frames, "reply": reply}


def test_two_rank_scrape_aggregates_over_tag_metrics():
    """The ISSUE acceptance: one scrape on a running JobService sees
    the mesh — task/comm/job families summed across both ranks via
    TAG_METRICS, gauges labeled per rank, and a histogram with correct
    bucket counts."""
    from parsec_tpu.comm.launch import run_distributed
    res = run_distributed(_scrape_worker, 2, timeout=180)
    reply = res[0]["reply"]
    assert reply["ok"]
    assert reply["ranks"] == [0, 1]
    text = reply["text"]
    # comm counters summed across ranks: at least every frame rank 1
    # alone sent (both ranks sent frames during the chain)
    m = re.search(r"parsec_comm_frames_sent_total (\d+)", text)
    assert m is not None
    total = int(m.group(1))
    assert total >= res[0]["frames"] + 1, (total, res)
    assert total >= res[1]["frames"] + 1, (total, res)
    # per-rank gauges carry the rank label
    assert re.search(r'parsec_pending_tasks\{rank="1"\}', text)
    # the clock-probe exchange fed the frame-RTT histogram
    m = re.search(r"parsec_comm_frame_rtt_seconds_count (\d+)", text)
    assert m is not None and int(m.group(1)) >= 1, text[:2000]
    # the job SLO histogram survived the merge with correct buckets
    counts = [int(mm.group(1)) for mm in re.finditer(
        r'parsec_job_duration_seconds_bucket\{le="[^"]+"\} (\d+)',
        text)]
    assert counts and counts == sorted(counts) and counts[-1] == 1
    assert re.search(r"parsec_job_duration_seconds_count 1\b", text)


# ---------------------------------------------------------------------------
# SLO breach wiring (metrics -> flight recorder)
# ---------------------------------------------------------------------------

def test_job_slo_breach_counts_and_triggers_incident(tmp_path):
    from parsec_tpu.service.service import JobService
    params.set("metrics_slo_job_s", 1e-9)   # every job breaches
    params.set("flightrec_enabled", 1)
    params.set("flightrec_dir", str(tmp_path))
    try:
        with JobService(nb_cores=2) as svc:
            job = svc.submit(_tiny_job_factory(), name="slo")
            assert job.wait(timeout=30)
            ctx = svc.context
            # wait() returns at the DONE transition; the job_done PINS
            # emission (breach count + incident dump) follows on the
            # finishing thread a moment later
            deadline = time.monotonic() + 10
            while ctx._flightrec.incidents < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert ctx._flightrec.incidents >= 1
            text = render_text(ctx.metrics.samples())
            assert re.search(r"parsec_jobs_slo_breached_total [1-9]",
                             text)
            assert (tmp_path / "rank0.ptt").exists()
    finally:
        params.unset("metrics_slo_job_s")
        params.unset("flightrec_enabled")
        params.unset("flightrec_dir")
