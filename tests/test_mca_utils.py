"""Tests of the MCA parameter system, component repository, zone allocator,
mempool, and output streams (reference: utils/mca_param.c behavior)."""

import os

import pytest

from parsec_tpu.utils.mca import (SRC_ENV, SRC_FILE, ComponentRepository,
                                  ParamRegistry)
from parsec_tpu.utils.mempool import MemoryPool
from parsec_tpu.utils.output import Output, FatalError, fatal
from parsec_tpu.utils.zone_alloc import ZoneAllocator


def test_param_register_and_default():
    r = ParamRegistry()
    r.register("sched_lfq_queue_size", 4, "queue size")
    assert r.get("sched_lfq_queue_size") == 4
    assert r.source_of("sched_lfq_queue_size") == "default"


def test_param_precedence_env_over_file():
    r = ParamRegistry()
    os.environ["PARSEC_MCA_TEST_PRECEDENCE"] = "7"
    try:
        r.register("test_precedence", 1)
        assert r.get("test_precedence") == 7
        assert r.source_of("test_precedence") == "env"
        r.set("test_precedence", 3, src=SRC_FILE)
        assert r.get("test_precedence") == 7  # env beats file
        r.set("test_precedence", 9)  # override beats env
        assert r.get("test_precedence") == 9
        assert r.source_of("test_precedence") == "override"
    finally:
        del os.environ["PARSEC_MCA_TEST_PRECEDENCE"]


def test_param_set_before_register():
    r = ParamRegistry()
    r.set("late_param", "5")
    r.register("late_param", 0)
    assert r.get("late_param") == 5  # coerced to registered int type


def test_param_type_coercion_bool():
    r = ParamRegistry()
    r.register("device_tpu_enabled", True)
    r.set("device_tpu_enabled", "0")
    assert r.get("device_tpu_enabled") is False
    r.set("device_tpu_enabled", "yes")
    assert r.get("device_tpu_enabled") is True


def test_param_cmdline_and_dump():
    r = ParamRegistry()
    r.register("sched", "", "scheduler selection")
    rest = r.parse_cmdline(["prog", "--mca", "sched", "spq", "positional"])
    assert rest == ["prog", "positional"]
    assert r.get("sched") == "spq"
    assert any("sched" in line for line in r.dump())
    with pytest.raises(ValueError):
        r.parse_cmdline(["--mca", "sched"])


def test_param_keyval_file(tmp_path):
    r = ParamRegistry()
    f = tmp_path / "mca.conf"
    f.write_text("# comment\nsched = lfq\ndebug_verbose 5\n")
    assert r.load_keyval_file(str(f)) == 2
    r.register("sched", "")
    r.register("debug_verbose", 1)
    assert r.get("sched") == "lfq"
    assert r.get("debug_verbose") == 5
    assert r.source_of("sched") == "file"


def test_component_repository_selection():
    r = ParamRegistry()
    repo = ComponentRepository(r)
    repo.add("sched", "gd", "GD", priority=10)
    repo.add("sched", "lfq", "LFQ", priority=50)
    assert repo.available("sched") == ["lfq", "gd"]
    name, comp = repo.select("sched")
    assert (name, comp) == ("lfq", "LFQ")  # highest priority wins
    r.set("sched", "gd")
    name, comp = repo.select("sched")
    assert (name, comp) == ("gd", "GD")
    name, comp = repo.select("sched", requested="nope,lfq")
    assert name == "lfq"  # preference list skips unknown
    with pytest.raises(KeyError):
        repo.select("sched", requested="missing")


def test_zone_allocator():
    z = ZoneAllocator(1024, unit_bytes=64)
    a = z.malloc(100)   # 2 units
    b = z.malloc(64)    # 1 unit
    assert a == 0 and b == 128
    assert z.used_bytes() == 192
    z.free(a)
    c = z.malloc(128)   # reuses the coalesced hole at 0
    assert c == 0
    z.free(b)
    z.free(c)
    assert z.check_defrag()
    assert z.malloc(2048) is None  # larger than zone
    with pytest.raises(ValueError):
        z.free(64)  # not a live segment start


def test_mempool_reuse():
    made = []
    pool = MemoryPool(factory=lambda: made.append(1) or {"x": 0},
                      reset=lambda o: o.update(x=0))
    o1 = pool.alloc()
    o1["x"] = 5
    pool.release(o1)
    o2 = pool.alloc()
    assert o2 is o1 and o2["x"] == 0
    assert len(made) == 1


def test_output_streams(tmp_path, capsys):
    out = Output()
    logfile = tmp_path / "stream.log"
    sid = out.open(prefix="comm", verbosity=2, filename=str(logfile))
    out.emit(sid, 1, "inform", "hello")
    out.emit(sid, 9, "debug", "too verbose, dropped")
    out.set_verbosity(sid, 9)
    assert out.get_verbosity(sid) == 9
    out.emit(sid, 9, "debug", "now visible")
    out.close(sid)
    text = logfile.read_text()
    assert "hello" in text and "now visible" in text
    assert "dropped" not in text


def test_fatal_raises():
    with pytest.raises(FatalError):
        fatal("boom %d", 7)


def test_param_read_only_ignores_pending_and_env():
    os.environ["PARSEC_MCA_COMM_RANK"] = "99"
    try:
        r = ParamRegistry()
        r.set("comm_rank", 50)  # pre-registration override attempt
        r.register("comm_rank", 0, read_only=True)
        assert r.get("comm_rank") == 0
        with pytest.raises(ValueError):
            r.set("comm_rank", 7)
    finally:
        del os.environ["PARSEC_MCA_COMM_RANK"]


def test_param_int_coercion_edge_cases():
    r = ParamRegistry()
    r.register("n_threads", 1)
    r.set("n_threads", "010")
    assert r.get("n_threads") == 10
    r.set("n_threads", "0x10")
    assert r.get("n_threads") == 16
    r.set("n_threads", 2.7)
    assert r.get("n_threads") == 2


def test_zone_smaller_than_unit_rejected():
    with pytest.raises(ValueError):
        ZoneAllocator(100, unit_bytes=512)


def test_logfile_has_no_ansi(tmp_path):
    out = Output()
    sid = out.open(verbosity=5, filename=str(tmp_path / "f.log"))
    out.emit(sid, 1, "inform", "plain")
    out.close(sid)
    assert "\x1b[" not in (tmp_path / "f.log").read_text()


# -- info registry (reference: class/info.{c,h}) ----------------------------

def test_info_space_and_object_array():
    from parsec_tpu.utils.info import InfoObjectArray, InfoSpace
    sp = InfoSpace("t")
    iid = sp.register("streams", constructor=lambda owner: {"n": owner})
    assert sp.register("streams") == iid          # idempotent
    arr = InfoObjectArray(sp, owner=7)
    assert arr.get("streams") == {"n": 7}         # lazy constructor
    arr.set("streams", "override")
    assert arr.get(iid) == "override"
    assert arr.get("unknown", default=3) == 3
    sp.unregister("streams")
    arr2 = InfoObjectArray(sp, owner=1)
    assert arr2.get("streams", default="gone") == "gone"


def test_info_on_taskpool_and_device():
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.devices.device import HostDevice
    from parsec_tpu.utils.info import device_info, taskpool_info
    taskpool_info.register("userdata")
    tp = Taskpool("t")
    tp.info.set("userdata", 42)
    assert tp.info.get("userdata") == 42
    device_info.register("workspace", constructor=lambda d: [d.name])
    assert HostDevice().info.get("workspace") == ["cpu"]


# -- debug history + paranoia tiers (reference: debug_marks.{c,h},
# PARSEC_DEBUG_PARANOID) ----------------------------------------------------

def test_debug_history_ring_and_tiers():
    from parsec_tpu.utils.debug_history import (clear_history, dump_history,
                                                mark, paranoid, refresh_tier)
    from parsec_tpu.utils.mca import params
    clear_history()
    refresh_tier()
    assert not paranoid(1)
    mark("dropped %d", 1)                  # tier 0: not recorded
    assert dump_history() == []
    params.set("debug_paranoid", 1)
    params.set("debug_history_size", 4)
    refresh_tier()
    try:
        assert paranoid(1) and not paranoid(2)
        for i in range(9):
            mark("msg %d", i)
        hist = dump_history()
        assert len(hist) == 4              # ring bounded
        assert "msg 8" in hist[-1]
    finally:
        params.unset("debug_paranoid")
        params.unset("debug_history_size")
        refresh_tier()
        clear_history()


def test_show_help_templates(capfd):
    from parsec_tpu.utils.output import register_help, show_help
    text = show_help("device-oom", budget=64, nbytes=1024)
    assert "64 MiB" in text and "1024-byte" in text
    assert "device-oom" in capfd.readouterr().err
    register_help("custom-topic", "hello {who}")
    assert show_help("custom-topic", warn=False, who="world") == "hello world"
    assert "no help text" in show_help("missing", warn=False)
