"""Serving-fabric tests (ISSUE 16): mesh carving, gang dispatch of
concurrent tenants, SLO-driven predictive admission, preemption
round-trips, device-death elasticity, and the F1/F2/F3 fabric
invariants of the offline journal auditor
(service/fabric.py, tools/journal_audit.py)."""

import os
import sys
import time

import numpy as np
import pytest

from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.service.fabric import (FabricProfiles, MeshCarver,
                                       ServingFabric)
from parsec_tpu.service.job import AdmissionError, JobStatus

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import journal_audit  # noqa: E402


def _fab_chain(nt, delay=0.0, name="chain", device=None):
    """Job factory: own 1-tile collection + nt-deep increment chain;
    result() is the final tile value (== nt when every task ran —
    including after a preempt-then-resume restart, which re-runs the
    factory and rebuilds the collection from zero)."""
    def factory():
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0

        def body(T, k):
            if delay:
                time.sleep(delay)
            return T + 1.0

        p = PTG(name, NT=nt)
        tb = p.task("S", k=Range(0, nt - 1)) \
            .affinity(lambda k, A=A: A(0, 0)) \
            .flow("T", "RW",
                  IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                      when=lambda k, NT=nt: k < NT - 1),
                  OUT(DATA(lambda A=A: A(0, 0)),
                      when=lambda k, NT=nt: k == NT - 1))
        if device:
            tb.body(lambda T: T + 1.0, device=device)
        else:
            tb.body(body)

        def result():
            return float(np.asarray(
                A.data_of(0, 0).pull_to_host().payload)[0, 0])
        return p.build(), result
    return factory


def _wait_progress(svc, job, min_tasks=1, timeout=15.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        done = svc.gauges.job_task_counts(job.job_id)["tasks_retired"]
        if job.status() == JobStatus.RUNNING and done >= min_tasks:
            return
        time.sleep(0.005)
    raise AssertionError(f"{job} made no progress")


def _bundle_of(svc):
    """This service's journal as an audit bundle."""
    return {0: [svc.context.journal.snapshot()]}


def _events(svc, kinds=None):
    evs = svc.context.journal.tail(4096)
    if kinds is None:
        return evs
    return [e for e in evs if e.get("e") in kinds]


# ---------------------------------------------------------------------------
# MeshCarver: the free-list allocator
# ---------------------------------------------------------------------------

def test_carver_disjoint_leases_and_free_list_reuse():
    c = MeshCarver(range(1, 9))          # spaces 1..8
    a = c.carve(1, 3)
    b = c.carve(2, 3)
    d = c.carve(3, 2)
    assert a and b and d
    assert not (set(a) & set(b)) and not (set(a) & set(d)) \
        and not (set(b) & set(d))
    assert c.free_count() == 0
    # exhausted: the next ask fails, so does a double-carve
    assert c.carve(4, 1) is None
    assert c.carve(1, 1) is None          # owner already holds a lease
    # release returns devices to the free list; they are reused
    assert set(c.release(2)) == set(b)
    e = c.carve(5, 3)
    assert set(e) == set(b)
    assert c.lease(2) == ()
    assert c.release(99) == ()            # unknown owner: no-op


def test_carver_best_fit_contiguous_and_scattered_fallback():
    c = MeshCarver(range(8))
    # leave two holes: [0,1] and [4..7] (sizes 2 and 4)
    c.carve(1, 8)
    c.release(1)
    a = c.carve(1, 2)                     # takes [0,1]
    c.carve(2, 2)                         # [2,3]
    c.release(1)
    c.carve(3, 4)                         # [4..7]
    # best fit: a 2-ask picks the SMALL hole [0,1], not a slice of a
    # bigger one
    lease = c.carve(4, 2)
    assert lease == (0, 1), lease
    c.release(4)
    c.release(2)                          # free = {0,1} + {2,3} = [0..3]
    assert c.fragmentation() == 0.0       # one contiguous hole
    c.carve(5, 1)                         # take 0 -> free {1,2,3}
    c.release(5)
    # fragmentation metric reacts to shattering
    c2 = MeshCarver(range(6))
    c2.carve(1, 6)
    c2.release(1)
    for owner, s in ((10, 1), (11, 3), (12, 5)):
        c2._free.discard(s)
        c2._leases[owner] = [s]
    assert c2.fragmentation() > 0.5
    # scattered fallback: no run of 3 exists, the ask still carves
    lease = c2.carve(20, 3)
    assert lease == (0, 2, 4)


def test_carver_grow_shrink_evict():
    c = MeshCarver(range(8))
    c.carve(1, 2)                         # [0,1]
    # grow prefers adjacency
    added = c.grow(1, 2)
    assert added == (2, 3)
    assert c.lease(1) == (0, 1, 2, 3)
    # shrink returns highest indices first
    assert c.shrink(1, 2) == (2, 3)
    assert c.lease(1) == (0, 1)
    # evicting a leased device removes it from the mesh entirely
    assert c.evict(1) == 1                # owner 1 shrank
    assert 1 not in c.spaces
    assert c.lease(1) == (0,)
    assert c.evict(5) is None             # free device: no owner
    assert 5 not in c.spaces
    # shrinking to nothing drops the lease
    assert c.shrink(1, 1) == (0,)
    assert c.lease(1) == ()


# ---------------------------------------------------------------------------
# FabricProfiles: the learned quote
# ---------------------------------------------------------------------------

def test_profiles_quote_learns_and_scales():
    p = FabricProfiles()
    assert p.quote("never-seen", 4) is None
    p.observe("a", makespan=8.0, chips=2, totals={"S": 16},
              means={"S": 1.0})
    q1, q2, q8 = p.quote("a", 1), p.quote("a", 2), p.quote("a", 8)
    assert q1 is not None and q2 is not None and q8 is not None
    # more chips never quotes slower
    assert q1 >= q2 >= q8
    # at the measured gang size the quote tracks the measured makespan
    # (dagsim list-scheduling model; generous model tolerance)
    assert 0.1 * 8.0 <= q2 <= 10.0 * 8.0
    # no class mix: linear strong-scaling fallback
    p.observe("b", makespan=6.0, chips=2, totals=None, means={})
    assert p.quote("b", 4) == pytest.approx(3.0)
    assert p.quote("b", 1) == pytest.approx(12.0)
    # EWMA folding moves the estimate toward the new measurement
    before = p.quote("b", 2)
    p.observe("b", makespan=2.0, chips=2, totals=None, means={})
    assert p.quote("b", 2) < before


# ---------------------------------------------------------------------------
# the fabric end-to-end (8 virtual XLA devices; tests/conftest.py)
# ---------------------------------------------------------------------------

def test_fabric_concurrent_tenants_on_disjoint_subsets():
    """≥3 concurrent jobs: two exclusive tenants on carved disjoint
    subsets plus a shared-remainder tenant, truly co-running; every
    decision journaled and the fabric invariants audit clean."""
    with ServingFabric(nb_cores=2, max_active=8) as svc:
        if len(svc._carver.spaces) < 6:
            pytest.skip("needs >=6 accelerator spaces")
        a = svc.submit(_fab_chain(40, delay=0.01, name="ta"),
                       devices=3, client="tenantA")
        b = svc.submit(_fab_chain(40, delay=0.01, name="tb"),
                       devices=3, client="tenantB")
        s = svc.submit(_fab_chain(40, delay=0.01, name="ts"),
                       devices=0, client="tenantS")
        # all three run CONCURRENTLY at some instant
        deadline = time.monotonic() + 15.0
        seen = 0
        while time.monotonic() < deadline:
            seen = max(seen, sum(j.status() == JobStatus.RUNNING
                                 for j in (a, b, s)))
            if seen == 3:
                break
            time.sleep(0.005)
        assert seen == 3
        assert a.result(timeout=60.0) == 40.0
        assert b.result(timeout=60.0) == 40.0
        assert s.result(timeout=60.0) == 40.0
        places = _events(svc, {"fabric_place"})
        excl = [e for e in places if not e.get("shared")]
        shared = [e for e in places if e.get("shared")]
        assert len(excl) == 2 and len(shared) == 1
        sets = [set(e["devices"]) for e in excl]
        assert len(sets[0]) == 3 and len(sets[1]) == 3
        assert not (sets[0] & sets[1])
        assert journal_audit.audit(_bundle_of(svc)) == []


def test_fabric_exclusive_subset_confines_device_execution():
    """The carve stamp reaches best_device: a 1-device tenant's device
    tasks execute ONLY on its leased accelerator."""
    with ServingFabric(nb_cores=2, max_active=4) as svc:
        accs = svc.context.device_registry.accelerators
        if len(accs) < 2:
            pytest.skip("needs >=2 accelerators")
        job = svc.submit(_fab_chain(8, name="pin", device="tpu"),
                         devices=1)
        assert job.result(timeout=60.0) == 8.0
        place = _events(svc, {"fabric_place"})[-1]
        lease = set(place["devices"])
        assert len(lease) == 1
        used = {d.space for d in accs if d.stats.executed_tasks > 0}
        assert used and used <= lease, (used, lease)


def test_fabric_quote_vs_measured_makespan():
    """A second submission of a profiled app gets a makespan quote in
    the same decade as the measured first run."""
    with ServingFabric(nb_cores=2, max_active=4) as svc:
        first = svc.submit(_fab_chain(25, delay=0.005, name="calib"),
                           app="calib")
        assert first.result(timeout=60.0) == 25.0
        measured = first.finished_at - first.started_at
        assert measured > 0
        # the profile folds in _release_job, just after the terminal
        # transition wakes result() — poll for it
        deadline = time.monotonic() + 5.0
        while svc._profiles.quote("calib", svc._chips_shared) is None \
                and time.monotonic() < deadline:
            time.sleep(0.005)
        again = svc.submit(_fab_chain(25, delay=0.005, name="calib2"),
                           app="calib", slo=3600.0)
        assert again.quote_eta is not None
        assert 0.1 * measured <= again.quote_eta <= 10.0 * measured, \
            (again.quote_eta, measured)
        assert again.verdict == "admit"
        assert again.result(timeout=60.0) == 25.0
        quotes = _events(svc, {"fabric_quote"})
        assert any(e.get("eta") is not None for e in quotes)
        assert journal_audit.audit(_bundle_of(svc)) == []


def test_fabric_over_slo_policies():
    """An over-SLO quote rejects / deprioritizes / queues per policy,
    with the verdicts journaled and F2 holding (a rejected job never
    places)."""
    with ServingFabric(nb_cores=2, max_active=4) as svc:
        svc._profiles.observe("slowapp", makespan=500.0, chips=1,
                              totals={"S": 10}, means={"S": 50.0})
        with pytest.raises(AdmissionError):
            svc.submit(_fab_chain(3, name="rej"), app="slowapp",
                       slo=0.5, slo_policy="reject")
        depri = svc.submit(_fab_chain(3, name="dep"), app="slowapp",
                           slo=0.5, slo_policy="deprioritize")
        queued = svc.submit(_fab_chain(3, name="que"), app="slowapp",
                            slo=0.5)          # default policy: queue
        assert depri.verdict == "deprioritize"
        assert depri.priority < 0             # the penalty applied
        assert queued.verdict == "queue"
        assert depri.result(timeout=60.0) == 3.0
        assert queued.result(timeout=60.0) == 3.0
        verdicts = {e["verdict"] for e in _events(svc, {"fabric_admit"})}
        assert {"reject", "deprioritize", "queue"} <= verdicts
        assert journal_audit.audit(_bundle_of(svc)) == []


def test_fabric_queue_position():
    with ServingFabric(nb_cores=2, max_active=1,
                       aging_weight=0.0) as svc:
        busy = svc.submit(_fab_chain(80, delay=0.01, name="busy"))
        _wait_progress(svc, busy)
        lo = svc.submit(_fab_chain(3, name="lo"), priority=1)
        hi = svc.submit(_fab_chain(3, name="hi"), priority=5)
        assert svc.queue_position(hi.job_id) == 0
        assert svc.queue_position(lo.job_id) == 1
        assert svc.queue_position(busy.job_id) is None
        for j in (busy, lo, hi):
            assert j.result(timeout=60.0) is not None


def test_fabric_preempt_then_resume_roundtrip():
    """A latency-critical tenant preempts a lower-priority resumable
    tenant holding the whole mesh; the victim re-queues, resumes after
    the critical job drains, and still produces the right answer.
    fabric_preempt + fabric_resume are journaled and F1/F2/F3 audit
    clean."""
    with ServingFabric(nb_cores=2, max_active=4,
                       aging_weight=0.0) as svc:
        nmesh = len(svc._carver.spaces)
        if nmesh < 2:
            pytest.skip("needs a carveable mesh")
        victim = svc.submit(_fab_chain(250, delay=0.01, name="victim"),
                            priority=0, devices=nmesh, resumable=True)
        _wait_progress(svc, victim, min_tasks=2)
        urgent = svc.submit(_fab_chain(5, name="urgent"), priority=10,
                            devices=2, slo=600.0)
        assert urgent.result(timeout=60.0) == 5.0
        assert victim.result(timeout=180.0) == 250.0
        assert victim.preemptions >= 1
        assert svc.preemptions >= 1
        kinds = [e["e"] for e in _events(svc)]
        assert "fabric_preempt" in kinds
        assert "fabric_resume" in kinds
        # the resume leg re-placed the victim: one outcome per epoch
        assert journal_audit.audit(_bundle_of(svc)) == []


def test_fabric_device_death_shrinks_owner_only():
    """Chaos: kill a device inside ONE tenant's carved subset (the
    mesh-level analog of a rank kill).  The owner's subset shrinks in
    place and its job completes on what is left; the other tenants are
    unaffected; the resize is journaled and the audit stays clean."""
    with ServingFabric(nb_cores=2, max_active=8) as svc:
        if len(svc._carver.spaces) < 6:
            pytest.skip("needs >=6 accelerator spaces")
        a = svc.submit(_fab_chain(120, delay=0.01, name="ka"),
                       devices=3, client="tenantA")
        b = svc.submit(_fab_chain(30, delay=0.01, name="kb"),
                       devices=3, client="tenantB")
        s = svc.submit(_fab_chain(30, delay=0.01, name="ks"),
                       devices=0, client="tenantS")
        _wait_progress(svc, a, min_tasks=2)
        assert a.devices is not None and len(a.devices) == 3
        dead = a.devices[0]
        svc.context.device_registry.get(dead).enabled = False
        owner = svc.device_dead(dead)
        assert owner == a.job_id
        assert a.devices is not None and dead not in a.devices
        assert len(a.devices) == 2
        assert a.result(timeout=120.0) == 120.0
        assert b.result(timeout=60.0) == 30.0
        assert s.result(timeout=60.0) == 30.0
        resize = [e for e in _events(svc, {"fabric_resize"})
                  if e.get("cause") == "device_dead"]
        assert resize and resize[-1]["delta"] == -1
        assert journal_audit.audit(_bundle_of(svc)) == []


# ---------------------------------------------------------------------------
# the auditor's fabric invariants on hand-built bundles
# ---------------------------------------------------------------------------

def _snap(rank, events):
    return {"rank": rank, "inc": 0, "nranks": 1, "wall": 0.0,
            "perf": 0.0, "clock": {}, "events": events}


def _fab_bundle(events):
    out = []
    for i, ev in enumerate(events):
        e = {"t": float(i), "seq": i + 1, "inc": 0}
        e.update(ev)
        out.append(e)
    return {0: [_snap(0, out)]}


def test_audit_clean_fabric_roundtrip():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1, 2],
         "shared": False},
        {"e": "fabric_admit", "job": 2, "verdict": "admit"},
        {"e": "fabric_preempt", "job": 1, "by": 2},
        {"e": "fabric_release", "job": 1, "devices": [1, 2],
         "cause": "preempt"},
        {"e": "fabric_place", "job": 2, "devices": [1, 2],
         "shared": False},
        {"e": "fabric_release", "job": 2, "devices": [1, 2],
         "cause": "done"},
        {"e": "job_done", "job": 2, "status": "done"},
        {"e": "fabric_resume", "job": 1},
        {"e": "fabric_place", "job": 1, "devices": [1, 2],
         "shared": False},
        {"e": "fabric_release", "job": 1, "devices": [1, 2],
         "cause": "done"},
        {"e": "job_done", "job": 1, "status": "done"},
    ])
    assert journal_audit.audit(b) == []


def test_audit_flags_overlapping_exclusive_subsets():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_admit", "job": 2, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1, 2],
         "shared": False},
        {"e": "fabric_place", "job": 2, "devices": [2, 3],
         "shared": False},
    ])
    vs = journal_audit.audit(b)
    assert any(v.startswith("F1") and "overlapping" in v
               for v in vs), vs


def test_audit_shared_placement_never_conflicts():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_admit", "job": 2, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1, 2],
         "shared": False},
        {"e": "fabric_place", "job": 2, "devices": [],
         "shared": True},
    ])
    assert journal_audit.audit(b) == []


def test_audit_flags_double_placement_without_resume():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1],
         "shared": False},
        {"e": "fabric_release", "job": 1, "devices": [1],
         "cause": "done"},
        {"e": "fabric_place", "job": 1, "devices": [1],
         "shared": False},
    ])
    vs = journal_audit.audit(b)
    assert any(v.startswith("F2") and "epoch" in v for v in vs), vs


def test_audit_flags_rejected_job_that_placed():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 9, "verdict": "reject"},
        {"e": "fabric_place", "job": 9, "devices": [1],
         "shared": False},
    ])
    vs = journal_audit.audit(b)
    assert any(v.startswith("F2") and "REJECTED" in v for v in vs), vs


def test_audit_flags_unresolved_preemption():
    b = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1],
         "shared": False},
        {"e": "fabric_preempt", "job": 1, "by": 2},
        {"e": "fabric_release", "job": 1, "devices": [1],
         "cause": "preempt"},
    ])
    vs = journal_audit.audit(b)
    assert any(v.startswith("F3") for v in vs), vs
    # a terminal job_done after the preempt resolves it (cancelled
    # while preempted)
    b2 = _fab_bundle([
        {"e": "fabric_admit", "job": 1, "verdict": "admit"},
        {"e": "fabric_place", "job": 1, "devices": [1],
         "shared": False},
        {"e": "fabric_preempt", "job": 1, "by": 2},
        {"e": "fabric_release", "job": 1, "devices": [1],
         "cause": "preempt"},
        {"e": "job_done", "job": 1, "status": "cancelled"},
    ])
    assert journal_audit.audit(b2) == []
