"""ICI transport tests: the XLA-collective comm-engine module.

Mirrors the reference's direct comm-engine vtable test
(reference: tests/dsl/dtd/dtd_test_ce.c — drives AM + put/get of the CE
directly) plus the runtime integration: a multi-device GEMM whose panel
fan-outs ride one collective broadcast per tile (SURVEY §5.8).
Runs on the virtual 8-device CPU mesh (conftest).
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic


@pytest.fixture
def ctx():
    with Context(nb_cores=4) as c:
        if c.ici is None:
            pytest.skip("needs >=2 XLA devices")
        yield c


def test_put_moves_tile_between_devices(ctx):
    ici = ctx.ici
    rng = np.random.default_rng(0)
    a = rng.standard_normal((8, 8)).astype(np.float32)
    src, dst = ici.xla_devices[0].space, ici.xla_devices[1].space
    import jax
    on_src = jax.device_put(a, ici.xla_devices[0].jdev)
    out = ici.put(on_src, dst)
    assert list(out.devices())[0] == ici.xla_devices[1].jdev
    np.testing.assert_array_equal(np.asarray(out), a)
    assert ici.stats.puts == 1 and ici.stats.put_bytes == a.nbytes


def test_bcast_replicates_to_requested_devices(ctx):
    ici = ctx.ici
    rng = np.random.default_rng(1)
    a = rng.standard_normal((4, 4)).astype(np.float32)
    targets = [d.space for d in ici.xla_devices[1:4]]
    out = ici.bcast(a, targets)
    assert sorted(out) == sorted(targets)
    for sp, arr in out.items():
        assert list(arr.devices())[0] == ici._jdev[sp]
        np.testing.assert_array_equal(np.asarray(arr), a)
    assert ici.stats.bcasts == 1


def test_permute_batches_edges_in_one_launch(ctx):
    ici = ctx.ici
    rng = np.random.default_rng(2)
    spaces = [d.space for d in ici.xla_devices]
    n = len(spaces)
    # full rotation: every device sends its tile to the next — a single
    # permutation round, ONE CollectivePermute launch
    import jax
    tiles = {}
    edges = []
    for i, sp in enumerate(spaces):
        t = rng.standard_normal((4, 4)).astype(np.float32)
        tiles[sp] = t
        edges.append((sp, spaces[(i + 1) % n],
                      jax.device_put(t, ici._jdev[sp])))
    out = ici.permute(edges)
    assert len(out) == n
    assert ici.stats.permutes == 1 and ici.stats.permute_edges == n
    for i, sp in enumerate(spaces):
        dst = spaces[(i + 1) % n]
        got = out[(sp, dst)]
        assert list(got.devices())[0] == ici._jdev[dst]
        np.testing.assert_array_equal(np.asarray(got), tiles[sp])


def test_permute_splits_non_permutation_batches(ctx):
    ici = ctx.ici
    spaces = [d.space for d in ici.xla_devices]
    a = np.ones((2, 2), np.float32)
    b = 2 * np.ones((2, 2), np.float32)
    # two edges from the SAME source: needs two rounds
    edges = [(spaces[0], spaces[1], a), (spaces[0], spaces[2], b)]
    out = ici.permute(edges)
    np.testing.assert_array_equal(np.asarray(out[(spaces[0], spaces[1])]), a)
    np.testing.assert_array_equal(np.asarray(out[(spaces[0], spaces[2])]), b)
    assert ici.stats.permutes == 2


def test_multidevice_gemm_uses_collective_bcast():
    """Owner-computes GEMM over the device mesh: C tiles pinned
    block-cyclically across devices, A/B panels reaching >=2 devices ride
    prebroadcast (one replication instead of N transfers)."""
    rng = np.random.default_rng(3)
    from parsec_tpu.apps.gemm import gemm_taskpool
    mb, nt = 16, 4
    n = mb * nt
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    c = np.zeros((n, n), np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A").from_array(a)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="B").from_array(b)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="C").from_array(c)
    with Context(nb_cores=4) as ctx:
        if ctx.ici is None:
            pytest.skip("needs >=2 XLA devices")
        C.distribute_devices(ctx)
        ctx.add_taskpool(gemm_taskpool(A, B, C, device="tpu",
                                       panel_bcast=True))
        ctx.wait(timeout=120)
        stats = ctx.ici.stats.as_dict()
    np.testing.assert_allclose(C.to_array(), a @ b, rtol=2e-3, atol=2e-3)
    assert stats["bcasts"] > 0, f"no collective broadcasts fired: {stats}"


def test_preplace_single_consumer_edge(ctx):
    """A produced device-resident copy moves proactively onto the single
    consumer's device and attaches as a coherent SHARED copy (the CE-put
    analog of prebroadcast); host-resident or already-resident copies
    are left for the normal stage-in."""
    import jax
    from parsec_tpu.data.data import Coherency, new_data
    ici = ctx.ici
    rng = np.random.default_rng(3)
    a = rng.standard_normal((8, 8)).astype(np.float32)
    datum = new_data(np.zeros((8, 8), np.float32))
    src, dst = ici.xla_devices[0].space, ici.xla_devices[1].space
    dc = datum.overwrite_on(src, jax.device_put(a, ici.xla_devices[0].jdev))
    assert ici.preplace(dc, dst)
    placed = datum.copy_on(dst)
    assert placed is not None and placed.coherency == Coherency.SHARED
    assert placed.version == dc.version
    np.testing.assert_array_equal(np.asarray(placed.payload), a)
    # second call: already resident -> no-op
    assert not ici.preplace(dc, dst)
    # host-resident copies are not preplaced
    host_datum = new_data(a.copy())
    assert not ici.preplace(host_datum.copy_on(0), dst)


def test_runtime_stencil_uses_preplace(ctx):
    """A cross-device producer->consumer chain through the runtime fires
    the proactive put (dryrun's owner-computes GEMM never does: its
    chains stay on one device)."""
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    ndev = ctx.ici.ndev
    V = VectorTwoDimCyclic(mb=8, lm=8 * ndev)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m)
    V.distribute_devices(ctx)   # tile k pinned to device k
    p = PTG("zig", NT=ndev)
    # S(k) runs on tile k's device and feeds S(k+1) on the NEXT device:
    # every edge crosses devices with exactly one consumer
    p.task("S", k=Range(0, ndev - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, ND=ndev: k < ND - 1),
              OUT(DATA(lambda k, V=V: V(k)),
                  when=lambda k, ND=ndev: k == ND - 1)) \
        .body(lambda T: T + 1.0, device="tpu") \
        .body(lambda T: T + 1.0)
    before = ctx.ici.stats.puts + ctx.ici.stats.permute_edges
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    ctx.flush_ici()   # drain any edges still inside the batching window
    got = np.asarray(V.data_of(ndev - 1).pull_to_host().payload)
    np.testing.assert_allclose(got, float(ndev))
    # serialized chain edges may flush singly (puts) or batched into
    # ppermute rounds depending on window timing: either is proactive
    after = ctx.ici.stats.puts + ctx.ici.stats.permute_edges
    assert after > before, "no proactive d2d placement fired"


def test_wavefront_edges_ride_batched_permute(ctx):
    """k same-wavefront single-consumer cross-device edges ride ONE
    CollectivePermute launch (SURVEY §5.8 "batched per DAG wavefront"):
    P producers complete together, each feeding one consumer on the next
    device; defer_place batches the full round and flushes it as a
    single ppermute instead of P separate puts."""
    from parsec_tpu.apps.wave import (expected_wave_result,
                                      fill_wave_inputs,
                                      permute_wave_taskpool)
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.utils.mca import params

    ndev = ctx.ici.ndev
    if ndev < 4:
        pytest.skip("needs >=4 devices")
    V = VectorTwoDimCyclic(mb=8, lm=8 * ndev)
    W = VectorTwoDimCyclic(mb=8, lm=8 * ndev)
    fill_wave_inputs(V, W)
    V.distribute_devices(ctx)
    W.distribute_devices(ctx)
    # a huge batching window: only the full-round trigger may flush, so
    # the assertion on launch count is deterministic
    params.set("comm_ici_permute_window_ms", 1000.0)
    try:
        before_p = ctx.ici.stats.permutes
        before_e = ctx.ici.stats.permute_edges
        before_put = ctx.ici.stats.puts
        ctx.add_taskpool(permute_wave_taskpool(V, W))
        ctx.wait(timeout=120)
    finally:
        params.unset("comm_ici_permute_window_ms")
    # every edge was cross-device single-consumer: the wave's first edge
    # opens the window with one immediate put, the remaining k-1 ride
    # ppermute rounds — k edges on <=2 launches
    assert ctx.ici.stats.permute_edges - before_e >= ndev - 2
    assert (ctx.ici.stats.permutes - before_p) \
        + (ctx.ici.stats.puts - before_put) <= 2
    for q in range(ndev):
        got = np.asarray(W.data_of(q).pull_to_host().payload)
        np.testing.assert_allclose(got, expected_wave_result(ndev, q))
