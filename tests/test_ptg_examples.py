"""Ports of the reference examples Ex00-Ex07 onto the PTG front-end
(reference: /root/reference/examples/Ex00_StartStop.c .. Ex07_RAW_CTL.jdf —
behavior reproduced, not translated; the DSL replaces the JDF compiler)."""

import threading

import numpy as np
import pytest

from parsec_tpu import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.dsl.ptg import DATA, IN, NEW, OUT, PTG, Range, TASK


def make_ctx(**kw):
    kw.setdefault("nb_cores", 2)
    return Context(**kw)


def test_ex00_start_stop():
    """Ex00_StartStop.c: init / start / wait / fini cycles, no tasks."""
    for _ in range(3):
        with make_ctx() as ctx:
            ctx.start()
            assert ctx.test()


def test_ex01_hello_world():
    """Ex01_HelloWorld.jdf: one task, no data."""
    said = []
    g = PTG("hello")
    g.task("HelloWorld").flow("X", "CTL").body(lambda: said.append("hi"))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert said == ["hi"]


def test_ex02_chain():
    """Ex02_Chain.jdf: NB tasks ordered by a CTL-less RW chain on one tile."""
    NB = 8
    A = VectorTwoDimCyclic(1, 1).from_array(np.zeros(1, np.float32))
    order = []

    g = PTG("chain", NB=NB)
    g.task("Task", k=Range(0, NB - 1)) \
     .affinity(lambda k: A(0)) \
     .flow("T", "RW",
           IN(DATA(lambda k: A(0)), when=lambda k: k == 0),
           IN(TASK("Task", "T", lambda k: dict(k=k - 1)),
              when=lambda k: k > 0),
           OUT(TASK("Task", "T", lambda k: dict(k=k + 1)),
               when=lambda k, NB=NB: k < NB - 1)) \
     .body(lambda k: order.append(k))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert order == list(range(NB))


def test_ex03_chain_distributed_placement():
    """Ex03_ChainMPI.jdf: owner-computes placement — each rank instantiates
    only its own tasks.  Two independent per-rank chains; the rank-0 context
    must execute exactly the rank-0 chain."""
    ran = []
    # two tiles, one per rank (1D cyclic over 2 nodes)
    V = VectorTwoDimCyclic(1, 2, nodes=2, myrank=0)

    g = PTG("chainmpi", NB=4)
    g.task("Task", r=Range(0, 1), k=Range(0, 3)) \
     .affinity(lambda r: V(r)) \
     .flow("T", "RW",
           IN(DATA(lambda r: V(r)), when=lambda k: k == 0),
           IN(TASK("Task", "T", lambda r, k: dict(r=r, k=k - 1)),
              when=lambda k: k > 0),
           OUT(TASK("Task", "T", lambda r, k: dict(r=r, k=k + 1)),
               when=lambda k: k < 3)) \
     .body(lambda r, k: ran.append((r, k)))
    tp = g.build()
    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=10)
    assert ran == [(0, k) for k in range(4)]   # rank-1 tasks never ran here


def test_ex04_chain_data():
    """Ex04_ChainData.jdf: data value flows down the chain and back home."""
    NB = 6
    a = np.zeros(1, np.float32)
    V = VectorTwoDimCyclic(1, 1).from_array(a)

    g = PTG("chaindata", NB=NB)
    g.task("Task", k=Range(0, NB - 1)) \
     .affinity(lambda k: V(0)) \
     .flow("T", "RW",
           IN(DATA(lambda k: V(0)), when=lambda k: k == 0),
           IN(TASK("Task", "T", lambda k: dict(k=k - 1)),
              when=lambda k: k > 0),
           OUT(TASK("Task", "T", lambda k: dict(k=k + 1)),
               when=lambda k, NB=NB: k < NB - 1),
           OUT(DATA(lambda k: V(0)), when=lambda k, NB=NB: k == NB - 1)) \
     .body(lambda T, k: T.__iadd__(k))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert a[0] == sum(range(NB))


def test_ex05_broadcast_range_dep():
    """Ex05_Broadcast.jdf: one task broadcasts to a range of receivers via
    a single JDF range dep (-> A TaskRecv(1 .. WORLD-1))."""
    WORLD = 7
    a = np.full(1, 3.0, np.float32)
    V = VectorTwoDimCyclic(1, 1).from_array(a)
    got = []
    lock = threading.Lock()

    def recv(A, k):
        with lock:
            got.append((k, float(A[0])))

    g = PTG("bcast", WORLD=WORLD)
    g.task("TaskBcast") \
     .affinity(lambda: V(0)) \
     .flow("A", "RW",
           IN(DATA(lambda: V(0))),
           OUT(TASK("TaskRecv", "A",
                    lambda WORLD=WORLD: [dict(k=k) for k in range(1, WORLD)]))) \
     .body(lambda A: A.__imul__(2))
    g.task("TaskRecv", k=Range(1, WORLD - 1)) \
     .affinity(lambda k: V(0)) \
     .flow("A", "READ", IN(TASK("TaskBcast", "A", lambda k: dict()))) \
     .body(recv)
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert sorted(got) == [(k, 6.0) for k in range(1, WORLD)]


def _raw_pools(with_ctl: bool, NB: int = 6):
    """Shared structure of Ex06_RAW / Ex07_RAW_CTL: TaskBcast(k) sends A to
    NB/2+1 TaskRecv readers and one TaskUpdate writer; without CTL the
    update may race the readers, with CTL it is ordered after all of them."""
    events = []
    lock = threading.Lock()
    K = 2
    a = np.zeros(K, np.float32)
    V = VectorTwoDimCyclic(1, K).from_array(a)
    recv_range = list(range(0, NB + 1, 2))

    g = PTG("raw_ctl" if with_ctl else "raw", NB=NB, K=K)
    g.task("TaskBcast", k=Range(0, K - 1)) \
     .affinity(lambda k: V(k)) \
     .flow("A", "RW",
           IN(DATA(lambda k: V(k))),
           OUT(TASK("TaskUpdate", "A", lambda k: dict(k=k))),
           OUT(TASK("TaskRecv", "A",
                    lambda k: [dict(k=k, n=n) for n in recv_range]))) \
     .body(lambda A, k: A.fill(k + 1))

    def recv_body(A, k, n):
        with lock:
            events.append(("recv", k, n, float(A[0])))

    g.task("TaskRecv", k=Range(0, K - 1), n=Range(0, NB, 2)) \
     .affinity(lambda k: V(k)) \
     .flow("A", "READ", IN(TASK("TaskBcast", "A", lambda k: dict(k=k)))) \
     .flow("ctl", "CTL",
           *([OUT(TASK("TaskUpdate", "ctl", lambda k: dict(k=k)))]
             if with_ctl else [])) \
     .body(recv_body)

    def update_body(A, k):
        with lock:
            events.append(("update", k))
        A.fill(-(k + 1))

    g.task("TaskUpdate", k=Range(0, K - 1)) \
     .affinity(lambda k: V(k)) \
     .flow("A", "RW",
           IN(TASK("TaskBcast", "A", lambda k: dict(k=k))),
           OUT(DATA(lambda k: V(k)))) \
     .flow("ctl", "CTL",
           *([IN(TASK("TaskRecv", "ctl",
                      lambda k: [dict(k=k, n=n) for n in recv_range]))]
             if with_ctl else [])) \
     .body(update_body)
    return g, events, a, recv_range


def test_ex07_raw_ctl_orders_update_after_reads():
    """Ex07_RAW_CTL.jdf: the CTL gather guarantees every reader saw the
    broadcast value before the anti-dependent update overwrote it."""
    g, events, a, recv_range = _raw_pools(with_ctl=True)
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    for k in (0, 1):
        upd = events.index(("update", k))
        recvs = [i for i, e in enumerate(events)
                 if e[0] == "recv" and e[1] == k]
        assert len(recvs) == len(recv_range)
        assert all(i < upd for i in recvs)          # CTL ordering held
    # every reader saw the pre-update value
    assert all(e[3] == e[1] + 1 for e in events if e[0] == "recv")
    assert list(a) == [-1.0, -2.0]                  # updates wrote home


def test_ex06_raw_runs_all_tasks():
    """Ex06_RAW.jdf (no CTL): all tasks still execute; read values may race
    the update by design (the example exists to show the hazard)."""
    g, events, a, recv_range = _raw_pools(with_ctl=False)
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert sum(1 for e in events if e[0] == "recv") == 2 * len(recv_range)
    assert sum(1 for e in events if e[0] == "update") == 2
    assert list(a) == [-1.0, -2.0]


def test_range_with_step_and_derived_bounds():
    hits = []
    g = PTG("steps", N=10)
    g.task("S", i=Range(0, lambda N: N - 1, 3),
           j=Range(lambda i: i, lambda i, N: min(i + 1, N - 1))) \
     .flow("X", "CTL") \
     .body(lambda i, j: hits.append((i, j)))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    expect = [(i, j) for i in range(0, 10, 3)
              for j in range(i, min(i + 1, 9) + 1)]
    assert sorted(hits) == sorted(expect)


def test_body_magic_names_and_globals():
    seen = {}
    V = VectorTwoDimCyclic(1, 1).from_array(np.ones(1, np.float32))
    g = PTG("magic", ANSWER=42)

    def body(es, task, X, ANSWER):
        seen["es"] = es is not None
        seen["task"] = str(task)
        seen["X"] = float(X[0])
        seen["ANSWER"] = ANSWER

    g.task("M").affinity(lambda: V(0)) \
     .flow("X", "READ", IN(DATA(lambda: V(0)))) \
     .body(body)
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert seen == {"es": True, "task": "M()", "X": 1.0, "ANSWER": 42}


def test_ctl_two_guarded_deps_count_as_two_edges():
    """A 2x2 wavefront: W(1,1) has TWO simultaneously-applying CTL input
    deps (from W(0,1) and W(1,0)) and must run exactly once, after both."""
    order = []
    V = VectorTwoDimCyclic(1, 1).from_array(np.zeros(1, np.float32))
    g = PTG("wave")
    g.task("W", m=Range(0, 1), n=Range(0, 1)) \
     .affinity(lambda: V(0)) \
     .flow("c", "CTL",
           IN(TASK("W", "c", lambda m, n: dict(m=m - 1, n=n)),
              when=lambda m: m > 0),
           IN(TASK("W", "c", lambda m, n: dict(m=m, n=n - 1)),
              when=lambda n: n > 0),
           OUT(TASK("W", "c", lambda m, n: dict(m=m + 1, n=n)),
               when=lambda m: m < 1),
           OUT(TASK("W", "c", lambda m, n: dict(m=m, n=n + 1)),
               when=lambda n: n < 1)) \
     .body(lambda m, n: order.append((m, n)))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert sorted(order) == [(0, 0), (0, 1), (1, 0), (1, 1)]  # exactly once
    assert order[0] == (0, 0) and order[-1] == (1, 1)


def test_empty_range_gather_is_no_dep():
    """Boundary instances with an empty JDF range gather run immediately."""
    ran = []
    g = PTG("empty_range", N=3)
    g.task("Leaf", k=Range(0, 2)) \
     .flow("c", "CTL",
           IN(TASK("Leaf", "c",
                   lambda k: [dict(k=j) for j in range(k + 1, 0)])),
           OUT(TASK("Leaf", "c", lambda k: []))) \
     .body(lambda k: ran.append(k))
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        ctx.wait(timeout=10)
    assert sorted(ran) == [0, 1, 2]


def test_apply_with_int_returning_op_terminates():
    from parsec_tpu.data.operators import apply_op
    a = np.zeros((2, 2), np.float32)
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(a)
    with make_ctx() as ctx:
        ctx.add_taskpool(apply_op(A, lambda T, m, n: 1))  # op returns int
        ctx.wait(timeout=10)


def test_data_gather_rejected():
    """Two data-carrying arrivals on one flow must fail loudly, not drop."""
    V = VectorTwoDimCyclic(1, 2).from_array(np.zeros(2, np.float32))
    g = PTG("badgather")
    g.task("P", i=Range(0, 1)) \
     .affinity(lambda i: V(i)) \
     .flow("T", "RW", IN(DATA(lambda i: V(i))),
           OUT(TASK("C", "X", lambda i: dict()))) \
     .body(lambda T: None)
    g.task("C").affinity(lambda: V(0)) \
     .flow("X", "READ",
           IN(TASK("P", "T", lambda: [dict(i=0), dict(i=1)]))) \
     .body(lambda X: None)
    with make_ctx() as ctx:
        ctx.add_taskpool(g.build())
        with pytest.raises(RuntimeError):
            ctx.wait(timeout=10)
