"""Recovery-plane tests (ISSUE 10: contain -> RECOVER -> rejoin).

Unit layers: the lineage planner's minimal re-execution set on
hand-built DAGs, the per-collection rank translation, the termdet
rewind, the run_epoch task fence, incarnation-epoch frame fencing, the
degraded-checkpoint fail-fast, and the service's degraded -> recovering
-> healthy bookkeeping.

End to end: 2-rank kill_rank plans (PTG potrf and DTD chain) that END
IN COMPLETED, NUMERICALLY VALIDATED jobs on the survivor; recovery
disabled reproducing PR 5's containment; a killed-then-restarted rank
rejoining over TAG_REJOIN and serving its partition again; and the
slow 3-rank mid-run-kill acceptance run with the makespan bound.
"""

import os
import sys
import time

import numpy as np
import pytest

from parsec_tpu.core.errors import (CheckpointDegradedError,
                                    PeerFailedError)
from parsec_tpu.core.recovery import (LineageRecord, RecoveryUnsupported,
                                      dtd_skip_prefix, lineage_plan,
                                      minimal_plan)
from parsec_tpu.utils.mca import params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _run_distributed_with_env(fn, nranks, env, timeout=120,
                              tolerate_ranks=()):
    from parsec_tpu.comm.launch import run_distributed
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return run_distributed(fn, nranks, timeout=timeout,
                               tolerate_ranks=tolerate_ranks)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# lineage planner: minimal re-execution set on hand-built DAGs
# ---------------------------------------------------------------------------

def test_lineage_plan_minimal_set():
    """Diamond DAG over tiles a/b/c/d; only d's final version is lost
    and b's intermediate survives -> re-execute exactly the producers
    on the lost path, not the whole log."""
    log = [
        LineageRecord("T1", reads=[("a", 0)], writes=[("b", 1)]),
        LineageRecord("T2", reads=[("a", 0)], writes=[("c", 1)]),
        LineageRecord("T3", reads=[("b", 1), ("c", 1)],
                      writes=[("d", 1)]),
        LineageRecord("T4", reads=[("d", 1)], writes=[("d", 2)]),
    ]
    surviving = {"a": 0, "b": 1, "c": 1}       # d died with its rank
    tasks, base = lineage_plan(log, surviving, {"d": 2})
    assert tasks == ["T3", "T4"]               # T1/T2 outputs survive
    assert base == {"b": 1, "c": 1}


def test_lineage_plan_walks_back_to_source():
    """Nothing of the lost chain survives: the walk reaches the version-0
    source (the registration snapshot / init_fn base)."""
    log = [
        LineageRecord("P0", reads=[("x", 0)], writes=[("x", 1)]),
        LineageRecord("P1", reads=[("x", 1)], writes=[("x", 2)]),
    ]
    tasks, base = lineage_plan(log, {"x": 0}, {"x": 2})
    assert tasks == ["P0", "P1"]
    assert base == {"x": 0}


def test_lineage_plan_broken_lineage_raises():
    with pytest.raises(RecoveryUnsupported):
        lineage_plan([], {}, {"ghost": 3})


# ---------------------------------------------------------------------------
# minimal_plan: the RECORDED-lineage replay set on hand-built DAGs
# (recorded plan == analytic plan; checkpoint-bounded cut; ring-evicted
# fallback)
# ---------------------------------------------------------------------------

def _chain_records(sent_to_dead=("T0",)):
    """Three-step in-place chain over tile a (v0 -> v3) plus an
    independent tile b task; T0's activations reached rank 1."""
    return [
        LineageRecord("T0", rmap={"C": ("a", 0)}, wmap={"C": ("a", 1)},
                      reads=[("a", 0)], writes=[("a", 1)],
                      dests={1} if "T0" in sent_to_dead else ()),
        LineageRecord("T1", rmap={"C": ("a", 1)}, wmap={"C": ("a", 2)},
                      reads=[("a", 1)], writes=[("a", 2)]),
        LineageRecord("T2", rmap={"C": ("a", 2)}, wmap={"C": ("a", 3)},
                      reads=[("a", 2)], writes=[("a", 3)]),
        LineageRecord("U0", rmap={"B": ("b", 0)}, wmap={"B": ("b", 1)},
                      reads=[("b", 0)], writes=[("b", 1)]),
    ]


_CHAIN_EDGES = {
    "T0": [("desc", "a", 0)],
    "T1": [("task", "T0", "C", "C", "local", False)],
    "T2": [("task", "T1", "C", "C", "local", False)],
    "U0": [("desc", "b", 0)],
}


def test_minimal_plan_matches_analytic_set():
    """Recorded plan == analytic plan: T0 fed the dead rank, so the
    whole a-chain re-runs (re-running T0 regresses tile a below its
    live version — every recorded later writer rejoins); the untouched
    b task stays OUT of the plan."""
    plan = minimal_plan(_chain_records(), dead_set={1},
                        live={"a": 3, "b": 1},
                        materializable={"a": {0}, "b": {0}},
                        edges=lambda k: _CHAIN_EDGES.get(k, ()))
    assert plan.tasks == {"T0", "T1", "T2"}     # analytic closure
    assert plan.base == {"a": 0}                # desc cut at snapshot
    assert not plan.needs and not plan.synth


def test_minimal_plan_synthesizes_materialized_edges():
    """A pending consumer of a SKIPPED producer gets its delivery
    synthesized from the live-intact version instead of re-running the
    producer."""
    edges = dict(_CHAIN_EDGES)
    edges["P0"] = [("task", "U0", "B", "X", "local", False)]
    plan = minimal_plan(_chain_records(), dead_set={1}, pending=["P0"],
                        live={"a": 3, "b": 1},
                        materializable={"a": {0}, "b": {0}},
                        edges=lambda k: edges.get(k, ()))
    assert "U0" not in plan.tasks and "P0" in plan.tasks
    assert ("P0", "X", "b", 1, "U0") in plan.synth


def test_minimal_plan_checkpoint_bounds_replay_depth():
    """Checkpoint-bounded cut: with tile a's v2 captured by the
    incremental checkpoint store, a consumer needing v2 synthesizes
    from the capture — the walk stops there instead of rewinding to
    the snapshot and re-running the whole chain."""
    edges = dict(_CHAIN_EDGES)
    edges["P1"] = [("task", "T1", "C", "X", "local", False)]
    # without the checkpoint: T1 must re-run, dragging T0 and T2 in
    deep = minimal_plan(_chain_records(sent_to_dead=()), dead_set={1},
                        pending=["P1"], live={"a": 3, "b": 1},
                        materializable={"a": {0}, "b": {0}},
                        edges=lambda k: edges.get(k, ()))
    assert {"T0", "T1", "T2"} <= deep.tasks
    # with (a, 2) checkpointed the plan is ONE pending task + a synth
    shallow = minimal_plan(_chain_records(sent_to_dead=()),
                           dead_set={1}, pending=["P1"],
                           live={"a": 3, "b": 1},
                           materializable={"a": {0, 2}, "b": {0}},
                           edges=lambda k: edges.get(k, ()))
    assert shallow.tasks == {"P1"}
    assert ("P1", "X", "a", 2, "T1") in shallow.synth


def test_minimal_plan_ring_evicted_falls_back():
    """A producer whose record the ring evicted cannot be planned
    around: RecoveryUnsupported — the caller takes the full
    restore-point replay (counted in full_replays)."""
    recs = _chain_records()[1:]    # T0's record evicted
    with pytest.raises(RecoveryUnsupported):
        minimal_plan(recs, dead_set={1}, pending=["P2"],
                     live={"a": 3, "b": 1},
                     materializable={"a": {0}, "b": {0}},
                     edges=lambda k:
                     {"P2": [("task", "T0", "C", "X", "local",
                              False)]}.get(k, ()))


def test_minimal_plan_unrecorded_later_writer_falls_back():
    """Rewinding a tile whose LIVE version has no recorded writer
    (the ring rolled past it) is unsound — the plan refuses."""
    recs = _chain_records()
    with pytest.raises(RecoveryUnsupported):
        minimal_plan(recs, dead_set={1}, live={"a": 9, "b": 1},
                     materializable={"a": {0}, "b": {0}},
                     edges=lambda k: _CHAIN_EDGES.get(k, ()))


def test_minimal_plan_remote_edges_become_needs():
    """A task-fed input produced on a LIVE survivor is a negotiation
    need, never a silent assumption."""
    edges = dict(_CHAIN_EDGES)
    edges["P3"] = [("task", "Q", "C", "Y", ("peer", 2), False)]
    plan = minimal_plan(_chain_records(sent_to_dead=()), dead_set={1},
                        pending=["P3"], live={"a": 3, "b": 1},
                        materializable={"a": {0}, "b": {0}},
                        edges=lambda k: edges.get(k, ()))
    assert (2, "P3", "Y") in plan.needs


def test_minimal_plan_synth_drops_when_producer_joins():
    """An edge that first chose synthesis must lose its synth twin if
    the producer later joins the plan (the natural re-delivery would
    otherwise double-arrive)."""
    recs = _chain_records(sent_to_dead=())
    recs.append(LineageRecord("D0", rmap={"B": ("b", 1)},
                              wmap={}, reads=[("b", 1)], dests={1}))
    edges = dict(_CHAIN_EDGES)
    edges["D0"] = [("task", "U0", "B", "X", "local", False)]
    # P4 needs b@0 which is NOT materializable as a synth-only story:
    # force U0 to rejoin via a desc rewind of b
    edges["P4"] = [("task", "U0", "B", "X", "local", False),
                   ("desc", "b", 0)]
    plan = minimal_plan(recs, dead_set={1}, pending=["P4"],
                        live={"a": 3, "b": 1},
                        materializable={"a": {0}, "b": {0}},
                        edges=lambda k: edges.get(k, ()))
    # rewinding b to 0 pulls writer U0 in; every synth against U0 is
    # dropped in favor of the natural delivery
    assert "U0" in plan.tasks
    assert not any(s[4] == "U0" for s in plan.synth)


# ---------------------------------------------------------------------------
# DTD insert-stream skip agreement: the pure prefix planner on
# hand-built write ladders (r15)
# ---------------------------------------------------------------------------

#: a 10-insert single-tile chain: insert i writes version i+1
_LADDER = [(i, "t") for i in range(10)]


def test_dtd_skip_prefix_full_prefix():
    """Every survivor's frontier covers the whole stream and someone
    holds the final version: the whole prefix skips."""
    k, holders, vcut = dtd_skip_prefix(
        {0: 10, 2: 10}, {0: {"t": 10}, 2: {"t": 4}}, _LADDER)
    assert k == 10 and holders == {"t": 0} and vcut == {"t": 10}


def test_dtd_skip_prefix_cuts_to_held_version():
    """Frontiers split inside a window (the mid-insert kill shape):
    the agreed prefix is the largest K where some survivor HOLDS the
    cut version — not just the min frontier."""
    # min frontier 8, but the best-landed survivor holds only v6: the
    # scan walks down to the materializable cut
    k, holders, vcut = dtd_skip_prefix(
        {0: 8, 2: 40}, {0: {"t": 6}, 2: {"t": 3}}, _LADDER)
    assert k == 6 and holders == {"t": 0} and vcut == {"t": 6}
    # the lower-landed survivor's version also works when it is the
    # only consistent cut
    k, holders, _ = dtd_skip_prefix(
        {0: 8, 2: 40}, {0: {"t": 0}, 2: {"t": 3}}, _LADDER)
    assert k == 3 and holders == {"t": 2}


def test_dtd_skip_prefix_no_holder_falls_back():
    """Nobody holds any cut version (the dead rank's payloads never
    landed): no common prefix — the gang takes the full replay."""
    k, holders, vcut = dtd_skip_prefix(
        {0: 10, 2: 10}, {0: {}, 2: {}}, _LADDER)
    assert k == 0 and not holders and not vcut


def test_dtd_skip_prefix_unwritten_tiles_need_no_holder():
    """A tile the prefix never writes (vcut 0) restores from the
    pool-attach snapshot instead of needing a holder."""
    writes = [(0, "a"), (1, "a")]
    k, holders, vcut = dtd_skip_prefix(
        {0: 5, 1: 5}, {0: {"a": 2, "b": 7}, 1: {}}, writes)
    assert k == 5
    assert holders == {"a": 0} and vcut == {"a": 2}


def test_dtd_skip_prefix_multi_tile_intersection():
    """Two tiles: the agreed K must satisfy BOTH materializable cuts
    simultaneously."""
    writes = [(0, "a"), (1, "b"), (2, "a"), (3, "b")]
    landed = {0: {"a": 2, "b": 1}, 1: {"a": 1, "b": 2}}
    k, holders, vcut = dtd_skip_prefix({0: 4, 1: 4}, landed, writes)
    assert k == 4
    assert vcut == {"a": 2, "b": 2}
    assert holders == {"a": 0, "b": 1}
    # rank 1's b-ladder stops at v1: K drops to where both cuts hold
    k, _h, vcut = dtd_skip_prefix(
        {0: 4, 1: 4}, {0: {"a": 2, "b": 1}, 1: {"a": 1, "b": 1}},
        writes)
    assert k == 3 and vcut == {"a": 2, "b": 1}


# ---------------------------------------------------------------------------
# DTD skip machinery: pool-level replay (ghost prefix, holder seeding,
# tid-gated filter) and the pool-side full votes
# ---------------------------------------------------------------------------

def _dtd_chain_pool(ctx, steps=10):
    import numpy as np
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import INOUT, DTDTaskpool
    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=1, myrank=0, name="Vsk")
    V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("skiptest")
    tp.recovery_collections = [V]
    ctx.add_taskpool(tp)
    ctx.start()
    t = tp.tile_of(V, 0)

    def step(T):
        return T + 1.0
    for _ in range(steps):
        tp.insert_task(step, (t, INOUT))
    tp.wait(timeout=30)
    return V, tp, t, step


def test_dtd_skip_replay_ghosts_prefix_and_seeds_holder():
    """Single-pool replay mechanics, deterministically: arm a skip at
    K=6 with this rank the holder of the seeded v6 cut — the replay
    ghost-tracks 6 inserts (versions advance, no body runs), the
    finalize seeds the cut payload, and exactly the 4 post-prefix
    bodies re-run to the exact final value."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import TaskpoolState
    from parsec_tpu.core.termdet import TermdetState
    from parsec_tpu.dsl.dtd import INOUT
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        V, tp, t, step = _dtd_chain_pool(ctx, steps=10)
        assert tp._lineage is not None
        rep = tp.dtd_skip_report()
        assert rep.get("full") is None and rep["frontier"] == 10
        wire = t.wire_key
        assert rep["landed"] == {wire: 10}
        # drive the restart shape _restart_pool uses
        tp.state = TaskpoolState.ATTACHED
        tp.run_epoch += 1
        assert tp.termdet.taskpool_reset(tp, force_terminated=True) \
            == TermdetState.TERMINATED
        with ctx._lock:
            ctx._active_taskpools += 1
        tp._done_event.clear()
        tp.termdet.taskpool_addto_runtime_actions(tp, 1)
        tp.recovery_reset()
        tp.dtd_arm_skip(6, {wire: 0},
                        {wire: np.full(4, 6.0, np.float32)}, {wire: 6})
        t2 = tp.tile_of(V, 0)
        for _ in range(10):
            tp.insert_task(step, (t2, INOUT))
        tp.dtd_skip_finish()
        tp.ready()
        assert tp.wait_local(30)
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, 10.0)
        assert sorted(tp._pos_done) == [6, 7, 8, 9]  # prefix ghosted
        # one skip per generation: the next death votes full
        assert tp.dtd_skip_report().get("full")
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_dtd_skip_report_votes_full_on_unskippable_pools():
    """Region lanes and tile_new wire keys latch the pool unskippable
    (the report votes full instead of planning from partial
    evidence)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.dsl.dtd import (INOUT, INPUT, DTDTaskpool, Region)
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        from parsec_tpu.data.matrix import VectorTwoDimCyclic
        V = VectorTwoDimCyclic(mb=4, lm=4, nodes=1, myrank=0,
                               name="Vrl")
        tp = DTDTaskpool("regions")
        tp.recovery_collections = [V]
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(V, 0)
        tp.insert_task(lambda T: None,
                       (t, INPUT | Region("u", (slice(0, 2),))))
        tp.wait(timeout=30)
        assert tp.dtd_skip_report()["full"] == "region lanes"

        tp2 = DTDTaskpool("news")
        tp2.recovery_collections = [V]
        ctx.add_taskpool(tp2)
        tn = tp2.tile_new((4,))
        tp2.insert_task(lambda T: T + 1.0, (tn, INOUT))
        tp2.wait(timeout=30)
        assert "tile_new" in tp2.dtd_skip_report()["full"]
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def _stub_rde(rank, peers, sent):
    import types
    ce = types.SimpleNamespace(
        rank=rank, nranks=max([rank] + list(peers)) + 1,
        dead_peers=set(),
        send_am=lambda tag, dst, payload: sent.append((dst, payload)))
    return types.SimpleNamespace(
        ce=ce, _live_peers=lambda: list(peers),
        recovery_coordinator=lambda: min([rank] + list(peers)))


def test_dtd_skip_round_coordinator_cuts_and_broadcasts():
    """Coordinator side of the skip round: a pre-delivered peer report
    (divergent frontier) cuts the prefix; a report from a FOREIGN rank
    (one that rejoined mid-round — not in the round's peer snapshot)
    is ignored."""
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        V, tp, t, _step = _dtd_chain_pool(ctx, steps=10)
        rec = ctx.recovery
        sent = []
        rec._rde = _stub_rde(0, [2], sent)
        wire = t.wire_key
        with rec._ctl_cond:
            rec._skip_reports[(tp.taskpool_id, 2)] = \
                (0, {"frontier": 6, "landed": {wire: 4}})
            # rank 3 rejoined mid-round: its unsolicited report must
            # not join the quorum (it is not in the peer snapshot)
            rec._skip_reports[(tp.taskpool_id, 3)] = \
                (0, {"frontier": 1, "landed": {}})
        spec = {"tp": tp, "collections": tp.recovery_collections,
                "replay": lambda tp: None}
        skip = rec._plan_dtd_skip(tp, spec, {1})
        # K honors rank 2's held v4 cut, not its frontier of 6 (this
        # rank holds v10, which no K <= 6 can use)
        assert skip["prefix"] == 4
        assert skip["holders"] == {wire: 2}
        assert skip["seeds"] == {}          # rank 2 holds the cut
        # the agreed prefix was broadcast to the round's peers only
        assert [d for d, _m in sent] == [2]
        assert sent[0][1]["k"] == "skipset" \
            and sent[0][1]["prefix"] == 4
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_dtd_skip_round_peer_full_vote_converges_gang():
    """A survivor whose lineage ring evicted votes full: the
    coordinator broadcasts prefix 0 (everyone falls back FAST instead
    of timing out) and takes the full replay itself."""
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        _V, tp, _t, _step = _dtd_chain_pool(ctx, steps=10)
        rec = ctx.recovery
        sent = []
        rec._rde = _stub_rde(0, [2], sent)
        with rec._ctl_cond:
            rec._skip_reports[(tp.taskpool_id, 2)] = \
                (0, {"full": "evicted ring"})
        spec = {"tp": tp, "collections": tp.recovery_collections,
                "replay": lambda tp: None}
        with pytest.raises(RecoveryUnsupported, match="voted full"):
            rec._plan_dtd_skip(tp, spec, {1})
        assert sent and sent[0][1]["prefix"] == 0
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_dtd_skip_round_participant_timeout_falls_back():
    """Participant side with no coordinator broadcast (a coordinator
    that died — or was displaced by a rejoin — mid-round): the bounded
    wait expires into the full-replay fallback instead of a hang."""
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        _V, tp, _t, _step = _dtd_chain_pool(ctx, steps=10)
        rec = ctx.recovery
        sent = []
        # this rank is NOT the coordinator: ce.rank 2, coordinator 0
        rde = _stub_rde(2, [0], sent)
        rec._rde = rde
        rec.agree_timeout = 0.2
        spec = {"tp": tp, "collections": tp.recovery_collections,
                "replay": lambda tp: None}
        t0 = time.monotonic()
        with pytest.raises(RecoveryUnsupported, match="never arrived"):
            rec._plan_dtd_skip(tp, spec, {1})
        assert time.monotonic() - t0 < 2.0
        # the report reached the coordinator before the wait
        assert sent and sent[0][0] == 0 and sent[0][1]["k"] == "skipf"
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


# ---------------------------------------------------------------------------
# multi-round need negotiation (r15): a widened closure re-negotiates
# against frozen plans instead of falling back
# ---------------------------------------------------------------------------

def _need_round_harness(ctx, cap):
    """A RecoveryCoordinator wired for _plan_minimal control-flow
    tests: _compute_minimal is a recorded stub that simulates a peer's
    re-feed seed landing MID-WINDOW (the merged closure then widens
    the remote needs — the exact r12 fallback shape)."""
    rec = ctx.recovery
    rec.need_rounds_cap = cap
    rec.agree_window = 0.01
    rec._rde = _stub_rde(0, [2], [])

    from parsec_tpu.core.recovery import ReplayPlan
    calls = {"negotiated": []}

    def compute(tp, spec, dead_set, extra):
        plan = ReplayPlan()
        plan.tasks = {"A"} | set(extra)
        if not extra:
            # a peer's need lands inside the pre-freeze window: the
            # freeze pops it and the recompute widens the needs
            with rec._ctl_cond:
                rec._extra_seeds[tp.taskpool_id] = {"B"}
        else:
            # the merged seed closure reaches a producer on rank 2
            plan.needs = [(2, "W", "F")]
        return plan

    def negotiate(tp, needs):
        calls["negotiated"].append(list(needs))
        return True

    rec._compute_minimal = compute
    rec._negotiate_needs = negotiate
    return rec, calls


def test_plan_minimal_second_round_recovers_widened_needs():
    """The r12 fallback shape — merged re-feed seeds widen the remote
    needs after the freeze — now negotiates a SECOND round and stays
    minimal, counter-proven (widened + acked move, exhausted does
    not)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        rec, calls = _need_round_harness(ctx, cap=2)
        tp = Taskpool("nr")
        before = dict(rec.need_round_counts)
        plan = rec._plan_minimal(tp, {"tp": tp}, {1})
        assert "B" in plan.tasks
        assert calls["negotiated"] == [[(2, "W", "F")]]
        after = rec.need_round_counts
        assert after["widened"] == before["widened"] + 1
        assert after["acked"] == before["acked"] + 1
        assert after["exhausted"] == before["exhausted"]
        # the frozen replay set is published for peers' second rounds
        with rec._ctl_cond:
            assert rec._frozen_tasks[tp.taskpool_id] == plan.tasks
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_plan_minimal_round_cap_exhausts_to_full():
    """recovery_need_rounds=0 restores the r12 single-shot behavior:
    a widened closure falls back, counted as exhausted."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        rec, calls = _need_round_harness(ctx, cap=0)
        tp = Taskpool("nr0")
        with pytest.raises(RecoveryUnsupported,
                           match="recovery_need_rounds"):
            rec._plan_minimal(tp, {"tp": tp}, {1})
        assert rec.need_round_counts["exhausted"] == 1
        assert not calls["negotiated"]
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_handle_need_acks_frozen_plan_when_covered():
    """A second-round need against a FROZEN plan acks iff the resolved
    producers are already in the frozen replay set (the r12
    unconditional nack forced full replays the plan satisfied
    anyway)."""
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        rec = ctx.recovery
        sent = []
        rec._rde = _stub_rde(0, [2], sent)
        tp, tc = _frozen_need_pool(ctx)
        tpid = tp.taskpool_id
        with rec._lock:
            rec._active.add(tpid)
        with rec._ctl_cond:
            rec._plan_state[tpid] = "frozen"
            rec._frozen_tasks[tpid] = {("W", 0), ("W", 1)}
        rec._handle_need(2, {"tp": tpid,
                             "needs": [[("W", 1), "P"]]})
        assert sent[-1][1] == {"k": "need_ack", "tp": tpid, "ok": True}
        # a need whose producer the frozen plan does NOT re-run nacks
        with rec._ctl_cond:
            rec._frozen_tasks[tpid] = {("W", 5)}
        rec._handle_need(2, {"tp": tpid,
                             "needs": [[("W", 1), "P"]]})
        assert sent[-1][1]["ok"] is False
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def _frozen_need_pool(ctx):
    """A 2-task chain pool whose need edges _resolve_need can invert:
    W(i) reads P from W(i-1)."""
    from parsec_tpu.core.task import (Dep, FromDesc, FromTask, READ,
                                      RW, TaskClass, ToDesc, ToTask)
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    V = VectorTwoDimCyclic(mb=2, lm=8, nodes=1, myrank=0, name="Vfn")
    V.set_init(lambda m, n=0: np.zeros(2, np.float32))
    tc = TaskClass(
        "W", params=[("i", lambda g, l: range(4))],
        affinity=lambda loc, V=V: V(loc["i"]),
        flows=[READ("P",
                    inputs=[Dep(FromTask("W", "T",
                                         lambda loc:
                                         {"i": loc["i"] - 1}),
                                guard=lambda loc: loc["i"] > 0)]),
               RW("T",
                  inputs=[Dep(FromDesc(lambda loc, V=V: V(loc["i"])))],
                  outputs=[Dep(ToTask("W", "P",
                                      lambda loc: {"i": loc["i"] + 1}),
                               guard=lambda loc: loc["i"] < 3),
                           Dep(ToDesc(lambda loc, V=V: V(loc["i"])))])],
        incarnations=[("cpu", lambda es, task: None)])
    tp = ParameterizedTaskpool("fn")
    tp.add_task_class(tc)
    tp.recovery_collections = [V]
    ctx.add_taskpool(tp)
    ctx.wait(timeout=30)
    return tp, tc


# ---------------------------------------------------------------------------
# completed-pool retirement handshake (r15): coordinator confirms
# global quiescence before a pool leaves restartable state
# ---------------------------------------------------------------------------

def test_retirement_handshake_coordinator_quorum():
    """Coordinator side: local completion alone keeps the pool
    restartable; once EVERY live rank reported, the pool retires, the
    confirmation broadcasts, and the counter moves."""
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import TaskpoolState
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    sent = []
    ce.send_am = lambda tag, dst, payload: sent.append((dst, payload))
    try:
        from parsec_tpu.core.taskpool import Taskpool
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2,
                              myrank=0, name="Aret")
        tp = Taskpool("ret")
        tp.recovery_collections = [A]
        ctx.add_taskpool(tp)
        rec = ctx.recovery
        tp.state = TaskpoolState.DONE          # locally complete
        rec._pool_done(tp)
        assert not tp.retired                  # rank 1 outstanding
        with rec._lock:
            assert rec._specs[tp.taskpool_id]["completed_at"] \
                is not None
        rec._on_recover_msg(1, {"k": "retire", "tp": tp.taskpool_id})
        assert tp.retired and rec.retirements == 1
        assert any(p.get("k") == "retired" for _d, p in sent)
        # retired pools are never recovery candidates again
        handled, leave = rec.on_peer_dead(
            1, PeerFailedError(1, "x", detector="close"), [])
        assert handled and leave == []
        with rec._lock:
            assert tp.taskpool_id not in rec._active
        tp.cancel()
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()
        params.set("recovery_enable", 0)


def test_retirement_broadcast_applies_on_peer():
    """Non-coordinator side: the coordinator's confirmed ``retired``
    broadcast retires a locally-complete pool; a pool mid-restart
    ignores a stale confirmation."""
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool, TaskpoolState
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    ce.send_am = lambda tag, dst, payload: None
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2,
                              myrank=0, name="Bret")
        tp = Taskpool("ret1")
        tp.recovery_collections = [A]
        ctx.add_taskpool(tp)
        rec = ctx.recovery
        tp.state = TaskpoolState.DONE
        # a restart owns the pool: the stale confirmation is ignored
        with rec._lock:
            rec._active.add(tp.taskpool_id)
        rec._on_recover_msg(0, {"k": "retired", "tp": tp.taskpool_id})
        assert not tp.retired
        with rec._lock:
            rec._active.discard(tp.taskpool_id)
        rec._on_recover_msg(0, {"k": "retired", "tp": tp.taskpool_id})
        assert tp.retired
        tp.cancel()
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()
        params.set("recovery_enable", 0)


def test_single_rank_pool_retires_at_completion():
    """No peers: local completion IS global quiescence — the pool
    leaves restartable state immediately instead of dangling through
    the 30 s grace window."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        from parsec_tpu.apps.potrf import potrf_taskpool
        n, mb = 32, 16
        rng = np.random.default_rng(2)
        a = rng.standard_normal((n, n)).astype(np.float32)
        spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                              name="Aret1").from_array(spd.copy())
        tp = potrf_taskpool(A, device="cpu")
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
        assert tp.retired
        assert ctx.recovery.retirements >= 1
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_retirement_succession_on_coordinator_death():
    """Coordinator succession (r17): the handshake coordinator dying
    with the collected reports must NOT degrade retirement to the
    grace window — survivors re-report to the new lowest live rank,
    which re-collects quorum over the shrunken live set."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool, TaskpoolState
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=3,
                              myrank=1, name="Asucc")
        tp = Taskpool("succ")
        tp.recovery_collections = [A]
        ctx.add_taskpool(tp)
        rec = ctx.recovery
        sent = []
        rec._rde = _stub_rde(1, [0, 2], sent)   # we are rank 1 of 3
        tp.state = TaskpoolState.DONE
        rec._pool_done(tp)
        # report went to the original coordinator (rank 0), who now
        # dies with it — the pool must still be restartable
        assert sent and sent[-1] == (0, {"k": "retire",
                                         "tp": tp.taskpool_id})
        assert not tp.retired
        rec._rde = _stub_rde(1, [2], sent)      # rank 0 died
        rec._rde.ce.dead_peers.add(0)
        rec._succeed_retirements(0)
        # this rank became coordinator and re-recorded its own report;
        # quorum over the live set {1, 2} still waits on rank 2
        assert not tp.retired
        evs = [ev for ev in ctx.journal.tail(256)
               if ev.get("e") == "retire_succession"]
        assert evs and evs[-1]["pool"] == tp.taskpool_id \
            and evs[-1]["coord"] == 1
        # rank 2's succession re-report completes quorum -> retired
        rec._on_recover_msg(2, {"k": "retire", "tp": tp.taskpool_id})
        assert tp.retired and rec.retirements == 1
        assert (2, {"k": "retired", "tp": tp.taskpool_id}) in sent
        tp.cancel()
    finally:
        ctx.fini()
        params.set("recovery_enable", 0)


def test_refired_completion_emits_exactly_one_job_done():
    """Service seam: a recovery restart re-firing a completed pool's
    termination callbacks is absorbed below the service — exactly ONE
    terminal job_done per job (SLO histograms and waiters would
    otherwise double-observe)."""
    from parsec_tpu.service.service import JobService
    from parsec_tpu.core.taskpool import Taskpool
    svc = JobService(max_active=1, nb_cores=1)
    try:
        events = []
        svc.context.pins_register(
            "job_done", lambda es, ev, job: events.append(job.job_id))
        job = svc.submit(lambda: Taskpool("j1"), name="j1")
        assert job.wait(10)
        deadline = time.monotonic() + 5
        while not events and time.monotonic() < deadline:
            time.sleep(0.01)
        assert events == [job.job_id]
        # the recovery restart re-fires the pool's completion path
        svc._finish(job)
        svc._finish(job)
        time.sleep(0.05)
        assert events == [job.job_id]
    finally:
        svc.shutdown(timeout=10)


# ---------------------------------------------------------------------------
# partition re-mapping: per-collection rank translation
# ---------------------------------------------------------------------------

def test_rank_translation_adopts_partition():
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=16, ln=16, nodes=2, myrank=0,
                          name="A")
    mine = set(A.local_tiles())
    assert all(A.rank_of(m, n) == 0 for m, n in mine)
    A.set_rank_translation({1: 0})
    try:
        # rank_of stays the pure distribution; owner_of routes around
        assert any(A.rank_of(m, n) == 1
                   for m in range(A.mt) for n in range(A.nt))
        assert all(A.owner_of(m, n) == 0
                   for m in range(A.mt) for n in range(A.nt))
        adopted = set(A.local_tiles()) - mine
        assert adopted, "dead rank's tiles must appear local"
        m, n = sorted(adopted)[0]
        assert A.data_of(m, n) is not None    # materializes, no raise
    finally:
        A.set_rank_translation(None)
    assert set(A.local_tiles()) == mine


def test_rank_translation_is_per_collection():
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2, myrank=0,
                          name="A")
    B = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2, myrank=0,
                          name="B")
    A.set_rank_translation({1: 0})
    try:
        assert len(A.local_tiles()) == 4
        assert len(B.local_tiles()) == 2      # B untouched
    finally:
        A.set_rank_translation(None)


def test_taskclass_rank_of_translates():
    from parsec_tpu.core.task import TaskClass
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=16, ln=16, nodes=2, myrank=0,
                          name="A")
    tc = TaskClass("T", params=[("m", lambda g, l: range(4))],
                   affinity=lambda loc, A=A: A(0, loc["m"]))
    ranks = {m: tc.rank_of({"m": m}) for m in range(4)}
    assert 1 in ranks.values()
    A.set_rank_translation({1: 0})
    try:
        assert all(tc.rank_of({"m": m}) == 0 for m in range(4))
    finally:
        A.set_rank_translation(None)


# ---------------------------------------------------------------------------
# termdet rewind + run_epoch fence
# ---------------------------------------------------------------------------

def test_termdet_reset_rewinds_without_firing():
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.core.termdet import LocalTermdet
    td = LocalTermdet()
    tp = Taskpool("t")
    fired = []
    td.monitor(tp, lambda: fired.append(1))
    td.taskpool_addto_runtime_actions(tp, 1)
    td.taskpool_ready(tp)
    td.taskpool_addto_nb_tasks(tp, 5)
    from parsec_tpu.core.termdet import TermdetState
    assert td.taskpool_reset(tp) == TermdetState.BUSY
    assert tp.nb_tasks == 0 and tp.nb_pending_actions == 0
    assert not fired
    # the rewound pool re-runs the attach->ready lifecycle and
    # terminates on the NEW generation's counts only
    td.taskpool_addto_runtime_actions(tp, 1)
    td.taskpool_addto_nb_tasks(tp, 2)
    td.taskpool_ready(tp)
    td.taskpool_addto_runtime_actions(tp, -1)
    td.taskpool_addto_nb_tasks(tp, -2)
    assert fired == [1]
    # a TERMINATED pool refuses the plain rewind (completed
    # concurrently)...
    assert td.taskpool_reset(tp) is None
    # ...but force_terminated rewinds it — local completion is not
    # global completion, and the caller re-arms the released
    # bookkeeping on the returned TERMINATED
    assert td.taskpool_reset(tp, force_terminated=True) \
        == TermdetState.TERMINATED


def test_run_epoch_fence_discards_stale_tasks():
    """A task scheduled before a restart must neither execute nor touch
    the re-counted termdet when it surfaces after the epoch bump."""
    from parsec_tpu.core import scheduling
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.task import Task, TaskClass
    from parsec_tpu.core.taskpool import Taskpool
    ctx = Context(nb_cores=1)
    try:
        tp = Taskpool("fence")
        ran = []
        tc = TaskClass("X", body=lambda: ran.append(1))
        tp.add_task_class(tc)
        ctx.add_taskpool(tp)
        stale = Task(tc, tp, {})
        tp.run_epoch += 1                  # restart happened
        before = tp.nb_tasks
        scheduling.task_progress(ctx.streams[0], stale)
        assert not ran
        assert tp.nb_tasks == before       # no decrement
        scheduling.complete_execution(ctx.streams[0], stale)
        assert tp.nb_tasks == before
        tp.cancel()
        ctx.wait(timeout=10)
    finally:
        ctx.fini()


def test_recovery_busy_blocks_quiescence_idle():
    """A queued/active recovery restart must hold global quiescence
    open: _local_idle stays False and the sole-survivor short-circuit
    waits — otherwise Context.wait hands tiles to the application
    while the restore rewinds them (the completed-pool-grace race)."""
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    try:
        rec = ctx.recovery
        assert not rec.busy() and rde._local_idle()
        with rec._lock:
            rec._pending_dead.add(1)     # death accepted, not processed
        assert rec.busy()
        assert not rde._local_idle()     # quiescence must not pass
        with pytest.raises(TimeoutError):
            rde._wait_recovery_idle(time.monotonic() + 0.1)
        with rec._lock:
            rec._pending_dead.clear()
        assert rde._local_idle()
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()
        params.set("recovery_enable", 0)


def test_stale_body_discard_taints_tile_versions():
    """A stale-generation body that RAN may have mutated its write-flow
    tiles in place without a version bump (complete_write is skipped by
    the discard).  The epoch-fence discard must advance those version
    clocks, or minimal replay would synthesize from a 'live-intact'
    payload that is neither — the silent-corruption class the chaos
    smoke caught under load."""
    from parsec_tpu.core import scheduling
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.task import RW, Task, TaskClass
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4, name="At")
        d = A.data_of(0, 0)
        tp = Taskpool("taint")
        tc = TaskClass("X", flows=[RW("T")], body=lambda T: None)
        tp.add_task_class(tc)
        ctx.add_taskpool(tp)
        stale = Task(tc, tp, {})
        stale.data["T"] = d.copy_on(0)
        before = d.newest_version()
        tp.run_epoch += 1            # a restart fenced the generation
        scheduling.complete_execution(ctx.streams[0], stale)
        assert d.newest_version() > before   # the mutation is visible
        tp.cancel()
        ctx.wait(timeout=10)
    finally:
        ctx.fini()


# ---------------------------------------------------------------------------
# incarnation-epoch frame fencing + Safra reconcile
# ---------------------------------------------------------------------------

def test_epoch_fence_drops_stale_incarnation_frames():
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    try:
        with rde._term_lock:
            rde._sent_to[1] = 3
            rde._recv_from[1] = 2
            rde._app_sent += 3
            rde._app_recv += 2
        rde.recovery_reconcile(1)
        assert rde._balance() == 0         # dead contribution removed
        # a pre-death straggler (no _ep) is fenced WITHOUT a credit
        rde._activate_cb(1, {"tp": 999, "_fid": (1, 7)})
        assert rde._balance() == 0
        with rde._dlock:
            assert not rde._delayed        # not even parked
        # the rejoined incarnation (epoch 1) passes the fence
        rde.note_peer_epoch(1, 1)
        rde._activate_cb(1, {"tp": 999, "_ep": 1, "_fid": (1, 1 << 48)})
        with rde._term_lock:
            assert rde._app_recv == 1      # credited
        with rde._dlock:
            assert rde._delayed            # parked for the unknown pool
            rde._delayed.clear()           # stop the retry timer chain
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()


def test_pool_epoch_gate_drops_and_parks_activations():
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    try:
        tp = Taskpool("gate")
        ctx.add_taskpool(tp, start=True)
        tp.run_epoch = 2
        base = {"tp": tp.taskpool_id, "root": 1, "ranks": [0],
                "deliveries": {}, "data": None}
        rde._try_activation(1, {**base, "pe": 1})   # torn generation
        with rde._dlock:
            assert not rde._delayed                 # dropped outright
        rde._try_activation(1, {**base, "pe": 3})   # future generation
        with rde._dlock:
            assert len(rde._delayed) == 1           # parked, not lost
        # once the local restart catches up, the parked frame delivers
        tp.run_epoch = 3
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            rde.retry_delayed()
            with rde._dlock:
                if not rde._delayed:
                    break
            time.sleep(0.02)
        with rde._dlock:
            assert not rde._delayed
        tp.cancel()
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()


# ---------------------------------------------------------------------------
# checkpoint under a degraded context (satellite bugfix)
# ---------------------------------------------------------------------------

def test_checkpoint_degraded_fails_fast(tmp_path):
    import types
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.checkpoint import checkpoint, restore
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, name="A")
        A.data_of(0, 0)
        path = str(tmp_path / "ck")
        # healthy single-rank checkpoint still works
        checkpoint(ctx, [A], path)
        # a dead, UNEXCUSED peer fails fast with the structured error
        # instead of wedging in the collective barrier
        ctx.comm = types.SimpleNamespace(
            ce=types.SimpleNamespace(dead_peers={1}, excused_peers=set()))
        with pytest.raises(CheckpointDegradedError) as ei:
            checkpoint(ctx, [A], str(tmp_path / "ck2"))
        assert ei.value.ranks == [1]
        with pytest.raises(CheckpointDegradedError):
            restore(ctx, [A], path)
        # an EXCUSED death proceeds (the barrier narrowed to survivors;
        # nranks=1 here so no wire traffic) and records the marker
        ctx.comm = None
        restore(ctx, [A], path)
    finally:
        ctx.comm = None
        ctx.fini()


def test_checkpoint_records_excused_marker(tmp_path):
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.checkpoint import checkpoint
    import types
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, name="A")
        A.data_of(0, 0)

        class _BarrierCE:
            dead_peers = {1}
            excused_peers = {1}

            def barrier(self, timeout=60.0):
                pass
        ctx.comm = types.SimpleNamespace(ce=_BarrierCE())
        out = checkpoint(ctx, [A], str(tmp_path / "ck"))
        with np.load(out) as zf:
            assert list(zf["__excused__"]) == [1]
    finally:
        ctx.comm = None
        ctx.fini()


# ---------------------------------------------------------------------------
# service bookkeeping: degraded -> recovering -> healthy (satellite)
# ---------------------------------------------------------------------------

def test_service_recovery_state_transitions():
    from parsec_tpu.service.service import JobService
    svc = JobService(max_active=1, nb_cores=1)
    try:
        assert svc.stats()["recovering"] is False
        svc.note_recovery("start", 1)
        st = svc.stats()
        assert st["degraded"] and st["degraded_ranks"] == [1]
        assert st["recovering"] and st["recovering_ranks"] == [1]
        svc.note_recovery("done", 1)
        st = svc.stats()
        assert not st["degraded"] and not st["recovering"]
        # a failed recovery leaves the degradation standing
        svc.note_recovery("start", 2)
        svc.note_recovery("failed", 2)
        st = svc.stats()
        assert st["degraded_ranks"] == [2] and not st["recovering"]
        # ...until the rank rejoins
        svc.note_recovery("rejoin", 2)
        assert svc.stats()["degraded"] is False
    finally:
        svc.shutdown(timeout=10)


# ---------------------------------------------------------------------------
# end to end: kill -> recover -> COMPLETED with correct numerics
# ---------------------------------------------------------------------------

def test_kill_close_recovers_potrf():
    """The acceptance shape: a 2-rank potrf whose peer hard-dies
    mid-run COMPLETES on the survivor with validated numbers (adopted
    tiles included — local_tiles routes through the translation)."""
    import chaos
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=11;kill_rank=1@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=150",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "45"},
        timeout=90, tolerate_ranks=(1,))
    assert res[0] == "ok" and res[1] is None   # victim actually died


def test_kill_close_recovers_dtd_chain():
    """DTD lineage replay: the insert stream re-runs on the survivor
    against the snapshot-restored tile — EXACT final value."""
    import chaos
    res = _run_distributed_with_env(
        chaos.dtd_chain_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=7;kill_rank=1@t+1.2s,mode=close;"
         "delay_frame=tag:DTD,p=1,ms=60",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "30"},
        timeout=90, tolerate_ranks=(1,))
    assert res[0] == "ok" and res[1] is None


def test_kill_rank_zero_recovers_on_new_root():
    """Killing rank 0 exercises the generalized ring/barrier root: the
    surviving rank 1 becomes coordinator, initiator, AND barrier root,
    adopts rank 0's partition, and completes with validated numbers."""
    import chaos
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=13;kill_rank=0@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=150",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "45"},
        timeout=90, tolerate_ranks=(0,))
    assert res[1] == "ok" and res[0] is None


def test_recovery_disabled_reproduces_containment():
    """PARSEC_MCA_RECOVERY_ENABLE=0 (the default): the same kill plan
    fails the pool with the PR 5 structured PeerFailedError — recovery
    never engages implicitly."""
    import chaos
    with pytest.raises(RuntimeError) as ei:
        _run_distributed_with_env(
            chaos.potrf_recover_workload, 2,
            {"PARSEC_MCA_FAULT_PLAN":
             "seed=11;kill_rank=1@t+1.0s,mode=close;"
             "delay_frame=tag:ACT,p=1,ms=150",
             "PARSEC_MCA_RECOVERY_ENABLE": "0",
             "PARSEC_CHAOS_WAIT_S": "30"},
            timeout=90)
    assert "PeerFailedError" in str(ei.value)


# ---------------------------------------------------------------------------
# elastic rejoin: killed -> restarted -> serving its partition again.
# Parametrized over transports: shm exercises the ring RE-CREATION in
# the TAG_REJOIN handshake (previously the one transport that could
# not rejoin — the receiver's unlink left no ring to come back to).
# ---------------------------------------------------------------------------

@pytest.mark.parametrize("transport", ["evloop", "shm"])
def test_killed_rank_rejoins_and_serves(transport):
    import chaos
    ok, detail = chaos.rejoin_scenario(transport, timeout=150.0)
    assert ok, detail


# ---------------------------------------------------------------------------
# lineage recording + the incremental checkpoint plane
# ---------------------------------------------------------------------------

def test_lineage_log_records_completed_tasks():
    """With recovery armed, every completed task of a registered pool
    lands in the ring with flow-keyed, version-stamped reads/writes —
    and the write versions march the datum version clock upward (the
    chain the minimal planner walks)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    try:
        ctx = Context(nb_cores=1)
        try:
            from parsec_tpu.apps.potrf import potrf_taskpool
            n, mb = 32, 16
            rng = np.random.default_rng(2)
            a = rng.standard_normal((n, n)).astype(np.float32)
            spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
            A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                                  name="Alin").from_array(spd.copy())
            tp = potrf_taskpool(A, device="cpu")
            ctx.add_taskpool(tp)
            assert tp._lineage is not None     # armed at registration
            ctx.wait(timeout=30)
            lin = tp._lineage
            assert not lin.overflow
            assert len(lin.records) == len(lin.completed) > 0
            by_key = {r.key: r for r in lin.records}
            # every task class completed and recorded tile writes
            names = {k[0] for k in by_key}
            assert {"POTRF", "TRSM", "SYRK", "POTRFL"} <= names
            # the diagonal chain: SYRK(1, 0)'s T write supersedes its
            # T read of the same tile (in-place version discipline)
            rec = by_key[("SYRK", 1, 0)]
            rt, rv = rec.rmap["T"]
            wt, wv = rec.wmap["T"]
            assert rt == wt == ("Alin", 1, 1)
            assert wv > rv
        finally:
            ctx.fini()
    finally:
        params.set("recovery_enable", 0)


def test_tile_checkpoint_store_interval_and_keep():
    from parsec_tpu.utils.checkpoint import TileCheckpointStore
    st = TileCheckpointStore(3600.0, keep=2)    # huge interval
    st.note_write(("a", 0, 0), 1, np.ones(4))
    st.note_write(("a", 0, 0), 2, np.full(4, 2.0))   # inside interval
    assert st.versions(("a", 0, 0)) == (1,)          # rate-bounded
    st2 = TileCheckpointStore(0.0, keep=2)      # capture every write
    for v in (1, 2, 3):
        st2.note_write(("a", 0, 0), v, np.full(4, float(v)))
    assert st2.versions(("a", 0, 0)) == (2, 3)  # keep bound evicts v1
    np.testing.assert_allclose(st2.get(("a", 0, 0), 3), 3.0)
    assert st2.get(("a", 0, 0), 1) is None


def test_lineage_hook_feeds_checkpoint_store():
    """recovery_checkpoint_interval_s > 0 arms the capture plane: the
    complete_execution lineage hook snapshots version-stamped dirty
    tiles into the store (the replay cut of long version chains)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    params.set("recovery_enable", 1)
    params.set("recovery_checkpoint_interval_s", 0.0001)
    try:
        ctx = Context(nb_cores=1)
        try:
            from parsec_tpu.apps.potrf import potrf_taskpool
            n, mb = 32, 16
            rng = np.random.default_rng(2)
            a = rng.standard_normal((n, n)).astype(np.float32)
            spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
            A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                                  name="Ack").from_array(spd.copy())
            ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
            ctx.wait(timeout=30)
            st = ctx.recovery.ckpt
            assert st is not None and st.captures > 0
            # a captured version is retrievable at its exact stamp;
            # keys scope by COLLECTION IDENTITY so a later job's
            # same-named tiles can never read this job's bytes
            key = (id(A), ("Ack", 0, 0))
            vs = st.versions(key)
            assert vs
            assert st.get(key, vs[-1]) is not None
            # spec retirement evicts the captures with it
            st.drop_owner(id(A))
            assert st.versions(key) == ()
        finally:
            ctx.fini()
    finally:
        params.set("recovery_checkpoint_interval_s", 0.0)
        params.set("recovery_enable", 0)


def test_checkpoint_shards_carry_version_stamps(tmp_path):
    """Format-2 collective shards stamp each tile's version — the
    replay-cut metadata shard_versions reads back."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.checkpoint import (checkpoint, restore,
                                             shard_versions)
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, name="Avz")
        d = A.data_of(0, 0)
        d.overwrite_host(np.ones((4, 4), np.float32))
        A.data_of(1, 1)
        path = str(tmp_path / "ck")
        checkpoint(ctx, [A], path)
        vs = shard_versions(path, 0)
        assert vs["Avz:0:0"] == d.newest_version()
        assert "Avz:1:1" in vs
        # and the stamped shard still restores
        d.overwrite_host(np.zeros((4, 4), np.float32))
        restore(ctx, [A], path)
        np.testing.assert_allclose(
            np.asarray(A.data_of(0, 0).pull_to_host().payload), 1.0)
    finally:
        ctx.fini()


# ---------------------------------------------------------------------------
# end to end: minimal replay, dyn-hold recovery, multi-death agreement
# ---------------------------------------------------------------------------

def test_minimal_replay_reexecutes_strictly_fewer():
    """The headline A/B: on the SAME mid-run kill, recorded-lineage
    minimal replay re-executes strictly fewer tasks than
    replay-from-restore-point, and each leg provably took its path
    (minimal_replays / full_replays counters)."""
    import chaos
    ab = chaos.run_ab_pair(timeout=120.0)
    assert ab["minimal"]["minimal"] >= 1 and ab["minimal"]["full"] == 0
    assert ab["full"]["full"] >= 1
    assert ab["minimal"]["reexec"] < ab["full"]["reexec"], ab


def test_kill_dtd_chain_skip_minimal_sole_survivor():
    """2-rank DTD chain kill: the sole survivor SHORT-CIRCUITS the
    skip agreement to its local view (no wire round), ghost-replays
    the completed prefix, and ends with the exact final value — the
    counters prove the minimal path (full_replays stays 0); the wired
    multi-survivor round is the chaos kill-dtd-minimal 3-rank case."""
    import chaos
    res = _run_distributed_with_env(
        chaos.dtd_ab_chain_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=5;kill_rank=1@t+2.0s,mode=close;"
         "delay_dispatch=key~_dtd_chain_step,ms=100",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "45"},
        timeout=120, tolerate_ranks=(1,))
    surv = res[0]
    assert surv is not None and surv[0] == "ok" and res[1] is None
    assert surv[2] >= 1 and surv[3] == 0    # minimal, never full
    assert surv[4] >= 1                     # skip agreement concluded


def test_kill_recovers_dynamic_taskpool_with_hold():
    """A DynamicTaskpool killed while its distributed termination hold
    is outstanding restarts on the survivor with the hold RE-ARMED
    (previously stranded) and finishes with exact values."""
    import chaos
    res = _run_distributed_with_env(
        chaos.dyn_chain_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=1;kill_rank=1@t+0.8s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "40"},
        timeout=90, tolerate_ranks=(1,))
    assert res[0] == "ok" and res[1] is None


def test_multi_death_agreement_converges_survivors():
    """Two near-simultaneous deaths on a 4-rank gang: the TAG_RECOVER
    agreement round lands both survivors on the SAME confirmed dead
    set and the run completes with validated numerics."""
    import chaos
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 4,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=2;kill_rank=2@t+1.0s,mode=close;"
         "kill_rank=3@t+1.05s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=120;delay_frame=tag:BATCH,p=1,ms=120",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_MCA_RECOVERY_MAX_ATTEMPTS": "3",
         "PARSEC_CHAOS_WAIT_S": "60"},
        timeout=120, tolerate_ranks=(2, 3))
    assert res[0] == "ok" and res[1] == "ok"
    assert res[2] is None and res[3] is None   # both kills fired


# ---------------------------------------------------------------------------
# observability: metrics families + flight-recorder hook
# ---------------------------------------------------------------------------

def test_recovery_metrics_families_scrape():
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    try:
        ctx = Context(nb_cores=1)
        try:
            assert ctx.recovery is not None
            names = {s["n"] for s in ctx.metrics.samples()}
            assert "parsec_recoveries_total" in names
            assert "parsec_tasks_reexecuted_total" in names
            assert "parsec_rank_rejoins_total" in names
            assert "parsec_recovery_duration_seconds" in names
            assert "parsec_recovery_minimal_replays_total" in names
            assert "parsec_recovery_full_replays_total" in names
            stages = {s["l"].get("stage")
                      for s in ctx.metrics.samples()
                      if s["n"] == "parsec_recoveries_total"}
            assert {"started", "completed", "failed"} <= stages
        finally:
            ctx.fini()
    finally:
        params.set("recovery_enable", 0)


# ---------------------------------------------------------------------------
# acceptance (slow): 3-rank mid-run kill, multi-survivor re-execution
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_three_rank_potrf_survives_midrun_kill():
    """Two survivors recover a third's mid-run death TOGETHER: the dead
    partition re-maps onto one adopter, both re-enumerate, cross-rank
    activations of the new generation flow, numerics validate, and the
    killed run stays within ~2x the no-fault makespan (the ISSUE
    bound; the loose assert guards the invariant under host noise —
    the measured ratio is recorded in BENCH.md)."""
    import chaos
    env = {"PARSEC_MCA_RECOVERY_ENABLE": "1",
           "PARSEC_CHAOS_WAIT_S": "60"}
    t0 = time.monotonic()
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 3,
        {**env, "PARSEC_MCA_FAULT_PLAN":
         "seed=4;delay_frame=tag:ACT,p=1,ms=120"},
        timeout=120)
    base_s = time.monotonic() - t0
    assert res == ["ok", "ok", "ok"]
    t0 = time.monotonic()
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 3,
        {**env, "PARSEC_MCA_FAULT_PLAN":
         "seed=4;kill_rank=2@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=120"},
        timeout=180, tolerate_ranks=(2,))
    kill_s = time.monotonic() - t0
    assert res[0] == "ok" and res[1] == "ok"
    ratio = kill_s / max(base_s, 1e-9)
    print(f"3-rank mid-run kill: baseline {base_s:.1f}s, "
          f"killed {kill_s:.1f}s, ratio {ratio:.2f}x")
    assert ratio < 3.0, (base_s, kill_s)


@pytest.mark.slow
def test_chaos_recover_catalog():
    """The full recovery catalog (close/hang x evloop/shm/threads +
    DTD + minimal replay + the DTD skip agreement + dyn holds +
    multi-death agreement + survivor exhaustion, plus the shm
    kill->restart->rejoin leg) through the chaos harness."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--recover", "--seeds", "12", "--timeout", "120"],
        capture_output=True, text=True, timeout=1500,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
