"""Recovery-plane tests (ISSUE 10: contain -> RECOVER -> rejoin).

Unit layers: the lineage planner's minimal re-execution set on
hand-built DAGs, the per-collection rank translation, the termdet
rewind, the run_epoch task fence, incarnation-epoch frame fencing, the
degraded-checkpoint fail-fast, and the service's degraded -> recovering
-> healthy bookkeeping.

End to end: 2-rank kill_rank plans (PTG potrf and DTD chain) that END
IN COMPLETED, NUMERICALLY VALIDATED jobs on the survivor; recovery
disabled reproducing PR 5's containment; a killed-then-restarted rank
rejoining over TAG_REJOIN and serving its partition again; and the
slow 3-rank mid-run-kill acceptance run with the makespan bound.
"""

import multiprocessing as mp
import os
import sys
import time
import traceback

import numpy as np
import pytest

from parsec_tpu.core.errors import (CheckpointDegradedError,
                                    PeerFailedError)
from parsec_tpu.core.recovery import (LineageRecord, RecoveryUnsupported,
                                      lineage_plan)
from parsec_tpu.utils.mca import params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, os.path.join(REPO, "tools"))


def _run_distributed_with_env(fn, nranks, env, timeout=120,
                              tolerate_ranks=()):
    from parsec_tpu.comm.launch import run_distributed
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return run_distributed(fn, nranks, timeout=timeout,
                               tolerate_ranks=tolerate_ranks)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


# ---------------------------------------------------------------------------
# lineage planner: minimal re-execution set on hand-built DAGs
# ---------------------------------------------------------------------------

def test_lineage_plan_minimal_set():
    """Diamond DAG over tiles a/b/c/d; only d's final version is lost
    and b's intermediate survives -> re-execute exactly the producers
    on the lost path, not the whole log."""
    log = [
        LineageRecord("T1", reads=[("a", 0)], writes=[("b", 1)]),
        LineageRecord("T2", reads=[("a", 0)], writes=[("c", 1)]),
        LineageRecord("T3", reads=[("b", 1), ("c", 1)],
                      writes=[("d", 1)]),
        LineageRecord("T4", reads=[("d", 1)], writes=[("d", 2)]),
    ]
    surviving = {"a": 0, "b": 1, "c": 1}       # d died with its rank
    tasks, base = lineage_plan(log, surviving, {"d": 2})
    assert tasks == ["T3", "T4"]               # T1/T2 outputs survive
    assert base == {"b": 1, "c": 1}


def test_lineage_plan_walks_back_to_source():
    """Nothing of the lost chain survives: the walk reaches the version-0
    source (the registration snapshot / init_fn base)."""
    log = [
        LineageRecord("P0", reads=[("x", 0)], writes=[("x", 1)]),
        LineageRecord("P1", reads=[("x", 1)], writes=[("x", 2)]),
    ]
    tasks, base = lineage_plan(log, {"x": 0}, {"x": 2})
    assert tasks == ["P0", "P1"]
    assert base == {"x": 0}


def test_lineage_plan_broken_lineage_raises():
    with pytest.raises(RecoveryUnsupported):
        lineage_plan([], {}, {"ghost": 3})


# ---------------------------------------------------------------------------
# partition re-mapping: per-collection rank translation
# ---------------------------------------------------------------------------

def test_rank_translation_adopts_partition():
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=16, ln=16, nodes=2, myrank=0,
                          name="A")
    mine = set(A.local_tiles())
    assert all(A.rank_of(m, n) == 0 for m, n in mine)
    A.set_rank_translation({1: 0})
    try:
        # rank_of stays the pure distribution; owner_of routes around
        assert any(A.rank_of(m, n) == 1
                   for m in range(A.mt) for n in range(A.nt))
        assert all(A.owner_of(m, n) == 0
                   for m in range(A.mt) for n in range(A.nt))
        adopted = set(A.local_tiles()) - mine
        assert adopted, "dead rank's tiles must appear local"
        m, n = sorted(adopted)[0]
        assert A.data_of(m, n) is not None    # materializes, no raise
    finally:
        A.set_rank_translation(None)
    assert set(A.local_tiles()) == mine


def test_rank_translation_is_per_collection():
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2, myrank=0,
                          name="A")
    B = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, nodes=2, myrank=0,
                          name="B")
    A.set_rank_translation({1: 0})
    try:
        assert len(A.local_tiles()) == 4
        assert len(B.local_tiles()) == 2      # B untouched
    finally:
        A.set_rank_translation(None)


def test_taskclass_rank_of_translates():
    from parsec_tpu.core.task import TaskClass
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=16, ln=16, nodes=2, myrank=0,
                          name="A")
    tc = TaskClass("T", params=[("m", lambda g, l: range(4))],
                   affinity=lambda loc, A=A: A(0, loc["m"]))
    ranks = {m: tc.rank_of({"m": m}) for m in range(4)}
    assert 1 in ranks.values()
    A.set_rank_translation({1: 0})
    try:
        assert all(tc.rank_of({"m": m}) == 0 for m in range(4))
    finally:
        A.set_rank_translation(None)


# ---------------------------------------------------------------------------
# termdet rewind + run_epoch fence
# ---------------------------------------------------------------------------

def test_termdet_reset_rewinds_without_firing():
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.core.termdet import LocalTermdet
    td = LocalTermdet()
    tp = Taskpool("t")
    fired = []
    td.monitor(tp, lambda: fired.append(1))
    td.taskpool_addto_runtime_actions(tp, 1)
    td.taskpool_ready(tp)
    td.taskpool_addto_nb_tasks(tp, 5)
    from parsec_tpu.core.termdet import TermdetState
    assert td.taskpool_reset(tp) == TermdetState.BUSY
    assert tp.nb_tasks == 0 and tp.nb_pending_actions == 0
    assert not fired
    # the rewound pool re-runs the attach->ready lifecycle and
    # terminates on the NEW generation's counts only
    td.taskpool_addto_runtime_actions(tp, 1)
    td.taskpool_addto_nb_tasks(tp, 2)
    td.taskpool_ready(tp)
    td.taskpool_addto_runtime_actions(tp, -1)
    td.taskpool_addto_nb_tasks(tp, -2)
    assert fired == [1]
    # a TERMINATED pool refuses the plain rewind (completed
    # concurrently)...
    assert td.taskpool_reset(tp) is None
    # ...but force_terminated rewinds it — local completion is not
    # global completion, and the caller re-arms the released
    # bookkeeping on the returned TERMINATED
    assert td.taskpool_reset(tp, force_terminated=True) \
        == TermdetState.TERMINATED


def test_run_epoch_fence_discards_stale_tasks():
    """A task scheduled before a restart must neither execute nor touch
    the re-counted termdet when it surfaces after the epoch bump."""
    from parsec_tpu.core import scheduling
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.task import Task, TaskClass
    from parsec_tpu.core.taskpool import Taskpool
    ctx = Context(nb_cores=1)
    try:
        tp = Taskpool("fence")
        ran = []
        tc = TaskClass("X", body=lambda: ran.append(1))
        tp.add_task_class(tc)
        ctx.add_taskpool(tp)
        stale = Task(tc, tp, {})
        tp.run_epoch += 1                  # restart happened
        before = tp.nb_tasks
        scheduling.task_progress(ctx.streams[0], stale)
        assert not ran
        assert tp.nb_tasks == before       # no decrement
        scheduling.complete_execution(ctx.streams[0], stale)
        assert tp.nb_tasks == before
        tp.cancel()
        ctx.wait(timeout=10)
    finally:
        ctx.fini()


# ---------------------------------------------------------------------------
# incarnation-epoch frame fencing + Safra reconcile
# ---------------------------------------------------------------------------

def test_epoch_fence_drops_stale_incarnation_frames():
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    try:
        with rde._term_lock:
            rde._sent_to[1] = 3
            rde._recv_from[1] = 2
            rde._app_sent += 3
            rde._app_recv += 2
        rde.recovery_reconcile(1)
        assert rde._balance() == 0         # dead contribution removed
        # a pre-death straggler (no _ep) is fenced WITHOUT a credit
        rde._activate_cb(1, {"tp": 999, "_fid": (1, 7)})
        assert rde._balance() == 0
        with rde._dlock:
            assert not rde._delayed        # not even parked
        # the rejoined incarnation (epoch 1) passes the fence
        rde.note_peer_epoch(1, 1)
        rde._activate_cb(1, {"tp": 999, "_ep": 1, "_fid": (1, 1 << 48)})
        with rde._term_lock:
            assert rde._app_recv == 1      # credited
        with rde._dlock:
            assert rde._delayed            # parked for the unknown pool
            rde._delayed.clear()           # stop the retry timer chain
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()


def test_pool_epoch_gate_drops_and_parks_activations():
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.taskpool import Taskpool
    ce = SocketCE(0, 2, _probe_port_base(2))
    ctx = Context(nb_cores=1, rank=0, nranks=2)
    rde = RemoteDepEngine(ce, ctx)
    try:
        tp = Taskpool("gate")
        ctx.add_taskpool(tp, start=True)
        tp.run_epoch = 2
        base = {"tp": tp.taskpool_id, "root": 1, "ranks": [0],
                "deliveries": {}, "data": None}
        rde._try_activation(1, {**base, "pe": 1})   # torn generation
        with rde._dlock:
            assert not rde._delayed                 # dropped outright
        rde._try_activation(1, {**base, "pe": 3})   # future generation
        with rde._dlock:
            assert len(rde._delayed) == 1           # parked, not lost
        # once the local restart catches up, the parked frame delivers
        tp.run_epoch = 3
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            rde.retry_delayed()
            with rde._dlock:
                if not rde._delayed:
                    break
            time.sleep(0.02)
        with rde._dlock:
            assert not rde._delayed
        tp.cancel()
    finally:
        ce._stop = True
        rde.fini()
        ctx.fini()


# ---------------------------------------------------------------------------
# checkpoint under a degraded context (satellite bugfix)
# ---------------------------------------------------------------------------

def test_checkpoint_degraded_fails_fast(tmp_path):
    import types
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.checkpoint import checkpoint, restore
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, name="A")
        A.data_of(0, 0)
        path = str(tmp_path / "ck")
        # healthy single-rank checkpoint still works
        checkpoint(ctx, [A], path)
        # a dead, UNEXCUSED peer fails fast with the structured error
        # instead of wedging in the collective barrier
        ctx.comm = types.SimpleNamespace(
            ce=types.SimpleNamespace(dead_peers={1}, excused_peers=set()))
        with pytest.raises(CheckpointDegradedError) as ei:
            checkpoint(ctx, [A], str(tmp_path / "ck2"))
        assert ei.value.ranks == [1]
        with pytest.raises(CheckpointDegradedError):
            restore(ctx, [A], path)
        # an EXCUSED death proceeds (the barrier narrowed to survivors;
        # nranks=1 here so no wire traffic) and records the marker
        ctx.comm = None
        restore(ctx, [A], path)
    finally:
        ctx.comm = None
        ctx.fini()


def test_checkpoint_records_excused_marker(tmp_path):
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.checkpoint import checkpoint
    import types
    ctx = Context(nb_cores=1)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=8, ln=8, name="A")
        A.data_of(0, 0)

        class _BarrierCE:
            dead_peers = {1}
            excused_peers = {1}

            def barrier(self, timeout=60.0):
                pass
        ctx.comm = types.SimpleNamespace(ce=_BarrierCE())
        out = checkpoint(ctx, [A], str(tmp_path / "ck"))
        with np.load(out) as zf:
            assert list(zf["__excused__"]) == [1]
    finally:
        ctx.comm = None
        ctx.fini()


# ---------------------------------------------------------------------------
# service bookkeeping: degraded -> recovering -> healthy (satellite)
# ---------------------------------------------------------------------------

def test_service_recovery_state_transitions():
    from parsec_tpu.service.service import JobService
    svc = JobService(max_active=1, nb_cores=1)
    try:
        assert svc.stats()["recovering"] is False
        svc.note_recovery("start", 1)
        st = svc.stats()
        assert st["degraded"] and st["degraded_ranks"] == [1]
        assert st["recovering"] and st["recovering_ranks"] == [1]
        svc.note_recovery("done", 1)
        st = svc.stats()
        assert not st["degraded"] and not st["recovering"]
        # a failed recovery leaves the degradation standing
        svc.note_recovery("start", 2)
        svc.note_recovery("failed", 2)
        st = svc.stats()
        assert st["degraded_ranks"] == [2] and not st["recovering"]
        # ...until the rank rejoins
        svc.note_recovery("rejoin", 2)
        assert svc.stats()["degraded"] is False
    finally:
        svc.shutdown(timeout=10)


# ---------------------------------------------------------------------------
# end to end: kill -> recover -> COMPLETED with correct numerics
# ---------------------------------------------------------------------------

def test_kill_close_recovers_potrf():
    """The acceptance shape: a 2-rank potrf whose peer hard-dies
    mid-run COMPLETES on the survivor with validated numbers (adopted
    tiles included — local_tiles routes through the translation)."""
    import chaos
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=11;kill_rank=1@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=150",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "45"},
        timeout=90, tolerate_ranks=(1,))
    assert res[0] == "ok" and res[1] is None   # victim actually died


def test_kill_close_recovers_dtd_chain():
    """DTD lineage replay: the insert stream re-runs on the survivor
    against the snapshot-restored tile — EXACT final value."""
    import chaos
    res = _run_distributed_with_env(
        chaos.dtd_chain_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=7;kill_rank=1@t+1.2s,mode=close;"
         "delay_frame=tag:DTD,p=1,ms=60",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "30"},
        timeout=90, tolerate_ranks=(1,))
    assert res[0] == "ok" and res[1] is None


def test_kill_rank_zero_recovers_on_new_root():
    """Killing rank 0 exercises the generalized ring/barrier root: the
    surviving rank 1 becomes coordinator, initiator, AND barrier root,
    adopts rank 0's partition, and completes with validated numbers."""
    import chaos
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 2,
        {"PARSEC_MCA_FAULT_PLAN":
         "seed=13;kill_rank=0@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=150",
         "PARSEC_MCA_RECOVERY_ENABLE": "1",
         "PARSEC_CHAOS_WAIT_S": "45"},
        timeout=90, tolerate_ranks=(0,))
    assert res[1] == "ok" and res[0] is None


def test_recovery_disabled_reproduces_containment():
    """PARSEC_MCA_RECOVERY_ENABLE=0 (the default): the same kill plan
    fails the pool with the PR 5 structured PeerFailedError — recovery
    never engages implicitly."""
    import chaos
    with pytest.raises(RuntimeError) as ei:
        _run_distributed_with_env(
            chaos.potrf_recover_workload, 2,
            {"PARSEC_MCA_FAULT_PLAN":
             "seed=11;kill_rank=1@t+1.0s,mode=close;"
             "delay_frame=tag:ACT,p=1,ms=150",
             "PARSEC_MCA_RECOVERY_ENABLE": "0",
             "PARSEC_CHAOS_WAIT_S": "30"},
            timeout=90)
    assert "PeerFailedError" in str(ei.value)


# ---------------------------------------------------------------------------
# elastic rejoin: killed -> restarted -> serving its partition again
# ---------------------------------------------------------------------------

def _rejoin_potrf_phase(ctx, rank, nranks, name):
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    n, mb = 64, 16
    rng = np.random.default_rng(5)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                          myrank=rank, name=name)
    for m, nn in A.local_tiles():
        np.asarray(A.data_of(m, nn).copy_on(0).payload)[:] = \
            spd[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
    ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
    ctx.wait(timeout=60)
    Lref = np.linalg.cholesky(spd.astype(np.float64))
    for m, nn in A.local_tiles():
        if nn > m:
            continue
        got = np.asarray(A.data_of(m, nn).pull_to_host().payload,
                         dtype=np.float64)
        ref = Lref[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
        if m == nn:
            got, ref = np.tril(got), np.tril(ref)
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)


def _rejoin_worker(rank, nranks, port_base, outq):
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        import jax
        jax.config.update("jax_platforms", "cpu")
    except Exception:
        pass
    try:
        from parsec_tpu.comm.engine import make_ce
        from parsec_tpu.comm.remote_dep import RemoteDepEngine
        from parsec_tpu.core.context import Context

        ce = make_ce(rank, nranks, port_base)
        ctx = Context(nb_cores=2, rank=rank, nranks=nranks)
        rde = RemoteDepEngine(ce, ctx)
        ce.barrier()
        # phase 1: the gang works; rank 1 then dies and restarts
        _rejoin_potrf_phase(ctx, rank, nranks, "A")
        ce.barrier()
        if rank == 1:
            rde.fini()                    # the rank goes down
            time.sleep(1.0)
            params.set("comm_epoch", 1)   # restarted incarnation
            ce = make_ce(rank, nranks, port_base)
            rde = RemoteDepEngine(ce, ctx)
            table = ctx.recovery.rejoin(timeout=30.0)
            assert isinstance(table, dict)
        else:
            deadline = time.monotonic() + 25
            while 1 not in ce.dead_peers:
                if time.monotonic() > deadline:
                    raise RuntimeError("rank 1 death never detected")
                time.sleep(0.02)
            while 1 in ce.dead_peers:     # cleared by peer_rejoined
                if time.monotonic() > deadline + 35:
                    raise RuntimeError("rank 1 never rejoined")
                time.sleep(0.02)
            assert 1 not in ce.excused_peers
            assert ctx.recovery.rejoins == 1
        ce.barrier(timeout=30)
        # phase 2: the REJOINED rank serves its partition again
        _rejoin_potrf_phase(ctx, rank, nranks, "B")
        ce.barrier(timeout=30)
        ce._stop = True
        outq.put((rank, None, "ok"))
        ctx.fini()
        rde.fini()
    except Exception:
        outq.put((rank, traceback.format_exc(), None))


def test_killed_rank_rejoins_and_serves():
    from parsec_tpu.comm.launch import _probe_port_base
    saved = os.environ.get("PARSEC_MCA_RECOVERY_ENABLE")
    os.environ["PARSEC_MCA_RECOVERY_ENABLE"] = "1"
    try:
        base = _probe_port_base(2)
        mpctx = mp.get_context("spawn")
        outq = mpctx.Queue()
        procs = [mpctx.Process(target=_rejoin_worker,
                               args=(r, 2, base, outq), daemon=True)
                 for r in range(2)]
        for p in procs:
            p.start()
        results = {}
        try:
            for _ in range(2):
                rank, err, res = outq.get(timeout=150)
                assert err is None, f"rank {rank} failed:\n{err}"
                results[rank] = res
        finally:
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()
        assert results == {0: "ok", 1: "ok"}
    finally:
        if saved is None:
            os.environ.pop("PARSEC_MCA_RECOVERY_ENABLE", None)
        else:
            os.environ["PARSEC_MCA_RECOVERY_ENABLE"] = saved


# ---------------------------------------------------------------------------
# observability: metrics families + flight-recorder hook
# ---------------------------------------------------------------------------

def test_recovery_metrics_families_scrape():
    from parsec_tpu.core.context import Context
    params.set("recovery_enable", 1)
    try:
        ctx = Context(nb_cores=1)
        try:
            assert ctx.recovery is not None
            names = {s["n"] for s in ctx.metrics.samples()}
            assert "parsec_recoveries_total" in names
            assert "parsec_tasks_reexecuted_total" in names
            assert "parsec_rank_rejoins_total" in names
            assert "parsec_recovery_duration_seconds" in names
            stages = {s["l"].get("stage")
                      for s in ctx.metrics.samples()
                      if s["n"] == "parsec_recoveries_total"}
            assert {"started", "completed", "failed"} <= stages
        finally:
            ctx.fini()
    finally:
        params.set("recovery_enable", 0)


# ---------------------------------------------------------------------------
# acceptance (slow): 3-rank mid-run kill, multi-survivor re-execution
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_three_rank_potrf_survives_midrun_kill():
    """Two survivors recover a third's mid-run death TOGETHER: the dead
    partition re-maps onto one adopter, both re-enumerate, cross-rank
    activations of the new generation flow, numerics validate, and the
    killed run stays within ~2x the no-fault makespan (the ISSUE
    bound; the loose assert guards the invariant under host noise —
    the measured ratio is recorded in BENCH.md)."""
    import chaos
    env = {"PARSEC_MCA_RECOVERY_ENABLE": "1",
           "PARSEC_CHAOS_WAIT_S": "60"}
    t0 = time.monotonic()
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 3,
        {**env, "PARSEC_MCA_FAULT_PLAN":
         "seed=4;delay_frame=tag:ACT,p=1,ms=120"},
        timeout=120)
    base_s = time.monotonic() - t0
    assert res == ["ok", "ok", "ok"]
    t0 = time.monotonic()
    res = _run_distributed_with_env(
        chaos.potrf_recover_workload, 3,
        {**env, "PARSEC_MCA_FAULT_PLAN":
         "seed=4;kill_rank=2@t+1.0s,mode=close;"
         "delay_frame=tag:ACT,p=1,ms=120"},
        timeout=180, tolerate_ranks=(2,))
    kill_s = time.monotonic() - t0
    assert res[0] == "ok" and res[1] == "ok"
    ratio = kill_s / max(base_s, 1e-9)
    print(f"3-rank mid-run kill: baseline {base_s:.1f}s, "
          f"killed {kill_s:.1f}s, ratio {ratio:.2f}x")
    assert ratio < 3.0, (base_s, kill_s)


@pytest.mark.slow
def test_chaos_recover_catalog():
    """The full recovery catalog (close/hang x evloop/shm/threads +
    DTD + survivor exhaustion) through the chaos harness."""
    import subprocess
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--recover", "--seeds", "8", "--timeout", "120"],
        capture_output=True, text=True, timeout=1200,
        env={**os.environ, "JAX_PLATFORMS": "cpu"})
    assert proc.returncode == 0, proc.stdout + proc.stderr
