"""Ring-pipeline tests: the sequence-parallel neighbor-exchange primitive
through the runtime (SURVEY §5.7 — ring schedules built from dataflow
edges; the data movement of ring attention / ring allreduce)."""

import numpy as np
import pytest

from parsec_tpu.apps.ring import ring_pipeline_taskpool
from parsec_tpu.comm.launch import run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic


def _setup(P, mb, nodes=1, myrank=0):
    V = VectorTwoDimCyclic(mb=mb, lm=mb * P, nodes=nodes, myrank=myrank,
                           name="V")
    A = VectorTwoDimCyclic(mb=mb, lm=mb * P, nodes=nodes, myrank=myrank,
                           name="A")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m + 1)
    for m, _ in A.local_tiles():
        A.data_of(m).copy_on(0).payload[:] = 0.0
    return V, A


def test_ring_allreduce_single_rank():
    """Every party ends with the sum of every block (default combine)."""
    P, mb = 5, 4
    V, A = _setup(P, mb)
    with Context(nb_cores=3) as ctx:
        ctx.add_taskpool(ring_pipeline_taskpool(V, A))
        ctx.wait(timeout=60)
    total = sum(range(1, P + 1))
    for q in range(P):
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), total)


def test_ring_custom_combine_order_invariant():
    """A max-combine ring (order-insensitive, like online softmax
    renormalization in ring attention)."""
    P, mb = 4, 2
    V, A = _setup(P, mb)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(ring_pipeline_taskpool(
            V, A, combine=lambda acc, b: np.maximum(np.asarray(acc),
                                                    np.asarray(b))))
        ctx.wait(timeout=60)
    for q in range(P):
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), float(P))


def _ring_ranks(ctx, rank, nranks):
    P, mb = nranks * 2, 4   # two parties per rank: intra+inter hops
    V, A = _setup(P, mb, nodes=nranks, myrank=rank)
    ctx.add_taskpool(ring_pipeline_taskpool(V, A))
    ctx.wait(timeout=180)
    total = float(sum(range(1, P + 1)))
    for q, _ in A.local_tiles():
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), total)
    return "ok"


def test_ring_across_4_ranks():
    """The ring's neighbor hops cross ranks: every edge is one
    interconnect message (the DCN case of the §5.7 story)."""
    assert run_distributed(_ring_ranks, 4, timeout=240) == ["ok"] * 4
