"""Ring-pipeline tests: the sequence-parallel neighbor-exchange primitive
through the runtime (SURVEY §5.7 — ring schedules built from dataflow
edges; the data movement of ring attention / ring allreduce)."""

import numpy as np
import pytest

from parsec_tpu.apps.ring import ring_pipeline_taskpool
from parsec_tpu.comm.launch import run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic


def _setup(P, mb, nodes=1, myrank=0):
    V = VectorTwoDimCyclic(mb=mb, lm=mb * P, nodes=nodes, myrank=myrank,
                           name="V")
    A = VectorTwoDimCyclic(mb=mb, lm=mb * P, nodes=nodes, myrank=myrank,
                           name="A")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m + 1)
    for m, _ in A.local_tiles():
        A.data_of(m).copy_on(0).payload[:] = 0.0
    return V, A


def test_ring_allreduce_single_rank():
    """Every party ends with the sum of every block (default combine)."""
    P, mb = 5, 4
    V, A = _setup(P, mb)
    with Context(nb_cores=3) as ctx:
        ctx.add_taskpool(ring_pipeline_taskpool(V, A))
        ctx.wait(timeout=60)
    total = sum(range(1, P + 1))
    for q in range(P):
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), total)


def test_ring_custom_combine_order_invariant():
    """A max-combine ring (order-insensitive, like online softmax
    renormalization in ring attention)."""
    P, mb = 4, 2
    V, A = _setup(P, mb)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(ring_pipeline_taskpool(
            V, A, combine=lambda acc, b: np.maximum(np.asarray(acc),
                                                    np.asarray(b))))
        ctx.wait(timeout=60)
    for q in range(P):
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), float(P))


def _ring_ranks(ctx, rank, nranks):
    P, mb = nranks * 2, 4   # two parties per rank: intra+inter hops
    V, A = _setup(P, mb, nodes=nranks, myrank=rank)
    ctx.add_taskpool(ring_pipeline_taskpool(V, A))
    ctx.wait(timeout=180)
    total = float(sum(range(1, P + 1)))
    for q, _ in A.local_tiles():
        np.testing.assert_allclose(
            np.asarray(A.data_of(q).pull_to_host().payload), total)
    return "ok"


def test_ring_across_4_ranks():
    """The ring's neighbor hops cross ranks: every edge is one
    interconnect message (the DCN case of the §5.7 story)."""
    assert run_distributed(_ring_ranks, 4, timeout=240) == ["ok"] * 4


# -- ring attention (SURVEY §5.7 long-context flagship) ---------------------

def _attn_setup(P, Tq, d, seed):
    from parsec_tpu.apps.ring_attention import pack_kv, pack_query
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    rng = np.random.default_rng(seed)
    Q = rng.standard_normal((P * Tq, d)).astype(np.float32)
    K = rng.standard_normal((P * Tq, d)).astype(np.float32)
    V = rng.standard_normal((P * Tq, d)).astype(np.float32)
    KV = TwoDimBlockCyclic(mb=2 * Tq, nb=d, lm=P * 2 * Tq, ln=d,
                           name="KV")
    ACC = TwoDimBlockCyclic(mb=Tq, nb=2 * d + 2, lm=P * Tq, ln=2 * d + 2,
                            name="ACC")
    for q in range(P):
        KV.data_of(q, 0).overwrite_host(
            pack_kv(K[q * Tq:(q + 1) * Tq], V[q * Tq:(q + 1) * Tq]))
        ACC.data_of(q, 0).overwrite_host(
            pack_query(Q[q * Tq:(q + 1) * Tq]))
    return Q, K, V, KV, ACC


def _attn_check(ACC, Q, K, V, P, Tq, d, causal=False):
    from parsec_tpu.apps.ring_attention import (dense_reference,
                                                unpack_output)
    want = dense_reference(Q, K, V, causal=causal)
    for q in range(P):
        acc = np.asarray(ACC.data_of(q, 0).pull_to_host().payload)
        got = unpack_output(acc, d)
        np.testing.assert_allclose(got, want[q * Tq:(q + 1) * Tq],
                                   rtol=2e-4, atol=2e-5)


@pytest.mark.parametrize("causal", [False, True])
@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_ring_attention_matches_dense(device, causal):
    """P-party ring attention over the runtime's neighbor-exchange
    schedule equals materialized-softmax attention over the full
    sequence — causal masking included (block skips + the diagonal
    triangle fall out of the global-position mask)."""
    from parsec_tpu.apps.ring_attention import ring_attention_taskpool
    P, Tq, d = 4, 8, 16
    Q, K, V, KV, ACC = _attn_setup(P, Tq, d, seed=11)
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(ring_attention_taskpool(KV, ACC, device=device,
                                                 causal=causal))
        ctx.wait(timeout=120)
    _attn_check(ACC, Q, K, V, P, Tq, d, causal=causal)


def test_ring_attention_multi_device_mesh():
    """Ring attention over the virtual device mesh: KV blocks hop the
    ICI preplace path between per-device resident accumulators."""
    from parsec_tpu.apps.ring_attention import ring_attention_taskpool
    P, Tq, d = 4, 4, 8
    Q, K, V, KV, ACC = _attn_setup(P, Tq, d, seed=12)
    with Context(nb_cores=4) as ctx:
        KV.distribute_devices(ctx)
        ACC.distribute_devices(ctx)
        ctx.add_taskpool(ring_attention_taskpool(KV, ACC, device="tpu"))
        ctx.wait(timeout=120)
    _attn_check(ACC, Q, K, V, P, Tq, d)
