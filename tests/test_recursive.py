"""Recursive device tests (reference: parsec/recursive.h parsec_recursivecall;
tests using the recursive device factor one tile by an inner taskpool).
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.core.recursive import recursive_call
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.data.subtile import SubtileMatrix
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, TASK


def _spd(n, seed=0):
    rng = np.random.default_rng(seed)
    a = rng.standard_normal((n, n)).astype(np.float32)
    return (a @ a.T + n * np.eye(n, dtype=np.float32))


def test_recursive_potrf_single_tile():
    """The reference's flagship recursive pattern: one big tile factored
    by an INNER tiled-Cholesky taskpool spawned from the outer task's
    body; the outer task completes when the inner pool does and the
    parent tile sees the committed result."""
    from parsec_tpu.apps.potrf import potrf_taskpool

    n, inner_mb = 64, 16
    a = _spd(n)
    A = TwoDimBlockCyclic(mb=n, nb=n, lm=n, ln=n, name="A") \
        .from_array(a.copy())
    after = []

    def body(T, es, task):
        sub = SubtileMatrix(task.data["T"].data, mb=inner_mb, nb=inner_mb)
        inner = potrf_taskpool(sub, device="cpu")
        return recursive_call(es, task, inner,
                              callback=lambda _t: sub.commit())

    p = PTG("rec")
    p.task("FACT") \
        .affinity(lambda A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0))),
              OUT(TASK("CHECK", "T", lambda: dict())),
              OUT(DATA(lambda A=A: A(0, 0)))) \
        .body(body)
    # a successor task proves the outer task's deps release only after
    # the inner pool committed (ordering evidence, not just results)
    p.task("CHECK") \
        .affinity(lambda A=A: A(0, 0)) \
        .flow("T", "READ",
              IN(TASK("FACT", "T", lambda: dict()))) \
        .body(lambda T: after.append(np.asarray(T).copy()))
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=120)

    expect = np.linalg.cholesky(a).astype(np.float32)
    got = np.tril(A.to_array())
    np.testing.assert_allclose(got, expect, rtol=1e-4, atol=1e-4)
    assert len(after) == 1
    np.testing.assert_allclose(np.tril(after[0]), expect, rtol=1e-4,
                               atol=1e-4)


def test_recursive_nests_two_levels():
    """Recursion composes: the inner pool's task itself recurses."""
    N = 16
    V = TwoDimBlockCyclic(mb=N, nb=N, lm=N, ln=N, name="V") \
        .from_array(np.ones((N, N), np.float32))

    def leaf_pool(sub):
        q = PTG("leaf", MT=sub.mt, NT=sub.nt)
        from parsec_tpu.dsl.ptg.api import Range
        q.task("ADD", m=Range(0, sub.mt - 1), n=Range(0, sub.nt - 1)) \
            .affinity(lambda m, n, S=sub: S(m, n)) \
            .flow("X", "RW",
                  IN(DATA(lambda m, n, S=sub: S(m, n))),
                  OUT(DATA(lambda m, n, S=sub: S(m, n)))) \
            .body(lambda X: X + 1.0)
        return q.build()

    def mid_body(T, es, task):
        sub = SubtileMatrix(task.data["T"].data, mb=N // 2, nb=N // 2)
        inner = PTG("mid", MT=sub.mt, NT=sub.nt)
        from parsec_tpu.dsl.ptg.api import Range

        def inner_body(X, es, task):
            s2 = SubtileMatrix(task.data["X"].data, mb=N // 4, nb=N // 4,
                               name="s2")
            return recursive_call(es, task, leaf_pool(s2),
                                  callback=lambda _t: s2.commit())

        inner.task("REC", m=Range(0, sub.mt - 1), n=Range(0, sub.nt - 1)) \
            .affinity(lambda m, n, S=sub: S(m, n)) \
            .flow("X", "RW",
                  IN(DATA(lambda m, n, S=sub: S(m, n))),
                  OUT(DATA(lambda m, n, S=sub: S(m, n)))) \
            .body(inner_body)
        return recursive_call(es, task, inner.build(),
                              callback=lambda _t: sub.commit())

    p = PTG("outer")
    p.task("GO") \
        .affinity(lambda V=V: V(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0, 0))),
              OUT(DATA(lambda V=V: V(0, 0)))) \
        .body(mid_body)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=120)
    np.testing.assert_allclose(V.to_array(), 2.0)


def test_recursive_inner_failure_fails_outer():
    """An inner-pool task error must fail the context, not hang it."""
    V = TwoDimBlockCyclic(mb=8, nb=8, lm=8, ln=8, name="V") \
        .from_array(np.ones((8, 8), np.float32))

    def body(T, es, task):
        inner = PTG("bad")
        inner.task("BOOM") \
            .affinity(lambda V=V: V(0, 0)) \
            .body(lambda: (_ for _ in ()).throw(RuntimeError("inner boom")))
        return recursive_call(es, task, inner.build())

    p = PTG("outer")
    p.task("GO") \
        .affinity(lambda V=V: V(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0, 0))),
              OUT(DATA(lambda V=V: V(0, 0)))) \
        .body(body)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        with pytest.raises(RuntimeError):
            ctx.wait(timeout=60)
