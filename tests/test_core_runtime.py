"""Core-runtime tests: hand-built task classes through the full lifecycle
(reference analogs: examples/Ex02_Chain, Ex04_ChainData, Ex05_Broadcast,
tests/runtime/multichain — SURVEY.md §3.2 call stack)."""

import threading

import numpy as np
import pytest

from parsec_tpu import (Context, ParameterizedTaskpool, TaskClass, Dep, RW,
                        READ, WRITE, CTL, FromDesc, FromTask, ToDesc, ToTask,
                        New, compose)
from parsec_tpu.data.arena import Arena
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.core.task import HookReturn


def make_ctx(**kw):
    kw.setdefault("nb_cores", 2)
    return Context(**kw)


def chain_taskpool(A, NT, body):
    """Ex02/Ex04-style linear chain on tile A(0,0):
    Step(0..NT-1), T flows through the chain and back to A."""
    tp = ParameterizedTaskpool("chain", globals_={"NT": NT})
    tc = TaskClass(
        "Step",
        params=[("k", lambda g, l: range(g["NT"]))],
        affinity=lambda l: A(0, 0),
        flows=[RW("T",
                  inputs=[Dep(FromDesc(lambda l: A(0, 0)),
                              guard=lambda l: l["k"] == 0),
                          Dep(FromTask("Step", "T",
                                       lambda l: {"k": l["k"] - 1}),
                              guard=lambda l: l["k"] > 0)],
                  outputs=[Dep(ToTask("Step", "T",
                                      lambda l: {"k": l["k"] + 1}),
                               guard=lambda l: l["k"] < NT - 1),
                           Dep(ToDesc(lambda l: A(0, 0)),
                               guard=lambda l: l["k"] == NT - 1)])],
        body=body)
    tp.add_task_class(tc)
    return tp


def test_chain_sequences_and_writes_back():
    a = np.zeros((4, 4), np.float32)
    A = TwoDimBlockCyclic(4, 4, 4, 4).from_array(a)
    seen = []

    def body(es, task):
        k = task.locals["k"]
        seen.append(k)
        task.data["T"].payload += 1

    with make_ctx() as ctx:
        ctx.add_taskpool(chain_taskpool(A, 10, body))
        ctx.wait(timeout=30)
    assert seen == list(range(10))          # strict chain order
    assert a[0, 0] == 10                    # all increments landed


@pytest.mark.parametrize("sched", ["gd", "ip", "ap", "spq", "rnd", "ll",
                                   "lfq", "pbq", "ltq", "lhq", "llp"])
def test_all_schedulers_run_chain(sched):
    a = np.zeros((2, 2), np.float32)
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(a)

    def body(es, task):
        task.data["T"].payload += 1

    with make_ctx(scheduler=sched) as ctx:
        ctx.add_taskpool(chain_taskpool(A, 6, body))
        ctx.wait(timeout=30)
    assert a[0, 0] == 6


def test_broadcast_fanout():
    """Ex05-style: one Root output fans out to N Reader tasks."""
    N = 8
    a = np.full((2, 2), 7.0, np.float32)
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(a)
    got = []
    lock = threading.Lock()

    tp = ParameterizedTaskpool("bcast")

    def root_body(es, task):
        task.data["T"].payload *= 2

    def reader_body(es, task):
        with lock:
            got.append((task.locals["i"], float(task.data["X"].payload[0, 0])))

    root = TaskClass(
        "Root", params=[],
        affinity=lambda l: A(0, 0),
        flows=[RW("T",
                  inputs=[Dep(FromDesc(lambda l: A(0, 0)))],
                  outputs=[Dep(ToTask("Reader", "X", lambda l, i=i: {"i": i}))
                           for i in range(N)] +
                          [Dep(ToDesc(lambda l: A(0, 0)))])],
        body=root_body)
    reader = TaskClass(
        "Reader", params=[("i", lambda g, l: range(N))],
        affinity=lambda l: A(0, 0),
        flows=[READ("X", inputs=[Dep(FromTask("Root", "T", lambda l: {}))])],
        body=reader_body)
    tp.add_task_class(root)
    tp.add_task_class(reader)

    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert sorted(got) == [(i, 14.0) for i in range(N)]
    assert a[0, 0] == 14.0


def test_diamond_join_counts_two_inputs():
    """Fork -> (Left, Right) -> Join: join waits for both arrivals."""
    a = np.ones((2, 2), np.float32)
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(a)
    order = []
    lock = threading.Lock()

    def mk_body(name, delta):
        def body(es, task):
            with lock:
                order.append(name)
            for c in task.data.values():
                if c is not None and task.task_class.flows[0].access & 0x2:
                    c.payload += delta
        return body

    tp = ParameterizedTaskpool("diamond")
    arena = Arena((2, 2), np.float32)
    tp.add_arena("default", arena)

    fork = TaskClass(
        "Fork", params=[], affinity=lambda l: A(0, 0),
        flows=[RW("T", inputs=[Dep(FromDesc(lambda l: A(0, 0)))],
                  outputs=[Dep(ToTask("Left", "L", lambda l: {})),
                           Dep(ToTask("Right", "R", lambda l: {}))])],
        body=mk_body("fork", 1))
    left = TaskClass(
        "Left", params=[], affinity=lambda l: A(0, 0),
        flows=[READ("L", inputs=[Dep(FromTask("Fork", "T", lambda l: {}))]),
               WRITE("O", inputs=[Dep(New("default"))],
                     outputs=[Dep(ToTask("Join", "A", lambda l: {}))])],
        body=lambda es, task: task.data["O"].payload.fill(
            task.data["L"].payload[0, 0] + 10))
    right = TaskClass(
        "Right", params=[], affinity=lambda l: A(0, 0),
        flows=[READ("R", inputs=[Dep(FromTask("Fork", "T", lambda l: {}))]),
               WRITE("O", inputs=[Dep(New("default"))],
                     outputs=[Dep(ToTask("Join", "B", lambda l: {}))])],
        body=lambda es, task: task.data["O"].payload.fill(
            task.data["R"].payload[0, 0] + 20))
    out = {}

    def join_body(es, task):
        out["sum"] = float(task.data["A"].payload[0, 0]
                           + task.data["B"].payload[0, 0])

    join = TaskClass(
        "Join", params=[], affinity=lambda l: A(0, 0),
        flows=[READ("A", inputs=[Dep(FromTask("Left", "O", lambda l: {}))]),
               READ("B", inputs=[Dep(FromTask("Right", "O", lambda l: {}))])],
        body=join_body)
    for tc in (fork, left, right, join):
        tp.add_task_class(tc)

    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    # fork ran first; join saw both arena outputs (2+10) + (2+20)
    assert order[0] == "fork"
    assert out["sum"] == 34.0
    # arena copies were retired after join consumed them
    assert arena.released == arena.allocated


def test_ctl_flow_ordering():
    """CTL edges order tasks with no data payload
    (reference: examples Ex07 CTL)."""
    order = []
    lock = threading.Lock()
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))

    tp = ParameterizedTaskpool("ctl", globals_={"N": 4})

    def first_body(es, task):
        with lock:
            order.append(("first", task.locals["i"]))

    def second_body(es, task):
        with lock:
            order.append(("second", 0))

    first = TaskClass(
        "First", params=[("i", lambda g, l: range(4))],
        affinity=lambda l: A(0, 0),
        flows=[CTL("C", outputs=[Dep(ToTask("Second", "C", lambda l: {}))])],
        body=first_body)
    # CTL gather: the JDF range form "<- CTL First(0..3)" is one dep with
    # multiplicity 4 — Second must wait for all four arrivals.
    second = TaskClass(
        "Second", params=[], affinity=lambda l: A(0, 0),
        flows=[CTL("C", inputs=[Dep(FromTask("First", "C", lambda l: {}),
                                    count=lambda l: 4)])],
        body=second_body)
    tp.add_task_class(first)
    tp.add_task_class(second)

    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert order[-1] == ("second", 0)
    assert len(order) == 5


def test_compound_sequencing():
    a = np.zeros((2, 2), np.float32)
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(a)
    marks = []

    def mk(mark):
        def body(es, task):
            marks.append(mark)
            task.data["T"].payload += 1
        return body

    tp1 = chain_taskpool(A, 3, mk("a"))
    tp2 = chain_taskpool(A, 3, mk("b"))
    with make_ctx() as ctx:
        ctx.add_taskpool(compose(tp1, tp2))
        ctx.wait(timeout=30)
    assert marks == ["a"] * 3 + ["b"] * 3
    assert a[0, 0] == 6


def test_body_error_propagates():
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))

    def body(es, task):
        raise ValueError("kaboom")

    with make_ctx() as ctx:
        ctx.add_taskpool(chain_taskpool(A, 2, body))
        with pytest.raises(RuntimeError, match="failed"):
            ctx.wait(timeout=30)


def test_again_reschedules():
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))
    tries = {"n": 0}

    def body(es, task):
        tries["n"] += 1
        if tries["n"] < 3:
            return HookReturn.AGAIN
        return HookReturn.DONE

    tp = ParameterizedTaskpool("again")
    tp.add_task_class(TaskClass(
        "T", params=[], affinity=lambda l: A(0, 0),
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=body))
    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert tries["n"] == 3


def test_priority_order_with_ap():
    """Higher-priority startup tasks run first under the ap scheduler with
    a single worker."""
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))
    ran = []

    tp = ParameterizedTaskpool("prio", globals_={"N": 6})
    tp.add_task_class(TaskClass(
        "P", params=[("i", lambda g, l: range(6))],
        affinity=lambda l: A(0, 0),
        priority=lambda l: l["i"],
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=lambda es, task: ran.append(task.locals["i"])))
    with make_ctx(nb_cores=1, scheduler="ap") as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert ran == sorted(ran, reverse=True)


def test_context_test_and_empty_pool():
    with make_ctx() as ctx:
        tp = ParameterizedTaskpool("empty")
        ctx.add_taskpool(tp)
        ctx.wait(timeout=10)
        assert ctx.test()
        assert tp.completed


def test_disable_falls_through_to_next_incarnation():
    """DISABLE must disable class-wide WITHOUT skipping the next chore
    (reference: PARSEC_HOOK_RETURN_DISABLE semantics)."""
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))
    hits = []
    tc = TaskClass(
        "D", params=[("i", lambda g, l: range(3))],
        affinity=lambda l: A(0, 0),
        incarnations=[("tpu", lambda es, t: hits.append("tpu")
                       or HookReturn.DISABLE)],
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=lambda es, t: hits.append("cpu"))
    tp = ParameterizedTaskpool("dis")
    tp.add_task_class(tc)
    with make_ctx(nb_cores=1) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    # first task tried tpu then fell through to cpu; later tasks skip tpu
    assert hits.count("tpu") == 1
    assert hits.count("cpu") == 3


def test_body_returning_true_is_done_not_again():
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))
    runs = []
    tp = ParameterizedTaskpool("boolret")
    tp.add_task_class(TaskClass(
        "B", params=[], affinity=lambda l: A(0, 0),
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=lambda es, t: runs.append(1) or True))
    with make_ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=10)
    assert runs == [1]


@pytest.mark.parametrize("sched", ["ll", "ap", "ltq", "pbq", "lfq"])
def test_again_no_livelock_single_worker(sched):
    """Fairness contract: an AGAIN task waiting on a sibling must not
    starve it on a single stream (reference: sched.h:58-99 distance)."""
    A = TwoDimBlockCyclic(2, 2, 2, 2).from_array(np.zeros((2, 2), np.float32))
    state = {"sibling_ran": False, "spins": 0}

    def waiter(es, task):
        if not state["sibling_ran"]:
            state["spins"] += 1
            if state["spins"] > 1000:
                raise RuntimeError("livelock")
            return HookReturn.AGAIN
        return HookReturn.DONE

    def sibling(es, task):
        state["sibling_ran"] = True

    tp = ParameterizedTaskpool("fair")
    tp.add_task_class(TaskClass(
        "Waiter", params=[], affinity=lambda l: A(0, 0),
        priority=lambda l: 100,
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=waiter))
    tp.add_task_class(TaskClass(
        "Sibling", params=[], affinity=lambda l: A(0, 0),
        priority=lambda l: 0,
        flows=[READ("X", inputs=[Dep(FromDesc(lambda l: A(0, 0)))])],
        body=sibling))
    with make_ctx(nb_cores=1, scheduler=sched) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert state["sibling_ran"]


def test_long_compound_of_empty_pools_no_recursion():
    from parsec_tpu import ParameterizedTaskpool as PTP
    pools = [PTP(f"p{i}") for i in range(300)]
    with make_ctx() as ctx:
        ctx.add_taskpool(compose(*pools))
        ctx.wait(timeout=30)
    assert all(p.completed for p in pools)
