"""Reshape engine tests: dtt-driven conversion on dependency edges
(reference: parsec_reshape.c + the reshape test matrix
tests/collections/reshape/ — these cover the local input-reshape from
task-fed edges and from the descriptor, the shared-promise fan-out, and
the reshape-on-writeback path).
"""

import numpy as np
import pytest

import ml_dtypes

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic, TwoDimBlockCyclic
from parsec_tpu.data.reshape import Dtt, ReshapeCache, convert, needs_reshape
from parsec_tpu.data.data import Data, DataCopy, Coherency
from parsec_tpu.dsl.ptg.api import DATA, IN, NEW, OUT, PTG, Range, TASK

bf16 = np.dtype(ml_dtypes.bfloat16)


def test_needs_reshape_and_convert_unit():
    d = Data(nb_elts=16)
    c = d.create_copy(0, payload=np.ones((2, 2), np.float32),
                      coherency=Coherency.SHARED, version=1)
    assert not needs_reshape(c, None)
    assert not needs_reshape(c, Dtt(dtype=np.float32))
    assert needs_reshape(c, Dtt(dtype=bf16))
    t = Dtt(transform=lambda a: a.T, inverse=lambda a: a.T, name="T")
    assert needs_reshape(c, t)
    a = np.arange(6, dtype=np.float32).reshape(2, 3)
    np.testing.assert_array_equal(convert(a, t), a.T)
    np.testing.assert_array_equal(convert(a, t, inverse=True), a.T)
    assert convert(a, Dtt(dtype=bf16)).dtype == bf16


def test_shared_promise_converts_once():
    cache = ReshapeCache()
    d = Data(nb_elts=16)
    c = d.create_copy(0, payload=np.ones((2, 2), np.float32),
                      coherency=Coherency.SHARED, version=1)
    t = Dtt(dtype=bf16)
    r1 = cache.get_copy(c, t)
    r2 = cache.get_copy(c, t)
    assert r1 is r2 and cache.conversions == 1
    assert np.asarray(r1.payload).dtype == bf16


def test_task_edge_reshape_f32_to_bf16():
    """f32 collection, bf16 task-fed edges: consumers see bf16 payloads,
    the writeback lands f32 at home (the mixed-precision staging edge)."""
    NT, mb = 2, 4
    base = np.arange(1.0, NT * mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(base.copy())
    seen = {}
    p = PTG("mix", NT=NT)
    p.task("P", i=Range(0, NT - 1)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(TASK("Q", "X", lambda i: dict(i=i)))) \
        .body(lambda: None)

    def q_body(X, i):
        seen[i] = np.asarray(X).dtype
        return (2.0 * np.asarray(X)).astype(np.float32)
    p.task("Q", i=Range(0, NT - 1)) \
        .flow("X", "RW",
              IN(TASK("P", "X", lambda i: dict(i=i)), dtt=Dtt(dtype=bf16)),
              OUT(DATA(lambda i, V=V: V(i)))) \
        .body(q_body)
    tp = p.build()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert all(dt == bf16 for dt in seen.values()), seen
    out = V.to_array()
    assert out.dtype == np.float32
    np.testing.assert_allclose(out, 2.0 * base, rtol=1e-2)  # bf16 rounding
    assert tp.reshape.conversions == NT


def test_desc_read_reshape():
    """IN(DATA(...), dtt=...): converting read straight from the
    collection (reference: parsec_get_copy_reshape_from_desc)."""
    NT, mb = 2, 4
    base = np.arange(1.0, NT * mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(base.copy())
    seen = {}

    def body(X, i):
        seen[i] = np.asarray(X).dtype
    p = PTG("dread", NT=NT)
    p.task("R", i=Range(0, NT - 1)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(i)), dtt=Dtt(dtype=bf16))) \
        .body(body)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=30)
    assert all(dt == bf16 for dt in seen.values()), seen
    # the collection itself was never converted
    assert V.to_array().dtype == np.float32


def test_writeback_inverse_transform():
    """OUT(DATA(...), dtt with transform): the edge layout is undone on
    the way home (reference: reverse reshape on writeback)."""
    mb = 4
    base = np.arange(12, dtype=np.float32).reshape(4, 3)
    M = TwoDimBlockCyclic(mb=4, nb=3, lm=4, ln=3).from_array(base.copy())
    tr = Dtt(transform=lambda a: a.T, inverse=lambda a: a.T, name="T")
    p = PTG("tposed")
    # P produces the tile in TRANSPOSED edge layout; the dtt's inverse
    # restores home layout on writeback
    p.task("P") \
        .flow("X", "RW",
              IN(DATA(lambda M=M: M(0, 0))),
              OUT(DATA(lambda M=M: M(0, 0)), dtt=tr)) \
        .body(lambda X: (2.0 * np.asarray(X)).T)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=30)
    np.testing.assert_allclose(M.to_array(), 2.0 * base, rtol=1e-6)


def _remote_reshape_worker(ctx, rank, nranks):
    """Rank 0 produces an f32 tile; rank 1's consumer declares a bf16
    edge — the payload is converted BEFORE it travels (pre-send
    reshape)."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt as _Dtt
    import ml_dtypes as _md
    V = VectorTwoDimCyclic(mb=4, lm=8, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m + 1)
    seen = {}
    p = PTG("rres")
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("Q", "X", lambda: dict()))) \
        .body(lambda: None)

    def q_body(X):
        seen["dtype"] = np.asarray(X).dtype
        seen["val"] = float(np.asarray(X)[0])
    p.task("Q") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()),
                 dtt=_Dtt(dtype=_md.bfloat16))) \
        .body(q_body)
    ctx.add_taskpool(p.build())
    ctx.wait()
    return seen


def test_remote_presend_reshape():
    from parsec_tpu.comm.launch import run_distributed
    results = run_distributed(_remote_reshape_worker, 2)
    recv = results[1]
    assert recv["dtype"] == bf16 and recv["val"] == 1.0


def test_fanout_shared_reshape_single_conversion():
    """Two readers demanding the same dtt share ONE converted copy
    (the datacopy-future promise semantics)."""
    mb = 4
    base = np.arange(1.0, mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=mb).from_array(base.copy())
    seen = []
    p = PTG("share")
    p.task("P") \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("R1", "X", lambda: dict())),
              OUT(TASK("R2", "X", lambda: dict()))) \
        .body(lambda: None)
    for rn in ("R1", "R2"):
        p.task(rn) \
            .flow("X", "READ",
                  IN(TASK("P", "X", lambda: dict()), dtt=Dtt(dtype=bf16))) \
            .body(lambda X: seen.append(np.asarray(X).dtype))
    tp = p.build()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert seen == [bf16, bf16]
    assert tp.reshape.conversions == 1


def test_out_dtt_dtype_only_lands_in_collection():
    """A dtype-only OUT dtt: the body's bf16 result must be cast home to
    the f32 collection — regression for the early-return that left the
    collection holding the stale pre-task value (reference: the remote/
    local writeback reshape paths of parsec_reshape.c)."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG

    V = VectorTwoDimCyclic(mb=4, lm=4)
    V.data_of(0).copy_on(0).payload[:] = 2.0
    p = PTG("outdtt")
    p.task("T") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "RW",
              IN(DATA(lambda V=V: V(0))),
              OUT(DATA(lambda V=V: V(0)),
                  dtt=Dtt(dtype=ml_dtypes.bfloat16))) \
        .body(lambda X: (np.asarray(X) * 3.0).astype(ml_dtypes.bfloat16))
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
    got = np.asarray(V.data_of(0).pull_to_host().payload)
    assert got.dtype == np.float32
    np.testing.assert_allclose(got, 6.0)


def test_edge_both_sides_dtt_consumer_wins():
    """Producer OUT dtt AND consumer IN dtt on ONE edge: the consumer's
    IN dtt governs what it is handed (reference: receiver-side datatype
    resolution, remote_dep_get_datatypes; the local engine applies the
    same precedence, engine._edge_dtt) — VERDICT r4 reshape-corpus gap:
    reshape declared on both producer and consumer side of one edge."""
    mb = 4
    base = np.arange(1.0, mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=mb).from_array(base.copy())
    seen = {}
    p = PTG("both")
    p.task("P") \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("Q", "X", lambda: dict()), dtt=Dtt(dtype=bf16))) \
        .body(lambda: None)
    p.task("Q") \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()),
                 dtt=Dtt(transform=lambda a: a * 2.0,
                         inverse=lambda a: a / 2.0, name="x2"))) \
        .body(lambda X: seen.update(dtype=np.asarray(X).dtype,
                                    val=float(np.asarray(X)[0])))
    tp = p.build()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    # consumer saw ITS dtt's form (transform applied to the f32 source),
    # not the producer's bf16 edge type
    assert seen["dtype"] == np.float32 and seen["val"] == 2.0
    assert tp.reshape.conversions == 1


def test_avoidable_reshape_ships_zero_conversions():
    """Reference corpus: avoidable_reshape.jdf — when the declared edge
    dtt already MATCHES the payload's type (producer OUT dtt and
    consumer IN dtt both naming the tile's own f32 layout), the reshape
    engine must detect the no-op and ship the original copy: zero
    conversions, payload identity preserved."""
    mb = 4
    base = np.arange(1.0, mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=mb).from_array(base.copy())
    f32 = Dtt(dtype=np.float32)
    seen = {}
    p = PTG("avoid")
    p.task("P") \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0)), dtt=f32),
              OUT(TASK("C", "X", lambda: dict()), dtt=f32)) \
        .body(lambda: None)
    p.task("C") \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()), dtt=f32)) \
        .body(lambda X: seen.update(dtype=np.asarray(X).dtype,
                                    val=float(np.asarray(X)[0])))
    tp = p.build()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert seen == {"dtype": np.dtype(np.float32), "val": 1.0}
    # the whole point of the corpus case: the no-op path converts NOTHING
    assert tp.reshape.conversions == 0


def test_local_new_flow_edge_reshape():
    """A NEW-flow arena temporary rides a dtt edge to its consumer: the
    reference's reshape-into-NEW case, locally (the arena defines the
    producer-side type; the consumer's IN dtt converts)."""
    p = PTG("newr")
    p.arena("scratch", (4,), np.float32)
    out = {}

    def produce(X):
        X[:] = np.arange(4, dtype=np.float32) + 1.0

    def consume(X):
        out.update(dtype=np.asarray(X).dtype,
                   vals=np.asarray(X).astype(np.float32))
    p.task("P") \
        .flow("X", "RW",
              IN(NEW("scratch")),
              OUT(TASK("C", "X", lambda: dict()))) \
        .body(produce)
    p.task("C") \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()), dtt=Dtt(dtype=bf16))) \
        .body(consume)
    tp = p.build()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=30)
    assert out["dtype"] == bf16
    np.testing.assert_allclose(out["vals"], [1, 2, 3, 4])
