"""VP map, thread placement, hierarchical/per-VP schedulers, and
scheduler statistics (reference: parsec/vpmap.{c,h}, bindthread.c,
sched_lhq/llp modules, the display_stats hook sched.h:299)."""

import numpy as np
import pytest

from parsec_tpu.core.vpmap import VPMap, bind_current_thread
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.utils.mca import params


def test_vpmap_flat():
    vm = VPMap.from_flat(6)
    assert vm.nb_vps == 1
    assert [vm.vp_of(i) for i in range(6)] == [0] * 6
    assert vm.threads_of_vp(0) == list(range(6))


def test_vpmap_from_parameters():
    vm = VPMap.from_parameters("2:2", 5)
    assert vm.nb_vps == 2
    assert [vm.vp_of(i) for i in range(5)] == [0, 0, 1, 1, 1]
    assert VPMap.from_parameters("garbage", 3).nb_vps == 1


def test_vpmap_from_hardware():
    vm = VPMap.from_hardware(4)
    assert vm.nb_threads == 4
    assert vm.nb_vps >= 1
    # cores are assigned (or None where unsupported)
    assert all(isinstance(vm.core_of(i), (int, type(None))) for i in range(4))


def test_bind_current_thread_roundtrip():
    import os
    if not hasattr(os, "sched_setaffinity"):
        pytest.skip("no sched_setaffinity on this platform")
    before = os.sched_getaffinity(0)
    try:
        assert bind_current_thread(sorted(before)[0])
        assert os.sched_getaffinity(0) == {sorted(before)[0]}
    finally:
        os.sched_setaffinity(0, before)


def _run_chain(scheduler, nb_cores=4, **ctx_kw):
    NT = 12
    V = VectorTwoDimCyclic(mb=2, lm=2 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("chain", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .body(lambda T: T + 1.0)
    with Context(nb_cores=nb_cores, scheduler=scheduler, **ctx_kw) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
        stats = ctx.scheduler.display_stats(None)
    np.testing.assert_allclose(
        np.asarray(V.data_of(NT - 1).pull_to_host().payload), float(NT))
    return stats


def test_llp_multi_vp():
    """llp with 2 VPs x 2 streams: per-VP ring LIFOs + cross-VP steal."""
    params.set("vpmap", "2:2")
    try:
        stats = _run_chain("llp", nb_cores=4)
    finally:
        params.unset("vpmap")
    assert "llp" in stats and "local=" in stats


def test_lhq_hierarchy_runs_and_reports():
    stats = _run_chain("lhq", nb_cores=4)
    assert "lhq" in stats
    # all selections are accounted somewhere in the hierarchy
    got = dict(kv.split("=") for kv in stats.split()[1:])
    assert int(got["local"]) + int(got["steals"]) + int(got["system"]) >= 12


def test_lfq_stats_nonempty():
    stats = _run_chain("lfq", nb_cores=2)
    assert stats.startswith("lfq:")


def test_worker_binding_smoke():
    """runtime_bind_threads=1 must not break execution (binding is
    best-effort; reference: parsec_bindthread)."""
    params.set("runtime_bind_threads", 1)
    params.set("vpmap", "hw")
    try:
        _run_chain("lfq", nb_cores=2)
    finally:
        params.unset("runtime_bind_threads")
        params.unset("vpmap")


def test_vpmap_from_file(tmp_path):
    """Reference vpmap file format (vpmap_init_from_file, vpmap.c:219):
    one VP per line 'rank:nbthreads:binding', rank-less lines apply to
    all ranks, bindings take comma lists and a-b ranges."""
    from parsec_tpu.core.vpmap import VPMap
    f = tmp_path / "vps.map"
    f.write_text(
        "# comment\n"
        ":2:0-1\n"          # every rank: VP of 2 threads on cores 0,1
        "0:1:3\n"           # rank 0 only: VP of 1 thread on core 3
        "1:4:4,5\n"         # rank 1 only: skipped on rank 0
    )
    m = VPMap.from_file(str(f), 3, rank=0)
    assert m.nb_vps == 2
    assert [m.vp_of(i) for i in range(3)] == [0, 0, 1]
    assert [m.core_of(i) for i in range(3)] == [0, 1, 3]
    # rank 1 sees its own line plus the rank-less VP
    m1 = VPMap.from_file(str(f), 6, rank=1)
    assert m1.nb_vps == 2
    assert [m1.core_of(i) for i in range(6)] == [0, 1, 4, 5, 4, 5]
    # thread-count mismatch maps round-robin rather than failing
    m2 = VPMap.from_file(str(f), 5, rank=0)
    assert m2.nb_threads == 5
    # missing file falls back to flat
    m3 = VPMap.from_file(str(tmp_path / "nope.map"), 4)
    assert m3.nb_vps == 1


def test_vpmap_file_mca_selection(tmp_path):
    from parsec_tpu.core.vpmap import VPMap
    from parsec_tpu.utils.mca import params
    f = tmp_path / "v.map"
    f.write_text(":2:\n:2:\n")
    params.set("vpmap", f"file:{f}")
    try:
        m = VPMap.from_mca(4)
        assert m.nb_vps == 2
        assert m.threads_of_vp(0) == [0, 1]
    finally:
        params.unset("vpmap")


def test_lhq_groups_follow_vpmap_topology():
    """lhq's mid-level hierarchy follows the vpmap's VP structure when
    one exists (reference: per-hwloc-level hbbuffer chains,
    sched_lhq_module.c:30-44) instead of the synthetic stream-id pairs."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.utils.mca import params
    params.set("vpmap", "2:2")
    params.set("sched", "lhq")
    try:
        with Context(nb_cores=4) as ctx:
            sched = ctx.scheduler
            assert ctx.vpmap.nb_vps == 2
            gids = [sched._gid(t) for t in range(4)]
            assert gids == [ctx.vpmap.vp_of(t) for t in range(4)]
            assert gids == [0, 0, 1, 1]
    finally:
        params.unset("vpmap")
        params.unset("sched")


# -- hardware topology discovery (VERDICT r3 #7: parsec_hwloc.c
# counterpart — cache/package levels from sysfs) ---------------------------

def _fake_sysfs(root, ncpu=8, pkgs=2, l3_groups=2, l2_share=2):
    """Synthesize a /sys/devices/system/cpu tree: ``pkgs`` packages,
    ``l3_groups`` shared-L3 islands, L2 shared by pairs."""
    import os
    base = os.path.join(root, "devices/system/cpu")
    per_pkg = ncpu // pkgs
    per_l3 = ncpu // l3_groups
    for c in range(ncpu):
        topo = os.path.join(base, f"cpu{c}", "topology")
        os.makedirs(topo, exist_ok=True)
        p0 = (c // per_pkg) * per_pkg
        with open(os.path.join(topo, "package_cpus_list"), "w") as f:
            f.write(f"{p0}-{p0 + per_pkg - 1}\n")
        cache = os.path.join(base, f"cpu{c}", "cache")
        specs = [(1, "Data", (c, c)), (1, "Instruction", (c, c)),
                 (2, "Unified", ((c // l2_share) * l2_share,
                                 (c // l2_share) * l2_share
                                 + l2_share - 1)),
                 (3, "Unified", ((c // per_l3) * per_l3,
                                 (c // per_l3) * per_l3 + per_l3 - 1))]
        for i, (lvl, ty, (lo, hi)) in enumerate(specs):
            d = os.path.join(cache, f"index{i}")
            os.makedirs(d, exist_ok=True)
            for name, val in (("level", str(lvl)), ("type", ty),
                              ("shared_cpu_list", f"{lo}-{hi}")):
                with open(os.path.join(d, name), "w") as f:
                    f.write(val + "\n")
    return root


def test_discover_topology_from_sysfs(tmp_path):
    from parsec_tpu.core.vpmap import discover_topology
    root = _fake_sysfs(str(tmp_path))
    topo = discover_topology(root)
    assert topo["cpus"] == list(range(8))
    assert topo["package"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert topo["l3"] == [[0, 1, 2, 3], [4, 5, 6, 7]]
    assert topo["l2"] == [[0, 1], [2, 3], [4, 5], [6, 7]]
    assert topo["l1"] == [[c] for c in range(8)]


def test_from_hardware_follows_packages(tmp_path):
    from parsec_tpu.core.vpmap import VPMap
    root = _fake_sysfs(str(tmp_path))
    vm = VPMap.from_hardware(8, sysfs_root=root)
    assert vm.nb_vps == 2
    # streams interleave across the two domains, bound inside them
    for i in range(8):
        vp = vm.vp_of(i)
        core = vm.core_of(i)
        assert vp in (0, 1) and core is not None
        assert core in ([0, 1, 2, 3] if vp == 0 else [4, 5, 6, 7])
    assert sorted(vm.vp_of(i) for i in range(8)) == [0] * 4 + [1] * 4


def test_from_hardware_no_sysfs_falls_back_flat(tmp_path):
    from parsec_tpu.core.vpmap import VPMap
    vm = VPMap.from_hardware(4, sysfs_root=str(tmp_path / "none"))
    assert vm.nb_threads == 4 and vm.nb_vps >= 1


def test_lhq_groups_follow_hardware_topology(tmp_path):
    """lhq's hierarchy comes from vpmap groups; with hw discovery the
    groups ARE the cache/package domains (sched_lhq_module.c:30-44)."""
    from parsec_tpu.core.vpmap import VPMap
    root = _fake_sysfs(str(tmp_path))
    vm = VPMap.from_hardware(8, sysfs_root=root)
    by_vp = {}
    for i in range(8):
        by_vp.setdefault(vm.vp_of(i), []).append(i)
    assert len(by_vp) == 2
    assert all(len(v) == 4 for v in by_vp.values())
