"""VP map, thread placement, hierarchical/per-VP schedulers, and
scheduler statistics (reference: parsec/vpmap.{c,h}, bindthread.c,
sched_lhq/llp modules, the display_stats hook sched.h:299)."""

import numpy as np
import pytest

from parsec_tpu.core.vpmap import VPMap, bind_current_thread
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.utils.mca import params


def test_vpmap_flat():
    vm = VPMap.from_flat(6)
    assert vm.nb_vps == 1
    assert [vm.vp_of(i) for i in range(6)] == [0] * 6
    assert vm.threads_of_vp(0) == list(range(6))


def test_vpmap_from_parameters():
    vm = VPMap.from_parameters("2:2", 5)
    assert vm.nb_vps == 2
    assert [vm.vp_of(i) for i in range(5)] == [0, 0, 1, 1, 1]
    assert VPMap.from_parameters("garbage", 3).nb_vps == 1


def test_vpmap_from_hardware():
    vm = VPMap.from_hardware(4)
    assert vm.nb_threads == 4
    assert vm.nb_vps >= 1
    # cores are assigned (or None where unsupported)
    assert all(isinstance(vm.core_of(i), (int, type(None))) for i in range(4))


def test_bind_current_thread_roundtrip():
    import os
    if not hasattr(os, "sched_setaffinity"):
        pytest.skip("no sched_setaffinity on this platform")
    before = os.sched_getaffinity(0)
    try:
        assert bind_current_thread(sorted(before)[0])
        assert os.sched_getaffinity(0) == {sorted(before)[0]}
    finally:
        os.sched_setaffinity(0, before)


def _run_chain(scheduler, nb_cores=4, **ctx_kw):
    NT = 12
    V = VectorTwoDimCyclic(mb=2, lm=2 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("chain", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .body(lambda T: T + 1.0)
    with Context(nb_cores=nb_cores, scheduler=scheduler, **ctx_kw) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
        stats = ctx.scheduler.display_stats(None)
    np.testing.assert_allclose(
        np.asarray(V.data_of(NT - 1).pull_to_host().payload), float(NT))
    return stats


def test_llp_multi_vp():
    """llp with 2 VPs x 2 streams: per-VP ring LIFOs + cross-VP steal."""
    params.set("vpmap", "2:2")
    try:
        stats = _run_chain("llp", nb_cores=4)
    finally:
        params.unset("vpmap")
    assert "llp" in stats and "local=" in stats


def test_lhq_hierarchy_runs_and_reports():
    stats = _run_chain("lhq", nb_cores=4)
    assert "lhq" in stats
    # all selections are accounted somewhere in the hierarchy
    got = dict(kv.split("=") for kv in stats.split()[1:])
    assert int(got["local"]) + int(got["steals"]) + int(got["system"]) >= 12


def test_lfq_stats_nonempty():
    stats = _run_chain("lfq", nb_cores=2)
    assert stats.startswith("lfq:")


def test_worker_binding_smoke():
    """runtime_bind_threads=1 must not break execution (binding is
    best-effort; reference: parsec_bindthread)."""
    params.set("runtime_bind_threads", 1)
    params.set("vpmap", "hw")
    try:
        _run_chain("lfq", nb_cores=2)
    finally:
        params.unset("runtime_bind_threads")
        params.unset("vpmap")
