"""mp-QR accuracy ladder tests (apps/qr_check.py — VERDICT r5 #9): the
CSNE LS-refinement must recover f32-class accuracy from low-precision
storage factors, mirroring potrf's HPL-AI refine_solve story."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic


def _factor(a, dtype):
    from parsec_tpu.apps.qr import qr_taskpool
    n = a.shape[0]
    mb = n // 4
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, dtype=dtype)
    for m, nn in A.local_tiles():
        A.data_of(m, nn).overwrite_host(
            a[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb].astype(dtype))
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(qr_taskpool(A, device="tpu"))
        ctx.wait()
    return A, mb


def test_ls_refine_f32_reaches_f32_class():
    import jax.numpy as jnp
    from parsec_tpu.apps.qr_check import ls_refine
    rng = np.random.default_rng(0)
    n = 64
    a = (0.1 * rng.standard_normal((n, n)) + np.eye(n)).astype(np.float32)
    A, mb = _factor(a, np.float32)
    orig = lambda i, j: jnp.asarray(
        a[i * mb:(i + 1) * mb, j * mb:(j + 1) * mb])
    hist = ls_refine(A, orig, steps=3)
    assert hist[0] < 1e-2               # direct CSNE already decent
    assert min(hist) <= 1e-6            # ladder reaches f32-class
    assert hist[-1] <= hist[0]


def test_ls_refine_recovers_from_bf16_storage():
    """The HPL-AI contract for QR: bf16-storage factor, f32-class
    solution accuracy after a few refinement steps."""
    import ml_dtypes
    import jax.numpy as jnp
    from parsec_tpu.apps.qr_check import ls_refine
    rng = np.random.default_rng(1)
    n = 64
    a32 = (0.05 * rng.standard_normal((n, n)) + np.eye(n)) \
        .astype(np.float32)
    A, mb = _factor(a32, ml_dtypes.bfloat16)
    # the factor factored the bf16-ROUNDED operand; refine against it
    ar = a32.astype(ml_dtypes.bfloat16).astype(np.float32)
    orig = lambda i, j: jnp.asarray(
        ar[i * mb:(i + 1) * mb, j * mb:(j + 1) * mb])
    hist = ls_refine(A, orig, steps=4)
    assert hist[0] > 1e-4               # bf16 factor alone is NOT f32
    assert min(hist) <= 1e-6            # ladder recovers f32-class
