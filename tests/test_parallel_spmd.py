"""SPMD schedule tests on the virtual 8-device CPU mesh (SURVEY.md §5.8)."""

import numpy as np
import pytest

from parsec_tpu.parallel.spmd import (halo_stencil_fn, make_mesh,
                                      ring_reduce_gemm_fn, summa_gemm_fn)


@pytest.fixture(scope="module")
def rng():
    return np.random.default_rng(3)


def test_make_mesh_square_factorization():
    mesh = make_mesh()
    assert mesh.devices.size == 8
    assert mesh.devices.shape == (2, 4)
    assert mesh.axis_names == ("p", "q")


def test_summa_gemm_matches_numpy(rng):
    mesh = make_mesh()
    p, q = mesh.devices.shape
    a = rng.standard_normal((4 * p, 8 * p * q)).astype(np.float32)
    b = rng.standard_normal((8 * p * q, 4 * q)).astype(np.float32)
    c = np.asarray(summa_gemm_fn(mesh)(a, b))
    np.testing.assert_allclose(c, a @ b, rtol=1e-4, atol=1e-4)


def test_ring_reduce_gemm_matches_numpy(rng):
    mesh = make_mesh(shape=(8,), axis_names=("p",))
    a = rng.standard_normal((16, 32)).astype(np.float32)
    b = rng.standard_normal((32, 24)).astype(np.float32)
    c = np.asarray(ring_reduce_gemm_fn(mesh)(a, b))
    np.testing.assert_allclose(c, a @ b, rtol=1e-4, atol=1e-4)


def test_halo_stencil_matches_serial(rng):
    mesh = make_mesh(shape=(8,), axis_names=("p",))
    x = rng.standard_normal(64).astype(np.float32)

    def serial_step(u):
        ext = np.concatenate([u[-1:], u, u[:1]])
        return (ext[:-2] + ext[2:] + u) / 3.0

    want = serial_step(serial_step(x))
    got = np.asarray(halo_stencil_fn(mesh, steps=2)(x))
    np.testing.assert_allclose(got, want, rtol=1e-5, atol=1e-5)


def test_graft_entry_contract():
    import __graft_entry__ as g
    import jax
    fn, args = g.entry()
    out = jax.jit(fn)(*args)
    assert np.asarray(out).shape == args[0].shape
    g.dryrun_multichip(8)
