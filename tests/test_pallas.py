"""Pallas tile-kernel tests (the user-kernel seam; reference: the BODY
[type=CUDA] incarnations + tests/dsl/ptg/cuda/stress.jdf pattern).
Off-TPU the kernels run in interpreter mode via the same entry points."""

import numpy as np
import pytest

from parsec_tpu.apps.pallas_kernels import pallas_gemm_tile
from parsec_tpu.utils.mca import params


def _rel_err(got, ref):
    return np.abs(got - ref).max() / max(1.0, np.abs(ref).max())


def test_pallas_blocked_matmul_matches():
    """bf16 panels + f32 accumulator through the blocked Pallas program."""
    import jax
    import ml_dtypes
    rng = np.random.default_rng(0)
    a = rng.standard_normal((256, 256)).astype(ml_dtypes.bfloat16)
    b = rng.standard_normal((256, 256)).astype(ml_dtypes.bfloat16)
    c = rng.standard_normal((256, 256)).astype(np.float32)
    fn = pallas_gemm_tile(1.0, bm=128, bn=128, bk=128)
    got = np.asarray(jax.jit(fn)(a, b, c))
    ref = c + np.asarray(a, np.float32) @ np.asarray(b, np.float32)
    assert _rel_err(got, ref) < 1e-3


def test_pallas_alpha_and_fallback():
    """Unaligned shapes (not multiples of 128) must take the fused-XLA
    fallback — Mosaic rejects such blocks — with alpha honored (TPU's
    default matmul precision is bf16, hence the tolerance)."""
    import jax
    rng = np.random.default_rng(1)
    for n in (100, 640 + 8):     # sub-block unaligned; super-block too
        a = rng.standard_normal((n, n)).astype(np.float32)
        got = np.asarray(jax.jit(pallas_gemm_tile(2.0))(a, a, a))
        ref = a + 2.0 * a @ a
        assert _rel_err(got, ref) < 5e-2
    # precision='highest' on the fallback forces f32 multiplies
    a = rng.standard_normal((100, 100)).astype(np.float32)
    got = np.asarray(jax.jit(
        pallas_gemm_tile(1.0, precision="highest"))(a, a, a))
    assert _rel_err(got, a + a @ a) < 1e-5


def test_gemm_taskpool_with_pallas_kernel():
    """The full runtime path with --mca gemm_pallas 1: every device GEMM
    task runs the hand-written kernel."""
    from parsec_tpu.apps import gemm as gemm_mod
    from parsec_tpu.apps.gemm import gemm_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    rng = np.random.default_rng(2)
    n, mb = 256, 128
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A").from_array(a)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="B").from_array(b)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="C").from_array(
        np.zeros((n, n), np.float32))
    params.set("gemm_pallas", 1)
    gemm_mod._kernels.clear()      # force kernel re-selection
    try:
        with Context(nb_cores=2) as ctx:
            if not ctx.device_registry.accelerators:
                pytest.skip("no accelerator attached")
            ctx.add_taskpool(gemm_taskpool(A, B, C, device="tpu"))
            ctx.wait(timeout=300)
        # the switch actually selected the Pallas kernel (a silently
        # broken param would still produce correct numerics via XLA)
        assert any(isinstance(k, tuple) and k and k[0] == "pallas"
                   for k in gemm_mod._kernels), gemm_mod._kernels.keys()
    finally:
        params.unset("gemm_pallas")
        gemm_mod._kernels.clear()
    assert _rel_err(C.to_array(), a @ b) < 5e-2


def test_pallas_gram_matches():
    """Blocked Gram kernel (the inner-blocked QR panel's HIGHEST hot
    spot): X^T X with f32 VMEM accumulation over the K-innermost grid."""
    import jax
    rng = np.random.default_rng(2)
    X = rng.standard_normal((512, 256)).astype(np.float32)
    from parsec_tpu.apps.pallas_kernels import pallas_gram_tile
    got = np.asarray(jax.jit(pallas_gram_tile(bn=128, bk=128))(X))
    ref = X.T @ X
    assert _rel_err(got, ref) < 1e-4


def test_pallas_gram_unaligned_fallback():
    import jax
    rng = np.random.default_rng(3)
    X = rng.standard_normal((100, 36)).astype(np.float32)
    from parsec_tpu.apps.pallas_kernels import pallas_gram_tile
    got = np.asarray(jax.jit(pallas_gram_tile())(X))
    assert _rel_err(got, X.T @ X) < 1e-4


def test_blocked_geqrt_with_pallas_gram():
    """The qr_pallas_gram MCA knob routes the blocked panel's Gram
    products through the Pallas kernel; the factorization contract is
    unchanged."""
    import jax.numpy as jnp
    from parsec_tpu.apps.qr import _mk_geqrt
    mb, ib = 256, 128
    rng = np.random.default_rng(4)
    T = rng.standard_normal((mb, mb)).astype(np.float32)
    out = _mk_geqrt(ib, pallas_gram=True)(
        jnp.asarray(T), jnp.zeros((mb, mb), jnp.float32))
    R = np.asarray(out["T"], np.float64)
    Q = np.asarray(out["Q"], np.float64)
    assert np.abs(Q.T @ Q - np.eye(mb)).max() < 5e-5
    assert np.abs(Q @ R - T).max() / np.abs(T).max() < 1e-5
