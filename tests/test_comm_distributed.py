"""Distributed tests: multiprocess SPMD over the socket comm engine.

Mirrors the reference's multi-process-on-one-node strategy (SURVEY.md §4:
``mpiexec -n N`` on one node; dtd_test_ce.c drives the comm-engine vtable
directly; Ex05_Broadcast exercises the activation fan-out; apps/pingpong
measures the link).  Worker functions are module-level for spawn pickling.
"""

import numpy as np
import pytest

from parsec_tpu.comm.launch import run_distributed

# -- comm engine direct (reference: dtd_test_ce.c) --------------------------

def _ce_echo(ctx, rank, nranks):
    import threading
    from parsec_tpu.comm.engine import TAG_USER
    got = []
    evt = threading.Event()

    def cb(src, payload):
        got.append((src, payload))
        evt.set()

    ce = ctx.comm.ce
    ce.tag_register(TAG_USER, cb)
    ce.barrier()
    ce.send_am(TAG_USER, (rank + 1) % nranks, {"hello": rank})
    if not evt.wait(30):
        raise TimeoutError("no AM received")
    ce.barrier()
    src, payload = got[0]
    assert src == (rank - 1) % nranks
    assert payload == {"hello": src}
    return "ok"


def test_ce_am_ring():
    assert run_distributed(_ce_echo, 3) == ["ok"] * 3


# -- PTG chain across ranks (reference: Ex03 chain over MPI) ----------------

def _chain(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    NT = 8
    V = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0

    p = PTG("chain", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=NT: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    ctx.add_taskpool(p.build())
    ctx.wait()
    # tile k ends with value k+1 (chain accumulates one increment per hop)
    out = {}
    for m, _ in V.local_tiles():
        out[m] = float(np.asarray(V.data_of(m).pull_to_host().payload)[0])
    return out


def test_ptg_chain_across_ranks():
    results = run_distributed(_chain, 2)
    merged = {}
    for r in results:
        merged.update(r)
    assert merged == {k: float(k + 1) for k in range(8)}


# -- broadcast fan-out (reference: Ex05_Broadcast + bcast topologies) -------

def _bcast(ctx, rank, nranks, topo):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    from parsec_tpu.utils.mca import params
    params.set("comm_coll_bcast", topo)
    ctx.comm.bcast = topo
    NT = nranks * 2
    # distinct source and sink collections: a sink must not alias the
    # root's tile through two flows
    V = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank,
                           name="V")
    W = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank,
                           name="W")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    for m, _ in W.local_tiles():
        W.data_of(m).copy_on(0).payload[:] = 0.0

    p = PTG("bcast", NT=NT)
    p.task("ROOT", z=Range(0, 0)) \
        .affinity(lambda V=V: V(0)) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("SINK", "T",
                       lambda NT=NT: [dict(i=i) for i in range(NT)]))) \
        .body(lambda T: T + 42.0)
    p.task("SINK", i=Range(0, NT - 1)) \
        .affinity(lambda i, W=W: W(i)) \
        .flow("T", "READ", IN(TASK("ROOT", "T", lambda: dict(z=0)))) \
        .flow("O", "RW", IN(DATA(lambda i, W=W: W(i))),
              OUT(DATA(lambda i, W=W: W(i)))) \
        .body(lambda T, O: {"O": np.asarray(O) + np.asarray(T)})
    ctx.add_taskpool(p.build())
    ctx.wait()
    vals = {}
    for m, _ in W.local_tiles():
        vals[m] = float(np.asarray(W.data_of(m).pull_to_host().payload)[0])
    return vals


@pytest.mark.parametrize("topo", ["star", "chain", "binomial"])
def test_broadcast_topologies(topo):
    results = run_distributed(_bcast, 3, args=(topo,))
    merged = {}
    for r in results:
        merged.update(r)
    assert merged == {i: 42.0 for i in range(6)}


# -- rendezvous GET for large payloads --------------------------------------

def _rendezvous(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    ctx.comm.eager = 16   # force the GET path for any real tile
    NT = 4
    V = VectorTwoDimCyclic(mb=256, lm=NT * 256, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m)

    p = PTG("rdv", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=NT: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    ctx.add_taskpool(p.build())
    ctx.wait()
    out = {}
    for m, _ in V.local_tiles():
        out[m] = float(np.asarray(V.data_of(m).pull_to_host().payload)[0])
    return out


def test_rendezvous_get_path():
    results = run_distributed(_rendezvous, 2)
    merged = {}
    for r in results:
        merged.update(r)
    # chain carries tile 0's value (0.0) forward, +1 per hop
    assert merged == {k: float(k + 1) for k in range(4)}


# -- distributed tiled GEMM (reference: the DPLASMA-style driver) -----------

def _seed(name, m, n):
    # deterministic across processes (str hash() is randomized per run)
    return (ord(name[0]) * 10007 + m * 101 + n) % (2**31)


def _dist_gemm(ctx, rank, nranks):
    from parsec_tpu.apps.gemm import gemm_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    mt = nt = kt = 4
    mb = 8
    P = 2
    mk = dict(nodes=nranks, myrank=rank, P=P)

    def fill(M):
        for m, n in M.local_tiles():
            rng = np.random.default_rng(_seed(M.name, m, n))
            M.data_of(m, n).copy_on(0).payload[:] = \
                rng.standard_normal((mb, mb)).astype(np.float32)

    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A",
                          **mk)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B",
                          **mk)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C",
                          **mk)
    for M in (A, B, C):
        fill(M)
    ctx.add_taskpool(gemm_taskpool(A, B, C, device="cpu"))
    ctx.wait()

    # every rank can rebuild the GLOBAL inputs deterministically and
    # check its local C tiles against the numpy answer
    def full(name, rows, cols):
        out = np.zeros((rows * mb, cols * mb), np.float32)
        for m in range(rows):
            for n in range(cols):
                rng = np.random.default_rng(_seed(name, m, n))
                out[m * mb:(m + 1) * mb, n * mb:(n + 1) * mb] = \
                    rng.standard_normal((mb, mb)).astype(np.float32)
        return out
    want = full("C", mt, nt) + full("A", mt, kt) @ full("B", kt, nt)
    for m, n in C.local_tiles():
        got = np.asarray(C.data_of(m, n).pull_to_host().payload)
        np.testing.assert_allclose(
            got, want[m * mb:(m + 1) * mb, n * mb:(n + 1) * mb],
            rtol=1e-3, atol=1e-3)
    return len(C.local_tiles())


def test_distributed_gemm_4ranks():
    counts = run_distributed(_dist_gemm, 4, timeout=180)
    assert sum(counts) == 16   # every C tile verified somewhere


# -- funnelled comm thread: many small messages (reference: the comm
# thread + dep_cmd_queue, remote_dep_mpi.c:461-503) ------------------------

def _many_small_msgs(ctx, rank, nranks):
    """A long cross-rank dependency chain of tiny payloads: every edge is
    one small message through the funnelled progress thread, stressing
    enqueue ordering and per-peer send aggregation."""
    import numpy as np
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import DTDTaskpool, AFFINITY, INOUT

    V = VectorTwoDimCyclic(mb=2, lm=2, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("stress")
    ctx.add_taskpool(tp)
    ctx.start()
    t = tp.tile_of(V, 0)
    steps = 240
    for i in range(steps):
        tp.insert_task(lambda T: T + 1.0, (t, INOUT),
                       (i % nranks, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 0:
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, float(steps))
    # short-circuit memcpy: a local copy thread-shifted onto the comm
    # progress thread (reference: parsec_remote_dep_memcpy)
    import time
    from parsec_tpu.data.data import new_data
    src = new_data(np.full(4, 7.0, np.float32)).copy_on(0)
    dst = new_data(np.zeros(4, np.float32)).copy_on(0)
    ctx.comm.memcpy_shift(dst, src)
    deadline = time.monotonic() + 10
    while not np.allclose(np.asarray(dst.payload), 7.0):
        if time.monotonic() > deadline:
            raise TimeoutError("memcpy_shift never landed")
        time.sleep(0.01)
    return "ok"


def test_funnelled_many_small_messages():
    assert run_distributed(_many_small_msgs, 3, timeout=240) == ["ok"] * 3


# -- CE one-sided put/get over registered memory (reference:
# dtd_test_ce.c drives the comm-engine vtable directly: AM + put/get;
# mpi_no_thread_put:793 / get:896) -----------------------------------------

def _ce_onesided(ctx, rank, nranks):
    import threading
    import numpy as np
    ce = ctx.comm.ce
    assert ce.CAP_ONESIDED and ce.CAP_MT
    # each rank registers a region; peers write and read it one-sidedly
    mine = np.zeros(8, np.float32)
    rid = ce.mem_register(mine)
    # exchange region ids (they happen to be equal, but don't assume)
    rids = [None] * nranks
    got_rids = threading.Event()
    from parsec_tpu.comm.engine import TAG_USER

    def rid_cb(src, payload):
        rids[src] = payload
        if all(r is not None for r in rids):
            got_rids.set()

    ce.tag_register(TAG_USER, rid_cb)
    ce.barrier()
    for r in range(nranks):
        ce.send_am(TAG_USER, r, rid)
    assert got_rids.wait(30)

    # PUT: write my pattern into my right neighbor's region
    right = (rank + 1) % nranks
    acked = threading.Event()
    errs = []
    ce.put(right, np.full(8, 10.0 + rank, np.float32), rids[right],
           on_complete=lambda err=None: (errs.append(err) if err else None,
                                         acked.set()))
    assert acked.wait(30)
    assert not errs, errs
    ce.barrier()
    np.testing.assert_allclose(mine, 10.0 + (rank - 1) % nranks)

    # GET: read my left neighbor's region back
    left = (rank - 1) % nranks
    box = {}
    fetched = threading.Event()

    def on_data(arr):
        box["arr"] = arr
        fetched.set()

    ce.get(left, rids[left], on_data)
    assert fetched.wait(30)
    np.testing.assert_allclose(box["arr"], 10.0 + (left - 1) % nranks)
    ce.barrier()
    ce.mem_unregister(rid)
    return "ok"


def test_ce_onesided_put_get():
    assert run_distributed(_ce_onesided, 3) == ["ok"] * 3


# -- remote reshape: the pre-send conversion path (reference:
# parsec_reshape.c remote paths; tests/collections/reshape/) ---------------

def _remote_reshape(ctx, rank, nranks):
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK

    NT = 4
    V = VectorTwoDimCyclic(mb=8, lm=8 * NT, nodes=nranks, myrank=rank)
    W = VectorTwoDimCyclic(mb=8, lm=8 * NT, nodes=nranks, myrank=rank,
                           name="W")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 1.5 + m
    for m, _ in W.local_tiles():
        W.data_of(m).copy_on(0).payload[:] = 0.0
    seen = {}

    bf16 = Dtt(dtype=ml_dtypes.bfloat16, name="bf16")
    p = PTG("rrs", NT=NT)
    # P(k) on V(k)'s rank ships its tile to C(k) on W(k+1 mod NT)'s rank
    # with a bf16 edge dtt: the CONVERTED payload travels (half the
    # bytes), and the consumer observes bf16
    p.task("P", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "READ",
              IN(DATA(lambda k, V=V: V(k))),
              OUT(TASK("C", "T", lambda k: dict(k=k)), dtt=bf16)) \
        .body(lambda: None)
    p.task("C", k=Range(0, NT - 1)) \
        .affinity(lambda k, W=W, NT=NT: W((k + 1) % NT)) \
        .flow("T", "READ", IN(TASK("P", "T", lambda k: dict(k=k)))) \
        .flow("O", "RW",
              IN(DATA(lambda k, W=W, NT=NT: W((k + 1) % NT))),
              OUT(DATA(lambda k, W=W, NT=NT: W((k + 1) % NT)))) \
        .body(lambda T, O, k, seen=seen: (
            seen.__setitem__(k, str(np.asarray(T).dtype)),
            np.asarray(T).astype(np.float32) * 2.0)[1])
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    for m, _ in W.local_tiles():
        k = (m - 1) % NT
        got = np.asarray(W.data_of(m).pull_to_host().payload)
        expect = 2.0 * np.asarray(
            np.full(8, 1.5 + k, np.float32).astype(ml_dtypes.bfloat16),
            dtype=np.float32)
        np.testing.assert_allclose(got, expect)
    # every consumer this rank ran saw a bf16 payload
    assert all(dt == "bfloat16" for dt in seen.values()), seen
    return "ok"


def test_remote_presend_reshape():
    assert run_distributed(_remote_reshape, 2) == ["ok"] * 2


# -- 8-rank scale (the north-star scaling axis, SURVEY §6: 8 -> 256
# chips; here 8 processes on one node per the reference's test strategy) ----

def _scale8(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    NT = nranks * 3
    V = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("scale", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=180)
    out = {}
    for m, _ in V.local_tiles():
        out[m] = float(np.asarray(V.data_of(m).pull_to_host().payload)[0])
    return out


def test_chain_8_ranks():
    results = run_distributed(_scale8, 8, timeout=300)
    merged = {}
    for r in results:
        merged.update(r)
    assert merged == {k: float(k + 1) for k in range(24)}


# -- failure detection: a dying peer fails waiters fast ---------------------

def _survivor_proc(rank, nranks, port_base, outq):
    """Standalone 2-rank harness (not run_distributed: its epilogue
    barrier would entangle the failure we are injecting)."""
    import os
    import time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    from parsec_tpu.core.context import Context
    ce = SocketCE(rank, nranks, port_base)
    ctx = Context(nb_cores=1, rank=rank, nranks=nranks)
    rde = RemoteDepEngine(ce, ctx)
    ce.barrier()
    if rank == 1:
        os._exit(17)              # crash without goodbye
    # rank 0: the loss must surface as a recorded ConnectionError AND
    # fail a barrier fast (well under its 60s timeout)
    t0 = time.monotonic()
    deadline = t0 + 60            # generous under 1-core suite load
    while not ctx._errors:
        if time.monotonic() > deadline:
            outq.put(("timeout", None, -1.0))
            return
        time.sleep(0.02)
    kind = type(ctx._errors[0][0]).__name__
    try:
        ce.barrier(timeout=60)
        bar = "no-error"
    except ConnectionError:
        bar = "connection-error"
    except TimeoutError:
        bar = "timeout"
    outq.put((kind, bar, time.monotonic() - t0))


def test_peer_death_detection():
    """_peer_lost records a ConnectionError on the survivor and wakes
    barrier waiters with a cause — removing the detection makes this
    time out, not pass vacuously."""
    import multiprocessing as mp
    from parsec_tpu.comm.launch import _probe_port_base
    mpctx = mp.get_context("spawn")
    outq = mpctx.Queue()
    base = _probe_port_base(2)
    procs = [mpctx.Process(target=_survivor_proc, args=(r, 2, base, outq),
                           daemon=True)
             for r in range(2)]
    for p in procs:
        p.start()
    kind, bar, dt = outq.get(timeout=120)
    for p in procs:
        p.join(timeout=10)
        if p.is_alive():
            p.terminate()
    # PR 5: peer loss surfaces as the structured PeerFailedError (a
    # ConnectionError subclass carrying the dead rank)
    assert kind in ("ConnectionError", "PeerFailedError"), kind
    assert bar == "connection-error", bar
    # the point is beating the 60s barrier timeout, with headroom for
    # a loaded 1-core host (the old 30s bound flaked under full-suite
    # contention — and its timeout branch put a 2-tuple the unpack
    # above crashed on)
    assert dt < 45, f"loss surfaced too slowly ({dt:.1f}s)"


# -- multi-host address book (the DCN deployment path) ----------------------

def _hosts_chain(ctx, rank, nranks):
    # same chain as _ce_echo but through the comm_hosts address book
    import threading
    from parsec_tpu.comm.engine import TAG_USER
    assert ctx.comm.ce._hosts == ["127.0.0.1"] * nranks
    got = threading.Event()
    ce = ctx.comm.ce
    ce.tag_register(TAG_USER, lambda src, p: got.set())
    ce.barrier()
    ce.send_am(TAG_USER, (rank + 1) % nranks, "hi")
    assert got.wait(30)
    ce.barrier()
    return "ok"


def test_multihost_address_book():
    import os
    os.environ["PARSEC_COMM_HOSTS"] = "127.0.0.1,127.0.0.1,127.0.0.1"
    try:
        assert run_distributed(_hosts_chain, 3) == ["ok"] * 3
    finally:
        del os.environ["PARSEC_COMM_HOSTS"]
    from parsec_tpu.comm.engine import SocketCE
    with pytest.raises(ValueError, match="2 hosts for 3"):
        os.environ["PARSEC_COMM_HOSTS"] = "a,b"
        try:
            SocketCE(0, 3, port_base=29123)
        finally:
            del os.environ["PARSEC_COMM_HOSTS"]


def _dist_qr(ctx, rank, nranks):
    # tiled QR across ranks: validates the compact-WY TSQRT/TSMQR
    # kernels' edge payloads (V/T^T pairs) riding the remote-dep
    # protocol (VERDICT r2 #4: QR at POTRF parity)
    from parsec_tpu.apps.qr import qr_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    nt, mb, P = 4, 8, 2
    n = nt * mb
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="Q",
                          nodes=nranks, myrank=rank, P=P)
    for m, nn in A.local_tiles():
        rng = np.random.default_rng(_seed("Q", m, nn))
        A.data_of(m, nn).copy_on(0).payload[:] = \
            rng.standard_normal((mb, mb)).astype(np.float32)
    ctx.add_taskpool(qr_taskpool(A, device="cpu"))
    ctx.wait()
    # rebuild the global input; R must be upper-triangular with
    # |R| matching the true QR's |R| (signs are convention-dependent)
    full = np.zeros((n, n), np.float32)
    for m in range(nt):
        for nn in range(nt):
            rng = np.random.default_rng(_seed("Q", m, nn))
            full[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb] = \
                rng.standard_normal((mb, mb)).astype(np.float32)
    want = np.abs(np.linalg.qr(full, mode="r"))
    checked = 0
    for m, nn in A.local_tiles():
        got = np.asarray(A.data_of(m, nn).pull_to_host().payload)
        blk = slice(m * mb, (m + 1) * mb), slice(nn * mb, (nn + 1) * mb)
        if m > nn:
            np.testing.assert_allclose(got, 0.0, atol=1e-3)
        elif m == nn:
            np.testing.assert_allclose(np.abs(np.triu(got)),
                                       want[blk], rtol=2e-2, atol=2e-2)
            np.testing.assert_allclose(np.tril(got, -1), 0.0, atol=1e-3)
        else:
            # above-diagonal R block: |R| matches up to per-row signs
            np.testing.assert_allclose(np.abs(got), want[blk],
                                       rtol=2e-2, atol=2e-2)
        checked += 1
    return checked


def test_distributed_qr_4ranks():
    counts = run_distributed(_dist_qr, 4, timeout=180)
    assert sum(counts) == 16   # every tile verified somewhere


def test_chain_16_ranks():
    """16-rank smoke: the address book, handshake, and chain dataflow
    hold at 2x the prior scale (VERDICT r2 #9 scale-axis hardening)."""
    results = run_distributed(_scale8, 16, timeout=420, nb_cores=1)
    merged = {}
    for r in results:
        merged.update(r)
    assert merged == {k: float(k + 1) for k in range(48)}


# -- wire-format guard (VERDICT r2 #9): a bad peer fails its connection,
# not the recv thread ------------------------------------------------------

def _wire_guard_victim(outq, port_base):
    import os
    import socket
    import struct
    import time
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ.pop("PALLAS_AXON_POOL_IPS", None)
    from parsec_tpu.comm.engine import (SocketCE, TAG_USER, _HANDSHAKE,
                                        _LEN, _WIRE_MAGIC, _WIRE_VERSION)
    from parsec_tpu.utils.mca import params
    params.set("comm_max_frame_mb", 1)
    errors = []
    got = []
    ce = SocketCE(0, 3, port_base=port_base)
    ce.on_error = errors.append
    ce.tag_register(TAG_USER, lambda src, p: got.append((src, p)))

    def dial(rank, magic=_WIRE_MAGIC, version=_WIRE_VERSION):
        s = socket.create_connection(("127.0.0.1", port_base), timeout=10)
        s.sendall(_HANDSHAKE.pack(magic, version, rank))
        return s

    # 1) cross-version peer: rejected at handshake, no peer registered
    bad = dial(1, version=99)
    time.sleep(0.3)
    handshake_rejected = 1 not in ce._peers

    # 2) well-behaved peer 1 sends a valid frame...
    good = dial(1)
    import pickle
    body = pickle.dumps("hello")
    good.sendall(_LEN.pack(TAG_USER, len(body), 0) + body)
    # 3) ...peer 2 handshakes fine, then sends an absurd length field
    evil = dial(2)
    evil.sendall(_LEN.pack(TAG_USER, 1 << 40, 0))
    time.sleep(0.5)
    # 4) and peer 1 can STILL talk (its recv loop was untouched)
    body2 = pickle.dumps("again")
    good.sendall(_LEN.pack(TAG_USER, len(body2), 0) + body2)
    deadline = time.monotonic() + 10
    while len(got) < 2 and time.monotonic() < deadline:
        time.sleep(0.05)
    outq.put({
        "handshake_rejected": handshake_rejected,
        "got": list(got),
        "dead": sorted(ce.dead_peers),
        "errors": [type(e).__name__ for e in errors],
    })
    # 5) a corrupt (unpicklable) frame from ANOTHER peer also severs
    # only its sender, and the surviving peer still delivers afterwards
    evil2 = dial(3)
    garbage = b"\x00\xde\xad\xbe\xef not a pickle"
    evil2.sendall(_LEN.pack(TAG_USER, len(garbage), 0) + garbage)
    body3 = pickle.dumps("still-here")
    good.sendall(_LEN.pack(TAG_USER, len(body3), 0) + body3)
    deadline = time.monotonic() + 10
    while (len(got) < 3 or 3 not in ce.dead_peers) \
            and time.monotonic() < deadline:
        time.sleep(0.05)
    outq.put({
        "got": list(got),
        "dead": sorted(ce.dead_peers),
    })
    for s in (bad, good, evil, evil2):
        try:
            s.close()
        except OSError:
            pass
    ce.fini()


def test_wire_format_guard():
    import multiprocessing as mp
    from parsec_tpu.comm.launch import _probe_port_base
    mpctx = mp.get_context("spawn")
    outq = mpctx.Queue()
    base = _probe_port_base(1)
    p = mpctx.Process(target=_wire_guard_victim, args=(outq, base),
                      daemon=True)
    p.start()
    res = outq.get(timeout=120)
    res2 = outq.get(timeout=120)
    p.join(timeout=15)
    if p.is_alive():
        p.terminate()
    assert res["handshake_rejected"], "cross-version peer was accepted"
    # the oversized frame severed ONLY rank 2's connection, with a cause
    assert 2 in res["dead"], res
    assert any(e in ("ConnectionError", "PeerFailedError")
               for e in res["errors"]), res
    # the well-behaved peer's messages all arrived, before AND after
    assert [m for _s, m in res["got"]] == ["hello", "again"], res
    # the unpicklable frame severed rank 3; the good peer kept talking
    assert 3 in res2["dead"], res2
    assert [m for _s, m in res2["got"]][-1] == "still-here", res2


# -- reshape-corpus remote cases (VERDICT r4 missing #3; reference:
# tests/collections/reshape/remote_read_reshape.jdf + remote_no_re_reshape
# + the NEW-typed remote case) ---------------------------------------------

def _remote_consumer_reshape(ctx, rank, nranks):
    """Receiver-side IN dtt on a remote edge: the payload crosses the
    wire in the producer's type; the CONSUMER's datatype lookup converts
    on arrival (reference: remote_dep_get_datatypes)."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, TASK
    bf = np.dtype(ml_dtypes.bfloat16)
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 3.0
    seen = {}
    p = PTG("rcr")
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("C", "X", lambda: dict()))) \
        .body(lambda: None)
    p.task("C") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()), dtt=Dtt(dtype=bf))) \
        .body(lambda X: seen.update(dtype=str(np.asarray(X).dtype),
                                    val=float(np.asarray(X)[0])))
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    return seen


def test_remote_consumer_side_reshape():
    res = run_distributed(_remote_consumer_reshape, 2)
    assert res[1] == {"dtype": "bfloat16", "val": 3.0}


def _remote_no_re_reshape(ctx, rank, nranks):
    """OUT dtt and IN dtt name the SAME type on a remote edge: the
    presend conversion must satisfy the receiver without a second
    conversion (reference: remote_no_re_reshape.jdf)."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, TASK
    bf = np.dtype(ml_dtypes.bfloat16)
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 5.0
    seen = {}
    p = PTG("rnr")
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("C", "X", lambda: dict()), dtt=Dtt(dtype=bf))) \
        .body(lambda: None)
    p.task("C") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()), dtt=Dtt(dtype=bf))) \
        .body(lambda X: seen.update(dtype=str(np.asarray(X).dtype),
                                    val=float(np.asarray(X)[0])))
    tp = p.build()
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    # receiver-side: the arrived payload is ALREADY bf16, so the IN dtt
    # must not convert again
    return {"seen": seen, "conv": tp.reshape.conversions}


def test_remote_no_re_reshape():
    res = run_distributed(_remote_no_re_reshape, 2)
    assert res[1]["seen"] == {"dtype": "bfloat16", "val": 5.0}
    assert res[1]["conv"] == 0      # consumer rank: no re-reshape


def _remote_new_flow_reshape(ctx, rank, nranks):
    """A NEW-flow arena temporary crossing ranks with a consumer-side
    dtt: the reference's remote reshape-into-NEW case."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import IN, NEW, OUT, PTG, TASK
    bf = np.dtype(ml_dtypes.bfloat16)
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    seen = {}
    p = PTG("rnew")
    p.arena("scratch", (4,), np.float32)

    def produce(X):
        X[:] = np.arange(4, dtype=np.float32) + 1.0
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "RW",
              IN(NEW("scratch")),
              OUT(TASK("C", "X", lambda: dict()))) \
        .body(produce)
    p.task("C") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()), dtt=Dtt(dtype=bf))) \
        .body(lambda X: seen.update(
            dtype=str(np.asarray(X).dtype),
            vals=[float(v) for v in np.asarray(X).astype(np.float32)]))
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    return seen


def test_remote_new_flow_reshape():
    res = run_distributed(_remote_new_flow_reshape, 2)
    assert res[1] == {"dtype": "bfloat16", "vals": [1.0, 2.0, 3.0, 4.0]}


def _remote_multi_outs_worker(ctx, rank, nranks):
    """Reference corpus: remote_multiple_outs_same_pred_flow.jdf — ONE
    predecessor flow with SEVERAL differently-typed outputs shipped
    remotely: each remote consumer declares its own edge dtt, so the
    same produced payload travels twice in two different wire types."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, TASK
    bf = np.dtype(ml_dtypes.bfloat16)
    half = Dtt(transform=lambda a: a * 0.5, inverse=lambda a: a * 2.0,
               name="half")
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 6.0
    seen = {}
    p = PTG("rmo")
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("CB", "X", lambda: dict()), dtt=Dtt(dtype=bf)),
              OUT(TASK("CH", "X", lambda: dict()), dtt=half)) \
        .body(lambda: None)
    # consumers take each edge's wire type as shipped (the corpus case
    # declares the types on the PRODUCER's outputs; an IN re-declaring
    # the transform would mean "convert again")
    p.task("CB") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()))) \
        .body(lambda X: seen.update(b_dtype=str(np.asarray(X).dtype),
                                    b_val=float(np.asarray(X)[0])))
    p.task("CH") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()))) \
        .body(lambda X: seen.update(h_dtype=str(np.asarray(X).dtype),
                                    h_val=float(np.asarray(X)[0])))
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    return seen


def test_remote_multiple_outs_same_pred_flow():
    res = run_distributed(_remote_multi_outs_worker, 2)
    assert res[1] == {"b_dtype": "bfloat16", "b_val": 6.0,
                      "h_dtype": "float32", "h_val": 3.0}


def _remote_multi_outs_multi_deps_worker(ctx, rank, nranks):
    """Reference corpus: remote_multiple_outs_same_pred_flow_multiple_
    deps.jdf — the SAME predecessor flow additionally fans a RANGE dep
    over several instances of one remote consumer class (its own dtt)
    next to the differently-typed single deps, all shipped remotely."""
    import ml_dtypes
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.data.reshape import Dtt
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    bf = np.dtype(ml_dtypes.bfloat16)
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 8.0
    seen = {}
    p = PTG("rmomd", N=2)
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("CB", "X", lambda: dict()), dtt=Dtt(dtype=bf)),
              OUT(TASK("CR", "X",
                       lambda: [dict(i=i) for i in range(2)]),
                  dtt=Dtt(transform=lambda a: a + 1.0,
                          inverse=lambda a: a - 1.0, name="p1"))) \
        .body(lambda: None)
    p.task("CB") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda: dict()))) \
        .body(lambda X: seen.update(b_dtype=str(np.asarray(X).dtype),
                                    b_val=float(np.asarray(X)[0])))

    def cr_body(X, i):
        seen[f"r{i}"] = float(np.asarray(X)[0])
    p.task("CR", i=Range(0, 1)) \
        .affinity(lambda i, V=V: V(1)) \
        .flow("X", "READ",
              IN(TASK("P", "X", lambda i: dict()))) \
        .body(cr_body)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    return seen


def test_remote_multiple_outs_same_pred_flow_multiple_deps():
    res = run_distributed(_remote_multi_outs_multi_deps_worker, 2)
    assert res[1] == {"b_dtype": "bfloat16", "b_val": 8.0,
                      "r0": 9.0, "r1": 9.0}
