"""Golden ports of the reference PTG compiler edge-case suite.

Reference: /root/reference/tests/dsl/ptg/ptgpp/ (one minimal JDF per
jdf2c generator path) plus the neighbouring dsl/ptg suites.  Mapping:

  reference case                    counterpart here
  -------------------------------   ------------------------------------
  output_NULL{,_true,_false}.jdf    test_output_null_rejected* (build
                                    error, same diagnostic text)
  output_NEW{,_true,_false}.jdf     test_output_new_rejected*
  forward_READ_NULL.jdf             test_forward_read_null (runtime
                                    "A NULL is forwarded" + completion)
  forward_RW_NULL.jdf               test_forward_rw_null
  write_check.jdf                   test_write_check (same 3-class
                                    dataflow, numerically validated)
  too_many_in_deps.jdf              test_many_in_deps_supported — the
  too_many_out_deps.jdf             reference asserts its C codegen
  too_many_read_flows.jdf           FAILS above fixed limits (dep
  too_many_write_flows.jdf          bitmask width, flow arrays); this
  too_many_local_vars.jdf           runtime has no such limits, so the
                                    counterparts assert the same shapes
                                    WORK instead (documented inversion)
  user-defined-functions/udf.jdf    test_user_defined_make_key
                                    (make_key_fn property; startup_fn /
                                    hash_struct N/A: enumeration and
                                    hashing are runtime-owned here)
  controlgather/ctlgat.jdf          tests/test_ptg_examples.py CTL
                                    gather cases (pre-existing)
  branching/choice/local-indices    test_branching_diamond,
                                    test_choice_guarded_paths,
                                    test_local_indices_derived_ranges
  startup.jdf / strange.jdf         covered by startup enumeration in
                                    ParameterizedTaskpool tests
  cuda/                             device-path tests in
                                    tests/test_apps_gemm.py (TPU analog)
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.api import (DATA, IN, NEW, NULL_END, OUT, PTG, Range,
                                    TASK)


def run(p, nb_cores=2, timeout=60):
    with Context(nb_cores=nb_cores) as ctx:
        ctx.add_taskpool(p.build() if isinstance(p, PTG) else p)
        ctx.wait(timeout=timeout)


# -- output_NULL / output_NEW: rejected at build time -----------------------

@pytest.mark.parametrize("guard", [None, lambda k: k < 5, lambda k: k >= 5],
                         ids=["plain", "true-case", "false-case"])
def test_output_null_rejected(guard):
    p = PTG("t", NB=10)
    with pytest.raises(ValueError, match="NULL data only supported in IN"):
        p.task("T", k=Range(0, 9)).flow(
            "A", "RW",
            IN(NULL_END()),
            OUT(NULL_END(), when=guard))


@pytest.mark.parametrize("guard", [None, lambda k: k < 5, lambda k: k >= 5],
                         ids=["plain", "true-case", "false-case"])
def test_output_new_rejected(guard):
    p = PTG("t", NB=10)
    with pytest.raises(ValueError,
                       match="NEW only supported in IN dependencies"):
        p.task("T", k=Range(0, 9)).flow(
            "A", "RW",
            IN(NEW()),
            OUT(NEW(), when=guard))


# -- forward_{READ,RW}_NULL: NULL flows forward with a runtime warning ------

def _null_chain(mode):
    NB = 6
    V = VectorTwoDimCyclic(mb=2, lm=2 * (NB + 1))
    seen = []

    p = PTG("nullfwd", NB=NB)
    p.task("T", k=Range(0, NB)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("A", mode,
              IN(NULL_END(), when=lambda k: k == 0),
              IN(TASK("T", "A", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("T", "A", lambda k: dict(k=k + 1)),
                  when=lambda k, NB=NB: k < NB)) \
        .body(lambda A, k: seen.append((k, A is None)))
    run(p)
    return seen


@pytest.mark.parametrize("mode", ["READ", "RW"])
def test_forward_null(mode, capfd):
    """The NULL input is forwarded task-to-task down the whole chain;
    every body receives None and the runtime flags the forward
    (reference: PASS_REGULAR_EXPRESSION "A NULL is forwarded")."""
    seen = _null_chain(mode)
    assert sorted(seen) == [(k, True) for k in range(7)]
    assert "A NULL is forwarded" in capfd.readouterr().err


# -- write_check.jdf: WRITE/RW/READ flow plumbing, numerically validated ----

def test_write_check():
    """Port of write_check.jdf: STARTUP writes a NEW tile with index
    values; TASK1 increments the collection tile and copies the index
    tile through a second NEW flow; TASK2 sums them back into the
    collection.  Final A(p, k)[i] == 2 + index."""
    P, NT, BLOCK = 2, 3, 8
    A = TwoDimBlockCyclic(mb=1, nb=BLOCK, lm=P + 1, ln=(NT + 1) * BLOCK,
                          name="A")
    for m, n in A.local_tiles():
        A.data_of(m, n).copy_on(0).payload[:] = 1.0

    p = PTG("write_check", P=P, NT=NT)
    p.arena("blk", (1, BLOCK))
    idx = np.arange(BLOCK, dtype=np.float32).reshape(1, BLOCK)
    p.task("STARTUP", p=Range(0, P), k=Range(0, NT)) \
        .affinity(lambda p, k, A=A: A(p, k)) \
        .flow("A1", "WRITE",
              IN(NEW("blk")),
              OUT(TASK("TASK1", "A2", lambda p, k: dict(p=p, k=k)))) \
        .body(lambda A1, k, idx=idx: k * BLOCK + idx)
    p.task("TASK1", p=Range(0, P), k=Range(0, NT)) \
        .affinity(lambda p, k, A=A: A(p, k)) \
        .flow("A3", "WRITE",
              IN(NEW("blk")),
              OUT(TASK("TASK2", "A1", lambda p, k: dict(p=p, k=k)))) \
        .flow("A1", "RW",
              IN(DATA(lambda p, k, A=A: A(p, k))),
              OUT(TASK("TASK2", "A2", lambda p, k: dict(p=p, k=k)))) \
        .flow("A2", "READ",
              IN(TASK("STARTUP", "A1", lambda p, k: dict(p=p, k=k)))) \
        .body(lambda A1, A2, A3: {"A1": A1 + 1.0, "A3": A2.copy()})
    p.task("TASK2", p=Range(0, P), k=Range(0, NT)) \
        .affinity(lambda p, k, A=A: A(p, k)) \
        .flow("A1", "READ",
              IN(TASK("TASK1", "A3", lambda p, k: dict(p=p, k=k)))) \
        .flow("A2", "RW",
              IN(TASK("TASK1", "A1", lambda p, k: dict(p=p, k=k))),
              OUT(DATA(lambda p, k, A=A: A(p, k)))) \
        .body(lambda A1, A2: A2 + A1)
    run(p)

    for m in range(P + 1):
        for n in range(NT + 1):
            got = np.asarray(A.data_of(m, n).pull_to_host().payload)
            np.testing.assert_allclose(
                got, 2.0 + n * BLOCK + idx,
                err_msg=f"A({m},{n})")


# -- too_many_*: the reference's codegen limits do not exist here -----------

def test_many_in_deps_supported():
    """too_many_in_deps.jdf must FAIL in the reference (dep bitmask
    width); counter-based tracking has no such limit — 30 CTL gather
    deps on one flow must work."""
    NB = 30
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1)
    done = []
    p = PTG("many_in", NB=NB)
    p.task("SRC", k=Range(0, NB - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "CTL",
              OUT(TASK("SINK", "X", lambda k: dict()))) \
        .body(lambda: None)
    p.task("SINK") \
        .affinity(lambda V=V: V(NB)) \
        .flow("X", "CTL",
              *[IN(TASK("SRC", "X", lambda i=i: dict(k=i)))
                for i in range(NB)]) \
        .body(lambda: done.append(1))
    run(p)
    assert done == [1]


def test_many_out_deps_supported():
    """too_many_out_deps.jdf inverse: 30 guarded OUT deps on one flow."""
    NB = 30
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1)
    got = []
    p = PTG("many_out", NB=NB)
    p.task("SRC") \
        .affinity(lambda V=V: V(NB)) \
        .flow("X", "CTL",
              *[OUT(TASK("SINK", "X", lambda i=i: dict(k=i)))
                for i in range(NB)]) \
        .body(lambda: None)
    p.task("SINK", k=Range(0, NB - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "CTL", IN(TASK("SRC", "X", lambda k: dict()))) \
        .body(lambda k: got.append(k))
    run(p)
    assert sorted(got) == list(range(NB))


def test_many_flows_supported():
    """too_many_{read,write}_flows.jdf inverse: 12 read + 12 write flows
    on one task class (the reference caps flows at MAX_PARAM_COUNT)."""
    N = 12
    V = VectorTwoDimCyclic(mb=2, lm=2 * N)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m)
    p = PTG("many_flows", N=N)
    tb = p.task("T").affinity(lambda V=V: V(0))
    for i in range(N):
        tb.flow(f"r{i}", "READ", IN(DATA(lambda i=i, V=V: V(i))))
    for i in range(N):
        tb.flow(f"w{i}", "RW", IN(DATA(lambda i=i, V=V: V(i))),
                OUT(DATA(lambda i=i, V=V: V(i))))

    def body(**kw):
        return tuple(kw[f"w{i}"] + kw[f"r{i}"] for i in range(N))
    import inspect  # kwargs-only body: give it explicit named params
    args = [f"r{i}" for i in range(N)] + [f"w{i}" for i in range(N)]
    exec_ns = {}
    exec("def body({0}):\n    return ({1})".format(
        ", ".join(args),
        ", ".join(f"w{i} + r{i}" for i in range(N))), exec_ns)
    tb.body(exec_ns["body"])
    run(p)
    for m in range(N):
        np.testing.assert_allclose(
            np.asarray(V.data_of(m).pull_to_host().payload), 2.0 * m)


def test_many_local_vars_supported():
    """too_many_local_vars.jdf inverse: a task class with 12 parameters
    (the reference caps MAX_LOCAL_COUNT)."""
    V = VectorTwoDimCyclic(mb=1, lm=1)
    hits = []
    p = PTG("many_locals")
    params = {f"p{i}": Range(0, 1) for i in range(12)}
    p.task("T", **params) \
        .affinity(lambda V=V, **kw: V(0)) \
        .body(lambda task: hits.append(
            tuple(task.locals[f"p{i}"] for i in range(12))))
    run(p)
    assert len(hits) == 2 ** 12
    assert len(set(hits)) == 2 ** 12


# -- user-defined make_key (udf.jdf [make_key_fn = ...]) --------------------

def test_user_defined_make_key():
    """Custom task keys drive dep tracking and the repo exactly like the
    default parameter-tuple keys (reference: udf.jdf UD_MAKE_KEY)."""
    NT = 5
    V = VectorTwoDimCyclic(mb=2, lm=2 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("udf", NT=NT)
    # keys deliberately scrambled: (7 * k + 13) — any hashable works
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .make_key(lambda k: 7 * k + 13) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .body(lambda T: T + 1.0)
    run(p)
    np.testing.assert_allclose(
        np.asarray(V.data_of(NT - 1).pull_to_host().payload), float(NT))


# -- branching / choice / local-indices -------------------------------------

def test_branching_diamond():
    """branching.jdf pattern: one producer fans out along guarded edges
    to two distinct consumer classes, which join in a sink."""
    V = VectorTwoDimCyclic(mb=2, lm=8)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 1.0
    p = PTG("branching", NB=4)
    p.task("SRC", k=Range(0, 3)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k))),
              OUT(TASK("EVEN", "T", lambda k: dict(k=k)),
                  when=lambda k: k % 2 == 0),
              OUT(TASK("ODD", "T", lambda k: dict(k=k)),
                  when=lambda k: k % 2 == 1)) \
        .body(lambda T, k: T * (k + 1.0))
    for cls, par, mul in (("EVEN", 0, 10.0), ("ODD", 1, 100.0)):
        p.task(cls, k=Range(par, 3, 2)) \
            .affinity(lambda k, V=V: V(k)) \
            .flow("T", "RW",
                  IN(TASK("SRC", "T", lambda k: dict(k=k))),
                  OUT(DATA(lambda k, V=V: V(k)))) \
            .body(lambda T, mul=mul: T * mul)
    run(p)
    for k in range(4):
        expect = (k + 1.0) * (10.0 if k % 2 == 0 else 100.0)
        np.testing.assert_allclose(
            np.asarray(V.data_of(k).pull_to_host().payload), expect)


def test_choice_guarded_paths():
    """choice.jdf pattern: a run-time global selects which guarded dep
    path carries the data; the not-taken path must produce no edge."""
    for choice in (0, 1):
        V = VectorTwoDimCyclic(mb=2, lm=4)
        for m, _ in V.local_tiles():
            V.data_of(m).copy_on(0).payload[:] = 3.0
        p = PTG("choice", C=choice)
        p.task("A") \
            .affinity(lambda V=V: V(0)) \
            .flow("T", "RW",
                  IN(DATA(lambda V=V: V(0))),
                  OUT(TASK("L", "T", lambda: dict()),
                      when=lambda C=choice: C == 0),
                  OUT(TASK("R", "T", lambda: dict()),
                      when=lambda C=choice: C == 1)) \
            .body(lambda T: T + 1.0)
        for cls, target in (("L", 0), ("R", 1)):
            p.task(cls) \
                .affinity(lambda V=V: V(1)) \
                .flow("T", "RW",
                      IN(TASK("A", "T", lambda: dict()),
                         when=lambda C=choice, c=target: C == c),
                      IN(NULL_END(), when=lambda C=choice, c=target: C != c),
                      OUT(DATA(lambda V=V: V(1)),
                          when=lambda C=choice, c=target: C == c)) \
                .body(lambda T: None if T is None else T * 2.0)
        run(p)
        np.testing.assert_allclose(
            np.asarray(V.data_of(1).pull_to_host().payload), 8.0)


def test_local_indices_derived_ranges():
    """local_indices.jdf pattern: later parameters range over earlier
    ones (triangular spaces) and dep expressions use derived locals."""
    NT = 4
    V = VectorTwoDimCyclic(mb=1, lm=NT * (NT + 1) // 2 + 1)
    hits = []
    p = PTG("locidx", NT=NT)
    p.task("T", k=Range(0, NT - 1), j=Range(0, lambda k: k)) \
        .affinity(lambda k, j, V=V: V(k * (k + 1) // 2 + j)) \
        .body(lambda k, j: hits.append((k, j)))
    run(p)
    assert sorted(hits) == [(k, j) for k in range(NT) for j in range(k + 1)]
