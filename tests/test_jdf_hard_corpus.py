"""JDF hard-corpus golden tests (VERDICT r4 missing #1): parse and run
the REFERENCE's hardest .jdf files through the textual front-end —
kcyclic.jdf (k-cyclic views + CTL reduce/broadcast chains, 4 ranks),
BT_reduction.jdf (interleaved derived locals feeding later range bounds,
inline-C helper calls, ternary flows), and project_dyn.jdf (%option
dynamic: runtime-pruned task space + dynamic termination detection,
reference: ptgpp --dynamic-termdet).
"""

import os
import threading

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.comm.launch import run_distributed
from parsec_tpu.data.collection import DataCollection
from parsec_tpu.data.data import new_data
from parsec_tpu.data.matrix import TwoDimBlockCyclic, block_cyclic_kview
from parsec_tpu.dsl.ptg.jdf import jdf_taskpool, parse_jdf

REF = "/root/reference"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF),
                               reason="reference tree not present")


# -- parser units -----------------------------------------------------------

def test_option_and_multiline_task_props_parse():
    jdf = parse_jdf("""
%option dynamic = ON
%option no_taskpool_instance = true

T (k) [ make_key_fn = mk
        startup_fn = su ]
  k = 0 .. 3
  d = k + 1
: A(k)
CTL C -> (d > 1) ? C T(k+1)
BODY
END
""")
    assert jdf.options["dynamic"] == "ON"
    assert jdf.options["no_taskpool_instance"] == "true"
    t = jdf.tasks[0]
    assert t.props == {"make_key_fn": "mk", "startup_fn": "su"}
    # declaration order preserved: range k, then derived local d
    assert [d[:2] for d in t.defs] == [("range", "k"), ("local", "d")]


def test_kview_permutation_matches_reference_formula():
    """kview_compute_m/n (two_dim_rectangle_cyclic.c:441-463) on a 2x2
    grid with kp=kq=2."""
    A = TwoDimBlockCyclic(mb=2, nb=2, lm=16, ln=16, nodes=4, myrank=0,
                          P=2, name="dA")
    V = block_cyclic_kview(A, 2, 2)

    def ref_perm(x, p, ps, xt):
        while True:
            x = x - x % (p * ps) + (x % ps) * p + (x // ps) % p
            if x < xt:
                return x

    for m in range(A.mt):
        assert V._pm(m) == ref_perm(m, 2, 2, A.mt)
        # a permutation: bijective over the tile range
    assert sorted(V._pm(m) for m in range(A.mt)) == list(range(A.mt))
    assert sorted(V._pn(n) for n in range(A.nt)) == list(range(A.nt))


# -- kcyclic.jdf: 4-rank golden run -----------------------------------------

def _kcyclic_worker(ctx, rank, nranks):
    n, mb = 12, 3
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                          myrank=rank, P=2, dtype=np.int32, name="dA")
    CA = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                           myrank=rank, P=2, kp=2, kq=2, dtype=np.int32,
                           name="dCA")
    VA = block_cyclic_kview(A, 2, 2, name="dVA")
    errors = []

    def fill_a(A, M, N):
        A.reshape(-1)[0:3] = (M, N, _a.rank_of(M, N))

    def fill_ca(CA, M, N):
        CA.reshape(-1)[0:3] = (M, N, _ca.rank_of(M, N))

    def compare(A, CA, VA, M, N):
        a, ca, va = (A.reshape(-1), CA.reshape(-1), VA.reshape(-1))
        if a[0] != ca[0] or a[1] != ca[1]:
            errors.append(("kcyclic", M, N))       # A and CA differ
        if va[2] != _a.rank_of(int(va[0]), int(va[1])):
            errors.append(("view", M, N))          # VA not a permutation

    _a, _ca = A, CA
    tp = jdf_taskpool(f"{REF}/tests/collections/kcyclic.jdf",
                      data={"dA": A, "dVA": VA, "dCA": CA},
                      bodies={"FILL_A": fill_a, "FILL_CA": fill_ca,
                              "READ_VA": lambda VA: None,
                              "COMPARE": compare})
    # hidden globals evaluated from the collection shim (dA->super.mt-1)
    assert tp.globals["MT"] == A.mt - 1 and tp.globals["NT"] == A.nt - 1
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    return errors


@needs_ref
def test_kcyclic_jdf_golden_4ranks():
    res = run_distributed(_kcyclic_worker, 4, timeout=300)
    assert res == [[], [], [], []]


# -- BT_reduction.jdf: generalized binomial-tree reduction ------------------

def _count_bits(N):
    return bin(N).count("1")


def _log_of_tree_size(N, t):
    cnt = 0
    for i in range(32):
        if (1 << i) & N:
            cnt += 1
        if cnt == t:
            return i
    raise AssertionError(N)


def _index_to_tree(N, idx):
    cnt = 0
    for i in range(32):
        if (1 << i) & N:
            cnt += 1
            if idx < (1 << i):
                return cnt
            idx -= 1 << i
    raise AssertionError(N)


def _global_to_local_index(N, idx):
    for i in range(32):
        if (1 << i) & N:
            if idx < (1 << i):
                return idx
            idx -= 1 << i
    raise AssertionError(N)


def _compute_offset(N, t):
    cnt, offset = 0, 0
    for i in range(32):
        if (1 << i) & N:
            cnt += 1
        if cnt == t:
            return offset
        if (1 << i) & N:
            offset += 1 << i
    raise AssertionError(N)


@needs_ref
def test_bt_reduction_jdf_golden():
    """tests/apps/generalized_reduction/BT_reduction.jdf: NT values are
    decomposed into power-of-two binomial trees reduced in parallel, then
    a linear pass chains the tree roots.  Exercises derived locals
    BETWEEN ranges feeding later bounds (s = 1..sz with sz derived from
    t) and inline-C calls to prologue helper functions."""
    NT, NB = 5, 4
    dataA = TwoDimBlockCyclic(mb=1, nb=NB, lm=NT, ln=NB, dtype=np.int32,
                              name="dataA")
    result = []

    def reduction(A, i):
        A[:] = i

    def bt_reduc(A, B):
        B += A

    def linear_reduc(B, C, i, tree_count):
        if tree_count != i and B is not None:
            C += B
        if i == 1:
            result.append(np.array(C).copy())

    tp = jdf_taskpool(
        f"{REF}/tests/apps/generalized_reduction/BT_reduction.jdf",
        globals={"NT": NT, "NB": NB, "count_bits": _count_bits,
                 "log_of_tree_size": _log_of_tree_size,
                 "index_to_tree": _index_to_tree,
                 "global_to_local_index": _global_to_local_index,
                 "compute_offset": _compute_offset},
        data={"dataA": dataA},
        bodies={"REDUCTION": reduction, "BT_REDUC": bt_reduc,
                "LINEAR_REDUC": linear_reduc,
                "LINE_TERMINATOR": lambda: None})
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert len(result) == 1
    assert (result[0].reshape(-1) == sum(range(NT))).all()


# -- project_dyn.jdf: dynamic task discovery --------------------------------

class TreeDist(DataCollection):
    """Minimal tree collection (the reference's test-local fixture
    tests/apps/haar_tree/tree_dist.c): (n, l) keys, data created on
    demand."""

    def __init__(self, nodes=1, myrank=0, name="treeA"):
        super().__init__(nodes=nodes, myrank=myrank, name=name)
        self._lock = threading.Lock()
        self.tiles = {}

    def data_key(self, n, l=0):
        return (n, l)

    def key_to_indices(self, key):
        return tuple(key)

    def rank_of(self, n, l=0):
        return 0 if self.nodes == 1 else (n * 31 + l) % self.nodes

    def data_of(self, n, l=0):
        with self._lock:
            d = self.tiles.get((n, l))
            if d is None:
                d = new_data(np.zeros(2), key=(self.name, n, l),
                             collection=self)
                self.tiles[(n, l)] = d
            return d


@needs_ref
def test_project_dyn_jdf_dynamic_termdet():
    """tests/apps/haar_tree/project_dyn.jdf: %option dynamic = ON — the
    declared space (n = 0..31, l = 0..2^n) is astronomically larger than
    what runs; a startup_fn seeds PROJECT(0, 0), each task decides AT
    RUNTIME whether to spawn its two children by overwriting the
    larger_than_thresh local (this_task->locals in the reference), and
    the pool terminates by dynamic task counting, not enumeration."""
    import math
    tree = TreeDist()
    ALPHA, THRESH, NMIN = 1.0, 0.02, 4
    executed, pruned = [], []

    def key_to_x(n, l):
        L = 10.0
        return -L + (2.0 * L * 2.0 ** -n) * (0.5 + l)

    def f(x):
        return math.exp(-(x / ALPHA) * (x / ALPHA))

    def project(task, n, l, NODE):
        executed.append((n, l))
        sl = f(key_to_x(n + 1, 2 * l))
        sr = f(key_to_x(n + 1, 2 * l + 1))
        d = 0.5 * (sl - sr)
        err = abs(d) * 2.0 ** (-0.5 * n)
        if n >= NMIN and err <= THRESH:
            # prune: kill the output guard (reference body:
            # this_task->locals.larger_than_thresh.value = 0)
            task.locals["larger_than_thresh"] = 0
            pruned.append((n, l))
        else:
            NODE[:] = (0.5 * (sl + sr), d)

    tp = jdf_taskpool(
        f"{REF}/tests/apps/haar_tree/project_dyn.jdf",
        globals={"NP": 1, "fakeDesc": tree, "thresh": THRESH,
                 "verbose": 0, "alpha": ALPHA},
        data={"treeA": tree},
        bodies={"PROJECT": project},
        arenas={"default": ((2,), np.float64)},
        funcs={"project_dyn_make_key":
               lambda n, l: (n << 32) | l,
               "my_project_dyn_startup":
               lambda globals_, rank: [dict(n=0, l=0)] if rank == 0
               else []})
    from parsec_tpu.core.taskpool import DynamicTaskpool
    assert isinstance(tp, DynamicTaskpool)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # full expansion through the n < NMIN levels...
    assert len(executed) >= 2 ** (NMIN + 1) - 1
    # ...then runtime pruning cut the 2^32-task declared space down
    assert pruned and len(executed) < 4096
    depths = {n for n, _ in executed}
    assert max(depths) > NMIN          # some branches went deeper
    # every non-root task was discovered through its parent edge
    ex = set(executed)
    for (n, l) in ex:
        if n:
            assert (n - 1, l // 2) in ex
    # leaves (pruned) spawned no children
    for (n, l) in pruned:
        assert (n + 1, 2 * l) not in ex and (n + 1, 2 * l + 1) not in ex
    # expanded nodes wrote their NODE payload home through -> treeA(n, l)
    root = tree.tiles[(0, 0)].pull_to_host().payload
    assert root[0] != 0.0


def _project_dyn_worker(ctx, rank, nranks):
    """Dynamic pool seeded ONLY on rank 0; every task on rank 1 arrives
    purely by remote discovery — the case that needs the distributed
    dynamic termdet (the pool-scoped quiescence hold): with plain local
    counting, rank 1 would terminate at startup with zero tasks."""
    import math
    tree = TreeDist(nodes=nranks, myrank=rank)
    ALPHA, THRESH, NMIN = 1.0, 0.02, 4
    executed = []

    def key_to_x(n, l):
        return -10.0 + (20.0 * 2.0 ** -n) * (0.5 + l)

    def f(x):
        return math.exp(-(x / ALPHA) * (x / ALPHA))

    def project(task, n, l, NODE):
        executed.append((n, l))
        sl = f(key_to_x(n + 1, 2 * l))
        sr = f(key_to_x(n + 1, 2 * l + 1))
        d = 0.5 * (sl - sr)
        if n >= NMIN and abs(d) * 2.0 ** (-0.5 * n) <= THRESH:
            task.locals["larger_than_thresh"] = 0
        else:
            NODE[:] = (0.5 * (sl + sr), d)

    tp = jdf_taskpool(
        f"{REF}/tests/apps/haar_tree/project_dyn.jdf",
        globals={"NP": nranks, "fakeDesc": tree, "thresh": THRESH,
                 "verbose": 0, "alpha": ALPHA},
        data={"treeA": tree}, bodies={"PROJECT": project},
        arenas={"default": ((2,), np.float64)},
        funcs={"project_dyn_make_key": lambda n, l: (n << 32) | l,
               "my_project_dyn_startup":
               lambda globals_, r: [dict(n=0, l=0)] if r == 0 else []})
    ctx.add_taskpool(tp)
    ctx.wait(timeout=120)
    return len(executed)


@needs_ref
def test_project_dyn_distributed_dynamic_termdet():
    counts = run_distributed(_project_dyn_worker, 2, timeout=300)
    assert sum(counts) >= 2 ** 5 - 1      # full expansion to NMIN depth
    assert all(c > 0 for c in counts)     # rank 1 ran discovered tasks
