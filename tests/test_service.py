"""Resident job service tests: concurrent multi-tenant submission on one
warm Context — admission control, weighted fairness, cancellation,
deadlines, failure isolation, per-job observability, and the socket
front end (service/{service,job,server}.py; ISSUE 1)."""

import time

import numpy as np
import pytest

from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.service import (AdmissionError, JobCancelled, JobError,
                                JobService, JobStatus, JobTimeout)


def _chain_factory(nt, delay=0.0, fail_at=None, name="chain"):
    """A job factory: its own 1-tile collection and an nt-deep increment
    chain over it; result() reads the final tile value (== nt when every
    task ran)."""
    def factory():
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0

        def body(T, k):
            if delay:
                time.sleep(delay)
            if fail_at is not None and k == fail_at:
                raise ValueError(f"{name}: injected failure at k={k}")
            return T + 1.0

        p = PTG(name, NT=nt)
        p.task("S", k=Range(0, nt - 1)) \
            .affinity(lambda k, A=A: A(0, 0)) \
            .flow("T", "RW",
                  IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                      when=lambda k, NT=nt: k < NT - 1),
                  OUT(DATA(lambda A=A: A(0, 0)),
                      when=lambda k, NT=nt: k == NT - 1)) \
            .body(body)

        def result():
            return float(np.asarray(
                A.data_of(0, 0).copy_on(0).payload)[0, 0])
        return p.build(), result
    return factory


def _wait_progress(svc, job, min_tasks=1, timeout=10.0):
    """Poll per-job gauges until the job has retired some tasks."""
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        done = svc.gauges.job_task_counts(job.job_id)["tasks_retired"]
        if job.status() == JobStatus.RUNNING and done >= min_tasks:
            return
        time.sleep(0.005)
    raise AssertionError(f"{job} made no progress")


def test_concurrent_heterogeneous_jobs_complete():
    """N heterogeneous jobs share one warm context; per-job results are
    independent and correct."""
    with JobService(nb_cores=2, max_active=8) as svc:
        lengths = [5, 11, 3, 8, 14]
        jobs = [svc.submit(_chain_factory(nt, name=f"j{i}"),
                           client=f"tenant{i}")
                for i, nt in enumerate(lengths)]
        for job, nt in zip(jobs, lengths):
            assert job.result(timeout=60.0) == float(nt)
            assert job.status() == JobStatus.DONE
        assert svc.stats()["total"] == len(lengths)
        # one context served everything: same context object, all pools
        # registered on it
        assert all(j.taskpool.context is svc.context for j in jobs)


def test_priority_inversion_high_job_overtakes():
    """A high-priority job submitted late finishes before a long
    low-priority job drains — job priority rides Taskpool.priority into
    task priorities, and the pbq scheduler interleaves accordingly."""
    with JobService(nb_cores=1, scheduler="pbq", max_active=4,
                    aging_weight=0.0) as svc:
        low = svc.submit(_chain_factory(60, delay=0.01, name="low"),
                         priority=0)
        _wait_progress(svc, low, min_tasks=2)
        high = svc.submit(_chain_factory(5, delay=0.01, name="high"),
                          priority=10)
        assert high.result(timeout=60.0) == 5.0
        # the long job is still going when the high one finished
        assert low.status() == JobStatus.RUNNING
        assert low.result(timeout=60.0) == 60.0
        assert high.finished_at < low.finished_at


def test_admission_cap_rejection_and_backpressure():
    with JobService(nb_cores=2, max_active=1, max_pending=1) as svc:
        first = svc.submit(_chain_factory(25, delay=0.01, name="busy"))
        _wait_progress(svc, first)
        queued = svc.submit(_chain_factory(3, name="queued"))
        # pending queue is full now: immediate rejection...
        with pytest.raises(AdmissionError):
            svc.submit(_chain_factory(3, name="reject"), block=False)
        # ...zero-budget backpressure also rejects...
        with pytest.raises(AdmissionError):
            svc.submit(_chain_factory(3, name="reject2"), block=True,
                       timeout=0.05)
        # ...but a patient backpressure wait admits once room frees
        third = svc.submit(_chain_factory(4, name="waited"), block=True,
                           timeout=30.0)
        assert first.result(timeout=60.0) == 25.0
        assert queued.result(timeout=60.0) == 3.0
        assert third.result(timeout=60.0) == 4.0


def test_cancellation_midflight_keeps_context_serving():
    with JobService(nb_cores=2, max_active=4) as svc:
        victim = svc.submit(_chain_factory(500, delay=0.005,
                                           name="victim"))
        _wait_progress(svc, victim, min_tasks=3)
        assert victim.cancel()
        assert victim.status() == JobStatus.CANCELLED
        with pytest.raises(JobCancelled):
            victim.result(timeout=10.0)
        # the cancelled pool quiesces (undelivered tasks dropped)
        assert victim.taskpool.wait_local(timeout=10.0)
        # cancelling twice is a no-op
        assert not victim.cancel()
        # the warm context keeps serving
        after = svc.submit(_chain_factory(6, name="after"))
        assert after.result(timeout=60.0) == 6.0


def test_pending_job_cancel():
    with JobService(nb_cores=2, max_active=1, max_pending=4) as svc:
        busy = svc.submit(_chain_factory(30, delay=0.01, name="busy"))
        _wait_progress(svc, busy)
        queued = svc.submit(_chain_factory(3, name="queued"))
        assert queued.status() == JobStatus.PENDING
        assert queued.cancel()
        with pytest.raises(JobCancelled):
            queued.result(timeout=5.0)
        assert busy.result(timeout=60.0) == 30.0


def test_deadline_expiry_cancels_job_not_context():
    with JobService(nb_cores=2, max_active=4) as svc:
        slow = svc.submit(_chain_factory(1000, delay=0.005, name="slow"),
                          deadline=0.3)
        with pytest.raises(JobTimeout):
            slow.result(timeout=30.0)
        assert slow.status() == JobStatus.TIMEOUT
        assert slow.taskpool.wait_local(timeout=10.0)
        ok = svc.submit(_chain_factory(5, name="ok"))
        assert ok.result(timeout=60.0) == 5.0


def test_failure_isolation_four_concurrent_jobs():
    """Acceptance: >=4 concurrent jobs on one warm Context; one raises,
    the other three complete; the context serves subsequent jobs."""
    with JobService(nb_cores=2, max_active=8) as svc:
        bad = svc.submit(_chain_factory(10, fail_at=4, name="bad"))
        good = [svc.submit(_chain_factory(nt, name=f"good{nt}"))
                for nt in (7, 12, 9)]
        for job, nt in zip(good, (7, 12, 9)):
            assert job.result(timeout=60.0) == float(nt)
        with pytest.raises(JobError) as ei:
            bad.result(timeout=60.0)
        assert isinstance(ei.value.__cause__, ValueError)
        assert bad.status() == JobStatus.FAILED
        # the failing pool never poisoned the context error list
        assert not svc.context._errors
        late = svc.submit(_chain_factory(4, name="late"))
        assert late.result(timeout=60.0) == 4.0


def test_per_job_gauges_via_aggregator():
    """Per-job gauges ride the existing aggregator path: a
    GaugePublisher streams JobGauges.snapshot() to an Aggregator and the
    published table carries per-job task counts."""
    from parsec_tpu.prof.aggregator import Aggregator, GaugePublisher
    with JobService(nb_cores=2, max_active=4) as svc:
        j1 = svc.submit(_chain_factory(9, name="g1"))
        j2 = svc.submit(_chain_factory(4, name="g2"))
        assert j1.result(timeout=60.0) == 9.0
        assert j2.result(timeout=60.0) == 4.0
        agg = Aggregator(port=0)
        pub = GaugePublisher(svc.gauges, rank=0, host="127.0.0.1",
                             port=agg.port, interval=0.05)
        try:
            deadline = time.monotonic() + 10.0
            while time.monotonic() < deadline:
                table = agg.table()
                if 0 in table and f"job{j1.job_id}_tasks_retired" in \
                        table[0]:
                    break
                time.sleep(0.05)
            row = agg.table()[0]
            assert row["jobs_done"] >= 2
            assert row[f"job{j1.job_id}_tasks_retired"] == 9
            assert row[f"job{j2.job_id}_tasks_retired"] == 4
            assert row[f"job{j1.job_id}_wall_ms"] > 0
        finally:
            pub.close()
            agg.close()


def test_job_pins_events_tagged_with_job_ids():
    """Job lifecycle emits PINS events carrying the job, and every task
    is attributable to its job via Taskpool.job_id."""
    events = []
    with JobService(nb_cores=2, max_active=4) as svc:
        svc.context.pins_register(
            "job_submit", lambda es, ev, job: events.append((ev,
                                                             job.job_id)))
        svc.context.pins_register(
            "job_done", lambda es, ev, job: events.append((ev,
                                                           job.job_id)))
        seen_jids = set()
        svc.context.pins_register(
            "complete_exec",
            lambda es, ev, task: seen_jids.add(task.taskpool.job_id))
        job = svc.submit(_chain_factory(5, name="tagged"))
        assert job.result(timeout=60.0) == 5.0
        job.wait(10.0)
        time.sleep(0.05)
    assert ("job_submit", job.job_id) in events
    assert ("job_done", job.job_id) in events
    assert job.job_id in seen_jids


def test_server_and_client_roundtrip():
    """Socket front end: submit named app jobs over the framed-JSON wire
    and read results back (tools/job_client.py uses the same library)."""
    from parsec_tpu.service.server import request, serve
    service, server = serve(port=0, nb_cores=2, max_active=4)
    try:
        host, port = server.host, server.port
        apps = request(host, port, {"op": "apps"})
        assert apps["ok"] and set(apps["apps"]) >= {"gemm", "potrf",
                                                    "stencil"}
        sub = request(host, port, {
            "op": "submit", "app": "stencil",
            "params": {"n": 32, "nb": 8, "steps": 3, "device": "cpu"},
            "priority": 1, "client": "pytest"})
        assert sub["ok"], sub
        jid = sub["job"]
        res = request(host, port, {"op": "result", "job": jid,
                                   "timeout": 60.0})
        assert res["ok"], res
        assert res["result"]["app"] == "stencil"
        assert res["result"]["norm"] > 0
        st = request(host, port, {"op": "status", "job": jid})
        assert st["ok"] and st["info"]["status"] == "DONE"
        pot = request(host, port, {
            "op": "submit", "app": "potrf",
            "params": {"n": 64, "nb": 16, "device": "cpu"}})
        res = request(host, port, {"op": "result", "job": pot["job"],
                                   "timeout": 60.0})
        assert res["ok"], res
        assert res["result"]["residual"] < 1e-4
        stats = request(host, port, {"op": "stats"})
        assert stats["ok"] and stats["stats"]["total"] == 2
        gz = request(host, port, {"op": "gauges"})
        assert gz["ok"] and gz["gauges"]["jobs_done"] >= 2
        bad = request(host, port, {"op": "submit", "app": "nope"})
        assert not bad["ok"]
    finally:
        server.close()
        service.shutdown(timeout=30.0)


def test_server_rejects_bad_magic():
    """Garbage magic still severs the connection.  (A leading ``GET ``
    is no longer garbage: the server sniffs it and answers a plain
    HTTP /metrics scrape — covered in test_metrics.py.)"""
    import socket as socket_mod
    from parsec_tpu.service.server import serve
    service, server = serve(port=0, nb_cores=2)
    try:
        with socket_mod.create_connection((server.host, server.port),
                                          timeout=5.0) as s:
            s.sendall(b"BAD?" + b"\0" * 16)
            s.settimeout(2.0)
            # server drops the connection instead of crashing (EOF or
            # RST depending on unread bytes at close)
            try:
                assert s.recv(64) == b""
            except ConnectionResetError:
                pass
    finally:
        server.close()
        service.shutdown(timeout=10.0)


def test_gauges_pending_accounts_discards():
    """Cancellation discards are first-class in the base gauges: pending
    drains to zero even when tasks were dropped, via tasks_discarded."""
    from parsec_tpu.prof.gauges import install_gauges
    with JobService(nb_cores=2, max_active=4) as svc:
        g = install_gauges(svc.context)
        victim = svc.submit(_chain_factory(400, delay=0.005, name="v"))
        _wait_progress(svc, victim, min_tasks=2)
        victim.cancel()
        victim.taskpool.wait_local(timeout=10.0)
        ok = svc.submit(_chain_factory(5, name="ok"))
        assert ok.result(timeout=60.0) == 5.0
        time.sleep(0.1)
        snap = g.snapshot()
        assert snap["pending_tasks"] == 0
        g.uninstall(svc.context)
