"""Tiled-GEMM app tests (reference: tests/dsl/dtd/dtd_test_simple_gemm.c)."""

import numpy as np
import pytest

from parsec_tpu.apps.gemm import gemm_taskpool
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic


def _fill(M, rng, mb):
    for m, n in M.local_tiles():
        M.data_of(m, n).copy_on(0).payload[:] = \
            rng.standard_normal((mb, mb)).astype(np.float32)


@pytest.mark.parametrize("device", ["tpu", "cpu"])
@pytest.mark.parametrize("alpha,beta", [(1.0, 1.0), (2.0, 0.0), (0.5, -1.0)])
def test_gemm_matches_numpy(device, alpha, beta):
    mt, nt, kt, mb = 2, 3, 2, 16
    rng = np.random.default_rng(11)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    for M in (A, B, C):
        _fill(M, rng, mb)
    want = alpha * (A.to_array() @ B.to_array()) + beta * C.to_array()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(gemm_taskpool(A, B, C, alpha=alpha, beta=beta,
                                       device=device))
        ctx.wait()
    np.testing.assert_allclose(C.to_array(), want, rtol=1e-3, atol=1e-3)


def test_gemm_repeat_runs_share_jit():
    """Rebuilding the pool reuses the same kernel fn (jit cache key)."""
    from parsec_tpu.apps.gemm import _tile_kernel
    assert _tile_kernel(1.0) is _tile_kernel(1.0)
    mt = nt = kt = 2
    mb = 8
    rng = np.random.default_rng(5)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    for M in (A, B, C):
        _fill(M, rng, mb)
    c0 = C.to_array().copy()
    ab = A.to_array() @ B.to_array()
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(gemm_taskpool(A, B, C))
        ctx.wait()
        ctx.add_taskpool(gemm_taskpool(A, B, C))
        ctx.wait()
    np.testing.assert_allclose(C.to_array(), c0 + 2 * ab, rtol=1e-3,
                               atol=1e-3)


def test_gemm_shape_mismatch_raises():
    A = TwoDimBlockCyclic(mb=8, nb=8, lm=16, ln=16, name="A")
    B = TwoDimBlockCyclic(mb=8, nb=8, lm=24, ln=16, name="B")
    C = TwoDimBlockCyclic(mb=8, nb=8, lm=16, ln=16, name="C")
    with pytest.raises(ValueError):
        gemm_taskpool(A, B, C)
