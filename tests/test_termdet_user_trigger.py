"""user_trigger termdet tests (reference:
mca/termdet/termdet_user_trigger_module.c; the dynamic-termdet pattern of
tests/apps/haar_tree project_dyn.jdf — pools whose task count is
unknowable terminate on an explicit user call, propagated to all ranks).
"""

import threading
import time

import numpy as np
import pytest

from parsec_tpu.comm.launch import run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.dsl.dtd import DTDTaskpool, INOUT


def test_user_trigger_local():
    """Zero counters never fire; trigger() does."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    V = VectorTwoDimCyclic(mb=2, lm=2)
    V.data_of(0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp = DTDTaskpool("dyn")
        tp.termdet_name = "user_trigger"
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(V, 0)
        for _ in range(5):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        # drain the inserted work, then prove the pool is still alive
        deadline = time.monotonic() + 30
        while tp._inflight > 0:
            assert time.monotonic() < deadline
            time.sleep(0.01)
        assert not tp.completed, \
            "user_trigger pool must not self-terminate on zero counters"
        tp.termdet.trigger(tp)
        assert tp.wait_local(10)
        ctx.wait(timeout=30)
    np.testing.assert_allclose(
        np.asarray(V.data_of(0).pull_to_host().payload), 5.0)


def _dyn_rank(ctx, rank, nranks):
    """Rank 0 declares termination; every rank's pool completes."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    V = VectorTwoDimCyclic(mb=2, lm=2 * nranks, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("dyn")
    tp.termdet_name = "user_trigger"
    ctx.add_taskpool(tp)
    ctx.start()
    t = tp.tile_of(V, rank)    # purely local work on each rank
    for _ in range(3 + rank):
        tp.insert_task(lambda T: T + 1.0, (t, INOUT))
    # rank 0 waits for its own work then declares global termination
    deadline = time.monotonic() + 30
    while tp._inflight > 0:
        if time.monotonic() > deadline:
            raise TimeoutError("local drain")
        time.sleep(0.01)
    ctx.comm.ce.barrier()      # all ranks drained their local work
    if rank == 0:
        tp.termdet.trigger(tp)
    if not tp.wait_local(30):
        raise TimeoutError(f"rank {rank}: pool never terminated")
    ctx.wait(timeout=60)
    got = np.asarray(V.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, float(3 + rank))
    return "ok"


def test_user_trigger_distributed():
    assert run_distributed(_dyn_rank, 3) == ["ok"] * 3
