"""Predictive health plane tests (ISSUE 19): scrape-time score
fusion and the state machine (prof/health.py), pessimistic cross-rank
merge of ``__health__`` sections, the metrics/status export surfaces,
the serving fabric's sustained-below-threshold drain/undrain loop, the
H1 invariant of the offline journal auditor, and the flight-recorder
health snapshot (tools/journal_audit.py, prof/flightrec.py)."""

import json
import os
import re
import sys
import time

import pytest

from parsec_tpu.prof.health import HealthMonitor, merge_health
from parsec_tpu.prof.metrics import render_text
from parsec_tpu.utils.mca import params

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
from tools import journal_audit  # noqa: E402


# ---------------------------------------------------------------------------
# merge_health: the cross-rank pessimistic fold
# ---------------------------------------------------------------------------

def _section(rank, scores, folds=0, transitions=0):
    return {"v": 1, "rank": rank, "folds": folds,
            "transitions": transitions,
            "scores": {str(r): {"score": s, "ewma": s, "trend": 0.0,
                                "state": "ok", "since_s": 0.0, "n": 1}
                       for r, s in scores.items()}}


def test_merge_health_counts_sum_exactly():
    doc = merge_health({
        0: _section(0, {0: 1.0}, folds=7, transitions=2),
        1: _section(1, {1: 1.0}, folds=5, transitions=1),
    })
    assert doc["folds"] == 12
    assert doc["transitions"] == 3


def test_merge_health_pessimistic_lowest_view_wins():
    """A wedged rank's rosy self-report must not mask what its peers
    measure: the LOWEST smoothed score any rank observed wins, and the
    observing rank is recorded as ``src``."""
    doc = merge_health({
        0: _section(0, {0: 1.0, 1: 0.4}),    # rank 0 sees peer 1 sick
        1: _section(1, {1: 0.95, 0: 0.99}),  # rank 1 self-reports fine
    })
    assert doc["ranks"][1]["ewma"] == 0.4
    assert doc["ranks"][1]["src"] == 0
    assert doc["ranks"][0]["ewma"] == 0.99
    assert doc["ranks"][0]["src"] == 1


def test_merge_health_tolerates_absent_and_malformed_sections():
    """A mid-pull death or a disabled plane leaves a rank's section
    absent (or empty) — it contributes nothing and kills nothing."""
    doc = merge_health({
        0: _section(0, {0: 0.9}),
        1: None,
        2: {},
        3: {"v": 1, "rank": 3, "scores": {"bogus": {"ewma": "NaNish"}}},
    })
    assert set(doc["ranks"]) == {0}
    assert merge_health(None) == {"ranks": {}, "folds": 0,
                                  "transitions": 0}
    assert merge_health({}) == {"ranks": {}, "folds": 0,
                                "transitions": 0}


# ---------------------------------------------------------------------------
# HealthMonitor: scoring, state machine, transition journal
# ---------------------------------------------------------------------------

class _JournalStub:
    def __init__(self):
        self.events = []

    def emit(self, etype, **fields):
        self.events.append({"e": etype, **fields})


class _CtxStub:
    def __init__(self):
        self.rank = 0
        self.journal = _JournalStub()


class _MetricsStub:
    def __init__(self):
        self.context = _CtxStub()


def _mk_monitor():
    m = _MetricsStub()
    return HealthMonitor(m), m.context.journal


def test_monitor_state_machine_and_transition_journal():
    """Driving declining scores through the fold walks ok ->
    degraded -> critical, each hop journaled as a health_transition
    with the OBSERVED rank in ``peer`` (merge stamps ``rank`` with
    the observer)."""
    hm, jr = _mk_monitor()
    now = time.monotonic()
    with hm._lock:
        for s in (1.0, 0.9, 0.5, 0.3, 0.1, 0.05, 0.02, 0.01):
            hm._observe_locked(1, s, now)
    snap = hm.snapshot()[1]
    assert snap["state"] == "critical"
    assert snap["ewma"] < 0.5
    kinds = [(e["frm"], e["to"]) for e in jr.events
             if e["e"] == "health_transition"]
    assert ("ok", "degraded") in kinds
    assert ("degraded", "critical") in kinds
    assert all(e.get("peer") == 1 for e in jr.events)
    assert hm.transitions == len(kinds)
    # trend over the declining window is negative
    assert snap["trend"] < 0.0


def test_monitor_hysteresis_damps_flapping():
    """Climbing back out of a state needs the threshold PLUS the
    hysteresis margin — a score dithering on the line must not spam
    the transition journal."""
    params.set("health_alpha", 1.0)      # ewma == last score: exact
    try:
        hm, jr = _mk_monitor()
        now = time.monotonic()
        thr_deg = hm._thr_deg
        hyst = hm._hyst
        with hm._lock:
            hm._observe_locked(1, thr_deg - 0.01, now)   # -> degraded
            assert hm._ranks[1].state == "degraded"
            # above the threshold but inside the margin: stays put
            hm._observe_locked(1, thr_deg + hyst / 2, now)
            assert hm._ranks[1].state == "degraded"
            # past the margin: recovers
            hm._observe_locked(1, thr_deg + hyst + 0.01, now)
            assert hm._ranks[1].state == "ok"
        trans = [e for e in jr.events if e["e"] == "health_transition"]
        assert len(trans) == 2          # one down, one up — no flap
    finally:
        params.unset("health_alpha")


def test_monitor_evidence_and_series_shapes():
    hm, _ = _mk_monitor()
    now = time.monotonic()
    with hm._lock:
        for s in (0.8, 0.6, 0.4):
            hm._observe_locked(2, s, now)
    ev = hm.evidence(2, k=2)
    assert len(ev) == 2
    assert [s for _age, s in ev] == [0.6, 0.4]      # newest last
    assert all(age >= 0.0 for age, _s in ev)
    series = hm.series_snapshot()
    assert len(series[2]) == 3
    assert hm.evidence(99) == []                    # unknown rank


def test_monitor_refresh_rate_limit_reuses_last_fold():
    params.set("health_interval_s", 3600.0)
    try:
        hm, _ = _mk_monitor()
        hm.refresh()
        hm.refresh()
        hm.refresh()
        assert hm.folds == 1        # inside the window: one real fold
        hm.refresh(force=True)
        assert hm.folds == 2
        # a context-less self fold scores this rank healthy
        assert hm.snapshot()[0]["ewma"] == 1.0
    finally:
        params.unset("health_interval_s")


# ---------------------------------------------------------------------------
# export surfaces: gauges + __health__ section on a live Context
# ---------------------------------------------------------------------------

def _n_pool(n, name="h"):
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG(name, N=n)
    p.task("E", i=Range(0, n - 1)).flow("x", "CTL").body(lambda: None)
    return p.build()


def test_health_gauges_and_section_ride_samples():
    from parsec_tpu.core.context import Context
    params.set("health_interval_s", 0.0)
    try:
        with Context(nb_cores=2) as ctx:
            assert ctx.metrics is not None
            assert ctx.metrics.health is not None
            ctx.add_taskpool(_n_pool(10))
            ctx.wait(timeout=60)
            samples = ctx.metrics.samples()
    finally:
        params.unset("health_interval_s")
    text = render_text(samples)
    assert re.search(r'parsec_rank_health\{rank="0"\} 1\b', text)
    assert "parsec_health_folds_total" in text
    sections = [s for s in samples if s.get("n") == "__health__"]
    assert len(sections) == 1
    doc = sections[0]["doc"]
    assert doc["scores"]["0"]["state"] == "ok"
    # the side-channel record itself never renders
    assert "__health__" not in text


def test_health_disarmed_by_knob():
    from parsec_tpu.core.context import Context
    params.set("health_enable", 0)
    try:
        with Context(nb_cores=1) as ctx:
            assert ctx.metrics is not None
            assert ctx.metrics.health is None
            samples = ctx.metrics.samples()
    finally:
        params.unset("health_enable")
    assert not [s for s in samples if s.get("n") == "__health__"]


# ---------------------------------------------------------------------------
# H1: the offline auditor on hand-built journals
# ---------------------------------------------------------------------------

def _bundle(events, rank=0):
    """One rank's snapshot list in the auditor's input shape."""
    evs = [{"seq": i, "inc": 0, **e} for i, e in enumerate(events)]
    return {rank: [{"rank": rank, "inc": 0, "nranks": 2, "clock": {},
                    "events": evs}]}


def _h1(violations):
    return [v for v in violations if v.startswith("H1")]


def test_audit_h1_clean_drain_sequence():
    evs = [
        {"e": "health_transition", "t": 1.0, "peer": 1, "frm": "ok",
         "to": "degraded", "score": 0.7},
        {"e": "health_transition", "t": 2.0, "peer": 1,
         "frm": "degraded", "to": "critical", "score": 0.45},
        {"e": "health_drain", "t": 3.0, "peer": 1, "score": 0.45,
         "thr": 0.5, "sustain_s": 2.0, "evidence": [[0.5, 0.45]]},
        {"e": "fabric_admit", "t": 3.5, "job": 1, "verdict": "admit"},
        {"e": "fabric_place", "t": 4.0, "job": 1, "devices": [],
         "shared": True, "ranks": [0]},
        {"e": "health_undrain", "t": 5.0, "peer": 1, "score": 0.9},
        {"e": "fabric_admit", "t": 5.5, "job": 2, "verdict": "admit"},
        {"e": "fabric_place", "t": 6.0, "job": 2, "devices": [],
         "shared": True, "ranks": [0, 1]},
    ]
    assert journal_audit.audit(_bundle(evs)) == []


def test_audit_h1_drain_without_evidence():
    evs = [{"e": "health_drain", "t": 1.0, "peer": 1, "score": 0.4,
            "thr": 0.5, "evidence": []}]
    v = _h1(journal_audit.audit(_bundle(evs)))
    assert len(v) == 1
    assert "no preceding below-threshold evidence" in v[0]


def test_audit_h1_recovered_evidence_does_not_back_a_drain():
    """A transition back to 'ok' RETIRES the evidence: a later drain
    needs fresh below-threshold observations."""
    evs = [
        {"e": "health_transition", "t": 1.0, "peer": 1, "frm": "ok",
         "to": "degraded", "score": 0.7},
        {"e": "health_transition", "t": 2.0, "peer": 1,
         "frm": "degraded", "to": "ok", "score": 0.9},
        {"e": "health_drain", "t": 3.0, "peer": 1, "score": 0.4,
         "thr": 0.5, "evidence": []},
    ]
    assert len(_h1(journal_audit.audit(_bundle(evs)))) == 1


def test_audit_h1_drain_score_not_below_threshold():
    evs = [
        {"e": "health_transition", "t": 1.0, "peer": 1, "frm": "ok",
         "to": "critical", "score": 0.45},
        {"e": "health_drain", "t": 2.0, "peer": 1, "score": 0.55,
         "thr": 0.5, "evidence": [[0.5, 0.55]]},
    ]
    v = _h1(journal_audit.audit(_bundle(evs)))
    assert len(v) == 1
    assert "not below its threshold" in v[0]


def test_audit_h1_placement_onto_drained_rank():
    evs = [
        {"e": "health_transition", "t": 1.0, "peer": 1, "frm": "ok",
         "to": "critical", "score": 0.4},
        {"e": "health_drain", "t": 2.0, "peer": 1, "score": 0.4,
         "thr": 0.5, "evidence": [[0.5, 0.4]]},
        {"e": "fabric_admit", "t": 2.5, "job": 7, "verdict": "admit"},
        {"e": "fabric_place", "t": 3.0, "job": 7, "devices": [],
         "shared": True, "ranks": [0, 1]},
    ]
    v = _h1(journal_audit.audit(_bundle(evs)))
    assert len(v) == 1
    assert "placement targets drained rank" in v[0]
    assert "job=7" in v[0]


def test_audit_h1_skips_pre_health_placements():
    """Placements without a ``ranks`` gang stamp predate the health
    plane and are not judged."""
    evs = [
        {"e": "health_transition", "t": 1.0, "peer": 1, "frm": "ok",
         "to": "critical", "score": 0.4},
        {"e": "health_drain", "t": 2.0, "peer": 1, "score": 0.4,
         "thr": 0.5, "evidence": [[0.5, 0.4]]},
        {"e": "fabric_admit", "t": 2.5, "job": 7, "verdict": "admit"},
        {"e": "fabric_place", "t": 3.0, "job": 7, "devices": [],
         "shared": True},
    ]
    assert _h1(journal_audit.audit(_bundle(evs))) == []


# ---------------------------------------------------------------------------
# serving fabric: sustained-below-threshold drain, then undrain
# ---------------------------------------------------------------------------

class _FakeMonitor:
    """Stands in for ctx.metrics._health: a scripted peer score the
    fabric's dispatcher tick consumes, journaling the transition the
    way the real monitor does so the decision audits clean."""

    def __init__(self, journal):
        self._journal = journal
        self.ewma = {1: 0.2}
        self._transitioned = set()

    def refresh(self, force=False):
        for r, e in self.ewma.items():
            if e < 0.75 and r not in self._transitioned:
                self._transitioned.add(r)
                self._journal.emit("health_transition", peer=r,
                                   frm="ok", to="critical", score=e)
        return self.snapshot()

    def snapshot(self):
        return {r: {"score": e, "ewma": e, "trend": 0.0, "state": "ok",
                    "since_s": 0.0, "n": 9}
                for r, e in self.ewma.items()}

    def evidence(self, rank, k=8):
        e = self.ewma.get(rank, 1.0)
        return [[0.3, e], [0.1, e]]


def test_fabric_drains_then_undrains_on_scripted_scores():
    from parsec_tpu.service.fabric import ServingFabric
    params.set("fabric_drain_sustain_s", 0.3)
    try:
        with ServingFabric(nb_cores=2, max_active=4) as svc:
            fake = _FakeMonitor(svc.context.journal)
            svc.context.metrics._health = fake
            assert svc._health_monitor() is fake
            # min smoothed score across the (undrained) gang
            deadline = time.monotonic() + 10.0
            while svc.drains < 1 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert svc.drains == 1
            assert 1 in svc._health_drained
            st = svc.stats()["fabric"]
            assert st["drained_ranks"] == [1]
            drains = [e for e in svc.context.journal.tail(4096)
                      if e.get("e") == "health_drain"]
            assert len(drains) == 1
            assert drains[0]["peer"] == 1
            assert drains[0]["score"] < drains[0]["thr"]
            assert drains[0]["evidence"]        # decision carries proof
            # a drained rank stops taxing quotes
            assert svc._gang_health() == 1.0
            # recovery past the undrain threshold lifts it
            fake.ewma[1] = 0.95
            while svc._health_drained and time.monotonic() < deadline:
                time.sleep(0.01)
            assert not svc._health_drained
            undrains = [e for e in svc.context.journal.tail(4096)
                        if e.get("e") == "health_undrain"]
            assert len(undrains) == 1 and undrains[0]["peer"] == 1
            snap = svc.context.journal.snapshot()
        assert journal_audit.audit({0: [snap]}) == []
    finally:
        params.unset("fabric_drain_sustain_s")


def test_fabric_one_bad_fold_does_not_drain():
    """The sustain window is the whole point: a single below-threshold
    observation must not shed a rank."""
    from parsec_tpu.service.fabric import ServingFabric
    params.set("fabric_drain_sustain_s", 30.0)
    try:
        with ServingFabric(nb_cores=2, max_active=4) as svc:
            fake = _FakeMonitor(svc.context.journal)
            svc.context.metrics._health = fake
            time.sleep(0.6)     # several dispatcher ticks
            assert svc.drains == 0
            assert 1 in svc._below_since        # stopwatch is running
            # recovery above the threshold resets the stopwatch
            fake.ewma[1] = 0.9
            deadline = time.monotonic() + 5.0
            while 1 in svc._below_since \
                    and time.monotonic() < deadline:
                time.sleep(0.01)
            assert 1 not in svc._below_since
    finally:
        params.unset("fabric_drain_sustain_s")


def test_fabric_gang_health_floor_and_disarm():
    from parsec_tpu.service.fabric import ServingFabric
    with ServingFabric(nb_cores=2, max_active=4) as svc:
        fake = _FakeMonitor(svc.context.journal)
        fake.ewma = {0: 1.0, 1: 0.4}
        svc.context.metrics._health = fake
        assert svc._gang_health() == 0.4
        svc._health_enable = False
        assert svc._gang_health() == 1.0


# ---------------------------------------------------------------------------
# flight recorder: the health snapshot in incident bundles
# ---------------------------------------------------------------------------

def test_flightrec_bundle_carries_health_and_comm_delta(tmp_path):
    from parsec_tpu.core.context import Context
    params.set("flightrec_enabled", 1)
    params.set("flightrec_dir", str(tmp_path))
    params.set("flightrec_min_interval_s", 0.0)
    params.set("health_interval_s", 0.0)
    try:
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(_n_pool(10))
            ctx.wait(timeout=60)
            ctx.metrics.health.refresh(force=True)
            bundle = ctx.telemetry_incident("unit-test incident")
    finally:
        for k in ("flightrec_enabled", "flightrec_dir",
                  "flightrec_min_interval_s", "health_interval_s"):
            params.unset(k)
    assert bundle is not None
    path = os.path.join(bundle, "health-rank0.json")
    assert os.path.exists(path)
    with open(path) as fh:
        doc = json.load(fh)
    assert doc["reason"] == "unit-test incident"
    assert doc["health"]["0"]["ewma"] == pytest.approx(1.0)
    assert doc["health_series"]["0"]        # bounded score series
    assert "comm_delta" in doc and "comm_window_s" in doc
