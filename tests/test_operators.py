"""Tests of the map/apply/reduce operator taskpools
(reference: tests/collections/reduce.c, api/operator.c)."""

import numpy as np
import pytest

from parsec_tpu import Context
from parsec_tpu.data.matrix import SymTwoDimBlockCyclic, TwoDimBlockCyclic
from parsec_tpu.data.operators import apply_op, map_op, reduce_op


def test_apply_scales_every_tile():
    a = np.arange(36, dtype=np.float32).reshape(6, 6)
    want = a * 2
    A = TwoDimBlockCyclic(2, 2, 6, 6).from_array(a)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(apply_op(A, lambda T, m, n: T.__imul__(2)))
        ctx.wait(timeout=10)
    np.testing.assert_allclose(A.to_array(), want)


def test_apply_sym_touches_stored_triangle_only():
    a = np.ones((4, 4), np.float32)
    S = SymTwoDimBlockCyclic(2, 2, 4, 4,
                             uplo=SymTwoDimBlockCyclic.LOWER).from_array(a)
    touched = []
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(apply_op(S, lambda T, m, n: touched.append((m, n))))
        ctx.wait(timeout=10)
    assert sorted(touched) == [(0, 0), (1, 0), (1, 1)]


def test_map_reads_a_writes_b():
    a = np.arange(16, dtype=np.float32).reshape(4, 4)
    b = np.zeros((4, 4), np.float32)
    A = TwoDimBlockCyclic(2, 2, 4, 4).from_array(a)
    B = TwoDimBlockCyclic(2, 2, 4, 4).from_array(b)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(map_op(A, B, lambda X, Y, m, n: np.copyto(Y, X + 1)))
        ctx.wait(timeout=10)
    np.testing.assert_allclose(b, a + 1)
    with pytest.raises(ValueError):
        map_op(A, TwoDimBlockCyclic(4, 4, 4, 4), lambda X, Y, m, n: None)


@pytest.mark.parametrize("mt,nt", [(1, 1), (2, 2), (3, 3), (4, 1)])
def test_reduce_tree_sums_all_tiles(mt, nt):
    lm, ln = 2 * mt, 2 * nt
    a = np.arange(lm * ln, dtype=np.float64).reshape(lm, ln)
    A = TwoDimBlockCyclic(2, 2, lm, ln, dtype=np.float64).from_array(a)
    tp, holder = reduce_op(A, lambda x, y: x + y)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=10)
    # sum of all tiles elementwise == sum over tile grid positions
    want = sum(a[2 * m:2 * m + 2, 2 * n:2 * n + 2]
               for m in range(mt) for n in range(nt))
    np.testing.assert_allclose(holder["value"], want)


def test_reduce_rejects_ragged_tiles():
    A = TwoDimBlockCyclic(4, 4, 6, 6)
    with pytest.raises(ValueError):
        reduce_op(A, lambda x, y: x + y)
