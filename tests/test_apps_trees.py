"""Irregular-app tests: DTD merge sort, adaptive Haar tree, all2all, and
band collections (reference: tests/apps/{merge_sort,haar_tree,all2all},
data_dist/matrix *_band variants)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.dsl.dtd import DTDTaskpool


def test_merge_sort_dtd():
    """reference: tests/apps/merge_sort — leaf sorts + merge tree."""
    from parsec_tpu.apps.trees import merge_sort_dtd
    rng = np.random.default_rng(0)
    data = rng.standard_normal(1000).astype(np.float32)
    with Context(nb_cores=4) as ctx:
        tp = DTDTaskpool("msort")
        ctx.add_taskpool(tp)
        ctx.start()
        out = merge_sort_dtd(tp, data, leaf=37)
        tp.wait(timeout=60)
        got = np.asarray(out.data.pull_to_host().payload)
    np.testing.assert_allclose(got, np.sort(data))


def test_haar_tree_dynamic_termination():
    """reference: tests/apps/haar_tree project_dyn — tasks spawn tasks
    at runtime; the user_trigger termdet ends the pool when the
    algorithm (not a task count) says so."""
    from parsec_tpu.apps.trees import HaarProjection

    def f(x):
        return np.where(x < 0.3, 0.0, np.where(x < 0.7, 1.0, 0.25))

    proj = HaarProjection(f, eps=1e-3, min_width=1e-3)
    with Context(nb_cores=4) as ctx:
        tp = DTDTaskpool("haar")
        tp.termdet_name = "user_trigger"
        ctx.add_taskpool(tp)
        ctx.start()
        proj.run(tp)
        tp.wait(timeout=60)
        ctx.wait(timeout=60)
    # adaptivity: refined near the jumps, coarse elsewhere
    assert proj.nodes > 16, "tree never refined"
    assert len(proj.leaves) >= 4
    xs = np.linspace(0.05, 0.95, 400)
    err = np.abs(proj.evaluate(xs) - f(xs))
    assert np.mean(err) < 0.05, np.mean(err)


def test_haar_tree_requires_user_trigger():
    from parsec_tpu.apps.trees import HaarProjection
    proj = HaarProjection(lambda x: x)
    with Context(nb_cores=2) as ctx:
        tp = DTDTaskpool("haar2")
        ctx.add_taskpool(tp)
        ctx.start()
        with pytest.raises(ValueError, match="user_trigger"):
            proj.run(tp)
        tp.wait(timeout=30)


def test_band_collection():
    """reference: *_band.c — only band tiles stored/addressable."""
    from parsec_tpu.data.matrix import BandTwoDimBlockCyclic
    B = BandTwoDimBlockCyclic(mb=4, nb=4, lm=24, ln=24, band_km=1,
                              name="B")
    assert B.tile_exists(2, 2) and B.tile_exists(2, 1) and B.tile_exists(2, 3)
    assert not B.tile_exists(0, 5) and not B.tile_exists(5, 0)
    with pytest.raises(KeyError):
        B.data_of(0, 4)
    assert sorted(B.local_tiles()) == [
        (m, n) for m in range(6) for n in range(6) if abs(m - n) <= 1]
    # lower-band variant
    L = BandTwoDimBlockCyclic(mb=4, nb=4, lm=24, ln=24, band_km=2, uplo=0,
                              name="L")
    assert L.tile_exists(3, 1) and not L.tile_exists(1, 3)
    # tiles work end to end
    B.data_of(1, 2).copy_on(0).payload[:] = 7.0
    np.testing.assert_allclose(
        np.asarray(B.data_of(1, 2).pull_to_host().payload), 7.0)


def _all2all(ctx, rank, nranks):
    """reference: tests/apps/all2all — every rank sends a distinct block
    to every other rank (PTG over two distributions)."""
    from parsec_tpu.data.matrix import TwoDimTabular
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK

    mb = 4
    # S(i,j) owned by rank i; R(i,j) owned by rank j: the (i,j) edge is
    # an i->j message — all pairs = all-to-all
    S = TwoDimTabular(mb=mb, nb=mb, lm=nranks * mb, ln=nranks * mb,
                      table=[i for i in range(nranks)
                             for _ in range(nranks)],
                      nodes=nranks, myrank=rank, name="S")
    R = TwoDimTabular(mb=mb, nb=mb, lm=nranks * mb, ln=nranks * mb,
                      table=[j for _ in range(nranks)
                             for j in range(nranks)],
                      nodes=nranks, myrank=rank, name="R")
    for i, j in S.local_tiles():
        S.data_of(i, j).copy_on(0).payload[:] = 100.0 * i + j
    for i, j in R.local_tiles():
        R.data_of(i, j).copy_on(0).payload[:] = -1.0

    p = PTG("a2a", N=nranks)
    p.task("SEND", i=Range(0, nranks - 1), j=Range(0, nranks - 1)) \
        .affinity(lambda i, j, S=S: S(i, j)) \
        .flow("T", "READ",
              IN(DATA(lambda i, j, S=S: S(i, j))),
              OUT(TASK("RECV", "T", lambda i, j: dict(i=i, j=j)))) \
        .body(lambda: None)
    p.task("RECV", i=Range(0, nranks - 1), j=Range(0, nranks - 1)) \
        .affinity(lambda i, j, R=R: R(i, j)) \
        .flow("T", "READ", IN(TASK("SEND", "T", lambda i, j: dict(i=i, j=j)))) \
        .flow("D", "RW",
              IN(DATA(lambda i, j, R=R: R(i, j))),
              OUT(DATA(lambda i, j, R=R: R(i, j)))) \
        .body(lambda T, D: np.asarray(T).copy())
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    for i, j in R.local_tiles():
        got = np.asarray(R.data_of(i, j).pull_to_host().payload)
        np.testing.assert_allclose(got, 100.0 * i + j,
                                   err_msg=f"R({i},{j}) on rank {rank}")
    return "ok"


def test_all2all_4ranks():
    from parsec_tpu.comm.launch import run_distributed
    assert run_distributed(_all2all, 4, timeout=240) == ["ok"] * 4
