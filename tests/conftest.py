"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh BEFORE jax is imported anywhere,
so multi-chip sharding paths compile and execute without TPU hardware
(mirrors the reference's strategy of testing multi-node with mpiexec on one
node, SURVEY.md §4).
"""

import os

os.environ.setdefault("JAX_PLATFORMS", "cpu")
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")
