"""Test harness configuration.

Forces JAX onto a virtual 8-device CPU mesh so multi-chip sharding paths
compile and execute without TPU hardware (mirrors the reference's strategy
of testing multi-node with mpiexec on one node, SURVEY.md §4).

The environment may have already imported jax at interpreter startup and
pointed it at real TPU hardware (platform "axon", registered by a
sitecustomize hook) — env vars alone are captured before any test code
runs, so the platform must be forced through jax.config as well.
"""

import os

os.environ["JAX_PLATFORMS"] = "cpu"
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=8").strip()
os.environ.setdefault("JAX_ENABLE_X64", "0")

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: long-running acceptance loops (excluded from tier-1 via "
        "-m 'not slow')")
