"""Textual JDF front-end golden tests: parse the REFERENCE's own .jdf
corpus (reference: examples/Ex01..Ex07, tests/apps/stencil/stencil_1D.jdf
— the grammar of parsec.y) and run the resulting taskpools against their
documented semantics, with inline-C bodies mapped to Python."""

import os
import threading

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.jdf import JdfError, jdf_taskpool, parse_jdf

REF = "/root/reference"
needs_ref = pytest.mark.skipif(not os.path.isdir(REF),
                               reason="reference tree not present")


def _ctx():
    return Context(nb_cores=2)


@needs_ref
def test_ex01_helloworld_runs():
    V = VectorTwoDimCyclic(mb=1, lm=1)
    said = []

    def body(k):
        said.append(k)
    tp = jdf_taskpool(f"{REF}/examples/Ex01_HelloWorld.jdf",
                      data={"taskdist": V}, bodies={"HelloWorld": body})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert said == [0]


@needs_ref
def test_ex02_chain_new_datum():
    NB = 7
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1)
    seen = []

    def body(A, k):
        A[0] = 0 if k == 0 else A[0] + 1
        seen.append(int(A[0]))
    tp = jdf_taskpool(f"{REF}/examples/Ex02_Chain.jdf",
                      globals={"NB": NB}, data={"taskdist": V},
                      bodies={"Task": body},
                      arenas={"default": ((1,), np.int32)})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # the NEW datum circulates the chain, incremented once per hop
    assert seen == list(range(NB + 1))


@needs_ref
def test_ex04_chaindata_roundtrip():
    NB = 5
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1, dtype=np.int32)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 100

    def body(A, k):
        A[0] += 1
    tp = jdf_taskpool(f"{REF}/examples/Ex04_ChainData.jdf",
                      globals={"NB": NB}, data={"mydata": V},
                      bodies={"Task": body})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # tile 0's datum flowed the whole chain (NB+1 increments) and was
    # written back at tile NB
    out = np.asarray(V.data_of(NB).pull_to_host().payload)
    assert out[0] == 100 + NB + 1


@needs_ref
def test_ex05_broadcast_fanout():
    NB = 6
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1, dtype=np.int32)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = -1
    lock = threading.Lock()
    recvs = []

    def bcast(A, k):
        A[0] = k

    def recv(A, k, n):
        with lock:
            recvs.append((k, n, int(A[0])))
    tp = jdf_taskpool(f"{REF}/examples/Ex05_Broadcast.jdf",
                      globals={"nodes": 1, "rank": 0, "NB": NB},
                      data={"mydata": V},
                      bodies={"TaskBcast": bcast, "TaskRecv": recv})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # k=0 broadcasts its value to n = 0..NB..2
    assert sorted(recvs) == [(0, n, 0) for n in range(0, NB + 1, 2)]


@needs_ref
def test_ex07_raw_ctl_orders_update_after_reads():
    NB = 6
    V = VectorTwoDimCyclic(mb=1, lm=2 * NB, dtype=np.int32)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0
    lock = threading.Lock()
    events = []

    def bcast(A, k):
        A[0] = k + 1

    def recv(A, k, n):
        with lock:
            events.append(("recv", k, n, int(A[0])))

    def update(A, k):
        with lock:
            events.append(("update", k))
        A[0] = -k - 1
    tp = jdf_taskpool(f"{REF}/examples/Ex07_RAW_CTL.jdf",
                      globals={"nodes": 1, "rank": 0, "NB": NB},
                      data={"mydata": V},
                      bodies={"TaskBcast": bcast, "TaskRecv": recv,
                              "TaskUpdate": update})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # the CTL gather orders the anti-dependent update after EVERY read,
    # and every reader saw the broadcast value (Ex07's documented point)
    upd = events.index(("update", 0))
    reads = [e for e in events if e[0] == "recv"]
    assert len(reads) == len(range(0, NB + 1, 2))
    assert all(events.index(r) < upd for r in reads)
    assert all(r[3] == 1 for r in reads)
    out = np.asarray(V.data_of(0).pull_to_host().payload)
    assert out[0] == -1


class _DescAdapter:
    """Reference-style tiled-matrix handle (descA->lmt etc.) over a
    VectorTwoDimCyclic, for JDFs written against parsec_tiled_matrix_t."""

    def __init__(self, V, lnt):
        import types
        self._V = V
        self.lmt = 1
        self.lnt = lnt
        self.mb = V.mb
        self.nb = V.mb
        self.ln = V.lm
        self.super = types.SimpleNamespace(myrank=0)   # descA->super.myrank

    def __call__(self, m, n):
        return self._V(n)


@needs_ref
def test_stencil_1d_jdf_parses_and_builds():
    """The stencil JDF (guards, NULL endpoints, derived locals, inline-C
    range bounds, type_remote/displ annotations) parses and builds; its
    inline-C body is rejected with a clear error when executed."""
    path = f"{REF}/tests/apps/stencil/stencil_1D.jdf"
    ast = parse_jdf(open(path).read())
    names = [t.name for t in ast.tasks]
    assert "task" in names
    t = next(tt for tt in ast.tasks if tt.name == "task")
    assert [f.name for f in t.flows] == ["AL", "AR", "A0", "A"]
    assert sum(len(f.deps) for f in t.flows) == 7
    V = VectorTwoDimCyclic(mb=4, lm=16)
    desc = _DescAdapter(V, lnt=4)
    tp = jdf_taskpool(open(path).read(),
                      globals={"descA": desc, "iter": 1, "R": 1,
                               "rank_neighbor": lambda *a: 0,
                               "sizeof_datatype": 8},
                      data={"descA": desc}, name="stencil1d")
    assert set(tp.task_classes) == {"task"}
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        with pytest.raises(RuntimeError) as exc:
            ctx.wait(timeout=60)
        assert "inline-C body" in str(exc.value.__cause__)


def test_jdf_error_reporting():
    # declarations+assignments+return ARE the supported subset now (r4);
    # control flow stays out
    with pytest.raises(JdfError, match="subset"):
        jdf_taskpool("T(k)\nk = 0 .. %{ while (x) x--; return x; %}\n"
                     ": d( k )\nBODY\n{}\nEND\n",
                     data={"d": VectorTwoDimCyclic(mb=1, lm=1)})
    with pytest.raises(JdfError, match="no range"):
        jdf_taskpool("T(k)\n: d( k )\nBODY\n{}\nEND\n",
                     data={"d": VectorTwoDimCyclic(mb=1, lm=1)})


@needs_ref
def test_ex03_chainmpi_chain_semantics():
    """Ex03_ChainMPI: the NEW datum chains through NB+1 increments
    (the MPI distribution collapses to 1 rank here — rank_of comes from
    the collection, exactly like the reference's taskdist)."""
    NB = 9
    V = VectorTwoDimCyclic(mb=1, lm=NB + 1)
    seen = []

    def body(A, k):
        A[0] = 0 if k == 0 else A[0] + 1
        seen.append(int(A[0]))
    tp = jdf_taskpool(f"{REF}/examples/Ex03_ChainMPI.jdf",
                      globals={"NB": NB}, data={"taskdist": V},
                      bodies={"Task": body},
                      arenas={"default": ((1,), np.int32)})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert seen == list(range(NB + 1))


@needs_ref
def test_ex06_raw_bcast_update():
    """Ex06_RAW: TaskBcast(k) fans A out to TaskRecv(k, 0..NB..2) and
    TaskUpdate(k); the WAR hazard means every Recv must observe the
    BROADCAST value (k+1), never Update's overwrite (-k-1)."""
    nodes = 1
    NB = 6
    V = VectorTwoDimCyclic(mb=1, lm=1 + NB + 1, dtype=np.int32)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0
    lock = threading.Lock()
    recvd = []

    def bcast(A, k):
        A[0] = k + 1

    def recv(A, k, n):
        with lock:
            recvd.append((k, n, int(A[0])))

    def update(A, k):
        A[0] = -k - 1
    tp = jdf_taskpool(f"{REF}/examples/Ex06_RAW.jdf",
                      globals={"nodes": nodes, "rank": 0, "mydata": V},
                      data={"mydata": V},
                      bodies={"TaskBcast": bcast, "TaskRecv": recv,
                              "TaskUpdate": update})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    # every Recv saw the broadcast value, post-WAR overwrite reached home
    assert sorted(recvd) == [(0, n, 1) for n in range(0, NB + 1, 2)]
    home = np.asarray(V.data_of(0).pull_to_host().payload)
    assert home[0] == -1


@needs_ref
def test_multichain_parses_and_runs():
    """tests/runtime/multichain.jdf: two task classes chained
    horizontally and vertically over two block-cyclic matrices — a
    harder corpus member than the examples (multi-flow classes with
    cross-class ternary deps)."""
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    NI, NJ = 4, 3
    mb = 2
    A = TwoDimBlockCyclic(mb=mb, nb=1, lm=mb * NI, ln=1, name="descA")
    B = TwoDimBlockCyclic(mb=mb, nb=1, lm=mb * NI, ln=1, name="descB")
    for M in (A, B):
        for m, n in M.local_tiles():
            M.data_of(m, n).copy_on(0).payload[:] = 0.0
    ran = {"H": 0, "V": 0}
    lock = threading.Lock()

    def horizontal(A, B, i):
        with lock:
            ran["H"] += 1
        B[:] = np.asarray(B) + 1.0

    def vertical(A, B, i, j):
        with lock:
            ran["V"] += 1
        B[:] = np.asarray(B) + 1.0
    tp = jdf_taskpool(f"{REF}/tests/runtime/multichain.jdf",
                      globals={"NI": NI, "NJ": NJ},
                      data={"descA": A, "descB": B},
                      bodies={"HORIZONTAL": horizontal,
                              "VERTICAL": vertical})
    with _ctx() as ctx:
        ctx.add_taskpool(tp)
        ctx.wait(timeout=60)
    assert ran == {"H": NI, "V": NI * NJ}
    # B(0,0) rode the whole HORIZONTAL chain then every VERTICAL column
    # chain wrote back to descB(i, 0): each home tile accumulated its
    # chains' increments
    out = np.asarray(B.data_of(0, 0).pull_to_host().payload)
    assert out.max() >= 1.0


def test_inline_c_statement_subset():
    """VERDICT r3 #6: inline-C with declarations + assignments + return
    translates (not just 'return EXPR;')."""
    from parsec_tpu.dsl.ptg.jdf import c2py
    expr = c2py("%{ int r = k + 1; r = r * 2; return r + n; %}")
    assert eval(expr, {"k": 3, "n": 10}) == 18
    # still rejects what the subset cannot express
    with pytest.raises(JdfError):
        c2py("%{ for (i = 0; i < 3; i++) x += i; return x; %}")


def test_inline_c_integer_division():
    """ADVICE r4 (medium): C '/' and '%' on integral operands keep C
    truncating semantics through translation; floats keep true division."""
    from parsec_tpu.dsl.ptg.jdf import C_EVAL_HELPERS, c2py
    ns = dict(C_EVAL_HELPERS)
    assert eval(c2py("%{ int r = k / 2; return r; %}"), {**ns, "k": 3}) == 1
    assert eval(c2py("k / 2"), {**ns, "k": 7}) == 3
    assert eval(c2py("(0 - 7) / 2"), ns) == -3     # truncation toward zero
    assert eval(c2py("(0 - 7) % 2"), ns) == -1     # C remainder sign
    assert eval(c2py("k / 2.0"), {**ns, "k": 7}) == 3.5
    # compound '/=' goes through the same rewrite
    assert eval(c2py("%{ int r = k; r /= 2; return r; %}"),
                {**ns, "k": 9}) == 4
