"""Live attribution plane (prof/liveattr.py): streaming class
profiles, the online exec/queue/comm/idle split, straggler detection,
the dagsim ETA, and the job server's status surface.

The two acceptance legs:

* a seeded keyed ``delay_dispatch`` fault plan makes the anomaly
  event, ``parsec_stragglers_total`` and the rate-limited flight
  recorder bundle all fire for the delayed class — and a clean run of
  the same workload stays silent;
* on the traced 2-rank rtt leg the ONLINE attribution split agrees
  with offline ``critpath.attribute()`` within 10 percentage points
  per bucket (offline coverage >= 0.9).
"""

import json
import os
import re
import socket
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from parsec_tpu.prof import liveattr as la_mod  # noqa: E402
from parsec_tpu.prof.liveattr import (bucket_quantile,  # noqa: E402
                                      class_totals, eta_seconds,
                                      merge_sections, telescope)
from parsec_tpu.prof.metrics import render_text  # noqa: E402
from parsec_tpu.utils import faultinject  # noqa: E402
from parsec_tpu.utils.mca import params  # noqa: E402


def _chain_pool(n, name="chain"):
    """Serial n-task chain rooted in one collection tile (the
    test_metrics chain shape, single rank)."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    V = VectorTwoDimCyclic(mb=4, lm=4)
    V.data_of(0).copy_on(0).payload[:] = 0.0
    p = PTG(name, NT=n)
    p.task("S", k=Range(0, n - 1)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, n=n: dict(k=k + 1)),
                  when=lambda k, n=n: k < n - 1),
              OUT(DATA(lambda k, V=V: V(0)),
                  when=lambda k, n=n: k == n - 1)) \
        .body(lambda T: T + 1.0)
    return p.build()


def _flat_pool(n, name="flat"):
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG(name, NT=n)
    p.task("W", k=Range(0, n - 1)).body(lambda: None)
    return p.build()


# ---------------------------------------------------------------------------
# unit: profiles / telescoping / merge / ETA
# ---------------------------------------------------------------------------

def test_profile_stream_and_quantiles():
    p = la_mod._Profile(ring=64)
    for i in range(100):
        p.observe(1e-3, alpha=0.2)
    p.observe(1.0, alpha=0.2)
    assert p.n == 101
    assert p.quantile(0.5) == pytest.approx(1e-3)
    assert p.quantile(0.99) in (1e-3, 1.0)
    assert 1e-3 < p.ewma < 1.0          # pulled toward the outlier
    w = p.to_wire()
    assert w["n"] == 101 and sum(w["b"]) == 101
    # bucket quantile off the wire form: the 1s outlier sits in the
    # top half of the log2 ladder
    assert bucket_quantile(w["b"], 0.5) == pytest.approx(
        2.0 ** -10, rel=1.0)


def test_telescope_sums_to_elapsed_and_clamps():
    t = telescope(10.0, 2.0, 1.0, 3.0)
    assert t["idle"] == pytest.approx(4.0)
    assert t["exec"] + t["queue"] + t["comm"] + t["idle"] == \
        pytest.approx(t["elapsed"]) == pytest.approx(10.0)
    # the comm ESTIMATE caps into what the measured buckets leave
    t = telescope(10.0, 2.0, 1.0, 100.0)
    assert t["comm"] == pytest.approx(7.0) and t["idle"] == 0.0
    # wide DAG: cumulative exec+queue beyond the window scale down
    t = telescope(6.0, 6.0, 3.0, 3.0)
    assert t["idle"] == 0.0 and t["comm"] == 0.0
    assert t["exec"] == pytest.approx(4.0)
    assert t["exec"] + t["queue"] == pytest.approx(6.0)
    assert telescope(0.0, 1.0, 1.0, 1.0)["elapsed"] == 0.0


def test_merge_sections_sums_counts_and_buckets():
    prof = la_mod._Profile(ring=16)
    for _ in range(10):
        prof.observe(1e-3, alpha=0.2)
    row = {"job": 7, "cls": "GEMM", "done": 10, "sel": 0,
           "t0": 5.0, "t1": 6.0, "lat": prof.to_wire(),
           "queue": None, "exec": None}
    sec_a = {"rank": 0, "recs": [row],
             "strag": [[7, "GEMM", "exec", 2]], "anomalies": [],
             "comm": {"acts": 4.0, "delay_s": 0.5, "per_peer": {}}}
    sec_b = {"rank": 1, "recs": [dict(row, done=5)],
             "strag": [[7, "GEMM", "exec", 1]], "anomalies": [],
             "comm": {"acts": 2.0, "delay_s": 0.5, "per_peer": {}}}
    m = merge_sections({0: sec_a, 1: sec_b})
    rec = m["recs"][(7, "GEMM")]
    assert rec["done"] == 15
    assert rec["lat"]["n"] == 20 and sum(rec["lat"]["b"]) == 20
    assert m["strag"][(7, "GEMM", "exec")] == 3
    # (4 + 2) acts x the pessimistic 0.5s delay x the 2.0 load factor
    assert m["comm_s"] == pytest.approx(6.0)
    assert m["window_s"] == pytest.approx(1.0)


def test_eta_through_dagsim():
    rows = [{"cls": "A", "pending": 100, "mean_s": 0.01},
            {"cls": "B", "pending": 100, "mean_s": 0.03}]
    eta = eta_seconds(rows, 200, n_chips=4)
    # 4 s of work over 4 chips: list scheduling lands near 1 s
    assert 0.9 <= eta <= 1.5
    # a class with no profile borrows the blended mean; never None
    # while any class has data
    rows.append({"cls": "C", "pending": 50, "mean_s": 0.0})
    assert eta_seconds(rows, 250, n_chips=4) > eta * 0.9
    assert eta_seconds([{"cls": "A", "pending": 5, "mean_s": 0.0}],
                       5, 2) is None


def test_eta_dynamic_pool_falls_back_to_aggregate_remaining():
    """Unknown per-class totals (DTD / over-cap enumeration): every
    row's pending is 0, but the aggregate remaining + the observed
    profiles must still quote (the __rest__ path)."""
    rows = [{"cls": "A", "pending": 0, "done": 50, "mean_s": 0.01}]
    eta = eta_seconds(rows, 200, n_chips=2)
    assert eta == pytest.approx(200 * 0.01 / 2, rel=0.5)
    # and __rest__ scales WITH the throughput calibration: the gang
    # completed 50 tasks over the 1s window -> sustains 50/s -> 200
    # remaining ~ 4s (not the raw 1s the uncalibrated mean quotes)
    eta = eta_seconds(rows, 200, n_chips=2, done_total=50,
                      window_s=1.0)
    assert 3.2 <= eta <= 4.8, eta


def test_finish_profile_is_non_destructive():
    """build_status finishes the same merged row once per job entry
    and once in the aggregate: both reads must agree."""
    prof = la_mod._Profile(ring=16)
    for _ in range(20):
        prof.observe(2e-3, alpha=0.2)
    merged = la_mod._merge_profile(None, prof.to_wire())
    first = la_mod._finish_profile(merged)
    second = la_mod._finish_profile(merged)
    assert first == second
    assert first["p99_s"] == pytest.approx(2e-3)   # ring, not bucket


def test_eta_throughput_calibration():
    """Sojourn-based means double-count queueing (dagsim models
    queueing itself — a deep-queued pool quoted 37x over before the
    fix): with the observed completion rate supplied, the quote
    extrapolates the measured throughput, not the inflated means."""
    rows = [{"cls": "W", "pending": 300, "done": 100,
             "mean_s": 0.1}]              # inflated sojourn mean
    # 100 tasks completed in a 1s window on 2 chips -> the gang
    # sustains 100/s -> 300 remaining ~ 3s (NOT 300 * 0.1 / 2 = 15s)
    eta = eta_seconds(rows, 300, n_chips=2, done_total=100,
                      window_s=1.0)
    assert 2.5 <= eta <= 3.6, eta
    # without observation data the raw profile means stand
    assert eta_seconds(rows, 300, n_chips=2) > 10


def test_eta_quote_tracks_actual_completion():
    """ETA honesty e2e: a mid-run quote from the status surface must
    land within a small factor of the ACTUAL remaining wall time."""
    params.set("metrics_sample", 1)
    from parsec_tpu.service.service import JobService
    from parsec_tpu.dsl.ptg.api import PTG, Range

    def slow_pool(n=400, ms=3.0):
        p = PTG("slowjob", NT=n)
        p.task("W", k=Range(0, n - 1)).body(
            lambda: time.sleep(ms * 1e-3))
        return p.build()

    try:
        svc = JobService(nb_cores=2)
        try:
            job = svc.submit(lambda: (slow_pool(), lambda: "ok"),
                             name="eta")
            la = svc.context.metrics.liveattr
            quote = None
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                doc = la_mod.build_status(svc.context, svc,
                                          {0: la.section()})
                js = [j for j in doc["jobs"]
                      if j["job"] == job.job_id]
                if js and js[0]["status"] == "RUNNING" \
                        and js[0]["eta_s"] is not None \
                        and js[0]["progress"]["done"] >= 80:
                    quote = (time.monotonic(), js[0]["eta_s"])
                    break
                time.sleep(0.05)
            assert quote, "no mid-run ETA quote observed"
            assert job.wait(timeout=120)
            actual = time.monotonic() - quote[0]
            # generous band: the quote must be the right ORDER — the
            # pre-fix failure mode was 37x over
            assert 0.15 * actual <= quote[1] <= 5.0 * actual, (
                quote[1], actual)
        finally:
            svc.shutdown(timeout=15)
    finally:
        params.unset("metrics_sample")


def test_class_totals_enumerates_and_caches():
    tp = _flat_pool(40)
    assert class_totals(tp) == {"W": 40}
    assert tp._liveattr_totals == {"W": 40}     # cached
    big = _flat_pool(50)
    big._liveattr_totals = None                 # simulate cap overflow
    assert class_totals(big) is None


# ---------------------------------------------------------------------------
# end-to-end single rank: exact counts, profiles, status document
# ---------------------------------------------------------------------------

def test_liveattr_counts_exactly_and_profiles(monkeypatch):
    params.set("metrics_sample", 1)
    try:
        from parsec_tpu.core.context import Context
        with Context(nb_cores=2) as ctx:
            tp = _flat_pool(200)
            ctx.add_taskpool(tp)
            ctx.wait()
            la = ctx.metrics.liveattr
            sec = la.section()
            rows = {r["cls"]: r for r in sec["recs"]}
            assert rows["W"]["done"] == 200
            assert rows["W"]["lat"]["n"] > 0
            doc = la_mod.build_status(ctx, None, {0: sec})
            agg = doc["aggregate"]
            assert agg["done"] == 200
            att = agg["attribution"]
            assert att["elapsed"] > 0
            assert att["exec"] + att["queue"] + att["comm"] \
                + att["idle"] == pytest.approx(att["elapsed"],
                                               rel=1e-3)
            # reset starts a fresh window AND invalidates the
            # per-TaskClass caches — a surviving class must not keep
            # counting into an orphaned row
            rec_old = tp.task_classes["W"]._la_rec
            la.reset()
            assert rec_old.la is None       # cache binding broken
            assert la.section()["recs"] == []
            ctx.add_taskpool(_flat_pool(30))
            ctx.wait()
            rows2 = {r["cls"]: r for r in la.section()["recs"]}
            assert rows2["W"]["done"] == 30
    finally:
        params.unset("metrics_sample")


def test_evicted_rec_does_not_orphan_live_classes():
    """Past liveattr_max_series the oldest row evicts; a TaskClass
    still pointing at the evicted row must re-resolve on its next
    task instead of updating telemetry nobody can see."""
    params.set("metrics_sample", 1)
    params.set("liveattr_max_series", 1)
    try:
        from parsec_tpu.core.context import Context
        from parsec_tpu.dsl.ptg.api import PTG, Range
        with Context(nb_cores=2) as ctx:
            p = PTG("two", NT=60)
            p.task("A", k=Range(0, 59)).body(lambda: None)
            p.task("B", k=Range(0, 59)).body(lambda: None)
            tp = p.build()
            ctx.add_taskpool(tp)
            ctx.wait()
            la = ctx.metrics.liveattr
            # the orphan invariant: any rec a TaskClass still binds to
            # must be the registered one (or invalidated)
            live = set(map(id, la._recs.values()))
            for tc in tp.task_classes.values():
                rec = getattr(tc, "_la_rec", None)
                if rec is not None and rec.la is la:
                    assert id(rec) in live
    finally:
        params.unset("metrics_sample")
        params.unset("liveattr_max_series")


def test_split_mode_separates_queue_and_exec():
    params.set("metrics_sample", 1)
    params.set("metrics_queue_wait", 1)
    try:
        from parsec_tpu.core.context import Context
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(_flat_pool(120))
            ctx.wait()
            sec = ctx.metrics.liveattr.section()
            row = {r["cls"]: r for r in sec["recs"]}["W"]
            assert row["done"] == 120
            assert row["sel"] == 120            # exact selections
            assert row["queue"] is not None and row["queue"]["n"] > 0
            assert row["exec"] is not None and row["exec"]["n"] > 0
    finally:
        params.unset("metrics_sample")
        params.unset("metrics_queue_wait")


# ---------------------------------------------------------------------------
# straggler detection: deterministic fault-plan e2e + clean twin
# ---------------------------------------------------------------------------

def _straggler_run(tmp_path, plan):
    """One chain run under (or without) a keyed delay plan; returns
    (anomalies, rendered metrics, bundle_dir)."""
    params.set("metrics_sample", 1)
    params.set("liveattr_straggler_min", 16)
    params.set("liveattr_straggler_mult", 8.0)
    params.set("liveattr_straggler_floor_ms", 40.0)
    params.set("flightrec_enabled", 1)
    params.set("flightrec_dir", str(tmp_path / "bundle"))
    if plan:
        faultinject.arm(plan)
    try:
        from parsec_tpu.core.context import Context
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(_chain_pool(260))
            ctx.wait(timeout=120)
            la = ctx.metrics.liveattr
            anomalies = la.anomalies()
            text = render_text(ctx.metrics.samples())
            if plan:
                # the incident dump runs on its own thread
                deadline = time.monotonic() + 10
                while not (tmp_path / "bundle" / "rank0.ptt").exists() \
                        and time.monotonic() < deadline:
                    time.sleep(0.05)
        return anomalies, text, tmp_path / "bundle"
    finally:
        if plan:
            faultinject.disarm()
        for k in ("metrics_sample", "liveattr_straggler_min",
                  "liveattr_straggler_mult",
                  "liveattr_straggler_floor_ms", "flightrec_enabled",
                  "flightrec_dir"):
            params.unset(k)


def test_straggler_fires_under_delay_plan(tmp_path):
    """A keyed delay_dispatch stall on one late task of an otherwise
    uniform chain: the anomaly event names the class and kind, the
    counter exports with {job,class,kind} labels, and the flight
    recorder captured the neighborhood."""
    anomalies, text, bundle = _straggler_run(
        tmp_path, "seed=5;delay_dispatch=key~k=250,ms=150")
    assert anomalies, "no straggler detected under the delay plan"
    ev = anomalies[-1]
    assert ev["cls"] == "S" and ev["kind"] == "exec"
    assert ev["latency_s"] > ev["threshold_s"] > 0
    assert "k=250" in ev["task"]
    m = re.search(
        r'parsec_stragglers_total\{class="S",job="-",kind="exec"\} '
        r'(\d+)', text)
    assert m is not None and int(m.group(1)) >= 1, text[:2000]
    assert (bundle / "rank0.ptt").exists()
    assert (bundle / "incidents.jsonl").exists()
    inc = (bundle / "incidents.jsonl").read_text()
    assert "straggler" in inc


def test_straggler_clean_run_stays_silent(tmp_path):
    """The same workload with no plan: no anomaly, no counter, no
    bundle — detection must not cry wolf on ordinary variance."""
    anomalies, text, bundle = _straggler_run(tmp_path, "")
    assert anomalies == []
    assert "parsec_stragglers_total" not in text
    # the recorder probes its dir at arm time; no INCIDENT may land
    assert not (bundle / "rank0.ptt").exists()
    assert not (bundle / "incidents.jsonl").exists()


# ---------------------------------------------------------------------------
# status surface: framed op, HTTP GET, tools entry points
# ---------------------------------------------------------------------------

def _http_get(host, port, path):
    with socket.create_connection((host, port), timeout=10) as s:
        s.settimeout(10)
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        buf = b""
        while True:
            c = s.recv(65536)
            if not c:
                break
            buf += c
    head, _, body = buf.partition(b"\r\n\r\n")
    return head, body


def test_status_op_and_http_surface():
    params.set("metrics_sample", 1)
    from parsec_tpu.service.server import JobServer, request
    from parsec_tpu.service.service import JobService
    try:
        svc = JobService(nb_cores=2)
        server = JobServer(svc, port=0)
        try:
            def factory():
                tp = _flat_pool(150, name="job-pool")
                return tp, lambda: {"ok": 1}
            job = svc.submit(factory, name="flat")
            assert job.wait(timeout=60)
            st = request(server.host, server.port, {"op": "status"})
            assert st["ok"]
            doc = st["status"]
            assert doc["ranks"] == [0]
            (j,) = doc["jobs"]
            assert j["job"] == job.job_id and j["status"] == "DONE"
            assert j["progress"]["done"] == 150
            cls = j["progress"]["classes"]["W"]
            assert cls["done"] == 150 and cls["pending"] == 0
            att = j["attribution"]
            assert att["exec"] + att["queue"] + att["comm"] \
                + att["idle"] == pytest.approx(att["elapsed"],
                                               rel=1e-3)
            assert j["stragglers"] == []
            assert doc["service"]["running"] == 0
            # the original per-job shape is untouched
            info = request(server.host, server.port,
                           {"op": "status", "job": job.job_id})
            assert info["ok"] and info["info"]["status"] == "DONE"
            # plain HTTP twin on the sniffed port
            head, body = _http_get(server.host, server.port, "/status")
            assert b"200 OK" in head and b"application/json" in head
            hdoc = json.loads(body)
            assert hdoc["jobs"][0]["progress"]["done"] == 150
            # /metrics still serves next to it
            head, body = _http_get(server.host, server.port,
                                   "/metrics")
            assert b"200 OK" in head
            assert b"parsec_tasks_retired_total" in body
        finally:
            server.close()
            svc.shutdown(timeout=15)
    finally:
        params.unset("metrics_sample")


def test_live_view_and_metrics_client_status(tmp_path):
    """tools/live_view.py remote mode + metrics_client --status render
    a live server (satellites: the advertised-but-error'd scrape mode
    now works)."""
    import subprocess
    from parsec_tpu.service.server import JobServer
    from parsec_tpu.service.service import JobService
    svc = JobService(nb_cores=2)
    server = JobServer(svc, port=0)
    try:
        def factory():
            return _flat_pool(60, name="jp"), lambda: None
        job = svc.submit(factory, name="tview")
        assert job.wait(timeout=60)
        repo = os.path.dirname(os.path.dirname(os.path.abspath(
            __file__)))
        env = {**os.environ, "JAX_PLATFORMS": "cpu"}
        r = subprocess.run(
            [sys.executable, "tools/live_view.py", "--host",
             server.host, "--port", str(server.port), "--once"],
            capture_output=True, text=True, timeout=60, cwd=repo,
            env=env)
        assert r.returncode == 0, r.stderr
        assert "tview" in r.stdout and "exec/queue/comm/idle" \
            in r.stdout
        r = subprocess.run(
            [sys.executable, "tools/metrics_client.py", "--host",
             server.host, "--port", str(server.port), "--status",
             "--job", str(job.job_id)],
            capture_output=True, text=True, timeout=60, cwd=repo,
            env=env)
        assert r.returncode == 0, r.stderr
        doc = json.loads(r.stdout)
        assert [j["job"] for j in doc["jobs"]] == [job.job_id]
    finally:
        server.close()
        svc.shutdown(timeout=15)


# ---------------------------------------------------------------------------
# 2-rank validation: online split vs offline critpath.attribute()
# ---------------------------------------------------------------------------

def _online_pp_worker(ctx, rank, nranks, outdir):
    from parsec_tpu.apps.pingpong import run_pingpong
    from parsec_tpu.prof.causal import install_causal_tracer
    from parsec_tpu.prof.pins import install_task_profiler
    from parsec_tpu.prof.profiling import Profile
    run_pingpong(ctx, 8, 10)                  # warm link + code paths
    prof = Profile(f"la-pp-r{rank}")
    mod = install_task_profiler(ctx, prof)
    tr = install_causal_tracer(ctx, prof)
    la = ctx.metrics.liveattr
    la.reset()                                # window = the measured run
    run_pingpong(ctx, 8, 150)
    deadline = time.time() + 15               # one clock round for the
    while len(ctx.comm.ce.clock) < nranks - 1 \
            and time.time() < deadline:       # offline merge + the
        time.sleep(0.05)                      # online comm estimate
    section = la.section()
    mod.uninstall(ctx)
    tr.uninstall(ctx)
    path = prof.dump(os.path.join(outdir, f"rank{rank}.ptt"))
    return {"path": path, "section": section}


def test_online_split_matches_offline_attribution(tmp_path):
    """ISSUE acceptance: on the traced 2-rank rtt leg the ONLINE
    exec/queue/comm/idle split agrees with the offline
    critpath.attribute() decomposition within 10 percentage points
    per bucket (offline coverage >= 0.9)."""
    from parsec_tpu.comm.launch import run_distributed
    from parsec_tpu.prof import critpath
    env = {"PARSEC_MCA_METRICS_SAMPLE": "1",
           "PARSEC_MCA_METRICS_QUEUE_WAIT": "1"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    last = None
    try:
        # one retry: host-load noise can produce a pathological
        # OFFLINE trace (coverage far from 1) or smear one window —
        # the same single-sample fragility the bench's min-of-pairs
        # discipline exists for
        for attempt in range(2):
            out = tmp_path / f"try{attempt}"
            out.mkdir()
            res = run_distributed(_online_pp_worker, 2,
                                  args=(str(out),), timeout=240)
            offline = critpath.attribution([r["path"] for r in res])
            merged = merge_sections({i: r["section"]
                                     for i, r in enumerate(res)})
            exec_s, queue_s = la_mod._bucket_sums(
                list(merged["recs"].values()))
            online = telescope(merged["window_s"], exec_s, queue_s,
                               merged["comm_s"])
            ms = offline["makespan"]
            last = (offline, online)
            if not 0.9 <= offline["coverage"] <= 1.1:
                continue     # unusable offline reference — re-trace
            deltas = {
                b: abs(offline["buckets"][b] / ms
                       - online[b] / online["elapsed"])
                for b in ("exec", "queue", "comm", "idle")}
            if all(d <= 0.10 for d in deltas.values()):
                return
        raise AssertionError(
            f"online split disagrees with offline attribution "
            f"beyond 10pp after retry: {last}")
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


if __name__ == "__main__":
    sys.exit(pytest.main([__file__, "-v"]))
