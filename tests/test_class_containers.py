"""Unit + multithreaded stress tests of the base containers.

Mirrors the reference's tests/class/{lifo,list,hash,atomics,rwlock,future,
future_datacopy}.c pyramid (SURVEY.md §4).
"""

import threading

import pytest

from parsec_tpu.containers.lists import Dequeue, Fifo, Lifo, OrderedList
from parsec_tpu.containers.hash_table import ConcurrentHashTable
from parsec_tpu.containers.futures import CountdownFuture, DataCopyFuture, Future
from parsec_tpu.containers.sync import AtomicCounter, Barrier, RWLock

NTHREADS = 8
NITEMS = 2000


def run_threads(n, fn):
    threads = [threading.Thread(target=fn, args=(i,)) for i in range(n)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()


class Item:
    def __init__(self, value, priority=0):
        self.value = value
        self.priority = priority


def test_lifo_order():
    s = Lifo()
    for i in range(10):
        s.push(i)
    assert [s.pop() for _ in range(10)] == list(range(9, -1, -1))
    assert s.pop() is None
    assert s.is_empty()


def test_fifo_order():
    q = Fifo()
    q.push_chain(range(10))
    assert [q.pop() for _ in range(10)] == list(range(10))
    assert q.pop() is None


def test_dequeue_both_ends():
    d = Dequeue()
    d.push_back(1)
    d.push_front(0)
    d.push_back(2)
    assert d.pop_front() == 0
    assert d.pop_back() == 2
    assert d.pop_back() == 1
    assert d.pop_back() is None


def test_ordered_list_priority():
    lst = OrderedList()
    lst.push_sorted(Item("lo", 1))
    lst.push_sorted(Item("hi", 10))
    lst.push_sorted(Item("mid", 5))
    assert lst.pop_front().value == "hi"
    assert lst.pop_front().value == "mid"
    assert lst.pop_front().value == "lo"


@pytest.mark.parametrize("cls", [Lifo, Fifo])
def test_queue_mt_stress(cls):
    """Every pushed item is popped exactly once (reference tests/class/lifo.c)."""
    q = cls()
    seen = [set() for _ in range(NTHREADS)]

    def worker(tid):
        for i in range(NITEMS):
            q.push((tid, i))
        got = None
        while True:
            got = q.pop()
            if got is None:
                break
            seen[tid].add(got)

    run_threads(NTHREADS, worker)
    # drain leftovers
    while True:
        got = q.pop()
        if got is None:
            break
        seen[0].add(got)
    all_seen = set().union(*seen)
    assert len(all_seen) == NTHREADS * NITEMS


def test_hash_table_basics():
    h = ConcurrentHashTable()
    h.insert(("tc", 1, 2), "v")
    assert h.find(("tc", 1, 2)) == "v"
    assert ("tc", 1, 2) in h
    assert h.remove(("tc", 1, 2)) == "v"
    assert h.find(("tc", 1, 2)) is None
    v, ins = h.find_or_insert("k", lambda: [0])
    assert ins and v == [0]
    v2, ins2 = h.find_or_insert("k", lambda: [1])
    assert not ins2 and v2 is v


def test_hash_table_mt(n=NTHREADS):
    """Concurrent find_or_insert yields exactly one value per key."""
    h = ConcurrentHashTable()
    winners = [[] for _ in range(n)]

    def worker(tid):
        for i in range(NITEMS):
            v, ins = h.find_or_insert(i % 101, lambda: object())
            winners[tid].append(v)

    run_threads(n, worker)
    # all threads must agree on the value for each key
    for i in range(101):
        agreed = {winners[t][j] for t in range(n)
                  for j in range(i, NITEMS, 101)}
        assert len(agreed) == 1


def test_hash_update_locked():
    h = ConcurrentHashTable()

    def worker(tid):
        for _ in range(NITEMS):
            h.update_locked("ctr", lambda v: v + 1, default=0)

    run_threads(NTHREADS, worker)
    assert h.find("ctr") == NTHREADS * NITEMS


def test_atomic_counter_mt():
    c = AtomicCounter()

    def worker(tid):
        for _ in range(NITEMS):
            c.add_and_fetch(1)

    run_threads(NTHREADS, worker)
    assert c.value == NTHREADS * NITEMS
    assert c.cas(c.value, 0)
    assert not c.cas(123456, 1)
    assert c.value == 0


def test_future_basic_and_callbacks():
    f = Future()
    hits = []
    f.on_ready(hits.append)
    assert not f.is_ready()
    f.set(42)
    assert f.is_ready() and f.get() == 42
    f.on_ready(hits.append)  # post-completion callback fires immediately
    assert hits == [42, 42]
    with pytest.raises(RuntimeError):
        f.set(1)


def test_future_blocking_get():
    f = Future()

    def setter():
        f.set("done")

    t = threading.Timer(0.05, setter)
    t.start()
    assert f.get(timeout=5) == "done"
    t.join()


def test_countdown_future():
    f = CountdownFuture(3, "fin")
    f.contribute(); f.contribute()
    assert not f.is_ready()
    f.contribute()
    assert f.get() == "fin"


def test_datacopy_future_triggers_once():
    """Reference tests/class/future_datacopy.c: one materialization, shared."""
    calls = []
    fut = DataCopyFuture(trigger=lambda spec: calls.append(spec) or spec * 2,
                         spec=21, nb_consumers=NTHREADS)
    results = []
    lock = threading.Lock()

    def worker(tid):
        v = fut.get_copy()
        with lock:
            results.append(v)
        fut.consume()

    run_threads(NTHREADS, worker)
    assert calls == [21]
    assert results == [42] * NTHREADS


def test_datacopy_future_cleanup_on_last_consumer():
    released = []
    fut = DataCopyFuture(trigger=lambda s: "copy", nb_consumers=2,
                         cleanup=released.append)
    assert fut.get_copy() == "copy"
    fut.consume()
    assert released == []
    fut.consume()
    assert released == ["copy"]


def test_rwlock():
    rw = RWLock()
    state = {"readers": 0, "max_readers": 0, "writes": 0}
    mx = threading.Lock()

    def reader(tid):
        for _ in range(200):
            with rw.read():
                with mx:
                    state["readers"] += 1
                    state["max_readers"] = max(state["max_readers"],
                                               state["readers"])
                with mx:
                    state["readers"] -= 1

    def writer(tid):
        for _ in range(50):
            with rw.write():
                assert state["readers"] == 0
                state["writes"] += 1

    threads = ([threading.Thread(target=reader, args=(i,)) for i in range(4)]
               + [threading.Thread(target=writer, args=(i,)) for i in range(2)])
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    assert state["writes"] == 100
    assert state["max_readers"] >= 1


def test_barrier():
    b = Barrier(NTHREADS)
    order = []
    lock = threading.Lock()

    def worker(tid):
        with lock:
            order.append(("pre", tid))
        b.wait()
        with lock:
            order.append(("post", tid))

    run_threads(NTHREADS, worker)
    pres = [i for i, (p, _) in enumerate(order) if p == "pre"]
    posts = [i for i, (p, _) in enumerate(order) if p == "post"]
    assert max(pres) < min(posts)


def test_dequeue_chain_front_preserves_order():
    d = Dequeue()
    d.push_back("tail")
    d.chain_front(["a", "b", "c"])
    assert d.pop_front() == "a"
    assert d.pop_front() == "b"
    assert d.pop_front() == "c"
    assert d.pop_front() == "tail"


def test_ordered_list_mixed_modes_no_inversion():
    lst = OrderedList()
    lst.push_back(Item("p10", 10))
    lst.push_front(Item("p1", 1))
    lst.push_sorted(Item("p5", 5))
    # sorted insertion lands before the first lower-priority item
    vals = [lst.pop_front().value for _ in range(3)]
    assert vals.index("p5") < vals.index("p1")


def test_hbbuffer_overflow_chain():
    """reference: parsec/hbbuffer.c — bounded pushes overflow to the
    parent store; pops drain local then parent; steal stays local."""
    from parsec_tpu.containers.lists import Dequeue, HBBuffer
    system = Dequeue()
    group = HBBuffer(capacity=2, parent=system)
    local = HBBuffer(capacity=2, parent=group)
    for i in range(7):
        local.push_back(i)
    assert len(local) == 2 and len(group) == 2 and len(system) == 3
    # pop drains local first, then walks up
    assert [local.pop_front() for _ in range(7)] == list(range(7))
    assert local.pop_front() is None
    # steal end never touches the parent
    local.push_back("a")
    group.push_back("g")
    assert local.pop_back() == "a"
    assert local.pop_back() is None and len(group) == 1
    # no parent: overflow is an error
    import pytest as _pytest
    lone = HBBuffer(capacity=1)
    lone.push_back(1)
    with _pytest.raises(OverflowError):
        lone.push_back(2)
