"""r14 C task-object core (schedext TaskCore/TaskVT/run_quantum):
native-vs-Python parity properties, batched-termdet semantics, the
coalesced worker doorbell, and the chaos kill with the C core active.

The parity property is the gate that matters: identical DAG results,
termdet final counts, PINS event counts, and lineage-ring contents
under both ``PARSEC_MCA_SCHED_NATIVE`` settings — a fast path that
drops an event or a count is a regression no throughput number can
excuse."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from parsec_tpu import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg import DATA, IN, NEW, OUT, PTG, Range, TASK
from parsec_tpu.native import load_schedext
from parsec_tpu.utils.mca import params

se = load_schedext()

pytestmark = pytest.mark.skipif(se is None,
                                reason="schedext did not build")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_EVENTS = ("select", "exec_begin", "exec_end", "complete_exec",
           "task_discard")


def _bail_delta(before):
    after = se.bailout_stats()
    return {k: after[k] - before.get(k, 0) for k in after
            if after[k] - before.get(k, 0)}


def _mixed_run(native: int):
    """One mixed DAG — a trivial CTL class (the C chain's r14 fast
    path) plus an RW data chain (the r17 EXTENDED chain: FromDesc
    binding, FromTask inputs, local ToTask delivery walks all C-side)
    — returning every observable the parity property compares.

    ICI is disabled for BOTH legs: the conftest's virtual 8-device
    mesh attaches an IciEngine, whose deferred-placement walk rides
    release_deps and (correctly) gates the extended chain off — this
    property is about the chain, so make it eligible."""
    params.set("sched_native", native)
    params.set("comm_ici_enabled", 0)
    try:
        order = []
        events = []       # list.append is GIL-atomic across workers
        A = VectorTwoDimCyclic(1, 1).from_array(
            np.zeros(1, np.float32))
        NE, NB = 40, 6

        def chain_body(T, k):
            order.append(k)
            T += 1.0

        g = PTG("parity", NE=NE, NB=NB)
        g.task("E", i=Range(0, NE - 1)).flow("x", "CTL") \
            .body(lambda: None)
        g.task("S", k=Range(0, NB - 1)) \
            .affinity(lambda k: A(0)) \
            .flow("T", "RW",
                  IN(DATA(lambda k: A(0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                      when=lambda k, NB=NB: k < NB - 1)) \
            .body(chain_body)
        tp = g.build()
        bail0 = se.bailout_stats()
        with Context(nb_cores=2) as ctx:
            assert (ctx.scheduler.name == "native") == bool(native)
            for ev in _EVENTS:
                ctx.pins_register(
                    ev, lambda es, e, t: events.append(e))
            ctx.add_taskpool(tp)
            ctx.wait(timeout=30)
        counts = {ev: events.count(ev) for ev in _EVENTS}
        val = float(np.asarray(A(0).resolve().copy_on(0).payload)[0])
        return {"order": order, "value": val, "counts": counts,
                "nb_tasks": tp.nb_tasks,
                "pending": tp.nb_pending_actions,
                "total": NE + NB, "bailouts": _bail_delta(bail0)}
    finally:
        params.unset("comm_ici_enabled")
        params.unset("sched_native")


def test_native_vs_python_parity_property():
    nat = _mixed_run(1)
    py = _mixed_run(0)
    # identical DAG results and execution order on the serialized chain
    assert nat["value"] == py["value"] == 6.0
    assert nat["order"] == py["order"] == list(range(6))
    # termdet final counts drained to zero on both paths
    assert nat["nb_tasks"] == py["nb_tasks"] == 0
    assert nat["pending"] == py["pending"] == 0
    # PINS event counts: every event fires exactly once per task on
    # BOTH paths (the C quantum dispatches the same five hooks)
    assert nat["counts"] == py["counts"]
    assert nat["counts"]["select"] == nat["total"]
    assert nat["counts"]["complete_exec"] == nat["total"]
    assert nat["counts"]["exec_begin"] == nat["total"]
    assert nat["counts"]["exec_end"] == nat["total"]
    assert nat["counts"]["task_discard"] == 0
    # r17: the RW chain is C-chain-covered end to end — not one task
    # fell back to Python (the coverage property the bailout counters
    # exist to gate)
    assert nat["bailouts"] == {}


def _lineage_run(native: int):
    """Recovery-armed single-rank chain: the lineage ring must record
    the same completions (keys, read/write versions) under both knob
    settings — with lineage installed the C chain defers to the Python
    completion path, and THAT is the property (recorded lineage can
    never silently thin out because the fast path got faster)."""
    params.set("sched_native", native)
    params.set("recovery_enable", 1)
    try:
        A = VectorTwoDimCyclic(1, 1).from_array(
            np.zeros(1, np.float32))
        NB = 5
        g = PTG("lin", NB=NB)
        g.task("S", k=Range(0, NB - 1)) \
            .affinity(lambda k: A(0)) \
            .flow("T", "RW",
                  IN(DATA(lambda k: A(0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                      when=lambda k, NB=NB: k < NB - 1),
                  OUT(DATA(lambda k: A(0)))) \
            .body(lambda T, k: T.__iadd__(1.0) and None)
        tp = g.build()
        tp.recovery_collections = [A]
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=30)
        lin = tp._lineage
        assert lin is not None, "lineage plane not installed"
        recs = sorted(
            (r.key, tuple(sorted((f, v) for f, (_, v) in r.rmap.items())),
             tuple(sorted((f, v) for f, (_, v) in r.wmap.items())))
            for r in lin.records)
        return recs
    finally:
        params.unset("recovery_enable")
        params.unset("sched_native")


def test_lineage_ring_parity():
    assert _lineage_run(1) == _lineage_run(0)


def _new_binding_run(native: int):
    """NEW-arena scratch binding through the extended chain: MAKE binds
    a fresh arena block (CK_NEW), fills it, and hands it to USE over a
    ToTask edge; USE folds it into a FromDesc-bound RW tile in place.
    Every binding kind the r17 prepare covers, in one DAG.  ICI off as
    in ``_mixed_run`` (the virtual test mesh would gate the chain)."""
    params.set("sched_native", native)
    params.set("comm_ici_enabled", 0)
    try:
        NI = 4
        A = VectorTwoDimCyclic(1, NI).from_array(
            np.zeros(NI, np.float32))
        g = PTG("newbind", NI=NI)
        g.arena("tmp", (2,))
        g.task("MAKE", i=Range(0, NI - 1)) \
            .affinity(lambda i: A(i)) \
            .flow("W", "WRITE",
                  IN(NEW("tmp")),
                  OUT(TASK("USE", "W", lambda i: dict(i=i)))) \
            .body(lambda W: np.full_like(W, 3.0))
        g.task("USE", i=Range(0, NI - 1)) \
            .affinity(lambda i: A(i)) \
            .flow("W", "READ",
                  IN(TASK("MAKE", "W", lambda i: dict(i=i)))) \
            .flow("T", "RW", IN(DATA(lambda i: A(i)))) \
            .body(lambda W, T: T.__iadd__(float(np.sum(W))) and None)
        tp = g.build()
        bail0 = se.bailout_stats()
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=30)
        vals = [float(np.asarray(A(i).resolve().copy_on(0).payload)[0])
                for i in range(NI)]
        return {"vals": vals, "nb_tasks": tp.nb_tasks,
                "pending": tp.nb_pending_actions,
                "bailouts": _bail_delta(bail0)}
    finally:
        params.unset("comm_ici_enabled")
        params.unset("sched_native")


def test_new_arena_binding_parity():
    nat = _new_binding_run(1)
    py = _new_binding_run(0)
    assert nat["vals"] == py["vals"] == [6.0] * 4
    assert nat["nb_tasks"] == py["nb_tasks"] == 0
    assert nat["pending"] == py["pending"] == 0
    assert nat["bailouts"] == {}


def _shm_mix_worker(ctx, rank, nranks):
    """Per-rank body of the 2-rank interleave property: a cross-rank
    RW chain (remote activations, Python path by design), a rank-LOCAL
    RW chain and trivial CTL tasks (both C-chain-eligible even with
    the RemoteDepEngine attached — r17 comm-attached fast-complete),
    all in one taskpool."""
    import numpy as np
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    from parsec_tpu.native import load_schedext
    se_ = load_schedext()
    NT, NB, NE = 8, 6, 24
    V = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    L = VectorTwoDimCyclic(mb=1, lm=nranks, nodes=nranks, myrank=rank,
                           name="L")
    for m, _ in L.local_tiles():
        L.data_of(m).copy_on(0).payload[:] = 0.0
    events = []
    g = PTG("mix", NT=NT, NB=NB, NE=NE)
    g.task("E", i=Range(0, NE - 1)) \
        .affinity(lambda i, L=L, nr=nranks: L(i % nr)) \
        .flow("x", "CTL").body(lambda: None)
    g.task("S", c=Range(0, nranks - 1), k=Range(0, NB - 1)) \
        .affinity(lambda c, k, L=L: L(c)) \
        .flow("T", "RW",
              IN(DATA(lambda c, k, L=L: L(c)), when=lambda c, k: k == 0),
              IN(TASK("S", "T", lambda c, k: dict(c=c, k=k - 1)),
                 when=lambda c, k: k > 0),
              OUT(TASK("S", "T", lambda c, k: dict(c=c, k=k + 1)),
                  when=lambda c, k, NB=NB: k < NB - 1)) \
        .body(lambda T: T + 1.0)
    g.task("R", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("R", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("R", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    tp = g.build()
    for ev in ("select", "exec_begin", "exec_end", "complete_exec",
               "task_discard"):
        ctx.pins_register(ev, lambda es, e, t: events.append(e))
    bail0 = dict(se_.bailout_stats()) if se_ else {}
    ctx.add_taskpool(tp)
    ctx.wait(timeout=60)
    bail = {}
    if se_ is not None:
        after = se_.bailout_stats()
        bail = {k: after[k] - bail0.get(k, 0) for k in after
                if after[k] - bail0.get(k, 0)}
    cross = {m: float(np.asarray(
        V.data_of(m).pull_to_host().payload)[0])
        for m, _ in V.local_tiles()}
    local = {m: float(np.asarray(L.data_of(m).copy_on(0).payload)[0])
             for m, _ in L.local_tiles()}
    counts = {ev: events.count(ev) for ev in set(events)} \
        if events else {}
    return {"cross": cross, "local": local, "counts": counts,
            "nb_tasks": tp.nb_tasks, "pending": tp.nb_pending_actions,
            "bailouts": bail,
            "native": 1 if ctx.scheduler.name == "native" else 0}


def _shm_mix(native: int):
    from parsec_tpu.comm.launch import run_distributed
    env = {"PARSEC_MCA_SCHED_NATIVE": str(native),
           "PARSEC_MCA_COMM_TRANSPORT": "shm",
           # the conftest's 8-device virtual mesh would attach an
           # IciEngine in the children and gate the extended chain
           "PARSEC_MCA_COMM_ICI_ENABLED": "0"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return run_distributed(_shm_mix_worker, 2, timeout=120)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_two_rank_shm_fast_complete_interleave():
    """Comm-attached fast-complete under real remote traffic: local
    trivial + local data-chain tasks ride the C chain while the
    cross-rank chain's activations interleave through the shm
    transport — identical results, per-rank PINS counts, and termdet
    finals vs the Python path, and the ONLY bailouts on the native
    legs are the cross-rank chain's own (plan-time comm_buffered /
    writeback), never the local classes'."""
    nat = _shm_mix(1)
    py = _shm_mix(0)
    for r in range(2):
        assert nat[r]["native"] == 1 and py[r]["native"] == 0
        # identical DAG results per rank
        assert nat[r]["cross"] == py[r]["cross"]
        assert nat[r]["local"] == py[r]["local"]
        # local chains accumulated NB increments in place
        assert list(nat[r]["local"].values()) == [6.0]
        # per-rank PINS parity and drained termdet on both paths
        assert nat[r]["counts"] == py[r]["counts"]
        assert nat[r]["nb_tasks"] == py[r]["nb_tasks"] == 0
        assert nat[r]["pending"] == py[r]["pending"] == 0
        # the ONLY tasks that left the C chain are the 4 cross-rank R
        # tasks this rank owns: a remote ToTask successor bails at
        # plan time (comm_buffered), the final writeback task bails
        # statically — the 24/2 E and 6 S tasks contributed ZERO,
        # which is the comm-attached fast-complete property
        bail = nat[r]["bailouts"]
        assert sum(bail.values()) == 4, bail
        assert bail.get("comm_buffered", 0) >= 3, bail
    # cross-rank chain value: tile k ends at k+1, merged across ranks
    merged = {}
    for r in nat:
        merged.update(r["cross"])
    assert merged == {k: float(k + 1) for k in range(8)}


def test_taskcore_object_contract():
    """vt.build_one's TaskCore matches Task field-for-field for the
    attributes every runtime layer reads, shares the process-global
    seq counter, and reprs identically."""
    from parsec_tpu.core.task import Task, TaskClass
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    params.set("sched_native", 1)
    try:
        tp = ParameterizedTaskpool("tc-contract")
        tp.priority = 7
        tc = tp.add_task_class(TaskClass(
            "C", params=[("i", lambda g, l: range(4))],
            priority=lambda loc: loc["i"] * 10,
            body=lambda es, task: None))
        vt = tc.native_vt()
        assert vt is not None and vt.trivial
        ct = vt.build_one({"i": 3})
        pt = Task(tc, tp, {"i": 3})
        assert type(ct) is se.TaskCore
        assert ct.key == pt.key == ("C", 3)
        assert ct.priority == pt.priority == 37   # class prio + pool bias
        assert ct.locals == pt.locals
        assert ct.status == 0 and ct.chore_mask == 0xFFFF
        assert ct.data == {} and ct.input_sources == {}
        assert ct.pinned_flows == set()
        assert ct.ready_at is None and ct.mtr_t0 is None
        assert ct.pool_epoch == 0 and ct.retries == 0
        assert repr(ct) == repr(pt) == "C(i=3)"
        # one process-global sequence: C- and Python-constructed tasks
        # interleave monotonically (lineage orders by seq)
        assert pt.seq == ct.seq + 1
        b = vt.build_range("i", 0, 4, 1)
        assert [t.key for t in b] == [("C", i) for i in range(4)]
        assert [t.priority for t in b] == [7, 17, 27, 37]
    finally:
        params.unset("sched_native")


def test_nontrivial_class_has_no_trivial_vtable():
    """Data flows keep a class off the TRIVIAL chain, but a single-cpu
    class with binding-table-coverable flows is extended-chain
    (cchain) eligible since r17; multiple incarnations keep a class
    off both chains (construction stays)."""
    from parsec_tpu.core.task import (Dep, FromDesc, RW, TaskClass)
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    params.set("sched_native", 1)
    try:
        tp = ParameterizedTaskpool("vt-gate")
        tc = tp.add_task_class(TaskClass(
            "D", params=[("i", lambda g, l: range(2))],
            flows=[RW("T", inputs=[Dep(FromDesc(lambda loc: None))])],
            body=lambda es, task: None))
        vt = tc.native_vt()
        assert vt is not None and not vt.trivial
        assert vt.cchain == 1
        tc2 = tp.add_task_class(TaskClass(
            "D2", params=[("i", lambda g, l: range(2))],
            incarnations=[("cpu", lambda es, task: None),
                          ("tpu", lambda es, task: None)]))
        vt2 = tc2.native_vt()
        assert vt2 is None or (not vt2.trivial and vt2.cchain == 0)
    finally:
        params.unset("sched_native")


def test_invalid_hook_return_is_contained_on_native_path():
    """A trivial body returning an int that is no HookReturn code must
    become a CONTAINED task failure on the C chain, exactly like the
    Python chain — not a ValueError escaping run_quantum that kills
    the worker thread and hangs the run with zero recorded errors
    (the review-round repro)."""
    import re
    from parsec_tpu.core.task import TaskClass
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    for native in (1, 0):
        params.set("sched_native", native)
        try:
            # raw incarnation hook (no PTG value-normalizing wrapper):
            # its return IS treated as a lifecycle code
            tp = ParameterizedTaskpool("badret")
            tp.add_task_class(TaskClass(
                "B", params=[("i", lambda g_, l: range(4))],
                properties={"idempotent": False},
                incarnations=[("cpu", lambda es, task: 7)]))
            with Context(nb_cores=2) as ctx:
                ctx.add_taskpool(tp)
                with pytest.raises(RuntimeError,
                                   match=re.escape("task B(")):
                    ctx.wait(timeout=15)
        finally:
            params.unset("sched_native")


def test_batched_termdet_epoch_fence():
    """A torn-generation batch flush drops under the termdet lock
    instead of corrupting the re-counted pool (the recovery rewind
    contract for accumulated decrements)."""
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.core.termdet import LocalTermdet, TermdetState
    tp = Taskpool("fence")
    td = LocalTermdet()
    fired = []
    td.monitor(tp, lambda: fired.append(1))
    td.taskpool_addto_nb_tasks(tp, 5)
    # matching epoch applies
    assert td.taskpool_addto_nb_tasks(tp, -2, epoch=tp.run_epoch) == 3
    # a restart bumped the generation: the stale batch drops whole
    tp.run_epoch += 1
    assert td.taskpool_addto_nb_tasks(tp, -3, epoch=0) == 3
    assert tp.nb_tasks == 3
    # current-generation flushes keep applying
    assert td.taskpool_addto_nb_tasks(tp, -3, epoch=1) == 0
    assert not fired   # NOT_READY: no termination fired


def test_doorbell_suppression_no_lost_wakeup():
    """ring_doorbell skips the condvar entirely while no worker has
    raised its waiting flag, and the probe-under-lock discipline means
    a push racing the flag is never lost: N sequential waves complete
    with the coalesced doorbell counted."""
    done = []
    g = PTG("db", N=64)
    g.task("E", i=Range(0, 63)).flow("x", "CTL") \
        .body(lambda: done.append(1))
    with Context(nb_cores=2) as ctx:
        for _ in range(5):
            p = PTG("dbw", N=64)
            p.task("E", i=Range(0, 63)).flow("x", "CTL") \
                .body(lambda: done.append(1))
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=20)
        # idle workers park with their waiting flag raised; the flag
        # count can never exceed the worker population
        assert 0 <= ctx._db_waiters <= ctx.nb_cores
    assert len(done) == 5 * 64


@pytest.mark.slow
def test_chaos_kill_with_c_task_core_active():
    """A mid-run rank kill with the C task core explicitly active —
    including the r17 extended chain, which sched_native=1 arms: the
    recover catalog's minimal-replay case must still pass (lineage
    recorded from completions while sched_native=1 — BOTH C chains'
    lineage gate defers those pools to the recording path)."""
    env = dict(os.environ)
    env["PARSEC_MCA_SCHED_NATIVE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--only", "kill-minimal-recover", "--seeds", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASS" in r.stdout
