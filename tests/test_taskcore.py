"""r14 C task-object core (schedext TaskCore/TaskVT/run_quantum):
native-vs-Python parity properties, batched-termdet semantics, the
coalesced worker doorbell, and the chaos kill with the C core active.

The parity property is the gate that matters: identical DAG results,
termdet final counts, PINS event counts, and lineage-ring contents
under both ``PARSEC_MCA_SCHED_NATIVE`` settings — a fast path that
drops an event or a count is a regression no throughput number can
excuse."""

import os
import subprocess
import sys
import threading

import numpy as np
import pytest

from parsec_tpu import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.native import load_schedext
from parsec_tpu.utils.mca import params

se = load_schedext()

pytestmark = pytest.mark.skipif(se is None,
                                reason="schedext did not build")

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))

_EVENTS = ("select", "exec_begin", "exec_end", "complete_exec",
           "task_discard")


def _mixed_run(native: int):
    """One mixed DAG — a trivial CTL class (the C chain's fast path)
    plus an RW data chain (the Python fallback path) — returning every
    observable the parity property compares."""
    params.set("sched_native", native)
    try:
        order = []
        events = []       # list.append is GIL-atomic across workers
        A = VectorTwoDimCyclic(1, 1).from_array(
            np.zeros(1, np.float32))
        NE, NB = 40, 6

        def chain_body(T, k):
            order.append(k)
            T += 1.0

        g = PTG("parity", NE=NE, NB=NB)
        g.task("E", i=Range(0, NE - 1)).flow("x", "CTL") \
            .body(lambda: None)
        g.task("S", k=Range(0, NB - 1)) \
            .affinity(lambda k: A(0)) \
            .flow("T", "RW",
                  IN(DATA(lambda k: A(0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                      when=lambda k, NB=NB: k < NB - 1)) \
            .body(chain_body)
        tp = g.build()
        with Context(nb_cores=2) as ctx:
            assert (ctx.scheduler.name == "native") == bool(native)
            for ev in _EVENTS:
                ctx.pins_register(
                    ev, lambda es, e, t: events.append(e))
            ctx.add_taskpool(tp)
            ctx.wait(timeout=30)
        counts = {ev: events.count(ev) for ev in _EVENTS}
        val = float(np.asarray(A(0).resolve().copy_on(0).payload)[0])
        return {"order": order, "value": val, "counts": counts,
                "nb_tasks": tp.nb_tasks,
                "pending": tp.nb_pending_actions,
                "total": NE + NB}
    finally:
        params.unset("sched_native")


def test_native_vs_python_parity_property():
    nat = _mixed_run(1)
    py = _mixed_run(0)
    # identical DAG results and execution order on the serialized chain
    assert nat["value"] == py["value"] == 6.0
    assert nat["order"] == py["order"] == list(range(6))
    # termdet final counts drained to zero on both paths
    assert nat["nb_tasks"] == py["nb_tasks"] == 0
    assert nat["pending"] == py["pending"] == 0
    # PINS event counts: every event fires exactly once per task on
    # BOTH paths (the C quantum dispatches the same five hooks)
    assert nat["counts"] == py["counts"]
    assert nat["counts"]["select"] == nat["total"]
    assert nat["counts"]["complete_exec"] == nat["total"]
    assert nat["counts"]["exec_begin"] == nat["total"]
    assert nat["counts"]["exec_end"] == nat["total"]
    assert nat["counts"]["task_discard"] == 0


def _lineage_run(native: int):
    """Recovery-armed single-rank chain: the lineage ring must record
    the same completions (keys, read/write versions) under both knob
    settings — with lineage installed the C chain defers to the Python
    completion path, and THAT is the property (recorded lineage can
    never silently thin out because the fast path got faster)."""
    params.set("sched_native", native)
    params.set("recovery_enable", 1)
    try:
        A = VectorTwoDimCyclic(1, 1).from_array(
            np.zeros(1, np.float32))
        NB = 5
        g = PTG("lin", NB=NB)
        g.task("S", k=Range(0, NB - 1)) \
            .affinity(lambda k: A(0)) \
            .flow("T", "RW",
                  IN(DATA(lambda k: A(0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                      when=lambda k, NB=NB: k < NB - 1),
                  OUT(DATA(lambda k: A(0)))) \
            .body(lambda T, k: T.__iadd__(1.0) and None)
        tp = g.build()
        tp.recovery_collections = [A]
        with Context(nb_cores=2) as ctx:
            ctx.add_taskpool(tp)
            ctx.wait(timeout=30)
        lin = tp._lineage
        assert lin is not None, "lineage plane not installed"
        recs = sorted(
            (r.key, tuple(sorted((f, v) for f, (_, v) in r.rmap.items())),
             tuple(sorted((f, v) for f, (_, v) in r.wmap.items())))
            for r in lin.records)
        return recs
    finally:
        params.unset("recovery_enable")
        params.unset("sched_native")


def test_lineage_ring_parity():
    assert _lineage_run(1) == _lineage_run(0)


def test_taskcore_object_contract():
    """vt.build_one's TaskCore matches Task field-for-field for the
    attributes every runtime layer reads, shares the process-global
    seq counter, and reprs identically."""
    from parsec_tpu.core.task import Task, TaskClass
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    params.set("sched_native", 1)
    try:
        tp = ParameterizedTaskpool("tc-contract")
        tp.priority = 7
        tc = tp.add_task_class(TaskClass(
            "C", params=[("i", lambda g, l: range(4))],
            priority=lambda loc: loc["i"] * 10,
            body=lambda es, task: None))
        vt = tc.native_vt()
        assert vt is not None and vt.trivial
        ct = vt.build_one({"i": 3})
        pt = Task(tc, tp, {"i": 3})
        assert type(ct) is se.TaskCore
        assert ct.key == pt.key == ("C", 3)
        assert ct.priority == pt.priority == 37   # class prio + pool bias
        assert ct.locals == pt.locals
        assert ct.status == 0 and ct.chore_mask == 0xFFFF
        assert ct.data == {} and ct.input_sources == {}
        assert ct.pinned_flows == set()
        assert ct.ready_at is None and ct.mtr_t0 is None
        assert ct.pool_epoch == 0 and ct.retries == 0
        assert repr(ct) == repr(pt) == "C(i=3)"
        # one process-global sequence: C- and Python-constructed tasks
        # interleave monotonically (lineage orders by seq)
        assert pt.seq == ct.seq + 1
        b = vt.build_range("i", 0, 4, 1)
        assert [t.key for t in b] == [("C", i) for i in range(4)]
        assert [t.priority for t in b] == [7, 17, 27, 37]
    finally:
        params.unset("sched_native")


def test_nontrivial_class_has_no_trivial_vtable():
    """Data flows, multiple incarnations, or a DTD release hook must
    keep the class off the C progress chain (construction stays)."""
    from parsec_tpu.core.task import (Dep, FromDesc, RW, TaskClass)
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    params.set("sched_native", 1)
    try:
        tp = ParameterizedTaskpool("vt-gate")
        tc = tp.add_task_class(TaskClass(
            "D", params=[("i", lambda g, l: range(2))],
            flows=[RW("T", inputs=[Dep(FromDesc(lambda loc: None))])],
            body=lambda es, task: None))
        vt = tc.native_vt()
        assert vt is not None and not vt.trivial
    finally:
        params.unset("sched_native")


def test_invalid_hook_return_is_contained_on_native_path():
    """A trivial body returning an int that is no HookReturn code must
    become a CONTAINED task failure on the C chain, exactly like the
    Python chain — not a ValueError escaping run_quantum that kills
    the worker thread and hangs the run with zero recorded errors
    (the review-round repro)."""
    import re
    from parsec_tpu.core.task import TaskClass
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    for native in (1, 0):
        params.set("sched_native", native)
        try:
            # raw incarnation hook (no PTG value-normalizing wrapper):
            # its return IS treated as a lifecycle code
            tp = ParameterizedTaskpool("badret")
            tp.add_task_class(TaskClass(
                "B", params=[("i", lambda g_, l: range(4))],
                properties={"idempotent": False},
                incarnations=[("cpu", lambda es, task: 7)]))
            with Context(nb_cores=2) as ctx:
                ctx.add_taskpool(tp)
                with pytest.raises(RuntimeError,
                                   match=re.escape("task B(")):
                    ctx.wait(timeout=15)
        finally:
            params.unset("sched_native")


def test_batched_termdet_epoch_fence():
    """A torn-generation batch flush drops under the termdet lock
    instead of corrupting the re-counted pool (the recovery rewind
    contract for accumulated decrements)."""
    from parsec_tpu.core.taskpool import Taskpool
    from parsec_tpu.core.termdet import LocalTermdet, TermdetState
    tp = Taskpool("fence")
    td = LocalTermdet()
    fired = []
    td.monitor(tp, lambda: fired.append(1))
    td.taskpool_addto_nb_tasks(tp, 5)
    # matching epoch applies
    assert td.taskpool_addto_nb_tasks(tp, -2, epoch=tp.run_epoch) == 3
    # a restart bumped the generation: the stale batch drops whole
    tp.run_epoch += 1
    assert td.taskpool_addto_nb_tasks(tp, -3, epoch=0) == 3
    assert tp.nb_tasks == 3
    # current-generation flushes keep applying
    assert td.taskpool_addto_nb_tasks(tp, -3, epoch=1) == 0
    assert not fired   # NOT_READY: no termination fired


def test_doorbell_suppression_no_lost_wakeup():
    """ring_doorbell skips the condvar entirely while no worker has
    raised its waiting flag, and the probe-under-lock discipline means
    a push racing the flag is never lost: N sequential waves complete
    with the coalesced doorbell counted."""
    done = []
    g = PTG("db", N=64)
    g.task("E", i=Range(0, 63)).flow("x", "CTL") \
        .body(lambda: done.append(1))
    with Context(nb_cores=2) as ctx:
        for _ in range(5):
            p = PTG("dbw", N=64)
            p.task("E", i=Range(0, 63)).flow("x", "CTL") \
                .body(lambda: done.append(1))
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=20)
        # idle workers park with their waiting flag raised; the flag
        # count can never exceed the worker population
        assert 0 <= ctx._db_waiters <= ctx.nb_cores
    assert len(done) == 5 * 64


@pytest.mark.slow
def test_chaos_kill_with_c_task_core_active():
    """A mid-run rank kill with the C task core explicitly active: the
    recover catalog's minimal-replay case must still pass (lineage
    recorded from completions while sched_native=1 — the C chain's
    lineage gate defers those pools to the recording path)."""
    env = dict(os.environ)
    env["PARSEC_MCA_SCHED_NATIVE"] = "1"
    env["JAX_PLATFORMS"] = "cpu"
    r = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--only", "kill-minimal-recover", "--seeds", "1"],
        cwd=REPO, env=env, capture_output=True, text=True, timeout=300)
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASS" in r.stdout
