"""Checkpoint/restore tests (SURVEY §5.4: the reference has none; the
TPU build snapshots collections after a quiesce — flush + termdet — and
restores them byte-exact, single-rank and collectively)."""

import numpy as np
import pytest

from parsec_tpu.comm.launch import run_distributed
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.utils.checkpoint import checkpoint, restore


def _inc_pool(V, NT):
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    p = PTG("inc", NT=NT)
    p.task("T", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "RW",
              IN(DATA(lambda k, V=V: V(k))),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda X: X + 1.0)
    return p.build()


def test_checkpoint_roundtrip_mid_computation(tmp_path):
    """Run a step, checkpoint, run more steps, restore — the state is
    byte-exact back at the checkpoint and the DAG resumes from there."""
    NT = 4
    V = VectorTwoDimCyclic(mb=8, lm=8 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(_inc_pool(V, NT))
        ctx.wait(timeout=60)
        path = checkpoint(ctx, [V], str(tmp_path / "ck"))
        # diverge: two more steps
        for _ in range(2):
            ctx.add_taskpool(_inc_pool(V, NT))
            ctx.wait(timeout=60)
        for m in range(NT):
            np.testing.assert_allclose(
                np.asarray(V.data_of(m).pull_to_host().payload), m + 3.0)
        # rewind and resume
        assert restore(ctx, [V], str(tmp_path / "ck")) == NT
        for m in range(NT):
            np.testing.assert_allclose(
                np.asarray(V.data_of(m).pull_to_host().payload), m + 1.0)
        ctx.add_taskpool(_inc_pool(V, NT))
        ctx.wait(timeout=60)
    for m in range(NT):
        np.testing.assert_allclose(
            np.asarray(V.data_of(m).pull_to_host().payload), m + 2.0)
    assert path.endswith(".r0.npz")


def test_checkpoint_device_state_flushes_home(tmp_path):
    """Tiles resident on the accelerator at checkpoint time land in the
    snapshot (the flush half of the quiesce contract)."""
    from parsec_tpu.apps.gemm import gemm_taskpool
    rng = np.random.default_rng(4)
    n, mb = 64, 32
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A").from_array(a)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="B").from_array(b)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="C").from_array(
        np.zeros((n, n), np.float32))
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(gemm_taskpool(A, B, C, device="tpu"))
        ctx.wait(timeout=120)
        checkpoint(ctx, [C], str(tmp_path / "gemm"))
        # wreck the host state, restore, verify
        for m, nn in C.local_tiles():
            np.asarray(C.data_of(m, nn).pull_to_host().payload)[:] = -1.0
        restore(ctx, [C], str(tmp_path / "gemm"))
    np.testing.assert_allclose(C.to_array(), a @ b, rtol=1e-3, atol=1e-3)


def test_restore_rejects_mismatched_layout(tmp_path):
    V = VectorTwoDimCyclic(mb=4, lm=8)
    with Context(nb_cores=1) as ctx:
        checkpoint(ctx, [V], str(tmp_path / "x"))
        import numpy as np_
        # forge a wrong-nranks meta
        src = str(tmp_path / "x") + ".r0.npz"
        data = dict(np_.load(src, allow_pickle=False))
        data["__meta__"] = np_.array([1, 0, 4])
        np_.savez(src.replace(".npz", ""), **data)
        with pytest.raises(ValueError, match="4 ranks"):
            restore(ctx, [V], str(tmp_path / "x"))


def _dist_ckpt(ctx, rank, nranks, path):
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks * 2, nodes=nranks,
                           myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 10.0 * rank + m
    checkpoint(ctx, [V], path)
    for m, _ in V.local_tiles():
        np.asarray(V.data_of(m).pull_to_host().payload)[:] = -5.0
    restore(ctx, [V], path)
    for m, _ in V.local_tiles():
        np.testing.assert_allclose(
            np.asarray(V.data_of(m).pull_to_host().payload),
            10.0 * rank + m)
    return "ok"


def test_checkpoint_distributed(tmp_path):
    path = str(tmp_path / "dck")
    assert run_distributed(_dist_ckpt, 3, args=(path,)) == ["ok"] * 3
