"""Native C++ runtime core tests (reference: the container unit tests of
tests/class/ — lifo.c, hash.c, atomics.c multithreaded stress — applied
to the ctypes-bound C++ primitives, plus parity with their Python twins
and end-to-end runtime use)."""

import threading

import numpy as np
import pytest

native = pytest.importorskip("parsec_tpu.native")

pytestmark = pytest.mark.skipif(not native.available(),
                                reason="native core did not build")


def test_dequeue_order_and_identity():
    d = native.NativeDequeue()
    objs = [object() for _ in range(8)]
    for o in objs:
        d.push_back(o)
    assert len(d) == 8
    assert d.pop_front() is objs[0]
    assert d.pop_back() is objs[-1]
    d.push_front(objs[0])
    assert d.pop_front() is objs[0]


def test_dequeue_same_object_twice():
    d = native.NativeDequeue()
    o = object()
    d.push_back(o)
    d.push_back(o)
    assert d.pop_front() is o and d.pop_front() is o and d.pop_front() is None


def test_dequeue_mpmc_stress():
    """Multithreaded producers/consumers: nothing lost, nothing doubled
    (the tests/class/lifo.c pattern)."""
    d = native.NativeDequeue()
    N, NPROD = 2000, 4
    seen = []
    seen_lock = threading.Lock()
    done = threading.Event()

    def produce(base):
        for i in range(N):
            d.push_back(base + i)

    def consume():
        while not (done.is_set() and len(d) == 0):
            v = d.pop_front()
            if v is not None:
                with seen_lock:
                    seen.append(v)

    cons = [threading.Thread(target=consume) for _ in range(3)]
    for c in cons:
        c.start()
    prods = [threading.Thread(target=produce, args=(k * N,))
             for k in range(NPROD)]
    for p in prods:
        p.start()
    for p in prods:
        p.join()
    done.set()
    for c in cons:
        c.join(timeout=30)
    assert sorted(seen) == sorted(k * N + i
                                  for k in range(NPROD) for i in range(N))


def test_zone_parity_with_python():
    """The native allocator mirrors ZoneAllocator semantics exactly."""
    from parsec_tpu.utils.zone_alloc import ZoneAllocator
    py = ZoneAllocator(8192, 512)
    cc = native.NativeZoneAllocator(8192, 512)
    offs_py, offs_cc = [], []
    for nbytes in (100, 512, 1024, 2048, 513):
        offs_py.append(py.malloc(nbytes))
        offs_cc.append(cc.malloc(nbytes))
    assert offs_py == offs_cc
    # free middle, realloc into the hole, coalesce checks
    py.free(offs_py[2]); cc.free(offs_cc[2])
    assert py.malloc(700) == cc.malloc(700)
    assert py.used_bytes() == cc.used_bytes()
    assert py.free_bytes() == cc.free_bytes()
    with pytest.raises(ValueError):
        cc.free(offs_cc[2] + 1 * 512 * 100)   # never-allocated offset


def test_zone_exhaustion_and_defrag():
    z = native.NativeZoneAllocator(2048, 512)
    offs = [z.malloc(512) for _ in range(4)]
    assert None not in offs
    assert z.malloc(1) is None
    for o in offs:
        z.free(o)
    assert z.check_defrag()
    assert z.malloc(2048) == 0


def test_trace_buffer_drain():
    t = native.NativeTraceBuffer()
    for i in range(100):
        t.event(i, i & 3, 1, i, 0, float(i))
    assert len(t) == 100
    evs = t.drain()
    assert evs[0] == (0, 0, 1, 0, 0, 0.0)
    assert evs[99] == (99, 3, 1, 99, 0, 99.0)
    assert t.drain(start=98) == evs[98:]


def test_runtime_on_native_queues():
    """A full PTG run with native system queues + native HBM zone budget
    produces correct numerics (the integration seam)."""
    from parsec_tpu.apps.gemm import gemm_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.utils.mca import params

    rng = np.random.default_rng(9)
    n, mb = 64, 16
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A").from_array(a)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="B").from_array(b)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="C").from_array(
        np.zeros((n, n), np.float32))
    params.set("device_mem_mb", 1)
    params.set("native_queues", 1)
    try:
        with Context(nb_cores=2, scheduler="gd") as ctx:
            assert type(ctx.scheduler._q).__name__ == "NativeDequeue"
            ctx.add_taskpool(gemm_taskpool(A, B, C, device="tpu"))
            ctx.wait(timeout=120)
    finally:
        params.unset("device_mem_mb")
        params.unset("native_queues")
    np.testing.assert_allclose(C.to_array(), a @ b, rtol=1e-3, atol=1e-3)


def test_native_trace_merges_with_info_events(tmp_path):
    """StreamBuffer routes info-less events through the native buffer and
    merges both sources in timestamp order at dump."""
    from parsec_tpu.prof import profiling
    from parsec_tpu.prof.reader import read_trace

    prof = profiling.Profile("native-merge")
    ec = prof.add_event_class("X")
    sb = prof.stream(0, "s0")
    sb.trace(ec.key, 1, 1, 1, timestamp=1.0)               # native
    sb.trace(ec.key, 2, 1, 1, info={"k": 2}, timestamp=2.0)  # python
    sb.trace(ec.key, 1, 1, 2, timestamp=3.0)               # native
    if sb._native is not None:
        # info-less events buffer in the pending list until the chunked
        # bulk flush (ONE ctypes crossing per ~1k events)
        assert len(sb.events) == 1 and len(sb._pending) == 2
        sb.flush_native()
        assert len(sb._pending) == 0 and len(sb._native) == 2
    path = prof.dump(str(tmp_path / "m.ptt"))
    _meta, df = read_trace(path)
    assert list(df["ts"]) == [1.0, 2.0, 3.0]
    assert df.iloc[1]["info"] == {"k": 2}


# ---------------------------------------------------------------------------
# build hardening (r11): stale-source rebuild + one rate-limited
# degradation warning per process
# ---------------------------------------------------------------------------

_MINI_C = r"""
#define PY_SSIZE_T_CLEAN
#include <Python.h>
static PyObject *answer(PyObject *s, PyObject *a) {
    (void)s; (void)a; return PyLong_FromLong(%d);
}
static PyMethodDef m[] = {{"answer", answer, METH_NOARGS, ""},
                          {NULL, NULL, 0, NULL}};
static struct PyModuleDef mod = {PyModuleDef_HEAD_INIT, "miniext",
                                 NULL, -1, m, NULL, NULL, NULL, NULL};
PyMODINIT_FUNC PyInit_miniext(void) { return PyModule_Create(&mod); }
"""


def test_stale_so_triggers_rebuild(tmp_path, monkeypatch):
    """An edited .c next to an older .so must rebuild, not load the
    stale artifact (the mtime check of native._stale/_load_cext)."""
    import os
    import time as _time
    monkeypatch.setattr(native, "_HERE", str(tmp_path))
    src = tmp_path / "miniext.c"
    src.write_text(_MINI_C % 1)
    mod = native._load_cext("miniext")
    assert mod is not None and mod.answer() == 1
    # new source, stale .so: the loader must rebuild the artifact
    # (CPython caches extension modules by name+path in-process, so
    # the contract is about the .so a FRESH process would load)
    so = tmp_path / "miniext.so"
    built_at = so.stat().st_mtime_ns
    _time.sleep(0.02)
    src.write_text(_MINI_C % 2)
    os.utime(src)
    native._cexts.pop("miniext")        # fresh-process semantics
    assert native._stale(str(so), str(src))
    assert native._load_cext("miniext") is not None
    assert so.stat().st_mtime_ns > built_at, "stale .so not rebuilt"
    assert not native._stale(str(so), str(src))


def test_missing_compiler_degrades_with_one_warning(tmp_path,
                                                    monkeypatch):
    """A failing toolchain falls back to the Python path with ONE
    rate-limited warning per process — not one per extension, and
    never one per import (the per-name cache makes repeats silent)."""
    import subprocess as sp

    calls = []

    def no_compiler(*a, **k):
        calls.append(a)
        raise FileNotFoundError("g++: not found")

    warned = []
    monkeypatch.setattr(native, "_HERE", str(tmp_path))
    monkeypatch.setattr(native, "_toolchain_warned", False)
    monkeypatch.setattr(native, "warning",
                        lambda msg, *a: warned.append(msg % a))
    monkeypatch.setattr(sp, "run", no_compiler)
    (tmp_path / "extone.c").write_text(_MINI_C % 1)
    (tmp_path / "exttwo.c").write_text(_MINI_C % 1)
    assert native._load_cext("extone") is None
    assert native._load_cext("exttwo") is None
    assert len(calls) == 2              # both attempted a build
    assert len(warned) == 1             # ...but ONE warning total
    assert "falling back" in warned[0]
    # cached result: later loads are silent no-ops (no new build)
    assert native._load_cext("extone") is None
    assert len(calls) == 2
