"""Driver app tests: Cholesky, QR, stencil, pingpong, redistribute
(reference: DPLASMA-style drivers named by BASELINE.json; tests/apps/)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import (TwoDimBlockCyclic, TwoDimTabular,
                                    VectorTwoDimCyclic)


def _spd(n, rng):
    B = rng.standard_normal((n, n)).astype(np.float32)
    return (B @ B.T + n * np.eye(n)).astype(np.float32)


@pytest.mark.parametrize("device", ["tpu", "cpu"])
@pytest.mark.parametrize("nt", [1, 2, 5])
def test_potrf_matches_numpy(device, nt):
    from parsec_tpu.apps.potrf import potrf_taskpool
    mb = 16
    n = nt * mb
    rng = np.random.default_rng(0)
    spd = _spd(n, rng)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(spd.copy())
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(potrf_taskpool(A, device=device))
        ctx.wait()
    L = np.tril(A.to_array())
    err = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
    assert err < 1e-4


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_potrf_bf16_panels_mixed_precision(device):
    """bf16-panel mixed precision (HPL-AI-style; bench.py potrf mp mode):
    the kernels are dtype-following, so storing off-diagonal tiles bf16
    must still produce a valid factorization of a (slightly perturbed)
    matrix — loose tolerance reflects bf16 storage rounding."""
    from ml_dtypes import bfloat16
    from parsec_tpu.apps.potrf import potrf_taskpool
    mb, nt = 16, 4
    n = nt * mb
    rng = np.random.default_rng(3)
    spd = _spd(n, rng)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, dtype=bfloat16)
    for m, nn in A.local_tiles():
        blk = spd[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
        A.data_of(m, nn).overwrite_host(blk.astype(bfloat16))
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(potrf_taskpool(A, device=device))
        ctx.wait()
    L = np.zeros((n, n), np.float32)
    for m, nn in A.local_tiles():
        if m < nn:
            continue
        L[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb] = \
            np.asarray(A.data_of(m, nn).pull_to_host().payload,
                       dtype=np.float32)
    L = np.tril(L)
    err = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
    assert err < 3e-2, err


@pytest.mark.parametrize("device", ["tpu", "cpu"])
@pytest.mark.parametrize("nt", [1, 2, 4])
def test_qr_matches_numpy(device, nt):
    from parsec_tpu.apps.qr import qr_taskpool
    mb = 8
    n = nt * mb
    rng = np.random.default_rng(1)
    a = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(a.copy())
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(qr_taskpool(A, device=device))
        ctx.wait()
    out = A.to_array()
    assert np.abs(np.tril(out, -1)).max() < 1e-4     # R is upper-triangular
    R = np.triu(out)
    ata = a.T @ a
    assert np.abs(R.T @ R - ata).max() / np.abs(ata).max() < 1e-4


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_stencil_matches_serial(device):
    from parsec_tpu.apps.stencil import stencil_reference, stencil_taskpool
    NT, mb, steps = 4, 8, 5
    rng = np.random.default_rng(2)
    x = rng.standard_normal(NT * mb).astype(np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(x.copy())
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(stencil_taskpool(V, steps, device=device))
        ctx.wait()
    want = stencil_reference(x, steps)
    np.testing.assert_allclose(V.to_array(), want, rtol=1e-4, atol=1e-5)


def test_stencil_fused_sweeps_match_reference():
    """VERDICT r4 #4: S-deep-halo sweep fusion — fused blocks (with a
    ragged remainder) produce the same values as the per-sweep pipeline
    and the serial reference."""
    from parsec_tpu.apps.stencil import stencil_reference, stencil_taskpool
    NT, mb, steps, fuse = 4, 8, 11, 4      # remainder block of 3
    rng = np.random.default_rng(2)
    x = rng.standard_normal(NT * mb).astype(np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(x.copy())
    with Context(nb_cores=4) as ctx:
        tp = stencil_taskpool(V, steps, device="cpu", fuse=fuse)
        # ceil(11/4)=3 blocks of NT tasks + NT INIT tasks
        ctx.add_taskpool(tp)
        ctx.wait()
    want = stencil_reference(x, steps)
    np.testing.assert_allclose(V.to_array(), want, rtol=1e-4, atol=1e-5)
    # fuse deeper than the tile is rejected (halo correctness bound)
    with pytest.raises(ValueError):
        stencil_taskpool(V, steps, fuse=mb + 1)


def test_pingpong_single_process():
    from parsec_tpu.apps.pingpong import run_pingpong
    with Context(nb_cores=2) as ctx:
        per_hop, mbps = run_pingpong(ctx, nbytes=1024, hops=50)
    assert per_hop > 0 and mbps > 0


def test_redistribute_between_distributions():
    from parsec_tpu.apps.redistribute import redistribute_taskpool
    mt = nt = 3
    mb = 8
    rng = np.random.default_rng(3)
    S = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="S")
    # target: tabular distribution with a scrambled (single-rank) table
    table = [0] * (mt * nt)
    T = TwoDimTabular(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, table=table,
                      name="T")
    for m, n in S.local_tiles():
        S.data_of(m, n).copy_on(0).payload[:] = \
            rng.standard_normal((mb, mb)).astype(np.float32)
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(redistribute_taskpool(S, T))
        ctx.wait()
    np.testing.assert_allclose(T.to_array(), S.to_array(), rtol=1e-6)


def test_geqrt_choleskyqr2_orthogonal_at_cond_1e3():
    """ADVICE medium: tiles with cond in ~1e2..3e3 pass the finite-chol
    check but single-pass Cholesky-QR loses orthogonality as cond^2*eps
    (~0.1 at cond 1e3 in f32).  The CholeskyQR2 reorthogonalization pass
    in the GEQRT fast branch must hold eps-level orthogonality there."""
    import jax.numpy as jnp
    from parsec_tpu.apps.qr import _mk_geqrt
    mb = 32
    rng = np.random.default_rng(5)
    u, _ = np.linalg.qr(rng.standard_normal((mb, mb)))
    v, _ = np.linalg.qr(rng.standard_normal((mb, mb)))
    s = np.logspace(0, -3, mb)                   # cond(T) = 1e3
    T = ((u * s) @ v.T).astype(np.float32)
    out = _mk_geqrt()(jnp.asarray(T), jnp.zeros((mb, mb), jnp.float32))
    R = np.asarray(out["T"], dtype=np.float64)
    Q = np.asarray(out["Q"], dtype=np.float64)
    orth = np.abs(Q.T @ Q - np.eye(mb)).max()
    assert orth < 5e-5, orth                     # 1 pass gives ~1e-1 here
    recon = np.abs(Q @ R - T).max() / np.abs(T).max()
    assert recon < 1e-5, recon


def test_qr_inner_blocked_matches_numpy():
    """r6 tentpole: the inner-blocked (ib) panel construction — HIGHEST
    work O(mb^2*ib) per panel — must produce the same factorization
    contract as the unblocked path (R upper-triangular, R^T R = A^T A)
    through the full driver."""
    from parsec_tpu.apps.qr import qr_taskpool
    from parsec_tpu.utils.mca import params
    mb, nt = 16, 3
    n = nt * mb
    rng = np.random.default_rng(11)
    a = rng.standard_normal((n, n)).astype(np.float32)
    params.set("qr_ib", 4)
    try:
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(a.copy())
        with Context(nb_cores=4) as ctx:
            ctx.add_taskpool(qr_taskpool(A, device="tpu"))
            ctx.wait()
    finally:
        params.unset("qr_ib")
    out = A.to_array()
    assert np.abs(np.tril(out, -1)).max() < 1e-4
    R = np.triu(out)
    ata = a.T @ a
    assert np.abs(R.T @ R - ata).max() / np.abs(ata).max() < 1e-4


def test_geqrt_blocked_orthogonal():
    """Blocked GEQRT (BCGS2-flavored CholeskyQR2 per ib-block with one
    HIGHEST re-projection pass): eps-class orthogonality and exact
    reconstruction at moderate condition."""
    import jax.numpy as jnp
    from parsec_tpu.apps.qr import _mk_geqrt
    mb, ib = 64, 16
    rng = np.random.default_rng(5)
    u, _ = np.linalg.qr(rng.standard_normal((mb, mb)))
    v, _ = np.linalg.qr(rng.standard_normal((mb, mb)))
    s = np.logspace(0, -3, mb)                   # cond(T) = 1e3
    T = ((u * s) @ v.T).astype(np.float32)
    out = _mk_geqrt(ib)(jnp.asarray(T), jnp.zeros((mb, mb), jnp.float32))
    R = np.asarray(out["T"], dtype=np.float64)
    Q = np.asarray(out["Q"], dtype=np.float64)
    assert np.abs(Q.T @ Q - np.eye(mb)).max() < 5e-5
    assert np.abs(Q @ R - T).max() / np.abs(T).max() < 1e-5
    assert np.abs(np.tril(R, -1)).max() == 0.0


def test_tsqrt_blocked_wy_pair_annihilates():
    """Blocked TSQRT: the aggregated panel-wide (V, T^T) pair — with
    the block-lower-triangular T-accumulation — must form an ORTHOGONAL
    transform that annihilates B and reproduces R' exactly, so TSMQR's
    unchanged 5-matmul application stays correct."""
    import jax.numpy as jnp
    from parsec_tpu.apps.qr import _mk_tsqrt
    mb, ib = 32, 8
    rng = np.random.default_rng(7)
    Rin = np.triu(rng.standard_normal((mb, mb))).astype(np.float32) \
        + 3 * np.eye(mb, dtype=np.float32)
    B = rng.standard_normal((mb, mb)).astype(np.float32)
    out = _mk_tsqrt(ib)(jnp.asarray(Rin), jnp.asarray(B),
                        jnp.zeros((2 * mb, mb), jnp.float32))
    Rp = np.asarray(out["T"], np.float64)
    pair = np.asarray(out["Q"], np.float64)
    V, Tt = pair[:mb], pair[mb:]
    W = np.vstack([np.eye(mb), V])
    Phi_t = np.eye(2 * mb) - W @ Tt @ W.T          # = Q^T
    stacked = np.vstack([Rin, B]).astype(np.float64)
    applied = Phi_t @ stacked
    assert np.abs(applied[:mb] - Rp).max() / np.abs(Rp).max() < 1e-5
    assert np.abs(applied[mb:]).max() < 1e-4       # B annihilated
    assert np.abs(Phi_t @ Phi_t.T - np.eye(2 * mb)).max() < 1e-5
    assert np.abs(np.asarray(out["B"])).max() == 0.0
