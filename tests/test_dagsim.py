"""DAG scheduling-efficiency simulator (parallel/dagsim.py): expansion
of real taskpools, hand-checkable schedules, and the potrf scaling curve
the bench eff mode publishes (VERDICT r3 #1/#2)."""

import numpy as np

from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import IN, OUT, PTG, Range, TASK
from parsec_tpu.parallel.dagsim import build_dag, critical_path, simulate


def _chain_pool(n):
    p = PTG("chain", N=n)
    p.task("T", i=Range(0, n - 1)) \
        .flow("x", "CTL",
              IN(TASK("T", "x", lambda i: dict(i=i - 1)),
                 when=lambda i: i > 0),
              OUT(TASK("T", "x", lambda i: dict(i=i + 1)),
                  when=lambda i, N=n: i < N - 1)) \
        .body(lambda: None)
    return p.build()


def test_chain_is_serial():
    tp = _chain_pool(10)
    dag = build_dag(tp, lambda tc, loc: 1.0)
    res = simulate(dag, n_chips=4)
    assert res["n_tasks"] == 10
    assert abs(res["makespan_s"] - 10.0) < 1e-9     # a chain cannot scale
    assert abs(res["efficiency"] - 10.0 / 40.0) < 1e-9
    assert abs(critical_path(dag) - 10.0) < 1e-9


def _fanout_pool(width):
    p = PTG("fan", W=width)
    p.task("SRC") \
        .flow("x", "CTL",
              OUT(TASK("W", "x",
                       lambda W=width: [dict(i=i) for i in range(W)]))) \
        .body(lambda: None)
    p.task("W", i=Range(0, width - 1)) \
        .flow("x", "CTL", IN(TASK("SRC", "x", lambda i: dict()))) \
        .body(lambda: None)
    return p.build()


def test_fanout_scales_with_chips():
    tp = _fanout_pool(8)

    def chips_rr(tc, loc):          # spread workers round-robin
        return loc.get("i", 0) % 4
    dag = build_dag(tp, lambda tc, loc: 1.0, chip_fn=chips_rr)
    res = simulate(dag, n_chips=4, alpha=0.0)
    # src(1s) then 8 workers over 4 chips (2s) = 3s makespan
    assert abs(res["makespan_s"] - 3.0) < 1e-9
    res1 = simulate(dag, n_chips=1, alpha=0.0)
    assert abs(res1["makespan_s"] - 9.0) < 1e-9


def test_comm_cost_charged_on_cross_chip_edges():
    tp = _chain_pool(2)

    def place(tc, loc):
        return loc["i"]             # the two tasks on different chips
    dag = build_dag(tp, lambda tc, loc: 1.0,
                    bytes_fn=lambda tc, fl: 10 ** 9, chip_fn=place)
    res = simulate(dag, n_chips=2, alpha=0.5, beta=1e9)
    # 1s + (0.5 latency + 1s transfer) + 1s
    assert abs(res["makespan_s"] - 3.5) < 1e-9


def test_priority_breaks_ties():
    p = PTG("prio", N=4)
    p.task("T", i=Range(0, 3)) \
        .priority(lambda i: i) \
        .flow("x", "CTL") \
        .body(lambda: None)
    tp = p.build()
    dag = build_dag(tp, lambda tc, loc: 1.0,
                    chip_fn=lambda tc, loc: 0)
    res = simulate(dag, n_chips=1)
    assert abs(res["makespan_s"] - 4.0) < 1e-9


def test_potrf_dag_expands_and_scales():
    """The real potrf taskpool, distributed 2D block-cyclic over 8
    chips: the DAG must expand to the textbook task counts and the
    simulated efficiency must rise with more parallelism-per-chip."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    NT, mb = 12, 64
    n = NT * mb
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=8, P=2, Q=4)
    tp = potrf_taskpool(A, device="cpu")

    def dur(tc, loc):
        return {"POTRF": 1.0, "POTRFL": 0.3, "TRSM": 2.0, "SYRK": 2.0,
                "GEMM": 2.0}[tc]
    dag = build_dag(tp, dur, bytes_fn=lambda tc, fl: mb * mb * 4)
    want = {
        "POTRF": NT - 1, "POTRFL": 1,
        "TRSM": NT * (NT - 1) // 2,
        "SYRK": NT * (NT - 1) // 2,
        "GEMM": NT * (NT - 1) * (NT - 2) // 6,
    }
    got = {}
    for node in dag.nodes.values():
        got[node["tc"]] = got.get(node["tc"], 0) + 1
    assert got == want
    r8 = simulate(dag, n_chips=8, alpha=2e-6, beta=4.5e10)
    r1 = simulate(dag, n_chips=1)
    assert r1["efficiency"] > 0.999          # serial = perfectly busy
    assert 0.0 < r8["efficiency"] <= 1.0
    # speedup is real but sub-linear on a small grid
    speedup = r1["makespan_s"] / r8["makespan_s"]
    assert 2.0 < speedup <= 8.0
    # the infinite-resource bound is respected
    assert r8["makespan_s"] >= critical_path(dag) - 1e-9


def test_efficiency_definition():
    tp = _fanout_pool(4)
    dag = build_dag(tp, lambda tc, loc: 2.0,
                    chip_fn=lambda tc, loc: loc.get("i", 0) % 2)
    res = simulate(dag, n_chips=2, overhead=0.5, alpha=0.0)
    # work = 5 tasks * 2.5s; makespan: src 2.5, then 2 waves of workers
    # per chip = 2.5 + 5.0
    assert abs(res["total_work_s"] - 12.5) < 1e-9
    assert abs(res["makespan_s"] - 7.5) < 1e-9
    assert abs(res["efficiency"] - 12.5 / 15.0) < 1e-9


def test_sim_vs_measured_single_processor():
    """VERDICT r4 #2: the simulator's makespan must track a MEASURED
    runtime wall.  On one worker the sim's model is total work +
    per-task overhead — measure per-class kernel seconds and the
    runtime wall for a small potrf, then require the prediction inside
    a generous band (CPU timing on a shared host is noisy; the bench's
    eff mode reports the tight number per run)."""
    import time
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.core.context import Context

    mb, nt = 32, 6
    n = mb * nt
    rng = np.random.default_rng(0)
    B = rng.standard_normal((n, n)).astype(np.float32)
    spd = (B @ B.T + n * np.eye(n)).astype(np.float32)

    def one_run():
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n,
                              ln=n).from_array(spd.copy())
        with Context(nb_cores=1) as ctx:
            t0 = time.perf_counter()
            ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
            ctx.wait(timeout=120)
            return time.perf_counter() - t0, A

    one_run()                                   # warm compiles
    wall, A2 = one_run()
    wall2, _ = one_run()
    wall = min(wall, wall2)

    # per-class durations measured the same way the bench calibrates:
    # average in-run body time per class via a fresh instrumented run
    from parsec_tpu.prof.pins import install_task_profiler
    from parsec_tpu.prof.profiling import EV_END, EV_START, Profile
    prof = Profile()
    A3 = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n,
                           ln=n).from_array(spd.copy())
    with Context(nb_cores=1) as ctx:
        mod = install_task_profiler(ctx, prof)
        ctx.add_taskpool(potrf_taskpool(A3, device="cpu"))
        ctx.wait(timeout=120)
        mod.uninstall(ctx)
    keys = {ec.key: name for name, ec in prof._dict.items()}
    sums, counts, open_ev = {}, {}, {}
    for sb in prof._streams.values():
        for key, flags, _tp, eid, _oid, ts, _info in sb.merged_events():
            if flags & EV_START:
                open_ev[eid] = (key, ts)
            elif flags & EV_END and eid in open_ev:
                k, t0 = open_ev.pop(eid)
                name = keys[k]
                sums[name] = sums.get(name, 0.0) + (ts - t0)
                counts[name] = counts.get(name, 0) + 1
    durs = {name: sums[name] / counts[name] for name in sums}
    assert set(durs) >= {"POTRF", "TRSM", "SYRK", "GEMM"}, durs

    A4 = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n)
    dag = build_dag(potrf_taskpool(A4, device="cpu"),
                    lambda tc, loc: durs[tc])
    pred = simulate(dag, 1, overhead=16e-6)["makespan_s"]
    # the model must be in the measured wall's neighborhood: body time
    # dominates, overhead/jitter bound the rest
    assert 0.3 * wall < pred < 2.0 * wall, (pred, wall, durs)
