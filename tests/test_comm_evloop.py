"""Event-loop transport edge cases (comm/engine.py EventLoopCE).

The tentpole contract tests: partial-write resume under a starved
SO_SNDBUF, interleaved out-of-band payloads from two peers on one loop,
peer death mid-frame failing the connection WITH a cause (engine.py's
documented contract), the eager-race rendezvous-handle purge path, the
adaptive eager threshold's feedback rules, activation coalescing, and a
tier-1-safe loopback stress over mixed eager+rendezvous traffic.
In-process cases run several EventLoopCEs in one process (each owns its
own loop thread + listener), so they cost no spawn overhead.
"""

import socket
import threading
import time

import numpy as np
import pytest

from parsec_tpu.comm.engine import (_HANDSHAKE, _LEN, _WIRE_MAGIC,
                                    _WIRE_VERSION, EventLoopCE, SocketCE,
                                    TAG_USER, make_ce)
from parsec_tpu.comm.launch import _probe_port_base, run_distributed
from parsec_tpu.utils.mca import params


def _mk_pair(n=2, **kw):
    base = _probe_port_base(n)
    ces = [EventLoopCE(r, n, base) for r in range(n)]
    return base, ces


def _fini(ces):
    for ce in ces:
        ce.fini()


# -- partial-write resume under a full send buffer --------------------------

def test_partial_write_resume_tiny_sndbuf():
    """A send buffer far smaller than the frame forces the loop through
    EPOLLOUT partial-write resume; every byte must still land, in
    order."""
    params.set("comm_sockbuf_bytes", 8192)
    try:
        _, (ce0, ce1) = _mk_pair(2)
    finally:
        params.unset("comm_sockbuf_bytes")
    try:
        got = []
        evt = threading.Event()

        def cb(src, msg):
            got.append(msg)
            if len(got) == 4:
                evt.set()

        ce0.tag_register(TAG_USER, cb)
        arrays = [np.arange(256 * 1024, dtype=np.float32) + i
                  for i in range(4)]
        for i, a in enumerate(arrays):
            ce1.send_am(TAG_USER, 0, {"i": i, **ce1.pack(a)})
        assert evt.wait(30), f"only {len(got)}/4 frames arrived"
        # in-order arrival with intact payloads
        assert [m["i"] for m in got] == [0, 1, 2, 3]
        for i, m in enumerate(got):
            np.testing.assert_array_equal(ce0.unpack(m), arrays[i])
        # the tiny SNDBUF actually exercised the resume path
        assert ce1.stats.partial_writes > 0
        assert not ce0.dead_peers and not ce1.dead_peers
    finally:
        _fini([ce0, ce1])


# -- interleaved out-of-band payloads from two peers ------------------------

def test_interleaved_oob_payloads_two_peers():
    """Two peers stream large out-of-band frames at one receiver loop
    concurrently; the per-peer incremental parsers must not cross."""
    _, ces = _mk_pair(3)
    ce0, ce1, ce2 = ces
    try:
        got = {1: [], 2: []}
        lock = threading.Lock()
        evt = threading.Event()

        def cb(src, msg):
            with lock:
                got[src].append(msg)
                if sum(len(v) for v in got.values()) == 12:
                    evt.set()

        ce0.tag_register(TAG_USER, cb)

        def blast(ce, tag_base):
            for i in range(6):
                a = np.full(128 * 1024, tag_base * 100 + i, np.float32)
                ce.send_am(TAG_USER, 0, {"seq": i, "from": tag_base,
                                         **ce.pack(a)})

        t1 = threading.Thread(target=blast, args=(ce1, 1))
        t2 = threading.Thread(target=blast, args=(ce2, 2))
        t1.start(); t2.start(); t1.join(); t2.join()
        assert evt.wait(30), f"got {[len(v) for v in got.values()]}"
        for src in (1, 2):
            assert [m["seq"] for m in got[src]] == list(range(6))
            for m in got[src]:
                arr = ce0.unpack(m)
                assert arr.shape == (128 * 1024,)
                np.testing.assert_array_equal(
                    arr, np.full(128 * 1024, src * 100 + m["seq"],
                                 np.float32))
    finally:
        _fini(ces)


# -- peer death mid-frame: the connection fails WITH a cause ----------------

def test_peer_death_mid_frame_cause():
    base = _probe_port_base(1)
    ce = EventLoopCE(0, 2, base)
    errors = []
    ce.on_error = errors.append
    try:
        s = socket.create_connection(("127.0.0.1", base), timeout=10)
        s.sendall(_HANDSHAKE.pack(_WIRE_MAGIC, _WIRE_VERSION, 1))
        # a frame header promising 4096 body bytes, then death after 100
        s.sendall(_LEN.pack(TAG_USER, 4096, 0) + b"x" * 100)
        time.sleep(0.3)
        s.close()
        deadline = time.monotonic() + 10
        while 1 not in ce.dead_peers and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in ce.dead_peers
        assert errors and isinstance(errors[0], ConnectionError)
        assert "mid-frame" in str(errors[0]), errors[0]
    finally:
        ce.fini()


def test_clean_close_between_frames_no_midframe_cause():
    """A peer closing at a frame boundary is a plain disconnect — the
    mid-frame cause must not fire spuriously."""
    base = _probe_port_base(1)
    ce = EventLoopCE(0, 2, base)
    errors = []
    ce.on_error = errors.append
    try:
        import pickle
        s = socket.create_connection(("127.0.0.1", base), timeout=10)
        s.sendall(_HANDSHAKE.pack(_WIRE_MAGIC, _WIRE_VERSION, 1))
        body = pickle.dumps("bye")
        s.sendall(_LEN.pack(TAG_USER, len(body), 0) + body)
        time.sleep(0.3)
        s.close()
        deadline = time.monotonic() + 10
        while 1 not in ce.dead_peers and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 1 in ce.dead_peers
        assert errors and "mid-frame" not in str(errors[0])
    finally:
        ce.fini()


# -- eager-race rendezvous-handle purge path --------------------------------

def _purged_handle_worker(ctx, rank, nranks):
    """A GET arriving after the serving rank purged (or never had) the
    handle must fail the RECEIVER with a clear miss, not the server."""
    import time
    from parsec_tpu.comm.engine import TAG_GET_REQ
    rde = ctx.comm
    rde.ce.barrier()
    if rank == 1:
        # fake a pending rendezvous pull whose handle rank 0 never
        # serves (the eager race: sender purged it before our GET)
        rde._pending_gets[(0, 987654)] = {"tp": None, "deliveries": []}
        rde._send_app(TAG_GET_REQ, 0, {"handle": 987654, "from": 1})
        deadline = time.monotonic() + 30
        while not ctx._errors:
            if time.monotonic() > deadline:
                return "no-error"
            time.sleep(0.02)
        msg = str(ctx._errors[0][0])
        assert "expired before our GET" in msg, msg
        assert (0, 987654) not in rde._pending_gets
        rde.ce.barrier()
        return "receiver-missed"
    rde.ce.barrier()        # rank 0 must survive the bogus GET
    return "server-alive"


def test_eager_race_rendezvous_purge():
    res = run_distributed(_purged_handle_worker, 2, timeout=120)
    assert res == ["server-alive", "receiver-missed"]


# -- adaptive eager threshold: feedback rules -------------------------------

class _FakeFeedbackCE:
    def __init__(self):
        self.fb = {"out_bytes": 0, "delay_ewma": None, "rate_ewma": None}

    def peer_feedback(self, dst):
        return self.fb


def _bare_rde(eager=65536):
    from parsec_tpu.comm.remote_dep import RemoteDepEngine
    rde = RemoteDepEngine.__new__(RemoteDepEngine)
    rde.eager = eager
    rde._proto_peer = {}
    rde._proto_lock = threading.Lock()
    rde.proto = {"eager_downshift": 0, "eager_upshift": 0}
    rde._bp_budget = float(params.get("comm_backpressure_ms", 2.0)) * 1e-3
    rde._eager_floor_cfg = int(params.get("comm_eager_min", 4096))
    rde._eager_cap_mult = max(1, int(params.get("comm_eager_cap_mult", 4)))
    rde.ce = _FakeFeedbackCE()
    return rde


def test_adaptive_eager_downshift_and_recovery():
    rde = _bare_rde(eager=65536)
    # healthy pipe: threshold never drops below base
    rde.ce.fb = {"out_bytes": 0, "delay_ewma": 1e-4, "rate_ewma": 1e9}
    t0 = rde._peer_eager(1)
    assert t0 >= 65536
    def expire_window():
        # adjustments are rate-limited to one per feedback window: step
        # past it instead of sleeping real time
        rde._proto_peer[1]["adj_at"] -= 1.0

    # congested: 100MB queued at 10MB/s -> projected 10s >> budget
    rde.ce.fb = {"out_bytes": 100 << 20, "delay_ewma": 0.5,
                 "rate_ewma": 10e6}
    expire_window()
    t1 = rde._peer_eager(1)
    assert t1 < t0

    for _ in range(20):            # sustained congestion -> the floor
        expire_window()
        rde._peer_eager(1)
    floor = min(int(params.get("comm_eager_min", 4096)), 65536)
    assert rde._proto_peer[1]["eager"] == floor
    assert rde.proto["eager_downshift"] > 0
    # a burst of queries WITHIN one window must shift at most once
    before = rde.proto["eager_downshift"]
    rde._proto_peer[1]["eager"] = 65536
    expire_window()
    for _ in range(10):
        rde._peer_eager(1)
    assert rde.proto["eager_downshift"] == before + 1
    rde._proto_peer[1]["eager"] = floor
    # drained pipe: threshold recovers (and may exceed base, to cap)
    rde.ce.fb = {"out_bytes": 0, "delay_ewma": 1e-5, "rate_ewma": 5e9}
    for _ in range(30):
        expire_window()
        rde._peer_eager(1)
    cap = 65536 * int(params.get("comm_eager_cap_mult", 4))
    assert rde._proto_peer[1]["eager"] == cap
    assert rde.proto["eager_upshift"] > 0


def test_adaptive_eager_disabled_keeps_base():
    rde = _bare_rde(eager=1234)
    rde.ce.fb = {"out_bytes": 100 << 20, "delay_ewma": 9.9,
                 "rate_ewma": 1.0}
    params.set("comm_adaptive_eager", False)
    try:
        assert rde._peer_eager(1) == 1234
    finally:
        params.unset("comm_adaptive_eager")


# -- activation coalescing: one frame per destination per task --------------

def _coalesce_worker(ctx, rank, nranks):
    """One producer task with TWO flows feeding rank 1: both activations
    must pack into ONE wire frame (TAG_BATCH)."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, TASK
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 2.0
    seen = {}
    p = PTG("coal")
    p.task("P") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("C", "X", lambda: dict()))) \
        .flow("Y", "READ",
              IN(DATA(lambda V=V: V(0))),
              OUT(TASK("C", "Y", lambda: dict()))) \
        .body(lambda: None)
    p.task("C") \
        .affinity(lambda V=V: V(1)) \
        .flow("X", "READ", IN(TASK("P", "X", lambda: dict()))) \
        .flow("Y", "READ", IN(TASK("P", "Y", lambda: dict()))) \
        .body(lambda X, Y: seen.update(
            x=float(np.asarray(X)[0]), y=float(np.asarray(Y)[0])))
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    return {"seen": seen, "stats": ctx.comm.stats()}


def test_activation_coalescing_one_frame_per_dst():
    res = run_distributed(_coalesce_worker, 2, timeout=120)
    assert res[1]["seen"] == {"x": 2.0, "y": 2.0}
    st = res[0]["stats"]
    assert st["coalesced_batches"] >= 1, st
    assert st["coalesced_msgs"] >= 2, st


# -- transport A/B knob ------------------------------------------------------

def test_make_ce_transport_knob():
    base = _probe_port_base(1)
    params.set("comm_transport", "threads")
    try:
        ce = make_ce(0, 1, base)
        assert isinstance(ce, SocketCE)
        ce.fini()
        params.set("comm_transport", "evloop")
        ce = make_ce(0, 1, base)
        assert isinstance(ce, EventLoopCE)
        ce.fini()
    finally:
        params.unset("comm_transport")


def _ab_chain(ctx, rank, nranks):
    assert type(ctx.comm.ce).__name__ == "SocketCE"
    assert ctx.comm.stats()["transport"] == "threads"
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    NT = 6
    V = VectorTwoDimCyclic(mb=4, lm=NT * 4, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("ab", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=NT: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda T: T + 1.0)
    ctx.add_taskpool(p.build())
    ctx.wait()
    out = {}
    for m, _ in V.local_tiles():
        out[m] = float(np.asarray(V.data_of(m).pull_to_host().payload)[0])
    return out


def test_threads_transport_ab_reproduces_old_path(monkeypatch):
    monkeypatch.setenv("PARSEC_MCA_COMM_TRANSPORT", "threads")
    results = run_distributed(_ab_chain, 2)
    merged = {}
    for r in results:
        merged.update(r)
    assert merged == {k: float(k + 1) for k in range(6)}


# -- tier-1-safe loopback stress: mixed eager + rendezvous, N seeds ---------

def _stress_worker(ctx, rank, nranks, seeds):
    """Chains over tiles around the eager threshold: every hop is a
    remote edge, randomly eager (small tile) or rendezvous (big tile)
    per seed; payload integrity is the assertion."""
    import numpy as np
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    ctx.comm.eager = 2048          # base threshold in bytes
    out = {}
    for i, seed in enumerate(seeds):
        NT = 8
        # tile sizes straddle the threshold: 64B/1KB ride eager, 32KB
        # exceeds even the adaptive cap (base * comm_eager_cap_mult)
        # -> rendezvous
        mb = [16, 256, 8192][i % 3]
        V = VectorTwoDimCyclic(mb=mb, lm=NT * mb, nodes=nranks,
                               myrank=rank, name=f"S{seed}")
        for m, _ in V.local_tiles():
            V.data_of(m).copy_on(0).payload[:] = 0.0
        p = PTG(f"stress{seed}", NT=NT)
        p.task("S", k=Range(0, NT - 1)) \
            .affinity(lambda k, V=V: V(k)) \
            .flow("T", "RW",
                  IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k, NT=NT: dict(k=k + 1)),
                      when=lambda k, NT=NT: k < NT - 1),
                  OUT(DATA(lambda k, V=V: V(k)))) \
            .body(lambda T: T + 1.0)
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=120)
        for m, _ in V.local_tiles():
            out[(seed, m)] = float(
                np.asarray(V.data_of(m).pull_to_host().payload)[0])
    st = ctx.comm.stats()
    return {"vals": out, "eager": st["act_eager"], "rdv": st["act_rdv"]}


def test_loopback_stress_mixed_eager_rdv():
    seeds = [11, 23, 47]
    res = run_distributed(_stress_worker, 2, args=(seeds,), timeout=240)
    merged = {}
    eager = rdv = 0
    for r in res:
        merged.update(r["vals"])
        eager += r["eager"]
        rdv += r["rdv"]
    for seed in seeds:
        for k in range(8):
            assert merged[(seed, k)] == float(k + 1), (seed, k)
    # the traffic really mixed both protocols
    assert eager > 0 and rdv > 0, (eager, rdv)


# -- cross-task flush window -------------------------------------------------

def _window_worker(ctx, rank, nranks):
    """Independent producers completing within the flush window: their
    same-destination activations may coalesce; correctness must hold."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
    N = 6
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    W = VectorTwoDimCyclic(mb=4, lm=4 * N * nranks, nodes=nranks,
                           myrank=rank, name="W")
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 1.0
    for m, _ in W.local_tiles():
        W.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("win", N=N)
    p.task("P", i=Range(0, N - 1)) \
        .affinity(lambda i, V=V: V(0)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(0))),
              OUT(TASK("C", "X", lambda i: dict(i=i)))) \
        .body(lambda: None)
    p.task("C", i=Range(0, N - 1)) \
        .affinity(lambda i, W=W: W(2 * i + 1)) \
        .flow("X", "READ", IN(TASK("P", "X", lambda i: dict(i=i)))) \
        .flow("O", "RW",
              IN(DATA(lambda i, W=W: W(2 * i + 1))),
              OUT(DATA(lambda i, W=W: W(2 * i + 1)))) \
        .body(lambda X, O: np.asarray(O) + np.asarray(X) + 1.0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    out = {}
    for m, _ in W.local_tiles():
        out[m] = float(np.asarray(W.data_of(m).pull_to_host().payload)[0])
    return out


def test_cross_task_flush_window(monkeypatch):
    monkeypatch.setenv("PARSEC_MCA_COMM_FLUSH_WINDOW_MS", "2")
    res = run_distributed(_window_worker, 2, timeout=120)
    merged = {}
    for r in res:
        merged.update(r)
    for i in range(6):
        assert merged[2 * i + 1] == 2.0, (i, merged)


# -- mid-run sibling death: rank 0 aborts the round for survivors -----------

def test_barrier_abort_fails_survivors_fast():
    """A sibling dying BEFORE arriving makes rank 0 abort the round:
    surviving non-root ranks fail promptly with the cause instead of
    riding out the full barrier timeout."""
    _, ces = _mk_pair(3)
    ce0, ce1, ce2 = ces
    try:
        ce2.fini()                 # rank 2 dies without arriving
        deadline = time.monotonic() + 10
        while (2 not in ce0.dead_peers or 2 not in ce1.dead_peers) \
                and time.monotonic() < deadline:
            time.sleep(0.02)
        assert 2 in ce0.dead_peers and 2 in ce1.dead_peers
        errs = {}

        def run(name, ce):
            t0 = time.monotonic()
            try:
                ce.barrier(timeout=30)
                errs[name] = ("none", time.monotonic() - t0)
            except Exception as exc:
                errs[name] = (exc, time.monotonic() - t0)

        t1 = threading.Thread(target=run, args=("r1", ce1))
        t1.start()
        run("r0", ce0)
        t1.join(timeout=30)
        exc0, _ = errs["r0"]
        exc1, dt1 = errs["r1"]
        assert isinstance(exc0, ConnectionError), exc0
        assert isinstance(exc1, ConnectionError), exc1
        assert dt1 < 10, f"survivor waited {dt1:.1f}s (timeout-class)"
    finally:
        _fini([ce0, ce1])


# -- undelivered-before-register replay holds on the loop thread ------------

def test_undelivered_backlog_replayed_on_register():
    _, (ce0, ce1) = _mk_pair(2)
    try:
        ce1.send_am(TAG_USER, 0, {"early": True})
        time.sleep(0.3)            # lands before anyone registered
        got = []
        evt = threading.Event()
        ce0.tag_register(TAG_USER, lambda s, p: (got.append((s, p)),
                                                 evt.set()))
        assert evt.wait(10)
        assert got == [(1, {"early": True})]
    finally:
        _fini([ce0, ce1])


# -- multi-core-host validation of the evloop freed-core claim ---------------
# (BENCH.md r6 residual: the r6 threads-vs-evloop parity was measured on
# a 1-core host, where the freed progress-thread core cannot show up.)

def _mc_pingpong_worker(ctx, rank, nranks, nbytes, hops):
    from parsec_tpu.apps.pingpong import run_pingpong
    run_pingpong(ctx, nbytes, 4)            # warm the link
    per_hop, mbps = run_pingpong(ctx, nbytes, hops)
    return per_hop, mbps, ctx.comm.stats()["transport"]


@pytest.mark.slow
def test_evloop_threads_parity_multicore():
    """Paired A/B on a host with >= 2 cores: the evloop transport must
    hold parity with the threads transport (generous band — CI hosts
    are noisy), and the datapoint is archived to a JSON file + the
    test log so the BENCH.md r6 freed-core claim accumulates real
    multi-core evidence (bw/rtt bench lines now record the host core
    inventory for the same reason)."""
    import json
    import os
    import tempfile
    try:
        cores = len(os.sched_getaffinity(0))
    except (AttributeError, OSError):
        cores = os.cpu_count() or 1
    if cores < 2:
        pytest.skip("multi-core validation needs >= 2 available cores "
                    f"(have {cores}); the 1-core parity leg is BENCH.md "
                    "r6")
    results = {}
    for transport in ("threads", "evloop"):
        prior = os.environ.get("PARSEC_MCA_COMM_TRANSPORT")
        os.environ["PARSEC_MCA_COMM_TRANSPORT"] = transport
        try:
            res = run_distributed(_mc_pingpong_worker, 2,
                                  args=(1 << 20, 24), timeout=240)
        finally:
            if prior is None:
                os.environ.pop("PARSEC_MCA_COMM_TRANSPORT", None)
            else:
                os.environ["PARSEC_MCA_COMM_TRANSPORT"] = prior
        assert all(r[2] == transport for r in res), res
        results[transport] = round(max(r[1] for r in res), 1)  # MB/s
    ratio = results["evloop"] / results["threads"]
    datapoint = {"cpu_count": os.cpu_count(), "cores_available": cores,
                 "bw_mbps": results, "evloop_over_threads": round(ratio, 3)}
    out = os.path.join(tempfile.gettempdir(),
                       "parsec_evloop_multicore.json")
    with open(out, "w") as fh:
        json.dump(datapoint, fh)
    print(f"multicore evloop datapoint (archived {out}): {datapoint}")
    # parity band: evloop must not collapse where cores stop being
    # shared; the freed-core UPSIDE is informational (the datapoint)
    assert ratio >= 0.5, datapoint
