"""parseclint pass corpus + clean-tree gate (ISSUE 7).

Each lint pass is exercised against a KNOWN-BAD snippet reproducing the
historical bug class it encodes — including the exact pre-fix shapes of
the geqrf ``device_put`` aliasing (r8 wrong-R) and the blocking
``sendmsg`` heartbeat (PR 5) — plus a known-good twin proving the pass
accepts the disciplined form.  The final test runs the full analyzer
over the real tree against the checked-in baseline: zero new findings
is a tier-1 invariant, which is what wires parseclint into the build.
"""

import os
import sys
import textwrap

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from tools.parseclint import FileCtx, Finding  # noqa: E402
from tools.parseclint.passes import (assert_hazard, device_put,  # noqa: E402
                                     evloop_blocking, except_hygiene,
                                     hot_path, journal_schema,
                                     lock_discipline, mca_knobs,
                                     prom_metrics)

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _ctx(src: str, rel: str = "parsec_tpu/comm/snippet.py") -> FileCtx:
    return FileCtx("/" + rel, rel, textwrap.dedent(src))


def _ids(findings):
    return [f.pass_id for f in findings]


# ---------------------------------------------------------------------------
# PCL-LOCK: guarded-by discipline
# ---------------------------------------------------------------------------

_LOCKED_CLASS = """
    import threading

    class Engine:
        def __init__(self):
            self._lock = threading.Lock()
            self._handles = {}        # guarded-by: _lock
            self._seq = 0             # guarded-by: _lock

        def method(self):
            __BODY__
"""


def _lock_findings(body: str):
    src = _LOCKED_CLASS.replace("__BODY__", body)
    return lock_discipline.check(_ctx(src))


def test_lock_flags_unlocked_write():
    fs = _lock_findings("self._seq += 1")
    assert _ids(fs) == ["PCL-LOCK"] and "Engine._seq" in fs[0].message


def test_lock_flags_unlocked_container_mutation():
    assert _lock_findings("self._handles[1] = 2")       # subscript store
    assert _lock_findings("self._handles.pop(1, None)")  # mutator call
    assert _lock_findings("del self._handles[1]")        # subscript del


def test_lock_accepts_locked_write():
    assert not _lock_findings(
        "with self._lock:\n                self._seq += 1\n"
        "                self._handles[self._seq] = 1")


def test_lock_accepts_reads_unlocked():
    assert not _lock_findings("return self._handles.get(1)")


def test_lock_holds_lock_annotation():
    src = """
        import threading

        class Engine:
            def __init__(self):
                self._lock = threading.Lock()
                self._seq = 0   # guarded-by: _lock

            def _bump_locked(self):   # holds-lock: _lock
                self._seq += 1
    """
    assert not lock_discipline.check(_ctx(src))


def test_lock_condition_alias_either_suffices():
    """guarded-by: _lock, _cond — the Condition-wrapping-the-same-lock
    idiom (core/context.py): a write under EITHER passes."""
    src = """
        import threading

        class Ctx:
            def __init__(self):
                self._lock = threading.RLock()
                self._cond = threading.Condition(self._lock)
                self._errors = []   # guarded-by: _lock, _cond

            def record(self, exc):
                with self._cond:
                    self._errors.append(exc)

            def admit(self):
                with self._lock:
                    self._errors.append(None)
    """
    assert not lock_discipline.check(_ctx(src))


def test_lock_inline_suppression():
    fs = _lock_findings(
        "self._seq += 1   # lint: ignore[PCL-LOCK] init-only path")
    assert not fs


def test_lock_subclass_inherits_base_annotations():
    src = """
        import threading

        class Base:
            def __init__(self):
                self._lock = threading.Lock()
                self._peers = {}   # guarded-by: _lock

        class Derived(Base):
            def drop(self, r):
                self._peers.pop(r, None)
    """
    fs = lock_discipline.check(_ctx(src))
    assert _ids(fs) == ["PCL-LOCK"] and "Derived._peers" in fs[0].message


# ---------------------------------------------------------------------------
# PCL-EVLOOP: blocking calls reachable from loop callbacks
# ---------------------------------------------------------------------------

def test_evloop_flags_time_sleep_in_funnelled_method():
    src = """
        import time

        class EvCE:
            FUNNELLED = True

            def _on_timer(self):
                time.sleep(0.1)
    """
    fs = evloop_blocking.check(_ctx(src))
    assert _ids(fs) == ["PCL-EVLOOP"] and "time.sleep" in fs[0].message


def test_evloop_flags_blocking_heartbeat_sendmsg():
    """The EXACT pre-fix PR 5 shape: the heartbeat path reaches a bare
    blocking sendmsg — the hung-peer detector wedges behind the very
    hang it exists to catch.  Reintroducing it must flag."""
    src = """
        class EvCE:
            FUNNELLED = True

            def heartbeat_tick(self):
                for r in self._peers:
                    self._hb_send(r)

            def _hb_send(self, r):
                s = self._peers[r]
                self._sendmsg_all(s, [b"hb"])

            def _sendmsg_all(self, s, parts):
                views = [memoryview(p) for p in parts]
                while views:
                    sent = s.sendmsg(views)
                    views = views[1:]
    """
    fs = evloop_blocking.check(_ctx(src))
    assert any("sendmsg" in f.message for f in fs), fs


def test_evloop_accepts_nonblocking_sendmsg_discipline():
    """The post-fix shape: sendmsg wrapped in the BlockingIOError
    try — the event loop's nonblocking contract — passes."""
    src = """
        class EvCE:
            FUNNELLED = True

            def _flush(self, peer):
                try:
                    sent = peer.sock.sendmsg(peer.wire)
                except (BlockingIOError, InterruptedError):
                    return
    """
    assert not evloop_blocking.check(_ctx(src))


def test_evloop_flags_select_select():
    """The PR 5 round-3 fd>=1024 hazard: select.select in loop-reachable
    code dies on a resident service holding thousands of fds."""
    src = """
        import select

        class EvCE:
            FUNNELLED = True

            def _writable(self, s):
                return bool(select.select([], [s], [], 0)[1])
    """
    fs = evloop_blocking.check(_ctx(src))
    assert _ids(fs) == ["PCL-EVLOOP"] and "fd>=1024" in fs[0].message


def test_evloop_flags_blocking_acquire_allows_nonblocking():
    src = """
        class EvCE:
            FUNNELLED = True

            def bad(self):
                self._lk.acquire()

            def good(self):
                if self._lk.acquire(blocking=False):
                    self._lk.release()
    """
    fs = evloop_blocking.check(_ctx(src))
    assert len(fs) == 1 and ".acquire()" in fs[0].message


def test_evloop_on_loop_marker_and_reachability():
    """A method marked on-loop is a root even outside a FUNNELLED
    class, and the pass follows self-calls to find the sleep."""
    src = """
        import time

        class Handlers:
            # lint: on-loop (AM handler)
            def _activate_cb(self, src, msg):
                self._slow_path()

            def _slow_path(self):
                time.sleep(1.0)

            def off_loop_helper(self):
                time.sleep(1.0)   # not reachable from a root: no flag
    """
    fs = evloop_blocking.check(_ctx(src))
    assert len(fs) == 1 and "_slow_path" in fs[0].message


def test_evloop_off_loop_and_waiver():
    src = """
        import time

        class EvCE:
            FUNNELLED = True

            def _dial(self, dst):   # lint: off-loop (init thread)
                time.sleep(0.05)

            def _shutdown_drain(self):
                time.sleep(0.002)   # lint: allow-blocking (teardown)
    """
    assert not evloop_blocking.check(_ctx(src))


# ---------------------------------------------------------------------------
# PCL-ALIAS: raw device_put / jnp.asarray stage-ins
# ---------------------------------------------------------------------------

def test_alias_flags_geqrf_prefix_shape():
    """The EXACT pre-fix r8 wrong-R shape: stage-in assigns a raw
    jax.device_put of a live payload — on the CPU client the 'copy'
    aliases the source, and a later donation corrupts the consumer's
    tile.  Reintroducing it in devices/ must flag."""
    src = """
        import jax

        class XlaDevice:
            def stage_in(self, datum, copy, payload):
                dc = datum.copy_on(self.space)
                dc.payload = jax.device_put(payload, self.jdev)
                dc.version = copy.version
                return dc
    """
    fs = device_put.check(_ctx(src, rel="parsec_tpu/devices/xla.py"))
    assert _ids(fs) == ["PCL-ALIAS"] and "wrong-R" in fs[0].message


def test_alias_flags_jnp_asarray_and_ici_scope():
    src = """
        import jax.numpy as jnp

        def put(self, payload, dst_space):
            return jnp.asarray(payload)
    """
    assert device_put.check(_ctx(src, rel="parsec_tpu/comm/ici.py"))


def test_alias_wrapper_and_waiver_accepted():
    src = """
        import jax

        def device_put_private(payload, jdev):   # lint: alias-wrapper
            out = jax.device_put(payload, jdev)
            return out

        def zeros_path(self, shape, dtype):
            return jax.device_put(   # lint: private-ok (fresh zeros)
                jnp.zeros(shape, dtype), self.jdev)
    """
    assert not device_put.check(_ctx(src, rel="parsec_tpu/devices/xla.py"))


def test_alias_out_of_scope_files_untouched():
    src = "import jax\n\ndef f(x, d):\n    return jax.device_put(x, d)\n"
    assert not device_put.check(_ctx(src, rel="parsec_tpu/apps/gemm.py"))


# ---------------------------------------------------------------------------
# PCL-MCA: knob drift
# ---------------------------------------------------------------------------

def _mca_run(sources, tmp_path):
    """sources: {rel: code}.  tmp_path has no parsec_tpu package, so
    the full-package gate is vacuously open (synthetic-tree mode)."""
    ctxs = {rel: _ctx(src, rel=rel) for rel, src in sources.items()}
    facts = [mca_knobs.facts(c) for c in ctxs.values()]
    return mca_knobs.tree_check(facts, str(tmp_path), ctxs)


def test_mca_flags_unregistered_read(tmp_path):
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'params.register("comm_foo", 1, "h")\n'
                   'v = params.get("comm_fooo", 1)\n'}, tmp_path)
    assert any("UNREGISTERED" in f.message and "comm_fooo" in f.message
               for f in fs)


def test_mca_flags_unread_registration(tmp_path):
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'params.register("comm_dead_knob", 1, "h")\n'},
                  tmp_path)
    assert any("never read" in f.message for f in fs)


def test_mca_flags_default_drift(tmp_path):
    """The drift class this pass caught FOR REAL on landing:
    comm_handle_timeout registered 600.0, read with fallback 120.0."""
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'params.register("comm_ttl", 600.0, "h")\n'
                   'v = params.get("comm_ttl", 120.0)\n'}, tmp_path)
    assert any("drifted" in f.message for f in fs)


def test_mca_flags_env_typo(tmp_path):
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'import os\n'
                   'params.register("comm_foo", 1, "h")\n'
                   'v = params.get("comm_foo")\n'
                   'w = os.environ.get("PARSEC_MCA_COMM_FOOO")\n'},
                  tmp_path)
    assert any("PARSEC_MCA_COMM_FOOO" in f.message for f in fs)


def test_mca_doc_table_cross_check(tmp_path):
    (tmp_path / "COMPONENTS.md").write_text(
        "| knob | `PARSEC_MCA_COMM_TYPO` selects it |\n")
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'params.register("comm_foo", 1, "h")\n'
                   'v = params.get("comm_foo")\n'}, tmp_path)
    assert any(f.path == "COMPONENTS.md" and "doc drift" in f.message
               for f in fs)


def test_mca_clean_roundtrip(tmp_path):
    fs = _mca_run({"parsec_tpu/comm/x.py":
                   'params.register("comm_foo", 4096, "h")\n'
                   'v = params.get("comm_foo", 4096)\n'}, tmp_path)
    assert fs == []


def test_mca_partial_scan_is_silent():
    """A subtree scan of the REAL repo (anything short of the whole
    parsec_tpu package) keeps the cross-checks off — registrations live
    all over the package, so a partial view would emit false
    'unregistered'/'doc drift' findings for knobs registered outside
    the scanned subtree."""
    ctx = _ctx('v = params.get("anything_at_all")\n',
               rel="parsec_tpu/comm/x.py")
    fs = mca_knobs.tree_check([mca_knobs.facts(ctx)], REPO,
                              {ctx.rel: ctx,
                               "parsec_tpu/utils/mca.py": ctx})
    assert fs == []


# ---------------------------------------------------------------------------
# PCL-PROM: metric-family doc drift
# ---------------------------------------------------------------------------

def _prom_run(sources, docs, tmp_path):
    """sources: {rel: code} (exporter rel paths get written to disk so
    the existence gate sees them); docs: {name: text}."""
    ctxs = {}
    for rel, src in sources.items():
        path = tmp_path / rel
        path.parent.mkdir(parents=True, exist_ok=True)
        path.write_text(textwrap.dedent(src))
        ctxs[rel] = _ctx(src, rel=rel)
    for name, text in docs.items():
        (tmp_path / name).write_text(text)
    facts = [prom_metrics.facts(c) for c in ctxs.values()]
    return prom_metrics.tree_check(facts, str(tmp_path), ctxs)


_EXPORTER = "parsec_tpu/prof/metrics.py"


def test_prom_flags_undocumented_family(tmp_path):
    fs = _prom_run(
        {_EXPORTER: 'out.append(counter_sample('
                    '"parsec_widgets_total", 1))\n'},
        {"README.md": "telemetry families: none yet\n"}, tmp_path)
    assert [f.pass_id for f in fs] == ["PCL-PROM"]
    assert "parsec_widgets_total" in fs[0].message
    assert fs[0].path == _EXPORTER


def test_prom_flags_stale_doc_series(tmp_path):
    """The encoded bug class: PR 7 round 2 dropped
    parsec_tasks_enabled_total from the registry; a doc row still
    naming it must flag AT THE DOC LINE."""
    fs = _prom_run(
        {_EXPORTER: 's = counter_sample('
                    '"parsec_tasks_retired_total", n)\n'},
        {"README.md": "families: `parsec_tasks_retired_total` and "
                      "`parsec_tasks_enabled_total`\n"}, tmp_path)
    assert any(f.path == "README.md"
               and "parsec_tasks_enabled_total" in f.message
               for f in fs)
    assert not any(f.path == _EXPORTER for f in fs)


def test_prom_prefix_mention_and_template_clean(tmp_path):
    """A family-prefix doc mention (parsec_comm_) covers both plain
    literals and f-string templates; series-suffixed doc tokens that
    resolve against a template are clean too."""
    fs = _prom_run(
        {_EXPORTER: '''
            for key in ("frames_sent", "frames_recv"):
                out.append(counter_sample(
                    f"parsec_comm_{key}_total", 1))
            out.append(gauge_sample("parsec_comm_dead_peers", 0))
         '''},
        {"README.md": "comm families (`parsec_comm_...`): "
                      "`parsec_comm_frames_sent_total` etc.\n"},
        tmp_path)
    assert fs == []


def test_prom_partial_scan_is_silent(tmp_path):
    """An exporter file present on disk but outside the scanned set
    keeps the cross-check off (the export universe is incomplete)."""
    (tmp_path / "parsec_tpu" / "prof").mkdir(parents=True)
    (tmp_path / _EXPORTER).write_text(
        'counter_sample("parsec_widgets_total", 1)\n')
    (tmp_path / "README.md").write_text("nothing\n")
    other = _ctx("x = 1\n", rel="parsec_tpu/comm/x.py")
    assert prom_metrics.tree_check(
        [prom_metrics.facts(other)], str(tmp_path),
        {other.rel: other}) == []


def test_prom_non_series_doc_tokens_ignored(tmp_path):
    """Reference-C symbol mentions (parsec_matrix_block_cyclic_kview)
    carry no series suffix and never flag doc-side."""
    fs = _prom_run(
        {_EXPORTER: 's = counter_sample('
                    '"parsec_tasks_retired_total", n)\n'},
        {"COMPONENTS.md":
         "rebuilds parsec_matrix_block_cyclic_kview; families: "
         "`parsec_tasks_retired_total`\n"}, tmp_path)
    assert fs == []


def test_prom_inline_suppression(tmp_path):
    fs = _prom_run(
        {_EXPORTER: 'counter_sample("parsec_internal_probe_total", '
                    '1)  # lint: ignore[PCL-PROM]\n'},
        {"README.md": "none\n"}, tmp_path)
    assert fs == []


# ---------------------------------------------------------------------------
# PCL-EXCEPT: containment hygiene
# ---------------------------------------------------------------------------

def test_except_flags_context_global_record():
    """The PR 5 round-4 class: a handler catching the structured
    PeerFailedError re-records it context-globally, poisoning every
    pool on the rank."""
    src = """
        from parsec_tpu.core.errors import PeerFailedError

        class Layer:
            def push(self, dst, msg):
                try:
                    self.send(dst, msg)
                except PeerFailedError as exc:
                    self.context.record_error(exc, None)
    """
    fs = except_hygiene.check(_ctx(src))
    assert _ids(fs) == ["PCL-EXCEPT"] and "CONTEXT-GLOBALLY" in fs[0].message


def test_except_flags_broad_catch_global_record():
    src = """
        from parsec_tpu.core.errors import PeerFailedError

        def drain(self):
            try:
                self.flush()
            except Exception as exc:
                self.context.record_error(exc, None)
    """
    assert except_hygiene.check(_ctx(src))


def test_except_flags_silent_swallow_and_accepts_waiver():
    bad = """
        from parsec_tpu.core.errors import PeerFailedError

        def push(self):
            try:
                self.send()
            except PeerFailedError:
                pass
    """
    assert except_hygiene.check(_ctx(bad))
    waived = bad.replace(
        "pass",
        "# lint: contained (death already routed)\n                pass")
    assert not except_hygiene.check(_ctx(waived))


def test_except_accepts_pool_routed_handler():
    src = """
        from parsec_tpu.core.errors import PeerFailedError

        def push(self, tp):
            try:
                self.send()
            except PeerFailedError as exc:
                self.context.record_pool_error(tp, exc)
    """
    assert not except_hygiene.check(_ctx(src))


def test_except_accepts_task_attributed_record():
    src = """
        from parsec_tpu.core.errors import PeerFailedError

        def run(self, task):
            try:
                task.body()
            except Exception as exc:
                self.context.record_error(exc, task)
    """
    assert not except_hygiene.check(_ctx(src))


# ---------------------------------------------------------------------------
# PCL-HOT: per-task lock round-trips in the task hot path
# ---------------------------------------------------------------------------

def test_hot_flags_termdet_call_in_complete_execution():
    """The EXACT r14 bug class: the per-task locked termdet decrement
    inside the completion chain."""
    src = """
        def complete_execution(es, task, failed=False):
            tp = task.taskpool
            task.status = 4
            tp.termdet.taskpool_addto_nb_tasks(tp, -1)
    """
    fs = hot_path.check(_ctx(src, rel="parsec_tpu/core/snippet.py"))
    assert _ids(fs) == ["PCL-HOT"] and \
        "taskpool_addto_nb_tasks" in fs[0].message


def test_hot_flags_lock_reached_through_helper():
    """Same-file reachability: a `with self._lock` two calls below
    task_progress still flags, naming the root it was reached from."""
    src = """
        def task_progress(es, task):
            _account(es, task)

        def _account(es, task):
            _bump(es.metrics, task)

        def _bump(m, task):
            with m._lock:
                m.count += 1
    """
    fs = hot_path.check(_ctx(src, rel="parsec_tpu/core/snippet.py"))
    assert _ids(fs) == ["PCL-HOT"]
    assert "with _lock" in fs[0].message
    assert "reached from task_progress" in fs[0].message


def test_hot_flags_acquire_in_marked_ready_queue_callback():
    """ReadyQueue callbacks opt in via `# lint: hot-path` on the def
    line (the scheduler schedule/select convention)."""
    src = """
        class Sched:
            # lint: hot-path (per scheduling event)
            def schedule(self, es, tasks, distance=0):
                self._qlock.acquire()
                try:
                    self._q.extend(tasks)
                finally:
                    self._qlock.release()
    """
    fs = hot_path.check(_ctx(src, rel="parsec_tpu/sched/snippet.py"))
    assert _ids(fs) == ["PCL-HOT"] and ".acquire()" in fs[0].message


def test_hot_flags_lock_construction():
    src = """
        import threading

        def task_progress(es, task):
            gate = threading.Lock()
            with gate:
                pass
    """
    fs = hot_path.check(_ctx(src, rel="parsec_tpu/core/snippet.py"))
    assert any("threading.Lock() construction" in f.message for f in fs)


def test_hot_waiver_and_cold_functions_untouched():
    """The batch-boundary flush carries a waiver; functions not
    reachable from a hot root never flag."""
    src = """
        def worker_loop(es):
            while True:
                _flush(es)

        def _flush(es):
            for tp, ent in es._td_acc.items():
                tp.termdet.taskpool_addto_nb_tasks(  # lint: ignore[PCL-HOT] batch boundary
                    tp, -ent[1], epoch=ent[0])

        def cold_admin_path(tp):
            with tp._lock:
                tp.nb_tasks = 0
    """
    assert not hot_path.check(
        _ctx(src, rel="parsec_tpu/core/snippet.py"))


# ---------------------------------------------------------------------------
# PCL-ASSERT: -O hazards
# ---------------------------------------------------------------------------

def test_assert_flags_module_level():
    """The TAG_NAMES class: an import-time wire-protocol invariant as
    an assert vanishes under python -O."""
    src = """
        TAGS = {"ACT": 1}
        assert TAGS["ACT"] == 1
    """
    fs = assert_hazard.check(_ctx(src))
    assert _ids(fs) == ["PCL-ASSERT"] and "module-level" in fs[0].message


def test_assert_flags_side_effecting_condition():
    src = """
        def f(q):
            assert q.pop() == 1
    """
    fs = assert_hazard.check(_ctx(src))
    assert _ids(fs) == ["PCL-ASSERT"] and ".pop" in fs[0].message


def test_assert_accepts_pure_conditions():
    src = """
        def f(xs, x):
            assert len(xs) > 0
            assert isinstance(x, int)
            assert x > 0, "message"
    """
    assert not assert_hazard.check(_ctx(src))


def test_assert_inline_suppression():
    src = """
        def f(q):
            assert q.flush()   # lint: ignore[PCL-ASSERT] test helper
    """
    assert not assert_hazard.check(_ctx(src))


# ---------------------------------------------------------------------------
# PCL-JRNL: control-plane journal schema drift
# ---------------------------------------------------------------------------

_JRNL_SCHEMA = """
    EVENT_SCHEMA = {
        "mode_decl": ("pool", "round", "mode", "peers"),
        "retired": ("pool",),
    }
"""

_JRNL_SCHEMA_REL = "parsec_tpu/prof/journal.py"


def _jrnl_run(sources):
    """sources: {rel: code}; the schema module above is always in
    scope (the pass's existence gate)."""
    ctxs = {rel: _ctx(src, rel=rel) for rel, src in sources.items()}
    facts = [journal_schema.facts(c) for c in ctxs.values()]
    return journal_schema.tree_check(facts, REPO, ctxs)


def test_jrnl_flags_unknown_event_type():
    """The encoded bug class: an emit whose type never entered the
    schema table is an event journal_audit cannot attribute."""
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py":
            'jr.emit("mode_declared", pool=1, round=2)\n'})
    assert _ids(fs) == ["PCL-JRNL"]
    assert "mode_declared" in fs[0].message


def test_jrnl_flags_round_scoped_emit_without_round():
    """Round-scoped protocol emits must carry round= — an emit the
    auditor cannot place in a round is one it cannot check."""
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py":
            'jr.emit("mode_decl", pool=1, mode="full", peers=[0])\n'})
    assert _ids(fs) == ["PCL-JRNL"]
    assert "round" in fs[0].message


def test_jrnl_flags_starstar_hiding_required_fields():
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py":
            'jr.emit("retired", **fields)\n'})
    assert _ids(fs) == ["PCL-JRNL"]
    assert "**kwargs" in fs[0].message


def test_jrnl_flags_non_literal_type_and_attr_receiver():
    """Computed event types flag; the attribute-chain receiver form
    (self.context.journal.emit) is recognized too."""
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py": """
            self.context.journal.emit(etype, pool=1)
        """})
    assert _ids(fs) == ["PCL-JRNL"]
    assert "non-literal" in fs[0].message


def test_jrnl_accepts_schema_conformant_emits():
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py": """
            jr.emit("mode_decl", pool=1, round=2, mode="minimal",
                    peers=[0, 1], extra="free-form is fine")
            ctx.journal.emit("retired", pool=1)
        """})
    assert fs == []


def test_jrnl_partial_scan_is_silent():
    """Without the schema module in the scanned set the cross-check
    stays off (the schema universe is incomplete)."""
    fs = _jrnl_run({
        "parsec_tpu/core/snip.py": 'jr.emit("bogus_event", pool=1)\n'})
    assert fs == []


def test_jrnl_inline_suppression():
    fs = _jrnl_run({
        _JRNL_SCHEMA_REL: _JRNL_SCHEMA,
        "parsec_tpu/core/snip.py":
            'jr.emit("oddball")  '
            '# lint: ignore[PCL-JRNL] prototype event\n'})
    assert fs == []


def test_jrnl_real_schema_covers_every_tree_emit():
    """Meta-gate on the REAL schema: every required-field tuple in
    prof/journal.py is well-formed and the live EVENT_SCHEMA parses
    out of the AST exactly as the runtime dict."""
    import ast as _ast
    from parsec_tpu.prof.journal import EVENT_SCHEMA
    with open(os.path.join(REPO, _JRNL_SCHEMA_REL)) as fh:
        tree = _ast.parse(fh.read())
    parsed = journal_schema._schema_from_tree(tree)
    assert parsed == {k: list(v) for k, v in EVENT_SCHEMA.items()}


# ---------------------------------------------------------------------------
# driver: baseline + the clean-tree tier-1 gate
# ---------------------------------------------------------------------------

def test_baseline_roundtrip(tmp_path):
    from tools.parseclint.engine import load_baseline, write_baseline
    f1 = Finding("a.py", 10, "PCL-LOCK", "msg one")
    f2 = Finding("b.py", 20, "PCL-MCA", "msg two")
    path = str(tmp_path / "baseline.txt")
    write_baseline([f1, f2], path)
    allowed = load_baseline(path)
    assert allowed[f1.baseline_key()] == 1
    # line shifts keep the identity: same path/pass/message matches
    shifted = Finding("a.py", 99, "PCL-LOCK", "msg one")
    assert shifted.baseline_key() in allowed


def test_clean_tree_zero_findings():
    """THE gate: the real tree, against the checked-in baseline, has
    zero new findings.  Every guarded-by/on-loop annotation, waiver,
    and knob-table entry in the repo is live input to this test —
    tier-1 fails on any new violation, which is what makes parseclint
    a pre-merge invariant rather than advice."""
    from tools.parseclint.engine import run
    new, baselined, errors = run(
        [os.path.join(REPO, "parsec_tpu")], use_processes=False)
    assert errors == [], errors
    assert new == [], "\n".join(f.render() for f in new)
