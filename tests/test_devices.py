"""Device-layer tests: XLA offload path on the virtual CPU mesh.

Mirrors the reference's GPU test strategy (reference: tests/dsl/ptg/cuda/
stress.jdf throughput, get_best_device_check.jdf placement; SURVEY.md §4):
device tasks run through the real stage-in / dispatch / async-complete
pipeline, on jax CPU devices standing in for TPU chips.
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.devices.device import DeviceRegistry, HostDevice
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.utils.mca import params


def make_ctx(**kw):
    return Context(nb_cores=2, **kw)


def test_registry_attach_and_spaces():
    reg = DeviceRegistry()
    assert reg.host.space == 0
    from parsec_tpu.devices.xla import XlaDevice
    import jax
    d = reg.attach(XlaDevice(jax.devices()[0]))
    assert d.space == 1
    assert reg.get(1) is d
    assert reg.accelerators == [d]
    d.fini()


def test_context_attaches_xla_devices():
    with make_ctx() as ctx:
        assert len(ctx.device_registry.accelerators) >= 1
        for d in ctx.device_registry.accelerators:
            assert d.kind in ("xla", "tpu")


def _chain_ptg(A, nt, device):
    """S(k): T = T@T' chain through a single tile, alternating devices."""
    p = PTG("chain", NT=nt)
    p.task("S", k=Range(0, nt - 1)) \
        .affinity(lambda k, A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                  when=lambda k, NT=nt: k < NT - 1),
              OUT(DATA(lambda A=A: A(0, 0)),
                  when=lambda k, NT=nt: k == NT - 1)) \
        .body(lambda T: T + 1.0, device=device)
    return p.build()


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_device_chain_matches_cpu(device):
    A = TwoDimBlockCyclic(mb=8, nb=8, lm=8, ln=8)
    tile = A.data_of(0, 0).copy_on(0).payload
    tile[:] = 0.0
    with make_ctx() as ctx:
        ctx.add_taskpool(_chain_ptg(A, 10, device))
        ctx.wait()
    np.testing.assert_allclose(np.asarray(A.data_of(0, 0).pull_to_host().payload),
                               np.full((8, 8), 10.0), rtol=1e-6)


def test_device_gemm_tiles_correct():
    """Tiled C += A@B on devices vs numpy."""
    mt = nt = kt = 2
    mb = 16
    rng = np.random.default_rng(0)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb,
                          name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb,
                          name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb,
                          name="C")
    for M in (A, B, C):
        for m, n in M.local_tiles():
            M.data_of(m, n).copy_on(0).payload[:] = rng.standard_normal((mb, mb),
                                                          ).astype(np.float32)
    refA = A.to_array().copy()
    refB = B.to_array().copy()
    refC = C.to_array() + refA @ refB

    p = PTG("gemm", MT=mt, NT=nt, KT=kt)
    p.task("GEMM", m=Range(0, mt - 1), n=Range(0, nt - 1),
           k=Range(0, kt - 1)) \
        .affinity(lambda m, n, C=C: C(m, n)) \
        .flow("Ai", "READ", IN(DATA(lambda m, k, A=A: A(m, k)))) \
        .flow("Bi", "READ", IN(DATA(lambda k, n, B=B: B(k, n)))) \
        .flow("Ci", "RW",
              IN(DATA(lambda m, n, C=C: C(m, n)), when=lambda k: k == 0),
              IN(TASK("GEMM", "Ci", lambda m, n, k: dict(m=m, n=n, k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("GEMM", "Ci",
                       lambda m, n, k: dict(m=m, n=n, k=k + 1)),
                  when=lambda k, KT=kt: k < KT - 1),
              OUT(DATA(lambda m, n, C=C: C(m, n)),
                  when=lambda k, KT=kt: k == KT - 1)) \
        .body(lambda Ai, Bi, Ci: Ci + Ai @ Bi, device="tpu")
    with make_ctx() as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait()
    np.testing.assert_allclose(C.to_array(), refC, rtol=1e-4, atol=1e-4)


def test_device_fallback_to_cpu_body():
    """tpu incarnation declines when no accelerator: cpu body runs."""
    params.set("device_enabled", 0)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0
        with make_ctx() as ctx:
            assert ctx.device_registry.accelerators == []
            p = PTG("fb", NT=1)
            p.task("S", k=Range(0, 0)) \
                .affinity(lambda k, A=A: A(0, 0)) \
                .flow("T", "RW", IN(DATA(lambda A=A: A(0, 0))),
                      OUT(DATA(lambda A=A: A(0, 0)))) \
                .body(lambda T: T + 7.0, device="tpu") \
                .body(lambda T: T + np.float32(3.0))
            ctx.add_taskpool(p.build())
            ctx.wait()
        assert np.asarray(A.data_of(0, 0).pull_to_host().payload)[0, 0] == 3.0
    finally:
        params.unset("device_enabled")


def test_lru_eviction_under_pressure():
    """Tiny copy-cache capacity forces evictions yet stays correct."""
    params.set("device_mem_mb", 1)     # 1 MiB cap
    params.set("device_max", 1)
    try:
        nt = 24
        mb = 128                        # 64 KiB per f32 tile; 24 > 1 MiB cap
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=nt * mb, ln=mb)
        for m, n in A.local_tiles():
            A.data_of(m, n).copy_on(0).payload[:] = float(m)
        with make_ctx() as ctx:
            # three chained sweeps over all tiles: proper dep edges between
            # revisits (racing on a tile without deps is UB, as in JDF)
            p = PTG("sweep", NT=nt)
            p.task("S", rep=Range(0, 2), m=Range(0, nt - 1)) \
                .affinity(lambda m, A=A: A(m, 0)) \
                .flow("T", "RW",
                      IN(DATA(lambda m, A=A: A(m, 0)),
                         when=lambda rep: rep == 0),
                      IN(TASK("S", "T", lambda rep, m: dict(rep=rep - 1,
                                                            m=m)),
                         when=lambda rep: rep > 0),
                      OUT(TASK("S", "T", lambda rep, m: dict(rep=rep + 1,
                                                             m=m)),
                          when=lambda rep: rep < 2),
                      OUT(DATA(lambda m, A=A: A(m, 0)),
                          when=lambda rep: rep == 2)) \
                .body(lambda T: T + 1.0, device="tpu")
            ctx.add_taskpool(p.build())
            ctx.wait()
            dev = ctx.device_registry.accelerators[0]
            stats = dev.stats
        for m, n in A.local_tiles():
            np.testing.assert_allclose(
                np.asarray(A.data_of(m, n).pull_to_host().payload),
                float(m) + 3.0)
        assert stats.evictions > 0
        assert stats.executed_tasks == 3 * nt
    finally:
        params.unset("device_mem_mb")
        params.unset("device_max")


def test_best_device_load_balance():
    """Without affinity hints, tasks spread across devices by load."""
    with make_ctx() as ctx:
        accs = ctx.device_registry.accelerators
        if len(accs) < 2:
            pytest.skip("needs >=2 jax devices")
        nt = 24
        A = TwoDimBlockCyclic(mb=8, nb=8, lm=nt * 8, ln=8)
        for m, n in A.local_tiles():
            A.data_of(m, n).copy_on(0).payload[:] = 1.0
        p = PTG("spread", NT=nt)
        p.task("S", m=Range(0, nt - 1)) \
            .affinity(lambda m, A=A: A(m, 0)) \
            .flow("T", "RW", IN(DATA(lambda m, A=A: A(m, 0))),
                  OUT(DATA(lambda m, A=A: A(m, 0)))) \
            .body(lambda T: T * 2.0, device="tpu")
        ctx.add_taskpool(p.build())
        ctx.wait()
        used = sum(1 for d in accs if d.stats.executed_tasks > 0)
        assert used >= 2


def test_device_fault_degrades_to_cpu():
    """Degraded mode (reference: device_cuda_module.c:2757-2762 — GPU
    errors disable the device and tasks fall back to the CPU
    incarnation, the reference's only fault tolerance)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    from parsec_tpu.utils.mca import params

    NT = 6
    V = VectorTwoDimCyclic(mb=4, lm=4 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 1.0

    def bad_kernel(X):
        raise RuntimeError("injected device fault")

    params.set("device_max_faults", 2)
    try:
        with Context(nb_cores=2) as ctx:
            if not ctx.device_registry.accelerators:
                pytest.skip("no accelerator attached")
            p = PTG("faulty", NT=NT)
            tb = p.task("T", k=Range(0, NT - 1)) \
                .affinity(lambda k, V=V: V(k)) \
                .flow("X", "RW",
                      IN(DATA(lambda k, V=V: V(k))),
                      OUT(DATA(lambda k, V=V: V(k))))
            tb.body(bad_kernel, device="tpu")
            tb.body(lambda X: X + 1.0)          # the CPU fallback
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=120)
            dev = ctx.device_registry.devices[1]
            assert not dev.enabled
            assert dev.stats.faults >= 2
    finally:
        params.unset("device_max_faults")
    for m in range(NT):
        np.testing.assert_allclose(
            np.asarray(V.data_of(m).pull_to_host().payload), 2.0)


def test_wavefront_fusion_batches_same_class_waves():
    """Wavefront launch fusion: when the device queue holds a wave of
    same-class ready tasks, the manager dispatches them as ONE jitted
    program (reference analog: the GPU manager draining its pending FIFO
    into exec streams, device_cuda_module.c:2697 — here the drain fuses
    the wave, amortizing per-launch latency on tunneled TPUs)."""
    import time as _time

    from parsec_tpu.core.context import Context

    MT = 16
    mb = 8
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mb, ln=MT * mb)
    rng = np.random.default_rng(3)
    ref = {}
    for _m, n in A.local_tiles():
        t = rng.standard_normal((mb, mb)).astype(np.float32)
        A.data_of(0, n).copy_on(0).payload[:] = t
        ref[n] = t * 2.0

    def mul_kernel(T):
        # trace-time stall (runs ONCE per compile, not per task): the
        # first launch traces while the rest of the wave queues behind
        # it, making the fusion window deterministic for the test
        _time.sleep(0.05)
        return T * 2.0

    params.set("device_fuse", 8)
    params.set("device_max", 1)   # one device => the whole wave queues there
    try:
        with Context(nb_cores=2) as ctx:
            if not ctx.device_registry.accelerators:
                pytest.skip("no accelerator attached")
            p = PTG("wave", MT=MT)
            tb = p.task("MUL", n=Range(0, MT - 1)) \
                .affinity(lambda n, A=A: A(0, n)) \
                .flow("T", "RW",
                      IN(DATA(lambda n, A=A: A(0, n))),
                      OUT(DATA(lambda n, A=A: A(0, n))))
            tb.body(mul_kernel, device="tpu")
            tb.body(lambda T: np.asarray(T) * 2.0)
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=120)
            dev = ctx.device_registry.devices[1]
            assert dev.stats.executed_tasks == MT
            # the wave behind the first (tracing) launch must have fused
            assert dev.stats.fused_launches >= 1
            assert dev.stats.fused_tasks >= 2
    finally:
        params.unset("device_fuse")
        params.unset("device_max")
    for n in range(MT):
        np.testing.assert_allclose(
            np.asarray(A.data_of(0, n).pull_to_host().payload), ref[n],
            rtol=1e-6)


def test_cross_panel_chain_fusion_potrf():
    """r6 tentpole: cross-panel fused dispatch — POTRF(k) is HELD at
    the device (its deps release eagerly with Deferred payloads) and
    its kernel is traced INTO the TRSM wave's launch, so the panel
    chain rides one dispatch.  The result must match numpy and the
    chained counters must show the fusion actually ran; the A/B knob
    (PARSEC_MCA_DEVICE_FUSE_PANEL=0) must reproduce the per-kernel
    path with zero chained launches."""
    from parsec_tpu.apps.potrf import potrf_taskpool

    def run(fuse_panel):
        mb, nt = 16, 5
        n = nt * mb
        rng = np.random.default_rng(21)
        B = rng.standard_normal((n, n)).astype(np.float32)
        spd = (B @ B.T + n * np.eye(n)).astype(np.float32)
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n,
                              ln=n).from_array(spd.copy())
        params.set("device_fuse_panel", fuse_panel)
        try:
            with Context(nb_cores=4) as ctx:
                if not ctx.device_registry.accelerators:
                    pytest.skip("no accelerator attached")
                ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
                ctx.wait(timeout=120)
                st = ctx.device_registry.accelerators[0].stats
                chained = (st.chained_launches, st.chained_tasks)
        finally:
            params.unset("device_fuse_panel")
        L = np.tril(A.to_array())
        err = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
        assert err < 1e-4, err
        return chained

    launches, tasks = run(1)
    assert launches > 0 and tasks > launches   # chains really fused
    launches, tasks = run(0)                   # A/B attribution knob
    assert launches == 0 and tasks == 0


def test_cross_panel_chain_fusion_qr_column():
    """The GEQRT -> TSQRT column chain: successive holds stack their
    placeholders on the SAME RW copy; the TSMQR/UNMQR waves force the
    chain and the factorization stays exact (regression for the
    resolution identity check)."""
    from parsec_tpu.apps.qr import qr_taskpool
    mb, nt = 8, 5
    n = nt * mb
    rng = np.random.default_rng(22)
    a = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(a.copy())
    with Context(nb_cores=4) as ctx:
        if not ctx.device_registry.accelerators:
            pytest.skip("no accelerator attached")
        ctx.add_taskpool(qr_taskpool(A, device="tpu"))
        ctx.wait(timeout=120)
        st = ctx.device_registry.accelerators[0].stats
        assert st.chained_launches > 0
    out = A.to_array()
    R = np.triu(out)
    ata = a.T @ a
    assert np.abs(np.tril(out, -1)).max() < 1e-4
    assert np.abs(R.T @ R - ata).max() / np.abs(ata).max() < 1e-4


def test_chain_hold_resolves_at_sync_without_consumer():
    """A held chain whose consumers run on the CPU incarnation (or
    never arrive) must still dispatch: stage_in_host forces the
    Deferred, and device sync resolves any straggler holds."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    mb, nt = 8, 3
    n = nt * mb
    rng = np.random.default_rng(23)
    B = rng.standard_normal((n, n)).astype(np.float32)
    spd = (B @ B.T + n * np.eye(n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(spd.copy())
    p = potrf_taskpool(A, device="tpu")
    # force every TRSM to the cpu incarnation: the held POTRF's W
    # output reaches a CPU body as a Deferred payload
    trsm = p.task_classes["TRSM"]
    for idx, (dev_type, _hook) in enumerate(trsm.incarnations):
        if dev_type != "cpu":
            trsm.chore_disabled_mask |= 1 << idx
    with Context(nb_cores=2) as ctx:
        if not ctx.device_registry.accelerators:
            pytest.skip("no accelerator attached")
        ctx.add_taskpool(p)
        ctx.wait(timeout=120)
    L = np.tril(A.to_array())
    err = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
    assert err < 1e-4, err
