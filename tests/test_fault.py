"""Fault-injection + failure-lifecycle tests (ISSUE 5: inject -> detect
-> contain -> diagnose).

In-process tests drive the transport/detector machinery directly (two
EventLoopCEs on loopback); end-to-end cases spawn 2-rank workloads under
seeded fault plans through the chaos harness's environment contract
(``PARSEC_MCA_FAULT_PLAN`` is inherited by spawned ranks and armed at
import, utils/faultinject.py)."""

import os
import subprocess
import sys
import time

import numpy as np
import pytest

from parsec_tpu.core.errors import (FaultInjected, PeerFailedError,
                                    TaskRetryExhausted)
from parsec_tpu.utils import faultinject
from parsec_tpu.utils.mca import params

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# plan grammar
# ---------------------------------------------------------------------------

def test_fault_plan_parsing():
    plan = faultinject.FaultPlan(
        "seed=9;drop_frame=tag:ACT,p=0.25,n=3;"
        "delay_frame=tag:DTD,pm='ver': 0,ms=250;"
        "kill_rank=1@t+2.5s,mode=hang;fail_task=key~POTRF(k=0),n=2;"
        "delay_dispatch=ms=5,p=0.1")
    assert plan.seed == 9
    kinds = [d.kind for d in plan.directives]
    assert kinds == ["drop_frame", "delay_frame", "kill_rank",
                     "fail_task", "delay_dispatch"]
    drop, delay, kill, ftask, disp = plan.directives
    assert drop.tag == 1 and drop.p == 0.25 and drop.n == 3
    assert delay.pm == "'ver': 0" and delay.ms == 250.0
    assert kill.rank == 1 and kill.at_s == 2.5 and kill.mode == "hang"
    assert ftask.key == "POTRF(k=0)" and ftask.n == 2
    assert disp.ms == 5.0 and disp.p == 0.1


def test_fault_plan_take_counts_and_determinism():
    faultinject.arm("seed=3;drop_frame=tag:ACT,n=2")
    try:
        cf = faultinject.comm_faults(0)
        hits = [cf.frame_action(1, 1, None) for _ in range(5)]
        assert [h is not None for h in hits] == [True, True, False,
                                                False, False]
        # seeded determinism: the same plan + rank replays the stream
        faultinject.arm("seed=3;drop_frame=tag:ACT,p=0.5")
        a = [faultinject.comm_faults(1).frame_action(1, 0, None)
             is not None for _ in range(1)]
        b = [faultinject.comm_faults(1).frame_action(1, 0, None)
             is not None for _ in range(1)]
        assert a == b
    finally:
        faultinject.disarm()
    assert not faultinject.ARMED


def test_delay_recv_parsing_and_matching():
    """ISSUE 7 satellite: recv-side per-frame delay (reorder coverage
    on the RECEIVE path — send-side delays cannot reorder what TCP
    delivers in stream order)."""
    plan = faultinject.FaultPlan(
        "seed=4;delay_recv=tag:DTD,p=0.5,ms=120,rank=1")
    (d,) = plan.directives
    assert d.kind == "delay_recv" and d.tag == 6 and d.ms == 120.0 \
        and d.rank == 1 and d.p == 0.5
    faultinject.arm("seed=4;delay_recv=tag:DTD,n=1,ms=50,rank=1")
    try:
        cf = faultinject.comm_faults(0)
        assert cf is not None and len(cf.recv_dirs) == 1
        # rank= scopes by SOURCE rank on the receive side
        assert cf.recv_delay_ms(6, 2, None) is None
        assert cf.recv_delay_ms(6, 1, None) == 50.0
        assert cf.recv_delay_ms(6, 1, None) is None   # n=1 consumed
        # outbound frame directives unaffected by a recv-only plan
        assert cf.frame_action(6, 1, None) is None
    finally:
        faultinject.disarm()


def test_delay_recv_reorders_dispatch_on_receive_path():
    """Two frames sent in order on one TCP stream dispatch REVERSED at
    the receiver when a delay_recv holds the first — the hook must not
    stall the loop (later frames flow during the hold), and the held
    frame's handler still runs (on the loop thread: the funnelled
    redelivery re-posts instead of dispatching off-thread)."""
    from parsec_tpu.comm.launch import _probe_port_base

    # WIDE margin (hold 1.2s vs 0.1s send gap): strict-order asserts
    # with tight margins are exactly the load-sensitive flake class
    # this repo keeps retiring — the second frame has >1s of slack to
    # dispatch before the held frame's redelivery timer fires
    faultinject.arm("seed=1;delay_recv=tag:16,n=1,ms=1200")
    try:
        ce0, ce1 = _pair_of_engines(_probe_port_base(2))
        try:
            got = []
            ce1.tag_register(16, lambda src, msg: got.append(msg["i"]))
            time.sleep(0.3)   # both lanes dialed in
            ce0.send_am(16, 1, {"i": 1})   # held 1.2s at the receiver
            time.sleep(0.1)
            ce0.send_am(16, 1, {"i": 2})   # flows past the held frame
            deadline = time.monotonic() + 6.0
            while len(got) < 2 and time.monotonic() < deadline:
                time.sleep(0.02)
            assert got == [2, 1], got
        finally:
            ce0.fini()
            ce1.fini()
    finally:
        faultinject.disarm()


def test_unarmed_hooks_are_inert():
    assert faultinject.comm_faults(0) is None
    assert faultinject.runtime() is None


# ---------------------------------------------------------------------------
# detect: hard close vs silent hang (the two detector paths)
# ---------------------------------------------------------------------------

def _pair_of_engines(port_base):
    from parsec_tpu.comm.engine import EventLoopCE
    ce0 = EventLoopCE(0, 2, port_base)
    ce1 = EventLoopCE(1, 2, port_base)
    return ce0, ce1


def test_hard_close_vs_silent_hang_detection_latency():
    """EOF detection is immediate; a HUNG peer (sockets open, nothing
    flowing) is only caught by the heartbeat timeout — within 2x
    comm_peer_timeout_s (the ISSUE acceptance bound)."""
    from parsec_tpu.comm.launch import _probe_port_base

    params.set("comm_peer_timeout_s", 1.0)
    try:
        # --- silent hang ---------------------------------------------
        ce0, ce1 = _pair_of_engines(_probe_port_base(2))
        errors = []
        ce0.on_error = errors.append
        try:
            for ce in (ce0, ce1):
                ce.add_periodic(ce.heartbeat_tick, 0.25)
                ce.add_periodic(ce.check_peer_timeouts, 0.25)
            time.sleep(0.8)          # a few heartbeat rounds flow
            assert not ce0.dead_peers
            t0 = time.monotonic()
            ce1.fault_kill("hang")   # mute: sockets stay OPEN
            deadline = t0 + 4.0
            while 1 not in ce0.dead_peers and time.monotonic() < deadline:
                time.sleep(0.02)
            dt = time.monotonic() - t0
            assert 1 in ce0.dead_peers, "hung peer never declared dead"
            assert dt <= 2.0 * 1.0 + 0.6, f"detection took {dt:.2f}s"
            assert errors and isinstance(errors[0], PeerFailedError)
            assert errors[0].rank == 1
            assert errors[0].detector == "heartbeat"
        finally:
            ce0.fini()
            ce1.fini()
        # --- hard close ----------------------------------------------
        ce0, ce1 = _pair_of_engines(_probe_port_base(2))
        errors = []
        ce0.on_error = errors.append
        try:
            time.sleep(0.3)
            t0 = time.monotonic()
            ce1.fault_kill("close")  # abrupt EOF on every socket
            deadline = t0 + 3.0
            while 1 not in ce0.dead_peers and time.monotonic() < deadline:
                time.sleep(0.02)
            dt = time.monotonic() - t0
            assert 1 in ce0.dead_peers, "closed peer never declared dead"
            assert dt <= 1.0, f"EOF detection took {dt:.2f}s"
            assert errors and isinstance(errors[0], PeerFailedError)
        finally:
            ce0.fini()
            ce1.fini()
    finally:
        params.unset("comm_peer_timeout_s")


def test_silent_hang_detection_threads_transport():
    """The legacy threads transport detects a silent hang too, and its
    heartbeat discipline is NONBLOCKING — a hung peer's full send buffer
    or an undialed rank must not wedge the thread that runs the
    detector (SocketCE._hb_send: established + try-lock + writability
    gates)."""
    from parsec_tpu.comm.engine import SocketCE
    from parsec_tpu.comm.launch import _probe_port_base

    params.set("comm_peer_timeout_s", 1.0)
    try:
        base = _probe_port_base(2)
        ce0, ce1 = SocketCE(0, 2, base), SocketCE(1, 2, base)
        errors = []
        ce0.on_error = errors.append
        try:
            for _ in range(8):       # connect + a few beats each way
                ce0.heartbeat_tick()
                ce1.heartbeat_tick()
                time.sleep(0.05)
            ce0.check_peer_timeouts()
            assert not ce0.dead_peers
            t0 = time.monotonic()
            ce1.fault_kill("hang")   # mute: sockets stay OPEN
            deadline = t0 + 4.0
            while 1 not in ce0.dead_peers and time.monotonic() < deadline:
                ce0.heartbeat_tick()     # must never block
                ce0.check_peer_timeouts()
                time.sleep(0.05)
            dt = time.monotonic() - t0
            assert 1 in ce0.dead_peers, "hung peer never declared dead"
            assert dt <= 2.0 * 1.0 + 0.6, f"detection took {dt:.2f}s"
            assert errors and isinstance(errors[0], PeerFailedError)
            assert errors[0].detector == "heartbeat"
        finally:
            ce0.fini()
            ce1.fini()
    finally:
        params.unset("comm_peer_timeout_s")


def test_starved_checker_rebases_instead_of_declaring():
    """A checker that itself was starved past the timeout (GIL storm)
    must NOT declare peers dead from its own silence."""
    from parsec_tpu.comm.engine import CommEngine

    params.set("comm_peer_timeout_s", 0.5)
    try:
        ce = CommEngine(0, 2)
        ce._last_heard[1] = time.monotonic() - 10.0
        ce._hb_check_at = time.monotonic() - 10.0   # WE were frozen
        ce.check_peer_timeouts()
        assert 1 not in ce.dead_peers
        # the rebase reset the peer's clock; sustained silence past a
        # HEALTHY check interval still declares
        ce._last_heard[1] = time.monotonic() - 10.0
        ce.check_peer_timeouts()
        assert 1 in ce.dead_peers
    finally:
        params.unset("comm_peer_timeout_s")


# ---------------------------------------------------------------------------
# end-to-end: frame faults + kills through the chaos harness contract
# ---------------------------------------------------------------------------

def _chaos(only, seeds=1, timeout=300):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "chaos.py"),
         "--seeds", str(seeds), "--only", only],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=timeout)


def test_chaos_frame_drop_dup_recovery():
    """Dropped GET_REP frames recover through rendezvous retry; dup'd
    activation/DTD frames are deduplicated — both complete CORRECTLY
    (the workloads validate their numbers internally)."""
    proc = _chaos("drop-getrep,dup-frames,dup-potrf", seeds=3)
    assert proc.returncode == 0, proc.stdout + proc.stderr


def test_chaos_kill_mid_run_fails_cleanly():
    """2-rank kill mid-workload: structured PeerFailedError, no hang,
    well inside the harness deadline."""
    proc = _chaos("kill-close,trunc-act", seeds=2)
    assert proc.returncode == 0, proc.stdout + proc.stderr


@pytest.mark.slow
def test_chaos_full_catalog():
    """The ISSUE acceptance run: 12 seeded plans, zero hangs, zero
    silent wrong answers (incl. the silent-hang kill detected by
    heartbeat within 2x comm_peer_timeout_s)."""
    proc = _chaos("", seeds=12, timeout=900)
    assert proc.returncode == 0, proc.stdout + proc.stderr


# ---------------------------------------------------------------------------
# contain: rendezvous terminal timeout, retry, service degraded mode
# ---------------------------------------------------------------------------

def _run_distributed_with_env(fn, nranks, env, timeout=120):
    from parsec_tpu.comm.launch import run_distributed
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        return run_distributed(fn, nranks, timeout=timeout)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v


def test_rendezvous_terminal_timeout():
    """Every GET_REP dropped: bounded retries, then the pull fails its
    pool with a structured rendezvous PeerFailedError — no infinite
    wait."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    with pytest.raises(RuntimeError) as ei:
        _run_distributed_with_env(
            chaos.potrf_workload, 2,
            {"PARSEC_MCA_FAULT_PLAN": "seed=5;drop_frame=tag:GET_REP,p=1",
             "PARSEC_MCA_COMM_EAGER_LIMIT": "512",
             "PARSEC_MCA_COMM_ADAPTIVE_EAGER": "0",
             "PARSEC_MCA_COMM_RDV_RETRY_S": "0.3",
             "PARSEC_MCA_COMM_RDV_TIMEOUT_S": "3",
             "PARSEC_CHAOS_WAIT_S": "30"})
    text = str(ei.value)
    assert "PeerFailedError" in text and "rendezvous" in text, text


def test_task_retry_transient_then_success():
    """A transiently-failing idempotent body retries against PRISTINE
    inputs (write-flow snapshot) and the pool completes correctly."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.dsl.dtd import INOUT, DTDTaskpool

    params.set("task_retry_max", 2)
    try:
        with Context(nb_cores=2) as ctx:
            assert ctx._retry_max == 2
            tp = DTDTaskpool("retry")
            ctx.add_taskpool(tp)
            ctx.start()
            from parsec_tpu.data.data import new_data
            datum = new_data(np.full(4, 7.0, np.float32))
            attempts = []

            def flaky(T):
                arr = np.asarray(T)
                attempts.append(arr.copy())
                if len(attempts) == 1:
                    arr[:] = -1.0          # corrupt in place...
                    raise RuntimeError("transient glitch")
                return arr * 2.0
            tp.insert_task(flaky, (datum, INOUT))
            tp.wait(timeout=30)
            ctx.wait(timeout=30)
            assert len(attempts) == 2
            # the retry saw the ORIGINAL value, not the corruption
            np.testing.assert_allclose(attempts[1], 7.0)
            np.testing.assert_allclose(
                np.asarray(datum.pull_to_host().payload), 14.0)
    finally:
        params.unset("task_retry_max")


def test_task_retry_exhausted_is_structured():
    from parsec_tpu.core.context import Context
    from parsec_tpu.dsl.dtd import INOUT, DTDTaskpool

    params.set("task_retry_max", 1)
    try:
        with Context(nb_cores=2) as ctx:
            tp = DTDTaskpool("exhaust")
            ctx.add_taskpool(tp)
            ctx.start()
            from parsec_tpu.data.data import new_data
            datum = new_data(np.zeros(4, np.float32))
            calls = []

            def always_fails(T):
                calls.append(1)
                raise FaultInjected("injected, forever")
            tp.insert_task(always_fails, (datum, INOUT))
            with pytest.raises(RuntimeError):
                tp.wait(timeout=30)
            assert len(calls) == 2       # first try + one retry
            exc = ctx._errors[0][0]
            assert isinstance(exc, TaskRetryExhausted)
            assert exc.attempts == 2
            assert isinstance(exc.__cause__, FaultInjected)
    finally:
        params.unset("task_retry_max")


def _slow_chain_factory(name, nt=30, delay=0.02):
    """PTG increment chain over a private tile (the test_service idiom):
    slow enough that peer death can be injected mid-run."""
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK

    def factory():
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0

        def body(T, k):
            time.sleep(delay)
            return T + 1.0

        p = PTG(name, NT=nt)
        p.task("S", k=Range(0, nt - 1)) \
            .affinity(lambda k, A=A: A(0, 0)) \
            .flow("T", "RW",
                  IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                      when=lambda k, NT=nt: k < NT - 1),
                  OUT(DATA(lambda A=A: A(0, 0)),
                      when=lambda k, NT=nt: k == NT - 1)) \
            .body(body)

        def result():
            return float(np.asarray(
                A.data_of(0, 0).copy_on(0).payload)[0, 0])
        return p.build(), result
    return factory


def test_service_degraded_mode_keeps_serving():
    """A job killed by a dead peer flips the service into degraded mode
    (rank recorded on service + handle); unaffected jobs keep running
    and new submissions are still admitted."""
    from parsec_tpu.service.service import JobService
    from parsec_tpu.service.job import JobError, JobStatus

    with JobService(nb_cores=2) as svc:
        victim = svc.submit(_slow_chain_factory("victim"), name="victim")
        bystander = svc.submit(_slow_chain_factory("bystander"),
                               name="bystander")
        # wait until the victim's pool is attached, then inject the
        # peer death through the containment route
        deadline = time.monotonic() + 10
        while victim.taskpool is None and time.monotonic() < deadline:
            time.sleep(0.01)
        assert victim.taskpool is not None
        svc.context.record_pool_error(
            victim.taskpool,
            PeerFailedError(3, "rank 0: peer rank 3 disconnected",
                            detector="heartbeat"))
        victim.wait(timeout=10)
        assert victim.status() == JobStatus.FAILED
        assert victim.failed_rank == 3
        with pytest.raises(JobError):
            victim.result(timeout=5)
        # the service is degraded but SERVING: the bystander finishes,
        # and a fresh submission is admitted and runs
        assert svc.degraded and svc.degraded_ranks() == [3]
        assert svc.stats()["degraded_ranks"] == [3]
        assert bystander.result(timeout=30) == 30.0
        late = svc.submit(_slow_chain_factory("late", nt=3, delay=0.0),
                          name="late")
        assert late.result(timeout=30) == 3.0
        assert late.status() == JobStatus.DONE
        assert victim.info()["failed_rank"] == 3


# ---------------------------------------------------------------------------
# diagnose: the hang autopsy
# ---------------------------------------------------------------------------

def test_hang_autopsy_emitted_on_soft_deadline(capfd):
    from parsec_tpu.core.context import Context
    from parsec_tpu.dsl.dtd import DTDTaskpool

    params.set("runtime_autopsy_s", 0.4)
    try:
        with Context(nb_cores=1) as ctx:
            tp = DTDTaskpool("stuck")
            ctx.add_taskpool(tp)       # insertion hold: never completes
            ctx.start()
            with pytest.raises(TimeoutError):
                ctx.wait(timeout=1.2)
            report = ctx.hang_autopsy()
            assert "hang autopsy" in report
            assert "stuck" in report and "pending_actions=1" in report
            tp.wait(timeout=10)        # release the hold for teardown
            ctx.wait(timeout=10)
        err = capfd.readouterr().err
        assert "hang autopsy" in err   # the one-shot in-wait emission
    finally:
        params.unset("runtime_autopsy_s")


def test_autopsy_includes_comm_state():
    """debug_state feeds the autopsy: termdet balance, parked work,
    per-peer liveness ages."""
    from parsec_tpu.comm.launch import _probe_port_base

    ce0, ce1 = _pair_of_engines(_probe_port_base(2))
    try:
        time.sleep(0.2)
        dbg = ce0.peer_debug()
        assert 1 in dbg and "last_heard_age_s" in dbg[1]
        assert dbg[1]["dead"] is False
    finally:
        ce0.fini()
        ce1.fini()


# ---------------------------------------------------------------------------
# the r6 DTD region-lane stale read, now a replayable fault plan
# ---------------------------------------------------------------------------

def _region_plan_env(seed):
    return {"PARSEC_MCA_FAULT_PLAN":
            f"seed={seed};delay_frame=tag:DTD,pm='ver': 0,ms=600"}


def test_dtd_region_ordering_under_delay_plan():
    """The ~1/12 load-sensitive stale-chain read, forced DETERMINISTICALLY:
    delaying the version-0 pristine-pull payload past the chain's final
    write used to clobber the tile (whole-covering applies on disjoint
    lanes take no mutual edges and extent-less lanes have no slices to
    preserve).  The applied_ver landing-order guard in dsl/dtd/insert.py
    keeps the late v0 payload from regressing the tile."""
    from tests.test_dtd_distributed import _region_ordering_only
    res = _run_distributed_with_env(_region_ordering_only, 2,
                                    _region_plan_env(1), timeout=120)
    assert res == ["ok"] * 2


@pytest.mark.slow
def test_geqrf_chain_under_dispatch_delay():
    """The r7 geqrf wrong-R flake's replay conditions: chained panel
    dispatch (device_fuse_panel=1, the default) with seeded
    delay_dispatch perturbation.  The r8 regression guard (chained
    launches never donate, device_fuse_donate=0) must keep R correct."""
    from parsec_tpu.apps.qr import qr_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    faultinject.arm("seed=31;delay_dispatch=ms=4,p=0.3")
    try:
        for i in range(3):
            rng = np.random.default_rng(2)
            mb, nt = 32, 6
            n = mb * nt
            a = rng.standard_normal((n, n)).astype(np.float32)
            Q = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                                  name=f"Aqr_f{i}").from_array(a.copy())
            with Context(nb_cores=4) as ctx:
                Q.distribute_devices(ctx)
                ctx.add_taskpool(qr_taskpool(Q, device="tpu"))
                ctx.wait(timeout=300)
            out = Q.to_array()
            ata = a.T @ a
            R = np.triu(out)
            qerr = np.abs(R.T @ R - ata).max() / np.abs(ata).max()
            assert qerr < 1e-4, f"iter {i}: wrong R (qerr={qerr:.3e})"
    finally:
        faultinject.disarm()


@pytest.mark.slow
def test_dtd_region_ordering_under_delay_plan_20x():
    """The ISSUE satellite's acceptance loop: 20 seeded runs under the
    plan, all green."""
    from tests.test_dtd_distributed import _region_ordering_only
    for seed in range(1, 21):
        env = {"PARSEC_MCA_FAULT_PLAN":
               f"seed={seed};delay_frame=tag:DTD,p=0.5,ms=120"
               if seed % 2 else
               f"seed={seed};delay_frame=tag:DTD,pm='ver': 0,ms=600"}
        res = _run_distributed_with_env(_region_ordering_only, 2, env,
                                        timeout=120)
        assert res == ["ok"] * 2, f"seed {seed}"


# ---------------------------------------------------------------------------
# the flight recorder's incident path (ISSUE 8): chaos kill under an
# armed ring must yield a merged, clock-aligned incident bundle
# ---------------------------------------------------------------------------

def test_flightrec_kill_rank_yields_merged_bundle(tmp_path):
    """chaos ``kill_rank`` with the flight recorder armed: both ranks'
    rings land in ONE bundle directory, the merged trace is
    clock-aligned with matched comm_send/dep_deliver pairs covering
    the kill window, and ``tools/trace2chrome.py --merge`` opens it
    unchanged."""
    sys.path.insert(0, os.path.join(REPO, "tools"))
    import chaos
    bundle = str(tmp_path / "bundle")
    with pytest.raises(RuntimeError) as ei:
        # the potrf workload rides the PTG activation path, so the ring
        # holds dep_deliver points (the DTD path's deliveries are lane
        # applies); frame delays stretch the run past the kill instant
        # (kill at 1.2s with 250ms/frame delays on BOTH activation
        # tags: the threads transport's progress loop aggregates
        # same-destination activations into TAG_BATCH frames, which an
        # ACT-only plan misses — it then outran the old 0.8s/150ms
        # window and completed before the kill; 0.5s was conversely
        # too early for evloop's first delayed wave to have recorded
        # any flow.  This pairing holds the kill mid-run on all three
        # transports.)
        _run_distributed_with_env(
            chaos.potrf_workload, 2,
            {"PARSEC_MCA_FAULT_PLAN":
                 "seed=7;kill_rank=1@t+1.2s,mode=close;"
                 "delay_frame=tag:ACT,p=1,ms=250;"
                 "delay_frame=tag:BATCH,p=1,ms=250",
             "PARSEC_MCA_FLIGHTREC_ENABLED": "1",
             "PARSEC_MCA_FLIGHTREC_DIR": bundle,
             "PARSEC_CHAOS_WAIT_S": "30"})
    assert "PeerFailedError" in str(ei.value)
    # the survivor's containment dumped its ring; the killed rank's own
    # failing sends dumped the other side of every flow edge
    import glob
    traces = sorted(glob.glob(os.path.join(bundle, "rank*.ptt")))
    assert len(traces) == 2, traces
    from parsec_tpu.prof.flightrec import summarize_bundle
    s = summarize_bundle(bundle)
    assert s["ranks"] == [0, 1]
    assert s["events"] > 0
    assert s["flows"]["matched"] >= 1, s
    assert s["incidents"] and any("PeerFailedError" in i["reason"]
                                  or "error" in i["reason"]
                                  for i in s["incidents"])
    # the merged trace pairs sends with deliveries on the consumer oid
    from parsec_tpu.prof.critpath import merge_traces
    df, _metas = merge_traces(traces)
    sends = {tuple(r.info["corr"]) for r in
             df[df["name"] == "comm_send"].itertuples()
             if r.info and r.info.get("corr")}
    delivers = {tuple(r.info["corr"]) for r in
                df[df["name"] == "dep_deliver"].itertuples()
                if r.info and r.info.get("corr")}
    assert sends & delivers, (len(sends), len(delivers))
    # trace2chrome --merge opens the bundle unchanged
    out = str(tmp_path / "incident.json")
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "trace2chrome.py"),
         "--merge", *traces, "-o", out],
        cwd=REPO, env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    import json as json_mod
    with open(out) as fh:
        chrome = json_mod.load(fh)
    assert chrome["traceEvents"], "empty merged timeline"
    pids = {e.get("pid") for e in chrome["traceEvents"]}
    assert {0, 1} <= pids, pids


def test_flightrec_autopsy_names_bundle(tmp_path):
    """The hang autopsy's text points the reader at the incident
    bundle when the recorder is armed (and dumps it)."""
    from parsec_tpu.core.context import Context
    params.set("flightrec_enabled", 1)
    params.set("flightrec_dir", str(tmp_path))
    try:
        with Context(nb_cores=1) as ctx:
            report = ctx.hang_autopsy()
            assert "flight recorder incident bundle" in report
            assert str(tmp_path) in report
            assert "trace2chrome.py --merge" in report
            # the dump runs on its own thread (containment must not
            # stall the comm loop): wait for it to land
            deadline = time.monotonic() + 10
            while ctx._flightrec.incidents < 1 \
                    and time.monotonic() < deadline:
                time.sleep(0.02)
            assert ctx._flightrec.incidents == 1
            assert (tmp_path / "rank0.ptt").exists()
            # the dump is rate-limited: a second autopsy re-reports the
            # SAME bundle instead of thrashing the disk
            ctx.hang_autopsy()
            time.sleep(0.1)
            assert ctx._flightrec.incidents == 1
    finally:
        params.unset("flightrec_enabled")
        params.unset("flightrec_dir")


# ---------------------------------------------------------------------------
# the donation soak (ISSUE 8 satellite): device_fuse_donate default flip
# ---------------------------------------------------------------------------

def test_fuse_donate_default_on():
    """Post-soak default: chained launches donate; the knob remains the
    off-switch."""
    assert int(params.get("device_fuse_donate", 1)) == 1


@pytest.mark.slow
def test_fused_chain_donation_soak():
    """The ROADMAP-mandated soak behind the device_fuse_donate=1 flip:
    50+ fused-chain geqrf/potrf iterations under seeded delay_dispatch
    load, asserting ZERO wrong results.  (The r8 wrong-R reproduced at
    ~2/22 under this load before the device_put_private fix; the flip
    rides this green loop.)"""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.apps.qr import qr_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    assert int(params.get("device_fuse_donate", 1)) == 1
    faultinject.arm("seed=53;delay_dispatch=ms=3,p=0.3")
    try:
        mb, nt = 16, 5
        n = mb * nt
        rng = np.random.default_rng(8)
        with Context(nb_cores=4) as ctx:
            chained0 = sum(d.stats.chained_launches
                           for d in ctx.device_registry.accelerators)
            for i in range(52):
                if i % 2 == 0:
                    a = rng.standard_normal((n, n)).astype(np.float32)
                    Q = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                                          name=f"soakQ{i}").from_array(
                        a.copy())
                    Q.distribute_devices(ctx)
                    ctx.add_taskpool(qr_taskpool(Q, device="tpu"))
                    ctx.wait(timeout=120)
                    R = np.triu(Q.to_array())
                    ata = (a.T @ a).astype(np.float64)
                    qerr = np.abs(R.astype(np.float64).T @ R - ata).max() \
                        / np.abs(ata).max()
                    assert qerr < 1e-4, f"iter {i}: wrong R ({qerr:.3e})"
                else:
                    b = rng.standard_normal((n, n)).astype(np.float32)
                    spd = (b @ b.T + n * np.eye(n)).astype(np.float32)
                    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                                          name=f"soakA{i}").from_array(
                        spd.copy())
                    A.distribute_devices(ctx)
                    ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
                    ctx.wait(timeout=120)
                    L = np.tril(A.to_array()).astype(np.float64)
                    perr = np.abs(L @ L.T - spd).max() / np.abs(spd).max()
                    assert perr < 1e-4, f"iter {i}: wrong L ({perr:.3e})"
            chained = sum(d.stats.chained_launches
                          for d in ctx.device_registry.accelerators)
        # the soak must actually have exercised chained (donating)
        # launches, not the plain path
        assert chained > chained0, "no fused chains ran — soak is void"
    finally:
        faultinject.disarm()


# ---------------------------------------------------------------------------
# shm transport (r11): the ring transport must produce the SAME
# structured detectors and containment as TCP
# ---------------------------------------------------------------------------

def test_shm_hard_close_vs_silent_hang_detection():
    """Over shm rings: a hard kill surfaces as a closed-ring EOF
    immediately; a silent hang (rings open, nothing flowing) is caught
    by the heartbeat timeout within 2x comm_peer_timeout_s — the same
    detector latencies the TCP transports guarantee."""
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.shm import ShmCE

    params.set("comm_peer_timeout_s", 1.0)
    try:
        # --- silent hang ---------------------------------------------
        base = _probe_port_base(2)
        ce0, ce1 = ShmCE(0, 2, base), ShmCE(1, 2, base)
        errors = []
        ce0.on_error = errors.append
        try:
            for ce in (ce0, ce1):
                ce.add_periodic(ce.heartbeat_tick, 0.25)
                ce.add_periodic(ce.check_peer_timeouts, 0.25)
            # attach both directions (heartbeats only beat attached
            # rings; a real run attaches at the first activation)
            ce0.send_am(13, 1, None)
            ce1.send_am(13, 0, None)
            time.sleep(0.8)          # a few heartbeat rounds flow
            assert not ce0.dead_peers
            t0 = time.monotonic()
            ce1.fault_kill("hang")   # mute: rings stay OPEN
            deadline = t0 + 4.0
            while 1 not in ce0.dead_peers and time.monotonic() < deadline:
                time.sleep(0.02)
            dt = time.monotonic() - t0
            assert 1 in ce0.dead_peers, "hung shm peer never declared"
            assert dt <= 2.0 * 1.0 + 0.6, f"detection took {dt:.2f}s"
            assert errors and isinstance(errors[0], PeerFailedError)
            assert errors[0].rank == 1
            assert errors[0].detector == "heartbeat"
        finally:
            ce0.fini()
            ce1.fini()
        # --- hard close ----------------------------------------------
        base = _probe_port_base(2)
        ce0, ce1 = ShmCE(0, 2, base), ShmCE(1, 2, base)
        errors = []
        ce0.on_error = errors.append
        try:
            ce0.send_am(13, 1, None)
            ce1.send_am(13, 0, None)
            time.sleep(0.3)
            t0 = time.monotonic()
            ce1.fault_kill("close")  # closed flag on every ring
            deadline = t0 + 3.0
            while 1 not in ce0.dead_peers and time.monotonic() < deadline:
                time.sleep(0.02)
            dt = time.monotonic() - t0
            assert 1 in ce0.dead_peers, "closed shm peer never declared"
            assert dt <= 1.0, f"closed-ring detection took {dt:.2f}s"
            assert errors and isinstance(errors[0], PeerFailedError)
        finally:
            ce0.fini()
            ce1.fini()
    finally:
        params.unset("comm_peer_timeout_s")


def test_shm_frame_directives_hook_send_path():
    """drop/delay fault-plan frame directives apply to shm sends: a
    dropped frame never dispatches, a delayed one arrives late (the
    directives hook ShmCE.send_am through the shared _fault_frame)."""
    from parsec_tpu.comm.launch import _probe_port_base
    from parsec_tpu.comm.shm import ShmCE

    faultinject.arm("seed=5;drop_frame=tag:ACT,n=1;"
                    "delay_frame=tag:DTD,n=1,ms=300")
    try:
        base = _probe_port_base(2)
        ce0, ce1 = ShmCE(0, 2, base), ShmCE(1, 2, base)
        got = []
        dropped = []
        ce0.on_frame_fault = lambda kind, tag, p, dst=-1: dropped.append(
            (kind, tag))
        ce1.tag_register(1, lambda src, p: got.append(("act", p)))
        ce1.tag_register(6, lambda src, p: got.append(("dtd", p)))
        try:
            t0 = time.monotonic()
            ce0.send_am(1, 1, {"n": 1})     # dropped (n=1 directive)
            ce0.send_am(6, 1, {"n": 2})     # delayed 300ms
            while len(got) < 1 and time.monotonic() - t0 < 5:
                time.sleep(0.02)
            dt = time.monotonic() - t0
            assert got and got[0][0] == "dtd"
            assert dt >= 0.25, f"delayed frame arrived after {dt:.3f}s"
            assert ("drop", 1) in dropped    # Safra reconcile fired
            time.sleep(0.2)
            assert all(k != "act" for k, _ in got), "dropped frame arrived"
        finally:
            ce0.fini()
            ce1.fini()
    finally:
        faultinject.disarm()


def test_chaos_kill_shm():
    """2-rank shm kills end-to-end (hard + silent) through the chaos
    contract: structured PeerFailedError containment, no hang."""
    proc = _chaos("kill-close-shm,kill-hang-shm", seeds=2)
    assert proc.returncode == 0, proc.stdout + proc.stderr
