"""Observability tests (reference: tests/profiling/check-async.py /
check-comms.py — run a traced pool, read the trace back, assert event
sanity; SURVEY.md §2.11)."""

import os

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.prof import (DotGrapher, install_gauges,
                             install_task_profiler, profiling_init,
                             read_trace)
from parsec_tpu.prof.reader import intervals


def _chain_pool(A, nt, device="cpu"):
    p = PTG("chain", NT=nt)
    p.task("S", k=Range(0, nt - 1)) \
        .affinity(lambda k, A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                  when=lambda k, NT=nt: k < NT - 1),
              OUT(DATA(lambda A=A: A(0, 0)),
                  when=lambda k, NT=nt: k == NT - 1)) \
        .body(lambda T: T + 1.0, device=device)
    return p.build()


@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_trace_intervals_complete(tmp_path, device):
    """Every executed task appears as one START/END pair with positive
    duration — including ASYNC device tasks (the reference's check-async
    property)."""
    nt = 12
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    prof = profiling_init("test")
    with Context(nb_cores=2) as ctx:
        mod = install_task_profiler(ctx, prof)
        ctx.add_taskpool(_chain_pool(A, nt, device))
        ctx.wait()
        mod.uninstall(ctx)
    path = prof.dump(str(tmp_path / "trace.ptt"))
    meta, df = read_trace(path)
    assert meta["hr_id"] == "test"
    ivs = intervals(df)
    assert len(ivs) == nt                       # one interval per task
    assert (ivs.duration > 0).all()
    assert set(ivs["name"].unique()) == {"S"}
    # info payloads carry the task parameters
    ks = sorted(iv["locals"]["k"] for iv in ivs["info"])
    assert ks == list(range(nt))


def test_gauges_track_lifecycle():
    nt = 9
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = install_gauges(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
        snap = g.snapshot()
    assert snap["tasks_retired"] == nt
    assert snap["pending_tasks"] == 0


def test_dot_grapher_records_dag(tmp_path):
    nt = 5
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher(rank=0)
        g.install(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
    path = g.dump(str(tmp_path / "dag.dot"))
    text = open(path).read()
    assert text.startswith("digraph")
    assert text.count("->") == nt - 1           # the chain's edges
    assert text.count('label="S(') == nt        # one node per task
    assert 'label="T"' in text                  # edges carry flow names


def test_dot_grapher_covers_dtd_edges(tmp_path):
    from parsec_tpu.dsl.dtd import DTDTaskpool, INOUT
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher()
        g.install(ctx)
        tp = DTDTaskpool("d")
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(A, 0, 0)
        for _ in range(4):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
    text = open(g.dump(str(tmp_path / "dtd.dot"))).read()
    assert text.count("->") == 3


def test_trace_roundtrip_dictionary_and_streams(tmp_path):
    prof = profiling_init("dicts")
    prof.add_information("who", "tester")
    sb = prof.stream(7, "custom")
    ec = prof.add_event_class("MYEV", "u64:val")
    sb.trace(ec.key, 4, 1, 1, 0, info={"val": 42})
    path = prof.dump(str(tmp_path / "t.ptt"))
    meta, df = read_trace(path)
    assert meta["info"]["who"] == "tester"
    assert (df["name"] == "MYEV").all()
    assert df.iloc[0]["info"] == {"val": 42}
    assert df.iloc[0]["stream"] == 7
