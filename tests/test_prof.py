"""Observability tests (reference: tests/profiling/check-async.py /
check-comms.py — run a traced pool, read the trace back, assert event
sanity; SURVEY.md §2.11)."""

import os

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.prof import (DotGrapher, install_gauges,
                             install_task_profiler, profiling_init,
                             read_trace)
from parsec_tpu.prof.reader import intervals


def _chain_pool(A, nt, device="cpu"):
    p = PTG("chain", NT=nt)
    p.task("S", k=Range(0, nt - 1)) \
        .affinity(lambda k, A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                  when=lambda k, NT=nt: k < NT - 1),
              OUT(DATA(lambda A=A: A(0, 0)),
                  when=lambda k, NT=nt: k == NT - 1)) \
        .body(lambda T: T + 1.0, device=device)
    return p.build()


@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_trace_intervals_complete(tmp_path, device):
    """Every executed task appears as one START/END pair with positive
    duration — including ASYNC device tasks (the reference's check-async
    property)."""
    nt = 12
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    prof = profiling_init("test")
    with Context(nb_cores=2) as ctx:
        mod = install_task_profiler(ctx, prof, with_locals=True)
        ctx.add_taskpool(_chain_pool(A, nt, device))
        ctx.wait()
        mod.uninstall(ctx)
    path = prof.dump(str(tmp_path / "trace.ptt"))
    meta, df = read_trace(path)
    assert meta["hr_id"] == "test"
    ivs = intervals(df)
    assert len(ivs) == nt                       # one interval per task
    assert (ivs.duration > 0).all()
    assert set(ivs["name"].unique()) == {"S"}
    # info payloads carry the task parameters
    ks = sorted(iv["locals"]["k"] for iv in ivs["info"])
    assert ks == list(range(nt))


def test_gauges_track_lifecycle():
    nt = 9
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = install_gauges(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
        snap = g.snapshot()
    assert snap["tasks_retired"] == nt
    assert snap["pending_tasks"] == 0


def test_dot_grapher_records_dag(tmp_path):
    nt = 5
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher(rank=0)
        g.install(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
    path = g.dump(str(tmp_path / "dag.dot"))
    text = open(path).read()
    assert text.startswith("digraph")
    assert text.count("->") == nt - 1           # the chain's edges
    assert text.count('label="S(') == nt        # one node per task
    assert 'label="T"' in text                  # edges carry flow names


def test_dot_grapher_covers_dtd_edges(tmp_path):
    from parsec_tpu.dsl.dtd import DTDTaskpool, INOUT
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher()
        g.install(ctx)
        tp = DTDTaskpool("d")
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(A, 0, 0)
        for _ in range(4):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
    text = open(g.dump(str(tmp_path / "dtd.dot"))).read()
    assert text.count("->") == 3


def test_trace_roundtrip_dictionary_and_streams(tmp_path):
    prof = profiling_init("dicts")
    prof.add_information("who", "tester")
    sb = prof.stream(7, "custom")
    ec = prof.add_event_class("MYEV", "u64:val")
    sb.trace(ec.key, 4, 1, 1, 0, info={"val": 42})
    path = prof.dump(str(tmp_path / "t.ptt"))
    meta, df = read_trace(path)
    assert meta["info"]["who"] == "tester"
    assert (df["name"] == "MYEV").all()
    assert df.iloc[0]["info"] == {"val": 42}
    assert df.iloc[0]["stream"] == 7


def test_trace_tools_cli(tmp_path):
    """tools/trace_info.py (dbpinfos analog) and tools/trace2chrome.py
    (the OTF2-role interoperable export) run on a real runtime trace
    (reference: tools/profiling/dbpinfos, profiling_otf2.c)."""
    import json
    import subprocess
    import sys

    import numpy as np
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    from parsec_tpu.prof.pins import TaskProfilerPins
    from parsec_tpu.prof import profiling

    prof = profiling.profiling_init("tools-test")
    V = VectorTwoDimCyclic(mb=2, lm=8)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("tooltrace", NT=4)
    p.task("T", k=Range(0, 3)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "RW", IN(DATA(lambda k, V=V: V(k))),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda X: X + 1.0)
    with Context(nb_cores=2) as ctx:
        pins = TaskProfilerPins(prof)
        pins.install(ctx)
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
        pins.uninstall(ctx)
    path = prof.dump(str(tmp_path / "tools.ptt"))
    profiling.profiling_fini()

    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "tools/trace_info.py", path, "--stats", "--gaps"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "dictionary" in r.stdout and "total events" in r.stdout
    # dbpinfos-style workhorse output: per-class stats + occupancy gaps
    assert "per-class interval stats" in r.stdout
    assert "count" in r.stdout and "mean" in r.stdout
    assert "per-stream occupancy" in r.stdout
    assert "util" in r.stdout and "largest gap" in r.stdout

    out = str(tmp_path / "tools.json")
    r = subprocess.run(
        [sys.executable, "tools/trace2chrome.py", path, "-o", out],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    assert doc["traceEvents"], "no events exported"
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in doc["traceEvents"])


def test_pins_mca_selection(capfd):
    """--mca pins installs named instrumentation modules at context init
    (reference: the pins framework module list, pins_init.c); unknown
    names warn instead of failing."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    from parsec_tpu.prof.pins import StealCounterPins
    from parsec_tpu.utils.mca import params

    params.set("pins", "print_steals,nosuchmodule")
    try:
        V = VectorTwoDimCyclic(mb=2, lm=8)
        for m, _ in V.local_tiles():
            V.data_of(m).copy_on(0).payload[:] = 0.0
        p = PTG("pinsrun", NT=4)
        p.task("T", k=Range(0, 3)) \
            .affinity(lambda k, V=V: V(k)) \
            .flow("X", "RW", IN(DATA(lambda k, V=V: V(k))),
                  OUT(DATA(lambda k, V=V: V(k)))) \
            .body(lambda X: X + 1.0)
        with Context(nb_cores=2) as ctx:
            mods = ctx._pins_modules
            assert len(mods) == 1 and isinstance(mods[0], StealCounterPins)
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=60)
            assert sum(mods[0].selects.values()) >= 4
            assert "selects total=" in mods[0].display()
    finally:
        params.unset("pins")
    err = capfd.readouterr().err
    assert "nosuchmodule" in err          # warned, not failed
    assert "StealCounterPins" in err      # stats displayed at fini


def test_properties_dictionary_runtime_and_taskpool():
    """Properties dictionary (reference: parsec/dictionary.c): a
    runtime-queryable hierarchical key space — live device counters and
    taskpool class properties readable by path, the aggregator-GUI
    pattern."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    rng = np.random.default_rng(0)
    n = 32
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = a @ a.T + n * np.eye(n, dtype=np.float32)
    A = TwoDimBlockCyclic(mb=8, nb=8, lm=n, ln=n).from_array(spd.copy())
    with Context(nb_cores=2) as ctx:
        ps = ctx.properties
        assert ps.lookup("runtime/nranks") == 1
        assert ps.lookup("runtime/scheduler")
        dev_paths = [p for p in ps.paths("runtime/devices")
                     if p.endswith("/executed_tasks")]
        assert dev_paths, "no device counters registered"
        before = sum(ps.lookup(p) for p in dev_paths)
        ctx.add_taskpool(potrf_taskpool(A, device="tpu"))
        # taskpool namespace appears on enqueue, with class properties
        flops = ps.lookup("taskpool/potrf/classes/GEMM/flops")
        assert flops == 2.0 * 8 ** 3
        assert ps.lookup("taskpool/potrf/nb_tasks") is not None
        ctx.wait(timeout=120)
        after = sum(ps.lookup(p) for p in dev_paths)
        assert after > before, "live counters did not advance"
        tree = ps.tree("taskpool/potrf/classes")
        assert any(p.endswith("POTRF/flops") for p in tree)


def test_iterators_checker_clean_run():
    """PINS iterators_checker (reference: mca/pins/iterators_checker):
    re-derived successor sets match the engine's deliveries on a real
    DAG; installed through MCA selection like any pins module."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.prof.pins import IteratorsCheckerPins
    rng = np.random.default_rng(1)
    n = 32
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = a @ a.T + n * np.eye(n, dtype=np.float32)
    A = TwoDimBlockCyclic(mb=8, nb=8, lm=n, ln=n).from_array(spd.copy())
    chk = IteratorsCheckerPins()
    with Context(nb_cores=2) as ctx:
        chk.install(ctx)
        ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
        ctx.wait(timeout=120)
        chk.uninstall(ctx)
    assert chk.checked > 0 and chk.flagged == 0, chk.display()


def test_iterators_checker_catches_lost_delivery(monkeypatch):
    """Negative: a seeded mis-delivery (one successor silently dropped —
    the class of dep-engine bug the checker exists for) is flagged."""
    import parsec_tpu.core.engine as eng
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.prof.pins import IteratorsCheckerPins

    V = VectorTwoDimCyclic(mb=2, lm=8)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    orig = eng.deliver_dep
    dropped = {"n": 0}

    def lossy(tp, succ_tc, succ_locals, dflow, copy, src):
        if succ_locals.get("k") == 2 and not dropped["n"]:
            dropped["n"] += 1
            return None          # lose exactly one delivery
        return orig(tp, succ_tc, succ_locals, dflow, copy, src)
    # the deliver PINS event must still fire per actual delivery, so
    # patch the engine's delivery fn (the checker observes the event
    # BEFORE delivery; losing the delivery leaves the successor starved
    # but the checker flags the stall's cause at producer completion)
    chk = IteratorsCheckerPins()
    p = PTG("chain", NT=4)
    p.task("T", k=Range(0, 3)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "RW",
              IN(DATA(lambda k, V=V: V(k)), when=lambda k: k == 0),
              IN(TASK("T", "X", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("T", "X", lambda k: dict(k=k + 1)),
                  when=lambda k: k < 3),
              OUT(DATA(lambda k, V=V: V(k)), when=lambda k: k == 3)) \
        .body(lambda X: X + 1.0)

    # patch the PINS hook instead: drop the checker's record of one
    # delivery, simulating an iterate_successors/delivery divergence
    real_deliver = chk._deliver

    def lossy_record(es, event, payload):
        _task, _tc, succ_locals, _fl = payload
        if succ_locals.get("k") == 2 and not dropped["n"]:
            dropped["n"] += 1
            return               # the checker never sees this delivery
        real_deliver(es, event, payload)
    chk._deliver = lossy_record

    with Context(nb_cores=2) as ctx:
        ctx.pins_register("deliver_dep", chk._deliver)
        ctx.pins_register("complete_exec", chk._complete)
        ctx.add_taskpool(p.build())
        with pytest.raises(RuntimeError) as exc:
            ctx.wait(timeout=60)
        assert "iterators_checker" in str(exc.value.__cause__)
    assert dropped["n"] == 1 and chk.flagged >= 1


# -- live aggregator (aggregator_visu counterpart, VERDICT r3 missing #6) --

def test_aggregator_ingest_and_totals():
    from parsec_tpu.prof.aggregator import Aggregator, render_table
    agg = Aggregator(port=0)
    try:
        agg.ingest(0, {"tasks_retired": 10, "pending_tasks": 2})
        agg.ingest(1, {"tasks_retired": 5, "pending_tasks": 1})
        agg.ingest(0, {"tasks_retired": 12, "pending_tasks": 0})
        t = agg.table()
        assert t[0]["tasks_retired"] == 12 and t[1]["tasks_retired"] == 5
        assert agg.totals()["tasks_retired"] == 17
        assert [v for _ts, v in agg.history(0, "tasks_retired")] == [10, 12]
        out = render_table(t, agg.totals())
        assert "rank" in out and "17" in out
    finally:
        agg.close()


def test_gauge_publisher_streams_over_tcp():
    import time
    from parsec_tpu.prof.aggregator import Aggregator, GaugePublisher

    class FakeGauges:
        def __init__(self):
            self.n = 0

        def snapshot(self):
            self.n += 1
            return {"tasks_retired": self.n}

    agg = Aggregator(port=0)
    pub = GaugePublisher(FakeGauges(), rank=3, host="127.0.0.1",
                         port=agg.port, interval=0.05)
    try:
        deadline = time.time() + 10
        while time.time() < deadline:
            t = agg.table()
            if 3 in t and t[3]["tasks_retired"] >= 2:
                break
            time.sleep(0.05)
        assert 3 in agg.table()
        assert agg.table()[3]["tasks_retired"] >= 2
    finally:
        pub.close()
        agg.close()


def test_aggregator_live_with_runtime_gauges():
    """End-to-end: a real Context's Gauges publish through TCP while a
    taskpool runs; the aggregator's final totals see every retirement."""
    import time
    from parsec_tpu.prof.aggregator import Aggregator, GaugePublisher

    nt = 30
    agg = Aggregator(port=0)
    try:
        with Context(nb_cores=2) as ctx:
            g = install_gauges(ctx)
            pub = GaugePublisher(g, rank=0, host="127.0.0.1",
                                 port=agg.port, interval=0.02)
            ctx.add_taskpool(_chain_pool(TwoDimBlockCyclic(
                mb=4, nb=4, lm=4, ln=4), nt))
            ctx.wait()
            pub.close()              # final flush carries the end state
        deadline = time.time() + 5
        while time.time() < deadline:
            if agg.totals().get("tasks_retired", 0) >= nt:
                break
            time.sleep(0.05)
        assert agg.totals()["tasks_retired"] >= nt
    finally:
        agg.close()


def test_aggregator_nonnumeric_ingest_and_clean_close():
    """ADVICE r4: a publisher sending string/null gauges must not crash
    render_table; close() joins the accept thread (VERDICT r4 #9)."""
    from parsec_tpu.prof.aggregator import Aggregator, render_table
    agg = Aggregator(port=0)
    try:
        agg.ingest(0, {"ok": 3, "bad": "oops", "worse": None, "f": 1.5})
        t = agg.table()
        assert "bad" not in t[0] and "worse" not in t[0]
        assert t[0]["ok"] == 3.0
        render_table(t, agg.totals())     # must not raise
    finally:
        agg.close()
    assert not agg._thread.is_alive()


def test_interval_single_crossing_pairs_edges():
    """VERDICT r5 #5: the begin/end pairing rides ONE C call
    (pinsext interval) — both edges must land with the caller's begin
    timestamp on the START record and a C-side END stamp, pairing by
    event id like the two-call path."""
    import time
    from parsec_tpu.prof.profiling import (EV_END, EV_START, Profile)
    prof = Profile()
    sb = prof.stream(0, "t")
    t0 = time.perf_counter()
    time.sleep(0.002)
    sb.interval(7, 3, 42, 99, t0)
    evs = sb.merged_events()
    assert len(evs) == 2
    (k1, f1, tp1, e1, o1, ts1, _i1), (k2, f2, tp2, e2, o2, ts2, _i2) = evs
    assert (k1, tp1, e1, o1) == (7, 3, 42, 99)
    assert (k2, tp2, e2, o2) == (7, 3, 42, 99)
    assert f1 == EV_START and f2 == EV_END
    assert ts1 == t0 and ts2 >= t0 + 0.002


def test_interval_python_fallback_matches():
    """Without the C sink the same call degrades to two plain records."""
    import time
    from parsec_tpu.prof.profiling import (EV_END, EV_START,
                                           StreamBuffer)
    sb = StreamBuffer(1, "t")
    sb._sink = None
    sb._sink_interval = None
    sb._native = None
    t0 = time.perf_counter()
    sb.interval(5, 2, 10, 0, t0)
    evs = sb.merged_events()
    assert [e[1] for e in evs] == [EV_START, EV_END]
    assert evs[0][5] == t0 and evs[1][5] >= t0


def test_task_profiler_deferred_begin_intervals_pair():
    """The task profiler's deferred-begin path: a traced run still
    yields one well-formed (START, END) interval per task."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.dsl.ptg.api import PTG, Range
    from parsec_tpu.prof.pins import install_task_profiler
    from parsec_tpu.prof.profiling import EV_END, EV_START, Profile

    N = 16
    p = PTG("iv", N=N)
    p.task("E", i=Range(0, N - 1)).flow("x", "CTL").body(lambda: None)
    prof = Profile()
    with Context(nb_cores=2) as ctx:
        mod = install_task_profiler(ctx, prof)
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
        mod.uninstall(ctx)
    opened = {}
    closed = 0
    for sb in prof._streams.values():
        for key, flags, _tp, eid, _oid, ts, _info in sb.merged_events():
            if flags & EV_START:
                opened[eid] = ts
            elif flags & EV_END:
                assert eid in opened and ts >= opened[eid]
                closed += 1
    assert closed == N
