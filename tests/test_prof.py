"""Observability tests (reference: tests/profiling/check-async.py /
check-comms.py — run a traced pool, read the trace back, assert event
sanity; SURVEY.md §2.11)."""

import os

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.prof import (DotGrapher, install_gauges,
                             install_task_profiler, profiling_init,
                             read_trace)
from parsec_tpu.prof.reader import intervals


def _chain_pool(A, nt, device="cpu"):
    p = PTG("chain", NT=nt)
    p.task("S", k=Range(0, nt - 1)) \
        .affinity(lambda k, A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                  when=lambda k, NT=nt: k < NT - 1),
              OUT(DATA(lambda A=A: A(0, 0)),
                  when=lambda k, NT=nt: k == NT - 1)) \
        .body(lambda T: T + 1.0, device=device)
    return p.build()


@pytest.mark.parametrize("device", ["cpu", "tpu"])
def test_trace_intervals_complete(tmp_path, device):
    """Every executed task appears as one START/END pair with positive
    duration — including ASYNC device tasks (the reference's check-async
    property)."""
    nt = 12
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    prof = profiling_init("test")
    with Context(nb_cores=2) as ctx:
        mod = install_task_profiler(ctx, prof)
        ctx.add_taskpool(_chain_pool(A, nt, device))
        ctx.wait()
        mod.uninstall(ctx)
    path = prof.dump(str(tmp_path / "trace.ptt"))
    meta, df = read_trace(path)
    assert meta["hr_id"] == "test"
    ivs = intervals(df)
    assert len(ivs) == nt                       # one interval per task
    assert (ivs.duration > 0).all()
    assert set(ivs["name"].unique()) == {"S"}
    # info payloads carry the task parameters
    ks = sorted(iv["locals"]["k"] for iv in ivs["info"])
    assert ks == list(range(nt))


def test_gauges_track_lifecycle():
    nt = 9
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = install_gauges(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
        snap = g.snapshot()
    assert snap["tasks_retired"] == nt
    assert snap["pending_tasks"] == 0


def test_dot_grapher_records_dag(tmp_path):
    nt = 5
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher(rank=0)
        g.install(ctx)
        ctx.add_taskpool(_chain_pool(A, nt))
        ctx.wait()
    path = g.dump(str(tmp_path / "dag.dot"))
    text = open(path).read()
    assert text.startswith("digraph")
    assert text.count("->") == nt - 1           # the chain's edges
    assert text.count('label="S(') == nt        # one node per task
    assert 'label="T"' in text                  # edges carry flow names


def test_dot_grapher_covers_dtd_edges(tmp_path):
    from parsec_tpu.dsl.dtd import DTDTaskpool, INOUT
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        g = DotGrapher()
        g.install(ctx)
        tp = DTDTaskpool("d")
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(A, 0, 0)
        for _ in range(4):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
    text = open(g.dump(str(tmp_path / "dtd.dot"))).read()
    assert text.count("->") == 3


def test_trace_roundtrip_dictionary_and_streams(tmp_path):
    prof = profiling_init("dicts")
    prof.add_information("who", "tester")
    sb = prof.stream(7, "custom")
    ec = prof.add_event_class("MYEV", "u64:val")
    sb.trace(ec.key, 4, 1, 1, 0, info={"val": 42})
    path = prof.dump(str(tmp_path / "t.ptt"))
    meta, df = read_trace(path)
    assert meta["info"]["who"] == "tester"
    assert (df["name"] == "MYEV").all()
    assert df.iloc[0]["info"] == {"val": 42}
    assert df.iloc[0]["stream"] == 7


def test_trace_tools_cli(tmp_path):
    """tools/trace_info.py (dbpinfos analog) and tools/trace2chrome.py
    (the OTF2-role interoperable export) run on a real runtime trace
    (reference: tools/profiling/dbpinfos, profiling_otf2.c)."""
    import json
    import subprocess
    import sys

    import numpy as np
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    from parsec_tpu.prof.pins import TaskProfilerPins
    from parsec_tpu.prof import profiling

    prof = profiling.profiling_init("tools-test")
    V = VectorTwoDimCyclic(mb=2, lm=8)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("tooltrace", NT=4)
    p.task("T", k=Range(0, 3)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("X", "RW", IN(DATA(lambda k, V=V: V(k))),
              OUT(DATA(lambda k, V=V: V(k)))) \
        .body(lambda X: X + 1.0)
    with Context(nb_cores=2) as ctx:
        pins = TaskProfilerPins(prof)
        pins.install(ctx)
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=60)
        pins.uninstall(ctx)
    path = prof.dump(str(tmp_path / "tools.ptt"))
    profiling.profiling_fini()

    env = {**__import__("os").environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "tools/trace_info.py", path, "--stats"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "dictionary" in r.stdout and "total events" in r.stdout

    out = str(tmp_path / "tools.json")
    r = subprocess.run(
        [sys.executable, "tools/trace2chrome.py", path, "-o", out],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    assert doc["traceEvents"], "no events exported"
    assert all(e["ph"] == "X" and e["dur"] >= 0 for e in doc["traceEvents"])


def test_pins_mca_selection(capfd):
    """--mca pins installs named instrumentation modules at context init
    (reference: the pins framework module list, pins_init.c); unknown
    names warn instead of failing."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range
    from parsec_tpu.prof.pins import StealCounterPins
    from parsec_tpu.utils.mca import params

    params.set("pins", "print_steals,nosuchmodule")
    try:
        V = VectorTwoDimCyclic(mb=2, lm=8)
        for m, _ in V.local_tiles():
            V.data_of(m).copy_on(0).payload[:] = 0.0
        p = PTG("pinsrun", NT=4)
        p.task("T", k=Range(0, 3)) \
            .affinity(lambda k, V=V: V(k)) \
            .flow("X", "RW", IN(DATA(lambda k, V=V: V(k))),
                  OUT(DATA(lambda k, V=V: V(k)))) \
            .body(lambda X: X + 1.0)
        with Context(nb_cores=2) as ctx:
            mods = ctx._pins_modules
            assert len(mods) == 1 and isinstance(mods[0], StealCounterPins)
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=60)
            assert sum(mods[0].selects.values()) >= 4
            assert "selects total=" in mods[0].display()
    finally:
        params.unset("pins")
    err = capfd.readouterr().err
    assert "nosuchmodule" in err          # warned, not failed
    assert "StealCounterPins" in err      # stats displayed at fini
