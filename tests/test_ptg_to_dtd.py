"""PTG -> DTD bridge tests (reference: the ptg_to_dtd PINS module,
mca/pins/ptg_to_dtd/pins_ptg_to_dtd_module.c — PTG-defined graphs
executed through the DTD engine)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic, VectorTwoDimCyclic
from parsec_tpu.dsl.dtd import DTDTaskpool
from parsec_tpu.dsl.dtd.bridge import run_ptg_as_dtd
from parsec_tpu.dsl.ptg.api import DATA, IN, NEW, OUT, PTG, Range, TASK


def _bridge_run(p, timeout=60):
    with Context(nb_cores=2) as ctx:
        tp = DTDTaskpool("bridged")
        ctx.add_taskpool(tp)
        ctx.start()
        run_ptg_as_dtd(p.build(), tp)
        tp.wait(timeout=timeout)
        ctx.wait(timeout=timeout)


def test_bridge_chain():
    """Ex02-style chain: task-fed RW edges through DTD versioning."""
    NT = 10
    V = VectorTwoDimCyclic(mb=2, lm=2 * NT)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    p = PTG("chain", NT=NT)
    p.task("S", k=Range(0, NT - 1)) \
        .affinity(lambda k, V=V: V(k)) \
        .flow("T", "RW",
              IN(DATA(lambda k, V=V: V(0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                  when=lambda k, NT=NT: k < NT - 1),
              OUT(DATA(lambda k, V=V: V(0)),
                  when=lambda k, NT=NT: k == NT - 1)) \
        .body(lambda T: T + 1.0)
    _bridge_run(p)
    np.testing.assert_allclose(
        np.asarray(V.data_of(0).pull_to_host().payload), float(NT))


def test_bridge_gemm():
    """Tiled GEMM through the bridge matches numpy (the DTD engine
    reproduces the PTG's RAW chains + fan-outs)."""
    from parsec_tpu.apps.gemm import gemm_taskpool

    n, mb = 64, 16
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n)).astype(np.float32)
    b = rng.standard_normal((n, n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="A").from_array(a)
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="B").from_array(b)
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, name="C").from_array(
        np.zeros((n, n), np.float32))
    with Context(nb_cores=2) as ctx:
        tp = DTDTaskpool("bridged-gemm")
        ctx.add_taskpool(tp)
        ctx.start()
        run_ptg_as_dtd(gemm_taskpool(A, B, C, device="cpu"), tp)
        tp.wait(timeout=120)
        ctx.wait(timeout=120)
    np.testing.assert_allclose(C.to_array(), a @ b, rtol=1e-4, atol=1e-4)


def test_bridge_ctl_ordering():
    """Ex07-style CTL: an update gated behind reads by pure control
    edges keeps its order through synthetic CTL tiles."""
    NT = 4
    V = VectorTwoDimCyclic(mb=2, lm=2)
    V.data_of(0).copy_on(0).payload[:] = 5.0
    reads = []
    p = PTG("ctl", NT=NT)
    p.task("READER", i=Range(0, NT - 1)) \
        .affinity(lambda i, V=V: V(0)) \
        .flow("X", "READ", IN(DATA(lambda i, V=V: V(0)))) \
        .flow("C", "CTL",
              OUT(TASK("UPD", "C", lambda i: dict()))) \
        .body(lambda X: reads.append(float(np.asarray(X)[0])))
    p.task("UPD") \
        .affinity(lambda V=V: V(0)) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0))),
              OUT(DATA(lambda V=V: V(0)))) \
        .flow("C", "CTL",
              *[IN(TASK("READER", "C", lambda i=i: dict(i=i)))
                for i in range(NT)]) \
        .body(lambda T: T * 100.0)
    _bridge_run(p)
    # every reader saw the PRE-update value
    assert reads == [5.0] * NT
    np.testing.assert_allclose(
        np.asarray(V.data_of(0).pull_to_host().payload), 500.0)


def test_bridge_new_flow():
    """NEW temporaries become synthetic DTD tiles shaped by the arena."""
    V = VectorTwoDimCyclic(mb=4, lm=4)
    V.data_of(0).copy_on(0).payload[:] = 0.0
    p = PTG("newflow")
    p.arena("tmp", (4,))
    p.task("MAKE") \
        .affinity(lambda V=V: V(0)) \
        .flow("W", "WRITE",
              IN(NEW("tmp")),
              OUT(TASK("USE", "W", lambda: dict()))) \
        .body(lambda W: W + 3.0)
    p.task("USE") \
        .affinity(lambda V=V: V(0)) \
        .flow("W", "READ", IN(TASK("MAKE", "W", lambda: dict()))) \
        .flow("T", "RW",
              IN(DATA(lambda V=V: V(0))),
              OUT(DATA(lambda V=V: V(0)))) \
        .body(lambda W, T: {"T": T + W})
    _bridge_run(p)
    np.testing.assert_allclose(
        np.asarray(V.data_of(0).pull_to_host().payload), 3.0)


def test_bridge_rejects_magic_args():
    V = VectorTwoDimCyclic(mb=2, lm=2)
    p = PTG("magic")
    p.task("T") \
        .affinity(lambda V=V: V(0)) \
        .flow("X", "RW", IN(DATA(lambda V=V: V(0))),
              OUT(DATA(lambda V=V: V(0)))) \
        .body(lambda X, task: X)
    with Context(nb_cores=1) as ctx:
        tp = DTDTaskpool("rej")
        ctx.add_taskpool(tp)
        ctx.start()
        with pytest.raises(TypeError, match="magic"):
            run_ptg_as_dtd(p.build(), tp)
        tp.wait(timeout=30)
