"""Tests of the data substrate: coherency protocol, arenas, repos,
and the tiled-matrix collections (reference: parsec/data.c semantics and
data_dist/matrix layouts)."""

import numpy as np
import pytest

from parsec_tpu.data.arena import Arena, ArenaDatatype
from parsec_tpu.data.collection import dc_lookup, dc_register, dc_unregister
from parsec_tpu.data.data import (ACCESS_READ, ACCESS_RW, ACCESS_WRITE,
                                  Coherency, Data, new_data)
from parsec_tpu.data.datarepo import DataRepo
from parsec_tpu.data.hash_datadist import HashDatadist
from parsec_tpu.data.matrix import (SymTwoDimBlockCyclic, TiledMatrix,
                                    TwoDimBlockCyclic, TwoDimTabular,
                                    VectorTwoDimCyclic)
from parsec_tpu.data.subtile import SubtileMatrix


# ---------------------------------------------------------------- coherency

def test_new_data_owned_on_host():
    d = new_data(np.zeros(4))
    c = d.copy_on(0)
    assert c.coherency == Coherency.OWNED and c.version == 1
    assert d.newest_copy() is c


def test_read_transfer_shares():
    d = new_data(np.arange(4.0))
    d.create_copy(1)  # INVALID device copy
    src = d.transfer_ownership(1, ACCESS_READ)
    assert src is d.copy_on(0)          # must pull from host copy
    assert d.copy_on(1).coherency == Coherency.SHARED
    assert d.copy_on(0).coherency == Coherency.OWNED


def test_write_transfer_invalidates_others():
    d = new_data(np.arange(4.0))
    d.create_copy(1)
    d.transfer_ownership(1, ACCESS_READ)
    d.copy_on(1).version = 1
    src = d.transfer_ownership(1, ACCESS_WRITE)
    assert src is None                  # already valid locally
    assert d.copy_on(1).coherency == Coherency.EXCLUSIVE
    assert d.copy_on(0).coherency == Coherency.INVALID
    d.complete_write(1)
    assert d.copy_on(1).version == 2
    assert d.newest_copy() is d.copy_on(1)


def test_stale_copy_needs_transfer():
    d = new_data(np.arange(4.0))
    d.create_copy(1)
    d.transfer_ownership(1, ACCESS_RW)
    d.complete_write(1)
    # host copy now stale; reading on host requires a pull from device 1
    src = d.transfer_ownership(0, ACCESS_READ)
    assert src is d.copy_on(1)


def test_exclusive_demoted_to_owned_on_remote_read():
    d = new_data(np.arange(4.0))
    d.create_copy(1)
    d.transfer_ownership(1, ACCESS_WRITE)
    d.complete_write(1)
    d.transfer_ownership(0, ACCESS_READ)
    assert d.copy_on(1).coherency == Coherency.OWNED
    assert d.copy_on(0).coherency == Coherency.SHARED


def test_reader_counts():
    d = new_data(np.zeros(2))
    d.start_read(0)
    d.start_read(0)
    assert d.copy_on(0).readers == 2
    d.end_read(0)
    d.end_read(0)
    assert d.copy_on(0).readers == 0


# ------------------------------------------------------------------- arena

def test_arena_freelist_reuse():
    a = Arena((8, 8), np.float32)
    c1 = a.get_copy()
    buf1 = c1.payload
    assert buf1.shape == (8, 8)
    a.release_copy(c1)
    c2 = a.get_copy()
    assert c2.payload is buf1           # freelist reuse
    assert a.allocated == 1
    adt = ArenaDatatype(a)
    assert adt.dtt == ((8, 8), np.dtype(np.float32).str)


def test_arena_release_foreign_copy_rejected():
    a1, a2 = Arena((2,)), Arena((2,))
    c = a1.get_copy()
    with pytest.raises(ValueError):
        a2.release_copy(c)


# -------------------------------------------------------------------- repo

def test_repo_usage_and_retirement():
    repo = DataRepo(nb_flows=2, name="POTRF")
    retired = []
    e = repo.lookup_entry_and_create(("k", 0))
    e.on_retire = lambda entry: retired.append(entry.key)
    e.copies[0] = "copyA"
    repo.entry_addto_usage_limit(("k", 0), 3)   # 3 consumers declared
    assert repo.lookup_entry(("k", 0)) is e
    repo.entry_used_once(("k", 0))
    repo.entry_used_once(("k", 0))
    assert not retired
    repo.entry_used_once(("k", 0))
    assert retired == [("k", 0)]
    assert repo.lookup_entry(("k", 0)) is None


def test_repo_consumers_racing_ahead_of_declaration():
    """The two-counter protocol (usagelmt/usagecnt, reference datarepo.h):
    consumers finishing before the producer declares the limit must NOT
    retire the entry — retirement requires the declaration."""
    repo = DataRepo(nb_flows=1)
    e = repo.lookup_entry_and_create("x")
    e.copies[0] = "out"
    repo.entry_used_once("x")                  # consumer done FIRST
    repo.entry_used_once("x")                  # second consumer too
    assert repo.lookup_entry("x") is e         # still alive: limit unknown
    repo.entry_addto_usage_limit("x", 2)       # producer declares
    assert repo.lookup_entry("x") is None      # retires exactly now


def test_repo_zero_consumers_retires_immediately():
    repo = DataRepo(nb_flows=1)
    repo.lookup_entry_and_create("y")
    repo.entry_addto_usage_limit("y", 0)
    assert repo.lookup_entry("y") is None


# ------------------------------------------------------------- collections

def test_two_dim_block_cyclic_ranks():
    # 4 ranks in a 2x2 grid, 4x4 tiles
    dcs = [TwoDimBlockCyclic(2, 2, 8, 8, nodes=4, myrank=r, P=2)
           for r in range(4)]
    A = dcs[0]
    assert A.mt == A.nt == 4
    # block-cyclic: rank(m,n) = (m%2)*2 + n%2
    for m in range(4):
        for n in range(4):
            assert A.rank_of(m, n) == (m % 2) * 2 + (n % 2)
    # every tile is local to exactly one rank
    for m in range(4):
        for n in range(4):
            owners = [r for r, dc in enumerate(dcs) if dc.is_local(m, n)]
            assert owners == [A.rank_of(m, n)]
    assert sorted(len(dc.local_tiles()) for dc in dcs) == [4, 4, 4, 4]


def test_block_cyclic_kp_kq_repetition():
    A = TwoDimBlockCyclic(1, 1, 8, 8, nodes=4, myrank=0, P=2, kp=2, kq=2)
    # with kp=kq=2, 2x2 super-blocks land on the same rank
    assert A.rank_of(0, 0) == A.rank_of(1, 1) == 0
    assert A.rank_of(2, 0) == A.rank_of(3, 1) == 2


def test_from_array_roundtrip_and_edge_tiles():
    a = np.arange(30, dtype=np.float32).reshape(5, 6)
    A = TwoDimBlockCyclic(2, 4, 5, 6).from_array(a)
    assert A.mt == 3 and A.nt == 2
    t = A.data_of(2, 1)                 # edge tile: 1x2
    payload = t.copy_on(0).payload
    assert payload.shape == (1, 2)
    assert payload[0, 0] == a[4, 4]
    payload[0, 0] = -1                  # view writes through
    assert a[4, 4] == -1
    assert np.shares_memory(A.to_array(), a)


def test_data_key_roundtrip():
    A = TwoDimBlockCyclic(2, 2, 8, 6)
    for m in range(A.mt):
        for n in range(A.nt):
            assert A.key_to_indices(A.data_key(m, n)) == (m, n)


def test_remote_tile_access_rejected():
    A = TwoDimBlockCyclic(2, 2, 8, 8, nodes=2, myrank=0, P=2, Q=1)
    with pytest.raises(KeyError):
        A.data_of(1, 0)  # owned by rank 1


def test_sym_matrix_triangle_only():
    S = SymTwoDimBlockCyclic(2, 2, 8, 8, uplo=SymTwoDimBlockCyclic.LOWER)
    assert S.rank_of(3, 1) == 0
    with pytest.raises(KeyError):
        S.rank_of(1, 3)
    with pytest.raises(KeyError):
        S.data_of(0, 2)


def test_tabular_distribution():
    table = [0, 1, 1, 0]
    T = TwoDimTabular(2, 2, 4, 4, table, nodes=2, myrank=0)
    assert T.rank_of(0, 0) == 0 and T.rank_of(0, 1) == 1
    assert T.rank_of(1, 0) == 1 and T.rank_of(1, 1) == 0
    with pytest.raises(ValueError):
        TwoDimTabular(2, 2, 4, 4, [0], nodes=2)


def test_vector_cyclic():
    V = VectorTwoDimCyclic(4, 10, nodes=3, myrank=1)
    assert [V.rank_of(m) for m in range(3)] == [0, 1, 2]
    t = V.data_of(1)
    assert t.copy_on(0).payload.shape == (4,)


def test_hash_datadist():
    H = HashDatadist(nodes=2, myrank=0)
    H.set_rank("root", 0)
    H.set_rank("leaf", 1)
    assert H.rank_of("root") == 0 and H.rank_of("leaf") == 1
    H.set_data("root", np.ones(3))
    assert H.data_of("root").copy_on(0).payload.sum() == 3
    with pytest.raises(KeyError):
        H.data_of("leaf")
    with pytest.raises(KeyError):
        H.rank_of("unknown")


def test_subtile_views_parent():
    a = np.arange(16.0).reshape(4, 4)
    A = TwoDimBlockCyclic(4, 4, 4, 4).from_array(a)
    parent = A.data_of(0, 0)
    sub = SubtileMatrix(parent, 2, 2)
    assert sub.mt == sub.nt == 2
    s = sub.data_of(1, 1).copy_on(0).payload
    assert np.shares_memory(s, a)
    assert s[0, 0] == a[2, 2]


def test_dataref_syntax():
    A = TwoDimBlockCyclic(2, 2, 4, 4)
    ref = A(1, 1)
    assert ref.rank == 0
    assert ref.resolve() is A.data_of(1, 1)


def test_dc_registry():
    A = TwoDimBlockCyclic(2, 2, 4, 4)
    dc_id = dc_register(A)
    assert dc_lookup(dc_id) is A
    dc_unregister(dc_id)
    assert dc_lookup(dc_id) is None


def test_write_only_access_needs_no_pull():
    d = new_data(np.arange(4.0))
    d.create_copy(1)
    assert d.transfer_ownership(1, ACCESS_WRITE) is None
    assert d.transfer_ownership(1, ACCESS_RW) is None  # now EXCLUSIVE locally


def test_rw_access_on_stale_copy_pulls():
    d = new_data(np.arange(4.0))
    d.create_copy(1)
    src = d.transfer_ownership(1, ACCESS_RW)
    assert src is d.copy_on(0)


def test_version_clock_never_regresses():
    d = new_data(np.arange(4.0))
    d.transfer_ownership(0, ACCESS_WRITE)
    d.complete_write(0)                      # host v2
    d.create_copy(1)
    d.transfer_ownership(1, ACCESS_WRITE)    # invalidates host (v2)
    d.complete_write(1)
    assert d.copy_on(1).version == 3         # monotonic, above stale host
    assert d.newest_copy() is d.copy_on(1)


def test_sym_local_tiles_and_is_local():
    S = SymTwoDimBlockCyclic(2, 2, 8, 8, uplo=SymTwoDimBlockCyclic.LOWER)
    tiles = S.local_tiles()
    assert (0, 1) not in tiles and (1, 0) in tiles
    assert len(tiles) == 10                  # lower triangle of 4x4 tiles
    assert not S.is_local(0, 1)


def test_vector_array_roundtrip():
    v = np.arange(10.0, dtype=np.float32)
    V = VectorTwoDimCyclic(4, 10).from_array(v)
    t = V.data_of(2)                         # edge tile len 2
    assert t.copy_on(0).payload.shape == (2,)
    assert np.shares_memory(V.to_array(), v)
    V2 = VectorTwoDimCyclic(4, 10)
    V2.data_of(0)
    out = V2.to_array()
    assert out.shape == (10,)


def test_from_array_after_materialization_rejected():
    A = TwoDimBlockCyclic(2, 2, 4, 4)
    A.data_of(0, 0)
    with pytest.raises(ValueError):
        A.from_array(np.zeros((4, 4), np.float32))
