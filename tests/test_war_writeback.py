"""Regression: same-wavefront read + ``-> DATA`` writeback of the same
collection tile (VERDICT r1 weak #1 — the stencil Gauss–Seidel
contamination).

A producer fans one tile out to a READ consumer and a WRITE consumer; the
writer's ``-> A(i)`` writeback is ordered BEFORE the reader stages its
input (a CTL edge), so an engine that lets the reader alias the
collection's live host storage reads the overwritten value.  The fixed
engine hands the reader a version-pinned snapshot (reference: repo
refcounts + versioned copies, datarepo.h:50-58, parsec.c:1783).
"""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import VectorTwoDimCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK


def _war_pool(V, seen, device):
    NT = V.mt
    p = PTG("war", NT=NT)
    # P(i) reads the tile once and fans it out to one reader + one writer
    p.task("P", i=Range(0, NT - 1)) \
        .flow("X", "READ",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(TASK("R", "X", lambda i: dict(i=i))),
              OUT(TASK("W", "X", lambda i: dict(i=i)))) \
        .body(lambda: None)
    # W(i) negates and writes back home; its CTL output orders R after it
    wb = p.task("W", i=Range(0, NT - 1)) \
        .flow("X", "RW",
              IN(TASK("P", "X", lambda i: dict(i=i))),
              OUT(DATA(lambda i, V=V: V(i)))) \
        .flow("c", "CTL",
              OUT(TASK("R", "c", lambda i: dict(i=i))))
    if device == "tpu":
        def neg(X):
            return -X
        wb.body(neg, device="tpu")
    wb.body(lambda X: -np.asarray(X))
    # R(i) runs strictly after W(i)'s writeback yet must see P's value

    def record(X, i):
        seen[i] = float(np.asarray(X)[0])
    p.task("R", i=Range(0, NT - 1)) \
        .flow("X", "READ", IN(TASK("P", "X", lambda i: dict(i=i)))) \
        .flow("c", "CTL", IN(TASK("W", "c", lambda i: dict(i=i)))) \
        .body(record)
    return p.build()


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_reader_sees_prewriteback_snapshot(device):
    NT, mb = 3, 4
    base = np.arange(1.0, NT * mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(base.copy())
    seen = {}
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(_war_pool(V, seen, device))
        ctx.wait(timeout=30)
    # readers saw the pre-writeback value of their tile...
    for i in range(NT):
        assert seen[i] == base[i * mb], \
            f"tile {i}: reader saw {seen[i]}, wanted {base[i * mb]}"
    # ...and the writeback landed in the user-visible array
    np.testing.assert_allclose(V.to_array(), -base, rtol=1e-6)


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_detached_snapshot_writeback_not_lost(device):
    """A writer bound to A(i) via FromDesc whose host copy an earlier
    writeback detached must still land its own ``-> A(i)`` update (the
    detached snapshot is NOT the in-place fast path)."""
    NT, mb = 2, 4
    base = np.arange(1.0, NT * mb + 1, dtype=np.float32)
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(base.copy())
    p = PTG("waw", NT=NT)
    # W1(i): negate the tile, write home, then unleash W2
    w1 = p.task("W1", i=Range(0, NT - 1)) \
        .flow("X", "RW",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(DATA(lambda i, V=V: V(i)))) \
        .flow("c", "CTL", OUT(TASK("W2", "c", lambda i: dict(i=i))))
    # W2(i): reads A(i) via FromDesc (bound before W1's writeback may
    # have replaced the host copy), multiplies by 10, writes home; CTL
    # orders it after W1 so the final value must be -10x
    w2 = p.task("W2", i=Range(0, NT - 1)) \
        .flow("X", "RW",
              IN(DATA(lambda i, V=V: V(i))),
              OUT(DATA(lambda i, V=V: V(i)))) \
        .flow("c", "CTL", IN(TASK("W1", "c", lambda i: dict(i=i))))
    if device == "tpu":
        w1.body(lambda X: -X, device="tpu")
        w2.body(lambda X: 10.0 * X, device="tpu")
    w1.body(lambda X: -np.asarray(X))
    w2.body(lambda X: 10.0 * np.asarray(X))
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(p.build())
        ctx.wait(timeout=30)
    np.testing.assert_allclose(V.to_array(), -10.0 * base, rtol=1e-6)


@pytest.mark.parametrize("device", ["tpu", "cpu"])
def test_backing_array_reflects_writeback_after_wait(device):
    """The replace-not-mutate writeback must still leave the user's
    original array updated once the pool quiesces (Ex07 contract)."""
    NT, mb = 2, 4
    base = np.arange(1.0, NT * mb + 1, dtype=np.float32)
    a = base.copy()
    V = VectorTwoDimCyclic(mb=mb, lm=NT * mb).from_array(a)
    seen = {}
    with Context(nb_cores=2) as ctx:
        ctx.add_taskpool(_war_pool(V, seen, device))
        ctx.wait(timeout=30)
    # reading through the collection AND through the user's own array
    np.testing.assert_allclose(V.to_array(), -base, rtol=1e-6)
    np.testing.assert_allclose(a, -base, rtol=1e-6)
