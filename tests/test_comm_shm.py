"""Shared-memory ring transport + native frame parser (r11).

In-process tests drive ShmCE pairs and the parser implementations
directly; the distributed case spawns a 2-rank pingpong over the shm
transport through the launch contract."""

import os
import random
import struct
import time

import numpy as np
import pytest

from parsec_tpu.comm.frames import PyFrameParser, make_parser
from parsec_tpu.comm.launch import _probe_port_base
from parsec_tpu.comm.shm import ShmCE, _ring_path
from parsec_tpu.utils.mca import params

_LEN = struct.Struct("!IQI")
_BUFLEN = struct.Struct("!Q")


def _stream(frames):
    """Serialize (tag, body, [oob...]) frames into one wire stream."""
    out = bytearray()
    for tag, body, oob in frames:
        out += _LEN.pack(tag, len(body), len(oob))
        out += body
        for b in oob:
            out += _BUFLEN.pack(len(b)) + b
    return bytes(out)


def _parsers():
    ps = [PyFrameParser(1 << 24)]
    nat, is_nat = make_parser(1 << 24)
    if nat is not None and is_nat:
        ps.append(nat)
    return ps


def test_parser_parity_random_chunking():
    """Python and native parsers produce identical frames from the
    same stream under adversarial chunk boundaries."""
    rng = random.Random(11)
    frames = [
        (1, b"x" * 5, []),
        (2, b"", []),                      # header-only
        (3, b"y" * 100, [b"z" * 70000, b""]),   # oob incl. empty
        (7, b"q" * 3, [b"w" * 9]),
    ]
    stream = _stream(frames)
    for fp in _parsers():
        got = []
        off = 0
        while off < len(stream):
            n = rng.randrange(1, 37)
            got.extend(fp.feed(stream[off:off + n]))
            off += n
        assert fp.idle()
        assert len(got) == len(frames)
        for (tag, body, oob), (gtag, gbody, goob) in zip(frames, got):
            assert gtag == tag
            assert bytes(gbody or b"") == body
            assert [bytes(b) for b in goob] == oob


def test_parser_bulk_target_zero_copy_path():
    big = os.urandom(200_000)
    stream = _stream([(5, b"hdr", [big])])
    for fp in _parsers():
        fp.feed(stream[:64])
        tgt = fp.bulk_target()
        assert tgt is not None
        n = min(len(tgt), len(stream) - 64)
        tgt[:n] = stream[64:64 + n]
        frames = fp.bulk_commit(n)
        if not frames:
            frames = fp.feed(stream[64 + n:])
        (tag, body, oob), = frames
        assert tag == 5 and bytes(oob[0]) == big


def test_parser_bound_violation_raises():
    bad = _LEN.pack(1, 1 << 40, 0)
    for fp in [PyFrameParser(1 << 20),
               make_parser(1 << 20, require=True)[0]]:
        with pytest.raises(ValueError):
            fp.feed(bad)


def test_parser_knob_selects_python_fallback():
    params.set("comm_frame_native", 0)
    try:
        fp, native = make_parser(1 << 20, require=True)
        assert isinstance(fp, PyFrameParser) and not native
        fp2, native2 = make_parser(1 << 20)
        assert fp2 is None and not native2
    finally:
        params.unset("comm_frame_native")


def _pair(base=None):
    base = base or _probe_port_base(2)
    return ShmCE(0, 2, base), ShmCE(1, 2, base)


def _drain(ces):
    for ce in ces:
        ce._stop = True
        ce.fini()


def test_shm_am_roundtrip_and_counters():
    ce0, ce1 = _pair()
    got = []
    try:
        ce1.tag_register(20, lambda src, p: got.append((src, p)))
        ce0.send_am(20, 1, {"k": 1})
        t0 = time.time()
        while not got and time.time() - t0 < 5:
            time.sleep(0.01)
        assert got == [(0, {"k": 1})]
        assert ce0.stats.frames_sent == 1
        assert ce1.stats.frames_recv == 1
        assert ce1.stats.syscalls_recv == 0    # the point of shm
        assert ce1.stats.frames_parsed_native == \
            (1 if ce1._peers[0].fp_native else 0)
    finally:
        _drain((ce0, ce1))


def test_shm_payload_larger_than_ring_streams_through():
    """A frame bigger than the ring streams through it in chunks (the
    producer publishes per chunk, the consumer frees space per parse),
    with backpressure stalls counted."""
    params.set("comm_shm_ring_mb", 1)    # ring << payload
    try:
        ce0, ce1 = _pair()
        out = []
        ce1.tag_register(21, lambda src, p: out.append(ShmCE.unpack(p)))
        arr = np.arange(1_500_000, dtype=np.float32)   # ~6MB
        ce0.send_am(21, 1, ShmCE.pack(arr))
        t0 = time.time()
        while not out and time.time() - t0 < 20:
            time.sleep(0.01)
        assert out and np.array_equal(out[0], arr)
        assert ce0.ring_full_stalls > 0
    finally:
        _drain((ce0, ce1))
        params.unset("comm_shm_ring_mb")


def test_shm_onesided_put_get():
    ce0, ce1 = _pair()
    try:
        target = np.zeros(128, np.float32)
        rid = ce1.mem_register(target)
        src = np.arange(128, dtype=np.float32)
        done = []
        ce0.put(1, src, rid, on_complete=done.append)
        t0 = time.time()
        while not done and time.time() - t0 < 5:
            time.sleep(0.01)
        assert done == [None]
        np.testing.assert_array_equal(target, src)
        got = []
        ce0.get(1, rid, got.append)
        t0 = time.time()
        while not got and time.time() - t0 < 5:
            time.sleep(0.01)
        np.testing.assert_array_equal(got[0], src)
    finally:
        _drain((ce0, ce1))


def test_shm_barrier_and_clock_probe():
    import threading
    ce0, ce1 = _pair()
    try:
        errs = []

        def bar(ce):
            try:
                ce.barrier(timeout=15)
            except Exception as exc:   # surfaced below
                errs.append(exc)
        t0 = threading.Thread(target=bar, args=(ce0,))
        t1 = threading.Thread(target=bar, args=(ce1,))
        t0.start(); t1.start(); t0.join(20); t1.join(20)
        assert not errs
        ce0.probe_clocks()
        t = time.time()
        while 1 not in ce0.clock and time.time() - t < 5:
            time.sleep(0.02)
        assert 1 in ce0.clock and ce0.clock[1]["rtt"] >= 0
    finally:
        _drain((ce0, ce1))


def test_shm_ring_files_cleaned_up():
    base = _probe_port_base(2)
    ce0, ce1 = _pair(base)
    paths = [_ring_path(base, 0, 1), _ring_path(base, 1, 0)]
    assert all(os.path.exists(p) for p in paths)
    _drain((ce0, ce1))
    assert not any(os.path.exists(p) for p in paths)


def test_make_ce_selects_shm_and_host_fallback():
    from parsec_tpu.comm.engine import EventLoopCE, make_ce
    params.set("comm_transport", "shm")
    try:
        ce = make_ce(0, 1, _probe_port_base(1))
        try:
            assert isinstance(ce, ShmCE) and ce.TRANSPORT == "shm"
        finally:
            ce._stop = True
            ce.fini()
        # multi-host address book: shm is same-host only -> evloop
        params.set("comm_hosts", "127.0.0.1")
        ce = make_ce(0, 1, _probe_port_base(1))
        try:
            assert isinstance(ce, EventLoopCE)
        finally:
            ce._stop = True
            ce.fini()
    finally:
        params.unset("comm_transport")
        params.unset("comm_hosts")


def _shm_pp(ctx, rank, nranks):
    from parsec_tpu.apps.pingpong import run_pingpong
    res = run_pingpong(ctx, 1 << 18, 8)
    return res[0], ctx.comm.stats()


def test_shm_distributed_pingpong():
    """2 spawned ranks over the shm transport: the dataflow path works
    end to end and the stats record the shm data plane."""
    from parsec_tpu.comm.launch import run_distributed
    env = {"PARSEC_MCA_COMM_TRANSPORT": "shm"}
    saved = {k: os.environ.get(k) for k in env}
    os.environ.update(env)
    try:
        res = run_distributed(_shm_pp, 2, timeout=120)
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    for _us, st in res:
        assert st["transport"] == "shm"
        assert st["frames_sent"] > 0
        assert st["syscalls_recv"] == 0
        assert "shm_doorbells_sent" in st


def test_shm_nested_send_during_stall_loses_nothing():
    """A handler dispatched by the stall path's drain-own-inbound
    deadlock breaker may SEND to the very peer being written: the
    nested frame must queue behind the in-progress write (the
    _writing latch), not interleave into its byte stream — the
    frame-loss/corruption class the r11 review reproduced."""
    params.set("comm_shm_ring_mb", 0)     # clamps to the 64KB floor
    try:
        ce0, ce1 = _pair()
        got1 = []
        ce1.tag_register(30, lambda src, p: got1.append(("big", len(p["b"]))))
        ce1.tag_register(31, lambda src, p: got1.append(("reply", p)))
        # ce0's handler replies to ce1 — it will run DURING ce0's
        # stalled big write (dispatched by the stall drain)
        ce0.tag_register(32, lambda src, p: ce0.send_am(31, 1, {"r": p}))
        # stall ce1's loop so ce0's 300KB frame overfills the 64KB ring
        ce1.post(time.sleep, 0.4)
        time.sleep(0.05)
        ce1.send_am(32, 0, 7)             # the trigger, parked inbound
        time.sleep(0.05)
        ce0.send_am(30, 1, {"b": b"x" * 300_000})
        t0 = time.time()
        while len(got1) < 2 and time.time() - t0 < 10:
            time.sleep(0.02)
        assert ("big", 300_000) in got1, got1
        assert ("reply", {"r": 7}) in got1, got1
        assert ce0.ring_full_stalls > 0   # the stall actually happened
        assert not ce0.dead_peers and not ce1.dead_peers
    finally:
        _drain((ce0, ce1))
        params.unset("comm_shm_ring_mb")


def test_shm_muted_loop_does_not_busy_spin():
    """A muted engine (silent-hang injection) with undrained inbound
    bytes must sleep in poll, not busy-spin on the dirty check."""
    import resource
    ce0, ce1 = _pair()
    try:
        ce1.send_am(13, 0, None)          # park bytes in ce0's inbound
        time.sleep(0.2)
        ce0.fault_kill("hang")            # mute: stops draining
        ce1.send_am(13, 0, None)          # now-undrainable bytes
        time.sleep(0.1)
        cpu0 = resource.getrusage(resource.RUSAGE_SELF).ru_utime
        time.sleep(1.0)
        cpu = resource.getrusage(resource.RUSAGE_SELF).ru_utime - cpu0
        assert cpu < 0.5, f"muted shm loop burned {cpu:.2f}s CPU/s"
    finally:
        _drain((ce0, ce1))
