"""DTD front-end tests (reference: tests/dsl/dtd/ — task_insertion, war,
simple_gemm patterns; SURVEY.md §4)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.dtd import (DTDTaskpool, INOUT, INPUT, OUTPUT, SCRATCH,
                                VALUE)
from parsec_tpu.utils.mca import params


def make_pool(ctx, name="dtd"):
    tp = DTDTaskpool(name)
    ctx.add_taskpool(tp)
    ctx.start()
    return tp


def test_chain_of_increments():
    """RAW chain through one tile (dtd_test_task_insertion pattern)."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        for _ in range(25):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 25.0)


def test_war_waw_hazards():
    """Writers wait for readers; readers see the right version
    (reference: dtd_test_war.c)."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4, name="A")
    B = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4, name="B")
    A.data_of(0, 0).copy_on(0).payload[:] = 5.0
    B.data_of(0, 0).copy_on(0).payload[:] = 0.0
    seen = []
    with Context(nb_cores=4) as ctx:
        tp = make_pool(ctx)
        ta = tp.tile_of(A, 0, 0)
        tb = tp.tile_of(B, 0, 0)
        # several readers of A's value 5 accumulate into distinct cells
        for i in range(4):
            def reader(src, dst, i=i):
                seen.append(float(np.asarray(src)[0, 0]))
                out = np.asarray(dst).copy()
                out[0, i] = np.asarray(src)[0, 0]
                return {"dst": out}
            tp.insert_task(reader, (ta, INPUT), (tb, INOUT))
        # then a writer overwrites A — must run after every reader
        tp.insert_task(lambda T: np.full_like(np.asarray(T), 9.0),
                       (ta, INOUT))
        # a final reader sees the new value
        def late(src, dst):
            out = np.asarray(dst).copy()
            out[3, 3] = np.asarray(src)[0, 0]
            return {"dst": out}
        tp.insert_task(late, (ta, INPUT), (tb, INOUT))
        tp.wait()
    assert seen == [5.0, 5.0, 5.0, 5.0]
    b = np.asarray(B.data_of(0, 0).pull_to_host().payload)
    np.testing.assert_allclose(b[0, :4], 5.0)
    assert b[3, 3] == 9.0


def test_value_and_scratch_args():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 1.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)

        def axpy(T, alpha, tmp):
            tmp[:] = np.asarray(T) * alpha
            return {"T": tmp}
        tp.insert_task(axpy, (t, INOUT), (3.0, VALUE), ((4, 4), SCRATCH))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 3.0)


def test_windowing_throttles_and_completes():
    params.set("dtd_window_size", 8)
    params.set("dtd_threshold_size", 4)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0
        with Context(nb_cores=2) as ctx:
            tp = make_pool(ctx)
            t = tp.tile_of(A, 0, 0)
            for _ in range(200):
                tp.insert_task(lambda T: T + 1.0, (t, INOUT))
            tp.wait()
        np.testing.assert_allclose(
            np.asarray(A.data_of(0, 0).pull_to_host().payload), 200.0)
    finally:
        params.unset("dtd_window_size")
        params.unset("dtd_threshold_size")


def test_tile_new_and_flush():
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_new((8, 8))
        tp.insert_task(lambda T: T + 2.5, (t, INOUT), device="tpu")
        tp.wait()
        tp.data_flush_all()
        np.testing.assert_allclose(
            np.asarray(t.data.copy_on(0).payload), 2.5)


def test_dtd_gemm_device_matches_numpy():
    """The reference's headline DTD test: tiled GEMM via insert_task on
    devices (dtd_test_simple_gemm.c)."""
    mt = nt = kt = 2
    mb = 16
    rng = np.random.default_rng(21)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    for M in (A, B, C):
        for m, n in M.local_tiles():
            M.data_of(m, n).copy_on(0).payload[:] = \
                rng.standard_normal((mb, mb)).astype(np.float32)
    want = C.to_array() + A.to_array() @ B.to_array()

    def gemm(a, b, c):
        return {"c": c + a @ b}

    with Context(nb_cores=4) as ctx:
        tp = make_pool(ctx)
        for m in range(mt):
            for n in range(nt):
                for k in range(kt):
                    tp.insert_task(gemm,
                                   (A(m, k), INPUT), (B(k, n), INPUT),
                                   (C(m, n), INOUT), device="tpu")
        tp.wait()
    np.testing.assert_allclose(C.to_array(), want, rtol=1e-3, atol=1e-3)


def test_failed_task_raises_instead_of_hanging():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0

    def boom(T):
        raise ValueError("kaboom")

    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(boom, (t, INOUT))
        tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        with pytest.raises(RuntimeError):
            tp.wait(timeout=10)


def test_affinity_marker_accepted():
    from parsec_tpu.dsl.dtd import AFFINITY
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 1.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        task = tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))
        tp.wait()
        assert task.dtd.affinity == 0
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 2.0)


def test_scratch_with_single_value_return():
    """SCRATCH is not an output flow: one-value return binds to T only."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 2.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(lambda T, tmp: np.asarray(T) * 2.0,
                       (t, INOUT), ((4, 4), SCRATCH))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 4.0)


def test_closure_free_lambdas_share_task_class():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        for _ in range(20):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
        assert len(tp.task_classes) == 1
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 20.0)


def test_mixed_dtd_then_second_pool():
    """Two DTD pools sequenced on one context."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp1 = make_pool(ctx, "p1")
        t1 = tp1.tile_of(A, 0, 0)
        tp1.insert_task(lambda T: T + 1.0, (t1, INOUT))
        tp1.wait()
        tp2 = make_pool(ctx, "p2")
        t2 = tp2.tile_of(A, 0, 0)
        tp2.insert_task(lambda T: T * 3.0, (t2, INOUT))
        tp2.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 3.0)
