"""DTD front-end tests (reference: tests/dsl/dtd/ — task_insertion, war,
simple_gemm patterns; SURVEY.md §4)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.dtd import (DTDTaskpool, INOUT, INPUT, OUTPUT, SCRATCH,
                                VALUE)
from parsec_tpu.utils.mca import params


def make_pool(ctx, name="dtd"):
    tp = DTDTaskpool(name)
    ctx.add_taskpool(tp)
    ctx.start()
    return tp


def test_chain_of_increments():
    """RAW chain through one tile (dtd_test_task_insertion pattern)."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        for _ in range(25):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 25.0)


def test_war_waw_hazards():
    """Writers wait for readers; readers see the right version
    (reference: dtd_test_war.c)."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4, name="A")
    B = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4, name="B")
    A.data_of(0, 0).copy_on(0).payload[:] = 5.0
    B.data_of(0, 0).copy_on(0).payload[:] = 0.0
    seen = []
    with Context(nb_cores=4) as ctx:
        tp = make_pool(ctx)
        ta = tp.tile_of(A, 0, 0)
        tb = tp.tile_of(B, 0, 0)
        # several readers of A's value 5 accumulate into distinct cells
        for i in range(4):
            def reader(src, dst, i=i):
                seen.append(float(np.asarray(src)[0, 0]))
                out = np.asarray(dst).copy()
                out[0, i] = np.asarray(src)[0, 0]
                return {"dst": out}
            tp.insert_task(reader, (ta, INPUT), (tb, INOUT))
        # then a writer overwrites A — must run after every reader
        tp.insert_task(lambda T: np.full_like(np.asarray(T), 9.0),
                       (ta, INOUT))
        # a final reader sees the new value
        def late(src, dst):
            out = np.asarray(dst).copy()
            out[3, 3] = np.asarray(src)[0, 0]
            return {"dst": out}
        tp.insert_task(late, (ta, INPUT), (tb, INOUT))
        tp.wait()
    assert seen == [5.0, 5.0, 5.0, 5.0]
    b = np.asarray(B.data_of(0, 0).pull_to_host().payload)
    np.testing.assert_allclose(b[0, :4], 5.0)
    assert b[3, 3] == 9.0


def test_value_and_scratch_args():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 1.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)

        def axpy(T, alpha, tmp):
            tmp[:] = np.asarray(T) * alpha
            return {"T": tmp}
        tp.insert_task(axpy, (t, INOUT), (3.0, VALUE), ((4, 4), SCRATCH))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 3.0)


def test_windowing_throttles_and_completes():
    params.set("dtd_window_size", 8)
    params.set("dtd_threshold_size", 4)
    try:
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0
        with Context(nb_cores=2) as ctx:
            tp = make_pool(ctx)
            t = tp.tile_of(A, 0, 0)
            for _ in range(200):
                tp.insert_task(lambda T: T + 1.0, (t, INOUT))
            tp.wait()
        np.testing.assert_allclose(
            np.asarray(A.data_of(0, 0).pull_to_host().payload), 200.0)
    finally:
        params.unset("dtd_window_size")
        params.unset("dtd_threshold_size")


def test_tile_new_and_flush():
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_new((8, 8))
        tp.insert_task(lambda T: T + 2.5, (t, INOUT), device="tpu")
        tp.wait()
        tp.data_flush_all()
        np.testing.assert_allclose(
            np.asarray(t.data.copy_on(0).payload), 2.5)


def test_dtd_gemm_device_matches_numpy():
    """The reference's headline DTD test: tiled GEMM via insert_task on
    devices (dtd_test_simple_gemm.c)."""
    mt = nt = kt = 2
    mb = 16
    rng = np.random.default_rng(21)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=kt * mb, name="A")
    B = TwoDimBlockCyclic(mb=mb, nb=mb, lm=kt * mb, ln=nt * mb, name="B")
    C = TwoDimBlockCyclic(mb=mb, nb=mb, lm=mt * mb, ln=nt * mb, name="C")
    for M in (A, B, C):
        for m, n in M.local_tiles():
            M.data_of(m, n).copy_on(0).payload[:] = \
                rng.standard_normal((mb, mb)).astype(np.float32)
    want = C.to_array() + A.to_array() @ B.to_array()

    def gemm(a, b, c):
        return {"c": c + a @ b}

    with Context(nb_cores=4) as ctx:
        tp = make_pool(ctx)
        for m in range(mt):
            for n in range(nt):
                for k in range(kt):
                    tp.insert_task(gemm,
                                   (A(m, k), INPUT), (B(k, n), INPUT),
                                   (C(m, n), INOUT), device="tpu")
        tp.wait()
    np.testing.assert_allclose(C.to_array(), want, rtol=1e-3, atol=1e-3)


def test_failed_task_raises_instead_of_hanging():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0

    def boom(T):
        raise ValueError("kaboom")

    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(boom, (t, INOUT))
        tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        with pytest.raises(RuntimeError):
            tp.wait(timeout=10)


def test_affinity_marker_accepted():
    from parsec_tpu.dsl.dtd import AFFINITY
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 1.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        task = tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))
        tp.wait()
        assert task.dtd.affinity == 0
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 2.0)


def test_scratch_with_single_value_return():
    """SCRATCH is not an output flow: one-value return binds to T only."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 2.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(lambda T, tmp: np.asarray(T) * 2.0,
                       (t, INOUT), ((4, 4), SCRATCH))
        tp.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 4.0)


def test_closure_free_lambdas_share_task_class():
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        for _ in range(20):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT))
        tp.wait()
        assert len(tp.task_classes) == 1
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 20.0)


def test_mixed_dtd_then_second_pool():
    """Two DTD pools sequenced on one context."""
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    with Context(nb_cores=2) as ctx:
        tp1 = make_pool(ctx, "p1")
        t1 = tp1.tile_of(A, 0, 0)
        tp1.insert_task(lambda T: T + 1.0, (t1, INOUT))
        tp1.wait()
        tp2 = make_pool(ctx, "p2")
        t2 = tp2.tile_of(A, 0, 0)
        tp2.insert_task(lambda T: T * 3.0, (t2, INOUT))
        tp2.wait()
    np.testing.assert_allclose(
        np.asarray(A.data_of(0, 0).pull_to_host().payload), 3.0)


def test_region_masks_disjoint_writers_run_unordered():
    """Region-masked deps (reference: insert_function.h region flags):
    writers of DISJOINT tile regions take no edge between them, while a
    whole-tile access orders against every lane."""
    from parsec_tpu.dsl.dtd import Region
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    RU, RL = Region("upper"), Region("lower")
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)

        def wr_u(T):
            T[0, :] = T[0, :] + 1.0

        def wr_l(T):
            T[3, :] = T[3, :] + 2.0
        t1 = tp.insert_task(wr_u, (t, INOUT | RU))
        t2 = tp.insert_task(wr_l, (t, INOUT | RL))
        # disjoint regions: the second writer has NO pending deps
        assert t2.dtd.remaining == 0
        # a whole-tile reader orders against BOTH lanes
        t3 = tp.insert_task(lambda T: None, (t, INPUT))
        assert t3.dtd.remaining in (1, 2)   # un-completed lane writers
        # and a whole-tile writer after it conflicts with everything
        t4 = tp.insert_task(lambda T: T * 2.0, (t, INOUT))
        tp.wait()
    out = np.asarray(A.data_of(0, 0).pull_to_host().payload)
    np.testing.assert_allclose(out[0, :], 2.0)    # (+1) * 2
    np.testing.assert_allclose(out[3, :], 4.0)    # (+2) * 2
    np.testing.assert_allclose(out[1:3, :], 0.0)


def test_ordering_only_region_accepted_shared_memory():
    """VERDICT r4 #8: extent-less (ordering-only) regions are legal
    everywhere — the r4 distributed guard is gone (the cross-rank
    behavior is covered by test_dtd_distributed's ordering-only case);
    here the lane semantics in shared memory: disjoint ordering-only
    lanes do not serialize, a whole-tile access orders against both."""
    from parsec_tpu.dsl.dtd import Region
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        RX, RY = Region("x"), Region("y")      # no slices

        def wr_x(T):
            T[0, :] = T[0, :] + 1.0

        def wr_y(T):
            T[3, :] = T[3, :] + 2.0
        tp.insert_task(wr_x, (t, INOUT | RX))
        t2 = tp.insert_task(wr_y, (t, INOUT | RY))
        assert t2.dtd.remaining == 0           # disjoint lanes: no edge
        t3 = tp.insert_task(lambda T: None, (t, INPUT))
        assert t3.dtd.remaining in (1, 2)      # orders after both lanes
        tp.wait()
    out = np.asarray(A.data_of(0, 0).pull_to_host().payload)
    np.testing.assert_allclose(out[0, :], 1.0)
    np.testing.assert_allclose(out[3, :], 2.0)


def test_pushout_forces_result_home():
    """PUSHOUT (reference: insert_function.h) writes the produced tile
    home at completion — the host copy is authoritative without any
    data_flush_all."""
    from parsec_tpu.dsl.dtd import PUSHOUT
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    host = A.data_of(0, 0).copy_on(0)
    host.payload[:] = 1.0
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(lambda T: T + 41.0, (t, INOUT | PUSHOUT))
        tp.wait()
        # no flush: the home copy must already hold the result
        datum = A.data_of(0, 0)
        newest = max(c.version for c in datum.copies().values()
                     if c.payload is not None)
        hc = datum.copy_on(0)
        assert hc is not None and hc.version == newest
        np.testing.assert_allclose(np.asarray(hc.payload), 42.0)


def test_create_task_class_add_chore():
    """Explicit task classes with per-device chores (reference:
    parsec_dtd_create_task_classv + parsec_dtd_add_chore): one logical
    task carries a TPU and a CPU chore; the runtime selects per
    execution, and the declared arg layout is validated at insert."""
    from parsec_tpu.dsl.dtd import DTDTaskClass  # noqa: F401 (API surface)
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 3.0
    ran = {"cpu": 0}
    with Context(nb_cores=2) as ctx:
        tp = make_pool(ctx)
        cls = tp.create_task_class("axpy", ["T", "s"], [INOUT, VALUE])
        cls.add_chore("tpu", lambda T, s: T * s)

        def cpu_axpy(T, s):
            ran["cpu"] += 1
            return np.asarray(T) * s
        cls.add_chore("cpu", cpu_axpy)
        t = tp.tile_of(A, 0, 0)
        tp.insert_task(cls, (t, INOUT), (2.0, VALUE))
        tp.insert_task(cls, (t, INOUT), (5.0, VALUE))
        with pytest.raises(TypeError, match="do not match"):
            tp.insert_task(cls, (t, INPUT), (1.0, VALUE))
        tp.wait()
    out = np.asarray(A.data_of(0, 0).pull_to_host().payload)
    np.testing.assert_allclose(out, 30.0)
    # the device chore was preferred (declared first); cpu stayed cold
    if len(Context.__mro__) and ran["cpu"]:
        # CPU fallback is legal if no accelerator was attached
        assert ran["cpu"] in (0, 2)
