"""Control-plane black box (ISSUE 15): journal ring + emit schema,
clock-aligned merge, the offline invariant auditor over hand-built
violation corpora, the job-port pull, flight-recorder bundle
inclusion, the autopsy tail, the retirement grace-window degradation
counter, and (slow) the full recover catalog under ``chaos
--audit-journal`` with a reconstructable 3-rank skip-agreement round.
"""

import json
import os
import sys
import time

import pytest

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

from parsec_tpu.prof.journal import (EVENT_SCHEMA, Journal,  # noqa: E402
                                     format_event, merge_journals)
from parsec_tpu.utils.mca import params  # noqa: E402
from tools import journal_audit  # noqa: E402

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


# ---------------------------------------------------------------------------
# ring + emit discipline
# ---------------------------------------------------------------------------

def test_ring_bounded_and_stamps():
    j = Journal(rank=3, cap=128)
    for i in range(300):
        j.emit("retired", pool=i)
    assert len(j) == 128
    evs = j.tail(128)
    # oldest overwritten, stamps monotone
    assert evs[0]["pool"] == 300 - 128
    assert [e["seq"] for e in evs] == sorted(e["seq"] for e in evs)
    assert all(e["inc"] == 0 and "t" in e for e in evs)


def test_disabled_journal_is_a_noop():
    params.set("journal_enabled", 0)
    try:
        j = Journal(rank=0)
        j.emit("retired", pool=1)
        assert len(j) == 0 and j.tail() == []
    finally:
        params.unset("journal_enabled")


def test_emit_normalizes_sets_for_the_wire():
    j = Journal(rank=0)
    j.emit("mode_decl", pool=1, round=2, mode="minimal",
           peers={2, 0, 1}, extra=frozenset({"b", "a"}))
    ev = j.tail(1)[0]
    assert ev["peers"] == [0, 1, 2]
    assert ev["extra"] == ["a", "b"]
    json.dumps(j.snapshot())   # must serialize as-is


def test_schema_table_well_formed():
    for etype, fields in EVENT_SCHEMA.items():
        assert isinstance(etype, str) and etype
        assert isinstance(fields, tuple)
        assert all(isinstance(f, str) for f in fields)
    # the round-scoped protocol families all demand round attribution
    for etype in ("mode_decl", "mode_vote", "mode_result", "skip_offer",
                  "skip_cut", "need_send", "need_round"):
        assert "round" in EVENT_SCHEMA[etype], etype


def test_dump_appends_and_loads_roundtrip(tmp_path):
    j = Journal(rank=2, cap=64)
    j.emit("epoch_fence", pool=1, epoch=1)
    path = j.dump(str(tmp_path))
    j.emit("retired", pool=1)
    assert j.dump(str(tmp_path)) == path
    snaps = journal_audit.load_file(path)
    assert len(snaps) == 2            # one header per dump, appended
    assert len(snaps[0]["events"]) == 1
    assert len(snaps[1]["events"]) == 2   # ring re-dumped whole
    per_rank = journal_audit.load_bundle([str(tmp_path)])
    assert sorted(per_rank) == [2]


# ---------------------------------------------------------------------------
# clock-aligned merge
# ---------------------------------------------------------------------------

def _snap(rank, events, clock=None, inc=0):
    return {"rank": rank, "inc": inc, "nranks": 2, "wall": 0.0,
            "perf": 0.0, "clock": clock or {}, "events": events}


def test_merge_aligns_on_reference_clock():
    """Rank 1's clock runs 100 s ahead; its own measured offset to
    rank 0 (clock_0 - clock_1 = -100) must pull its events back onto
    rank 0's timeline so causality reads correctly."""
    e0 = [{"e": "skip_cut", "t": 5.0, "seq": 1, "inc": 0, "pool": 1,
           "round": 1, "prefix": 3}]
    e1 = [{"e": "skip_offer", "t": 104.0, "seq": 1, "inc": 0,
           "pool": 1, "round": 1, "frontier": 4}]
    merged = merge_journals({
        0: _snap(0, e0),
        1: _snap(1, e1, clock={0: {"offset": -100.0, "rtt": 0.001}})})
    assert [m["e"] for m in merged] == ["skip_offer", "skip_cut"]
    assert abs(merged[0]["t"] - 4.0) < 1e-9
    assert merged[0]["rank"] == 1
    line = format_event(merged[0], t0=merged[0]["t"])
    assert "skip_offer" in line and "rank 1" in line


def test_merge_falls_back_to_reference_measurement():
    """No own-table entry: the reference's measurement of the peer is
    negated (offset = clock_peer - clock_ref)."""
    e1 = [{"e": "retired", "t": 107.0, "seq": 1, "inc": 0, "pool": 9}]
    merged = merge_journals({
        0: _snap(0, [], clock={1: {"offset": 100.0, "rtt": 0.001}}),
        1: _snap(1, e1)})
    assert abs(merged[0]["t"] - 7.0) < 1e-9
    # JSON round-trip stringifies clock keys; alignment must survive
    rt = json.loads(json.dumps(
        {0: _snap(0, [], clock={1: {"offset": 100.0}}), 1: _snap(1, e1)}))
    merged2 = merge_journals({int(r): s for r, s in rt.items()})
    assert abs(merged2[0]["t"] - 7.0) < 1e-9


# ---------------------------------------------------------------------------
# the invariant auditor: clean reference + one corpus per invariant
# ---------------------------------------------------------------------------

def _bundle(*rank_events, incs=None):
    """rank_events[i] = events of rank i (t/seq/inc auto-filled)."""
    per_rank = {}
    for rank, evs in enumerate(rank_events):
        out = []
        for i, ev in enumerate(evs):
            e = {"t": float(i), "seq": i + 1,
                 "inc": (incs or {}).get(rank, 0)}
            e.update(ev)
            out.append(e)
        per_rank[rank] = [_snap(rank, out)]
    return per_rank


def _clean_round():
    """A consistent 2-survivor skip round: same membership, cut under
    every offer, one retirement each, negotiation answered."""
    r0 = [
        {"e": "mode_decl", "pool": 1, "round": 1, "mode": "minimal",
         "peers": [0, 2]},
        {"e": "skip_offer", "pool": 1, "round": 1, "frontier": 18},
        {"e": "skip_offer", "pool": 1, "round": 1, "frontier": 40,
         "src": 2},
        {"e": "skip_cut", "pool": 1, "round": 1, "prefix": 17},
        {"e": "epoch_fence", "pool": 1, "epoch": 1},
        {"e": "need_req", "pool": 1, "src": 2, "n": 1},
        {"e": "need_ack", "pool": 1, "dst": 2, "ok": True},
        {"e": "retired", "pool": 1},
    ]
    r2 = [
        {"e": "mode_decl", "pool": 1, "round": 1, "mode": "minimal",
         "peers": [0, 2]},
        {"e": "skip_offer", "pool": 1, "round": 1, "frontier": 40},
        {"e": "skip_cut", "pool": 1, "round": 1, "prefix": 17,
         "src": 0},
        {"e": "need_send", "pool": 1, "round": 1, "peers": [0]},
        {"e": "need_round", "pool": 1, "round": 1, "outcome": "acked",
         "peers": [0]},
        {"e": "epoch_fence", "pool": 1, "epoch": 1},
        {"e": "retired", "pool": 1},
    ]
    return _bundle(r0, [], r2)


def test_audit_clean_reference_round():
    assert journal_audit.audit(_clean_round()) == []


def test_audit_flags_membership_disagreement():
    b = _clean_round()
    b[2][0]["events"][0]["peers"] = [0, 1, 2]   # divergent gang view
    vs = journal_audit.audit(b)
    assert any(v.startswith("I1") for v in vs), vs


def test_audit_flags_cut_above_offer():
    b = _clean_round()
    # rank 0's own offer drops below the agreed prefix
    b[0][0]["events"][1]["frontier"] = 10
    vs = journal_audit.audit(b)
    assert any(v.startswith("I2") and "exceeds" in v for v in vs), vs


def test_audit_flags_cut_despite_full_vote():
    b = _clean_round()
    b[2][0]["events"][1]["frontier"] = -1
    b[2][0]["events"][1]["full"] = "region-lane pool"
    vs = journal_audit.audit(b)
    assert any(v.startswith("I2") and "full" in v for v in vs), vs


def test_audit_flags_incarnation_regression():
    b = _clean_round()
    b[0][0]["events"][3]["inc"] = 1
    b[0][0]["events"][4]["inc"] = 0    # regressed mid-file
    vs = journal_audit.audit(b)
    assert any(v.startswith("I3") and "incarnation" in v for v in vs), vs


def test_audit_flags_nonmonotone_epoch_fence():
    b = _clean_round()
    b[0][0]["events"].append({"e": "epoch_fence", "pool": 1, "epoch": 1,
                              "t": 9.0, "seq": 99, "inc": 0})
    vs = journal_audit.audit(b)
    assert any(v.startswith("I3") and "run_epoch" in v for v in vs), vs


def test_audit_flags_double_retirement_outcome():
    b = _clean_round()
    b[0][0]["events"].append({"e": "retire_degraded", "pool": 1,
                              "t": 9.0, "seq": 99, "inc": 0})
    vs = journal_audit.audit(b)
    assert any(v.startswith("I4") for v in vs), vs


def test_audit_flags_unanswered_need():
    b = _clean_round()
    b[0][0]["events"].pop(6)           # the need_ack vanishes
    vs = journal_audit.audit(b)
    assert any(v.startswith("I5") and "unanswered" in v for v in vs), vs


def test_audit_flags_silent_need_round():
    b = _clean_round()
    b[2][0]["events"].pop(4)           # need_send with no outcome
    vs = journal_audit.audit(b)
    assert any(v.startswith("I5") and "no terminal outcome" in v
               for v in vs), vs


def test_audit_recycled_pool_id_across_incarnations_is_clean():
    """Pool ids are a per-process counter: a restarted incarnation
    legitimately reuses its predecessor's ids.  A rank that retired
    pool 1, restarted (higher inc), and retired a NEW pool 1 must not
    flag I3/I4 — the incarnation stamp disambiguates."""
    first = [{"e": "epoch_fence", "pool": 1, "epoch": 1, "t": 1.0,
              "seq": 1, "inc": 0},
             {"e": "retired", "pool": 1, "t": 2.0, "seq": 2, "inc": 0}]
    second = [{"e": "epoch_fence", "pool": 1, "epoch": 1, "t": 10.0,
               "seq": 1, "inc": 1},
              {"e": "need_req", "pool": 1, "src": 1, "t": 10.5,
               "seq": 2, "inc": 1},
              {"e": "need_ack", "pool": 1, "dst": 1, "ok": True,
               "t": 10.6, "seq": 3, "inc": 1},
              {"e": "retired", "pool": 1, "t": 11.0, "seq": 4,
               "inc": 1}]
    per_rank = {0: [_snap(0, first, inc=0), _snap(0, second, inc=1)]}
    assert journal_audit.audit(per_rank) == []
    # the true violations still flag WITHIN one incarnation
    per_rank[0][1]["events"].append(
        {"e": "retired", "pool": 1, "t": 12.0, "seq": 5, "inc": 1})
    vs = journal_audit.audit(per_rank)
    assert any(v.startswith("I4") for v in vs), vs


def test_skip_rounds_attribute_replay_to_its_own_round():
    """A pool whose round 1 fell back to full and whose round 2
    agreed a cut must not report ghost replays in round 1."""
    evs = [
        {"e": "skip_offer", "pool": 1, "round": 1, "frontier": -1,
         "full": "no prefix", "t": 1.0, "seq": 1, "inc": 0},
        {"e": "skip_cut", "pool": 1, "round": 1, "prefix": 0,
         "t": 1.1, "seq": 2, "inc": 0},
        {"e": "skip_offer", "pool": 1, "round": 2, "frontier": 20,
         "t": 5.0, "seq": 3, "inc": 0},
        {"e": "skip_cut", "pool": 1, "round": 2, "prefix": 17,
         "t": 5.1, "seq": 4, "inc": 0},
        {"e": "replay_mode", "pool": 1, "mode": "skip", "round": 2,
         "prefix": 17, "tasks": 9, "t": 5.2, "seq": 5, "inc": 0},
        {"e": "retired", "pool": 1, "t": 6.0, "seq": 6, "inc": 0},
    ]
    rounds = {(r["pool"], r["round"]): r
              for r in journal_audit.skip_rounds({0: [_snap(0, evs)]})}
    assert rounds[(1, 1)]["replays"] == []
    assert rounds[(1, 1)]["retired"] == []
    assert len(rounds[(1, 2)]["replays"]) == 1
    assert len(rounds[(1, 2)]["retired"]) == 1


def test_disabled_journal_skips_fini_dump(tmp_path):
    """A disabled journal must dump NOTHING at fini: a header-only
    file would let chaos --audit-journal pass vacuously over zero
    events."""
    params.set("journal_enabled", 0)
    params.set("journal_dir", str(tmp_path))
    from parsec_tpu.core.context import Context
    try:
        with Context(nb_cores=1):
            pass
        assert os.listdir(str(tmp_path)) == []
    finally:
        params.unset("journal_enabled")
        params.unset("journal_dir")


def test_skip_round_reconstruction_and_timeline():
    b = _clean_round()
    rounds = journal_audit.skip_rounds(b)
    assert len(rounds) == 1
    r = rounds[0]
    assert r["cut"]["prefix"] == 17
    offers = {o["rank"]: o["frontier"] for o in r["offers"]}
    assert offers == {0: 18, 2: 40}
    assert len(r["retired"]) == 2
    text = journal_audit.render_timeline(b)
    assert "skip round pool=1" in text and "agreed cut 17" in text


def test_chrome_export_instant_events(tmp_path):
    out = str(tmp_path / "ctl.json")
    n = journal_audit.write_chrome(_clean_round(), out)
    doc = json.load(open(out))
    evs = [e for e in doc["traceEvents"] if e["ph"] == "i"]
    assert len(evs) == n and n > 0
    assert {e["pid"] for e in evs} == {0, 2}
    assert all(e["ts"] >= 0 for e in evs)


# ---------------------------------------------------------------------------
# runtime wiring: job port, flight recorder, autopsy, degradation
# ---------------------------------------------------------------------------

def _n_pool(n, name="jpool"):
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG(name, N=n)
    p.task("T", i=Range(0, n - 1)).body(lambda: None)
    return p.build()


def test_journal_op_on_job_server():
    """The framed ``{"op": "journal"}`` pull returns this rank's
    snapshot with the job lifecycle on the record."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.service.server import JobServer, request
    from parsec_tpu.service.service import JobService
    with Context(nb_cores=2) as ctx:
        svc = JobService(context=ctx)
        server = JobServer(svc, port=0)
        try:
            job = svc.submit(lambda: _n_pool(8), name="boxed")
            assert job.wait(timeout=30)
            reply = request(server.host, server.port, {"op": "journal"})
        finally:
            server.close()
            svc.shutdown(timeout=10.0)
        assert reply["ok"]
        snap = reply["ranks"]["0"]
        kinds = [e["e"] for e in snap["events"]]
        assert "job_admit" in kinds and "job_start" in kinds \
            and "job_done" in kinds
        done = [e for e in snap["events"] if e["e"] == "job_done"]
        assert done[0]["status"] == "done"
        assert done[0]["job"] == job.job_id


def test_flightrec_bundle_includes_journal(tmp_path):
    """Every incident bundle carries the control-plane story next to
    the data-plane ring."""
    params.set("flightrec_enabled", 1)
    params.set("flightrec_dir", str(tmp_path))
    params.set("flightrec_min_interval_s", 0.0)
    from parsec_tpu.core.context import Context
    try:
        with Context(nb_cores=2) as ctx:
            ctx.journal.emit("epoch_fence", pool=7, epoch=1)
            bundle = ctx.telemetry_incident("unit-test incident")
            assert bundle == str(tmp_path)
            jpath = os.path.join(bundle, "journal-rank0.jsonl")
            # the dump runs on its own thread: poll until the CONTENT
            # lands (existence alone races the in-progress append)
            deadline = time.monotonic() + 10.0
            found = False
            while not found and time.monotonic() < deadline:
                if os.path.exists(jpath):
                    try:
                        snaps = journal_audit.load_file(jpath)
                        found = any(
                            e["e"] == "epoch_fence" and e["pool"] == 7
                            for s in snaps for e in s["events"])
                    except (ValueError, OSError):
                        pass   # torn mid-append read
                if not found:
                    time.sleep(0.05)
            assert found
    finally:
        params.unset("flightrec_enabled")
        params.unset("flightrec_dir")
        params.unset("flightrec_min_interval_s")


def test_autopsy_prints_clock_aligned_journal_tail():
    from parsec_tpu.core.context import Context
    with Context(nb_cores=2) as ctx:
        ctx.journal.emit("retired", pool=3)
        text = ctx.hang_autopsy()
    assert "control-plane journal tail" in text
    assert "retired" in text and "pool=3" in text


def test_retire_degraded_counted_and_journaled():
    """The PR 14 residual made observable: a completed pool whose
    retirement handshake never concluded (coordinator unreachable)
    falls back to the grace-window eviction — now counted in
    parsec_recovery_retire_degraded_total and journaled."""
    params.set("recovery_enable", 1)
    params.set("recovery_completed_grace_s", 0.05)
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    try:
        with Context(nb_cores=2) as ctx:
            rec = ctx.recovery
            assert rec is not None

            class _StubCE:
                nranks = 2
                rank = 0
                dead_peers = ()

                def send_am(self, *a, **k):
                    raise OSError("coordinator unreachable")

            class _StubRDE:
                ce = _StubCE()

                def recovery_coordinator(self):
                    return 1   # someone else — and unreachable

            rec._rde = _StubRDE()
            V = VectorTwoDimCyclic(mb=4, lm=16, nodes=1, myrank=0)
            for m, _ in V.local_tiles():
                V.data_of(m).copy_on(0).payload[:] = 0.0
            tp = _n_pool(4, name="degrader")
            tp.recovery_collections = [V]
            ctx.add_taskpool(tp, start=True)
            ctx.wait(timeout=30)
            time.sleep(0.1)        # past the shrunk grace window
            with rec._lock:
                rec._sweep_locked()
            assert rec.retire_degraded == 1
            assert rec.stats()["retire_degraded"] == 1
            kinds = [e["e"] for e in ctx.journal.tail(50)]
            assert "retire_report" in kinds
            assert "retire_degraded" in kinds
            fams = {s["n"]: s["v"] for s in rec._collect()
                    if s["t"] == "counter" and not s["l"]}
            assert fams["parsec_recovery_retire_degraded_total"] == 1
    finally:
        params.unset("recovery_enable")
        params.unset("recovery_completed_grace_s")


# ---------------------------------------------------------------------------
# cross-rank: the TAG_METRICS-lane journal pull
# ---------------------------------------------------------------------------

def _pull_worker(ctx, rank, nranks):
    from parsec_tpu.prof.journal import cluster_journals
    ctx.add_taskpool(_n_pool(6, name=f"wire{rank}"))
    ctx.wait(timeout=60)
    ctx.comm.ce.barrier(timeout=30)   # journaled on both ranks
    if rank != 0:
        # park long enough for rank 0's pull to find us alive
        time.sleep(3.0)
        return {"events": len(ctx.journal)}
    per_rank = cluster_journals(ctx, timeout=5.0)
    merged = merge_journals({r: s for r, s in per_rank.items()})
    return {"ranks": sorted(per_rank),
            "peer_kinds": sorted({e["e"] for e in merged
                                  if e["rank"] == 1})}


def test_two_rank_journal_pull_over_control_lane():
    from parsec_tpu.comm.launch import run_distributed
    res = run_distributed(_pull_worker, 2, timeout=180)
    assert res[0]["ranks"] == [0, 1]
    # the peer's barrier generations crossed the wire
    assert "barrier" in res[0]["peer_kinds"], res


# ---------------------------------------------------------------------------
# slow acceptance: the recover catalog under --audit-journal
# ---------------------------------------------------------------------------

@pytest.mark.slow
def test_recover_catalog_journal_audit_clean():
    """ISSUE 15 acceptance: the FULL 12-case recover catalog with
    journaling armed holds every auditor invariant (run_case fails a
    case on any violation — or on a silently-disarmed journal)."""
    from tools.chaos import _RECOVER, CATALOG, run_case
    cases = [c for c in CATALOG if c[0] in _RECOVER]
    assert len(cases) == 12
    failures = []
    for i, (name, plan_t, wl, expect, env) in enumerate(cases):
        ok, outcome, detail = run_case(
            name, plan_t.format(s=i + 1), wl, expect, env,
            timeout=120.0, audit_journal=True)
        if not ok:
            failures.append((name, outcome, detail[:300]))
    assert not failures, failures


@pytest.mark.slow
def test_skip_agreement_round_reconstructs_from_bundle(tmp_path):
    """ISSUE 15 acceptance: the 3-rank kill-dtd-minimal bundle
    reconstructs the skip-agreement round END TO END — votes (every
    survivor's offered cut) -> agreed cut -> ghost replay ->
    retirement — on one merged clock, with zero violations."""
    jdir = str(tmp_path / "bundle")
    plan = ("seed=11;kill_rank=1@t+2.0s,mode=close;"
            "delay_dispatch=key~_dtd_chain_step,ms=100")
    keys = {"PARSEC_MCA_FAULT_PLAN": plan,
            "PARSEC_MCA_JOURNAL_DIR": jdir,
            "PARSEC_CHAOS_WAIT_S": "45",
            "PARSEC_MCA_RECOVERY_ENABLE": "1"}
    saved = {k: os.environ.get(k) for k in keys}
    os.environ.update(keys)
    try:
        from tools.chaos import WORKLOADS
        from parsec_tpu.comm.launch import run_distributed
        res = run_distributed(WORKLOADS["dtd-minimal"], 3,
                              timeout=120, tolerate_ranks=[1])
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    assert res[1] is None, "the kill never fired — nothing recovered"
    per_rank = journal_audit.load_bundle([jdir])
    assert journal_audit.audit(per_rank) == []
    rounds = [r for r in journal_audit.skip_rounds(per_rank)
              if r["cut"] is not None and r["cut"]["prefix"] > 0]
    assert rounds, "no agreed skip cut on the record"
    r = rounds[0]
    # votes: BOTH survivors' offers are on the record, and the agreed
    # cut honors each (the auditor's I2, re-checked explicitly here)
    offer_ranks = {o["rank"] for o in r["offers"]}
    assert {0, 2} <= offer_ranks
    assert all(r["cut"]["prefix"] <= o["frontier"]
               for o in r["offers"] if o.get("full") is None)
    # ghost replay on every survivor, then the retirement handshake
    assert {rep["rank"] for rep in r["replays"]} == {0, 2}
    assert len(r["retired"]) >= 1
    # the protocol ORDER holds on the merged clock
    offers_t = max(o["t"] for o in r["offers"])
    assert offers_t <= r["cut"]["t"]
    assert r["cut"]["t"] <= min(rep["t"] for rep in r["replays"])
    assert min(rep["t"] for rep in r["replays"]) \
        <= min(x["t"] for x in r["retired"])
