"""Native scheduler hot path (r11): schedext ReadyQueue/DepTable
semantics, the sched_native A/B gate, and runtime equivalence of the
native and Python paths."""

import numpy as np
import pytest

from parsec_tpu.native import load_schedext
from parsec_tpu.utils.mca import params

se = load_schedext()

pytestmark = pytest.mark.skipif(se is None,
                                reason="schedext did not build")


class _T:
    __slots__ = ("priority", "status", "ready_at")

    def __init__(self, prio=0):
        self.priority = prio
        self.status = 0
        self.ready_at = None


def _rq():
    from parsec_tpu.core.task import TaskStatus
    return se.ReadyQueue(TaskStatus.READY), TaskStatus.READY


def test_ready_queue_priority_and_fifo_order():
    q, READY = _rq()
    ts = [_T(1), _T(5), _T(5), _T(0)]
    q.push_batch(ts, 0)
    assert len(q) == 4
    # highest priority first, FIFO among equals, then the rest
    assert q.pop() is ts[1]
    assert q.pop() is ts[2]
    assert q.pop() is ts[0]
    assert q.pop() is ts[3]
    assert q.pop() is None
    for t in ts:
        assert t.status is READY


def test_ready_queue_stamp_gates_ready_at():
    q, _ = _rq()
    a, b = _T(), _T()
    q.push_batch([a], 0)
    assert a.ready_at is None          # telemetry off: no stamp
    q.push_batch([b], 1)
    assert isinstance(b.ready_at, float) and b.ready_at > 0


def test_ready_queue_to_back_fairness():
    """distance-rescheduled tasks go behind EVERYTHING, priority
    notwithstanding (the sched/__init__.py fairness contract)."""
    q, _ = _rq()
    again = _T(100)
    normal = _T(0)
    q.push_batch([again], 0, 1)        # to_back
    q.push_batch([normal], 0)
    assert q.pop() is normal
    assert q.pop() is again


def test_ready_queue_stats():
    q, _ = _rq()
    q.push_batch([_T(), _T(), _T()], 0)
    q.pop()
    pushes, pops, max_len, pending = q.stats()
    assert (pushes, pops, max_len, pending) == (3, 1, 3, 2)


def test_dep_table_countdown_and_ready_payload():
    dt = se.DepTable()
    key = ("X", 1)
    assert dt.arrive(key, "a", None, None) is False   # miss
    dt.create(key, 2, {"i": 1})
    assert dt.arrive(key, "a", "COPY", ("tc", "k")) is None
    res = dt.arrive(key, "b", None, None)
    locals_, inputs, sources = res
    assert locals_ == {"i": 1}
    # EVERY arrival records its binding, None included (a CTL edge
    # must land flow->None in task.data)
    assert inputs == {"a": "COPY", "b": None}
    assert sources == {"a": ("tc", "k")}
    assert len(dt) == 0


def test_dep_table_create_keeps_existing_record():
    """Two workers racing the first arrivals both observe the miss;
    the second create must not wipe the first's recorded arrival."""
    dt = se.DepTable()
    key = ("Y", 0)
    dt.create(key, 2, {"j": 0})
    assert dt.arrive(key, "a", None, None) is None    # 1/2
    dt.create(key, 2, {"j": 0})                       # racing create
    assert dt.arrive(key, "b", None, None) is not None  # 2/2 ready


def test_dep_table_two_copies_on_data_flow_raises():
    dt = se.DepTable()
    dt.create(("Z",), 3, {})
    dt.arrive(("Z",), "d", "COPY1", None)
    with pytest.raises(RuntimeError, match="two copies"):
        dt.arrive(("Z",), "d", "COPY2", None)


def test_dep_table_none_does_not_clobber_copy():
    dt = se.DepTable()
    dt.create(("W",), 2, {})
    dt.arrive(("W",), "c", "REAL", None)
    _, inputs, _ = dt.arrive(("W",), "c", None, None)
    assert inputs == {"c": "REAL"}


def test_scheduler_selection_knob():
    """No explicit component + sched_native on -> the native queue;
    off -> the Python ladder (lfq by priority).  Pinned via params
    (override beats env) so the suite can run under
    PARSEC_MCA_SCHED_NATIVE=0 — the fallback-matrix leg."""
    from parsec_tpu.sched import create
    params.set("sched_native", 1)
    try:
        assert create().name == "native"
        assert create("lfq").name == "lfq"   # explicit always wins
        params.set("sched_native", 0)
        assert create().name == "lfq"
    finally:
        params.unset("sched_native")


@pytest.mark.parametrize("native", [1, 0])
def test_runtime_equivalence_potrf(native):
    """A/B: the same tiled Cholesky is numerically identical on the
    native and Python scheduler paths (deps countdown included)."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.core.context import Context
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    n, mb = 64, 16
    rng = np.random.default_rng(7)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    params.set("sched_native", native)
    try:
        A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n,
                              name="A").from_array(spd.copy())
        with Context(nb_cores=2) as ctx:
            assert (ctx.scheduler.name == "native") == bool(native)
            tp = potrf_taskpool(A, device="cpu")
            assert (tp._native_deps is not None) == bool(native)
            ctx.add_taskpool(tp)
            ctx.wait(timeout=120)
        L = np.tril(A.to_array())
        np.testing.assert_allclose(
            L, np.linalg.cholesky(spd.astype(np.float64)),
            rtol=5e-3, atol=5e-3)
    finally:
        params.unset("sched_native")


def test_again_task_does_not_livelock_native():
    """An AGAIN-returning body rides the to_back path and the work it
    waits on still runs (the fairness contract, end to end)."""
    from parsec_tpu.core.context import Context
    from parsec_tpu.core.task import HookReturn
    from parsec_tpu.dsl.ptg.api import PTG, Range

    state = {"done": False, "again": 0}

    def waiter():
        if not state["done"]:
            state["again"] += 1
            if state["again"] > 10000:
                raise RuntimeError("livelock: AGAIN starved the work")
            return HookReturn.AGAIN
        return None

    def worker():
        state["done"] = True

    p = PTG("fair", N=1)
    p.task("W", i=Range(0, 0)).flow("x", "CTL").body(waiter)
    p.task("D", i=Range(0, 0)).flow("x", "CTL").body(worker)
    params.set("sched_native", 1)
    try:
        with Context(nb_cores=1) as ctx:
            assert ctx.scheduler.name == "native"
            ctx.add_taskpool(p.build())
            ctx.wait(timeout=60)
    finally:
        params.unset("sched_native")
    assert state["done"]


def test_native_sched_metrics_family():
    """The sched scrape family reads the C queue's counters with zero
    hot-path hooks (prof/metrics.py _collect_sched)."""
    from bench import _empty_pool
    from parsec_tpu.core.context import Context

    params.set("sched_native", 1)
    try:
        with Context(nb_cores=1) as ctx:
            ctx.add_taskpool(_empty_pool(32))
            ctx.wait(timeout=60)
            names = {s["n"]: s for s in ctx.metrics.samples()}
            assert names["parsec_sched_native_pops_total"]["v"] >= 32
            assert names["parsec_sched_native_pushes_total"]["v"] >= 32
            assert "parsec_sched_native_fallbacks_total" in names
    finally:
        params.unset("sched_native")
