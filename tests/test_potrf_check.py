"""Numerics accounting for the Cholesky drivers (apps/potrf_check.py):
backward error of the factored tile grid and HPL-AI-style iterative
refinement recovering f32-class solve accuracy from a bf16 factor
(VERDICT r3 #3), plus the TSQRT ill-conditioning guard (ADVICE r3)."""

import numpy as np
import pytest

from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic


def _factor(n, mb, dtype, seed=0):
    """Run the real potrf taskpool over an SPD matrix stored in
    ``dtype`` tiles; returns (A tiled-matrix, orig_tile regen fn)."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    rng = np.random.default_rng(seed)
    B = rng.standard_normal((n, n)).astype(np.float32)
    spd = (B @ B.T + n * np.eye(n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, dtype=dtype)
    stored = {}
    for m, nn in A.local_tiles():
        blk = spd[m * mb:(m + 1) * mb,
                  nn * mb:(nn + 1) * mb].astype(dtype)
        stored[(m, nn)] = blk.copy()
        A.data_of(m, nn).overwrite_host(blk)
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
        ctx.wait()
    return A, lambda m, nn: stored[(m, nn)]


def test_backward_error_f32_tight():
    from parsec_tpu.apps.potrf_check import backward_error
    A, orig = _factor(64, 16, np.float32)
    err = backward_error(A, orig)
    assert err < 1e-5, err


def test_backward_error_bf16_at_storage_epsilon():
    from ml_dtypes import bfloat16
    from parsec_tpu.apps.potrf_check import backward_error
    A, orig = _factor(64, 16, bfloat16)
    err = backward_error(A, orig)
    # bf16 storage: error sits at bf16 epsilon (~8e-3), far above f32
    assert 1e-5 < err < 5e-2, err


def test_refinement_recovers_f32_accuracy_from_bf16_factor():
    """The HPL-AI contract: a bf16-storage factor + f32 residual
    iteration reaches f32-class solve accuracy in a few steps."""
    from ml_dtypes import bfloat16
    from parsec_tpu.apps.potrf_check import refine_solve
    A, orig = _factor(64, 16, bfloat16)
    hist = refine_solve(A, orig, steps=3, seed=1)
    assert hist[0] > 1e-6          # the raw bf16 solve is NOT f32-class
    assert hist[-1] < 1e-5         # refinement gets there
    assert hist[-1] < hist[0]


def test_refinement_baseline_f32_factor():
    from parsec_tpu.apps.potrf_check import refine_solve
    A, orig = _factor(64, 16, np.float32)
    hist = refine_solve(A, orig, steps=1, seed=1)
    assert hist[0] < 1e-5


def test_tsqrt_ill_conditioned_panel_no_nan():
    """ADVICE r3: chol(G) NaNs on an ill-conditioned stacked panel; the
    Householder fallback inside the TSQRT kernel must keep the QR
    factorization finite and correct."""
    from parsec_tpu.apps.qr import qr_taskpool
    mb, nt = 8, 2
    n = nt * mb
    rng = np.random.default_rng(7)
    # nearly rank-deficient columns: cond ~ 1e6, squared by Cholesky-QR
    # to ~1e12 — far beyond f32 chol
    U, _ = np.linalg.qr(rng.standard_normal((n, n)))
    V, _ = np.linalg.qr(rng.standard_normal((n, n)))
    s = np.logspace(0, -6, n)
    a = (U * s) @ V.T
    a = a.astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n).from_array(a.copy())
    with Context(nb_cores=4) as ctx:
        ctx.add_taskpool(qr_taskpool(A, device="tpu"))
        ctx.wait()
    out = A.to_array()
    assert np.isfinite(out).all()
    R = np.triu(out)
    ata = a.T @ a
    # R^T R == A^T A within f32 for a cond-1e6 matrix
    assert np.abs(R.T @ R - ata).max() / np.abs(ata).max() < 1e-2
