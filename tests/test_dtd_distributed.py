"""Distributed DTD tests: SPMD insert_task across ranks.

Mirrors the reference's distributed DTD coverage (reference:
tests/dsl/dtd/dtd_test_task_insertion.c MPI variants — chains across
ranks; dtd_test_war.c — WAR hazards; dtd_test_broadcast.c /
dtd_test_reduce.c / dtd_test_allreduce.c — collectives built on DTD;
remote writer tracking insert_function.c:3014-3163).  Every rank inserts
the identical task stream; placement follows AFFINITY or the owner of
the written tile (owner computes); cross-rank versions travel via the
comm engine's DTD tag.  Worker functions are module-level for spawn
pickling.
"""

import numpy as np
import pytest

from parsec_tpu.comm.launch import run_distributed


def _make_pool(ctx, name="dtd"):
    from parsec_tpu.dsl.dtd import DTDTaskpool
    tp = DTDTaskpool(name)
    ctx.add_taskpool(tp)
    ctx.start()
    return tp


# -- chain across ranks (dtd_test_task_insertion MPI pattern) ---------------

def _chain(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT

    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)          # home: rank 0
    steps = 13
    for i in range(steps):
        # bounce the chain around the ranks: each increment must observe
        # the previous rank's version (RAW across ranks)
        tp.insert_task(lambda T: T + 1.0, (t, INOUT),
                       (i % nranks, AFFINITY))
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    if rank == 0:
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, float(steps))
    return "ok"


@pytest.mark.parametrize("nranks", [2, 4])
def test_dtd_chain_across_ranks(nranks):
    assert run_distributed(_chain, nranks) == ["ok"] * nranks


# -- WAR hazard across ranks (dtd_test_war.c pattern) -----------------------

def _war(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT

    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 7.0
    R = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank,
                           name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0

    tp = _make_pool(ctx)
    src = tp.tile_of(V, 0)        # rank 0 owns the contested tile
    # every rank reads the pre-write value into its own result tile...
    for r in range(nranks):
        tp.insert_task(lambda s, out: np.asarray(s).copy(),
                       (src, INPUT), (tp.tile_of(R, r), OUTPUT))
    # ...then rank (nranks-1) overwrites it (WAR: the snapshot semantics
    # must hand every reader version 0, not the overwritten value)
    tp.insert_task(lambda T: T * 0.0 + 100.0, (src, INOUT),
                   (nranks - 1, AFFINITY))
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    mine = np.asarray(R.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(mine, 7.0)
    if rank == 0:
        final = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(final, 100.0)
    return "ok"


@pytest.mark.parametrize("nranks", [2, 4])
def test_dtd_war_across_ranks(nranks):
    assert run_distributed(_war, nranks) == ["ok"] * nranks


# -- broadcast (dtd_test_broadcast.c pattern) -------------------------------

def _broadcast(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT

    V = VectorTwoDimCyclic(mb=8, lm=8 * nranks, nodes=nranks, myrank=rank)
    R = VectorTwoDimCyclic(mb=8, lm=8 * nranks, nodes=nranks, myrank=rank,
                           name="R")
    for M in (V, R):
        for m, _ in M.local_tiles():
            M.data_of(m).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    root = tp.tile_of(V, 0)
    # root produces the value...
    tp.insert_task(lambda T: T + np.arange(8, dtype=np.float32),
                   (root, INOUT))
    # ...every rank copies it into its own result tile (one remote read
    # each — the dataflow broadcast of dtd_test_broadcast.c)
    for r in range(nranks):
        tp.insert_task(lambda s, out: np.asarray(s) * 2.0,
                       (root, INPUT), (tp.tile_of(R, r), OUTPUT))
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    got = np.asarray(R.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, 2.0 * np.arange(8, dtype=np.float32))
    return "ok"


def test_dtd_broadcast():
    assert run_distributed(_broadcast, 3) == ["ok"] * 3


# -- reduce to root (dtd_test_reduce.c pattern) -----------------------------

def _reduce(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import INOUT, INPUT

    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m + 1)
    acc = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank,
                             name="acc")
    if rank == 0:
        acc.data_of(0).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    out = tp.tile_of(acc, 0)
    for m in range(nranks):
        tp.insert_task(lambda a, x: a + np.asarray(x),
                       (out, INOUT), (tp.tile_of(V, m), INPUT))
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    if rank == 0:
        got = np.asarray(acc.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(got, sum(range(1, nranks + 1)))
    return "ok"


def test_dtd_reduce():
    assert run_distributed(_reduce, 4) == ["ok"] * 4


# -- allreduce (dtd_test_allreduce.c pattern) -------------------------------

def _allreduce(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import INOUT, INPUT, OUTPUT

    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = float(m + 1)
    S = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank,
                           name="S")
    for m, _ in S.local_tiles():
        S.data_of(m).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    # reduce onto rank 0's S(0)...
    root = tp.tile_of(S, 0)
    for m in range(nranks):
        tp.insert_task(lambda a, x: a + np.asarray(x),
                       (root, INOUT), (tp.tile_of(V, m), INPUT))
    # ...then broadcast the sum into every rank's S tile
    for r in range(1, nranks):
        tp.insert_task(lambda s, out: np.asarray(s).copy(),
                       (root, INPUT), (tp.tile_of(S, r), OUTPUT))
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    got = np.asarray(S.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, sum(range(1, nranks + 1)))
    return "ok"


def test_dtd_allreduce():
    assert run_distributed(_allreduce, 3) == ["ok"] * 3


# -- AFFINITY honored for rank placement ------------------------------------

def _affinity_placement(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT

    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank)
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    ran_here = []
    # tile homes are cyclic; AFFINITY forces every task onto rank 0
    for m in range(nranks):
        t = tp.insert_task(
            lambda T: (ran_here.append(1), T + 1.0)[1],
            (tp.tile_of(V, m), INOUT), (0, AFFINITY))
        if rank == 0:
            assert t is not None, "AFFINITY rank 0 must insert locally"
        else:
            assert t is None, "AFFINITY elsewhere must track remotely"
    tp.wait(timeout=60)
    ctx.wait(timeout=60)
    assert len(ran_here) == (nranks if rank == 0 else 0)
    # flush-home: each rank's own tile must hold the incremented value
    got = np.asarray(V.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, 1.0)
    return "ok"


def test_dtd_affinity_placement():
    assert run_distributed(_affinity_placement, 3) == ["ok"] * 3


# -- distributed DTD with DEVICE tasks: surrogate payload pulls must
# materialize eager device outputs before they ship ------------------------

def _device_chain(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT

    V = VectorTwoDimCyclic(mb=8, lm=8, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 1.0
    tp = _make_pool(ctx, "dev-chain")
    t = tp.tile_of(V, 0)
    steps = 8

    def bump(T):
        # device incarnation: runs through the XLA module when a device
        # is attached (spawned ranks run the CPU jax backend), else the
        # DTD cpu fallback
        return T * 2.0

    for i in range(steps):
        tp.insert_task(bump, (t, INOUT), (i % nranks, AFFINITY),
                       device="tpu")
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 0:
        got = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(got, float(2 ** steps))
    return "ok"


def test_dtd_distributed_device_chain():
    assert run_distributed(_device_chain, 2, timeout=240) == ["ok"] * 2


# -- ordering must survive SKIPPED surrogate versions (ADVICE r2 high) ------

def _skipped_version_reader(ctx, rank, nranks):
    """Two consecutive remote writes whose intermediate version has no
    local consumer: the recv-apply of the LATER version must still wait
    for a pending local reader of an older version (WAR through the
    skipped surrogate's WAW chain).  Pre-fix, the unneeded v2 surrogate
    dead-ended the chain and v3's payload overwrote the host copy while
    the slow reader was mid-body."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT

    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=4, lm=8 * nranks, nodes=nranks, myrank=rank,
                           name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0
    tp = _make_pool(ctx, "skip-war")
    t = tp.tile_of(V, 0)              # home: rank 0; every write there
    res1 = tp.tile_of(R, 1)           # home: rank 1
    res2 = tp.tile_of(R, nranks + 1)  # home: rank 1

    def slow_read(s, out):
        import time
        time.sleep(1.0)               # v3's payload arrives mid-body
        return np.asarray(s).copy()

    tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))  # v1
    tp.insert_task(slow_read, (t, INPUT), (res1, OUTPUT), (1, AFFINITY))
    tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))  # v2: no
    tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))  # reader
    tp.insert_task(lambda s, out: np.asarray(s).copy(),           # needs v3
                   (t, INPUT), (res2, OUTPUT), (1, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 1:
        got1 = np.asarray(R.data_of(1).pull_to_host().payload)
        np.testing.assert_allclose(got1, 1.0)   # the slow reader saw v1
        got2 = np.asarray(R.data_of(nranks + 1).pull_to_host().payload)
        np.testing.assert_allclose(got2, 3.0)   # the late reader saw v3
    if rank == 0:
        final = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(final, 3.0)
    return "ok"


def test_dtd_skipped_surrogate_reader_order():
    assert run_distributed(_skipped_version_reader, 2,
                           timeout=240) == ["ok"] * 2


def _skipped_version_local_writer(ctx, rank, nranks):
    """A LOCAL writer after a skipped remote version must wait for the
    pending reader of the older version (WAW through the unneeded
    surrogate carries the WAR edge).  Pre-fix the local-OUTPUT path
    skipped the edge and the overwrite raced the reader."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT

    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks, myrank=rank,
                           name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0
    tp = _make_pool(ctx, "skip-waw")
    t = tp.tile_of(V, 0)
    res = tp.tile_of(R, 1)

    def slow_read(s, out):
        import time
        time.sleep(1.0)
        return np.asarray(s).copy()

    tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))  # v1
    tp.insert_task(slow_read, (t, INPUT), (res, OUTPUT), (1, AFFINITY))
    tp.insert_task(lambda T: T + 1.0, (t, INOUT), (0, AFFINITY))  # v2: no
    # pure OUTPUT on rank 1: overwrites without reading — but only after
    # the slow reader of v1 is done                               # reader
    tp.insert_task(lambda T: np.full((4,), 50.0, np.float32),
                   (t, OUTPUT), (1, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 1:
        got = np.asarray(R.data_of(1).pull_to_host().payload)
        np.testing.assert_allclose(got, 1.0)    # reader saw v1, not 50
    if rank == 0:
        final = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(final, 50.0)
    return "ok"


def test_dtd_skipped_surrogate_local_writer_order():
    assert run_distributed(_skipped_version_local_writer, 2,
                           timeout=240) == ["ok"] * 2


# -- rendezvous path for large DTD payloads ---------------------------------

def _rdv_chain(ctx, rank, nranks):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT
    from parsec_tpu.utils.mca import params

    params.set("comm_eager_limit", 64)    # force every tile over the limit
    try:
        V = VectorTwoDimCyclic(mb=256, lm=256, nodes=nranks, myrank=rank)
        if rank == 0:
            V.data_of(0).copy_on(0).payload[:] = 0.0
        tp = _make_pool(ctx, "rdv")
        t = tp.tile_of(V, 0)
        steps = 6
        for i in range(steps):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT),
                           (i % nranks, AFFINITY))
        tp.wait(timeout=120)
        ctx.wait(timeout=120)
        # the serve-once regions drain as the last GETs are served — a
        # peer's pull may complete a beat after our quiescence returns
        import time
        deadline = time.monotonic() + 15
        while ctx.comm.ce._regions and time.monotonic() < deadline:
            time.sleep(0.02)
        assert not ctx.comm.ce._regions, dict(ctx.comm.ce._regions)
        assert ctx.comm.dtd_refs_pending == 0
        if rank == 0:
            val = np.asarray(V.data_of(0).pull_to_host().payload)
            np.testing.assert_allclose(val, float(steps))
    finally:
        params.unset("comm_eager_limit")
    return "ok"


def test_dtd_rendezvous_large_payloads():
    assert run_distributed(_rdv_chain, 2, timeout=240) == ["ok"] * 2


# -- distributed region lanes (VERDICT r3 #5: insert_function.h:60-78
# region masks work across ranks via per-region wire payloads) ------------

def _region_disjoint(ctx, rank, nranks):
    """Two ranks write DISJOINT halves of one rank-0-owned tile through
    region lanes, each chaining privately (RAW within a lane, no false
    serialization across lanes), then rank 0 reads the whole tile."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import (AFFINITY, INOUT, INPUT, OUTPUT,
                                    Region)

    V = VectorTwoDimCyclic(mb=8, lm=8, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=8, lm=8 * nranks, nodes=nranks,
                           myrank=rank, name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0

    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    lo = Region("lo", slices=(slice(0, 4),))
    hi = Region("hi", slices=(slice(4, 8),))

    def add_lo(T):            # a lane body touches ONLY its extent
        out = np.asarray(T).copy()
        out[0:4] += 1.0
        return out

    def add_hi(T):
        out = np.asarray(T).copy()
        out[4:8] += 2.0
        return out

    steps = 5
    # rank 0 increments the low half, rank 1 the high half — in lanes,
    # so the two chains never serialize against each other
    for i in range(steps):
        tp.insert_task(add_lo, (t, INOUT | lo), (0, AFFINITY))
        tp.insert_task(add_hi, (t, INOUT | hi), (nranks - 1, AFFINITY))
    # a whole-tile reader on each rank observes BOTH lanes' final values
    for r in range(nranks):
        tp.insert_task(lambda s, out: np.asarray(s).copy(),
                       (t, INPUT), (tp.tile_of(R, r), OUTPUT))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    want = np.concatenate([np.full(4, float(steps)),
                           np.full(4, 2.0 * steps)]).astype(np.float32)
    got = np.asarray(R.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, want)
    return "ok"


def test_dtd_distributed_region_lanes_disjoint_writers():
    assert run_distributed(_region_disjoint, 2, timeout=240) == ["ok"] * 2


def _region_lane_chain_with_whole_tile_barrier(ctx, rank, nranks):
    """A whole-tile write after lane writes must observe every lane
    (conflicts with all), and lane writes after it chain off it."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, Region

    V = VectorTwoDimCyclic(mb=8, lm=8, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    lo = Region("lo", slices=(slice(0, 4),))
    hi = Region("hi", slices=(slice(4, 8),))

    def add_lo(T, bump=1.0):
        out = np.asarray(T).copy()
        out[0:4] += bump
        return out

    def add_hi(T):
        out = np.asarray(T).copy()
        out[4:8] += 2.0
        return out

    tp.insert_task(add_lo, (t, INOUT | lo), (0, AFFINITY))
    tp.insert_task(add_hi, (t, INOUT | hi), (nranks - 1, AFFINITY))
    # whole-tile doubling on rank 1: must see lo=1 and hi=2
    tp.insert_task(lambda T: T * 2.0, (t, INOUT), (nranks - 1, AFFINITY))
    # lane write after the barrier, back on rank 0
    tp.insert_task(lambda T: add_lo(T, 10.0), (t, INOUT | lo),
                   (0, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 0:
        got = np.asarray(V.data_of(0).pull_to_host().payload)
        want = np.concatenate([np.full(4, 12.0), np.full(4, 4.0)])
        np.testing.assert_allclose(got, want.astype(np.float32))
    return "ok"


def test_dtd_distributed_region_whole_tile_barrier():
    assert run_distributed(_region_lane_chain_with_whole_tile_barrier, 2,
                           timeout=240) == ["ok"] * 2


def _region_three_rank_disjoint(ctx, rank, nranks):
    """Reviewer scenario (r4): ranks 1 and 2 lane-write disjoint slices
    of a rank-0-home tile; rank 0 reads the whole tile.  The two recv
    appliers on rank 0 are unordered — the apply-lock + slice merges
    must keep both lanes' bytes."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT, Region

    V = VectorTwoDimCyclic(mb=8, lm=8, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=8, lm=8 * nranks, nodes=nranks,
                           myrank=rank, name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0
    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    lo = Region("lo", slices=(slice(0, 4),))
    hi = Region("hi", slices=(slice(4, 8),))

    def add(sl, bump):
        def body(T):
            out = np.asarray(T).copy()
            out[sl] += bump
            return out
        return body

    tp.insert_task(add(slice(0, 4), 3.0), (t, INOUT | lo), (1, AFFINITY))
    tp.insert_task(add(slice(4, 8), 4.0), (t, INOUT | hi), (2, AFFINITY))
    tp.insert_task(lambda s, o: np.asarray(s).copy(),
                   (t, INPUT), (tp.tile_of(R, 0), OUTPUT), (0, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 0:
        got = np.asarray(R.data_of(0).pull_to_host().payload)
        want = np.concatenate([np.full(4, 3.0), np.full(4, 4.0)])
        np.testing.assert_allclose(got, want.astype(np.float32))
    return "ok"


def test_dtd_region_three_rank_disjoint_appliers():
    assert run_distributed(_region_three_rank_disjoint, 3,
                           timeout=240) == ["ok"] * 3


def _region_output_then_whole_read(ctx, rank, nranks):
    """Reviewer scenario (r4): an OUTPUT-mode lane write on a non-home
    rank must not suppress the pristine v0 pull — a later whole-tile
    read there needs home's bytes for the uncovered extent."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INPUT, OUTPUT, Region

    V = VectorTwoDimCyclic(mb=8, lm=8, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 7.0   # home bytes
    R = VectorTwoDimCyclic(mb=8, lm=8 * nranks, nodes=nranks,
                           myrank=rank, name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0
    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    lo = Region("lo", slices=(slice(0, 4),))

    def write_lo(T):
        out = np.asarray(T).copy()
        out[0:4] = 9.0
        return out

    # rank 1 OUTPUT-writes ONLY the low lane of the rank-0-home tile...
    tp.insert_task(write_lo, (t, OUTPUT | lo), (1, AFFINITY))
    # ...then reads the whole tile: rows 4-8 must be home's 7s
    tp.insert_task(lambda s, o: np.asarray(s).copy(),
                   (t, INPUT), (tp.tile_of(R, 1), OUTPUT), (1, AFFINITY))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    if rank == 1:
        got = np.asarray(R.data_of(1).pull_to_host().payload)
        want = np.concatenate([np.full(4, 9.0), np.full(4, 7.0)])
        np.testing.assert_allclose(got, want.astype(np.float32))
    return "ok"


def test_dtd_region_output_lane_then_whole_read():
    assert run_distributed(_region_output_then_whole_read, 2,
                           timeout=240) == ["ok"] * 2


def _region_four_rank_quarters(ctx, rank, nranks):
    """4 ranks each own one quarter-lane of a rank-0-home tile and
    chain privately over 3 rounds; every rank then reads the whole
    tile.  Exercises v0 pulls, lane surrogates, sliced payloads, and
    version-aware flushes under maximal interleaving."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT, Region

    V = VectorTwoDimCyclic(mb=16, lm=16, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=16, lm=16 * nranks, nodes=nranks,
                           myrank=rank, name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0
    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    quarters = [Region(f"q{i}", slices=(slice(4 * i, 4 * i + 4),))
                for i in range(4)]

    def bump(i):
        def body(T):
            out = np.asarray(T).copy()
            out[4 * i:4 * i + 4] += i + 1
            return out
        return body

    for _ in range(3):
        for i, q in enumerate(quarters):
            tp.insert_task(bump(i), (t, INOUT | q), (i, AFFINITY))
    for r in range(nranks):
        tp.insert_task(lambda s, o: np.asarray(s).copy(),
                       (t, INPUT), (tp.tile_of(R, r), OUTPUT))
    tp.wait(timeout=180)
    ctx.wait(timeout=180)
    want = np.repeat(np.arange(1.0, 5.0) * 3, 4).astype(np.float32)
    got = np.asarray(R.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, want)
    return "ok"


def test_dtd_region_four_rank_quarter_lanes():
    assert run_distributed(_region_four_rank_quarters, 4,
                           timeout=300) == ["ok"] * 4


def _region_ordering_only(ctx, rank, nranks):
    """VERDICT r4 #8: EXTENT-LESS (ordering-only) region lanes across
    ranks — the reference's region masks need no user byte extent
    (insert_function.h:60-78).  The lane id + version keep the lane's
    write chain totally ordered on the wire; payloads ship whole-tile."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, INPUT, OUTPUT, Region

    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    R = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks,
                           myrank=rank, name="R")
    for m, _ in R.local_tiles():
        R.data_of(m).copy_on(0).payload[:] = -1.0

    tp = _make_pool(ctx)
    t = tp.tile_of(V, 0)
    x = Region("x")                      # NO slices: ordering-only

    def step(T):
        return np.asarray(T) * 2.0 + 1.0

    # order-sensitive chain bouncing between ranks inside one lane:
    # 0 -> 1 -> 3 -> 7 -> 15 -> 31 -> 63 (any reordering changes it)
    for i in range(6):
        tp.insert_task(step, (t, INOUT | x), (i % nranks, AFFINITY))
    # a whole-tile reader on each rank conflicts with every lane and
    # must observe the final chained value
    for r in range(nranks):
        tp.insert_task(lambda s, out: np.asarray(s).copy(),
                       (t, INPUT), (tp.tile_of(R, r), OUTPUT))
    tp.wait(timeout=120)
    ctx.wait(timeout=120)
    got = np.asarray(R.data_of(rank).pull_to_host().payload)
    np.testing.assert_allclose(got, np.full(4, 63.0, np.float32))
    return "ok"


def test_dtd_region_ordering_only_across_ranks():
    assert run_distributed(_region_ordering_only, 2, timeout=240) \
        == ["ok"] * 2
