"""Causal trace pipeline: clock alignment, cross-rank merge, critical
path + makespan attribution (reference role: PINS + binary trace +
OTF2 + external analysis, collapsed into prof/causal.py ->
prof/critpath.py -> tools/trace2chrome.py --merge)."""

import json
import os
import time

import numpy as np
import pytest

from parsec_tpu.comm.engine import clock_offset_estimate
from parsec_tpu.core.context import Context
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.prof import critpath
from parsec_tpu.prof.causal import install_causal_tracer
from parsec_tpu.prof.pins import install_task_profiler
from parsec_tpu.prof.profiling import (EV_END, EV_POINT, EV_START,
                                       Profile)


# -- clock-offset estimator -------------------------------------------------

def test_clock_offset_symmetric_delay_exact():
    """Symmetric path delay: the midpoint estimate recovers the true
    offset exactly, whatever the delay magnitude."""
    true_off = 3.25          # peer clock ahead of ours by 3.25s
    for delay in (1e-4, 2e-3, 0.5):
        t0 = 100.0
        t1 = t0 + delay + true_off           # peer stamps on ITS clock
        t2 = t0 + 2 * delay
        off, rtt = clock_offset_estimate([(t0, t1, t2)])
        assert off == pytest.approx(true_off, abs=1e-12)
        assert rtt == pytest.approx(2 * delay)


def test_clock_offset_asymmetric_delay_bounded_by_half_rtt():
    """Asymmetric delay biases the estimate by (fwd-back)/2 — always
    within rtt/2 (the Cristian bound the estimator documents)."""
    true_off = -7.5
    fwd, back = 3e-3, 1e-3
    t0 = 50.0
    t1 = t0 + fwd + true_off
    t2 = t0 + fwd + back
    off, rtt = clock_offset_estimate([(t0, t1, t2)])
    assert abs(off - true_off) <= rtt / 2 + 1e-12
    assert off - true_off == pytest.approx((fwd - back) / 2)


def test_clock_offset_min_rtt_sample_wins():
    """Queueing only inflates rtt, so the tightest sample is the most
    symmetric: a noisy batch must resolve to the clean sample's
    estimate, not an average polluted by the congested ones."""
    true_off = 1.0
    clean = (10.0, 10.0 + 1e-4 + true_off, 10.0 + 2e-4)
    noisy = [(t0, t0 + 0.05 + true_off + 0.04, t0 + 0.1)   # asym + slow
             for t0 in (11.0, 12.0, 13.0)]
    off, rtt = clock_offset_estimate(noisy + [clean] + noisy)
    assert off == pytest.approx(true_off, abs=1e-9)
    assert rtt == pytest.approx(2e-4)


# -- critical path on a synthetic hand-built DAG ----------------------------

def _mk_profile(rank, nranks, offsets=None):
    p = Profile(f"synth-r{rank}")
    p.add_information("rank", str(rank))
    p.add_information("nranks", str(nranks))
    if offsets:
        p.add_information("clock_offsets", json.dumps(offsets))
    return p


def _iv(p, sb, name, tpid, oid, t0, t1):
    k = p.add_event_class(name).key
    eid = p.next_event_id()
    sb.trace(k, EV_START, tpid, eid, oid, timestamp=t0)
    sb.trace(k, EV_END, tpid, eid, oid, timestamp=t1)


def _pt(p, sb, name, oid, ts, info, tpid=1):
    # node identity is (rank, taskpool, oid): the point events must
    # carry the same pool id as the intervals they bind to
    k = p.add_event_class(name).key
    sb.trace(k, EV_POINT, tpid, p.next_event_id(), oid, info,
             timestamp=ts)


def test_critpath_synthetic_known_path(tmp_path):
    """Hand-built 2-rank DAG with a known critical path A -> C (the
    cross-rank comm edge), a decoy local chain A -> B, and buckets that
    sum exactly to the makespan."""
    # rank 0: A [0,1] -> B [1.5,3] locally; A also feeds C on rank 1
    p0 = _mk_profile(0, 2)
    w0 = p0.stream(0, "worker-0")
    c0 = p0.stream(800, "comm")
    _iv(p0, w0, "A", 1, 101, 0.0, 1.0)
    _iv(p0, w0, "B", 1, 102, 1.5, 3.0)
    _pt(p0, w0, "dep_edge", 101, 1.0, {"dst": 102})
    _pt(p0, c0, "comm_send", 101, 1.0,
        {"corr": (0, 1), "tag": 1, "dst": 1, "nbytes": 64})
    # rank 1 (clock offset 0): recv at 1.2, C ready 1.4, runs [2.0,5.0]
    p1 = _mk_profile(1, 2, offsets={"0": 0.0})
    w1 = p1.stream(0, "worker-0")
    c1 = p1.stream(800, "comm")
    _pt(p1, c1, "comm_recv", 0, 1.2,
        {"corr": (0, 1), "tag": 1, "src": 0, "sent_at": 1.0,
         "nbytes": 64})
    _pt(p1, c1, "dep_deliver", 103, 1.2, {"corr": (0, 1)})
    _iv(p1, w1, "queue_wait", 1, 103, 1.4, 2.0)
    _iv(p1, w1, "C", 1, 103, 2.0, 5.0)
    paths = [p0.dump(str(tmp_path / "r0.ptt")),
             p1.dump(str(tmp_path / "r1.ptt"))]

    att = critpath.attribution(paths)
    names = [s["task"] for s in att["path"]]
    assert names == ["A", "C"], names          # not the decoy A -> B
    assert att["path"][0]["via"] == "local"
    assert att["path"][1]["via"] == "comm"
    b = att["buckets"]
    # A exec 1.0 + comm 0.2 (1.0->1.2) + idle 0.2 (1.2->1.4)
    # + queue 0.6 (1.4->2.0) + C exec 3.0 == makespan 5.0
    assert b["exec"] == pytest.approx(4.0)
    assert b["comm"] == pytest.approx(0.2)
    assert b["idle"] == pytest.approx(0.2)
    assert b["queue"] == pytest.approx(0.6)
    assert att["makespan"] == pytest.approx(5.0)
    assert att["coverage"] == pytest.approx(1.0)
    assert att["flows"] == {"sends": 1, "recvs": 1, "matched": 1}


def test_critpath_clock_offset_alignment(tmp_path):
    """A rank whose clock runs 100s ahead merges onto the reference
    timeline through its recorded offset: the cross-rank edge stays
    causal (recv after send) instead of 100s in the past."""
    p0 = _mk_profile(0, 2)
    _iv(p0, p0.stream(0, "w"), "A", 1, 1, 0.0, 1.0)
    _pt(p0, p0.stream(800, "comm"), "comm_send", 1, 1.0,
        {"corr": (0, 1), "tag": 1, "dst": 1, "nbytes": 0})
    # rank 1's clock reads t+100: its measured offset to rank 0 is -100
    p1 = _mk_profile(1, 2, offsets={"0": -100.0})
    _pt(p1, p1.stream(800, "comm"), "dep_deliver", 2, 101.5,
        {"corr": (0, 1)})
    _iv(p1, p1.stream(0, "w"), "C", 1, 2, 102.0, 103.0)
    att = critpath.attribution([p0.dump(str(tmp_path / "a.ptt")),
                                p1.dump(str(tmp_path / "b.ptt"))])
    assert [s["task"] for s in att["path"]] == ["A", "C"]
    assert att["makespan"] == pytest.approx(3.0)
    assert att["buckets"]["comm"] == pytest.approx(0.5)   # 1.0 -> 1.5
    assert att["coverage"] == pytest.approx(1.0)


# -- single-rank causal spans ----------------------------------------------

def _chain_pool(A, nt, device="cpu"):
    p = PTG("chain", NT=nt)
    p.task("S", k=Range(0, nt - 1)) \
        .affinity(lambda k, A=A: A(0, 0)) \
        .flow("T", "RW",
              IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
              IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                 when=lambda k: k > 0),
              OUT(TASK("S", "T", lambda k, NT=nt: dict(k=k + 1)),
                  when=lambda k, NT=nt: k < NT - 1),
              OUT(DATA(lambda A=A: A(0, 0)),
                  when=lambda k, NT=nt: k == NT - 1)) \
        .body(lambda T: T + 1.0, device=device)
    return p.build()


def test_causal_spans_single_rank(tmp_path):
    """Queue-wait and device spans land with the SAME object id as the
    task profiler's exec interval, so the per-task latency decomposes;
    local dep_edge events reconstruct the chain."""
    from parsec_tpu.prof.reader import intervals, read_trace
    nt = 10
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    prof = Profile("causal")
    with Context(nb_cores=2) as ctx:
        mod = install_task_profiler(ctx, prof)
        tr = install_causal_tracer(ctx, prof)
        ctx.add_taskpool(_chain_pool(A, nt, device="tpu"))
        ctx.wait(timeout=120)
        mod.uninstall(ctx)
        tr.uninstall(ctx)
    meta, df = read_trace(prof.dump(str(tmp_path / "c.ptt")))
    assert meta["info"]["rank"] == "0"
    iv = intervals(df)
    ex = iv[iv["name"] == "S"]
    qw = iv[iv["name"] == "queue_wait"]
    dev = iv[iv["name"] == "dev:S"]
    assert len(ex) == nt and len(qw) == nt and len(dev) == nt
    assert set(qw["object_id"]) == set(ex["object_id"])
    assert set(dev["object_id"]) == set(ex["object_id"])
    assert (qw["duration"] >= 0).all() and (dev["duration"] > 0).all()
    edges = df[df["name"] == "dep_edge"]
    assert len(edges) == nt - 1              # the chain's local edges
    # the causal DAG extracted from the trace IS the chain
    df["rank"] = 0
    tasks, preds, ready = critpath.build_dag(df)
    path = critpath.critical_path(tasks, preds)
    assert len(path) == nt
    att = critpath.attribute(path, tasks, ready)
    assert abs(sum(att["buckets"].values()) - att["makespan"]) \
        <= 0.05 * att["makespan"]


def test_dtd_lane_events_traced(tmp_path):
    """DTD region-lane operations (the machinery behind the ROADMAP's
    ordering-race flake) leave dtd_lane events: per-lane writes, reads,
    and lane ids are all in the trace."""
    from parsec_tpu.dsl.dtd import DTDTaskpool, INOUT, INPUT, Region
    from parsec_tpu.prof.reader import read_trace
    A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
    A.data_of(0, 0).copy_on(0).payload[:] = 0.0
    prof = Profile("dtd")
    top = Region("top", slices=(slice(0, 2),))
    bot = Region("bot", slices=(slice(2, 4),))
    with Context(nb_cores=2) as ctx:
        tr = install_causal_tracer(ctx, prof)
        tp = DTDTaskpool("lanes")
        ctx.add_taskpool(tp)
        ctx.start()
        t = tp.tile_of(A, 0, 0)
        for _ in range(3):
            tp.insert_task(lambda T: T + 1.0, (t, INOUT | top))
            tp.insert_task(lambda T: T + 2.0, (t, INOUT | bot))
        tp.insert_task(lambda T: None, (t, INPUT))
        tp.wait()
        tr.uninstall(ctx)
    _meta, df = read_trace(prof.dump(str(tmp_path / "d.ptt")))
    lanes = df[df["name"] == "dtd_lane"]
    assert len(lanes)
    ops = {i["op"] for i in lanes["info"]}
    assert "write" in ops and "read" in ops
    lane_ids = {i["lane"] for i in lanes["info"]}
    assert {"top", "bot"} <= lane_ids
    # per-lane write versions are recorded in insertion order
    top_vers = [i["ver"] for i in lanes["info"]
                if i["op"] == "write" and i["lane"] == "top"]
    assert top_vers == sorted(top_vers) and len(top_vers) == 3


# -- 2-rank loopback: the acceptance-criteria run ---------------------------

def _traced_potrf(ctx, rank, nranks, outdir):
    from parsec_tpu.apps.potrf import potrf_taskpool
    prof = Profile(f"potrf-r{rank}")
    mod = install_task_profiler(ctx, prof)
    tr = install_causal_tracer(ctx, prof)
    n, mb = 64, 16
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = a @ a.T + n * np.eye(n, dtype=np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                          myrank=rank, name="A")
    for m, nn in A.local_tiles():
        np.asarray(A.data_of(m, nn).copy_on(0).payload)[:] = \
            spd[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
    ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
    ctx.wait(timeout=120)
    # the clock handshake runs through the same loop the workload used;
    # wait for at least one pong round before the header snapshot
    deadline = time.time() + 15
    while len(ctx.comm.ce.clock) < nranks - 1 and time.time() < deadline:
        time.sleep(0.05)
    mod.uninstall(ctx)
    tr.uninstall(ctx)
    return prof.dump(os.path.join(outdir, f"rank{rank}.ptt"))


def test_two_rank_potrf_merged_trace(tmp_path):
    """The ISSUE's acceptance run: a 2-rank potrf whose merged trace
    (a) matches a recv to EVERY cross-rank activation send, (b) has
    clock offsets in both headers, and (c) attributes the makespan into
    buckets summing within 5%."""
    import subprocess
    import sys
    from parsec_tpu.comm.engine import TAG_ACTIVATE
    from parsec_tpu.comm.launch import run_distributed
    paths = run_distributed(_traced_potrf, 2, args=(str(tmp_path),),
                            timeout=240)
    df, metas = critpath.merge_traces(paths)
    assert json.loads(metas[1]["info"]["clock_offsets"]).keys() == {"0"}
    # (a) every activation's send event has its matched recv event
    acts = df[(df["name"] == "comm_send")]
    act_corrs = {tuple(i["corr"]) for i in acts["info"]
                 if i.get("tag") == TAG_ACTIVATE}
    assert act_corrs, "no cross-rank activations traced"
    recv_corrs = {tuple(i["corr"])
                  for i in df[df["name"] == "comm_recv"]["info"]}
    assert act_corrs <= recv_corrs
    # cross-rank deliveries bind the flow edges to consumer tasks
    delivered = {tuple(i["corr"])
                 for i in df[df["name"] == "dep_deliver"]["info"]
                 if i.get("corr") is not None}
    assert delivered & act_corrs
    # (c) attribution buckets sum to within 5% of the measured makespan
    att = critpath.attribution(paths)
    assert att["makespan"] > 0
    assert abs(sum(att["buckets"].values()) - att["makespan"]) \
        <= 0.05 * att["makespan"], att
    assert any(s["via"] == "comm" for s in att["path"])

    # trace2chrome --merge: one Perfetto file, one flow arrow per
    # matched activation
    out = str(tmp_path / "merged.json")
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "tools/trace2chrome.py", "--merge", *paths,
         "-o", out], capture_output=True, text=True, timeout=120,
        env=env)
    assert r.returncode == 0, r.stderr
    doc = json.load(open(out))
    flows_s = {e["id"] for e in doc["traceEvents"] if e["ph"] == "s"}
    flows_f = {e["id"] for e in doc["traceEvents"] if e["ph"] == "f"}
    assert flows_s == flows_f
    act_ids = {f"{c[0]}:{c[1]}" for c in act_corrs}
    assert act_ids <= flows_s
    assert doc["otherData"]["attribution"]["coverage"] >= 0.95

    # trace_info --stats on one rank's file: the r7 columns
    r = subprocess.run(
        [sys.executable, "tools/trace_info.py", paths[1], "--stats"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "per-class queue-wait" in r.stdout
    assert "comm delay by source rank" in r.stdout
    assert "UNCORRECTED" not in r.stdout     # offsets were recorded


def _traced_fanout(ctx, rank, nranks, outdir):
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    prof = Profile(f"fan-r{rank}")
    mod = install_task_profiler(ctx, prof)
    tr = install_causal_tracer(ctx, prof)
    V = VectorTwoDimCyclic(mb=4, lm=4 * nranks, nodes=nranks,
                           myrank=rank, name="V")
    for m, _ in V.local_tiles():
        V.data_of(m).copy_on(0).payload[:] = 1.0
    p = PTG("fan", NR=nranks)
    p.task("A", k=Range(0, 0)) \
        .affinity(lambda k, V=V: V(0)) \
        .flow("X", "RW", IN(DATA(lambda k, V=V: V(0))),
              OUT(TASK("B", "Y", lambda k: dict(r=1))),
              OUT(TASK("B", "Y", lambda k: dict(r=2)))) \
        .body(lambda X: X + 1.0)
    p.task("B", r=Range(1, nranks - 1)) \
        .affinity(lambda r, V=V: V(r)) \
        .flow("Y", "RW", IN(TASK("A", "X", lambda r: dict(k=0))),
              OUT(DATA(lambda r, V=V: V(r)))) \
        .body(lambda Y: Y * 2.0)
    ctx.add_taskpool(p.build())
    ctx.wait(timeout=120)
    mod.uninstall(ctx)
    tr.uninstall(ctx)
    return prof.dump(os.path.join(outdir, f"rank{rank}.ptt"))


def test_tree_forwarded_edge_attributes_to_producer(tmp_path):
    """Chain broadcast on 3 ranks: rank 1 FORWARDS rank 0's activation
    to rank 2.  The forwarded frame's flow edge must attach to the
    producer's task node on rank 0 (the frame's root), not to a
    nonexistent task on the forwarder."""
    from parsec_tpu.comm.launch import run_distributed
    prior = os.environ.get("PARSEC_MCA_COMM_COLL_BCAST")
    os.environ["PARSEC_MCA_COMM_COLL_BCAST"] = "chain"
    try:
        paths = run_distributed(_traced_fanout, 3,
                                args=(str(tmp_path),), timeout=240)
    finally:
        if prior is None:
            os.environ.pop("PARSEC_MCA_COMM_COLL_BCAST", None)
        else:
            os.environ["PARSEC_MCA_COMM_COLL_BCAST"] = prior
    df, _metas = critpath.merge_traces(paths)
    # rank 1's forward carries the producer's rank
    fwd = [i for i in df[(df["name"] == "comm_send")
                         & (df["rank"] == 1)]["info"]
           if i.get("src_rank") == 0]
    assert fwd, "no forwarded activation traced on the relay rank"
    tasks, preds, _ready = critpath.build_dag(df)
    b2 = [n for n, t in tasks.items()
          if t["name"] == "B" and t["rank"] == 2]
    assert b2, "consumer task missing from rank 2's trace"
    comm_in = [(pn, e) for pn, e in preds.get(b2[0], [])
               if e is not None]
    assert comm_in, "no flow edge into the forwarded consumer"
    assert any(tasks.get(pn, {}).get("name") == "A" and pn[0] == 0
               for pn, _e in comm_in), comm_in


def test_reader_tolerates_unknown_event_classes(tmp_path):
    """A trace whose dictionary misses a key (a newer writer's class)
    or carries extra dictionary fields still reads: unknown classes
    degrade to key<N> names, and trace_info runs on it."""
    import subprocess
    import sys
    from parsec_tpu.prof.reader import read_trace
    p = Profile("fwd")
    sb = p.stream(0, "w")
    k = p.add_event_class("KNOWN").key
    _iv_id = p.next_event_id()
    sb.trace(k, EV_START, 1, _iv_id, 7, timestamp=1.0)
    sb.trace(k, EV_END, 1, _iv_id, 7, timestamp=2.0)
    sb.trace(k + 57, EV_POINT, 1, p.next_event_id(), 0,
             {"new": True}, timestamp=1.5)    # class not in dictionary
    path = p.dump(str(tmp_path / "f.ptt"))
    # future dictionaries may carry extra per-class fields
    import pickle
    import struct
    from parsec_tpu.prof.profiling import MAGIC
    raw = open(path, "rb").read()
    (mlen,) = struct.unpack_from("!Q", raw, 8)
    meta = pickle.loads(raw[16:16 + mlen])
    meta["dictionary"] = [(kk, nn, aa, {"future": 1})
                          for kk, nn, aa in meta["dictionary"]]
    mb = pickle.dumps(meta, protocol=pickle.HIGHEST_PROTOCOL)
    with open(path, "wb") as f:
        f.write(MAGIC + struct.pack("!Q", len(mb)) + mb
                + raw[16 + mlen:])
    meta2, df = read_trace(path)
    assert set(df["name"]) == {"KNOWN", f"key{k + 57}"}
    env = {**os.environ, "JAX_PLATFORMS": "cpu"}
    r = subprocess.run(
        [sys.executable, "tools/trace_info.py", path, "--stats",
         "--events"],
        capture_output=True, text=True, timeout=120, env=env)
    assert r.returncode == 0, r.stderr
    assert "total events: 3" in r.stdout
    assert f"key{k + 57}" in r.stdout    # unknown class, named not dropped
