"""tools/bench_guard.py: the pre-merge bench regression smoke — diff a
fresh bench JSON against the previous BENCH_r*.json artifact, exit
non-zero on >threshold regression of any shared recorded metric."""

import json
import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(__file__), "..",
                                "tools"))
import bench_guard  # noqa: E402


def _write(tmp_path, name, obj):
    p = tmp_path / name
    p.write_text(json.dumps(obj))
    return str(p)


def _artifact(tmp_path, r, parsed):
    return _write(tmp_path, f"BENCH_r{r:02d}.json",
                  {"n": r, "rc": 0, "tail": "...", "parsed": parsed})


def test_pass_when_within_threshold(tmp_path):
    _artifact(tmp_path, 5, {"metric": "task_throughput",
                            "value": 60000.0, "unit": "tasks/s",
                            "vs_baseline": 6.0})
    new = _write(tmp_path, "new.json",
                 {"metric": "task_throughput", "value": 55000.0,
                  "unit": "tasks/s", "vs_baseline": 5.5})
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 0


def test_fails_on_throughput_regression(tmp_path):
    _artifact(tmp_path, 5, {"metric": "task_throughput",
                            "value": 60000.0, "unit": "tasks/s",
                            "vs_baseline": 6.0})
    new = _write(tmp_path, "new.json",
                 {"metric": "task_throughput", "value": 30000.0,
                  "unit": "tasks/s", "vs_baseline": 3.0})
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 1


def test_latency_metrics_regress_upward(tmp_path):
    _artifact(tmp_path, 4, {"metric": "task_rtt", "value": 600.0,
                            "unit": "us/hop", "vs_baseline": 1.7})
    # latency DROPPED 20%: an improvement, must pass
    new = _write(tmp_path, "new.json",
                 {"metric": "task_rtt", "value": 480.0,
                  "unit": "us/hop", "vs_baseline": 2.1})
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 0
    # latency ROSE 50%: regression
    worse = _write(tmp_path, "worse.json",
                   {"metric": "task_rtt", "value": 900.0,
                    "unit": "us/hop", "vs_baseline": 1.1})
    assert bench_guard.main([worse, "--repo", str(tmp_path)]) == 1


def test_cross_mode_compares_shared_keys_only(tmp_path):
    """A tasks-probe run against a gemm-mode artifact shares no keys:
    nothing to fail on (new metrics are reported, not punished)."""
    _artifact(tmp_path, 3, {"metric": "tiled_gemm_gflops",
                            "value": 155191.0, "unit": "GFLOP/s",
                            "vs_baseline": 1.43})
    new = _write(tmp_path, "new.json",
                 {"metric": "task_throughput", "value": 10.0,
                  "unit": "tasks/s", "vs_baseline": 0.001})
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 0


def test_picks_highest_round_artifact(tmp_path):
    _artifact(tmp_path, 2, {"metric": "task_throughput", "value": 1.0,
                            "unit": "tasks/s", "vs_baseline": 1.0})
    _artifact(tmp_path, 10, {"metric": "task_throughput",
                             "value": 60000.0, "unit": "tasks/s",
                             "vs_baseline": 6.0})
    new = _write(tmp_path, "new.json",
                 {"metric": "task_throughput", "value": 30000.0,
                  "unit": "tasks/s", "vs_baseline": 3.0})
    # vs r10 (60000): -50% -> fail; would pass vs the stale r02
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 1


def test_merged_northstar_keys_compare(tmp_path):
    """The r6 default mode folds tiled_potrf_mp_gflops into the gemm
    line; the guard compares the north-star key across rounds."""
    _artifact(tmp_path, 6, {"metric": "tiled_gemm_gflops",
                            "value": 155000.0, "unit": "GFLOP/s",
                            "vs_baseline": 1.43,
                            "tiled_potrf_mp_gflops": 110.0e3,
                            "potrf_vs_baseline": 1.01})
    new = _write(tmp_path, "new.json",
                 {"metric": "tiled_gemm_gflops", "value": 154000.0,
                  "unit": "GFLOP/s", "vs_baseline": 1.42,
                  "tiled_potrf_mp_gflops": 60.0e3,
                  "potrf_vs_baseline": 0.55})
    assert bench_guard.main([new, "--repo", str(tmp_path)]) == 1
