#!/bin/sh
# Pre-merge bench smoke: run the CPU-only host-side probes and diff each
# against the last driver artifact (BENCH_r*.json) with bench_guard.
#
# These probes time the Python+TCP runtime layers (no accelerator), so
# they run anywhere in ~3 minutes and catch scheduler/transport
# regressions — including the r6 protocol-mix guards (frames_sent,
# syscalls_per_mb, and act_eager coverage under the bw/rtt "protocol"
# key; wakeups/partial_writes are recorded but not gated — they track
# OS scheduling timing, not the code under test) — before a change
# merges.  Documented in BENCH.md ("Pre-merge guard").
#
# r7 added the TRACER-OVERHEAD gate: the tasks probe runs a second
# time with the full tracing stack installed (PARSEC_BENCH_TRACE=1:
# binary task profiler + causal tracer's queue-wait spans and dep
# edges).  Since r14 the gate bounds the ABSOLUTE per-task tracing
# cost ($trace_bound_us, default 8 us/task; measured ~2.3 on the
# 1-core container, down from r7's ~5.4) instead of a ratio — see the
# usage note.  The tracing-OFF cost staying ~0 is covered by the
# default tasks probe itself: its task_throughput gates against the
# last driver artifact above.
#
# r8 adds the CHAOS smoke: a seeded subset of tools/chaos.py fault
# plans (delayed v0 DTD payload, hard rank kill, transient task faults
# with retry) asserting the no-hang invariant — every run completes
# correctly or fails with a structured error within its deadline.  The
# full catalog is `python tools/chaos.py --seeds 12`.
#
# r10 added the TELEMETRY-OVERHEAD gate: the always-on metrics
# registry plus an ARMED flight recorder and the live attribution
# engine with straggler detection (prof/liveattr.py).  Since r14 the
# gate bounds the ABSOLUTE armed-plane cost ($telemetry_bound_us,
# default 0.5 us/task — the same magnitude the old 5%-of-7us contract
# allowed, but stable under base speedups).  The measurement is
# bench.py's telemetry mode (four back-to-back off/on pairs in one
# process, gating on the MINIMUM pair reading — host-load noise
# contaminates single pairs in either direction but a real regression
# shows in all of them; the JSON records both the ratio and
# overhead_us, and bench_guard compares them by absolute delta).
#
# r16 adds the JOURNAL-OVERHEAD gate (control-plane black box,
# prof/journal.py): the tasks probe armed vs off through bench.py's
# journal mode, bounded ABSOLUTE at $journal_bound (default 0.3
# us/task).  The journal has no per-task emit sites by construction —
# this leg proves the C run_quantum fast path never crosses it.  The
# chaos smoke below additionally runs --audit-journal (per-case
# journal bundles through tools/journal_audit.py's invariant auditor).
#
# Usage:  sh tools/premerge_bench.sh [threshold] [trace_bound_us] \
#             [telemetry_bound_us] [native_margin] [journal_bound_us]
#         threshold:   relative regression that fails (default 0.15)
#         trace_bound_us: max ABSOLUTE tracing cost in us/task
#             (default 8.0).  r14 changed this gate from a ratio to an
#             absolute bound: at the 482k+/s headline (~2 us/task) the
#             old 50% ratio tripped on a tracing cost that had in fact
#             DROPPED from r7's ~5.4 to ~2.3 us/task — a faster base
#             must not turn a constant overhead into a regression.
#         telemetry_bound_us: max ABSOLUTE armed-plane cost in us/task
#             (default 0.5; same rationale — the old <=5% ratio bound
#             was 5% of a 7 us base = 0.35 us, so the absolute bound
#             preserves the old contract's magnitude while surviving
#             base speedups; bench telemetry mode reports both)
#         native_margin: min native/fallback tasks ratio (default 1.05)
#         ntasks_margin (arg 6): min native/fallback ratio on the
#             NON-trivial (data-carrying chain) probe (default 1.3) —
#             the r17 extended-chain gate; the same leg fails on ANY
#             native-path bailout (coverage, not just speed)
# r11 adds the NATIVE-vs-PYTHON pairing: the tasks probe (which runs
# with the native scheduler hot path by default) is re-run with
# PARSEC_MCA_SCHED_NATIVE=0 — the fallback line goes through
# bench_guard like every probe (a fallback regression fails), and the
# native line must (a) actually have the native path active in its
# JSON (sched_native=1 — a silently-degraded build is a no-op native
# path) and (b) beat the fallback by >= $native_margin (default 5%).
# The shm transport gets its own rtt probe through bench_guard (the
# same-host ring must keep beating the loopback-TCP artifact).
#
# r17 adds the FABRIC smoke (multi-tenant serving fabric,
# service/fabric.py): the bench fabric probe (many small jobs/s with
# p50/p99 admission->completion latency, self-auditing its journal)
# goes through bench_guard like every probe, and a carved-subset smoke
# runs 3 concurrent tenants on disjoint exclusive device subsets of an
# 8-device CPU mesh plus one temporal-sharing job, then replays the
# journal through tools/journal_audit.py's F1/F2/F3 fabric invariants
# (disjoint subsets always, one placement outcome per admission,
# preemptions resolve).
#
# r9 prepends the PARSECLINT gate: the project static analyzer
# (tools/parseclint — lock discipline, event-loop blocking calls,
# device_put aliasing, MCA knob drift, containment exception hygiene,
# -O assert hazards) must be clean against its baseline BEFORE any
# bench cycle is spent; a violation fails the premerge outright.
set -e
repo="$(cd "$(dirname "$0")/.." && pwd)"
threshold="${1:-0.15}"
trace_bound="${2:-8.0}"
telemetry_bound="${3:-0.5}"
rc=0
tasks_off=""
echo "== premerge gate: parseclint (static analysis) =="
if ! (cd "$repo" && python -m tools.parseclint parsec_tpu); then
    echo "premerge: parseclint found violations (fix, waive with a"
    echo "          'lint:' comment, or baseline in tools/parseclint/)"
    exit 1
fi
echo "== premerge gate: native build-from-source =="
# r14: every native source (core.cpp + the pinsext/schedext/commext
# CPython extensions) must compile from a clean tree into a scratch
# directory — the .so artifacts are built on demand (gitignored), so
# a source that no longer compiles is a SILENT fleet-wide degradation:
# every fresh container would fall back to the Python twins with one
# rate-limited warning nobody reads.  (No mtime drift check: the
# runtime's _stale() rebuild-on-load already guarantees the probes
# below never measure an old build of an edited source.)
scratch="$(mktemp -d)"
if ! make -s -C "$repo/parsec_tpu/native" OUT="$scratch" all; then
    echo "premerge: native build-from-source FAILED (compile error)"
    rm -rf "$scratch"
    exit 1
fi
rm -rf "$scratch"
for mode in tasks rtt bw; do
    echo "== premerge probe: $mode =="
    out="/tmp/premerge_${mode}_$$.json"
    if ! JAX_PLATFORMS=cpu PARSEC_BENCH_APP=$mode \
         python "$repo/bench.py" > "$out" 2>/dev/null; then
        echo "premerge: $mode probe FAILED to run"
        rc=1
        continue
    fi
    if ! python "$repo/tools/bench_guard.py" "$out" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
    if [ "$mode" = tasks ]; then
        tasks_off="$out"     # kept for the tracer-overhead comparison
    else
        rm -f "$out"
    fi
done
echo "== premerge probe: tracer overhead (tasks, tracing on) =="
on="/tmp/premerge_tasks_on_$$.json"
if [ -n "$tasks_off" ] && JAX_PLATFORMS=cpu PARSEC_BENCH_APP=tasks \
     PARSEC_BENCH_TRACE=1 python "$repo/bench.py" > "$on" 2>/dev/null; then
    if ! python - "$tasks_off" "$on" "$trace_bound" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
off = last_json(sys.argv[1])["value"]
on = last_json(sys.argv[2])["value"]
bound = float(sys.argv[3])
cost_us = (1e6 / on - 1e6 / off) if on and off else float("inf")
print(f"premerge: tracer cost {cost_us:+.2f} us/task "
      f"(bound {bound} us; off {off:.0f} -> on {on:.0f} tasks/s)")
sys.exit(1 if cost_us > bound else 0)
EOF
    then
        rc=1
    fi
else
    echo "premerge: traced tasks probe FAILED to run"
    rc=1
fi
echo "== premerge probe: native-vs-python A/B (tasks) =="
native_margin="${4:-1.05}"
fb="/tmp/premerge_tasks_fb_$$.json"
if [ -n "$tasks_off" ] && JAX_PLATFORMS=cpu PARSEC_BENCH_APP=tasks \
     PARSEC_MCA_SCHED_NATIVE=0 python "$repo/bench.py" > "$fb" 2>/dev/null; then
    # the FALLBACK path regressing is as pre-merge-fatal as the native
    # one: every probe artifact before r11 was measured on it
    if ! python "$repo/tools/bench_guard.py" "$fb" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
    if ! python - "$tasks_off" "$fb" "$native_margin" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
nat, fb = last_json(sys.argv[1]), last_json(sys.argv[2])
margin = float(sys.argv[3])
active = (nat.get("native") or {}).get("sched_native")
ratio = nat["value"] / fb["value"] if fb["value"] else float("inf")
print(f"premerge: sched native A/B {fb['value']:.0f} -> "
      f"{nat['value']:.0f} tasks/s (x{ratio:.2f}, need >= x{margin}; "
      f"native active: {active})")
if active != 1:
    print("premerge: NATIVE PATH INACTIVE in the default tasks probe "
          "(build degraded?) — a no-op native path fails pre-merge")
    sys.exit(1)
sys.exit(0 if ratio >= margin else 1)
EOF
    then
        rc=1
    fi
else
    echo "premerge: fallback tasks probe FAILED to run"
    rc=1
fi
rm -f "$fb"
rm -f "$tasks_off" "$on"
echo "== premerge probe: native-vs-python A/B (ntasks, data-carrying chains) =="
# r17: the EXTENDED C progress chain (per-class binding tables +
# C-side local delivery walk) gets its own paired A/B on the
# non-trivial probe — native must beat the fallback by
# >= $ntasks_margin (default 1.3) AND report ZERO bailouts (any
# non-empty reason means data tasks silently popped back to Python
# and the number no longer measures the chain).
ntasks_margin="${6:-1.3}"
nt_nat="/tmp/premerge_ntasks_$$.json"
nt_fb="/tmp/premerge_ntasks_fb_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=ntasks \
     python "$repo/bench.py" > "$nt_nat" 2>/dev/null \
   && JAX_PLATFORMS=cpu PARSEC_BENCH_APP=ntasks \
     PARSEC_MCA_SCHED_NATIVE=0 python "$repo/bench.py" > "$nt_fb" \
     2>/dev/null; then
    if ! python "$repo/tools/bench_guard.py" "$nt_nat" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
    if ! python - "$nt_nat" "$nt_fb" "$ntasks_margin" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
nat, fb = last_json(sys.argv[1]), last_json(sys.argv[2])
margin = float(sys.argv[3])
active = (nat.get("native") or {}).get("sched_native")
bail = nat.get("bailouts") or {}
ratio = nat["value"] / fb["value"] if fb["value"] else float("inf")
print(f"premerge: non-trivial chain A/B {fb['value']:.0f} -> "
      f"{nat['value']:.0f} tasks/s (x{ratio:.2f}, need >= x{margin}; "
      f"native active: {active}; bailouts: {bail or 'none'})")
if active != 1:
    print("premerge: NATIVE PATH INACTIVE in the ntasks probe "
          "(build degraded?) — a no-op extended chain fails pre-merge")
    sys.exit(1)
if bail:
    print("premerge: UNEXPECTED BAILOUTS on the native ntasks probe — "
          "data tasks fell back to Python; the extended chain lost "
          "coverage")
    sys.exit(1)
sys.exit(0 if ratio >= margin else 1)
EOF
    then
        rc=1
    fi
else
    echo "premerge: ntasks probe FAILED to run"
    rc=1
fi
rm -f "$nt_nat" "$nt_fb"
echo "== premerge probe: aggregate multi-rank throughput (shm) =="
# r17: N same-host ranks over shm, each with a live RemoteDepEngine —
# comm-attached fast-complete must keep every (purely local) task on
# the C chain: zero comm_buffered bailouts, on top of the bench_guard
# diff of the aggregate headline.  Self-scales N to the core count
# (N=2 smoke on a 1-core host, with the skip reason in the JSON).
agg="/tmp/premerge_aggregate_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=aggregate \
     python "$repo/bench.py" > "$agg" 2>/dev/null; then
    if ! python "$repo/tools/bench_guard.py" "$agg" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
    if ! python - "$agg" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
obj = last_json(sys.argv[1])
bail = obj.get("bailouts") or {}
skip = obj.get("skipped") or {}
print(f"premerge: aggregate {obj['value']:.0f} tasks/s over "
      f"{obj.get('ranks')} ranks (eff {obj.get('scaling_efficiency')}; "
      f"bailouts: {bail or 'none'}"
      + (f"; skipped: {skip}" if skip else "") + ")")
if bail.get("comm_buffered"):
    print("premerge: comm_buffered bailouts in the aggregate probe — "
          "comm-attached fast-complete regressed (local tasks left "
          "the C chain because a comm engine was attached)")
    sys.exit(1)
sys.exit(0)
EOF
    then
        rc=1
    fi
else
    echo "premerge: aggregate probe FAILED to run"
    rc=1
fi
rm -f "$agg"
echo "== premerge probe: shm transport rtt =="
shmout="/tmp/premerge_shm_rtt_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=rtt PARSEC_MCA_COMM_TRANSPORT=shm \
     python "$repo/bench.py" > "$shmout" 2>/dev/null; then
    if ! python "$repo/tools/bench_guard.py" "$shmout" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
else
    echo "premerge: shm rtt probe FAILED to run"
    rc=1
fi
rm -f "$shmout"
echo "== premerge probe: telemetry overhead (metrics + flight recorder + liveattr armed) =="
tel="/tmp/premerge_telemetry_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=telemetry \
     python "$repo/bench.py" > "$tel" 2>/dev/null; then
    if ! python - "$tel" "$telemetry_bound" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
obj = last_json(sys.argv[1])
cost_us = obj.get("overhead_us")
bound = float(sys.argv[2])
if cost_us is None:   # pre-r14 bench build: fall back to the ratio
    cost_us = obj["value"] * 7.0   # vs the old 7 us/task base
print(f"premerge: telemetry cost {cost_us:.3f} us/task "
      f"(bound {bound} us; ratio {obj['value']:+.1%}; off "
      f"{obj.get('tasks_off')} -> armed {obj.get('tasks_on')} tasks/s)")
sys.exit(1 if cost_us > bound else 0)
EOF
    then
        rc=1
    fi
else
    echo "premerge: telemetry probe FAILED to run"
    rc=1
fi
rm -f "$tel"
echo "== premerge probe: journal overhead (control-plane black box armed) =="
# r16: the control-plane journal is always-on; its emit sites are
# control-plane only (recovery rounds, retirement handshakes,
# barriers, job lifecycle — NO per-task emits), so the tasks probe
# armed-vs-off must read ~0 us/task.  The absolute bound proves the C
# run_quantum fast path never crosses the journal.
journal_bound="${5:-0.3}"
jnl="/tmp/premerge_journal_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=journal \
     python "$repo/bench.py" > "$jnl" 2>/dev/null; then
    if ! python - "$jnl" "$journal_bound" <<'EOF'
import json, sys
def last_json(path):
    for line in reversed(open(path).read().splitlines()):
        line = line.strip()
        if line.startswith("{"):
            return json.loads(line)
    raise SystemExit(f"premerge: no JSON in {path}")
obj = last_json(sys.argv[1])
cost_us = obj.get("overhead_us")
bound = float(sys.argv[2])
if cost_us is None:
    print("premerge: journal probe JSON carries no overhead_us "
          "(every pair skipped?)")
    sys.exit(1)
print(f"premerge: journal cost {cost_us:.3f} us/task "
      f"(bound {bound} us; ratio {obj['value']:+.1%}; off "
      f"{obj.get('tasks_off')} -> armed {obj.get('tasks_on')} tasks/s)")
sys.exit(1 if cost_us > bound else 0)
EOF
    then
        rc=1
    fi
else
    echo "premerge: journal probe FAILED to run"
    rc=1
fi
rm -f "$jnl"
echo "== premerge probe: fabric serving (jobs/s + latency, self-audited) =="
fab="/tmp/premerge_fabric_$$.json"
if JAX_PLATFORMS=cpu PARSEC_BENCH_APP=fabric \
     python "$repo/bench.py" > "$fab" 2>/dev/null; then
    if ! python "$repo/tools/bench_guard.py" "$fab" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
else
    echo "premerge: fabric probe FAILED to run"
    rc=1
fi
rm -f "$fab"
echo "== premerge probe: fabric carved-subset smoke (3 tenants, audited) =="
# three concurrent tenants on disjoint exclusive 2-device subsets of an
# 8-device CPU mesh plus one temporal-sharing job; every placement is
# journaled and the bundle must pass journal_audit's F1/F2/F3 fabric
# invariants.  Concurrency is asserted from the journal itself: the
# third exclusive placement lands before any of the three releases.
if ! JAX_PLATFORMS=cpu \
     XLA_FLAGS=--xla_force_host_platform_device_count=8 \
     REPO="$repo" python - <<'EOF'
import os, sys, time
repo = os.environ["REPO"]
sys.path.insert(0, repo)
sys.path.insert(0, os.path.join(repo, "tools"))
from parsec_tpu.data.matrix import TwoDimBlockCyclic
from parsec_tpu.dsl.ptg.api import DATA, IN, OUT, PTG, Range, TASK
from parsec_tpu.service.fabric import ServingFabric
import journal_audit

NT = 12

def chain_factory(i):
    def factory():
        A = TwoDimBlockCyclic(mb=4, nb=4, lm=4, ln=4)
        A.data_of(0, 0).copy_on(0).payload[:] = 0.0
        p = PTG(f"smoke{i}", NT=NT)
        p.task("S", k=Range(0, NT - 1)) \
            .affinity(lambda k, A=A: A(0, 0)) \
            .flow("T", "RW",
                  IN(DATA(lambda A=A: A(0, 0)), when=lambda k: k == 0),
                  IN(TASK("S", "T", lambda k: dict(k=k - 1)),
                     when=lambda k: k > 0),
                  OUT(TASK("S", "T", lambda k: dict(k=k + 1)),
                      when=lambda k: k < NT - 1),
                  OUT(DATA(lambda A=A: A(0, 0)),
                      when=lambda k: k == NT - 1)) \
            .body(lambda T: (time.sleep(0.02), T + 1.0)[1])
        return p.build()
    return factory

with ServingFabric(nb_cores=4, max_active=8) as svc:
    mesh = len(svc.context.accelerator_spaces())
    if mesh < 7:
        raise SystemExit(
            f"premerge: fabric smoke wants an 8-device mesh, got {mesh}")
    excl = [svc.submit(chain_factory(i), devices=2, name=f"excl{i}")
            for i in range(3)]
    shared = svc.submit(chain_factory(9), name="shared")
    for j in excl + [shared]:
        if not j.wait(timeout=120.0):
            raise SystemExit(f"premerge: fabric smoke job {j} hung")
    bundle = {0: [svc.context.journal.snapshot()]}

evs = bundle[0][0]["events"]
excl_ids = {j.job_id for j in excl}
placed = {}          # job -> index of its first exclusive placement
released = []        # indices of releases of the three tenants
for idx, ev in enumerate(evs):
    if (ev.get("e") == "fabric_place" and not ev.get("shared")
            and ev.get("job") in excl_ids):
        placed.setdefault(ev["job"], idx)
        if len(ev.get("devices") or ()) != 2:
            raise SystemExit(f"premerge: tenant {ev['job']} placed on "
                             f"{ev.get('devices')} (wanted 2 devices)")
    elif ev.get("e") == "fabric_release" and ev.get("job") in excl_ids:
        released.append(idx)
if len(placed) != 3:
    raise SystemExit(f"premerge: {len(placed)}/3 tenants placed "
                     "exclusively")
if not any(ev.get("e") == "fabric_place" and ev.get("shared")
           and ev.get("job") == shared.job_id for ev in evs):
    raise SystemExit("premerge: temporal-sharing job never placed")
if released and max(placed.values()) > min(released):
    raise SystemExit("premerge: tenants never held their subsets "
                     "concurrently (3rd placement after 1st release)")
violations = journal_audit.audit(bundle)
if violations:
    raise SystemExit("premerge: fabric journal audit FAILED: "
                     + "; ".join(violations[:3]))
print(f"premerge: fabric smoke 3 exclusive tenants + 1 shared on "
      f"{mesh}-device mesh, concurrent placements, audit clean")
EOF
then
    echo "premerge: fabric carved-subset smoke FAILED"
    rc=1
fi
echo "== premerge probe: chaos (seeded fault plans, no-hang invariant) =="
# 8 seeds = one pass over the quick catalog, which now includes the
# shm-transport kill, the recv-reorder legs, AND the r12 recovery
# cases (kill-close-recover / kill-dtd-recover: kill_rank plans that
# must end in COMPLETED jobs with validated numbers on the survivor).
# r16 arms --audit-journal: every smoke case runs with the
# control-plane journal on and tools/journal_audit.py's invariant
# auditor over the per-case bundle afterwards — a protocol-invariant
# violation fails premerge even when the workload outcome matched.
if ! JAX_PLATFORMS=cpu python "$repo/tools/chaos.py" --seeds 8 --quick \
     --audit-journal; then
    rc=1
fi
echo "== premerge probe: recovery minimal-vs-full replay A/B =="
# r13: the recorded-lineage minimal replay must re-execute STRICTLY
# FEWER tasks than replay-from-restore-point on the acceptance kill,
# with each leg provably taking its intended path (a silent fallback
# to full replay fails the gate).  r15 adds a SECOND A/B line to the
# same gate: the 3-rank DTD chain down the cross-rank skip-agreement
# path (insert-stream prefix agreed over the wire between two
# survivors) vs the forced full insert-stream replay.
if ! JAX_PLATFORMS=cpu python "$repo/tools/chaos.py" --ab-minimal; then
    rc=1
fi
echo "== premerge probe: chaos soak (random recover schedules) =="
# r15: N=4 randomly seeded schedules drawn from the recover catalog,
# each with the full per-run invariant checks (validated numerics,
# no hang, recovery observed); the master seed is printed so any
# failure replays exactly (PARSEC_CHAOS_SOAK_SEED=<seed> --soak 4)
if ! JAX_PLATFORMS=cpu python "$repo/tools/chaos.py" --soak 4; then
    rc=1
fi
echo "== premerge probe: chaos degrade (drain-before-death, audited) =="
# r19: a seeded ramped degradation of rank 1 (frame delay incl.
# heartbeats + task-body jitter, tools/chaos.py --degrade) on a
# 2-rank gang.  The health plane (prof/health.py) must score the
# rank down from its heartbeat gap/jitter inflation, the serving
# fabric must journal an evidence-carrying pre-emptive drain and
# stop placing on the rank STRICTLY BEFORE the heartbeat detector
# declares it dead (comm_peer_timeout_s is never approached), and
# the journal must pass the auditor clean — including the r19 H1
# health invariant (drains evidence-backed, drained ranks never
# placement-targeted).
if ! JAX_PLATFORMS=cpu python "$repo/tools/chaos.py" --degrade; then
    rc=1
fi
exit $rc
