#!/bin/sh
# Pre-merge bench smoke: run the CPU-only host-side probes and diff each
# against the last driver artifact (BENCH_r*.json) with bench_guard.
#
# These probes time the Python+TCP runtime layers (no accelerator), so
# they run anywhere in ~3 minutes and catch scheduler/transport
# regressions — including the r6 protocol-mix guards (frames_sent,
# syscalls_per_mb, and act_eager coverage under the bw/rtt "protocol"
# key; wakeups/partial_writes are recorded but not gated — they track
# OS scheduling timing, not the code under test) — before a change
# merges.  Documented in BENCH.md ("Pre-merge guard").
#
# Usage:  sh tools/premerge_bench.sh [threshold]
#         threshold: relative regression that fails (default 0.15)
set -e
repo="$(cd "$(dirname "$0")/.." && pwd)"
threshold="${1:-0.15}"
rc=0
for mode in tasks rtt bw; do
    echo "== premerge probe: $mode =="
    out="/tmp/premerge_${mode}_$$.json"
    if ! JAX_PLATFORMS=cpu PARSEC_BENCH_APP=$mode \
         python "$repo/bench.py" > "$out" 2>/dev/null; then
        echo "premerge: $mode probe FAILED to run"
        rc=1
        continue
    fi
    if ! python "$repo/tools/bench_guard.py" "$out" --repo "$repo" \
         --threshold "$threshold"; then
        rc=1
    fi
    rm -f "$out"
done
exit $rc
