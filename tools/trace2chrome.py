#!/usr/bin/env python
"""Convert a binary trace (.ptt) to Chrome trace-event JSON.

The interoperable-trace-format role of the reference's OTF2 backend
(reference: parsec/profiling_otf2.c), targeted at the tooling that is
native on TPU stacks: chrome://tracing and Perfetto open the output
directly.  Usage:

    python tools/trace2chrome.py run.ptt -o run.json
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help=".ptt trace file")
    ap.add_argument("-o", "--out", default=None,
                    help="output JSON (default: <trace>.json)")
    args = ap.parse_args(argv)
    out = args.out or (os.path.splitext(args.trace)[0] + ".json")

    from parsec_tpu.prof.reader import intervals, read_trace
    meta, df = read_trace(args.trace)
    iv = intervals(df) if len(df) else df

    events = []
    if len(iv):
        t0 = float(iv["ts_begin"].min())
        for row in iv.itertuples():
            events.append({
                "name": row.name,
                "cat": "task",
                "ph": "X",                      # complete event
                "ts": (float(row.ts_begin) - t0) * 1e6,
                "dur": float(row.duration) * 1e6,
                "pid": int(row.taskpool_id),
                "tid": int(row.stream),
                "args": {"event_id": int(row.event_id),
                         "info": repr(row.info) if row.info is not None
                         else ""},
            })
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"hr_id": meta["hr_id"], **meta.get("info", {})},
    }
    with open(out, "w") as f:
        json.dump(doc, f)
    print(f"{out}: {len(events)} events")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
