#!/usr/bin/env python
"""Convert binary traces (.ptt) to Chrome trace-event JSON.

The interoperable-trace-format role of the reference's OTF2 backend
(reference: parsec/profiling_otf2.c), targeted at the tooling that is
native on TPU stacks: chrome://tracing and Perfetto open the output
directly.  Usage:

    python tools/trace2chrome.py run.ptt -o run.json
    python tools/trace2chrome.py --merge rank0.ptt rank1.ptt -o run.json

``--merge`` takes one trace per rank, aligns their clocks with the
TAG_CLOCK offsets recorded in each header, and emits ONE timeline
(pid = rank, tid = stream) with Perfetto flow arrows linking every
matched cross-rank activation's send event to its recv event, plus the
critical-path attribution summary in ``otherData``.
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _interval_events(iv, t0, pid_of):
    events = []
    for row in iv.itertuples():
        events.append({
            "name": row.name,
            "cat": "task",
            "ph": "X",                      # complete event
            "ts": (float(row.ts_begin) - t0) * 1e6,
            "dur": float(row.duration) * 1e6,
            "pid": pid_of(row),
            "tid": int(row.stream),
            "args": {"event_id": int(row.event_id),
                     "info": repr(row.info) if row.info is not None
                     else ""},
        })
    return events


def _flow_events(df, t0):
    """Matched comm_send/comm_recv pairs -> anchor slices + s/f flow
    arrows (Perfetto binds an arrow to the slice enclosing each end)."""
    sends, recvs = {}, {}
    for row in df[df["name"] == "comm_send"].itertuples():
        if row.info and row.info.get("corr") is not None:
            sends[tuple(row.info["corr"])] = row
    for row in df[df["name"] == "comm_recv"].itertuples():
        if row.info and row.info.get("corr") is not None:
            recvs[tuple(row.info["corr"])] = row
    events = []
    arrows = 0
    for corr in sorted(set(sends) & set(recvs)):
        s, r = sends[corr], recvs[corr]
        fid = f"{corr[0]}:{corr[1]}"
        s_ts = (float(s.ts) - t0) * 1e6
        r_ts = (float(r.ts) - t0) * 1e6
        for row, ts, nm in ((s, s_ts, "comm_send"), (r, r_ts, "comm_recv")):
            events.append({
                "name": nm, "cat": "comm", "ph": "X",
                "ts": ts, "dur": 1,
                "pid": int(row.rank), "tid": int(row.stream),
                "args": {"corr": fid,
                         "tag": (row.info or {}).get("tag"),
                         "nbytes": (row.info or {}).get("nbytes")},
            })
        events.append({"name": "activation", "cat": "comm", "ph": "s",
                       "id": fid, "pid": int(s.rank),
                       "tid": int(s.stream), "ts": s_ts})
        events.append({"name": "activation", "cat": "comm", "ph": "f",
                       "bp": "e", "id": fid, "pid": int(r.rank),
                       "tid": int(r.stream), "ts": max(r_ts, s_ts + 1)})
        arrows += 1
    return events, arrows


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("traces", nargs="+", help=".ptt trace file(s)")
    ap.add_argument("--merge", action="store_true",
                    help="merge per-rank traces into one clock-aligned "
                         "timeline with cross-rank flow arrows")
    ap.add_argument("-o", "--out", default=None,
                    help="output JSON (default: <trace>.json)")
    args = ap.parse_args(argv)
    if len(args.traces) > 1 and not args.merge:
        ap.error("several traces need --merge")
    out = args.out or (os.path.splitext(args.traces[0])[0] + ".json")

    from parsec_tpu.prof.reader import intervals, read_trace

    if args.merge:
        from parsec_tpu.prof import critpath
        df, metas = critpath.merge_traces(args.traces)
        iv = intervals(df) if len(df) else df
        t0 = float(df["ts"].min()) if len(df) else 0.0
        events = _interval_events(iv, t0, lambda r: int(r.rank)) \
            if len(iv) else []
        flow, arrows = _flow_events(df, t0)
        events.extend(flow)
        other = {"ranks": sorted(metas), "flow_arrows": arrows}
        try:
            tasks, preds, ready = critpath.build_dag(df)
            path = critpath.critical_path(tasks, preds)
            other["attribution"] = critpath.attribute(path, tasks, ready)
        except Exception as exc:     # the timeline must still export
            other["attribution_error"] = str(exc)[:200]
        doc = {"traceEvents": events, "displayTimeUnit": "ms",
               "otherData": other}
        with open(out, "w") as f:
            json.dump(doc, f)
        print(f"{out}: {len(events)} events, {arrows} flow arrows")
        return 0

    meta, df = read_trace(args.traces[0])
    iv = intervals(df) if len(df) else df
    events = []
    if len(iv):
        t0 = float(iv["ts_begin"].min())
        events = _interval_events(iv, t0,
                                  lambda r: int(r.taskpool_id))
    doc = {
        "traceEvents": events,
        "displayTimeUnit": "ms",
        "otherData": {"hr_id": meta["hr_id"], **meta.get("info", {})},
    }
    with open(out, "w") as f:
        json.dump(doc, f)
    print(f"{out}: {len(events)} events")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
