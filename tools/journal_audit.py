#!/usr/bin/env python
"""journal_audit — merge, render, and AUDIT control-plane journals.

The offline half of the control-plane black box (prof/journal.py):
every rank's protocol journal — recovery rounds, termdet rewinds,
retirement handshakes, rejoin fencing, barrier generations, job
lifecycle — lands in ``journal-rank<N>.jsonl`` files (``--mca
journal_dir``, a flight-recorder incident bundle, or the job port's
``{"op": "journal"}`` pull saved to disk).  This tool:

* ``--timeline``   merges the per-rank journals onto rank 0's clock
                   (the recorded TAG_CLOCK offsets, the same alignment
                   prof/critpath.merge_traces uses) and prints ONE
                   human-readable protocol timeline;
* ``--chrome F``   emits the merged events as Perfetto/chrome instant
                   events (pid = rank) — open next to a
                   ``trace2chrome.py --merge`` view of the same bundle
                   and the control plane lines up under the data plane;
* ``--audit``      runs the offline INVARIANT AUDITOR; violations
                   print one per line and exit nonzero.

Audited invariants (the protocol contracts PRs 9/11/14 argue in prose,
now assertable from evidence):

  I1  mode votes within one (pool, round_id) agree on the round's
      MEMBERSHIP — every voter declared the same live gang;
  I2  an agreed DTD skip prefix is <= EVERY rank's offered cut in its
      round, and no round with a ``full`` offer agreed a nonzero cut;
  I3  incarnation epochs are MONOTONE per rank (journal-file order)
      and pool run_epoch fences are strictly increasing per
      (rank, pool);
  I4  exactly ONE retirement outcome per (rank, pool): never a
      duplicate, never both ``retired`` and ``retire_degraded``;
  I5  every need-negotiation request is ANSWERED or explicitly
      degraded: per (rank, pool) the need_req count equals the
      need_ack count, and every requester round carries a terminal
      outcome (acked / nacked / widened / exhausted);
  F1  serving-fabric mesh carving (service/fabric.py): replaying
      fabric_place / fabric_resize / fabric_release chronologically,
      the EXCLUSIVE device subsets of distinct jobs are disjoint at
      every instant;
  F2  exactly one placement outcome per admitted job per admission
      epoch: per job, count(fabric_place) - count(fabric_resume) is
      0 or 1, and a REJECTED job records no placement at all;
  F3  every preemption resolves: a fabric_preempt is followed by a
      fabric_resume or a terminal job_done for that job;
  H1  health decisions are evidence-backed (prof/health.py): every
      pre-emptive drain is PRECEDED by recorded below-threshold
      evidence for that rank (a health_transition out of "ok") and
      carries a score strictly below its own threshold — and no rank
      is both drained and placement-targeted while the drain is in
      force (replaying health_drain / health_undrain / fabric_place
      chronologically per fabric).

Usage:
    python tools/journal_audit.py <bundle-dir-or-files> --timeline
    python tools/journal_audit.py <bundle> --audit
    python tools/journal_audit.py <bundle> --chrome ctl.json
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys
from collections import defaultdict
from typing import Dict, List, Optional, Tuple

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

_RANK_RE = re.compile(r"journal-rank(\d+)\.jsonl$")


# ---------------------------------------------------------------------------
# loading
# ---------------------------------------------------------------------------

def load_file(path: str) -> List[dict]:
    """One journal file -> list of SNAPSHOTS (a file holds one header
    + events per dump; a restarted incarnation APPENDS another pair,
    and the auditor checks epoch monotonicity across that boundary)."""
    snaps: List[dict] = []
    cur: Optional[dict] = None
    with open(path) as fh:
        for line in fh:
            line = line.strip()
            if not line:
                continue
            rec = json.loads(line)
            if "h" in rec:
                cur = dict(rec["h"])
                cur["events"] = []
                snaps.append(cur)
            elif cur is not None:
                cur["events"].append(rec)
    return snaps


def load_bundle(paths: List[str]) -> Dict[int, List[dict]]:
    """Bundle dirs and/or journal files -> rank -> snapshot list (in
    dump order)."""
    files: List[str] = []
    for p in paths:
        if os.path.isdir(p):
            files.extend(sorted(glob.glob(
                os.path.join(p, "journal-rank*.jsonl"))))
        else:
            files.append(p)
    if not files:
        raise FileNotFoundError(
            f"no journal-rank*.jsonl under {paths!r}")
    per_rank: Dict[int, List[dict]] = defaultdict(list)
    for f in files:
        m = _RANK_RE.search(os.path.basename(f))
        snaps = load_file(f)
        for snap in snaps:
            rank = int(snap.get("rank",
                                m.group(1) if m else len(per_rank)))
            per_rank[rank].append(snap)
    return dict(per_rank)


def _merge_rank_snaps(snaps: List[dict]) -> dict:
    """Concatenate one rank's dumps (events stay in file order; the
    LAST snapshot's clock table wins — it has the freshest offsets)."""
    if not snaps:
        return {}
    out = dict(snaps[-1])
    events: List[dict] = []
    for s in snaps:
        events.extend(s.get("events", ()))
    out["events"] = events
    return out


def merged_events(per_rank: Dict[int, List[dict]]) -> List[dict]:
    """All ranks' events on the reference clock, time-ordered."""
    from parsec_tpu.prof.journal import merge_journals
    return merge_journals({r: _merge_rank_snaps(s)
                           for r, s in per_rank.items()})


# ---------------------------------------------------------------------------
# the invariant auditor
# ---------------------------------------------------------------------------

def audit(per_rank: Dict[int, List[dict]]) -> List[str]:
    """Run every invariant; returns violation strings (empty = clean).

    Keying note: per-rank invariants (I3/I4/I5) include the
    incarnation stamp so a restarted rank's RECYCLED pool ids (the id
    is a per-process counter) never alias its predecessor's events.
    The cross-rank round invariants (I1/I2) group by (pool, round)
    only — a round spans ranks whose incarnation stamps legitimately
    differ (a rejoined voter), so incarnation cannot join the key;
    the residual aliasing there needs a recycled pool id to reach the
    SAME restart-attempt round number again within one bundle."""
    violations: List[str] = []
    events = merged_events(per_rank)

    # I1: mode votes within one (pool, round) agree on membership
    members: Dict[Tuple, List[Tuple[int, tuple]]] = defaultdict(list)
    for ev in events:
        if ev.get("e") == "mode_decl":
            members[(ev.get("pool"), ev.get("round"))].append(
                (ev["rank"], tuple(sorted(ev.get("peers") or ()))))
    for (pool, rnd), decls in members.items():
        views = {v for _r, v in decls}
        if len(views) > 1:
            violations.append(
                f"I1 pool={pool} round={rnd}: mode votes disagree on "
                f"membership: "
                + "; ".join(f"rank {r} saw {list(v)}"
                            for r, v in sorted(set(decls))))

    # I2: agreed skip prefix <= every offered cut in its round
    offers: Dict[Tuple, List[Tuple[int, int, Optional[str]]]] = \
        defaultdict(list)
    cuts: Dict[Tuple, int] = {}
    for ev in events:
        key = (ev.get("pool"), ev.get("round"))
        if ev.get("e") == "skip_offer":
            offerer = ev.get("src", ev["rank"])
            offers[key].append((int(offerer),
                                int(ev.get("frontier", -1)),
                                ev.get("full")))
        elif ev.get("e") == "skip_cut":
            cuts[key] = max(cuts.get(key, 0), int(ev.get("prefix", 0)))
    for key, prefix in cuts.items():
        if prefix <= 0:
            continue
        # dedup: a rank's own offer and the coordinator's receive-side
        # record of it are the same ballot
        seen: Dict[int, Tuple[int, Optional[str]]] = {}
        for offerer, frontier, full in offers.get(key, ()):
            seen.setdefault(offerer, (frontier, full))
        for offerer, (frontier, full) in sorted(seen.items()):
            if full is not None:
                violations.append(
                    f"I2 pool={key[0]} round={key[1]}: prefix {prefix} "
                    f"agreed although rank {offerer} voted full "
                    f"({full})")
            elif frontier >= 0 and prefix > frontier:
                violations.append(
                    f"I2 pool={key[0]} round={key[1]}: agreed prefix "
                    f"{prefix} exceeds rank {offerer}'s offered cut "
                    f"{frontier}")

    # I3: incarnations monotone per rank; run_epoch fences strictly
    # increasing per (rank, incarnation, pool).  Pool ids are a
    # per-PROCESS counter, so a restarted incarnation legitimately
    # reuses its predecessor's ids — the incarnation stamp (monotone
    # within one rank's stream, checked first) disambiguates them.
    for rank, snaps in sorted(per_rank.items()):
        last_inc = None
        fences: Dict[Tuple, int] = {}
        for snap in snaps:
            for ev in snap.get("events", ()):
                inc = int(ev.get("inc", 0))
                if last_inc is not None and inc < last_inc:
                    violations.append(
                        f"I3 rank {rank}: incarnation regressed "
                        f"{last_inc} -> {inc} at seq {ev.get('seq')}")
                last_inc = inc
                if ev.get("e") == "epoch_fence":
                    pool, epoch = ev.get("pool"), int(ev.get("epoch", 0))
                    prev = fences.get((inc, pool))
                    if prev is not None and epoch <= prev:
                        violations.append(
                            f"I3 rank {rank} pool={pool}: run_epoch "
                            f"fence not monotone ({prev} -> {epoch})")
                    fences[(inc, pool)] = epoch

    # I4: exactly one retirement outcome per (rank, incarnation, pool)
    # — the incarnation key keeps a restarted rank's recycled pool id
    # from aliasing its predecessor's outcome
    outcomes: Dict[Tuple, List[str]] = defaultdict(list)
    for ev in events:
        if ev.get("e") in ("retired", "retire_degraded"):
            outcomes[(ev["rank"], ev.get("inc", 0),
                      ev.get("pool"))].append(ev["e"])
    for (rank, _inc, pool), outs in sorted(outcomes.items()):
        if len(outs) > 1:
            violations.append(
                f"I4 rank {rank} pool={pool}: {len(outs)} retirement "
                f"outcomes ({outs}) — expected exactly one")

    # I5: negotiation rounds answered or explicitly degraded (keyed
    # per incarnation for the same pool-id-recycling reason)
    reqs: Dict[Tuple, int] = defaultdict(int)
    acks: Dict[Tuple, int] = defaultdict(int)
    terminal = {"acked", "nacked", "widened", "exhausted"}
    for ev in events:
        key = (ev["rank"], ev.get("inc", 0), ev.get("pool"))
        if ev.get("e") == "need_req":
            reqs[key] += 1
        elif ev.get("e") == "need_ack":
            acks[key] += 1
        elif ev.get("e") == "need_round" \
                and ev.get("outcome") not in terminal:
            violations.append(
                f"I5 rank {ev['rank']} pool={ev.get('pool')}: "
                f"negotiation round {ev.get('round')} has non-terminal "
                f"outcome {ev.get('outcome')!r}")
    for key in sorted(set(reqs) | set(acks)):
        if reqs[key] != acks[key]:
            violations.append(
                f"I5 rank {key[0]} pool={key[2]}: {reqs[key]} "
                f"need_req(s) but {acks[key]} need_ack(s) — an "
                "unanswered negotiation")
    # requester side: a need_send with no terminal need_round in the
    # same (rank, inc, pool, round) is a round that went silent
    sends = {(ev["rank"], ev.get("inc", 0), ev.get("pool"),
              ev.get("round"))
             for ev in events if ev.get("e") == "need_send"}
    rounds = {(ev["rank"], ev.get("inc", 0), ev.get("pool"),
               ev.get("round"))
              for ev in events if ev.get("e") == "need_round"}
    for rank, _inc, pool, rnd in sorted(sends - rounds):
        violations.append(
            f"I5 rank {rank} pool={pool}: need round {rnd} was sent "
            "but records no terminal outcome")

    # F1: exclusive subsets disjoint at every instant — replay the
    # placement stream chronologically, tracking job -> device set per
    # (rank, incarnation) fabric (the fabric is rank-local; a resize
    # event carries the subset AFTER the change)
    holdings: Dict[Tuple, Dict[Tuple, set]] = defaultdict(dict)
    for ev in events:
        e = ev.get("e")
        if e not in ("fabric_place", "fabric_resize", "fabric_release"):
            continue
        fab = (ev["rank"], ev.get("inc", 0))
        jkey = (fab, ev.get("job"))
        if e == "fabric_release":
            holdings[fab].pop(jkey, None)
            continue
        if e == "fabric_place" and (ev.get("shared")
                                    or not ev.get("devices")):
            continue                       # temporal sharing: no claim
        devs = set(ev.get("devices") or ())
        for other, held in holdings[fab].items():
            if other != jkey and held & devs:
                violations.append(
                    f"F1 rank {ev['rank']}: jobs {other[1]} and "
                    f"{ev.get('job')} hold overlapping exclusive "
                    f"devices {sorted(held & devs)} at t={ev['t']:.6f}")
        if devs:
            holdings[fab][jkey] = devs
        else:
            holdings[fab].pop(jkey, None)  # shrunk to nothing

    # F2: one placement outcome per admitted job per admission epoch
    # (a resume opens a new epoch); a rejected job never places
    admits: Dict[Tuple, str] = {}
    places: Dict[Tuple, int] = defaultdict(int)
    resumes: Dict[Tuple, int] = defaultdict(int)
    for ev in events:
        key = (ev["rank"], ev.get("inc", 0), ev.get("job"))
        e = ev.get("e")
        if e == "fabric_admit":
            admits[key] = ev.get("verdict")
        elif e == "fabric_place":
            places[key] += 1
        elif e == "fabric_resume":
            resumes[key] += 1
    for key, verdict in sorted(admits.items()):
        n = places[key] - resumes[key]
        if verdict == "reject":
            if places[key]:
                violations.append(
                    f"F2 rank {key[0]} job={key[2]}: REJECTED but "
                    f"records {places[key]} placement(s)")
        elif n not in (0, 1):
            violations.append(
                f"F2 rank {key[0]} job={key[2]}: {places[key]} "
                f"placement(s) over {resumes[key]} resume(s) — "
                "expected one outcome per admission epoch")
    for key in sorted(set(places) - set(admits)):
        violations.append(
            f"F2 rank {key[0]} job={key[2]}: placed with no admission "
            "record")

    # F3: every preemption resolves — resumed, or terminal job_done
    # after the preemption (a cancelled-while-preempted job)
    outstanding: Dict[Tuple, float] = {}
    for ev in events:
        key = (ev["rank"], ev.get("inc", 0), ev.get("job"))
        e = ev.get("e")
        if e == "fabric_preempt":
            outstanding[key] = ev["t"]
        elif e == "fabric_resume":
            outstanding.pop(key, None)
        elif e == "job_done" and key in outstanding:
            outstanding.pop(key, None)
    for (rank, _inc, job), t in sorted(outstanding.items()):
        violations.append(
            f"F3 rank {rank} job={job}: preempted at t={t:.6f} but "
            "never resumed nor terminal")

    # H1: drains evidence-backed, drained ranks never placement
    # targets — chronological replay per (rank, incarnation) fabric.
    # ``peer`` is the OBSERVED rank (merge stamps ``rank`` with the
    # observer).  Placements without a ``ranks`` gang stamp predate
    # the health plane and are skipped.
    below_seen: Dict[Tuple, set] = defaultdict(set)
    drained: Dict[Tuple, set] = defaultdict(set)
    for ev in events:
        e = ev.get("e")
        if e not in ("health_transition", "health_drain",
                     "health_undrain", "fabric_place"):
            continue
        fab = (ev["rank"], ev.get("inc", 0))
        if e == "health_transition":
            if ev.get("to") != "ok":
                below_seen[fab].add(ev.get("peer"))
            else:
                below_seen[fab].discard(ev.get("peer"))
        elif e == "health_drain":
            peer = ev.get("peer")
            if peer not in below_seen[fab]:
                violations.append(
                    f"H1 rank {ev['rank']} peer={peer}: drained at "
                    f"t={ev['t']:.6f} with no preceding below-threshold "
                    "evidence (no health_transition out of 'ok')")
            score, thr = ev.get("score"), ev.get("thr")
            if score is not None and thr is not None \
                    and float(score) >= float(thr):
                violations.append(
                    f"H1 rank {ev['rank']} peer={peer}: drain score "
                    f"{score} is not below its threshold {thr}")
            drained[fab].add(peer)
        elif e == "health_undrain":
            drained[fab].discard(ev.get("peer"))
        elif e == "fabric_place" and ev.get("ranks") is not None:
            hit = drained[fab] & set(ev.get("ranks") or ())
            if hit:
                violations.append(
                    f"H1 rank {ev['rank']} job={ev.get('job')}: "
                    f"placement targets drained rank(s) {sorted(hit)} "
                    f"at t={ev['t']:.6f}")
    return violations


# ---------------------------------------------------------------------------
# round reconstruction (the --timeline summary + test hook)
# ---------------------------------------------------------------------------

def _round_for(rounds: Dict[Tuple, dict], ev: dict) -> Optional[dict]:
    """The round dict a pool-scoped event belongs to: the event's own
    ``round`` stamp when present, else the pool's nearest round whose
    cut (or latest offer) precedes the event."""
    pool = ev.get("pool")
    if ev.get("round") is not None:
        return rounds.get((pool, ev["round"]))
    cands = [r for r in rounds.values() if r["pool"] == pool]
    if not cands:
        return None

    def anchor(r) -> float:
        if r["cut"] is not None:
            return r["cut"]["t"]
        return max((o["t"] for o in r["offers"]), default=float("inf"))

    before = [r for r in cands if anchor(r) <= ev["t"]]
    return max(before, key=anchor) if before \
        else min(cands, key=anchor)


def skip_rounds(per_rank: Dict[int, List[dict]]) -> List[dict]:
    """Reconstruct each DTD skip-agreement round end-to-end from a
    merged bundle: offers (votes) -> agreed cut -> ghost replay ->
    retirement.  One dict per (pool, round) seen."""
    events = merged_events(per_rank)
    rounds: Dict[Tuple, dict] = {}

    def rec(pool, rnd) -> dict:
        return rounds.setdefault((pool, rnd), {
            "pool": pool, "round": rnd, "offers": [], "cut": None,
            "replays": [], "retired": []})

    for ev in events:
        e = ev.get("e")
        if e == "skip_offer":
            r = rec(ev.get("pool"), ev.get("round"))
            r["offers"].append({"rank": ev.get("src", ev["rank"]),
                                "frontier": ev.get("frontier"),
                                "full": ev.get("full"), "t": ev["t"]})
        elif e == "skip_cut":
            r = rec(ev.get("pool"), ev.get("round"))
            if r["cut"] is None or ev.get("prefix", 0) >= \
                    r["cut"]["prefix"]:
                r["cut"] = {"prefix": int(ev.get("prefix", 0)),
                            "t": ev["t"]}
        elif e == "replay_mode" and ev.get("mode") == "skip":
            # attribute to the EVENT'S round when stamped (r16 emits
            # carry it); otherwise the nearest preceding agreed round
            # — a pool whose first round fell back to full must not
            # report ghost replays in it
            tgt = _round_for(rounds, ev)
            if tgt is not None:
                tgt["replays"].append({"rank": ev["rank"],
                                       "prefix": ev.get("prefix"),
                                       "tasks": ev.get("tasks"),
                                       "t": ev["t"]})
        elif e == "retired":
            # retirement is pool-scoped, not round-scoped: attach to
            # the pool's last round that AGREED a cut before this
            # event (timeline cosmetics only — I4 audits retirement)
            cands = [r for r in rounds.values()
                     if r["pool"] == ev.get("pool")
                     and r["cut"] is not None
                     and r["cut"]["t"] <= ev["t"]]
            if cands:
                tgt = max(cands, key=lambda r: r["cut"]["t"])
                tgt["retired"].append({"rank": ev["rank"],
                                       "t": ev["t"]})
    out = []
    for r in rounds.values():
        # dedup offers per rank (own emit + coordinator's receive)
        seen: Dict[int, dict] = {}
        for o in r["offers"]:
            seen.setdefault(o["rank"], o)
        r["offers"] = [seen[k] for k in sorted(seen)]
        out.append(r)
    return sorted(out, key=lambda r: (str(r["pool"]), r["round"] or 0))


# ---------------------------------------------------------------------------
# renderers
# ---------------------------------------------------------------------------

def render_timeline(per_rank: Dict[int, List[dict]]) -> str:
    from parsec_tpu.prof.journal import format_event
    events = merged_events(per_rank)
    if not events:
        return "(empty journal bundle)"
    t0 = events[0]["t"]
    lines = [f"control-plane timeline: ranks {sorted(per_rank)}, "
             f"{len(events)} events (t0 = first event, rank "
             f"{min(per_rank)}'s clock)"]
    lines.extend(format_event(ev, t0) for ev in events)
    for r in skip_rounds(per_rank):
        if r["cut"] is None and not r["offers"]:
            continue
        offs = ", ".join(
            f"rank {o['rank']}:"
            + (f"full({o['full']})" if o.get("full") is not None
               else str(o.get("frontier")))
            for o in r["offers"])
        cut = r["cut"]["prefix"] if r["cut"] else "none"
        lines.append(
            f"skip round pool={r['pool']} round={r['round']}: "
            f"offers [{offs}] -> agreed cut {cut} -> "
            f"{len(r['replays'])} ghost replay(s) -> "
            f"{len(r['retired'])} retirement(s)")
    return "\n".join(lines)


def write_chrome(per_rank: Dict[int, List[dict]], out_path: str) -> int:
    """Merged journal -> chrome/Perfetto instant events (pid = rank,
    one thread row per rank's control plane) — open alongside the
    trace2chrome --merge view of the same incident bundle; both are on
    the reference rank's clock so the rows line up."""
    events = merged_events(per_rank)
    trace: List[dict] = []
    for r in sorted(per_rank):
        trace.append({"name": "process_name", "ph": "M", "pid": r,
                      "args": {"name": f"rank {r} control plane"}})
    for ev in events:
        args = {k: v for k, v in ev.items()
                if k not in ("e", "t", "rank")}
        trace.append({
            "name": ev.get("e", "?"), "ph": "i", "s": "p",
            "pid": ev["rank"], "tid": 0,
            "ts": ev["t"] * 1e6,       # chrome wants microseconds
            "args": args,
        })
    with open(out_path, "w") as fh:
        json.dump({"traceEvents": trace}, fh)
    return len(events)


# ---------------------------------------------------------------------------
# CLI
# ---------------------------------------------------------------------------

def main(argv=None) -> int:
    ap = argparse.ArgumentParser(
        description=__doc__.splitlines()[0],
        formatter_class=argparse.RawDescriptionHelpFormatter)
    ap.add_argument("paths", nargs="+",
                    help="bundle directory (journal-rank*.jsonl) "
                         "and/or journal files")
    ap.add_argument("--timeline", action="store_true",
                    help="print the merged clock-aligned protocol "
                         "timeline")
    ap.add_argument("--audit", action="store_true",
                    help="run the invariant auditor; exits 1 on any "
                         "violation")
    ap.add_argument("--chrome", metavar="OUT.json", default="",
                    help="write merged instant events for the "
                         "trace2chrome Perfetto view")
    ap.add_argument("--json", action="store_true",
                    help="machine-readable output")
    args = ap.parse_args(argv)
    per_rank = load_bundle(args.paths)
    rc = 0
    did = False
    if args.timeline:
        did = True
        if args.json:
            print(json.dumps({"events": merged_events(per_rank),
                              "skip_rounds": skip_rounds(per_rank)}))
        else:
            print(render_timeline(per_rank))
    if args.chrome:
        did = True
        n = write_chrome(per_rank, args.chrome)
        print(f"journal_audit: wrote {n} instant events to "
              f"{args.chrome}", file=sys.stderr)
    if args.audit or not did:
        violations = audit(per_rank)
        if args.json:
            print(json.dumps({"violations": violations,
                              "ranks": sorted(per_rank)}))
        elif violations:
            for v in violations:
                print(f"VIOLATION {v}")
        else:
            nev = sum(len(s.get("events", ()))
                      for snaps in per_rank.values() for s in snaps)
            print(f"journal_audit: {len(per_rank)} rank(s), {nev} "
                  "event(s), zero invariant violations")
        rc = 1 if violations else 0
    return rc


if __name__ == "__main__":
    sys.exit(main())
