#!/usr/bin/env python
"""Live runtime viewer: terminal dashboard over a running job server.

Two modes:

* **remote scrape** (default): poll a resident JobServer's plain-HTTP
  ``GET /status`` + ``GET /metrics`` surface (service/server.py — the
  same port the framed protocol rides) and render the per-job table
  in place: progress, the online exec/queue/comm/idle attribution
  split, stragglers, and the dagsim ETA (prof/liveattr.py)::

      python tools/live_view.py --port 41990 [--interval 1.0]

* **aggregator host** (``--serve``): the original gauge-aggregator
  table (reference: tools/aggregator_visu/basic_gui.py — the GUI end
  of the PAPI-SDE live pipeline); ranks' GaugePublishers publish to
  this process::

      python tools/live_view.py --serve --port 21900
"""

import argparse
import json
import socket
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])


def http_get(host: str, port: int, path: str,
             timeout: float = 10.0) -> bytes:
    """Minimal HTTP/1.0 GET (the server answers one-shot and closes);
    returns the body, raises on a non-200 status."""
    with socket.create_connection((host, port), timeout=timeout) as s:
        s.settimeout(timeout)
        s.sendall(f"GET {path} HTTP/1.0\r\n\r\n".encode())
        buf = b""
        while True:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
    head, _, body = buf.partition(b"\r\n\r\n")
    status_line = head.split(b"\r\n", 1)[0]
    status = status_line.split()
    if len(status) < 2 or status[1] != b"200":
        raise ConnectionError(
            f"GET {path}: {status_line.decode('latin-1', 'replace')}")
    return body


def _fmt_eta(j: dict) -> str:
    eta = j.get("eta_s")
    if eta is None:
        return "-"
    return f"{eta:.2f}s" if eta < 120 else f"{eta / 60:.1f}m"


def _fmt_split(att: dict) -> str:
    e = att.get("elapsed", 0.0) or 0.0
    if e <= 0:
        return "-"
    return "/".join(f"{att.get(k, 0.0) / e:4.0%}"
                    for k in ("exec", "queue", "comm", "idle"))


def _trend_arrow(trend: float) -> str:
    return "↑" if trend > 0.02 else "↓" if trend < -0.02 else "→"


def render_health(doc: dict) -> str:
    """One-line per-rank health strip from the status document's
    ``health`` block (prof/health.py merge_health): smoothed score,
    trend arrow, and — when a rank left 'ok' — its state and how long
    it has been there.  Empty string when the plane is disarmed."""
    ranks = (doc.get("health") or {}).get("ranks") or {}
    if not ranks:
        return ""
    cells = []
    for r in sorted(ranks, key=lambda x: int(x)):
        ent = ranks[r] or {}
        score = float(ent.get("ewma", ent.get("score", 1.0)) or 1.0)
        cell = (f"r{r} {score:.2f}"
                f"{_trend_arrow(float(ent.get('trend', 0.0) or 0.0))}")
        state = str(ent.get("state", "ok"))
        if state != "ok":
            cell += f" {state.upper()} {float(ent.get('since_s', 0)):.0f}s"
        cells.append(cell)
    out = "health: " + "   ".join(cells)
    tr = int((doc.get("health") or {}).get("transitions", 0) or 0)
    if tr:
        out += f"   ({tr} transition{'s' if tr != 1 else ''})"
    return out


def render_status(doc: dict, metrics: dict) -> str:
    lines = []
    svc = doc.get("service") or {}
    lines.append(
        f"parsec_tpu live view — ranks {doc.get('ranks')}  "
        f"pending={svc.get('pending', '-')} "
        f"running={svc.get('running', '-')} "
        f"degraded={svc.get('degraded', '-')}  "
        f"stragglers={doc.get('stragglers_total', 0)}")
    health = render_health(doc)
    if health:
        lines.append(health)
    hdr = (f"{'job':>5} {'name':<16} {'status':<9} {'done':>7} "
           f"{'left':>7} {'exec/queue/comm/idle':<24} {'eta':>8}")
    lines.append(hdr)
    lines.append("-" * len(hdr))
    for j in doc.get("jobs", []):
        prog = j.get("progress") or {}
        lines.append(
            f"{j.get('job', '?'):>5} {str(j.get('name', ''))[:16]:<16} "
            f"{str(j.get('status', '?'))[:9]:<9} "
            f"{prog.get('done', 0):>7} "
            f"{prog.get('remaining', 0):>7} "
            f"{_fmt_split(j.get('attribution') or {}):<24} "
            f"{_fmt_eta(j):>8}")
    if not doc.get("jobs"):
        lines.append("  (no jobs)")
    agg = doc.get("aggregate") or {}
    lines.append("")
    lines.append(f"aggregate: {agg.get('done', 0)} tasks done, split "
                 f"{_fmt_split(agg.get('attribution') or {})}")
    strag = doc.get("stragglers") or []
    if strag:
        lines.append("recent stragglers:")
        for ev in strag[-5:]:
            lines.append(
                f"  {ev.get('cls')} job={ev.get('job')} "
                f"{ev.get('kind')} {ev.get('latency_s', 0) * 1e3:.1f}ms "
                f"(> {ev.get('threshold_s', 0) * 1e3:.1f}ms) "
                f"{ev.get('task', '')}")
    if metrics:
        lines.append("")
        lines.append("  ".join(f"{k}={metrics[k]:g}"
                               for k in sorted(metrics)))
    return "\n".join(lines)


def _pick_metrics(text: str) -> dict:
    """A few headline families off the /metrics exposition."""
    want = ("parsec_tasks_retired_total", "parsec_pending_tasks",
            "parsec_jobs_slo_breached_total", "parsec_comm_dead_peers")
    out = {}
    for line in text.splitlines():
        if line.startswith("#"):
            continue
        key = line.split("{", 1)[0].split(" ", 1)[0]
        if key in want:
            try:
                out[key] = out.get(key, 0.0) + float(line.rsplit(
                    " ", 1)[1])
            except (ValueError, IndexError):
                continue
    return out


def watch_remote(args) -> None:
    while True:
        try:
            doc = json.loads(http_get(args.host, args.port, "/status"))
            metrics = _pick_metrics(http_get(
                args.host, args.port, "/metrics").decode(
                    "utf-8", "replace"))
            out = render_status(doc, metrics)
        except (OSError, ValueError, ConnectionError) as exc:
            out = f"scrape failed: {exc}"
        if args.once:
            print(out)
            return
        sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
        sys.stdout.flush()
        time.sleep(args.interval)


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=None,
                    help="job-server port (remote mode; default: the "
                         "registered service_port knob) or aggregator "
                         "port (--serve; default 21900)")
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=1.0)
    ap.add_argument("--serve", action="store_true",
                    help="host the gauge aggregator here instead of "
                         "scraping a job server (ranks publish to "
                         "this process)")
    ap.add_argument("--once", action="store_true",
                    help="print one table and exit (scripting)")
    args = ap.parse_args()
    if not args.serve:
        if args.port is None:
            from parsec_tpu.utils.mca import params
            args.port = int(params.get("service_port", 41990))
        try:
            watch_remote(args)
        except KeyboardInterrupt:
            pass
        return
    from parsec_tpu.prof.aggregator import Aggregator, render_table
    agg = Aggregator(host=args.host,
                     port=args.port if args.port is not None else 21900)
    print(f"aggregating on {args.host}:{agg.port}", file=sys.stderr)
    try:
        while True:
            out = render_table(agg.table(), agg.totals())
            if args.once:
                print(out)
                return
            sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        agg.close()


if __name__ == "__main__":
    main()
