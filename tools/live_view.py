#!/usr/bin/env python
"""Live gauge viewer: terminal dashboard over the gauge aggregator
(reference: tools/aggregator_visu/basic_gui.py + plot_gui.py — the GUI
end of the PAPI-SDE live pipeline; this renders the same table in a
terminal, refreshing in place).

Run an aggregator and point ranks' GaugePublishers at it, then:

    python tools/live_view.py --port 21900 [--interval 0.5]

or, to host the aggregator in-process (the common single-host case):

    python tools/live_view.py --serve --port 21900
"""

import argparse
import sys
import time

sys.path.insert(0, __file__.rsplit("/", 2)[0])

from parsec_tpu.prof.aggregator import Aggregator, render_table  # noqa: E402


def main() -> None:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("--port", type=int, default=21900)
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--interval", type=float, default=0.5)
    ap.add_argument("--serve", action="store_true",
                    help="host the aggregator here (ranks publish to "
                         "this process)")
    ap.add_argument("--once", action="store_true",
                    help="print one table and exit (scripting)")
    args = ap.parse_args()
    if not args.serve:
        ap.error("remote-scrape mode is not implemented — run with "
                 "--serve and point publishers here")
    agg = Aggregator(host=args.host, port=args.port)
    print(f"aggregating on {args.host}:{agg.port}", file=sys.stderr)
    try:
        while True:
            out = render_table(agg.table(), agg.totals())
            if args.once:
                print(out)
                return
            sys.stdout.write("\x1b[2J\x1b[H" + out + "\n")
            sys.stdout.flush()
            time.sleep(args.interval)
    except KeyboardInterrupt:
        pass
    finally:
        agg.close()


if __name__ == "__main__":
    main()
