#!/usr/bin/env python
"""Scrape a resident parsec_tpu job server's telemetry plane.

One-shot (prints the Prometheus text exposition, cross-rank aggregated
over TAG_METRICS by the server) or ``--watch`` (re-scrapes on an
interval and prints per-second rates for counter families):

    python tools/metrics_client.py --port 41990
    python tools/metrics_client.py --watch 2
    python tools/metrics_client.py --grep parsec_comm
    curl http://127.0.0.1:41990/metrics        # same data, plain HTTP

The framed request is ``{"op": "metrics"}`` (service/server.py); pass
``--local`` to skip the cross-rank pull and read only the server
rank's registry.
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def scrape(host: str, port: int, aggregate: bool = True,
           timeout: float = 10.0) -> str:
    from parsec_tpu.service.server import request
    reply = request(host, port, {"op": "metrics", "aggregate": aggregate},
                    timeout=timeout)
    if not reply.get("ok"):
        raise RuntimeError(f"scrape failed: {reply.get('error')}")
    return reply["text"]


def _parse_counters(text: str):
    """name{labels} -> value for counter-typed series (rate display)."""
    out = {}
    typ = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _h, _t, name, kind = line.split()
            typ[name] = kind
            continue
        if line.startswith("#") or not line.strip():
            continue
        try:
            key, val = line.rsplit(" ", 1)
            base = key.split("{", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix):
                    base = base[:-len(suffix)]
            if typ.get(base) == "counter":   # labeled series included
                out[key] = float(val)
        except ValueError:
            continue
    return out


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="job-server port (default: the registered "
                         "service_port knob, 41990)")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=0.0,
                    help="re-scrape on this interval; counter families "
                         "print per-second rates alongside totals")
    ap.add_argument("--grep", default="",
                    help="only print lines containing this substring")
    ap.add_argument("--local", action="store_true",
                    help="server rank only (skip the TAG_METRICS "
                         "cross-rank pull)")
    args = ap.parse_args(argv)
    port = args.port
    if port is None:
        from parsec_tpu.utils.mca import params
        port = int(params.get("service_port", 41990))

    def emit(text: str) -> None:
        for line in text.splitlines():
            if args.grep and args.grep not in line:
                continue
            print(line)

    if args.watch <= 0:
        emit(scrape(args.host, port, aggregate=not args.local))
        return 0

    prev = None
    prev_t = None
    while True:
        text = scrape(args.host, port, aggregate=not args.local)
        now = time.monotonic()
        print(f"--- scrape @ {time.strftime('%H:%M:%S')} ---")
        emit(text)
        cur = _parse_counters(text)
        if prev is not None and now > prev_t:
            dt = now - prev_t
            rates = [(k, (v - prev.get(k, 0.0)) / dt)
                     for k, v in sorted(cur.items())
                     if v != prev.get(k, 0.0)]
            if rates:
                print("--- rates (per second) ---")
                for k, r in rates:
                    if not args.grep or args.grep in k:
                        print(f"{k} {r:.1f}/s")
        prev, prev_t = cur, now
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
