#!/usr/bin/env python
"""Scrape a resident parsec_tpu job server's telemetry plane.

One-shot (prints the Prometheus text exposition, cross-rank aggregated
over TAG_METRICS by the server) or ``--watch`` (re-scrapes on an
interval and prints per-second rates for counter families):

    python tools/metrics_client.py --port 41990
    python tools/metrics_client.py --watch 2
    python tools/metrics_client.py --grep parsec_comm
    python tools/metrics_client.py --job 7          # one job's series
    python tools/metrics_client.py --status         # live status doc
    python tools/metrics_client.py --status --watch 2
    curl http://127.0.0.1:41990/metrics        # same data, plain HTTP

The framed requests are ``{"op": "metrics"}`` and ``{"op": "status"}``
(service/server.py); ``--status`` prints the live attribution document
(per-job progress, exec/queue/comm/idle split, stragglers, dagsim ETA
— prof/liveattr.py).  Pass ``--local`` to skip the cross-rank pull and
read only the server rank's registry.
"""

from __future__ import annotations

import argparse
import json
import os
import sys
import time

sys.path.insert(0, os.path.join(os.path.dirname(
    os.path.abspath(__file__)), ".."))


def scrape(host: str, port: int, aggregate: bool = True,
           timeout: float = 10.0) -> str:
    from parsec_tpu.service.server import request
    reply = request(host, port, {"op": "metrics", "aggregate": aggregate},
                    timeout=timeout)
    if not reply.get("ok"):
        raise RuntimeError(f"scrape failed: {reply.get('error')}")
    return reply["text"]


def scrape_status(host: str, port: int, aggregate: bool = True,
                  timeout: float = 10.0) -> dict:
    from parsec_tpu.service.server import request
    reply = request(host, port, {"op": "status", "aggregate": aggregate},
                    timeout=timeout)
    if not reply.get("ok"):
        raise RuntimeError(f"status failed: {reply.get('error')}")
    return reply["status"]


def _parse_counters(text: str):
    """name{labels} -> value for counter-typed series (rate display)."""
    out = {}
    typ = {}
    for line in text.splitlines():
        if line.startswith("# TYPE "):
            _h, _t, name, kind = line.split()
            typ[name] = kind
            continue
        if line.startswith("#") or not line.strip():
            continue
        try:
            key, val = line.rsplit(" ", 1)
            base = key.split("{", 1)[0]
            for suffix in ("_bucket", "_sum", "_count"):
                if base.endswith(suffix):
                    base = base[:-len(suffix)]
            if typ.get(base) == "counter":   # labeled series included
                out[key] = float(val)
        except ValueError:
            continue
    return out


def render_health_table(doc: dict) -> str:
    """Per-rank health table from the status document's ``health``
    block (prof/health.py): smoothed score, raw last fold, trend
    arrow, state, time in state, and which rank's view won the
    pessimistic merge."""
    health = doc.get("health") or {}
    ranks = health.get("ranks") or {}
    if not ranks:
        return "(health plane disarmed or no observations yet)"
    hdr = (f"{'rank':>5} {'score':>7} {'last':>7} {'tr':>3} "
           f"{'state':<9} {'for':>7} {'src':>4}")
    lines = [hdr, "-" * len(hdr)]
    for r in sorted(ranks, key=lambda x: int(x)):
        ent = ranks[r] or {}
        t = float(ent.get("trend", 0.0) or 0.0)
        arrow = "↑" if t > 0.02 else "↓" if t < -0.02 else "→"
        lines.append(
            f"{r:>5} {float(ent.get('ewma', 1.0)):>7.3f} "
            f"{float(ent.get('score', 1.0)):>7.3f} {arrow:>3} "
            f"{str(ent.get('state', 'ok'))[:9]:<9} "
            f"{float(ent.get('since_s', 0.0)):>6.1f}s "
            f"{ent.get('src', '-'):>4}")
    lines.append(f"folds={health.get('folds', 0)} "
                 f"transitions={health.get('transitions', 0)}")
    return "\n".join(lines)


def _status_filtered(doc: dict, job: int | None) -> dict:
    if job is None:
        return doc
    return {**doc,
            "jobs": [j for j in doc.get("jobs", [])
                     if j.get("job") == job],
            "stragglers": [e for e in doc.get("stragglers", [])
                           if e.get("job") == job]}


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=None,
                    help="job-server port (default: the registered "
                         "service_port knob, 41990)")
    ap.add_argument("--watch", type=float, metavar="SECONDS", default=0.0,
                    help="re-scrape on this interval; counter families "
                         "print per-second rates alongside totals")
    ap.add_argument("--grep", default="",
                    help="only print lines containing this substring")
    ap.add_argument("--job", type=int, default=None,
                    help="filter to one job: metric series carrying "
                         'its job="<id>" label, or its entry in the '
                         "--status document")
    ap.add_argument("--status", action="store_true",
                    help="print the live job-status document (per-job "
                         "progress, attribution split, stragglers, "
                         "ETA) instead of the Prometheus exposition")
    ap.add_argument("--health", action="store_true",
                    help="render the per-rank health table (smoothed "
                         "score, trend, state, time-in-state) from the "
                         "status document's health block instead of "
                         "raw JSON")
    ap.add_argument("--local", action="store_true",
                    help="server rank only (skip the TAG_METRICS "
                         "cross-rank pull)")
    args = ap.parse_args(argv)
    port = args.port
    if port is None:
        from parsec_tpu.utils.mca import params
        port = int(params.get("service_port", 41990))

    if args.status or args.health:
        while True:
            doc = _status_filtered(
                scrape_status(args.host, port,
                              aggregate=not args.local), args.job)
            if args.watch > 0:
                print(f"--- status @ {time.strftime('%H:%M:%S')} ---")
            if args.health:
                print(render_health_table(doc))
            else:
                print(json.dumps(doc, indent=2, sort_keys=True))
            if args.watch <= 0:
                return 0
            try:
                time.sleep(args.watch)
            except KeyboardInterrupt:
                return 0

    job_tag = None if args.job is None else f'job="{args.job}"'

    def emit(text: str) -> None:
        for line in text.splitlines():
            if args.grep and args.grep not in line:
                continue
            if job_tag and job_tag not in line:
                continue
            print(line)

    if args.watch <= 0:
        emit(scrape(args.host, port, aggregate=not args.local))
        return 0

    prev = None
    prev_t = None
    while True:
        text = scrape(args.host, port, aggregate=not args.local)
        now = time.monotonic()
        print(f"--- scrape @ {time.strftime('%H:%M:%S')} ---")
        emit(text)
        cur = _parse_counters(text)
        if prev is not None and now > prev_t:
            dt = now - prev_t
            rates = [(k, (v - prev.get(k, 0.0)) / dt)
                     for k, v in sorted(cur.items())
                     if v != prev.get(k, 0.0)]
            if rates:
                print("--- rates (per second) ---")
                for k, r in rates:
                    if args.grep and args.grep not in k:
                        continue
                    if job_tag and job_tag not in k:
                        continue
                    print(f"{k} {r:.1f}/s")
        prev, prev_t = cur, now
        try:
            time.sleep(args.watch)
        except KeyboardInterrupt:
            return 0


if __name__ == "__main__":
    sys.exit(main())
