"""Repo tooling (bench guard, chaos harness, parseclint, trace tools)."""
