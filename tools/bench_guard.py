#!/usr/bin/env python
"""Pre-merge bench regression guard.

Diffs a fresh ``bench.py`` JSON line against the previous round's
driver artifact (``BENCH_r*.json``, highest round number wins) and
exits non-zero when any shared recorded metric regressed by more than
the threshold (default 15%).  Direction-aware: ``us``/latency-class
metrics regress UP, throughput metrics regress DOWN.

Intended as the CPU-only pre-merge smoke over the host-side probes:

    PARSEC_BENCH_APP=tasks  python bench.py > /tmp/tasks.json
    python tools/bench_guard.py /tmp/tasks.json
    PARSEC_BENCH_APP=rtt    python bench.py > /tmp/rtt.json
    python tools/bench_guard.py /tmp/rtt.json
    PARSEC_BENCH_APP=tracer python bench.py > /tmp/tracer.json
    python tools/bench_guard.py /tmp/tracer.json

Usage:
    bench_guard.py NEW.json [--repo DIR] [--threshold 0.15]
                   [--prev FILE]

``NEW.json`` may be either a raw bench line ({"metric": ...}) or a
driver artifact ({"parsed": {...}}); ``-`` reads stdin.  A metric
that only exists on one side is reported but never fails the guard
(new metrics appear, modes differ per round).
"""

from __future__ import annotations

import argparse
import glob
import json
import os
import re
import sys

#: ratio-valued metrics compared by ABSOLUTE delta, smaller is better:
#: their normal baseline is 0.0 (the telemetry gate's min-of-pairs
#: clamps there), where relative change is undefined and any ratio or
#: cap scheme turns noise into a discontinuity.  Their derived
#: vs_baseline is skipped for the same reason — the value IS the gate.
ABSOLUTE_DELTA = ("telemetry_overhead", "journal_overhead",
                  "overhead_us")

#: metrics where SMALLER is better (everything else: bigger is better)
LOWER_IS_BETTER = ("task_rtt", "tracer_overhead", "telemetry_overhead",
                   "journal_overhead",
                   "backward_error", "recovery_makespan_ratio",
                   "factorization_residual",
                   # bw/rtt protocol-mix guards (the r6 event-loop
                   # transport): more wire frames or more syscalls per
                   # MB moved for the same probe is a transport
                   # regression even when the headline number hides in
                   # host noise.  act_eager stays higher-is-better by
                   # default: the probes declare eager coverage, so
                   # eroding it IS a regression
                   "frames_sent", "syscalls_per_mb")

#: keys that are configuration/metadata or noise diagnostics, never
#: compared.  rep_band/best are extreme order statistics of a protocol
#: with documented ~20% run-to-run tunnel variance — only the median
#: headline gates; the refinement LADDERS (per-step residual histories)
#: legitimately move by orders of magnitude and are accuracy evidence,
#: not rate metrics.
SKIP_KEYS = {"metric", "unit", "storage", "note", "ib",
             "fuse_panel", "potrf_protocol", "potrf_storage",
             "potrf_fuse_panel", "rep_band_gflops", "best_gflops",
             "potrf_rep_band_gflops", "potrf_best_gflops",
             "ir_residuals", "potrf_ir_residuals", "ls_refine_errors",
             # partial_writes depends on transient kernel send-buffer
             # state and wakeups on OS thread-scheduling timing — not
             # on the code under test; act_rdv/act_inline/coalesced are
             # direction-less mix descriptors (act_eager alone gates:
             # eager coverage eroding is the regression)
             "partial_writes", "wakeups", "act_rdv", "act_inline",
             "coalesced_msgs", "transport",
             # critical-path attribution (PARSEC_BENCH_TRACE=1) — and
             # its r14 online twin + the per-bucket agreement — is
             # informational: the buckets reshuffle with host load and
             # have no regression direction; the tracer-overhead gate
             # is the off-vs-on tasks comparison in premerge_bench.sh
             "attribution", "attribution_online",
             "attribution_agreement_pp",
             # host core inventory on bw/rtt lines (where the number
             # was measured, not what was measured) and the telemetry
             # mode's raw side readings (the gated value is the ratio)
             "host", "tasks_off", "tasks_on",
             # r14 tasks-probe diagnostics: the staged per-task budget
             # breakdown localizes a headline regression (the gated
             # value is task_throughput itself) and the suppressed-
             # doorbell count tracks scheduling burst shape, not the
             # code under test
             "budget", "doorbell",
             # r17 fast-path coverage + multi-rank diagnostics: the
             # bailout histogram is gated EXACTLY (zero expected) by
             # the premerge ntasks/aggregate legs, not by relative
             # diff; rank topology and per-rank/solo side readings say
             # where the aggregate headline was measured; the
             # oversubscribed-host scaling_efficiency measures
             # time-slicing fairness (the headline value gates);
             # "skipped" records why a multi-core-only leg did not run
             "bailouts", "chains", "ranks", "nb_cores_per_rank",
             "per_rank_tasks_s", "solo_tasks_s", "scaling_efficiency",
             "skipped",
             # recovery A/B side readings (r13; r15 adds the nested
             # "dtd" leg — insert-stream skip-agreement re-execution
             # counts + makespan ratios): host-load-sensitive
             # makespans and exact re-execution counts are evidence,
             # not rate metrics — the gated value is the headline
             # minimal-makespan ratio (lower-is-better), and the
             # minimal<full invariant on BOTH DAGs is asserted by
             # chaos --ab-minimal in premerge
             "recovery"}


def _load(path: str) -> dict:
    if path == "-":
        raw = sys.stdin.read()
    else:
        with open(path) as f:
            raw = f.read()
    # accept a whole driver artifact, a bare JSON object, or the last
    # JSON line of a bench run's stdout
    try:
        obj = json.loads(raw)
    except ValueError:
        obj = None
        for line in reversed(raw.splitlines()):
            line = line.strip()
            if not line.startswith("{"):
                continue
            try:
                obj = json.loads(line)
                break
            except ValueError:
                continue
        if obj is None:
            raise SystemExit(f"bench_guard: no JSON object in {path}")
    if isinstance(obj, dict) and isinstance(obj.get("parsed"), dict):
        obj = obj["parsed"]
    return obj


def _previous(repo: str) -> str:
    arts = []
    for p in glob.glob(os.path.join(repo, "BENCH_r*.json")):
        m = re.search(r"BENCH_r(\d+)\.json$", p)
        if m:
            arts.append((int(m.group(1)), p))
    if not arts:
        raise SystemExit(f"bench_guard: no BENCH_r*.json under {repo}")
    return max(arts)[1]


def _flatten(obj: dict, prefix: str = "") -> dict:
    """Numeric leaves by dotted path; lists index by position."""
    out = {}
    for k, v in obj.items():
        if k in SKIP_KEYS:
            continue
        path = f"{prefix}{k}"
        if isinstance(v, bool):
            continue
        if isinstance(v, (int, float)):
            out[path] = float(v)
        elif isinstance(v, dict):
            out.update(_flatten(v, path + "."))
        elif isinstance(v, list) and v and \
                all(isinstance(x, (int, float)) for x in v):
            for i, x in enumerate(v):
                out[f"{path}[{i}]"] = float(x)
    return out


def _lower_is_better(path: str) -> bool:
    # vs_baseline ratios are normalized "higher is better" for EVERY
    # metric (bench.py inverts latency-class targets itself)
    if path.endswith("vs_baseline"):
        return False
    segs = [s.split("[")[0] for s in path.split(".")]
    leaf = segs[-1]
    # leaf-scoped: the counter's own direction wherever it appears
    # (protocol breakdown keys, error leaves incl. prefixed forms like
    # potrf_backward_error)
    if any(tag in leaf for tag in LOWER_IS_BETTER):
        return True
    # metric-scoped: ONLY the namespaced headline (metric.value)
    # inherits the metric's direction from its prefix — a protocol leaf
    # under task_rtt.* must not inherit "lower is better" (that
    # inverted act_eager gating for the rtt probe)
    if leaf == "value":
        return any(tag in seg for seg in segs[:-1]
                   for tag in LOWER_IS_BETTER)
    return False


def _namespaced(obj: dict) -> dict:
    """Flatten, prefixing the mode-generic keys (value, vs_baseline,
    band...) with the metric name so two artifacts from different bench
    modes never compare a GEMM rate against a tasks/s number."""
    flat = _flatten(obj)
    metric = obj.get("metric")
    if not metric:
        return flat
    return {(f"{metric}.{k}" if not k.startswith(("tiled_", "potrf_",
                                                  "task_", "dataflow_",
                                                  "stencil_", "tracer_",
                                                  "dag_"))
             else k): v for k, v in flat.items()}


def compare(new: dict, prev: dict, threshold: float):
    """Returns (regressions, report_lines).  Only keys present on BOTH
    sides are compared; vs_baseline-style ratios compare like their
    underlying value."""
    new_f = _namespaced(new)
    prev_f = _namespaced(prev)
    regressions = []
    lines = []
    for path in sorted(set(new_f) & set(prev_f)):
        a, b = prev_f[path], new_f[path]
        if any(tag in path for tag in ABSOLUTE_DELTA):
            if path.endswith("vs_baseline"):
                continue
            delta = b - a
            bad = delta > threshold
            mark = "REGRESSION" if bad else "ok"
            lines.append(f"  {path}: {a:g} -> {b:g} "
                         f"({delta:+.3f} abs) {mark}")
            if bad:
                regressions.append((path, a, b, delta))
            continue
        if a == 0:
            continue
        change = (b - a) / abs(a)
        bad = change > threshold if _lower_is_better(path) \
            else change < -threshold
        mark = "REGRESSION" if bad else "ok"
        lines.append(f"  {path}: {a:g} -> {b:g} ({change:+.1%}) {mark}")
        if bad:
            regressions.append((path, a, b, change))
    for path in sorted(set(new_f) - set(prev_f)):
        lines.append(f"  {path}: (new) {new_f[path]:g}")
    for path in sorted(set(prev_f) - set(new_f)):
        lines.append(f"  {path}: (gone; was {prev_f[path]:g})")
    return regressions, lines


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("new", help="fresh bench JSON ('-' = stdin)")
    ap.add_argument("--repo", default=os.path.dirname(
        os.path.dirname(os.path.abspath(__file__))),
        help="repo dir holding BENCH_r*.json artifacts")
    ap.add_argument("--prev", default=None,
                    help="explicit previous JSON (overrides --repo scan)")
    ap.add_argument("--threshold", type=float, default=0.15,
                    help="relative regression that fails (default 0.15)")
    args = ap.parse_args(argv)

    new = _load(args.new)
    prev_path = args.prev or _previous(args.repo)
    prev = _load(prev_path)
    if new.get("metric") and prev.get("metric") and \
            new["metric"] != prev["metric"]:
        # different modes: compare only the overlap (e.g. a potrf run
        # against a gemm+potrf merged artifact still shares the
        # tiled_potrf_* keys when present)
        print(f"bench_guard: metric {new['metric']!r} vs previous "
              f"{prev['metric']!r} — comparing shared keys only")
    regs, lines = compare(new, prev, args.threshold)
    print(f"bench_guard: {args.new} vs {prev_path} "
          f"(threshold {args.threshold:.0%})")
    for ln in lines:
        print(ln)
    if regs:
        print(f"bench_guard: {len(regs)} metric(s) regressed >"
              f"{args.threshold:.0%}:")
        for path, a, b, change in regs:
            print(f"  {path}: {a:g} -> {b:g} ({change:+.1%})")
        return 1
    print("bench_guard: no regression beyond threshold")
    return 0


if __name__ == "__main__":
    sys.exit(main())
