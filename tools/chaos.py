#!/usr/bin/env python
"""Chaos harness: seeded fault plans vs 2-rank workloads, asserting the
NO-HANG invariant.

Every run must end, within its deadline, in exactly one of:

  * correct completion — the workload validates its numbers internally,
    so a silently wrong answer fails the run (zero silent corruption);
  * a STRUCTURED failure — PeerFailedError / TaskRetryExhausted
    somewhere in the collected per-rank tracebacks (kill plans
    additionally require the SURVIVOR to report PeerFailedError).

A run that neither completes nor errors before the harness deadline is
a HANG — the one outcome the robustness layer exists to abolish.

Usage:
    python tools/chaos.py --seeds 12            # the acceptance run
    python tools/chaos.py --seeds 3 --quick     # premerge smoke
    python tools/chaos.py --list                # show the plan catalog

Each seed rotates through the plan catalog (drop/dup/delay/trunc frame
faults, hard-close and silent-hang rank kills, transient task faults
with and without retry budget) over two workloads: a 2-rank tiled potrf
(PTG/dataflow path, rendezvous traffic forced via a small eager limit)
and a 2-rank DTD increment chain (lane/surrogate path, exact-value
check).  The fault plan reaches the spawned ranks through
``PARSEC_MCA_FAULT_PLAN`` in the environment (utils/faultinject.py).
"""

from __future__ import annotations

import argparse
import os
import sys
import time

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

import numpy as np  # noqa: E402


# ---------------------------------------------------------------------------
# workloads (module-level: spawn pickling)
# ---------------------------------------------------------------------------

def _wait_s() -> float:
    return float(os.environ.get("PARSEC_CHAOS_WAIT_S", "60"))


def potrf_workload(ctx, rank, nranks, recover=False):
    """Tiled Cholesky with an internal numerical check — the
    PTG/remote-dep path (activations, rendezvous, writebacks).  With
    ``recover`` the collection carries an init_fn re-runnable source,
    so a kill_rank plan ends in lineage re-execution on the survivors
    instead of a structured failure — and the survivors validate the
    ADOPTED tiles too (local_tiles routes through the translated
    owner)."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic

    n, mb = 96, 16
    rng = np.random.default_rng(3)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                          myrank=rank, name="A")
    if recover:
        A.set_init(lambda m, nn: spd[m * mb:(m + 1) * mb,
                                     nn * mb:(nn + 1) * mb])
    for m, nn in A.local_tiles():
        np.asarray(A.data_of(m, nn).copy_on(0).payload)[:] = \
            spd[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
    ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
    ctx.wait(timeout=_wait_s())
    # every rank knows the full answer (same seed): validate the LOCAL
    # tiles — a silently wrong tile fails ITS rank
    Lref = np.linalg.cholesky(spd.astype(np.float64))
    for m, nn in A.local_tiles():
        if nn > m:
            continue
        got = np.asarray(A.data_of(m, nn).pull_to_host().payload,
                         dtype=np.float64)
        ref = Lref[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
        if m == nn:
            got, ref = np.tril(got), np.tril(ref)
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)
    return "ok"


def potrf_recover_workload(ctx, rank, nranks):
    return potrf_workload(ctx, rank, nranks, recover=True)


def dtd_chain_workload(ctx, rank, nranks):
    """2-rank DTD increment chain bouncing between ranks — the
    lane/surrogate path, with an EXACT final-value check."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, DTDTaskpool

    steps = 40
    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("chaos-chain")
    ctx.add_taskpool(tp)
    ctx.start()
    t = tp.tile_of(V, 0)
    for i in range(steps):
        tp.insert_task(lambda T: T + 1.0, (t, INOUT),
                       (i % nranks, AFFINITY))
    tp.wait(timeout=_wait_s())
    ctx.wait(timeout=_wait_s())
    if rank == 0:
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, float(steps))
    return "ok"


def dtd_chain_recover_workload(ctx, rank, nranks):
    """The DTD increment chain with a recovery spec: the insertion
    stream doubles as the ``recovery_replay`` lineage, so a killed rank
    mid-chain re-executes the whole chain on the survivor against the
    snapshot-restored tile — EXACT final value required."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, DTDTaskpool

    steps = 40
    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank)
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("chaos-chain-r")

    def insert_stream(pool, V=V, steps=steps, nranks=nranks):
        t = pool.tile_of(V, 0)
        for i in range(steps):
            pool.insert_task(lambda T: T + 1.0, (t, INOUT),
                             (i % nranks, AFFINITY))

    tp.recovery_collections = [V]
    tp.recovery_replay = insert_stream
    ctx.add_taskpool(tp)
    ctx.start()
    insert_stream(tp)
    tp.wait(timeout=_wait_s())
    ctx.wait(timeout=_wait_s())
    if rank == 0:
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, float(steps))
    return "ok"


def _dtd_chain_step(T):
    """Named DTD chain body: the function name lands in the task key,
    so keyed fault directives (``delay_dispatch=key~_dtd_chain_step``)
    can stall exactly these bodies."""
    return T + 1.0


def dtd_ab_chain_workload(ctx, rank, nranks):
    """Multi-rank DTD increment chain with keyed 100 ms bodies and a
    recovery spec — the DTD minimal-vs-full A/B DAG.  Inserts
    alternate between ranks 0 and 1 (rank 2+, when present, tracks the
    SPMD stream as a pure observer and participates in the skip
    agreement over the wire).  A mid-chain kill leaves the survivor a
    completed skippable prefix at any kill point; replay-from-restore-
    point re-runs the whole stream either way, so minimal < full
    deterministically.  Returns the survivor's replay accounting."""
    from parsec_tpu.data.matrix import VectorTwoDimCyclic
    from parsec_tpu.dsl.dtd import AFFINITY, INOUT, DTDTaskpool

    steps = int(os.environ.get("PARSEC_CHAOS_DTD_STEPS", 40))
    V = VectorTwoDimCyclic(mb=4, lm=4, nodes=nranks, myrank=rank,
                           name="Vdtdab")
    # re-runnable v0 source: an ADOPTING survivor with no attach
    # snapshot of the tile (it was never local) restores from here
    V.set_init(lambda m, n=0: np.zeros(4, np.float32))
    if rank == 0:
        V.data_of(0).copy_on(0).payload[:] = 0.0
    tp = DTDTaskpool("chaos-dtd-ab")

    def insert_stream(pool, V=V, steps=steps):
        t = pool.tile_of(V, 0)
        for i in range(steps):
            pool.insert_task(_dtd_chain_step, (t, INOUT),
                             (i % 2, AFFINITY))

    tp.recovery_collections = [V]
    tp.recovery_replay = insert_stream
    ctx.add_taskpool(tp)
    ctx.start()
    insert_stream(tp)
    tp.wait(timeout=_wait_s())
    ctx.wait(timeout=_wait_s())
    if rank == 0:
        val = np.asarray(V.data_of(0).pull_to_host().payload)
        np.testing.assert_allclose(val, float(steps))
    rec = ctx.recovery
    st = rec.stats() if rec is not None else {}
    return ("ok", st.get("tasks_reexecuted", 0),
            st.get("minimal_replays", 0), st.get("full_replays", 0),
            st.get("skip_agreements", 0))


def dtd_minimal_recover_workload(ctx, rank, nranks):
    """The DTD A/B chain under a kill, with the SKIP-AGREEMENT path
    asserted on every survivor: a full-replay fallback FAILS the case
    (observed-outcome discipline — the counters prove which path ran,
    a silent fallback is a regression, not a pass)."""
    r = dtd_ab_chain_workload(ctx, rank, nranks)
    if r[2] < 1 or r[3] > 0 or r[4] < 1:
        raise AssertionError(
            f"DTD minimal replay did not engage (minimal={r[2]}, "
            f"full={r[3]}, skip_agreements={r[4]}) — silent fallback "
            "to full insert-stream replay")
    return r


def _chain_hook(es, task):
    """Shared CPU incarnation of the dyn and A/B chains' W(i): own
    tile T := predecessor P + 1 (P is READ — never mutated, so sharing
    the producer's copy on local edges is safe)."""
    import numpy as np
    p = task.data.get("P")
    base = 0.0 if p is None else float(np.asarray(p.payload).flat[0])
    t = task.data["T"]
    arr = np.asarray(t.payload, dtype=np.float32)
    t.payload = np.full_like(arr, base + 1.0)
    return None


def dyn_chain_recover_workload(ctx, rank, nranks):
    """Distributed DynamicTaskpool chain (runtime task discovery, the
    dyn-hold pool-scoped quiescence round) with a recovery spec: a
    mid-chain kill must restart the pool on the survivor, RE-ARM the
    distributed termination hold across the restart (previously a kill
    with the hold outstanding stranded it), and end with the exact
    final values on every surviving rank."""
    import numpy as np
    from parsec_tpu.core.task import (Dep, FromDesc, FromTask, READ,
                                      RW, TaskClass, ToDesc, ToTask)
    from parsec_tpu.core.taskpool import DynamicTaskpool
    from parsec_tpu.data.matrix import VectorTwoDimCyclic

    steps = 12
    V = VectorTwoDimCyclic(mb=2, lm=2 * steps, nodes=nranks,
                           myrank=rank, name="Vdyn")
    V.set_init(lambda m, n=0: np.zeros(2, np.float32))
    # each W(i) reads its predecessor's T (task-fed READ, discovered
    # at delivery — never enumerated) and writes its OWN tile
    # V(i) = i + 1, handing T on across the 1D-cyclic owners
    tc = TaskClass(
        "W", params=[("i", lambda g, l: range(steps))],
        affinity=lambda loc, V=V: V(loc["i"]),
        flows=[READ("P",
                    inputs=[Dep(FromTask("W", "T",
                                         lambda loc:
                                         {"i": loc["i"] - 1}),
                                guard=lambda loc: loc["i"] > 0)]),
               RW("T",
                  inputs=[Dep(FromDesc(lambda loc, V=V: V(loc["i"])))],
                  outputs=[Dep(ToTask("W", "P",
                                      lambda loc: {"i": loc["i"] + 1}),
                               guard=lambda loc, s=steps:
                               loc["i"] < s - 1),
                           Dep(ToDesc(lambda loc, V=V: V(loc["i"])))])],
        incarnations=[("cpu", _chain_hook)],
        properties={"startup_fn":
                    lambda g, r: [{"i": 0}] if r == 0 else []})
    tp = DynamicTaskpool("dyn-chain")
    tp.add_task_class(tc)
    tp.recovery_collections = [V]
    ctx.add_taskpool(tp)
    ctx.wait(timeout=_wait_s())
    for m, _n in V.local_tiles():
        got = np.asarray(V.data_of(m).pull_to_host().payload)
        np.testing.assert_allclose(got, float(m + 1))
    return "ok"


def potrf_recover_count_workload(ctx, rank, nranks):
    """The recover potrf plus this rank's replay accounting — the
    minimal-vs-full A/B leg reads the survivor's re-execution count."""
    r = potrf_workload(ctx, rank, nranks, recover=True)
    rec = ctx.recovery
    st = rec.stats() if rec is not None else {}
    return (r, st.get("tasks_reexecuted", 0),
            st.get("minimal_replays", 0), st.get("full_replays", 0))


def ab_chain_minimal_workload(ctx, rank, nranks):
    """The A/B chain under a kill, with the MINIMAL path asserted: on
    this DAG a survivor that fell back to full replay is a regression,
    not a pass (the fallback counters prove which path ran)."""
    r = ab_chain_recover_workload(ctx, rank, nranks)
    if r[2] < 1 or r[3] > 0:
        raise AssertionError(
            f"minimal replay did not engage (minimal={r[2]}, "
            f"full={r[3]}) — silent fallback to restore-point replay")
    return r


WORKLOADS = {"potrf": potrf_workload, "dtd": dtd_chain_workload,
             "potrf-recover": potrf_recover_workload,
             "dtd-recover": dtd_chain_recover_workload,
             "dyn-recover": dyn_chain_recover_workload,
             "potrf-recover-count": potrf_recover_count_workload,
             "ab-chain-minimal": ab_chain_minimal_workload,
             "dtd-ab-chain": dtd_ab_chain_workload,
             "dtd-minimal": dtd_minimal_recover_workload}


# ---------------------------------------------------------------------------
# kill -> restart -> rejoin scenario (all transports, incl. shm ring
# re-creation) — not a fault-plan case: the victim RESTARTS in-process
# with a bumped incarnation epoch and must serve its partition again
# ---------------------------------------------------------------------------

def _rejoin_phase(ctx, rank, nranks, name):
    """One full 2-rank potrf with per-rank numeric validation."""
    from parsec_tpu.apps.potrf import potrf_taskpool
    from parsec_tpu.data.matrix import TwoDimBlockCyclic
    n, mb = 64, 16
    rng = np.random.default_rng(5)
    a = rng.standard_normal((n, n)).astype(np.float32)
    spd = (a @ a.T + n * np.eye(n)).astype(np.float32)
    A = TwoDimBlockCyclic(mb=mb, nb=mb, lm=n, ln=n, nodes=nranks,
                          myrank=rank, name=name)
    for m, nn in A.local_tiles():
        np.asarray(A.data_of(m, nn).copy_on(0).payload)[:] = \
            spd[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
    ctx.add_taskpool(potrf_taskpool(A, device="cpu"))
    ctx.wait(timeout=60)
    Lref = np.linalg.cholesky(spd.astype(np.float64))
    for m, nn in A.local_tiles():
        if nn > m:
            continue
        got = np.asarray(A.data_of(m, nn).pull_to_host().payload,
                         dtype=np.float64)
        ref = Lref[m * mb:(m + 1) * mb, nn * mb:(nn + 1) * mb]
        if m == nn:
            got, ref = np.tril(got), np.tril(ref)
        np.testing.assert_allclose(got, ref, rtol=5e-2, atol=5e-2)


def _rejoin_proc(rank, nranks, port_base, transport, outq):
    import time as _time
    import traceback
    os.environ["JAX_PLATFORMS"] = "cpu"
    os.environ["PARSEC_MCA_COMM_TRANSPORT"] = transport
    os.environ["PARSEC_MCA_RECOVERY_ENABLE"] = "1"
    try:
        from parsec_tpu.comm.engine import make_ce
        from parsec_tpu.comm.remote_dep import RemoteDepEngine
        from parsec_tpu.core.context import Context
        from parsec_tpu.utils.mca import params

        ce = make_ce(rank, nranks, port_base)
        ctx = Context(nb_cores=2, rank=rank, nranks=nranks)
        rde = RemoteDepEngine(ce, ctx)
        ce.barrier()
        _rejoin_phase(ctx, rank, nranks, "A")
        ce.barrier()
        if rank == 1:
            rde.fini()                    # the rank goes down
            _time.sleep(1.0)
            params.set("comm_epoch", 1)   # restarted incarnation
            ce = make_ce(rank, nranks, port_base)
            rde = RemoteDepEngine(ce, ctx)
            table = ctx.recovery.rejoin(timeout=30.0)
            assert isinstance(table, dict)
        else:
            deadline = _time.monotonic() + 25
            while 1 not in ce.dead_peers:
                if _time.monotonic() > deadline:
                    raise RuntimeError("rank 1 death never detected")
                _time.sleep(0.02)
            while 1 in ce.dead_peers:     # cleared by peer_rejoined
                if _time.monotonic() > deadline + 35:
                    raise RuntimeError("rank 1 never rejoined")
                _time.sleep(0.02)
            assert ctx.recovery.rejoins == 1
        ce.barrier(timeout=30)
        # the REJOINED rank serves its partition again, over the
        # RE-CREATED transport state (fresh shm rings on shm)
        _rejoin_phase(ctx, rank, nranks, "B")
        ce.barrier(timeout=30)
        ce._stop = True
        outq.put((rank, None, "ok"))
        ctx.fini()
        rde.fini()
    except Exception:
        outq.put((rank, traceback.format_exc(), None))


def rejoin_scenario(transport="shm", timeout=150.0):
    """Run the kill -> restart -> TAG_REJOIN -> serves-again scenario
    on one transport; returns (ok, detail)."""
    import multiprocessing as mp
    from parsec_tpu.comm.launch import _probe_port_base
    base = _probe_port_base(2)
    mpctx = mp.get_context("spawn")
    outq = mpctx.Queue()
    procs = [mpctx.Process(target=_rejoin_proc,
                           args=(r, 2, base, transport, outq),
                           daemon=True)
             for r in range(2)]
    for p in procs:
        p.start()
    results, errs = {}, []
    try:
        for _ in range(2):
            rank, err, res = outq.get(timeout=timeout)
            if err is not None:
                errs.append(f"rank {rank}: {err}")
            results[rank] = res
    except Exception as exc:
        errs.append(f"harness: {exc!r}")
    finally:
        for p in procs:
            p.join(timeout=10)
            if p.is_alive():
                p.terminate()
    ok = not errs and results == {0: "ok", 1: "ok"}
    return ok, "; ".join(errs) if errs else repr(results)


# ---------------------------------------------------------------------------
# degrade -> drain-before-death scenario (the predictive health plane's
# validation workload, ISSUE 19): rank 1 is DYING, not dead — a seeded
# ramped degrade inflates its task latencies and every outbound frame
# (heartbeats included) while staying far under the death timeout.  The
# fabric on rank 0 must journal a pre-emptive health_drain with its
# below-threshold evidence, stop placing onto rank 1, and the heartbeat
# detector must NEVER fire — then the offline auditor (incl. the H1
# health invariant) replays the whole decision trail clean.
# ---------------------------------------------------------------------------

def _health_job_factory():
    """Tiny local 4-task pool: enough to produce real fabric_place
    records (with their gang stamps) around the drain."""
    from parsec_tpu.dsl.ptg.api import PTG, Range
    p = PTG("hjob", N=4)
    p.task("T", i=Range(0, 3)).body(lambda: None)
    return p.build()


def _degrade_proc(rank, nranks, port_base, outq):
    import time as _time
    import traceback
    os.environ["JAX_PLATFORMS"] = "cpu"
    try:
        from parsec_tpu.comm.engine import make_ce
        from parsec_tpu.comm.remote_dep import RemoteDepEngine
        from parsec_tpu.core.context import Context

        run_s = float(os.environ.get("PARSEC_CHAOS_DEGRADE_RUN_S", "45"))
        ce = make_ce(rank, nranks, port_base)
        ctx = Context(nb_cores=2, rank=rank, nranks=nranks)
        rde = RemoteDepEngine(ce, ctx)
        ce.barrier()
        t0 = _time.monotonic()
        if rank != 0:
            # the degrading rank: idle but ALIVE.  The armed fault
            # plan's ramped degrade directive is doing the work — every
            # outbound frame (TAG_HB included) gains a growing, seeded-
            # jittered delay that stays far below comm_peer_timeout_s
            while _time.monotonic() - t0 < run_s + 8.0:
                _time.sleep(0.2)
            outq.put((rank, None, "ok"))
            return

        # rank 0: the health consumer — fabric + monitor + auditor
        from parsec_tpu.service.fabric import ServingFabric
        from tools import journal_audit

        svc = ServingFabric(ctx)
        hm = getattr(ctx.metrics, "_health", None)
        assert hm is not None, "health plane disarmed on the consumer"
        # one placement BEFORE the degradation bites: its gang stamp
        # must carry BOTH ranks (the healthy baseline the audit's
        # drained-placement check contrasts against)
        pre = svc.submit(_health_job_factory, name="pre-degrade")
        pre_ok = pre.wait(timeout=30)
        assert pre_ok, "pre-degrade job never finished"

        deadline = t0 + run_s
        drained_at = None
        while _time.monotonic() < deadline:
            if svc.drains >= 1:
                drained_at = round(_time.monotonic() - t0, 1)
                break
            _time.sleep(0.2)
        checks = []
        if drained_at is None:
            snap = hm.snapshot().get(1, {})
            checks.append(f"drain never fired within {run_s}s "
                          f"(rank 1 health: {snap!r})")
        # drain-before-DEATH: the liveness detector must never have
        # seen anything — the rank is slow, not silent
        if 1 in ce.dead_peers:
            checks.append("rank 1 declared DEAD — the drain did not "
                          "beat the heartbeat detector")
        st = svc.stats()["fabric"]
        if drained_at is not None and st["drained_ranks"] != [1]:
            checks.append(f"drained_ranks={st['drained_ranks']!r}, "
                          "expected [1]")
        # one placement AFTER the drain: its gang stamp must exclude
        # the drained rank (the H1 invariant audited below)
        if drained_at is not None:
            post = svc.submit(_health_job_factory, name="post-drain")
            if not post.wait(timeout=30):
                checks.append("post-drain job never finished")
        events = ctx.journal.snapshot()["events"]
        if any(e.get("e") == "peer_dead" and e.get("peer") == 1
               for e in events):
            checks.append("peer_dead journaled for the degrading rank")
        drains = [e for e in events if e.get("e") == "health_drain"]
        if drained_at is not None:
            if not drains:
                checks.append("health_drain missing from the journal")
            elif not drains[0].get("evidence"):
                checks.append("health_drain carries no evidence")
        places = [e for e in events if e.get("e") == "fabric_place"]
        if drained_at is not None and \
                not any(e.get("ranks") == [0] for e in places):
            checks.append("no post-drain placement with gang [0] "
                          f"(placements: {[e.get('ranks') for e in places]!r})")
        violations = journal_audit.audit({0: [ctx.journal.snapshot()]})
        if violations:
            checks.append("journal audit: " + "; ".join(violations[:4]))
        svc.shutdown(timeout=5.0)
        if checks:
            outq.put((rank, "; ".join(checks), None))
        else:
            outq.put((rank, None,
                      f"drained rank 1 at t+{drained_at}s "
                      f"(evidence pts={len(drains[0]['evidence'])}, "
                      f"placements={len(places)}, "
                      f"events={len(events)})"))
    except Exception:
        outq.put((rank, traceback.format_exc(), None))


def degrade_scenario(seed=7, timeout=120.0):
    """Run the seeded degrade -> drain-before-death case; returns
    (ok, detail).  Replayable: the ramp's jitter stream is seeded, so
    the same seed degrades the same way."""
    import multiprocessing as mp
    from parsec_tpu.comm.launch import _probe_port_base
    keys = _CHAOS_ENV + ("PARSEC_MCA_COMM_CLOCK_PROBE_S",
                         "PARSEC_MCA_FABRIC_DRAIN_SCORE",
                         "PARSEC_MCA_FABRIC_DRAIN_SUSTAIN_S",
                         "PARSEC_MCA_HEALTH_DEGRADED",
                         "PARSEC_MCA_HEALTH_INTERVAL_S",
                         "PARSEC_CHAOS_DEGRADE_RUN_S")
    saved = {k: os.environ.get(k) for k in keys}
    os.environ.update({
        # the dying-not-dead plan: frame+task delays ramp 0 -> 5 s over
        # 10 s starting at t+4 s — far under the 30 s death timeout
        "PARSEC_MCA_FAULT_PLAN":
            f"seed={seed};degrade=rank=1,ms=5000,ramp=10,at=4",
        "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "30",
        # heartbeat cadence rides min(clock_probe, timeout/3): probe at
        # 0.3 s so the gap/jitter baseline learns fast and the jitter
        # penalty reads against a tight cadence
        "PARSEC_MCA_COMM_CLOCK_PROBE_S": "0.3",
        "PARSEC_MCA_HEALTH_INTERVAL_S": "0.5",
        # evidence strictly precedes the drain: the 'degraded'
        # transition fires at 0.9, the drain only below 0.85 sustained
        # (healthy ranks sit at 1.0 — the margin is against fold noise,
        # not against health)
        "PARSEC_MCA_HEALTH_DEGRADED": "0.9",
        "PARSEC_MCA_FABRIC_DRAIN_SCORE": "0.85",
        "PARSEC_MCA_FABRIC_DRAIN_SUSTAIN_S": "2.0",
    })
    try:
        base = _probe_port_base(2)
        mpctx = mp.get_context("spawn")
        outq = mpctx.Queue()
        procs = [mpctx.Process(target=_degrade_proc,
                               args=(r, 2, base, outq), daemon=True)
                 for r in range(2)]
        for p in procs:
            p.start()
        results, errs = {}, []
        try:
            for _ in range(2):
                rank, err, res = outq.get(timeout=timeout)
                if err is not None:
                    errs.append(f"rank {rank}: {err}")
                results[rank] = res
        except Exception as exc:
            errs.append(f"harness: {exc!r}")
        finally:
            for p in procs:
                p.join(timeout=10)
                if p.is_alive():
                    p.terminate()
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    ok = not errs and set(results) == {0, 1} and results[1] == "ok" \
        and results[0] is not None
    return ok, "; ".join(errs) if errs else str(results[0])


# ---------------------------------------------------------------------------
# minimal-vs-full replay A/B (the premerge --ab-minimal leg and the
# bench recovery mode both drive this)
# ---------------------------------------------------------------------------

def ab_chain_recover_workload(ctx, rank, nranks):
    """The minimal-vs-full A/B DAG: a 20-step chain whose FIRST half
    lives entirely on rank 0 and second half on rank 1 (tabular
    placement), each body stalled 100 ms by a keyed delay_dispatch.
    Built so the survivor PROVABLY holds completed-and-not-needed work
    at ANY mid-run kill point: a kill during rank 0's half leaves its
    completed prefix skippable (no remote send happened yet), and a
    kill during rank 1's half leaves everything before the one
    cross-rank edge skippable (the re-feed closure stops at the
    boundary producer, whose output synthesizes from the live tile).
    Replay-from-restore-point re-runs the WHOLE local partition either
    way, so minimal < full deterministically."""
    from parsec_tpu.core.task import (Dep, FromDesc, FromTask, READ,
                                      WRITE, TaskClass, ToDesc, ToTask)
    from parsec_tpu.core.taskpool import ParameterizedTaskpool
    from parsec_tpu.data.matrix import TwoDimTabular

    # PARSEC_CHAOS_AB_STEPS scales the chain (the r14 residual
    # re-measure: a bigger DAG with an earlier kill, where the
    # survivor's skippable share dominates)
    steps = int(os.environ.get("PARSEC_CHAOS_AB_STEPS", 20))
    half = steps // 2
    V = TwoDimTabular(2, 1, 2 * steps, 1,
                      table=[0] * half + [1] * (steps - half),
                      nodes=nranks, myrank=rank, name="Vab")
    V.set_init(lambda m, n=0: np.zeros((2, 1), np.float32))
    tc = TaskClass(
        "W", params=[("i", lambda g, l: range(steps))],
        affinity=lambda loc, V=V: V(loc["i"], 0),
        flows=[READ("P",
                    inputs=[Dep(FromTask("W", "T",
                                         lambda loc:
                                         {"i": loc["i"] - 1}),
                                guard=lambda loc: loc["i"] > 0)]),
               # WRITE access (full overwrite): a mid-body kill's
               # stale-mutation taint on this tile must not force the
               # minimal path into its fallback — the re-run rewrites
               # the tile from P alone
               WRITE("T",
                     inputs=[Dep(FromDesc(lambda loc, V=V:
                                          V(loc["i"], 0)))],
                     outputs=[Dep(ToTask("W", "P",
                                         lambda loc:
                                         {"i": loc["i"] + 1}),
                                  guard=lambda loc, s=steps:
                                  loc["i"] < s - 1),
                              Dep(ToDesc(lambda loc, V=V:
                                         V(loc["i"], 0)))])],
        incarnations=[("cpu", _chain_hook)])
    p = ParameterizedTaskpool("ab-chain")
    p.add_task_class(tc)
    p.recovery_collections = [V]
    ctx.add_taskpool(p)
    ctx.wait(timeout=_wait_s())
    for m, nn in V.local_tiles():
        got = np.asarray(V.data_of(m, nn).pull_to_host().payload)
        np.testing.assert_allclose(got, float(m + 1))
    rec = ctx.recovery
    st = rec.stats() if rec is not None else {}
    return ("ok", st.get("tasks_reexecuted", 0),
            st.get("minimal_replays", 0), st.get("full_replays", 0))


def _ab_plan() -> str:
    """The A/B kill plan; PARSEC_CHAOS_AB_KILL_S moves the kill point
    (earlier kill = more completed-and-skippable survivor work on the
    default tabular split) and PARSEC_CHAOS_AB_BODY_MS the per-body
    stall for bigger-DAG runs."""
    kill_s = os.environ.get("PARSEC_CHAOS_AB_KILL_S", "1.0")
    body_ms = os.environ.get("PARSEC_CHAOS_AB_BODY_MS", "100")
    return (f"seed=11;kill_rank=1@t+{kill_s}s,mode=close;"
            f"delay_dispatch=key~W(,ms={body_ms}")


def _run_ab_legs(plan: str, workload, nranks: int, timeout: float,
                 label: str = ""):
    """The shared A/B scaffolding: run one kill plan twice — minimal
    replay vs forced replay-from-restore-point — with env save/restore
    and the kill-actually-fired validation.  Returns
    ``{mode: {"reexec", "minimal", "full", ["skip"], "makespan_s"}}``;
    raises RuntimeError when either leg fails or the kill never fired
    (a run that outpaced its trigger exercised no recovery)."""
    from parsec_tpu.comm.launch import run_distributed
    keys = _CHAOS_ENV + ("PARSEC_MCA_RECOVERY_MINIMAL",)
    out = {}
    for mode, knob in (("minimal", "1"), ("full", "0")):
        saved = {k: os.environ.get(k) for k in keys}
        os.environ["PARSEC_MCA_FAULT_PLAN"] = plan
        os.environ["PARSEC_CHAOS_WAIT_S"] = "45"
        os.environ["PARSEC_MCA_RECOVERY_ENABLE"] = "1"
        os.environ["PARSEC_MCA_RECOVERY_MINIMAL"] = knob
        t0 = time.monotonic()
        try:
            res = run_distributed(workload, nranks,
                                  timeout=timeout, tolerate_ranks=[1])
        finally:
            for k, v in saved.items():
                if v is None:
                    os.environ.pop(k, None)
                else:
                    os.environ[k] = v
        dt = time.monotonic() - t0
        surv = res[0]
        if surv is None or surv[0] != "ok":
            raise RuntimeError(f"{label}{mode} leg failed: {res!r}")
        if res[1] is not None:
            raise RuntimeError(
                f"{label}{mode} leg outpaced its kill trigger (victim "
                "completed) — no recovery was exercised")
        ent = {"reexec": surv[1], "minimal": surv[2],
               "full": surv[3], "makespan_s": round(dt, 2)}
        if len(surv) > 4:
            ent["skip"] = surv[4]
        out[mode] = ent
    return out


def run_ab_pair(timeout=120.0):
    """The PTG A/B: recorded-lineage minimal replay vs forced
    replay-from-restore-point on the same deterministic chain kill."""
    return _run_ab_legs(_ab_plan(), ab_chain_recover_workload, 2,
                        timeout)


def _dtd_ab_plan() -> str:
    """The DTD A/B kill plan: keyed 100 ms chain bodies make the
    40-step chain's makespan >= 4 s, so the t+2.0s kill always lands
    mid-stream — late enough that the survivor provably holds a
    completed, skippable prefix even on a loaded host (spawn + jax
    import eat the first second or more of the kill budget)."""
    kill_s = os.environ.get("PARSEC_CHAOS_AB_KILL_S", "2.0")
    body_ms = os.environ.get("PARSEC_CHAOS_AB_BODY_MS", "100")
    return (f"seed=11;kill_rank=1@t+{kill_s}s,mode=close;"
            f"delay_dispatch=key~_dtd_chain_step,ms={body_ms}")


def run_ab_pair_dtd(timeout=120.0, nranks=3):
    """The DTD insert-stream A/B: the same mid-chain kill under the
    cross-rank skip agreement vs forced full replay.  3 ranks by
    default so the skip round runs OVER THE WIRE between two survivors
    (2 ranks would short-circuit at the sole survivor)."""
    return _run_ab_legs(_dtd_ab_plan(), dtd_ab_chain_workload, nranks,
                        timeout, label="dtd ")


def run_ab_minimal(timeout=120.0) -> int:
    """CI leg: assert tasks_reexecuted(minimal) < tasks_reexecuted(full)
    on the acceptance DAG — BOTH A/B lines: the PTG chain (recorded-
    lineage plan) and the DTD chain (insert-stream skip agreement) —
    with each leg provably taking its intended path."""
    try:
        ab = run_ab_pair(timeout=timeout)
    except RuntimeError as exc:
        print(f"[FAIL] ab-minimal: {exc}")
        return 1
    ok = (ab["minimal"]["minimal"] >= 1 and ab["minimal"]["full"] == 0
          and ab["full"]["full"] >= 1
          and ab["minimal"]["reexec"] < ab["full"]["reexec"])
    status = "PASS" if ok else "FAIL"
    print(f"[{status}] ab-minimal: minimal re-executed "
          f"{ab['minimal']['reexec']} vs full {ab['full']['reexec']} "
          f"task(s) on the same kill "
          f"(paths: minimal={ab['minimal']['minimal']}/"
          f"{ab['minimal']['full']}, full={ab['full']['minimal']}/"
          f"{ab['full']['full']}; makespans "
          f"{ab['minimal']['makespan_s']}s vs "
          f"{ab['full']['makespan_s']}s)")
    rc = 0 if ok else 1
    try:
        dab = run_ab_pair_dtd(timeout=timeout)
    except RuntimeError as exc:
        print(f"[FAIL] ab-minimal-dtd: {exc}")
        return 1
    dok = (dab["minimal"]["minimal"] >= 1
           and dab["minimal"]["full"] == 0
           and dab["minimal"]["skip"] >= 1
           and dab["full"]["full"] >= 1
           and dab["minimal"]["reexec"] < dab["full"]["reexec"])
    status = "PASS" if dok else "FAIL"
    print(f"[{status}] ab-minimal-dtd: skip-agreed replay re-executed "
          f"{dab['minimal']['reexec']} vs full "
          f"{dab['full']['reexec']} task(s) on the same kill "
          f"(paths: minimal={dab['minimal']['minimal']}/"
          f"{dab['minimal']['full']} skip={dab['minimal']['skip']}, "
          f"full={dab['full']['minimal']}/{dab['full']['full']}; "
          f"makespans {dab['minimal']['makespan_s']}s vs "
          f"{dab['full']['makespan_s']}s)")
    return rc or (0 if dok else 1)

#: (name, plan template, workload, expected outcome, extra env).
#: {s} is the seed.  Expected outcomes:
#:   complete     both ranks return "ok" (numbers validated in-worker)
#:   peer-failed  >= 1 rank reports a structured PeerFailedError
#:   task-failed  >= 1 rank reports TaskRetryExhausted
CATALOG = [
    ("delay-frames",
     "seed={s};delay_frame=tag:ACT,p=0.4,ms=40;"
     "delay_frame=tag:DTD,p=0.4,ms=40",
     "dtd", "complete", {}),
    ("delay-v0",
     "seed={s};delay_frame=tag:DTD,pm='ver': 0,ms=800",
     "dtd", "complete", {}),
    ("delay-recv",
     # RECEIVE-side holds: frames arrive in TCP order but dispatch out
     # of order (utils/faultinject delay_recv) — the reorder coverage
     # send-side delays cannot reach.  The DTD lane landing-order
     # guards and versioned surrogates must still converge exactly.
     "seed={s};delay_recv=tag:DTD,p=0.5,ms=150;"
     "delay_recv=tag:ACT,p=0.3,ms=80",
     "dtd", "complete", {}),
    ("dup-frames",
     "seed={s};dup_frame=tag:ACT,p=0.5;dup_frame=tag:DTD,p=0.5",
     "dtd", "complete", {}),
    ("dup-potrf",
     "seed={s};dup_frame=tag:ACT,p=0.5;dup_frame=tag:GET_REQ,p=0.5",
     "potrf", "complete", {}),
    ("drop-getrep",
     "seed={s};drop_frame=tag:GET_REP,p=0.5,n=3",
     "potrf", "complete",
     {"PARSEC_MCA_COMM_EAGER_LIMIT": "512",
      "PARSEC_MCA_COMM_ADAPTIVE_EAGER": "0",
      "PARSEC_MCA_COMM_RDV_RETRY_S": "0.5"}),
    ("trunc-act",
     "seed={s};trunc_frame=tag:ACT,n=1",
     "potrf", "peer-failed", {}),
    ("kill-close",
     "seed={s};kill_rank=1@t+1.2s,mode=close;"
     "delay_frame=tag:DTD,p=1,ms=60",
     "dtd", "peer-failed", {"PARSEC_CHAOS_WAIT_S": "30"}),
    ("kill-hang",
     "seed={s};kill_rank=1@t+1.2s,mode=hang;"
     "delay_frame=tag:DTD,p=1,ms=60",
     "dtd", "peer-failed",
     {"PARSEC_CHAOS_WAIT_S": "25",
      "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "2"}),
    ("fail-task-retry",
     "seed={s};fail_task=p=0.25,n=6",
     "potrf", "complete", {"PARSEC_MCA_TASK_RETRY_MAX": "8"}),
    ("fail-task-exhaust",
     "seed={s};fail_task=key~POTRF(k=0),n=3",
     "potrf", "task-failed", {"PARSEC_MCA_TASK_RETRY_MAX": "1"}),
    # shm-transport legs (r11): the ring transport must produce the
    # SAME structured detectors and containment as TCP — hard kill
    # (closed-ring EOF path), silent hang (heartbeat-timeout path),
    # and recv-side reorder holds hooking the ring's dispatch
    ("kill-close-shm",
     "seed={s};kill_rank=1@t+1.2s,mode=close;"
     "delay_frame=tag:DTD,p=1,ms=60",
     "dtd", "peer-failed",
     {"PARSEC_CHAOS_WAIT_S": "30",
      "PARSEC_MCA_COMM_TRANSPORT": "shm"}),
    ("kill-hang-shm",
     "seed={s};kill_rank=1@t+1.2s,mode=hang;"
     "delay_frame=tag:DTD,p=1,ms=60",
     "dtd", "peer-failed",
     {"PARSEC_CHAOS_WAIT_S": "25",
      "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "2",
      "PARSEC_MCA_COMM_TRANSPORT": "shm"}),
    ("delay-recv-shm",
     "seed={s};delay_recv=tag:DTD,p=0.5,ms=150;"
     "delay_recv=tag:ACT,p=0.3,ms=80",
     "dtd", "complete", {"PARSEC_MCA_COMM_TRANSPORT": "shm"}),
    # RECOVERY legs (r12): kill_rank plans that END IN COMPLETED JOBS
    # with correct numerics — the surviving rank re-maps the dead
    # rank's partition onto itself, restores the lineage base, and
    # re-executes; the killed rank's own (expected) failure is
    # tolerated by the harness (_TOLERATE).  recovery off reproduces
    # the kill-close/kill-hang containment entries above exactly.
    ("kill-close-recover",
     "seed={s};kill_rank=1@t+1.0s,mode=close;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "45",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-hang-recover",
     "seed={s};kill_rank=1@t+1.0s,mode=hang;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "25",
      "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "2",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-dtd-recover",
     "seed={s};kill_rank=1@t+1.2s,mode=close;"
     "delay_frame=tag:DTD,p=1,ms=60",
     "dtd-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "30",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-close-recover-shm",
     "seed={s};kill_rank=1@t+1.0s,mode=close;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "45",
      "PARSEC_MCA_COMM_TRANSPORT": "shm",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-close-recover-threads",
     "seed={s};kill_rank=1@t+1.0s,mode=close;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "45",
      "PARSEC_MCA_COMM_TRANSPORT": "threads",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-hang-recover-shm",
     "seed={s};kill_rank=1@t+1.0s,mode=hang;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "25",
      "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "2",
      "PARSEC_MCA_COMM_TRANSPORT": "shm",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    ("kill-hang-recover-threads",
     "seed={s};kill_rank=1@t+1.0s,mode=hang;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "25",
      "PARSEC_MCA_COMM_PEER_TIMEOUT_S": "2",
      "PARSEC_MCA_COMM_TRANSPORT": "threads",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    # minimal replay (r13): the deterministic A/B chain DAG, where the
    # survivor MUST take the recorded-lineage minimal path — the
    # workload raises if recovery silently fell back to full replay
    # (the quantitative minimal<full check is chaos --ab-minimal)
    ("kill-minimal-recover",
     "seed={s};kill_rank=1@t+1.0s,mode=close;"
     "delay_dispatch=key~W(,ms=100",
     "ab-chain-minimal", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "45",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    # DTD skip agreement (r15): a 3-rank DTD chain kill down the
    # cross-rank skip-agreement path — two survivors agree the
    # skippable insert prefix OVER THE WIRE and the workload RAISES
    # if recovery silently fell back to the full insert-stream replay
    # (the quantitative minimal<full check is chaos --ab-minimal's
    # second A/B line)
    ("kill-dtd-minimal",
     "seed={s};kill_rank=1@t+2.0s,mode=close;"
     "delay_dispatch=key~_dtd_chain_step,ms=100",
     "dtd-minimal", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "45", "_NRANKS": "3",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    # dyn-hold recovery (r13): a DynamicTaskpool killed with its
    # distributed termination hold outstanding restarts on the survivor
    # with the hold RE-ARMED (previously stranded across the restart)
    ("kill-dyn-recover",
     "seed={s};kill_rank=1@t+0.8s,mode=close;"
     "delay_frame=tag:ACT,p=1,ms=150;delay_frame=tag:BATCH,p=1,ms=150",
     "dyn-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "40",
      "PARSEC_MCA_RECOVERY_ENABLE": "1", "_TOLERATE": "1"}),
    # dead-set agreement (r13): two NEAR-SIMULTANEOUS deaths on a
    # 4-rank gang — the two survivors must converge on one confirmed
    # dead set (coordinator broadcast) and complete with validated
    # numerics instead of transiently divergent translation tables
    ("multi-death-agreement",
     "seed={s};kill_rank=2@t+1.0s,mode=close;"
     "kill_rank=3@t+1.05s,mode=close;"
     "delay_frame=tag:ACT,p=1,ms=120;delay_frame=tag:BATCH,p=1,ms=120",
     "potrf-recover", "recovered",
     {"PARSEC_CHAOS_WAIT_S": "60", "_NRANKS": "4", "_TOLERATE": "2,3",
      "PARSEC_MCA_RECOVERY_ENABLE": "1",
      "PARSEC_MCA_RECOVERY_MAX_ATTEMPTS": "3"}),
    # survivor exhaustion: a second kill past the recovery budget must
    # end in a CLEAN structured failure, never a loop or a hang
    ("double-kill",
     "seed={s};kill_rank=1@t+1.0s,mode=close;kill_rank=2@t+2.0s,"
     "mode=close;delay_frame=tag:ACT,p=1,ms=150;"
     "delay_frame=tag:BATCH,p=1,ms=150",
     "potrf-recover", "peer-failed",
     {"PARSEC_CHAOS_WAIT_S": "30", "_NRANKS": "3",
      "PARSEC_MCA_RECOVERY_ENABLE": "1",
      "PARSEC_MCA_RECOVERY_MAX_ATTEMPTS": "1"}),
]

_QUICK = ("delay-v0", "delay-recv", "kill-close", "fail-task-retry",
          "kill-close-shm", "delay-recv-shm", "kill-close-recover",
          "kill-dtd-recover")

_RECOVER = ("kill-close-recover", "kill-hang-recover",
            "kill-dtd-recover", "kill-close-recover-shm",
            "kill-close-recover-threads", "kill-hang-recover-shm",
            "kill-hang-recover-threads", "double-kill",
            "kill-minimal-recover", "kill-dyn-recover",
            "kill-dtd-minimal", "multi-death-agreement")

_CHAOS_ENV = ("PARSEC_MCA_FAULT_PLAN", "PARSEC_CHAOS_WAIT_S",
              "PARSEC_MCA_COMM_PEER_TIMEOUT_S",
              "PARSEC_MCA_TASK_RETRY_MAX",
              "PARSEC_MCA_COMM_EAGER_LIMIT",
              "PARSEC_MCA_COMM_ADAPTIVE_EAGER",
              "PARSEC_MCA_COMM_RDV_RETRY_S",
              "PARSEC_MCA_COMM_TRANSPORT",
              "PARSEC_MCA_RECOVERY_ENABLE",
              "PARSEC_MCA_RECOVERY_MAX_ATTEMPTS",
              "PARSEC_MCA_JOURNAL_DIR")


def _audit_journals(jdir: str):
    """Run the offline invariant auditor (tools/journal_audit.py) over
    one case's per-rank journal bundle.  Returns (violations, nevents)
    — a missing bundle reads as zero events, and the caller treats
    zero EVENTS (not just zero files) as a disarmed black box: a
    header-only dump must not let an audit pass vacuously."""
    from tools import journal_audit
    try:
        per_rank = journal_audit.load_bundle([jdir])
    except FileNotFoundError:
        return [], 0
    nevents = sum(len(s.get("events", ()))
                  for snaps in per_rank.values() for s in snaps)
    return journal_audit.audit(per_rank), nevents


def run_case(name, plan, workload, expect, env, timeout,
             audit_journal=False):
    """One seeded plan against one workload; returns (ok, outcome,
    detail).  Harness-private env keys: ``_NRANKS`` (gang size,
    default 2) and ``_TOLERATE`` (comma-separated ranks whose failure
    is the EXPECTED kill — recovery cases require the survivors to
    complete with validated numbers while the victim's own error is
    ignored).  ``audit_journal`` arms the control-plane journal for
    the run (PARSEC_MCA_JOURNAL_DIR, a fresh bundle per case) and
    runs tools/journal_audit.py over it afterwards: any invariant
    violation fails the case even if the workload outcome matched."""
    import shutil
    import tempfile

    from parsec_tpu.comm.launch import run_distributed

    env = dict(env)
    nranks = int(env.pop("_NRANKS", 2))
    tolerate = [int(r) for r in env.pop("_TOLERATE", "").split(",")
                if r != ""]
    saved = {k: os.environ.get(k) for k in _CHAOS_ENV}
    os.environ["PARSEC_MCA_FAULT_PLAN"] = plan
    os.environ.update(env)
    jdir = None
    if audit_journal:
        jdir = tempfile.mkdtemp(prefix="parsec-journal-")
        os.environ["PARSEC_MCA_JOURNAL_DIR"] = jdir
    try:
        try:
            res = run_distributed(WORKLOADS[workload], nranks,
                                  timeout=timeout,
                                  tolerate_ranks=tolerate)
            if expect == "recovered":
                # 'recovered' is OBSERVED, not assumed: the kill victim
                # must actually have died (its tolerated slot is None).
                # A run that outpaced its kill_rank trigger completed
                # WITHOUT exercising recovery and must not pass as if
                # it had
                killed = bool(tolerate) and \
                    all(res[r] is None for r in tolerate)
                outcome = "recovered" if killed else "complete"
            else:
                outcome = "complete"
            detail = repr(res)
        except TimeoutError as exc:
            # the harness deadline fired with ranks unreported: a HANG —
            # the invariant violation this tool exists to catch
            outcome, detail = "hang", str(exc)[:300]
        except RuntimeError as exc:
            # one structured failure commonly cascades (a rank failing
            # its pool tears its engine down, the PEER then reports the
            # death): classify by which structured markers appear, with
            # the EXPECTED one winning when present
            text = str(exc)
            found = [m for m, marker in
                     (("task-failed", "TaskRetryExhausted"),
                      ("peer-failed", "PeerFailedError"))
                     if marker in text]
            if expect in found:
                outcome = expect
            else:
                outcome = found[0] if found else "error"
            detail = text[:400]
    finally:
        for k, v in saved.items():
            if v is None:
                os.environ.pop(k, None)
            else:
                os.environ[k] = v
    ok = outcome == expect
    if jdir is not None:
        try:
            violations, jevents = _audit_journals(jdir)
            if ok and jevents == 0:
                # the run held its invariant but journaled ZERO
                # events: the black box was silently disarmed (env
                # not inherited, journal_enabled=0 leaked, dump path
                # broken) — an un-audited pass must not read as an
                # audited one
                ok = False
                outcome = f"{outcome}+journal-missing"
                detail = (f"zero journal events under {jdir} "
                          f"(journal disarmed?) | {detail}")
            if violations:
                ok = False
                outcome = f"{outcome}+journal-violations"
                detail = (f"journal audit ({jevents} event(s)): "
                          + "; ".join(violations[:6])
                          + (f" (+{len(violations) - 6} more)"
                             if len(violations) > 6 else "")
                          + f" | {detail}")
        except Exception as exc:   # the auditor must not mask the run
            ok = False
            outcome = f"{outcome}+journal-audit-error"
            detail = f"journal audit failed: {exc!r} | {detail}"
        shutil.rmtree(jdir, ignore_errors=True)
    return ok, outcome, detail


def run_soak(n: int, timeout: float) -> int:
    """``--soak N``: N RANDOMLY seeded schedules drawn from the recover
    catalog, each with the full per-run invariant checks (numerics
    validated in-worker, no hang, recovery OBSERVED when expected).
    The master seed and every (case, seed) pair are printed so any
    failure replays exactly:

        PARSEC_CHAOS_SOAK_SEED=<master> python tools/chaos.py --soak N
        # or one case: --only <case> --seeds 1 with the printed plan
    """
    import random
    master = int(os.environ.get("PARSEC_CHAOS_SOAK_SEED",
                                str(int(time.time()) % 1000000)))
    rng = random.Random(master)
    cases = [c for c in CATALOG if c[0] in _RECOVER]
    print(f"soak: {n} random recover schedules "
          f"(PARSEC_CHAOS_SOAK_SEED={master})")
    failures = 0
    for i in range(n):
        name, plan_t, wl, expect, env = rng.choice(cases)
        seed = rng.randrange(1, 1000000)
        plan = plan_t.format(s=seed)
        t0 = time.monotonic()
        ok, outcome, detail = run_case(name, plan, wl, expect, env,
                                       timeout)
        dt = time.monotonic() - t0
        status = "PASS" if ok else "FAIL"
        print(f"[{status}] soak {i + 1}/{n} {name:20s} seed={seed} "
              f"expect={expect} got={outcome} ({dt:.1f}s)", flush=True)
        if not ok:
            failures += 1
            print(f"       plan: {plan}", flush=True)
            print(f"       {detail}", flush=True)
    print(f"soak: {n - failures}/{n} random schedules held the "
          f"invariants (replay: PARSEC_CHAOS_SOAK_SEED={master})")
    return 1 if failures else 0


def main(argv=None):
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--seeds", type=int, default=12,
                    help="seeded plan runs (rotating over the catalog)")
    ap.add_argument("--soak", type=int, default=0, metavar="N",
                    help="N randomly seeded schedules from the recover "
                         "catalog with per-run invariant checks; "
                         "seeds printed for replay "
                         "(PARSEC_CHAOS_SOAK_SEED pins the draw)")
    ap.add_argument("--quick", action="store_true",
                    help="premerge smoke: only the quick catalog subset")
    ap.add_argument("--recover", action="store_true",
                    help="only the RECOVERY catalog subset: kill plans "
                         "that must end in COMPLETED jobs with correct "
                         "numerics (plus survivor exhaustion)")
    ap.add_argument("--timeout", type=float, default=90.0,
                    help="per-run harness deadline (hang detector)")
    ap.add_argument("--audit-journal", action="store_true",
                    help="arm the control-plane journal for every run "
                         "(PARSEC_MCA_JOURNAL_DIR per case) and run "
                         "tools/journal_audit.py over the bundle "
                         "afterwards — invariant violations fail the "
                         "case even when the workload outcome matched")
    ap.add_argument("--ab-minimal", action="store_true",
                    help="minimal-vs-full replay A/B on the acceptance "
                         "kill: asserts tasks_reexecuted(minimal) < "
                         "tasks_reexecuted(full) (the premerge leg)")
    ap.add_argument("--rejoin", default="",
                    help="run the kill->restart->TAG_REJOIN scenario "
                         "on one transport (threads/evloop/shm)")
    ap.add_argument("--degrade", action="store_true",
                    help="run the seeded degrade -> drain-before-death "
                         "scenario: a ramped slowdown on rank 1 must "
                         "trigger a journaled, evidence-carrying "
                         "pre-emptive fabric drain STRICTLY before the "
                         "heartbeat detector fires, and the offline "
                         "audit (incl. the H1 health invariant) must "
                         "replay clean")
    ap.add_argument("--degrade-seed", type=int, default=7,
                    help="seed of the degrade ramp's jitter stream")
    ap.add_argument("--only", default="",
                    help="comma-separated catalog entry names")
    ap.add_argument("--transport", default="",
                    help="force every case onto one transport "
                         "(threads/evloop/shm) — runs the whole "
                         "catalog against it")
    ap.add_argument("--list", action="store_true")
    args = ap.parse_args(argv)

    if args.soak:
        return run_soak(args.soak, args.timeout)
    if args.ab_minimal:
        return run_ab_minimal(timeout=args.timeout)
    if args.rejoin:
        ok, detail = rejoin_scenario(args.rejoin,
                                     timeout=max(args.timeout, 150.0))
        print(f"[{'PASS' if ok else 'FAIL'}] rejoin-{args.rejoin}: "
              f"{detail}")
        return 0 if ok else 1
    if args.degrade:
        ok, detail = degrade_scenario(seed=args.degrade_seed,
                                      timeout=max(args.timeout, 120.0))
        print(f"[{'PASS' if ok else 'FAIL'}] degrade-drain: "
              f"{detail[:400]}")
        return 0 if ok else 1

    catalog = CATALOG
    if args.quick:
        catalog = [c for c in CATALOG if c[0] in _QUICK]
    if args.recover:
        catalog = [c for c in CATALOG if c[0] in _RECOVER]
    if args.only:
        keep = set(args.only.split(","))
        catalog = [c for c in CATALOG if c[0] in keep]
    if args.transport:
        catalog = [(n, p, wl, ex,
                    {**env, "PARSEC_MCA_COMM_TRANSPORT": args.transport})
                   for n, p, wl, ex, env in catalog]
    if args.list:
        for name, plan, wl, expect, env in catalog:
            print(f"{name:20s} [{wl}] expect={expect}  {plan}")
        return 0

    failures = 0
    for i in range(args.seeds):
        name, plan_t, wl, expect, env = catalog[i % len(catalog)]
        plan = plan_t.format(s=i + 1)
        t0 = time.monotonic()
        ok, outcome, detail = run_case(name, plan, wl, expect, env,
                                       args.timeout,
                                       audit_journal=args.audit_journal)
        dt = time.monotonic() - t0
        status = "PASS" if ok else "FAIL"
        print(f"[{status}] seed={i + 1} {name:20s} [{wl}] "
              f"expect={expect} got={outcome} ({dt:.1f}s)", flush=True)
        if not ok:
            failures += 1
            print(f"       {detail}", flush=True)
    total = args.seeds
    if args.recover:
        # the rejoin leg rides the recover acceptance run: shm was the
        # one transport that could not rejoin before the ring
        # re-creation landed (comm/shm.py)
        total += 1
        t0 = time.monotonic()
        ok, detail = rejoin_scenario("shm",
                                     timeout=max(args.timeout, 150.0))
        dt = time.monotonic() - t0
        print(f"[{'PASS' if ok else 'FAIL'}] rejoin-shm ({dt:.1f}s)",
              flush=True)
        if not ok:
            failures += 1
            print(f"       {detail[:400]}", flush=True)
    print(f"chaos: {total - failures}/{total} plans held the "
          "no-hang invariant")
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
