#!/usr/bin/env python
"""Summarize a binary trace (.ptt) — the dbpinfos analog.

Reference: tools/profiling/dbpreader.c + dbpinfos — dump a trace's
header, dictionary, per-stream event counts, and per-event-class timing
statistics.  Usage:

    python tools/trace_info.py run.ptt [--events] [--stats]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help=".ptt trace file")
    ap.add_argument("--events", action="store_true",
                    help="dump every event row")
    ap.add_argument("--stats", action="store_true",
                    help="per-class interval timing statistics")
    args = ap.parse_args(argv)

    from parsec_tpu.prof.reader import intervals, read_trace
    meta, df = read_trace(args.trace)

    print(f"trace: {args.trace}")
    print(f"hr_id: {meta['hr_id']}")
    for k, v in sorted(meta.get("info", {}).items()):
        print(f"info : {k} = {v}")
    print(f"dictionary ({len(meta['dictionary'])} classes):")
    for key, name, attrs in meta["dictionary"]:
        print(f"  [{key:3d}] {name}{'  ' + attrs if attrs else ''}")
    print(f"streams ({len(meta['streams'])}):")
    for sid, name, nev in meta["streams"]:
        print(f"  [{sid:3d}] {name or '<unnamed>'}: {nev} events")
    print(f"total events: {len(df)}")

    if args.events:
        print(df.to_string())
    if args.stats and len(df):
        iv = intervals(df)
        if len(iv):
            g = iv.groupby("name")["duration"]
            print("per-class interval stats (seconds):")
            print(g.agg(["count", "sum", "mean", "min", "max"])
                  .to_string(float_format=lambda v: f"{v:.6f}"))
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
