#!/usr/bin/env python
"""Summarize a binary trace (.ptt) — the dbpinfos analog.

Reference: tools/profiling/dbpreader.c + dbpinfos — dump a trace's
header, dictionary, per-stream event counts, and per-event-class timing
statistics.  Usage:

    python tools/trace_info.py run.ptt [--events] [--stats]
"""

from __future__ import annotations

import argparse
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _causal_stats(meta, df, iv) -> None:
    """The r7 --stats columns: per-class queue-wait (the causal
    tracer's ready->select spans joined to exec intervals by object id)
    and per-source comm delay (comm_recv arrival minus the sender's
    embedded clock stamp, corrected by this rank's measured offset to
    that peer when the header carries one)."""
    import json as _json
    qw = iv[iv["name"] == "queue_wait"]
    ex = iv[(iv["name"] != "queue_wait")
            & ~iv["name"].str.startswith("dev:")]
    if len(qw) and len(ex):
        # task identity is (taskpool, key hash): a warmup pool reruns
        # the same task keys, and an object_id-only join would pair its
        # spans with the main pool's
        j = ex[["name", "taskpool_id", "object_id"]].merge(
            qw[["taskpool_id", "object_id", "duration"]],
            on=["taskpool_id", "object_id"])
        if len(j):
            print("per-class queue-wait (seconds, ready -> selected):")
            print(j.groupby("name")["duration"]
                  .agg(["count", "mean", "max"])
                  .to_string(float_format=lambda v: f"{v:.6f}"))
    rx = df[df["name"] == "comm_recv"]
    if len(rx):
        try:
            offsets = {int(r): float(o) for r, o in _json.loads(
                meta.get("info", {}).get("clock_offsets", "{}")).items()}
        except (TypeError, ValueError):
            offsets = {}
        rows = {}
        for row in rx.itertuples():
            info = row.info or {}
            sent, src = info.get("sent_at"), info.get("src")
            if sent is None or src is None:
                continue
            # sent_at is on the SENDER's clock; offset = clock_src -
            # clock_mine, so the local-clock send time is sent - offset
            delay = row.ts - (sent - offsets.get(src, 0.0))
            rows.setdefault(src, []).append(delay)
        if rows:
            print("comm delay by source rank (seconds, send -> recv"
                  + ("" if offsets else "; UNCORRECTED clocks") + "):")
            for src in sorted(rows):
                d = rows[src]
                print(f"  from rank {src}: n={len(d)} "
                      f"mean={sum(d) / len(d):.6f} "
                      f"max={max(d):.6f}")


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__)
    ap.add_argument("trace", help=".ptt trace file")
    ap.add_argument("--events", action="store_true",
                    help="dump every event row")
    ap.add_argument("--stats", action="store_true",
                    help="per-class interval timing statistics")
    ap.add_argument("--gaps", action="store_true",
                    help="per-stream occupancy: busy/span/utilization and "
                         "idle-gap statistics (dbpinfos' occupancy view)")
    args = ap.parse_args(argv)

    from parsec_tpu.prof.reader import intervals, read_trace
    meta, df = read_trace(args.trace)

    print(f"trace: {args.trace}")
    print(f"hr_id: {meta['hr_id']}")
    for k, v in sorted(meta.get("info", {}).items()):
        print(f"info : {k} = {v}")
    print(f"dictionary ({len(meta['dictionary'])} classes):")
    for entry in meta["dictionary"]:
        # tolerate entries with extra (future) fields beyond
        # (key, name, attrs)
        key, name = entry[0], entry[1]
        attrs = entry[2] if len(entry) > 2 else ""
        print(f"  [{key:3d}] {name}{'  ' + str(attrs) if attrs else ''}")
    print(f"streams ({len(meta['streams'])}):")
    for sid, name, nev in meta["streams"]:
        print(f"  [{sid:3d}] {name or '<unnamed>'}: {nev} events")
    print(f"total events: {len(df)}")

    if args.events:
        print(df.to_string())
    if args.stats and len(df):
        iv = intervals(df)
        if len(iv):
            g = iv.groupby("name")["duration"]
            print("per-class interval stats (seconds):")
            print(g.agg(["count", "sum", "mean", "min", "max"])
                  .to_string(float_format=lambda v: f"{v:.6f}"))
            _causal_stats(meta, df, iv)
    if args.gaps and len(df):
        iv = intervals(df)
        if len(iv):
            print("per-stream occupancy:")
            for sid, rows in iv.groupby("stream"):
                spans = sorted(zip(rows["ts_begin"], rows["ts_end"]))
                span = max(e for _b, e in spans) - spans[0][0]
                busy = sum(e - b for b, e in spans)
                gaps, largest, cursor = 0.0, 0.0, spans[0][0]
                for b, e in spans:
                    if b > cursor:
                        gaps += b - cursor
                        largest = max(largest, b - cursor)
                    cursor = max(cursor, e)
                util = busy / span if span > 0 else 1.0
                print(f"  stream {sid}: {len(spans)} intervals, "
                      f"busy {busy:.6f}s / span {span:.6f}s "
                      f"(util {util:.1%}), idle {gaps:.6f}s "
                      f"(largest gap {largest:.6f}s)")
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
