"""parseclint driver: file discovery, per-file parallel analysis,
tree-level cross-checks, baseline filtering, reporting."""

from __future__ import annotations

import os
import sys
from typing import Dict, Iterable, List, Optional, Tuple

from tools.parseclint import FileCtx, Finding
from tools.parseclint.passes import ALL_PASSES

#: repo root = the directory holding tools/ (baseline + doc paths and
#: repo-relative finding paths anchor here, independent of cwd)
REPO_ROOT = os.path.dirname(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))

DEFAULT_BASELINE = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                                "baseline.txt")

_SKIP_DIRS = frozenset(("__pycache__", ".git", "parseclint"))


def discover(paths: Iterable[str]) -> List[str]:
    out: List[str] = []
    for p in paths:
        p = os.path.abspath(p)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in sorted(dirnames)
                           if d not in _SKIP_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def analyze_file(path: str):
    """One file through every per-file pass; returns (rel, findings,
    {pass_id: facts}, comment-view, error-or-empty).  Runs in worker
    processes; the comment view rides back so the driver's tree-level
    passes never re-parse the file."""
    rel = os.path.relpath(path, REPO_ROOT)
    try:
        with open(path, encoding="utf-8") as fh:
            source = fh.read()
        ctx = FileCtx(path, rel, source)
    except (OSError, SyntaxError, ValueError) as exc:
        return rel, [], {}, None, f"{rel}: unparseable: {exc}"
    findings: List[Finding] = []
    facts: Dict[str, dict] = {}
    for mod in ALL_PASSES:
        check = getattr(mod, "check", None)
        if check is not None:
            findings.extend(check(ctx))
        fact_fn = getattr(mod, "facts", None)
        if fact_fn is not None:
            facts[mod.PASS_ID] = fact_fn(ctx)
    return rel, findings, facts, ctx.comment_view(), ""


def _analyze_parallel(files: List[str], jobs: int):
    if jobs <= 1 or len(files) < 8:
        return [analyze_file(f) for f in files]
    try:
        import multiprocessing as mp
        from concurrent.futures import ProcessPoolExecutor
        mp_ctx = mp.get_context("fork") if hasattr(os, "fork") else None
        with ProcessPoolExecutor(max_workers=jobs,
                                 mp_context=mp_ctx) as pool:
            return list(pool.map(analyze_file, files, chunksize=4))
    except Exception:
        # any pool failure (sandbox, recursion in spawn) degrades to
        # serial — the analysis result must not depend on the executor
        return [analyze_file(f) for f in files]


def load_baseline(path: str) -> Dict[str, int]:
    """baseline key -> allowed count (a key listed N times admits N
    findings with that identity)."""
    out: Dict[str, int] = {}
    if not path or not os.path.exists(path):
        return out
    with open(path, encoding="utf-8") as fh:
        for line in fh:
            line = line.strip()
            if line and not line.startswith("#"):
                out[line] = out.get(line, 0) + 1
    return out


def run(paths: Iterable[str], baseline_path: Optional[str] = None,
        jobs: Optional[int] = None,
        use_processes: bool = True) -> Tuple[List[Finding], List[Finding], List[str]]:
    """Analyze ``paths``; returns (new_findings, baselined, errors)."""
    files = discover(paths)
    njobs = jobs if jobs is not None else min(8, os.cpu_count() or 1)
    if not use_processes:
        njobs = 1
    results = _analyze_parallel(files, njobs)

    findings: List[Finding] = []
    errors: List[str] = []
    all_facts: Dict[str, List[dict]] = {}
    ctxs: Dict[str, object] = {}   # rel -> CommentView (from the workers)
    for rel, per_file, facts, view, err in results:
        findings.extend(per_file)
        if err:
            errors.append(err)
        if view is not None:
            ctxs[rel] = view
        for pid, fx in facts.items():
            all_facts.setdefault(pid, []).append(fx)

    for mod in ALL_PASSES:
        tree_check = getattr(mod, "tree_check", None)
        if tree_check is not None:
            findings.extend(tree_check(all_facts.get(mod.PASS_ID, []),
                                       REPO_ROOT, ctxs))

    findings.sort(key=lambda f: (f.path, f.line, f.pass_id))
    allowed = load_baseline(baseline_path if baseline_path is not None
                            else DEFAULT_BASELINE)
    new: List[Finding] = []
    baselined: List[Finding] = []
    for f in findings:
        key = f.baseline_key()
        if allowed.get(key, 0) > 0:
            allowed[key] -= 1
            baselined.append(f)
        else:
            new.append(f)
    return new, baselined, errors


def write_baseline(findings: List[Finding], path: str) -> None:
    with open(path, "w", encoding="utf-8") as fh:
        fh.write("# parseclint baseline: accepted pre-existing findings"
                 " (line-number-free keys).\n"
                 "# Regenerate with: python -m tools.parseclint"
                 " --write-baseline <paths>\n")
        for f in findings:
            fh.write(f.baseline_key() + "\n")


def main(argv: Optional[List[str]] = None) -> int:
    import argparse
    ap = argparse.ArgumentParser(
        prog="parseclint",
        description="project-specific static analysis for parsec_tpu")
    ap.add_argument("paths", nargs="*", default=["parsec_tpu"],
                    help="files/directories to analyze "
                         "(default: parsec_tpu)")
    ap.add_argument("--baseline", default=None,
                    help=f"baseline file (default: {DEFAULT_BASELINE})")
    ap.add_argument("--no-baseline", action="store_true",
                    help="report every finding, baseline ignored")
    ap.add_argument("--write-baseline", action="store_true",
                    help="accept all current findings into the baseline")
    ap.add_argument("--jobs", type=int, default=None,
                    help="parallel analysis processes (default: auto)")
    ap.add_argument("--serial", action="store_true",
                    help="single-process analysis (debugging)")
    ap.add_argument("-q", "--quiet", action="store_true")
    ap.add_argument("--list-passes", action="store_true")
    args = ap.parse_args(argv)

    if args.list_passes:
        for mod in ALL_PASSES:
            doc = (mod.__doc__ or "").strip().splitlines()[0]
            print(f"{mod.PASS_ID:12s} {doc}")
        return 0

    paths = args.paths or ["parsec_tpu"]
    paths = [p if os.path.isabs(p) else
             (p if os.path.exists(p) else os.path.join(REPO_ROOT, p))
             for p in paths]
    files = discover(paths)   # once; run() passes file paths through
    baseline = "" if args.no_baseline else args.baseline
    new, baselined, errors = run(files, baseline_path=baseline,
                                 jobs=args.jobs,
                                 use_processes=not args.serial)

    if args.write_baseline:
        target = args.baseline or DEFAULT_BASELINE
        write_baseline(new + baselined, target)
        print(f"parseclint: wrote {len(new) + len(baselined)} finding(s)"
              f" to {target}")
        return 0

    for f in new:
        print(f.render())
    for e in errors:
        print(f"parseclint: ERROR {e}", file=sys.stderr)
    if not args.quiet:
        note = f", {len(baselined)} baselined" if baselined else ""
        status = "clean" if not new else f"{len(new)} finding(s)"
        print(f"parseclint: {status}{note} ({len(files)} files)",
              file=sys.stderr)
    return 1 if (new or errors) else 0
