"""parseclint — project-specific static analysis for parsec_tpu.

Encodes the runtime's concurrency, aliasing, and knob invariants as
AST-level passes (stdlib ``ast`` only, no dependencies).  Each pass
corresponds to a bug class this repo has actually shipped and fixed:

  PCL-LOCK    ``# guarded-by:`` lock discipline on shared mutable state
  PCL-EVLOOP  blocking calls reachable from event-loop callbacks
  PCL-ALIAS   raw ``jax.device_put``/``jnp.asarray`` stage-ins that can
              alias a host buffer (the geqrf wrong-R class)
  PCL-MCA     MCA knob drift: unregistered reads, unread registrations,
              default mismatches, env/doc typos
  PCL-EXCEPT  containment-path exception hygiene (PeerFailedError must
              stay per-pool, never swallowed or context-global)
  PCL-ASSERT  asserts ``python -O`` would strip: side-effecting
              conditions and module-level (import-time) invariants

Run:        python -m tools.parseclint parsec_tpu/
Suppress:   trailing ``# lint: ignore[PCL-XXX] reason`` on the flagged
            line (or the line above), or record the finding in
            tools/parseclint/baseline.txt.
Annotate:   see each pass module's docstring for its source-level
            annotation conventions (guarded-by / holds-lock / on-loop /
            off-loop / alias-wrapper and the per-pass waivers).
"""

from __future__ import annotations

import ast
import io
import re
import tokenize
from dataclasses import dataclass
from typing import Dict, List, Optional

_IGNORE_RE = re.compile(r"lint:\s*ignore(?:\[([A-Z0-9, -]+)\])?")


@dataclass(frozen=True)
class Finding:
    """One structured finding: ``{path}:{line}: {pass_id} {message}``."""

    path: str        # repo-relative
    line: int
    pass_id: str
    message: str

    def render(self) -> str:
        return f"{self.path}:{self.line}: {self.pass_id} {self.message}"

    def baseline_key(self) -> str:
        """Line-number-free identity so accepted findings survive
        unrelated edits shifting line numbers."""
        return f"{self.path}|{self.pass_id}|{self.message}"


class _CommentLookup:
    """Suppression/annotation lookups over a comment map.  Subclasses
    provide ``comments`` ({line: text}) and ``_comment_lines`` (line
    numbers that are comment-ONLY lines)."""

    comments: Dict[int, str]
    _comment_lines: frozenset

    def comment_near(self, line: int) -> str:
        """The comment text attached to ``line``: trailing, or the
        directly preceding comment-only line."""
        parts = []
        if line in self.comments:
            parts.append(self.comments[line])
        prev = line - 1
        if prev in self.comments and prev in self._comment_lines:
            parts.append(self.comments[prev])
        return " ".join(parts)

    def ignored(self, line: int, pass_id: str) -> bool:
        m = _IGNORE_RE.search(self.comment_near(line))
        if not m:
            return False
        ids = m.group(1)
        return ids is None or pass_id in {s.strip()
                                          for s in ids.split(",")}

    def has_marker(self, line: int, marker: str) -> bool:
        """True when ``line`` (or the comment line above) carries the
        given ``lint: <marker>`` waiver/annotation."""
        return f"lint: {marker}" in self.comment_near(line)

    def comment_block_above(self, line: int, span: int = 6) -> str:
        """The contiguous comment block ending just above ``line`` —
        where ``#:`` attribute doc-comments (and their ``guarded-by:``
        annotations) live."""
        parts: List[str] = []
        ln = line - 1
        while ln > 0 and ln >= line - span and ln in self.comments \
                and ln in self._comment_lines:
            parts.append(self.comments[ln])
            ln -= 1
        return " ".join(reversed(parts))


class CommentView(_CommentLookup):
    """Picklable comment/suppression view — the subset of FileCtx the
    driver's tree-level passes need, shipped back from analysis workers
    so the driver never re-parses a file."""

    def __init__(self, comments: Dict[int, str], comment_lines):
        self.comments = comments
        self._comment_lines = frozenset(comment_lines)


class FileCtx(_CommentLookup):
    """Everything a per-file pass needs: source, AST, and the comment
    map ``ast`` discards (annotations and waivers live in comments)."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel
        self.source = source
        self.lines = source.splitlines()
        self.tree = ast.parse(source, filename=path)
        self.comments: Dict[int, str] = {}
        try:
            for tok in tokenize.generate_tokens(
                    io.StringIO(source).readline):
                if tok.type == tokenize.COMMENT:
                    self.comments[tok.start[0]] = tok.string
        except tokenize.TokenError:
            pass   # ast.parse accepted it; comments stay best-effort
        self._comment_lines = frozenset(
            ln for ln in self.comments
            if self.lines[ln - 1].lstrip().startswith("#"))

    def comment_view(self) -> CommentView:
        return CommentView(self.comments, self._comment_lines)


def self_attr(node: ast.AST) -> Optional[str]:
    """``self.<name>`` -> name, else None."""
    if isinstance(node, ast.Attribute) and \
            isinstance(node.value, ast.Name) and node.value.id == "self":
        return node.attr
    return None
