"""PCL-LOCK — ``# guarded-by:`` lock discipline on shared mutable state.

Annotation convention (source-level, checked here):

* In ``__init__``, a shared mutable attribute carries a ``guarded-by:``
  comment naming the lock attribute(s) that protect it — trailing on the
  assignment, or in the ``#:`` doc-comment block directly above it::

      #: peer sockets, lazily dialed  (guarded-by: _plock)
      self._peers = {}
      self._bar_gen = 0   # guarded-by: _bar_cond

  Several alternatives (``guarded-by: _lock, _cond``) mean ANY of them
  suffices — the idiom for a Condition wrapping the same underlying
  lock.

* Every WRITE to an annotated attribute (assignment, augmented
  assignment, subscript store/delete, or a mutating method call such as
  ``.append``/``.pop``/``.clear``) outside the declaring ``__init__``
  must sit inside ``with self.<lock>:`` for one of the named locks.

* A method whose CALLER holds the lock declares it on its ``def`` line:
  ``def _apply_locked(self, ...):  # holds-lock: _apply_lock`` — its
  whole body is then treated as guarded.

Bug class: the PR 3-5 review rounds repeatedly re-found unlocked writes
to comm/termdet shared state (Safra counters, barrier generations,
handle tables) by eyeball; this pass makes the discipline mechanical.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, List, Optional, Set, Tuple

from tools.parseclint import FileCtx, Finding, self_attr

PASS_ID = "PCL-LOCK"

_GUARDED_RE = re.compile(r"guarded-by:\s*([A-Za-z_][\w]*(?:\s*,\s*[\w]+)*)")
_HOLDS_RE = re.compile(r"holds-lock:\s*([A-Za-z_][\w]*(?:\s*,\s*[\w]+)*)")

#: method names that mutate their receiver (write-through on the
#: annotated container itself)
_MUTATORS = frozenset((
    "append", "appendleft", "extend", "extendleft", "insert", "add",
    "discard", "remove", "pop", "popleft", "popitem", "clear", "update",
    "setdefault", "sort", "reverse",
))


def _names(m: "re.Match") -> Set[str]:
    return {s.strip() for s in m.group(1).split(",") if s.strip()}


def _collect_annotations(ctx: FileCtx, cls: ast.ClassDef) -> Dict[str, Set[str]]:
    """attr -> lock names, from guarded-by comments in ``__init__``."""
    out: Dict[str, Set[str]] = {}
    for fn in cls.body:
        if not (isinstance(fn, (ast.FunctionDef, ast.AsyncFunctionDef))
                and fn.name == "__init__"):
            continue
        for node in ast.walk(fn):
            if not isinstance(node, (ast.Assign, ast.AnnAssign)):
                continue
            targets = node.targets if isinstance(node, ast.Assign) \
                else [node.target]
            attrs = [a for a in (self_attr(t) for t in targets) if a]
            if not attrs:
                continue
            text = ctx.comment_near(node.lineno) + " " + \
                ctx.comment_block_above(node.lineno)
            m = _GUARDED_RE.search(text)
            if m:
                for a in attrs:
                    out.setdefault(a, set()).update(_names(m))
    return out


def _writes_of(stmt: ast.AST) -> List[Tuple[int, str, str]]:
    """(line, attr, kind) for every self-attribute write in ``stmt``
    itself (not recursing — the caller walks)."""
    hits: List[Tuple[int, str, str]] = []

    def target_attr(t: ast.AST) -> Optional[str]:
        # self.x = / self.x[...] =  (subscript store mutates the
        # container the annotation names)
        a = self_attr(t)
        if a is not None:
            return a
        if isinstance(t, ast.Subscript):
            return self_attr(t.value)
        return None

    if isinstance(stmt, ast.Assign):
        for t in stmt.targets:
            for el in (t.elts if isinstance(t, (ast.Tuple, ast.List))
                       else [t]):
                a = target_attr(el)
                if a:
                    hits.append((stmt.lineno, a, "write"))
    elif isinstance(stmt, (ast.AugAssign, ast.AnnAssign)):
        a = target_attr(stmt.target)
        if a:
            hits.append((stmt.lineno, a, "write"))
    elif isinstance(stmt, ast.Delete):
        for t in stmt.targets:
            a = target_attr(t)
            if a:
                hits.append((stmt.lineno, a, "del"))
    elif isinstance(stmt, ast.Call):
        f = stmt.func
        if isinstance(f, ast.Attribute) and f.attr in _MUTATORS:
            a = self_attr(f.value)
            if a:
                hits.append((stmt.lineno, a, f".{f.attr}()"))
    return hits


class _MethodChecker(ast.NodeVisitor):
    """Walk one method tracking the set of locks held via nested
    ``with self.<lock>:`` blocks."""

    def __init__(self, ctx: FileCtx, cls_name: str,
                 annotations: Dict[str, Set[str]], seed_locks: Set[str]):
        self.ctx = ctx
        self.cls_name = cls_name
        self.ann = annotations
        self.held: List[str] = list(seed_locks)
        self.findings: List[Finding] = []

    def visit_With(self, node: ast.With) -> None:
        got = []
        for item in node.items:
            a = self_attr(item.context_expr)
            if a is not None:
                got.append(a)
        self.held.extend(got)
        for stmt in node.body:
            self.visit(stmt)
        if got:
            del self.held[-len(got):]

    def generic_visit(self, node: ast.AST) -> None:
        for line, attr, kind in _writes_of(node):
            locks = self.ann.get(attr)
            if locks and not (locks & set(self.held)) \
                    and not self.ctx.ignored(line, PASS_ID):
                want = "' or 'with self.".join(sorted(locks))
                self.findings.append(Finding(
                    self.ctx.rel, line, PASS_ID,
                    f"{kind} to {self.cls_name}.{attr} outside "
                    f"'with self.{want}' (guarded-by annotation)"))
        super().generic_visit(node)


def check(ctx: FileCtx) -> List[Finding]:
    findings: List[Finding] = []
    classes: Dict[str, ast.ClassDef] = {
        n.name: n for n in ast.walk(ctx.tree)
        if isinstance(n, ast.ClassDef)}
    ann_by_class: Dict[str, Dict[str, Set[str]]] = {
        name: _collect_annotations(ctx, cls)
        for name, cls in classes.items()}

    def resolved(cls: ast.ClassDef) -> Dict[str, Set[str]]:
        """Own annotations plus same-file base classes' (a subclass
        writing base-annotated state obeys the base's discipline)."""
        out: Dict[str, Set[str]] = {}
        for base in cls.bases:
            if isinstance(base, ast.Name) and base.id in classes:
                out.update(resolved(classes[base.id]))
        out.update(ann_by_class.get(cls.name, {}))
        return out

    for cls in classes.values():
        ann = resolved(cls)
        if not ann:
            continue
        for fn in cls.body:
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "__init__":
                continue   # construction precedes sharing
            seed: Set[str] = set()
            m = _HOLDS_RE.search(ctx.comment_near(fn.lineno))
            if m:
                seed = _names(m)
            checker = _MethodChecker(ctx, cls.name, ann, seed)
            for stmt in fn.body:
                checker.visit(stmt)
            findings.extend(checker.findings)
    return findings
