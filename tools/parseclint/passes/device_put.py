"""PCL-ALIAS — raw ``jax.device_put``/``jnp.asarray`` stage-ins.

On the CPU client (virtual meshes, tests, the dryrun) ``jax.device_put``
of an aligned host buffer — and ``jnp.asarray`` of one — can silently
ALIAS the source instead of copying.  A later donation or in-place
update of either side then corrupts the other: the geqrf wrong-R root
cause, which escaped twice more after the first fix because new
stage-in sites kept calling the raw API.

Rule: in the device layer (``devices/``) and the ICI transport
(``comm/ici.py``), every ``jax.device_put(...)`` / ``jnp.asarray(...)``
call is a finding UNLESS

* it sits inside a sanctioned wrapper — a function whose ``def`` line
  carries ``# lint: alias-wrapper`` (``device_put_private`` and
  ``device_put_replicated_private`` in devices/xla.py, which probe the
  output buffer pointer and defensively copy on alias); or
* the call line carries ``# lint: private-ok (reason)`` — for sites
  that are alias-safe by construction (e.g. staging a freshly created
  ``jnp.zeros`` that cannot alias host state).

Everything else must go through ``device_put_private`` (point-to-point)
or ``device_put_replicated_private`` (sharded replication).
"""

from __future__ import annotations

import ast
from typing import List

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-ALIAS"

_SCOPED = ("devices/", "comm/ici.py")


def _in_scope(rel: str) -> bool:
    r = rel.replace("\\", "/")
    return any(s in r for s in _SCOPED)


def check(ctx: FileCtx) -> List[Finding]:
    if not _in_scope(ctx.rel):
        return []
    findings: List[Finding] = []

    def scan(node: ast.AST, wrapped: bool) -> None:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            wrapped = wrapped or ctx.has_marker(node.lineno,
                                                "alias-wrapper")
        if isinstance(node, ast.Call) and not wrapped:
            f = node.func
            if isinstance(f, ast.Attribute) and \
                    isinstance(f.value, ast.Name):
                mod, attr = f.value.id, f.attr
                hit = (mod == "jax" and attr == "device_put") or \
                      (mod == "jnp" and attr == "asarray")
                if hit and not ctx.ignored(node.lineno, PASS_ID) and \
                        not ctx.has_marker(node.lineno, "private-ok"):
                    findings.append(Finding(
                        ctx.rel, node.lineno, PASS_ID,
                        f"raw {mod}.{attr}() stage-in can alias the "
                        "host buffer (geqrf wrong-R class) — use "
                        "device_put_private / "
                        "device_put_replicated_private, or waive with "
                        "'lint: private-ok (reason)'"))
        for child in ast.iter_child_nodes(node):
            scan(child, wrapped)

    scan(ctx.tree, False)
    return findings
