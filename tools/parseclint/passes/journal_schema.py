"""PCL-JRNL — control-plane journal schema drift.

The journal (prof/journal.py) is only as auditable as its schema: the
offline invariant auditor (tools/journal_audit.py) groups events by
type and ROUND, so an emit whose type never entered the event-schema
table — or a round-scoped protocol emit that forgot its ``round=`` —
is an event the auditor silently cannot check.  That is the
schema-drift bug class this pass encodes, tree-wide:

* every ``journal.emit("<type>", ...)`` call (any receiver named
  ``jr``/``journal`` or an attribute access ending in ``.journal``,
  the repo's journal-handle convention) must pass a STRING LITERAL
  event type that appears in ``EVENT_SCHEMA``;
* every field the schema lists as required for that type must be
  passed as an explicit keyword — in particular ``round`` on every
  round-scoped emit (mode votes, skip offers/cuts, need rounds):
  an emit built from ``**kwargs`` hides exactly the drift this pass
  exists to catch;
* a computed (non-literal) event type is flagged too: the auditor
  and this pass can only reason about literals.

Scope-gated like PCL-MCA/PCL-PROM: the cross-check runs only when
``parsec_tpu/prof/journal.py`` (the schema's home) is in the scanned
set, so partial scans stay silent.
"""

from __future__ import annotations

import ast
from typing import Dict, List

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-JRNL"

SCHEMA_FILE = "parsec_tpu/prof/journal.py"

#: receiver names that mark a call as a journal emit (the repo
#: convention: ``jr = self.context.journal`` / ``context.journal``)
_JOURNAL_NAMES = frozenset(("jr", "jr2", "journal"))


def _is_journal_recv(node: ast.expr) -> bool:
    """Is this ``.emit``'s receiver a journal handle?  A bare name in
    the convention set, or any attribute chain ending in ``journal``
    (``self.context.journal``, ``ctx.journal``)."""
    if isinstance(node, ast.Name):
        return node.id in _JOURNAL_NAMES
    if isinstance(node, ast.Attribute):
        return node.attr == "journal"
    return False


def _schema_from_tree(tree: ast.AST) -> Dict[str, List[str]]:
    """Parse the EVENT_SCHEMA dict literal out of the schema module."""
    for node in ast.walk(tree):
        targets = []
        if isinstance(node, ast.Assign):
            targets = node.targets
        elif isinstance(node, ast.AnnAssign) and node.value is not None:
            targets = [node.target]
        else:
            continue
        if not any(isinstance(t, ast.Name) and t.id == "EVENT_SCHEMA"
                   for t in targets):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            continue
        schema: Dict[str, List[str]] = {}
        for k, v in zip(value.keys, value.values):
            if not (isinstance(k, ast.Constant)
                    and isinstance(k.value, str)):
                continue
            fields: List[str] = []
            if isinstance(v, (ast.Tuple, ast.List)):
                for el in v.elts:
                    if isinstance(el, ast.Constant) \
                            and isinstance(el.value, str):
                        fields.append(el.value)
            schema[k.value] = fields
        return schema
    return {}


def facts(ctx: FileCtx) -> Dict[str, list]:
    rel = ctx.rel.replace("\\", "/")
    out: Dict[str, list] = {"rel": rel, "emits": []}
    if rel == SCHEMA_FILE:
        out["schema"] = _schema_from_tree(ctx.tree)
    for node in ast.walk(ctx.tree):
        if not (isinstance(node, ast.Call)
                and isinstance(node.func, ast.Attribute)
                and node.func.attr == "emit"
                and _is_journal_recv(node.func.value)):
            continue
        etype = None
        literal = False
        if node.args:
            a0 = node.args[0]
            if isinstance(a0, ast.Constant) and isinstance(a0.value, str):
                etype = a0.value
                literal = True
        kwargs = [kw.arg for kw in node.keywords if kw.arg is not None]
        has_star = any(kw.arg is None for kw in node.keywords)
        out["emits"].append({"line": node.lineno, "type": etype,
                             "literal": literal, "kwargs": kwargs,
                             "star": has_star})
    return out


def tree_check(all_facts: List[Dict[str, list]], repo_root: str,
               ctxs: Dict[str, FileCtx]) -> List[Finding]:
    schema: Dict[str, List[str]] = {}
    seen_schema_file = False
    for fx in all_facts:
        if fx.get("rel") == SCHEMA_FILE:
            seen_schema_file = True
            schema = fx.get("schema") or {}
    if not seen_schema_file:
        return []   # partial scan: the schema universe is incomplete
    findings: List[Finding] = []

    def ignored(rel: str, line: int) -> bool:
        c = ctxs.get(rel)
        return c is not None and c.ignored(line, PASS_ID)

    for fx in all_facts:
        rel = fx.get("rel", "")
        if rel == SCHEMA_FILE or not rel.startswith("parsec_tpu/"):
            # the schema module's own docstrings/tests stay out; so do
            # tools/tests (their emits build corpus events on purpose)
            continue
        for em in fx.get("emits", ()):
            line = em["line"]
            if ignored(rel, line):
                continue
            if not em["literal"]:
                findings.append(Finding(
                    rel, line, PASS_ID,
                    "journal.emit with a non-literal event type — the "
                    "offline auditor can only check literal types in "
                    "EVENT_SCHEMA"))
                continue
            etype = em["type"]
            if etype not in schema:
                findings.append(Finding(
                    rel, line, PASS_ID,
                    f"journal.emit({etype!r}) is not in the "
                    "EVENT_SCHEMA table (prof/journal.py) — add the "
                    "type and its required fields so journal_audit "
                    "can attribute it"))
                continue
            required = schema[etype]
            missing = [f for f in required if f not in em["kwargs"]]
            if missing and em["star"]:
                # **kwargs MAY carry them, but hides the drift this
                # pass encodes: required fields must be explicit
                findings.append(Finding(
                    rel, line, PASS_ID,
                    f"journal.emit({etype!r}) passes required "
                    f"field(s) {missing} via **kwargs — make them "
                    "explicit keywords"))
            elif missing:
                what = ("round-scoped emit must carry round="
                        if "round" in missing else "missing required")
                findings.append(Finding(
                    rel, line, PASS_ID,
                    f"journal.emit({etype!r}) is missing required "
                    f"field(s) {missing} ({what}; see EVENT_SCHEMA)"))
    return findings
