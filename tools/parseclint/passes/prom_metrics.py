"""PCL-PROM — metric-family drift between the exporters and the docs.

The telemetry plane's contract with operators is the README/COMPONENTS
family tables: dashboards and alert rules are written against them.
PR 7 round 2 dropped ``parsec_tasks_enabled_total`` from the registry
(it violated counter monotonicity) — nothing reconciled the docs, and
a stale doc row pointing at a family no scrape serves (or a shipped
family no doc names) is exactly the silent drift class PCL-MCA
encodes for knobs.  This pass reconciles, tree-wide:

* every ``parsec_*`` metric-family string literal exported from
  ``prof/metrics.py`` / ``prof/liveattr.py`` (plain literals full-match
  ``parsec_[a-z0-9_]+``; f-string templates like
  ``f"parsec_comm_{key}_total"`` become ``parsec_comm_*_total``
  wildcards) must be mentioned in README.md or COMPONENTS.md — an
  exact mention, a family-prefix mention (``parsec_comm_``), or a
  wildcard-matching one all satisfy it;
* every doc token that CLAIMS to be a family — ``parsec_*`` ending in
  a series suffix (``_total``/``_seconds``/``_bytes``/``_count``) —
  must match an exported literal or wildcard (doc tokens without a
  series suffix are treated as prose prefixes and only checked in the
  export->doc direction, so reference-C symbol mentions like
  ``parsec_matrix_block_cyclic_kview`` stay out of scope).

Scope-gated like PCL-MCA: the cross-check only runs when every
exporter module that exists under the repo root was scanned, so a
subtree scan stays silent instead of flagging families exported
outside its view.
"""

from __future__ import annotations

import ast
import fnmatch
import os
import re
from typing import Dict, List, Tuple

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-PROM"

#: the modules whose ``parsec_*`` string literals ARE the scrape
#: surface (prof/metrics.py collectors, prof/liveattr.py stragglers,
#: and the recovery coordinator's scrape-time collector — r13 brought
#: its families into the documented README/COMPONENTS contract)
EXPORT_FILES = ("parsec_tpu/prof/metrics.py",
                "parsec_tpu/prof/liveattr.py",
                "parsec_tpu/core/recovery.py")

DOC_FILES = ("README.md", "COMPONENTS.md")

_NAME_RE = re.compile(r"^parsec_[a-z0-9_]+$")
_DOC_RE = re.compile(r"parsec_[a-z0-9_]+")
#: doc tokens carrying one of these suffixes claim to name a concrete
#: series and must resolve against the exporters
_SERIES_SUFFIXES = ("_total", "_seconds", "_bytes", "_count")

#: not a metric family: the package itself
_EXCLUDE = frozenset(("parsec_tpu",))


def _fstring_pattern(node: ast.JoinedStr) -> str:
    """f-string -> fnmatch pattern (constant parts kept, each
    formatted placeholder a ``*``); empty when it cannot be a family
    template."""
    parts: List[str] = []
    for v in node.values:
        if isinstance(v, ast.Constant) and isinstance(v.value, str):
            parts.append(v.value)
        else:
            parts.append("*")
    pat = "".join(parts)
    return pat if pat.startswith("parsec_") else ""


def facts(ctx: FileCtx) -> Dict[str, List]:
    """Exported family literals of one exporter module (empty for
    every other file)."""
    if ctx.rel.replace("\\", "/") not in EXPORT_FILES:
        return {}
    names: List[Tuple[str, int]] = []
    patterns: List[Tuple[str, int]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            if _NAME_RE.match(node.value) \
                    and node.value not in _EXCLUDE:
                names.append((node.value, node.lineno))
        elif isinstance(node, ast.JoinedStr):
            pat = _fstring_pattern(node)
            if pat and "*" in pat:
                patterns.append((pat, node.lineno))
    return {"names": names, "patterns": patterns,
            "rel": ctx.rel.replace("\\", "/")}


def _doc_mentions(repo_root: str) -> List[Tuple[str, str, int]]:
    out: List[Tuple[str, str, int]] = []
    for doc in DOC_FILES:
        path = os.path.join(repo_root, doc)
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as fh:
            for ln, text in enumerate(fh, 1):
                for m in _DOC_RE.finditer(text):
                    tok = m.group(0)
                    if tok not in _EXCLUDE:
                        out.append((tok, doc, ln))
    return out


def _covered(name: str, tokens: List[str]) -> bool:
    """An exported family is documented when some doc token names it
    exactly or is a prefix of it (the README writes whole families as
    ``parsec_comm_...`` prefixes)."""
    return any(name == t or name.startswith(t) for t in tokens)


def _resolves(tok: str, names: List[str], patterns: List[str]) -> bool:
    """A doc series token resolves against an exported literal, an
    exported prefix of it, or a template wildcard."""
    if any(tok == n or n.startswith(tok) or tok.startswith(n)
           for n in names):
        return True
    return any(fnmatch.fnmatchcase(tok, p) or p.startswith(tok)
               for p in patterns)


def _suppressed(ctxs: Dict[str, FileCtx], rel: str, line: int) -> bool:
    c = ctxs.get(rel)
    return c is not None and c.ignored(line, PASS_ID)


def tree_check(all_facts: List[Dict[str, List]], repo_root: str,
               ctxs: Dict[str, FileCtx]) -> List[Finding]:
    scanned = {rel.replace("\\", "/") for rel in ctxs}
    exporters_present = [f for f in EXPORT_FILES
                         if os.path.exists(os.path.join(repo_root, f))]
    if not exporters_present:
        return []
    if any(f not in scanned for f in exporters_present):
        return []   # partial scan: the export set would be incomplete
    names: List[Tuple[str, int, str]] = []
    patterns: List[Tuple[str, int, str]] = []
    for fx in all_facts:
        rel = fx.get("rel")
        if not rel:
            continue
        names.extend((n, ln, rel) for n, ln in fx.get("names", ()))
        patterns.extend((p, ln, rel)
                        for p, ln in fx.get("patterns", ()))
    mentions = _doc_mentions(repo_root)
    tokens = [t for t, _d, _l in mentions]
    findings: List[Finding] = []

    for name, line, rel in names:
        if not _covered(name, tokens) \
                and not _suppressed(ctxs, rel, line):
            findings.append(Finding(
                rel, line, PASS_ID,
                f"metric family {name!r} is exported but mentioned in "
                "neither README.md nor COMPONENTS.md (operators write "
                "dashboards against the doc tables — document it or "
                "drop the series)"))
    for pat, line, rel in patterns:
        prefix = pat.split("*", 1)[0]
        if not any(t.startswith(prefix) or prefix.startswith(t)
                   for t in tokens) \
                and not _suppressed(ctxs, rel, line):
            findings.append(Finding(
                rel, line, PASS_ID,
                f"metric-family template {pat!r} has no README.md/"
                "COMPONENTS.md mention covering its prefix"))

    name_list = [n for n, _l, _r in names]
    pat_list = [p for p, _l, _r in patterns]
    for tok, doc, line in mentions:
        if not tok.endswith(_SERIES_SUFFIXES):
            continue
        if not _resolves(tok, name_list, pat_list):
            findings.append(Finding(
                doc, line, PASS_ID,
                f"doc mentions metric family {tok!r} but the "
                "exporters serve no such series (the "
                "parsec_tasks_enabled_total drop class — stale doc "
                "row, or a renamed family)"))
    return findings
