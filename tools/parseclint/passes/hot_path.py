"""PCL-HOT — per-task lock acquisitions reachable from hot-path code.

The r14 tentpole removed a ``threading.Lock`` round-trip PER TASK from
the completion chain (``termdet.taskpool_addto_nb_tasks`` called from
``complete_execution`` — decrements now accumulate per worker and
flush at batch boundaries).  At 500k tasks/s one locked counter move
is ~30% of the whole per-task budget, and the cost hides: the probe
headline drops with no failure anywhere.  This pass encodes the bug
class so a per-task lock cannot quietly return to the hot chain.

Roots of the reachability analysis:

* the canonical scheduler-core chain, by MODULE-LEVEL name:
  ``task_progress``, ``complete_execution``, ``execute``, ``schedule``,
  ``worker_loop`` (the __parsec_task_progress lineage — any file
  defining one of these at module level owns a task hot loop);
* any function or method marked ``# lint: hot-path`` on its ``def``
  line (ReadyQueue callbacks — scheduler ``schedule``/``select``
  methods — and future hot entry points static analysis cannot name).
  r19 roots the health SCRAPE path this way (``HealthMonitor.refresh``
  / ``section`` / ``samples`` in prof/health.py): it is not per-task,
  but the fabric's dispatcher tick and every metrics pull run it, so
  per-fold lock or allocation creep silently taxes every scrape — the
  deliberate rate-limited monitor/liveattr locks carry waivers; any
  NEW acquisition in the fold chain gets flagged.

From the roots the pass follows same-file calls (the PCL-EVLOOP
resolution: ``self.method`` through same-file bases, plus module-level
functions) and flags:

* ``with <x>`` where the context manager's name looks like a lock
  (``lock``/``cond``/``mutex``/``sem`` suffixes — the ``_lock`` /
  ``_cond`` conventions of this codebase);
* ``<x>.acquire(...)`` calls;
* ``threading.Lock()`` / ``RLock()`` / ``Condition()`` / ``Semaphore()``
  constructions (allocating a lock per task is as bad as taking one);
* calls to the termdet counter API (``taskpool_addto_nb_tasks`` /
  ``taskpool_addto_runtime_actions``) — the exact per-task round-trip
  r14 removed; batched flushes live OUTSIDE the per-task chain or
  carry a waiver.

Waiver: ``lint: ignore[PCL-HOT] (reason)`` on the flagged line — the
batch-boundary flush and the deliberate ``termdet_batch=1`` A/B
fallback are the legitimate carriers.
"""

from __future__ import annotations

import ast
import re
from typing import List, Optional, Set

from tools.parseclint import FileCtx, Finding
from tools.parseclint.passes.evloop_blocking import _Index, FuncKey

PASS_ID = "PCL-HOT"

#: the scheduler-core chain, rooted by module-level name.  r17 adds
#: the engine functions the extended C chain now has C-resident twins
#: for (prepare_input / release_deps' delivery walk / deliver_dep) and
#: the native-path containment helpers: per-task lock or dict work
#: creeping into a Python twin silently diverges it from the C chain
#: it must stay byte-identical with — and still costs every bailed-out
#: task.
_ROOT_NAMES = frozenset(("task_progress", "complete_execution",
                         "execute", "schedule", "worker_loop",
                         "deliver_dep", "release_deps", "prepare_input",
                         "_native_body_failed", "_native_hook_return"))

#: lock-ish context-manager / attribute name shapes
_LOCKY = re.compile(r"(?:^|_)(?:lock|cond|mutex|sem(?:aphore)?)\d*$",
                    re.IGNORECASE)

#: lock constructors under the threading module
_LOCK_CTORS = frozenset(("Lock", "RLock", "Condition", "Semaphore",
                         "BoundedSemaphore"))

#: the per-task termdet round-trip this pass exists to keep out
_TERMDET_API = frozenset(("taskpool_addto_nb_tasks",
                          "taskpool_addto_runtime_actions"))


def _tail_name(node: ast.AST) -> Optional[str]:
    """The last name component of a Name/Attribute expression."""
    if isinstance(node, ast.Attribute):
        return node.attr
    if isinstance(node, ast.Name):
        return node.id
    return None


def _roots(ctx: FileCtx, index: _Index) -> List[FuncKey]:
    roots: List[FuncKey] = []
    for node in ctx.tree.body:
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if node.name in _ROOT_NAMES or \
                    ctx.has_marker(node.lineno, "hot-path"):
                roots.append((None, node.name))
        elif isinstance(node, ast.ClassDef):
            for item in node.body:
                if isinstance(item, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)) and \
                        ctx.has_marker(item.lineno, "hot-path"):
                    roots.append((node.name, item.name))
    return roots


def _scan_func(ctx: FileCtx, index: _Index, key: FuncKey, fn: ast.AST,
               findings: List[Finding], reach_from: str) -> Set[FuncKey]:
    callees: Set[FuncKey] = set()
    cls = key[0]

    def flag(line: int, what: str) -> None:
        if ctx.ignored(line, PASS_ID):
            return
        where = f"{cls + '.' if cls else ''}{key[1]}"
        via = "" if where == reach_from else f" (reached from {reach_from})"
        findings.append(Finding(
            ctx.rel, line, PASS_ID,
            f"{what} in {where}{via}: a per-task lock round-trip in the "
            "task hot path — batch it out or waive with a reason"))

    def walk(node: ast.AST) -> None:
        if isinstance(node, ast.With):
            for item in node.items:
                cm = item.context_expr
                if isinstance(cm, ast.Call):
                    cm = cm.func
                name = _tail_name(cm)
                if name and _LOCKY.search(name):
                    flag(node.lineno, f"'with {name}' lock acquisition")
        elif isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                base_name = base.id if isinstance(base, ast.Name) else None
                if f.attr == "acquire":
                    flag(node.lineno, ".acquire()")
                elif f.attr in _TERMDET_API:
                    flag(node.lineno, f"termdet .{f.attr}()")
                elif base_name == "threading" and f.attr in _LOCK_CTORS:
                    flag(node.lineno, f"threading.{f.attr}() construction")
                elif base_name == "self":
                    target = index.resolve(cls, f.attr)
                    if target is not None:
                        callees.add(target)
            elif isinstance(f, ast.Name):
                target = index.resolve(None, f.id)
                if target is not None:
                    callees.add(target)
        for child in ast.iter_child_nodes(node):
            walk(child)

    for stmt in fn.body:   # skip the def line/decorators
        walk(stmt)
    return callees


def check(ctx: FileCtx) -> List[Finding]:
    # cheap gate: only files naming a root or carrying the marker pay
    if "hot-path" not in ctx.source and \
            not any(n in ctx.source for n in _ROOT_NAMES):
        return []
    index = _Index(ctx)
    findings: List[Finding] = []
    seen: Set[FuncKey] = set()
    for root in _roots(ctx, index):
        root_name = f"{root[0] + '.' if root[0] else ''}{root[1]}"
        stack = [root]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            fn = index.funcs.get(key)
            if fn is None:
                continue
            stack.extend(_scan_func(ctx, index, key, fn, findings,
                                    root_name))
    uniq = {}
    for f in findings:
        uniq.setdefault((f.line, f.message.split(" (reached")[0]), f)
    return sorted(uniq.values(), key=lambda f: f.line)
