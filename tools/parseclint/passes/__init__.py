"""Pass registry: every pass module exports ``PASS_ID`` and
``check(ctx) -> [Finding]``; tree-level passes additionally export
``facts(ctx) -> dict`` (collected per file, possibly in parallel) and
``tree_check(all_facts, repo_root, ctxs) -> [Finding]`` (run once in
the driver).  Adding a pass = adding a module here and listing it in
``ALL_PASSES``."""

from tools.parseclint.passes import (assert_hazard, device_put,
                                     evloop_blocking, except_hygiene,
                                     hot_path, journal_schema,
                                     lock_discipline, mca_knobs,
                                     prom_metrics)

ALL_PASSES = (
    lock_discipline,
    evloop_blocking,
    hot_path,
    device_put,
    mca_knobs,
    prom_metrics,
    journal_schema,
    except_hygiene,
    assert_hazard,
)
