"""PCL-MCA — knob drift between registration, read sites, env, and docs.

The MCA registry (utils/mca.py) resolves UNREGISTERED names to the raw
environment string or the caller's fallback, so a typo'd
``params.get("comm_eagr_limit")`` silently returns the default forever
— and a registered knob nobody reads is dead configuration surface.
Both classes shipped during PRs 3-5 and were caught only at runtime (or
not at all).  This pass reconciles, across the whole scanned tree:

* every literal ``params.get/set/unset("name")`` site against literal
  ``params.register("name", ...)`` / ``reg_int``/``reg_str``/``reg_bool``
  registrations — an unregistered reference flags at the read site, a
  never-referenced registration flags at the registration;
* ``params.get("name", default)`` fallbacks against the registered
  default — a mismatch is misleading (the registered default always
  wins at runtime), so drift between the two literals flags;
* ``PARSEC_MCA_<NAME>`` string literals (env reads, docstrings, shell
  helpers) — the lowercased knob must be registered;
* ``PARSEC_MCA_<NAME>`` mentions in COMPONENTS.md / README.md (the knob
  tables) — doc drift flags at the doc line.

Dynamic names (``params.get(framework)``, ComponentRepository's
framework registrations) are invisible to this pass by design; only
literals participate, so there are no false "unregistered" findings for
computed lookups.
"""

from __future__ import annotations

import ast
import os
import re
from typing import Any, Dict, List, Tuple

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-MCA"

_ENV_RE = re.compile(r"PARSEC_MCA_([A-Z0-9_]+)")
_REG_FNS = frozenset(("register", "reg_int", "reg_str", "reg_bool"))


def _literal(node: ast.AST) -> Any:
    return node.value if isinstance(node, ast.Constant) else None


def facts(ctx: FileCtx) -> Dict[str, List]:
    """Per-file collection, merged tree-wide by ``tree_check``."""
    registers: List[Tuple[str, Any, int, str]] = []   # name, default, line, rel
    refs: List[Tuple[str, str, Any, int, str]] = []   # name, kind, default, ...
    envs: List[Tuple[str, int, str]] = []
    for node in ast.walk(ctx.tree):
        if isinstance(node, ast.Constant) and isinstance(node.value, str):
            for m in _ENV_RE.finditer(node.value):
                envs.append((m.group(1).lower(), node.lineno, ctx.rel))
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if not (isinstance(f, ast.Attribute) and
                isinstance(f.value, ast.Name) and f.value.id == "params"):
            continue
        if f.attr in _REG_FNS and node.args:
            if f.attr == "register":
                name = _literal(node.args[0])
                default = _literal(node.args[1]) \
                    if len(node.args) > 1 else None
            else:   # reg_int/reg_str/reg_bool join three literal parts
                parts = [_literal(a) for a in node.args[:3]]
                if any(not isinstance(p, str) for p in parts):
                    continue
                name = "_".join(p for p in parts if p)
                default = _literal(node.args[3]) \
                    if len(node.args) > 3 else None
            if isinstance(name, str):
                registers.append((name, default, node.lineno, ctx.rel))
        elif f.attr in ("get", "set", "unset") and node.args:
            name = _literal(node.args[0])
            if isinstance(name, str):
                default = _literal(node.args[1]) \
                    if f.attr == "get" and len(node.args) > 1 else None
                refs.append((name, f.attr, default, node.lineno, ctx.rel))
    return {"registers": registers, "refs": refs, "envs": envs}


def _suppressed(ctxs: Dict[str, FileCtx], rel: str, line: int) -> bool:
    c = ctxs.get(rel)
    return c is not None and c.ignored(line, PASS_ID)


def _full_package_in_scope(repo_root: str, ctxs: Dict) -> bool:
    """Registrations are spread across the whole package, so the
    cross-checks are only sound when EVERY parsec_tpu module was
    scanned — a subtree scan (``parseclint parsec_tpu/utils``) must
    stay silent rather than flag knobs registered outside its scope.
    A repo_root with no parsec_tpu package (the synthetic trees the
    corpus tests build) is vacuously fully in scope."""
    pkg = os.path.join(repo_root, "parsec_tpu")
    scanned = {rel.replace("\\", "/") for rel in ctxs}
    for dirpath, dirnames, filenames in os.walk(pkg):
        dirnames[:] = [d for d in dirnames if d != "__pycache__"]
        for fn in filenames:
            if not fn.endswith(".py"):
                continue
            rel = os.path.relpath(os.path.join(dirpath, fn), repo_root)
            if rel.replace("\\", "/") not in scanned:
                return False
    return True


def tree_check(all_facts: List[Dict[str, List]], repo_root: str,
               ctxs: Dict[str, FileCtx]) -> List[Finding]:
    if not _full_package_in_scope(repo_root, ctxs):
        return []
    registers: Dict[str, Tuple[Any, int, str]] = {}
    refs: List[Tuple[str, str, Any, int, str]] = []
    envs: List[Tuple[str, int, str]] = []
    for fx in all_facts:
        for name, default, line, rel in fx.get("registers", ()):
            registers.setdefault(name, (default, line, rel))
        refs.extend(fx.get("refs", ()))
        envs.extend(fx.get("envs", ()))

    findings: List[Finding] = []
    referenced = {name for name, *_ in refs} | {name for name, *_ in envs}

    for name, kind, default, line, rel in refs:
        if name not in registers:
            if not _suppressed(ctxs, rel, line):
                findings.append(Finding(
                    rel, line, PASS_ID,
                    f"params.{kind}({name!r}) reads an UNREGISTERED "
                    "knob (typo, or missing params.register)"))
        elif kind == "get" and default is not None:
            reg_default = registers[name][0]
            if reg_default is not None and default != reg_default \
                    and not _suppressed(ctxs, rel, line):
                findings.append(Finding(
                    rel, line, PASS_ID,
                    f"params.get({name!r}, {default!r}) fallback drifted "
                    f"from the registered default {reg_default!r} "
                    "(the registration always wins at runtime — align "
                    "the literals)"))

    for name, (default, line, rel) in sorted(registers.items()):
        if name not in referenced and not _suppressed(ctxs, rel, line):
            findings.append(Finding(
                rel, line, PASS_ID,
                f"registered knob {name!r} is never read "
                "(dead configuration surface, or the read site uses a "
                "different spelling)"))

    for name, line, rel in envs:
        if name not in registers and not _suppressed(ctxs, rel, line):
            findings.append(Finding(
                rel, line, PASS_ID,
                f"PARSEC_MCA_{name.upper()} names an unregistered knob "
                f"({name!r})"))

    # knob tables in the docs must match the registry
    for doc in ("COMPONENTS.md", "README.md"):
        path = os.path.join(repo_root, doc)
        if not os.path.exists(path):
            continue
        with open(path, encoding="utf-8") as fh:
            for ln, text in enumerate(fh, 1):
                for m in _ENV_RE.finditer(text):
                    name = m.group(1).lower()
                    if name not in registers:
                        findings.append(Finding(
                            doc, ln, PASS_ID,
                            f"doc mentions PARSEC_MCA_{m.group(1)} but "
                            f"no knob {name!r} is registered (doc "
                            "drift)"))
    return findings
