"""PCL-ASSERT — asserts ``python -O`` silently deletes.

``-O`` strips every ``assert``: a load-bearing guard (the TAG_NAMES
wire-tag drift check that is now an explicit raise in comm/engine.py)
or an assert whose CONDITION has side effects simply vanishes in
optimized deployments.  Two shapes flag:

* a module-level assert (import-time invariant): these guard protocol/
  registry consistency and must be explicit ``raise`` statements;
* an assert whose condition CALLS anything outside a small pure
  whitelist (``len``/``isinstance``/``getattr``/... and read-only
  method names like ``.get``/``.keys``): the call's effect — a queue
  pop, a state transition, an RPC — disappears under ``-O`` together
  with the check.

Waiver: ``# lint: ignore[PCL-ASSERT] reason`` on the assert line.
Tests are outside the default scan scope (pytest runs without ``-O``).
"""

from __future__ import annotations

import ast
from typing import List, Optional

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-ASSERT"

_PURE_FUNCS = frozenset((
    "len", "isinstance", "issubclass", "getattr", "hasattr", "min",
    "max", "abs", "all", "any", "sorted", "sum", "tuple", "list", "set",
    "dict", "frozenset", "str", "int", "float", "bool", "repr", "id",
    "type", "callable", "round", "divmod", "format", "ord", "chr",
    "enumerate", "zip", "range",
))

_PURE_METHODS = frozenset((
    "get", "keys", "values", "items", "count", "index", "startswith",
    "endswith", "strip", "lstrip", "rstrip", "lower", "upper", "split",
    "join", "as_dict", "is_deleted", "isdigit", "copy",
))


def _impure_call(test: ast.AST) -> Optional[ast.Call]:
    for node in ast.walk(test):
        if not isinstance(node, ast.Call):
            continue
        f = node.func
        if isinstance(f, ast.Name) and f.id in _PURE_FUNCS:
            continue
        if isinstance(f, ast.Attribute) and f.attr in _PURE_METHODS:
            continue
        return node
    return None


def _call_name(call: ast.Call) -> str:
    f = call.func
    if isinstance(f, ast.Name):
        return f.id
    if isinstance(f, ast.Attribute):
        return f".{f.attr}"
    return "<call>"


def check(ctx: FileCtx) -> List[Finding]:
    findings: List[Finding] = []

    def in_function(node: ast.Assert) -> bool:
        # module-level asserts have col_offset 0 and sit in tree.body
        # or in top-level if/for/try blocks; detect by walking scopes
        return node in func_asserts

    func_asserts = set()
    for node in ast.walk(ctx.tree):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)):
            for sub in ast.walk(node):
                if isinstance(sub, ast.Assert):
                    func_asserts.add(sub)

    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.Assert):
            continue
        if ctx.ignored(node.lineno, PASS_ID):
            continue
        if not in_function(node):
            findings.append(Finding(
                ctx.rel, node.lineno, PASS_ID,
                "module-level assert guards an import-time invariant — "
                "python -O strips it (the TAG_NAMES class); use an "
                "explicit raise"))
            continue
        call = _impure_call(node.test)
        if call is not None:
            findings.append(Finding(
                ctx.rel, node.lineno, PASS_ID,
                f"assert condition calls {_call_name(call)}() — the "
                "call (and its side effects) vanish under python -O; "
                "hoist the call or use an explicit raise"))
    return findings
