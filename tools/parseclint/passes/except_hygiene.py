"""PCL-EXCEPT — containment-path exception hygiene.

``PeerFailedError`` is the structured, CONTAINED failure: the transport
routes it into the taskpools that touch the dead rank (per-pool
``error_sink``) so one job's dead peer never poisons concurrently
running jobs.  The PR 5 round-4 bug class was handlers undoing that
containment — catching the structured error and re-recording it
context-globally (``record_error(exc, None)``), or silently swallowing
it so nothing surfaced at all.

Rules (scoped to runtime code, not tests):

* an ``except`` catching ``PeerFailedError`` — explicitly, or via
  ``Exception``/``BaseException``/bare — whose handler calls
  ``record_error(..., None)`` (no task attribution = context-global)
  flags: route through ``record_pool_error`` instead;
* an ``except`` naming ``PeerFailedError`` explicitly whose handler
  only ``pass``es / ``return``s / ``continue``s (a swallow) flags
  UNLESS the handler carries ``# lint: contained (reason)`` — the
  waiver documents WHY the loss is already routed elsewhere (e.g. the
  transport's death path recorded it before the send raised).

``record_error(exc, task)`` with a real task is NOT flagged — task
attribution routes through the pool's error sink, which is the
contained path.
"""

from __future__ import annotations

import ast
from typing import List

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-EXCEPT"

_BROAD = frozenset(("Exception", "BaseException"))


def _caught_names(handler: ast.ExceptHandler) -> List[str]:
    t = handler.type
    if t is None:
        return ["<bare>"]
    elts = t.elts if isinstance(t, ast.Tuple) else [t]
    out = []
    for e in elts:
        if isinstance(e, ast.Name):
            out.append(e.id)
        elif isinstance(e, ast.Attribute):
            out.append(e.attr)
    return out


def _catches_peer_failed(names: List[str]) -> bool:
    return "PeerFailedError" in names or "<bare>" in names or \
        bool(set(names) & _BROAD)


def _global_records(handler: ast.ExceptHandler) -> List[ast.Call]:
    """record_error(..., None) calls in the handler body."""
    hits = []
    for node in ast.walk(handler):
        if isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr == "record_error":
            if len(node.args) >= 2 and \
                    isinstance(node.args[1], ast.Constant) and \
                    node.args[1].value is None:
                hits.append(node)
    return hits


def _is_swallow(handler: ast.ExceptHandler) -> bool:
    """Body is only pass/return/continue/warning-style logging."""
    for stmt in handler.body:
        if isinstance(stmt, (ast.Pass, ast.Continue)):
            continue
        if isinstance(stmt, ast.Return) and stmt.value is None:
            continue
        if isinstance(stmt, ast.Expr) and \
                isinstance(stmt.value, ast.Call):
            f = stmt.value.func
            name = f.id if isinstance(f, ast.Name) else \
                (f.attr if isinstance(f, ast.Attribute) else "")
            if name in ("warning", "debug_verbose", "mark", "inform"):
                continue
        return False
    return True


def check(ctx: FileCtx) -> List[Finding]:
    if "PeerFailedError" not in ctx.source:
        return []
    findings: List[Finding] = []
    for node in ast.walk(ctx.tree):
        if not isinstance(node, ast.ExceptHandler):
            continue
        names = _caught_names(node)
        if not _catches_peer_failed(names):
            continue
        line = node.lineno
        for call in _global_records(node):
            if not ctx.ignored(call.lineno, PASS_ID):
                findings.append(Finding(
                    ctx.rel, call.lineno, PASS_ID,
                    f"handler catching {'/'.join(names)} records the "
                    "failure CONTEXT-GLOBALLY (record_error(exc, None) "
                    "poisons every pool on the rank) — route through "
                    "record_pool_error"))
        # the waiver may sit on the except line or anywhere in the
        # handler body (the natural place for the "why" comment)
        end = getattr(node, "end_lineno", line) or line
        waived = any(
            "lint: contained" in ctx.comments.get(ln, "")
            for ln in range(line, end + 1))
        if "PeerFailedError" in names and _is_swallow(node) and \
                not ctx.ignored(line, PASS_ID) and not waived:
            findings.append(Finding(
                ctx.rel, line, PASS_ID,
                "PeerFailedError swallowed (pass/return) — a contained "
                "failure must reach record_pool_error somewhere; if the "
                "death was already routed, waive with "
                "'lint: contained (reason)'"))
    return findings
