"""PCL-EVLOOP — blocking calls reachable from event-loop callbacks.

The single-threaded comm engine (``EventLoopCE``) owns accept/recv/send
for EVERY peer socket on one thread; anything that blocks that thread
wedges the whole comm plane — including the hung-peer detector that is
supposed to catch exactly such wedges (the PR 5 blocking-``sendmsg``
heartbeat bug), and ``select.select`` dies outright at fd >= 1024 (the
PR 5 round-3 hazard).

Roots of the reachability analysis:

* every method of a class with a ``FUNNELLED = True`` class attribute
  (the event-loop transport convention), except methods marked
  ``# off-loop`` on their ``def`` line (constructors/teardown/dial
  helpers that run on other threads; ``__init__``/``fini`` are exempt
  by default);
* any function or method marked ``# on-loop`` on its ``def`` line (AM
  callbacks and periodic hooks the loop invokes through registration
  tables static analysis cannot see).

From the roots, the pass follows same-file ``self.method(...)`` calls
(resolved through same-file base classes, upward only) and module-level
function calls, then flags:

* ``time.sleep(...)``
* ``select.select(...)``   (FD_SETSIZE: raises at fd >= 1024)
* ``<lock>.acquire()`` without ``blocking=False``
* socket-blocking methods (``sendall``/``sendmsg``/``send``/``sendto``/
  ``recv``/``recv_into``/``recvfrom``/``accept``/``connect``) UNLESS
  the call sits in a ``try`` whose handlers catch ``BlockingIOError``
  — the nonblocking-socket discipline the loop requires.

Waiver: ``# lint: allow-blocking (reason)`` on the call line — e.g. the
bounded post-stop ``_shutdown_drain`` sleep.
"""

from __future__ import annotations

import ast
from typing import Dict, List, Optional, Set, Tuple

from tools.parseclint import FileCtx, Finding

PASS_ID = "PCL-EVLOOP"

_SOCK_BLOCKING = frozenset((
    "sendall", "sendmsg", "send", "sendto", "recv", "recv_into",
    "recvfrom", "accept", "connect",
))

#: teardown/bring-up methods that run off the loop by convention
_DEFAULT_OFF_LOOP = frozenset(("__init__", "fini"))

FuncKey = Tuple[Optional[str], str]   # (class name or None, func name)


def _catches_blocking(handler_types: List[ast.expr]) -> bool:
    for t in handler_types:
        elts = t.elts if isinstance(t, ast.Tuple) else [t]
        for e in elts:
            name = e.id if isinstance(e, ast.Name) else \
                (e.attr if isinstance(e, ast.Attribute) else None)
            if name in ("BlockingIOError", "InterruptedError"):
                return True
    return False


class _Index:
    """Per-file function index + static call graph."""

    def __init__(self, ctx: FileCtx):
        self.ctx = ctx
        self.funcs: Dict[FuncKey, ast.AST] = {}
        self.bases: Dict[str, List[str]] = {}
        for node in ctx.tree.body:
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                self.funcs[(None, node.name)] = node
            elif isinstance(node, ast.ClassDef):
                self.bases[node.name] = [
                    b.id for b in node.bases if isinstance(b, ast.Name)]
                for item in node.body:
                    if isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                        self.funcs[(node.name, item.name)] = item

    def resolve(self, cls: Optional[str], name: str) -> Optional[FuncKey]:
        """self.<name> resolution: the caller's class, then same-file
        bases (upward only — a base method never dispatches DOWN into a
        transport the loop does not run)."""
        seen: Set[str] = set()
        stack = [cls] if cls else []
        while stack:
            c = stack.pop(0)
            if c is None or c in seen:
                continue
            seen.add(c)
            if (c, name) in self.funcs:
                return (c, name)
            stack.extend(self.bases.get(c, []))
        if (None, name) in self.funcs:
            return (None, name)
        return None


def _roots(ctx: FileCtx, index: _Index) -> List[FuncKey]:
    roots: List[FuncKey] = []
    for node in ctx.tree.body:
        if isinstance(node, ast.ClassDef):
            funnelled = any(
                isinstance(s, ast.Assign) and any(
                    isinstance(t, ast.Name) and t.id == "FUNNELLED"
                    for t in s.targets)
                and isinstance(s.value, ast.Constant)
                and s.value.value is True
                for s in node.body)
            for item in node.body:
                if not isinstance(item, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                on = ctx.has_marker(item.lineno, "on-loop")
                off = item.name in _DEFAULT_OFF_LOOP or \
                    ctx.has_marker(item.lineno, "off-loop")
                if on or (funnelled and not off):
                    roots.append((node.name, item.name))
        elif isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            if ctx.has_marker(node.lineno, "on-loop"):
                roots.append((None, node.name))
    return roots


def _scan_func(ctx: FileCtx, index: _Index, key: FuncKey,
               fn: ast.AST, findings: List[Finding],
               reach_from: str) -> Set[FuncKey]:
    """Flag blocking calls in ``fn``; return same-file callees."""
    callees: Set[FuncKey] = set()
    cls = key[0]

    def flag(line: int, what: str) -> None:
        if ctx.ignored(line, PASS_ID) or \
                ctx.has_marker(line, "allow-blocking"):
            return
        where = f"{cls + '.' if cls else ''}{key[1]}"
        via = "" if where == reach_from else f" (reached from {reach_from})"
        findings.append(Finding(
            ctx.rel, line, PASS_ID,
            f"{what} in {where}{via}: would wedge the single-threaded "
            "event loop"))

    def walk(node: ast.AST, guarded: bool) -> None:
        if isinstance(node, ast.Try):
            g = guarded or _catches_blocking(
                [h.type for h in node.handlers if h.type is not None])
            for child in node.body:
                walk(child, g)
            for h in node.handlers:
                for child in h.body:
                    walk(child, guarded)
            for child in node.orelse + node.finalbody:
                walk(child, guarded)
            return
        if isinstance(node, ast.Call):
            f = node.func
            if isinstance(f, ast.Attribute):
                base = f.value
                base_name = base.id if isinstance(base, ast.Name) else None
                if base_name == "time" and f.attr == "sleep":
                    flag(node.lineno, "time.sleep()")
                elif base_name == "select" and f.attr == "select":
                    flag(node.lineno,
                         "select.select() (FD_SETSIZE: dies at fd>=1024; "
                         "use select.poll)")
                elif f.attr == "acquire":
                    nonblocking = any(
                        kw.arg == "blocking" and
                        isinstance(kw.value, ast.Constant) and
                        kw.value.value is False
                        for kw in node.keywords) or (
                        node.args and
                        isinstance(node.args[0], ast.Constant) and
                        node.args[0].value is False)
                    if not nonblocking:
                        flag(node.lineno, "blocking .acquire()")
                elif f.attr in _SOCK_BLOCKING and not guarded:
                    flag(node.lineno,
                         f"socket .{f.attr}() with no BlockingIOError "
                         "handler (nonblocking discipline)")
                elif base_name == "self":
                    target = index.resolve(cls, f.attr)
                    if target is not None:
                        callees.add(target)
            elif isinstance(f, ast.Name):
                target = index.resolve(None, f.id)
                if target is not None:
                    callees.add(target)
        for child in ast.iter_child_nodes(node):
            walk(child, guarded)

    for stmt in fn.body:   # skip the def line/decorators
        walk(stmt, False)
    return callees


def check(ctx: FileCtx) -> List[Finding]:
    # cheap gate: only files that define a funnelled class or carry
    # on-loop annotations pay the graph walk
    if "FUNNELLED" not in ctx.source and "on-loop" not in ctx.source:
        return []
    index = _Index(ctx)
    findings: List[Finding] = []
    seen: Set[FuncKey] = set()
    for root in _roots(ctx, index):
        root_name = f"{root[0] + '.' if root[0] else ''}{root[1]}"
        stack = [root]
        while stack:
            key = stack.pop()
            if key in seen:
                continue
            seen.add(key)
            fn = index.funcs.get(key)
            if fn is None:
                continue
            stack.extend(_scan_func(ctx, index, key, fn, findings,
                                    root_name))
    # dedup: one function reachable from several roots flags once
    uniq = {}
    for f in findings:
        uniq.setdefault((f.line, f.message.split(" (reached")[0]), f)
    return sorted(uniq.values(), key=lambda f: f.line)
