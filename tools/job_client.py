#!/usr/bin/env python
"""CLI client for the resident job server (parsec_tpu/service/server.py).

Submit named app jobs to a warm runtime from another process:

    # in one terminal: the resident server
    python -m parsec_tpu.service.server --port 41990 --cores 4

    # from anywhere else
    python tools/job_client.py submit gemm --set n=512 --set nb=128 \
        --priority 5 --wait

    # serving-fabric tenancy (server started with --fabric): declare a
    # completion SLO, ask for an exclusive 2-device subset elastic to
    # 4, opt into preemption; the reply prints the quoted makespan and
    # the admission verdict
    python tools/job_client.py submit gemm --set n=512 --slo 30 \
        --devices 2 --devices-max 4 --resumable

    python tools/job_client.py status 1
    python tools/job_client.py result 1
    python tools/job_client.py cancel 1
    python tools/job_client.py jobs
    python tools/job_client.py stats
    python tools/job_client.py gauges

The wire is the framed-JSON protocol of service/server.py (magic +
version header, comm/engine.py framing discipline).
"""

from __future__ import annotations

import argparse
import json
import os
import sys

sys.path.insert(0, os.path.join(os.path.dirname(__file__), ".."))


def _num(v: str):
    try:
        return int(v, 0)
    except ValueError:
        try:
            return float(v)
        except ValueError:
            return v


def main(argv=None) -> int:
    ap = argparse.ArgumentParser(description=__doc__.splitlines()[0])
    ap.add_argument("--host", default="127.0.0.1")
    ap.add_argument("--port", type=int, default=41990)
    sub = ap.add_subparsers(dest="cmd", required=True)

    p = sub.add_parser("submit", help="submit a named app job")
    p.add_argument("app", help="gemm | potrf | stencil (see 'apps')")
    p.add_argument("--set", action="append", default=[], metavar="K=V",
                   help="app parameter, e.g. --set n=512 --set nb=128")
    p.add_argument("--priority", type=int, default=0)
    p.add_argument("--deadline", type=float, default=None,
                   help="seconds from submission before the job is "
                        "cancelled (TIMEOUT)")
    p.add_argument("--client", default="job_client")
    p.add_argument("--name", default="")
    p.add_argument("--block", action="store_true",
                   help="backpressure-wait for queue room instead of "
                        "failing when the pending queue is full")
    p.add_argument("--slo", type=float, default=None,
                   help="fabric: declared completion SLO in seconds "
                        "from submission; the server quotes a makespan "
                        "and queues/deprioritizes/rejects against it")
    p.add_argument("--devices", type=int, default=None,
                   help="fabric: exclusive accelerator subset to carve "
                        "(0 = temporal sharing of the remainder)")
    p.add_argument("--devices-max", type=int, default=0,
                   help="fabric: elastic ceiling the subset may grow "
                        "to when devices free up")
    p.add_argument("--resumable", action="store_true",
                   help="fabric: allow mid-DAG preemption; the job is "
                        "re-queued and resumed from its materialized "
                        "tiles")
    p.add_argument("--slo-policy", default="",
                   choices=("", "queue", "deprioritize", "reject"),
                   help="fabric: override the server's over-SLO policy "
                        "for this submit")
    p.add_argument("--wait", action="store_true",
                   help="block for and print the job result")
    p.add_argument("--timeout", type=float, default=600.0,
                   help="result wait budget with --wait")

    for name, with_timeout in (("status", False), ("result", True),
                               ("cancel", False)):
        q = sub.add_parser(name)
        q.add_argument("job", type=int)
        if with_timeout:
            q.add_argument("--timeout", type=float, default=600.0)

    sub.add_parser("jobs", help="list all jobs the server has seen")
    sub.add_parser("stats", help="service queue/admission counters")
    sub.add_parser("gauges", help="per-job gauge snapshot")
    sub.add_parser("apps", help="list the server's named apps")

    args = ap.parse_args(argv)
    from parsec_tpu.service.server import request

    def rpc(obj, timeout=120.0):
        return request(args.host, args.port, obj, timeout=timeout)

    if args.cmd == "submit":
        params = {}
        for kv in args.set:
            if "=" not in kv:
                ap.error(f"--set wants K=V, got {kv!r}")
            k, v = kv.split("=", 1)
            params[k.strip()] = _num(v.strip())
        req = {"op": "submit", "app": args.app, "params": params,
               "priority": args.priority, "deadline": args.deadline,
               "client": args.client, "name": args.name,
               "block": args.block}
        # fabric admission fields (ignored by a plain JobService front)
        if args.slo is not None:
            req["slo"] = args.slo
        if args.devices is not None:
            req["devices"] = args.devices
        if args.devices_max:
            req["devices_max"] = args.devices_max
        if args.resumable:
            req["resumable"] = True
        if args.slo_policy:
            req["slo_policy"] = args.slo_policy
        if args.block:
            # bound the server-side backpressure wait: an unbounded wait
            # outlives the client's socket timeout and admits a job no
            # one is watching
            req["timeout"] = args.timeout
        reply = rpc(req, timeout=args.timeout + 10.0)
        print(json.dumps(reply, indent=2))
        if reply.get("verdict") is not None or reply.get("rejected"):
            eta = reply.get("quote_eta")
            print(f"quote: eta="
                  f"{'n/a' if eta is None else f'{eta:.3f}s'} "
                  f"verdict={reply.get('verdict') or 'reject'}",
                  file=sys.stderr)
        if not reply.get("ok"):
            return 1
        if args.wait:
            reply = rpc({"op": "result", "job": reply["job"],
                         "timeout": args.timeout},
                        timeout=args.timeout + 10.0)
            print(json.dumps(reply, indent=2))
            return 0 if reply.get("ok") else 1
        return 0

    req = {"op": args.cmd}
    if args.cmd in ("status", "result", "cancel"):
        req["job"] = args.job
    if args.cmd == "result":
        req["timeout"] = args.timeout
        reply = rpc(req, timeout=args.timeout + 10.0)
    else:
        reply = rpc(req)
    print(json.dumps(reply, indent=2))
    if args.cmd == "status" and reply.get("queue_position") is not None:
        print(f"queue position: {reply['queue_position']}",
              file=sys.stderr)
    return 0 if reply.get("ok") else 1


if __name__ == "__main__":
    sys.exit(main())
