/* Native comm framing: the per-peer incremental frame parser and the
 * gather-write part assembly in C.
 *
 * The event-loop transport (comm/engine.py EventLoopCE) and the
 * shared-memory ring transport (comm/shm.py) both speak the same
 * byte-stream frame format: a 16-byte header (!IQI: tag, pickle
 * length, out-of-band buffer count), the pickle body, then per-buffer
 * length (!Q) + raw buffer.  The Python state machine costs several
 * function calls and slice copies per frame; here one ``feed()``
 * crossing consumes a whole read() worth of bytes and returns the
 * completed frames, and ``bulk_target``/``bulk_commit`` expose the
 * in-progress large payload buffer so the transport can recv_into it
 * directly (the zero-copy out-of-band path keeps working).
 *
 * Single-consumer discipline per parser (one parser per peer
 * connection/ring, driven by the comm loop thread under the GIL).
 */
#define PY_SSIZE_T_CLEAN
#include <Python.h>
#include <stdint.h>
#include <string.h>

#define HDR_SIZE 16     /* !IQI */
#define BLEN_SIZE 8     /* !Q   */
#define MAX_NBUFS 4096
/* below this, copying through feed() beats a dedicated recv_into */
#define BULK_MIN 65536

enum { ST_HDR, ST_BODY, ST_BLEN, ST_BUF };

typedef struct {
    PyObject_HEAD
    int stage;
    Py_ssize_t want, got;
    unsigned char small[HDR_SIZE];
    PyObject *target;       /* bytearray being filled (BODY/BUF) */
    uint32_t tag;
    uint64_t ln;
    uint32_t nbufs;
    PyObject *body;         /* completed body bytearray or NULL */
    PyObject *oob;          /* list of completed oob bytearrays */
    uint64_t max_frame;
    /* stats: frames completed through this parser */
    uint64_t frames;
} FPObject;

static inline uint32_t be32(const unsigned char *p) {
    return ((uint32_t)p[0] << 24) | ((uint32_t)p[1] << 16) |
           ((uint32_t)p[2] << 8) | (uint32_t)p[3];
}

static inline uint64_t be64(const unsigned char *p) {
    return ((uint64_t)be32(p) << 32) | (uint64_t)be32(p + 4);
}

static inline void put_be32(unsigned char *p, uint32_t v) {
    p[0] = (unsigned char)(v >> 24);
    p[1] = (unsigned char)(v >> 16);
    p[2] = (unsigned char)(v >> 8);
    p[3] = (unsigned char)v;
}

static inline void put_be64(unsigned char *p, uint64_t v) {
    put_be32(p, (uint32_t)(v >> 32));
    put_be32(p + 4, (uint32_t)v);
}

static void fp_expect_hdr(FPObject *f) {
    f->stage = ST_HDR;
    f->want = HDR_SIZE;
    f->got = 0;
    Py_CLEAR(f->target);
}

/* one stage filled: advance the machine; completed frames append to
 * ``out``.  Returns 0, or -1 with an exception set (corruption). */
static int fp_advance(FPObject *f, PyObject *out) {
    switch (f->stage) {
    case ST_HDR: {
        f->tag = be32(f->small);
        f->ln = be64(f->small + 4);
        f->nbufs = be32(f->small + 12);
        if (f->ln > f->max_frame || f->nbufs > MAX_NBUFS) {
            PyErr_Format(PyExc_ValueError,
                         "frame length %llu/%u bufs exceeds the bound "
                         "(tag=%u)", (unsigned long long)f->ln, f->nbufs,
                         f->tag);
            return -1;
        }
        Py_CLEAR(f->body);
        Py_CLEAR(f->oob);
        f->oob = PyList_New(0);
        if (!f->oob)
            return -1;
        if (f->ln) {
            f->target = PyByteArray_FromStringAndSize(NULL,
                                                      (Py_ssize_t)f->ln);
            if (!f->target)
                return -1;
            f->stage = ST_BODY;
            f->want = (Py_ssize_t)f->ln;
            f->got = 0;
            return 0;
        }
        break;    /* fall through to next_buf */
    }
    case ST_BODY:
        f->body = f->target;
        f->target = NULL;
        break;
    case ST_BLEN: {
        uint64_t bln = be64(f->small);
        if (bln > f->max_frame) {
            PyErr_Format(PyExc_ValueError,
                         "oob buffer length %llu (tag=%u)",
                         (unsigned long long)bln, f->tag);
            return -1;
        }
        f->target = PyByteArray_FromStringAndSize(NULL, (Py_ssize_t)bln);
        if (!f->target)
            return -1;
        if (bln) {
            f->stage = ST_BUF;
            f->want = (Py_ssize_t)bln;
            f->got = 0;
            return 0;
        }
        /* zero-length buffer: complete immediately */
        if (PyList_Append(f->oob, f->target) < 0)
            return -1;
        Py_CLEAR(f->target);
        break;
    }
    case ST_BUF:
        if (PyList_Append(f->oob, f->target) < 0)
            return -1;
        Py_CLEAR(f->target);
        break;
    }
    /* next_buf */
    if ((uint32_t)PyList_GET_SIZE(f->oob) < f->nbufs) {
        f->stage = ST_BLEN;
        f->want = BLEN_SIZE;
        f->got = 0;
        return 0;
    }
    /* frame complete */
    {
        PyObject *body = f->body ? f->body : Py_None;
        PyObject *tup = Py_BuildValue("(IOO)", f->tag, body, f->oob);
        if (!tup)
            return -1;
        int rc = PyList_Append(out, tup);
        Py_DECREF(tup);
        if (rc < 0)
            return -1;
        Py_CLEAR(f->body);
        Py_CLEAR(f->oob);
        f->frames++;
    }
    fp_expect_hdr(f);
    return 0;
}

/* feed(data) -> [(tag, body|None, [oob...]), ...] */
static PyObject *fp_feed(PyObject *self_, PyObject *const *args,
                         Py_ssize_t nargs) {
    FPObject *f = (FPObject *)self_;
    if (nargs != 1) {
        PyErr_SetString(PyExc_TypeError, "feed(data)");
        return NULL;
    }
    Py_buffer view;
    if (PyObject_GetBuffer(args[0], &view, PyBUF_SIMPLE) < 0)
        return NULL;
    PyObject *out = PyList_New(0);
    if (!out) {
        PyBuffer_Release(&view);
        return NULL;
    }
    const unsigned char *p = (const unsigned char *)view.buf;
    Py_ssize_t n = view.len, off = 0;
    while (off < n) {
        Py_ssize_t take = f->want - f->got;
        if (take > n - off)
            take = n - off;
        if (f->target) {
            memcpy(PyByteArray_AS_STRING(f->target) + f->got, p + off,
                   (size_t)take);
        } else {
            memcpy(f->small + f->got, p + off, (size_t)take);
        }
        f->got += take;
        off += take;
        if (f->got == f->want && fp_advance(f, out) < 0) {
            PyBuffer_Release(&view);
            Py_DECREF(out);
            return NULL;
        }
    }
    PyBuffer_Release(&view);
    return out;
}

/* bulk_target() -> writable memoryview of the in-progress payload's
 * remaining region, or None when the parser is between frames / the
 * remainder is small.  The parser keeps the backing bytearray alive;
 * the caller must recv_into the view and call bulk_commit(n) before
 * any other parser call. */
static PyObject *fp_bulk_target(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    FPObject *f = (FPObject *)self_;
    if (!f->target || f->want - f->got < BULK_MIN)
        Py_RETURN_NONE;
    return PyMemoryView_FromMemory(
        PyByteArray_AS_STRING(f->target) + f->got,
        f->want - f->got, PyBUF_WRITE);
}

/* bulk_commit(n) -> frames completed by those n bytes (usually []) */
static PyObject *fp_bulk_commit(PyObject *self_, PyObject *const *args,
                                Py_ssize_t nargs) {
    FPObject *f = (FPObject *)self_;
    if (nargs != 1) {
        PyErr_SetString(PyExc_TypeError, "bulk_commit(nbytes)");
        return NULL;
    }
    Py_ssize_t nb = PyLong_AsSsize_t(args[0]);
    if (nb == -1 && PyErr_Occurred())
        return NULL;
    if (!f->target || nb < 0 || f->got + nb > f->want) {
        PyErr_SetString(PyExc_ValueError,
                        "bulk_commit outside an in-progress payload");
        return NULL;
    }
    PyObject *out = PyList_New(0);
    if (!out)
        return NULL;
    f->got += nb;
    if (f->got == f->want && fp_advance(f, out) < 0) {
        Py_DECREF(out);
        return NULL;
    }
    return out;
}

static PyObject *fp_stats(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    return PyLong_FromUnsignedLongLong(((FPObject *)self_)->frames);
}

/* idle() -> True when the parser sits exactly between frames (EOF
 * here is a clean close; anywhere else the peer died mid-frame). */
static PyObject *fp_idle(PyObject *self_, PyObject *noargs) {
    (void)noargs;
    FPObject *f = (FPObject *)self_;
    return PyBool_FromLong(f->stage == ST_HDR && f->got == 0);
}

static void fp_dealloc(PyObject *self_) {
    FPObject *f = (FPObject *)self_;
    Py_CLEAR(f->target);
    Py_CLEAR(f->body);
    Py_CLEAR(f->oob);
    Py_TYPE(self_)->tp_free(self_);
}

static int fp_init(PyObject *self_, PyObject *args, PyObject *kwds) {
    (void)kwds;
    FPObject *f = (FPObject *)self_;
    unsigned long long max_frame;
    if (!PyArg_ParseTuple(args, "K", &max_frame))
        return -1;
    f->max_frame = max_frame;
    fp_expect_hdr(f);
    return 0;
}

static PyObject *fp_new(PyTypeObject *type, PyObject *args,
                        PyObject *kwds) {
    (void)args;
    (void)kwds;
    FPObject *f = (FPObject *)type->tp_alloc(type, 0);
    if (f) {
        f->target = f->body = f->oob = NULL;
        f->frames = 0;
        f->max_frame = 0;
        fp_expect_hdr(f);
    }
    return (PyObject *)f;
}

static PyMethodDef fp_methods[] = {
    {"feed", (PyCFunction)(void (*)(void))fp_feed, METH_FASTCALL,
     "feed(data) -> [(tag, body|None, [oob...]), ...]"},
    {"bulk_target", (PyCFunction)fp_bulk_target, METH_NOARGS,
     "writable view of the in-progress large payload, or None"},
    {"bulk_commit", (PyCFunction)(void (*)(void))fp_bulk_commit,
     METH_FASTCALL, "bulk_commit(n) -> frames completed"},
    {"idle", (PyCFunction)fp_idle, METH_NOARGS,
     "True when between frames (clean-close detector)"},
    {"stats", (PyCFunction)fp_stats, METH_NOARGS,
     "frames completed through this parser"},
    {NULL, NULL, 0, NULL}};

static PyTypeObject FPType = {
    PyVarObject_HEAD_INIT(NULL, 0).tp_name = "commext.FrameParser",
    .tp_basicsize = sizeof(FPObject),
    .tp_dealloc = fp_dealloc,
    .tp_flags = Py_TPFLAGS_DEFAULT,
    .tp_methods = fp_methods,
    .tp_init = fp_init,
    .tp_new = fp_new,
};

/* frame_parts(tag, body_bytes, raws) -> [header, body?, blen, raw, ...]
 * — the gather-write part list (one C crossing builds every length
 * header; the raw buffers themselves are passed through untouched). */
static PyObject *mod_frame_parts(PyObject *self_, PyObject *const *args,
                                 Py_ssize_t nargs) {
    (void)self_;
    if (nargs != 3) {
        PyErr_SetString(PyExc_TypeError, "frame_parts(tag, body, raws)");
        return NULL;
    }
    unsigned long tag = PyLong_AsUnsignedLong(args[0]);
    if (tag == (unsigned long)-1 && PyErr_Occurred())
        return NULL;
    Py_ssize_t blen = 0;
    if (args[1] != Py_None) {
        blen = PyObject_Length(args[1]);
        if (blen < 0)
            return NULL;
    }
    PyObject *raws = PySequence_Fast(args[2], "raws must be a sequence");
    if (!raws)
        return NULL;
    Py_ssize_t nb = PySequence_Fast_GET_SIZE(raws);
    PyObject *hdr = PyBytes_FromStringAndSize(NULL, HDR_SIZE);
    if (!hdr) {
        Py_DECREF(raws);
        return NULL;
    }
    unsigned char *hp = (unsigned char *)PyBytes_AS_STRING(hdr);
    put_be32(hp, (uint32_t)tag);
    put_be64(hp + 4, (uint64_t)blen);
    put_be32(hp + 12, (uint32_t)nb);
    PyObject *out = PyList_New(0);
    if (!out)
        goto fail;
    if (PyList_Append(out, hdr) < 0)
        goto fail;
    if (blen && PyList_Append(out, args[1]) < 0)
        goto fail;
    for (Py_ssize_t i = 0; i < nb; i++) {
        PyObject *raw = PySequence_Fast_GET_ITEM(raws, i);
        Py_buffer v;
        if (PyObject_GetBuffer(raw, &v, PyBUF_SIMPLE) < 0)
            goto fail;
        Py_ssize_t rn = v.len;
        PyBuffer_Release(&v);
        PyObject *bl = PyBytes_FromStringAndSize(NULL, BLEN_SIZE);
        if (!bl)
            goto fail;
        put_be64((unsigned char *)PyBytes_AS_STRING(bl), (uint64_t)rn);
        int rc = PyList_Append(out, bl);
        Py_DECREF(bl);
        if (rc < 0)
            goto fail;
        if (rn && PyList_Append(out, raw) < 0)
            goto fail;
    }
    Py_DECREF(hdr);
    Py_DECREF(raws);
    return out;
fail:
    Py_XDECREF(out);
    Py_DECREF(hdr);
    Py_DECREF(raws);
    return NULL;
}

static PyMethodDef mod_methods[] = {
    {"frame_parts", (PyCFunction)(void (*)(void))mod_frame_parts,
     METH_FASTCALL,
     "frame_parts(tag, body, raws) -> gather-write part list"},
    {NULL, NULL, 0, NULL}};

static struct PyModuleDef commext_module = {
    PyModuleDef_HEAD_INIT, "commext",
    "native comm framing: incremental parser + part assembly", -1,
    mod_methods, NULL, NULL, NULL, NULL};

PyMODINIT_FUNC PyInit_commext(void) {
    if (PyType_Ready(&FPType) < 0)
        return NULL;
    PyObject *m = PyModule_Create(&commext_module);
    if (!m)
        return NULL;
    Py_INCREF(&FPType);
    if (PyModule_AddObject(m, "FrameParser", (PyObject *)&FPType) < 0) {
        Py_DECREF(&FPType);
        Py_DECREF(m);
        return NULL;
    }
    return m;
}
