// Native runtime core: the hot-path primitives the reference keeps in C
// (reference: parsec/class/{lifo,fifo,dequeue,list}.c lock-free task
// queues; utils/zone_malloc.c segment allocator; profiling.c per-thread
// binary event buffers).  Compiled to a shared library and bound via
// ctypes; queues store opaque 64-bit handles so the Python layer can
// park object identities while the bookkeeping runs without the
// interpreter.
//
// Build: make -C parsec_tpu/native   (g++ -O2 -shared -fPIC)

#include <cstdint>
#include <cstring>
#include <deque>
#include <map>
#include <mutex>
#include <vector>

extern "C" {

// ---------------------------------------------------------------------------
// MPMC dequeue of u64 handles (reference: parsec_dequeue_t)
// ---------------------------------------------------------------------------

struct ptq_deq {
    std::mutex m;
    std::deque<uint64_t> q;
};

void* ptq_deq_new() { return new ptq_deq(); }
void ptq_deq_delete(void* h) { delete static_cast<ptq_deq*>(h); }

void ptq_deq_push_back(void* h, uint64_t v) {
    auto* d = static_cast<ptq_deq*>(h);
    std::lock_guard<std::mutex> g(d->m);
    d->q.push_back(v);
}

void ptq_deq_push_front(void* h, uint64_t v) {
    auto* d = static_cast<ptq_deq*>(h);
    std::lock_guard<std::mutex> g(d->m);
    d->q.push_front(v);
}

int ptq_deq_pop_front(void* h, uint64_t* out) {
    auto* d = static_cast<ptq_deq*>(h);
    std::lock_guard<std::mutex> g(d->m);
    if (d->q.empty()) return 0;
    *out = d->q.front();
    d->q.pop_front();
    return 1;
}

int ptq_deq_pop_back(void* h, uint64_t* out) {
    auto* d = static_cast<ptq_deq*>(h);
    std::lock_guard<std::mutex> g(d->m);
    if (d->q.empty()) return 0;
    *out = d->q.back();
    d->q.pop_back();
    return 1;
}

uint64_t ptq_deq_len(void* h) {
    auto* d = static_cast<ptq_deq*>(h);
    std::lock_guard<std::mutex> g(d->m);
    return d->q.size();
}

// ---------------------------------------------------------------------------
// Zone (segment) allocator (reference: utils/zone_malloc.{c,h})
// ---------------------------------------------------------------------------

struct ptq_zone {
    std::mutex m;
    uint64_t unit;
    uint64_t nb_units;
    // start_unit -> (units, free)
    std::map<uint64_t, std::pair<uint64_t, bool>> segs;
};

void* ptq_zone_new(uint64_t total_bytes, uint64_t unit_bytes) {
    if (total_bytes == 0 || unit_bytes == 0 || total_bytes < unit_bytes)
        return nullptr;
    auto* z = new ptq_zone();
    z->unit = unit_bytes;
    z->nb_units = total_bytes / unit_bytes;
    z->segs[0] = {z->nb_units, true};
    return z;
}

void ptq_zone_delete(void* h) { delete static_cast<ptq_zone*>(h); }

int64_t ptq_zone_malloc(void* h, uint64_t nbytes) {
    auto* z = static_cast<ptq_zone*>(h);
    uint64_t units = (nbytes + z->unit - 1) / z->unit;
    if (units == 0) units = 1;
    std::lock_guard<std::mutex> g(z->m);
    for (auto& kv : z->segs) {                  // first fit
        uint64_t start = kv.first;
        auto& seg = kv.second;
        if (!seg.second || seg.first < units) continue;
        if (seg.first > units)
            z->segs[start + units] = {seg.first - units, true};
        seg = {units, false};
        return static_cast<int64_t>(start * z->unit);
    }
    return -1;
}

static void ptq_zone_coalesce(ptq_zone* z) {
    auto it = z->segs.begin();
    while (it != z->segs.end()) {
        auto nxt = std::next(it);
        if (nxt == z->segs.end()) break;
        if (it->second.second && nxt->second.second &&
            it->first + it->second.first == nxt->first) {
            it->second.first += nxt->second.first;
            z->segs.erase(nxt);
        } else {
            it = nxt;
        }
    }
}

int ptq_zone_release(void* h, int64_t offset) {
    auto* z = static_cast<ptq_zone*>(h);
    std::lock_guard<std::mutex> g(z->m);
    auto it = z->segs.find(static_cast<uint64_t>(offset) / z->unit);
    if (it == z->segs.end() || it->second.second) return -1;
    it->second.second = true;
    ptq_zone_coalesce(z);
    return 0;
}

uint64_t ptq_zone_used(void* h) {
    auto* z = static_cast<ptq_zone*>(h);
    std::lock_guard<std::mutex> g(z->m);
    uint64_t used = 0;
    for (auto& kv : z->segs)
        if (!kv.second.second) used += kv.second.first;
    return used * z->unit;
}

uint64_t ptq_zone_free_bytes(void* h) {
    auto* z = static_cast<ptq_zone*>(h);
    std::lock_guard<std::mutex> g(z->m);
    uint64_t freeu = 0;
    for (auto& kv : z->segs)
        if (kv.second.second) freeu += kv.second.first;
    return freeu * z->unit;
}

int ptq_zone_defragmented(void* h) {
    auto* z = static_cast<ptq_zone*>(h);
    std::lock_guard<std::mutex> g(z->m);
    return z->segs.size() == 1 && z->segs.begin()->second.second ? 1 : 0;
}

// ---------------------------------------------------------------------------
// Binary trace buffer (reference: profiling.c per-thread append-only
// buffers of fixed-size events {key, flags, taskpool_id, event_id,
// object_id, timestamp})
// ---------------------------------------------------------------------------

#pragma pack(push, 1)
struct ptq_ev {
    int32_t key;
    int32_t flags;
    uint64_t taskpool_id;
    uint64_t event_id;
    uint64_t object_id;
    double ts;
};
#pragma pack(pop)

struct ptq_trace {
    std::mutex m;
    std::vector<ptq_ev> events;
};

void* ptq_trace_new(uint64_t reserve) {
    auto* t = new ptq_trace();
    t->events.reserve(reserve ? reserve : 1024);
    return t;
}

void ptq_trace_delete(void* h) { delete static_cast<ptq_trace*>(h); }

void ptq_trace_event(void* h, int32_t key, int32_t flags,
                     uint64_t taskpool_id, uint64_t event_id,
                     uint64_t object_id, double ts) {
    auto* t = static_cast<ptq_trace*>(h);
    std::lock_guard<std::mutex> g(t->m);
    t->events.push_back({key, flags, taskpool_id, event_id, object_id, ts});
}

// Bulk ingest of packed events (same layout as ptq_ev): the Python
// tracer batches its hot path into ONE boundary crossing per ~1k
// events instead of a ctypes call per event.
void ptq_trace_events_bulk(void* h, const uint8_t* buf, uint64_t nbytes) {
    auto* t = static_cast<ptq_trace*>(h);
    uint64_t n = nbytes / sizeof(ptq_ev);
    if (!n) return;
    const ptq_ev* evs = reinterpret_cast<const ptq_ev*>(buf);
    std::lock_guard<std::mutex> g(t->m);
    t->events.insert(t->events.end(), evs, evs + n);
}

uint64_t ptq_trace_count(void* h) {
    auto* t = static_cast<ptq_trace*>(h);
    std::lock_guard<std::mutex> g(t->m);
    return t->events.size();
}

uint64_t ptq_trace_event_size() { return sizeof(ptq_ev); }

// Copy out up to maxbytes of packed events starting at event `from`;
// returns bytes written.
uint64_t ptq_trace_read(void* h, uint64_t from, uint8_t* buf,
                        uint64_t maxbytes) {
    auto* t = static_cast<ptq_trace*>(h);
    std::lock_guard<std::mutex> g(t->m);
    if (from >= t->events.size()) return 0;
    uint64_t n = t->events.size() - from;
    uint64_t fit = maxbytes / sizeof(ptq_ev);
    if (n > fit) n = fit;
    std::memcpy(buf, t->events.data() + from, n * sizeof(ptq_ev));
    return n * sizeof(ptq_ev);
}

}  // extern "C"
