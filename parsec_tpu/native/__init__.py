"""ctypes bindings for the native runtime core.

The C++ library (core.cpp) carries the hot-path primitives the reference
keeps native — MPMC handle queues, the zone allocator, binary trace
buffers — built on demand (atomically, rename-into-place).  Every Python
consumer keeps a pure-Python fallback, selected via ``available()`` /
``--mca native_core``:

  utils.zone_alloc           <- NativeZoneAllocator (device HBM ledger,
                                default on)
  prof.profiling             <- NativeTraceBuffer (event append path,
                                default on)
  containers.make_dequeue    <- NativeDequeue (OPT-IN via native_queues:
                                measured slower for Python-object
                                payloads, see make_dequeue)
"""

from __future__ import annotations

import ctypes
import os
import struct as struct_mod
import subprocess
import threading
from typing import Optional

from parsec_tpu.utils.mca import params
from parsec_tpu.utils.output import debug_verbose, warning

params.register("native_core", 1,
                "use the C++ runtime core when it builds (0 = pure Python)")

_HERE = os.path.dirname(os.path.abspath(__file__))
_SO = os.path.join(_HERE, "libparsec_tpu.so")
_lock = threading.Lock()
_lib: Optional[ctypes.CDLL] = None
_tried = False

#: build-degradation warning, rate-limited to ONE per process across
#: every extension (a missing compiler on a 4-extension import chain
#: must not spam four warnings per rank — and never one per import:
#: the per-extension _tried caches make later loads silent anyway)
_toolchain_warned = False


def _warn_build(what: str, detail: str) -> None:
    global _toolchain_warned
    if not _toolchain_warned:
        _toolchain_warned = True
        warning("%s unavailable (falling back to the pure-Python "
                "path; further native build failures this process "
                "are logged at debug level): %s", what, detail)
    else:
        debug_verbose(3, "%s unavailable: %s", what, detail)


def _stale(so: str, src: str) -> bool:
    """Rebuild when the artifact is missing or older than its source
    (an edited .c next to a stale .so must never load the old code)."""
    return not os.path.exists(so) or \
        os.path.getmtime(so) < os.path.getmtime(src)


def _compile(cmd: list, so: str, what: str) -> bool:
    """Compile to a temp name and rename into place: spawned rank
    processes may build concurrently on a fresh checkout, and a reader
    must never dlopen a half-written .so (rename is atomic)."""
    tmp = f"{so}.tmp.{os.getpid()}"
    try:
        r = subprocess.run(cmd + ["-o", tmp],
                           capture_output=True, text=True, timeout=120)
        if r.returncode != 0:
            _warn_build(what, "build failed:\n" + r.stderr[-2000:])
            return False
        os.replace(tmp, so)
        return True
    except (OSError, subprocess.TimeoutExpired) as exc:
        _warn_build(what, f"build tool error: {exc}")
        return False
    finally:
        try:
            os.unlink(tmp)
        except OSError:
            pass


def _build() -> bool:
    return _compile(
        ["g++", "-O2", "-std=c++17", "-fPIC", "-Wall", "-Wextra",
         "-shared", os.path.join(_HERE, "core.cpp")],
        _SO, "native core")


def load() -> Optional[ctypes.CDLL]:
    """Build (once) and load the shared library; None when disabled or
    the toolchain is missing."""
    global _lib, _tried
    with _lock:
        if _tried:
            return _lib
        _tried = True
        if not int(params.get("native_core", 1)):
            return None
        if _stale(_SO, os.path.join(_HERE, "core.cpp")):
            if not _build():
                return None
        try:
            lib = ctypes.CDLL(_SO)
        except OSError as exc:
            _warn_build("native core", f"load failed: {exc}")
            return None
        _sign(lib)
        _lib = lib
        debug_verbose(5, "native core loaded: %s", _SO)
        return _lib


def available() -> bool:
    return load() is not None


#: CPython extension modules (pinsext, schedext, commext) share one
#: build + import path; name -> loaded module or None (build/load
#: failed: the Python fallback serves this process)
_cexts: dict = {}


def _load_cext(name: str):
    """Build (once per process) and import the CPython extension
    ``<name>.c`` -> ``<name>.so``; None when disabled or the
    toolchain/headers are missing — callers keep a Python fallback."""
    with _lock:
        if name in _cexts:
            return _cexts[name]
        _cexts[name] = None
        if not int(params.get("native_core", 1)):
            return None
        src = os.path.join(_HERE, f"{name}.c")
        so = os.path.join(_HERE, f"{name}.so")
        if _stale(so, src):
            import sysconfig
            inc = sysconfig.get_paths()["include"]
            if not _compile(["g++", "-O2", "-fPIC", "-shared",
                             f"-I{inc}", src], so, name):
                return None
        try:
            import importlib.util
            spec = importlib.util.spec_from_file_location(name, so)
            mod = importlib.util.module_from_spec(spec)
            spec.loader.exec_module(mod)
        except Exception as exc:   # pragma: no cover - load portability
            _warn_build(name, f"load failed: {exc}")
            return None
        _cexts[name] = mod
        debug_verbose(5, "%s loaded: %s", name, so)
        return mod


def load_pinsext():
    """Build (once) and import the CPython trace-sink extension
    (pinsext.c).  ctypes costs ~1us per crossing — the whole tracer
    budget — so the per-event path is a real extension module; returns
    None when disabled or the toolchain/headers are missing."""
    global _pinsext, _pinsext_tried
    if _pinsext_tried:
        return _pinsext
    mod = _load_cext("pinsext")
    if mod is not None:
        # the sink stamps with CLOCK_MONOTONIC; only usable if that is
        # the same timeline Python's perf_counter reads (true on Linux).
        # Bracket the C read between two Python reads and retry a few
        # times: a single unlucky deschedule between two reads must not
        # silently disable the fast path for the whole process.
        import time as _time
        same_clock = False
        for _ in range(5):
            a = _time.perf_counter()
            b = mod.now()
            c = _time.perf_counter()
            if a - 1e-4 <= b <= c + 1e-4:
                same_clock = True
                break
        if not same_clock:
            debug_verbose(3, "pinsext clock differs from perf_counter; "
                          "falling back to the Python event path")
            mod = None
    _pinsext = mod
    _pinsext_tried = True
    return _pinsext


def load_schedext():
    """The native scheduler hot path (schedext.c: ReadyQueue +
    DepTable); gated by ``sched_native`` at its consumers
    (sched/native.py, core/engine.py), by ``native_core`` here."""
    return _load_cext("schedext")


def load_commext():
    """The native comm framing (commext.c: FrameParser + frame_parts);
    gated by ``comm_frame_native`` at its consumers (comm/frames.py)."""
    return _load_cext("commext")


_pinsext = None
_pinsext_tried = False
_PINS_SO = os.path.join(_HERE, "pinsext.so")


def _sign(lib: ctypes.CDLL) -> None:
    C = ctypes
    u64, i64, i32 = C.c_uint64, C.c_int64, C.c_int32
    p, d = C.c_void_p, C.c_double
    sigs = {
        "ptq_deq_new": ([], p),
        "ptq_deq_delete": ([p], None),
        "ptq_deq_push_back": ([p, u64], None),
        "ptq_deq_push_front": ([p, u64], None),
        "ptq_deq_pop_front": ([p, C.POINTER(u64)], C.c_int),
        "ptq_deq_pop_back": ([p, C.POINTER(u64)], C.c_int),
        "ptq_deq_len": ([p], u64),
        "ptq_zone_new": ([u64, u64], p),
        "ptq_zone_delete": ([p], None),
        "ptq_zone_malloc": ([p, u64], i64),
        "ptq_zone_release": ([p, i64], C.c_int),
        "ptq_zone_used": ([p], u64),
        "ptq_zone_free_bytes": ([p], u64),
        "ptq_zone_defragmented": ([p], C.c_int),
        "ptq_trace_new": ([u64], p),
        "ptq_trace_delete": ([p], None),
        "ptq_trace_event": ([p, i32, i32, u64, u64, u64, d], None),
        "ptq_trace_events_bulk": ([p, C.POINTER(C.c_uint8), u64], None),
        "ptq_trace_count": ([p], u64),
        "ptq_trace_event_size": ([], u64),
        "ptq_trace_read": ([p, u64, C.POINTER(C.c_uint8), u64], u64),
    }
    for name, (argtypes, restype) in sigs.items():
        fn = getattr(lib, name)
        fn.argtypes = argtypes
        fn.restype = restype


# ---------------------------------------------------------------------------
# Python wrappers
# ---------------------------------------------------------------------------

class NativeDequeue:
    """MPMC dequeue of Python objects over native u64 handles (reference:
    parsec_dequeue_t).  Handles are id()s parked in a side table so the
    queue discipline itself runs without the interpreter lock."""

    def __init__(self):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._h = self._lib.ptq_deq_new()
        self._objs = {}
        self._olock = threading.Lock()

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.ptq_deq_delete(h)
            self._h = None

    def _park(self, obj) -> int:
        key = id(obj)
        with self._olock:
            self._objs.setdefault(key, []).append(obj)
        return key

    def _claim(self, key: int):
        with self._olock:
            lst = self._objs[key]
            obj = lst.pop()
            if not lst:
                del self._objs[key]
            return obj

    def push_back(self, obj) -> None:
        self._lib.ptq_deq_push_back(self._h, self._park(obj))

    def push_front(self, obj) -> None:
        self._lib.ptq_deq_push_front(self._h, self._park(obj))

    def chain_back(self, objs) -> None:
        for o in objs:
            self.push_back(o)

    def _pop(self, fn):
        out = ctypes.c_uint64()
        if not fn(self._h, ctypes.byref(out)):
            return None
        return self._claim(out.value)

    def pop_front(self):
        return self._pop(self._lib.ptq_deq_pop_front)

    def pop_back(self):
        return self._pop(self._lib.ptq_deq_pop_back)

    def __len__(self):
        return int(self._lib.ptq_deq_len(self._h))


class NativeZoneAllocator:
    """Drop-in twin of utils.zone_alloc.ZoneAllocator over the C++
    segment allocator (reference: utils/zone_malloc.c)."""

    def __init__(self, total_bytes: int, unit_bytes: int = 512):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._h = self._lib.ptq_zone_new(int(total_bytes), int(unit_bytes))
        if not self._h:
            raise ValueError("zone size and unit must be positive and "
                             "total >= unit")
        self.unit = unit_bytes
        self.nb_units = total_bytes // unit_bytes

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.ptq_zone_delete(h)
            self._h = None

    def malloc(self, nbytes: int):
        off = self._lib.ptq_zone_malloc(self._h, int(nbytes))
        return None if off < 0 else int(off)

    def free(self, offset: int) -> None:
        if self._lib.ptq_zone_release(self._h, int(offset)) != 0:
            raise ValueError(f"bad free at offset {offset}")

    def used_bytes(self) -> int:
        return int(self._lib.ptq_zone_used(self._h))

    def free_bytes(self) -> int:
        return int(self._lib.ptq_zone_free_bytes(self._h))

    def check_defrag(self) -> bool:
        return bool(self._lib.ptq_zone_defragmented(self._h))


class NativeTraceBuffer:
    """Append-only event buffer (reference: the per-thread buffers of
    profiling.c).  ``drain()`` returns (key, flags, taskpool_id,
    event_id, object_id, ts) tuples."""

    #: signed 64-bit fields on the way OUT so negative sentinels (e.g.
    #: object_id -1) round-trip through the C struct's two's complement
    _EVFMT = "<iiqqqd"

    def __init__(self, reserve: int = 4096):
        self._lib = load()
        if self._lib is None:
            raise RuntimeError("native core unavailable")
        self._h = self._lib.ptq_trace_new(int(reserve))
        self._evsz = int(self._lib.ptq_trace_event_size())

    def __del__(self):
        lib, h = getattr(self, "_lib", None), getattr(self, "_h", None)
        if lib is not None and h:
            lib.ptq_trace_delete(h)
            self._h = None

    def event(self, key: int, flags: int, taskpool_id: int, event_id: int,
              object_id: int, ts: float) -> None:
        self._lib.ptq_trace_event(self._h, key, flags, taskpool_id,
                                  event_id, object_id, ts)

    #: packed layout for events_bulk — unsigned 64-bit on the way IN
    #: (matches ptq_trace_event's parameter types; negative object_ids
    #: fold to two's complement like the per-event path)
    _EVFMT_IN = struct_mod.Struct("<iiQQqd")
    #: whole-chunk packers, one C pack call per batch instead of one per
    #: event (the tracer's amortized-ingest cost is dominated by Python
    #: pack calls otherwise); lazily built per batch length
    _CHUNK_PACKERS: dict = {}

    def events_bulk(self, events) -> None:
        """One boundary crossing for a batch of (key, flags, tp, eid,
        oid, ts) tuples — the tracer hot path's amortized ingest."""
        if not events:
            return
        n = len(events)
        packer = self._CHUNK_PACKERS.get(n)
        if packer is None:
            # signed 64-bit: same bit pattern as the Q layout for the
            # values in range, and it accepts the odd negative id too
            packer = self._CHUNK_PACKERS[n] = \
                struct_mod.Struct("<" + "iiqqqd" * n)
            if len(self._CHUNK_PACKERS) > 64:   # odd tail sizes: bounded
                self._CHUNK_PACKERS.clear()
        flat = []
        ext = flat.extend
        for ev in events:
            ext(ev)
        buf = packer.pack(*flat)
        carr = (ctypes.c_uint8 * len(buf)).from_buffer_copy(buf)
        self._lib.ptq_trace_events_bulk(self._h, carr, len(buf))

    def __len__(self):
        return int(self._lib.ptq_trace_count(self._h))

    def drain(self, start: int = 0):
        import struct
        n = len(self) - start
        if n <= 0:
            return []
        buf = (ctypes.c_uint8 * (n * self._evsz))()
        got = self._lib.ptq_trace_read(self._h, start, buf, len(buf))
        raw = bytes(buf[:got])
        return [struct.unpack_from(self._EVFMT, raw, i * self._evsz)
                for i in range(got // self._evsz)]
